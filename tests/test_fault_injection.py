"""Crash-resume parity through the real CLI (`make test-fault`): a tiny
CPU run is killed by an injected fault (PFX_FAULT), relaunched with
auto_resume, and the resumed loss stream must be token-for-token identical
to an uninterrupted reference run.

Three injected failure modes, one per test:

  sigterm        preemption: finish the step, checkpoint with the
                 `preempted` marker, exit 0, resume seamlessly
  save_crash     hard-exit mid-save (after arrays, before meta.json):
                 the marker-less dir is skipped, resume falls back
  ckpt_truncate  bit-rot in a complete-looking newest checkpoint: it is
                 quarantined (*.corrupt) and resume falls back

All runs share one synthetic corpus + config (1 CPU device, 2-layer GPT)
and the persistent XLA compile cache exported by conftest, so the whole
file fits the tier-1 budget.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MAX_STEPS = 6


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    from paddlefleetx_tpu.data.gpt_dataset import write_synthetic_corpus

    data = tmp_path_factory.mktemp("fault_corpus")
    write_synthetic_corpus(str(data / "corp"), vocab_size=128, num_docs=16)
    return str(data)


def _run(corpus, out_dir, metrics, max_steps=MAX_STEPS, fault=None,
         extra=(), check=True):
    overrides = [
        "Model.num_layers=2", "Model.hidden_size=32",
        "Model.num_attention_heads=4", "Model.vocab_size=128",
        "Model.max_position_embeddings=32",
        "Global.global_batch_size=8", "Global.local_batch_size=8",
        "Global.micro_batch_size=8",
        f"Engine.max_steps={max_steps}", "Engine.logging_freq=1",
        "Engine.eval_freq=0", "Engine.mix_precision.enable=False",
        "Engine.save_load.save_steps=2",
        "Engine.save_load.auto_resume=True",
        f"Engine.save_load.output_dir={out_dir}",
        f"Engine.metrics_file={metrics}",
        f"Data.Train.dataset.input_dir={corpus}",
        "Data.Train.dataset.max_seq_len=32",
    ] + list(extra)
    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PFX_FAULT", None)
    if fault:
        env["PFX_FAULT"] = fault
    cmd = [sys.executable, os.path.join(REPO, "tools", "train.py"), "-c",
           os.path.join(REPO, "configs/gpt/pretrain_gpt_345M_single.yaml")]
    for o in overrides:
        cmd += ["-o", o]
    out = subprocess.run(
        cmd, capture_output=True, text=True, timeout=420, cwd=REPO, env=env
    )
    if check:
        assert out.returncode == 0, (out.returncode, out.stderr[-2000:])
    return out


def _loss_stream(metrics_path):
    """step -> loss from a metrics jsonl; a resumed run appends, so steps
    replayed after a rollback-to-checkpoint appear twice — last wins (the
    parity assert then proves the replay matched anyway)."""
    stream = {}
    with open(metrics_path) as f:
        for line in f:
            rec = json.loads(line)
            if "loss" in rec and "step" in rec:
                stream[rec["step"]] = rec["loss"]
    return stream


@pytest.fixture(scope="module")
def ref_stream(corpus, tmp_path_factory):
    """Uninterrupted reference run: the loss stream every faulted+resumed
    run must reproduce exactly."""
    root = tmp_path_factory.mktemp("fault_ref")
    metrics = str(root / "metrics.jsonl")
    _run(corpus, str(root / "out"), metrics)
    stream = _loss_stream(metrics)
    assert sorted(stream) == list(range(1, MAX_STEPS + 1)), stream
    return stream


@pytest.mark.slow  # ~25s two full CLI runs; the preemption contract
# stays tier-1 via test_fault_tolerance's in-process units (SIGTERM
# finishes the step, writes the `preempted` marker, resumes at step+1)
# and the nan-rollback CLI drill keeps a crash-resume parity path
# drilled; this flagship parity drill still runs in make test-fault /
# test-all (PR 8 tier-1 budget convention)
def test_sigterm_preempt_resume_parity(corpus, ref_stream, tmp_path):
    """Injected SIGTERM at step 3: run 1 checkpoints (preempted marker) and
    exits 0; the relaunch resumes at step 4 and the full loss stream equals
    the uninterrupted run token-for-token."""
    out = tmp_path / "out"
    metrics = str(tmp_path / "metrics.jsonl")
    run1 = _run(corpus, str(out), metrics, fault="sigterm:3")
    log1 = run1.stdout + run1.stderr
    assert "exiting cleanly" in log1, log1[-2000:]
    meta = json.load(open(out / "step_3" / "meta.json"))
    assert meta.get("preempted") is True and meta["step"] == 3
    assert not (out / f"step_{MAX_STEPS}").exists()  # really stopped early

    run2 = _run(corpus, str(out), metrics)
    log2 = run2.stdout + run2.stderr
    assert "auto_resume: found" in log2 and "step_3" in log2
    assert _loss_stream(metrics) == ref_stream


@pytest.mark.slow  # tier-1 budget (870s): the SIGTERM preempt-resume
# drill above keeps the crash-resume parity contract in tier-1; this
# mid-save variant overlaps it + the checkpoint units and rides
# `make test-fault` / test-all instead
def test_save_crash_resume_parity(corpus, ref_stream, tmp_path):
    """Hard crash mid-save at step 4 (arrays written, meta.json never
    lands): the marker-less dir is skipped, resume falls back to step 2,
    replays 3-4 identically, and finishes with the reference stream."""
    out = tmp_path / "out"
    metrics = str(tmp_path / "metrics.jsonl")
    run1 = _run(corpus, str(out), metrics, fault="save_crash:4", check=False)
    assert run1.returncode == 17, (run1.returncode, run1.stderr[-2000:])
    assert (out / "step_4").is_dir()
    assert not (out / "step_4" / "meta.json").exists()  # marker-less
    assert (out / "step_2" / "meta.json").exists()

    run2 = _run(corpus, str(out), metrics)
    log2 = run2.stdout + run2.stderr
    assert "auto_resume: found" in log2 and "step_2" in log2
    assert _loss_stream(metrics) == ref_stream


@pytest.mark.slow  # tier-1 budget: quarantine/fallback is unit-covered
# in test_fault_tolerance.py; the through-the-CLI spelling rides
# `make test-fault` / test-all
def test_ckpt_truncate_quarantine_fallback_parity(corpus, ref_stream, tmp_path):
    """Bit-rot in the newest (complete-looking) checkpoint: resume
    quarantines it to *.corrupt, falls back to the previous good one, and
    reproduces the reference stream.

    (Historical note: runs here once had to keep the reference max_steps
    because the shuffle was keyed by num_samples = max_steps * batch;
    epoch-keyed index maps made the data order length-independent, so
    that constraint is gone.)  Count=2 catches both writes of step_6
    (periodic + final save)."""
    out = tmp_path / "out"
    metrics = str(tmp_path / "metrics.jsonl")
    run1 = _run(corpus, str(out), metrics, fault="ckpt_truncate:6:2")
    assert "truncated" in run1.stdout + run1.stderr
    assert (out / "step_6" / "meta.json").exists()  # LOOKS complete

    # relaunch: resume must quarantine step_6, fall back to step_4, and
    # replay steps 5-6 token-for-token (then its final save recreates a
    # healthy step_6)
    run2 = _run(corpus, str(out), metrics)
    log2 = run2.stdout + run2.stderr
    assert "QUARANTINED" in log2, log2[-2000:]
    assert (out / "step_6.corrupt").is_dir()
    assert "step_4" in log2  # fell back to the previous good checkpoint
    assert _loss_stream(metrics) == ref_stream


def test_nan_rollback_rewind_replay_parity(corpus, ref_stream, tmp_path):
    """Injected NaN batch at step 3 trips the anomaly guard
    (max_skip_streak=1); the engine rolls back to the step-2 checkpoint AND
    REWINDS the data stream to the checkpoint position, so steps 3-6 replay
    with the exact batches an uninterrupted run serves — the full loss
    stream (last-wins over the poisoned first pass) must equal the
    reference token-for-token.  This is the contract PR 2 could not give
    ("the loader does NOT rewind"); the rewindable-iterator pipeline
    closes it."""
    out = tmp_path / "out"
    metrics = str(tmp_path / "metrics.jsonl")
    run1 = _run(
        corpus, str(out), metrics, fault="nan_grads:3:1",
        extra=("Engine.resilience.max_skip_streak=1",),
    )
    log = run1.stdout + run1.stderr
    assert "ANOMALY" in log and "rolling back" in log, log[-2000:]
    assert "data stream rewound" in log, log[-2000:]

    events = [json.loads(line) for line in open(metrics)]
    rollbacks = [e for e in events if e.get("event") == "rollback"]
    assert len(rollbacks) == 1 and rollbacks[0]["rewound"] is True
    assert rollbacks[0]["ckpt"].endswith("step_2")

    # token-for-token replay: the post-rollback stream overwrote the
    # poisoned steps with exactly the reference losses
    assert _loss_stream(metrics) == ref_stream
    # the poisoned first pass really happened (a NaN loss was recorded
    # before the replay overwrote it)
    nan_steps = [
        e["step"] for e in events
        if "loss" in e and isinstance(e["loss"], float) and e["loss"] != e["loss"]
    ]
    assert nan_steps, "injection never produced a NaN step"
