"""Continuous-batching engine + scheduler (`core/continuous_batching.py`).

The acceptance criteria, in-process and deterministic:

  - greedy continuous output is TOKEN-IDENTICAL to the sequential
    (coalesce-path) GenerationServer for the same request set, including
    requests admitted MID-decode of the running batch;
  - a mid-decode deadline eviction frees the row's blocks immediately
    and later requests still produce token-identical output;
  - retraces are bounded per (prompt bucket, table-width bucket) and
    counted in stats["traces"];
  - the scheduler keeps every PR 3 admission contract (bounded submit,
    QueueFull/QueueClosed, try_remove, graceful drain) and an arena
    failure fails exactly the live rows, then keeps serving.
"""

import time

import pytest

TINY = {
    "Global": {"global_batch_size": 8, "seed": 3},
    "Engine": {"mix_precision": {"enable": False},
               "save_load": {"save_steps": 0}},
    "Model": {
        "module": "GPTModule",
        "vocab_size": 96,
        "hidden_size": 32,
        "num_layers": 2,
        "num_attention_heads": 4,
        "max_position_embeddings": 128,
        "dtype": "float32",
    },
    "Distributed": {},
    "Optimizer": {"name": "FusedAdamW",
                  "lr": {"name": "Constant", "learning_rate": 1e-3}},
    "Generation": {"max_dec_len": 8, "decode_strategy": "greedy_search",
                   "pad_to_multiple": 16, "eos_token_id": 95,
                   "pad_token_id": 0},
}


@pytest.fixture(scope="module")
def server():
    import jax

    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict.from_nested(TINY)
    cfg = process_configs(cfg, num_devices=jax.device_count())
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    return GenerationServer(cfg, mesh, module)


def _engine(server, **kw):
    from paddlefleetx_tpu.core.continuous_batching import PagedDecodeEngine

    kw.setdefault("max_batch", 4)
    return PagedDecodeEngine(server, **kw)


def _drain(engine, max_steps=96):
    for _ in range(max_steps):
        engine.step()
        if not engine.active.any() and all(
            r is None or r.prefill_done for r in engine.slots
        ):
            return
    raise AssertionError("engine never drained")


PROMPTS = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 10], [11, 12, 13, 14]]


@pytest.fixture(scope="module")
def sequential(server):
    """Reference outputs: each request served alone on the coalesce path."""
    return [server.generate_ids([p], max_dec_len=6)[0] for p in PROMPTS]


def test_greedy_parity_with_mid_decode_admission(server, sequential):
    """THE acceptance parity: rows admitted at different step boundaries
    (one while others are mid-decode) decode token-identically to the
    sequential path — per-row positions, masks, and processor chains are
    independent of batch composition."""
    eng = _engine(server)
    s0 = eng.admit(PROMPTS[0], 6)
    s1 = eng.admit(PROMPTS[1], 6)
    eng.step()
    eng.step()
    s2 = eng.admit(PROMPTS[2], 6)  # mid-decode of rows 0/1
    eng.step()
    s3 = eng.admit(PROMPTS[3], 6)  # later still
    _drain(eng)
    got = [eng.slots[s].tokens for s in (s0, s1, s2, s3)]
    assert got == sequential
    # finished rows release cleanly and the pool returns to empty
    for s in (s0, s1, s2, s3):
        eng.release(s)
    assert eng.cache.stats()["kv_blocks_used"] == 0


def test_mid_decode_eviction_frees_blocks_and_parity_survives(server, sequential):
    """Evict a row mid-decode: its blocks return to the pool at once, a
    request admitted into the freed capacity decodes token-identically,
    and the survivors are unperturbed (their rows never saw the evicted
    row's cache)."""
    # pool sized so the 4th request CANNOT fit until one row is evicted
    eng = _engine(server, max_batch=4, num_blocks=4)  # 3 usable blocks
    s0 = eng.admit(PROMPTS[0], 6)   # 1 block (cap 16)
    s1 = eng.admit(PROMPTS[1], 6)   # 1 block
    s2 = eng.admit(PROMPTS[2], 6)   # 1 block — pool now full
    assert not eng.can_admit(len(PROMPTS[3]), 6)
    eng.step()
    eng.step()
    used_before = eng.cache.stats()["kv_blocks_used"]
    eng.release(s1)  # mid-decode eviction (deadline shed path)
    assert eng.cache.stats()["kv_blocks_used"] == used_before - 1
    assert eng.can_admit(len(PROMPTS[3]), 6)
    s3 = eng.admit(PROMPTS[3], 6)  # rides the freed block + slot
    _drain(eng)
    assert eng.slots[s0].tokens == sequential[0]
    assert eng.slots[s2].tokens == sequential[2]
    assert eng.slots[s3].tokens == sequential[3]


def test_retrace_count_is_bounded_and_asserted(server, sequential):
    """One compiled prefill per prompt bucket, one compiled step per
    table-width bucket: repeating the same traffic mix adds ZERO traces
    (the coalesce-path `stats["traces"]` contract, paged edition)."""
    eng = _engine(server)
    for _ in range(2):
        slots = [eng.admit(p, 6) for p in PROMPTS]
        _drain(eng)
        outs = [eng.slots[s].tokens for s in slots]
        assert outs == sequential
        for s in slots:
            eng.release(s)
        # prompt buckets: all four pad to bucket 16 -> ONE prefill compile;
        # table width: every row needs 2 blocks (cap 16+6 -> 22) -> ONE
        # step compile at width bucket 2
        assert eng.stats["traces"] == 2, eng.stats


def test_exhaustion_is_loud_and_admission_waits(server):
    from paddlefleetx_tpu.core.paged_cache import BlockPoolExhausted

    eng = _engine(server, max_batch=2, num_blocks=3)  # 2 usable blocks
    eng.admit([1, 2], 6)
    eng.admit([3, 4], 6)
    assert not eng.can_admit(2, 6)
    with pytest.raises((BlockPoolExhausted, RuntimeError)):
        eng.admit([5, 6], 6)
    with pytest.raises(ValueError, match="KV blocks"):
        eng.validate_request(100, 100)


def test_scheduler_end_to_end_parity_and_ttft_stamps(server, sequential):
    """The threaded scheduler resolves futures with the sequential-path
    tokens; lifecycle stamps (enqueued/picked/resolved) feed the request
    spans like RequestQueue's."""
    from paddlefleetx_tpu.core.continuous_batching import ContinuousScheduler

    sched = ContinuousScheduler(_engine(server), max_depth=8)
    sched.start()
    futs = [sched.submit([p], 6, deadline_s=120) for p in PROMPTS]
    got = [f.result(timeout=300)[0] for f in futs]
    assert got == sequential
    for f in futs:
        assert {"enqueued", "picked", "resolved"} <= set(f.times)
    assert sched.stats["completed"] == len(PROMPTS)
    assert sched.stats["evictions"] == 0
    assert sched.shutdown(timeout=30)


def test_scheduler_burst_over_capacity_stays_queued(server, sequential):
    """A burst larger than the running-batch capacity WAITS, it never
    hard-fails: the admission pull accounts for its own same-iteration
    picks (regression — surplus rows used to pass can_admit, then hit
    admit()'s no-free-slot RuntimeError and surface as 500s instead of
    queueing)."""
    from paddlefleetx_tpu.core.continuous_batching import ContinuousScheduler

    sched = ContinuousScheduler(_engine(server), max_depth=16)
    prompts = PROMPTS + PROMPTS[:2]  # 6 single-row requests > 4 slots
    futs = [sched.submit([p], 6, deadline_s=120) for p in prompts]
    sched.start()  # first iteration sees the whole burst at once
    got = [f.result(timeout=300)[0] for f in futs]
    assert got == sequential + sequential[:2]
    assert sched.stats["gen_errors"] == 0
    assert sched.stats["completed"] == len(prompts)
    assert sched.shutdown(timeout=30)


@pytest.mark.slow  # fresh config -> cold compiles; runs in make test-paged
def test_forced_eos_parity_with_coalesce_path():
    """With forced_eos_token_id set and a budget that is NOT a multiple
    of the 32 decode bucket, the contiguous path forces EOS at the
    BUCKETED run end — beyond the trimmed output — so the paged path must
    too (regression: it forced at max_news-1, truncating the row)."""
    import copy

    import jax

    from paddlefleetx_tpu.core.continuous_batching import PagedDecodeEngine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    tiny = copy.deepcopy(TINY)
    tiny["Generation"]["forced_eos_token_id"] = 94
    cfg = AttrDict.from_nested(tiny)
    cfg = process_configs(cfg, num_devices=jax.device_count())
    mesh = init_dist_env(cfg)
    srv = GenerationServer(cfg, mesh, build_module(cfg))
    ref = [srv.generate_ids([p], max_dec_len=6)[0] for p in PROMPTS[:2]]
    eng = PagedDecodeEngine(srv, max_batch=2)
    s0 = eng.admit(PROMPTS[0], 6)
    s1 = eng.admit(PROMPTS[1], 6)
    _drain(eng)
    assert [eng.slots[s0].tokens, eng.slots[s1].tokens] == ref


@pytest.mark.slow  # covered shape-wise by the single-prompt e2e above;
# runs in make test-paged / test-all (tier-1 guards the 870s budget)
def test_scheduler_multi_prompt_entry_resolves_atomically(server, sequential):
    from paddlefleetx_tpu.core.continuous_batching import ContinuousScheduler

    sched = ContinuousScheduler(_engine(server), max_depth=8)
    sched.start()
    fut = sched.submit(PROMPTS, 6, deadline_s=120)
    rows = fut.result(timeout=300)
    assert rows == sequential
    assert sched.shutdown(timeout=30)


def test_scheduler_admission_bounds_and_close(server):
    from paddlefleetx_tpu.core.continuous_batching import ContinuousScheduler
    from paddlefleetx_tpu.core.request_queue import QueueClosed, QueueFull

    sched = ContinuousScheduler(_engine(server), max_depth=2)
    # not started: entries pile up in the waiting queue
    sched.submit([[1, 2]], 4)
    sched.submit([[3, 4]], 4)
    with pytest.raises(QueueFull):
        sched.submit([[5, 6]], 4)
    assert sched.stats["rejected_full"] == 1
    sched.close()
    with pytest.raises(QueueClosed):
        sched.submit([[7, 8]], 4)
    assert sched.stats["rejected_closed"] == 1
    # draining a never-started scheduler: flush path answers waiters
    assert sched.shutdown(drain=False, timeout=10)
    with pytest.raises(ValueError, match="non-empty"):
        sched.submit([], 4)


def test_scheduler_mid_decode_deadline_eviction(server, sequential):
    """A request whose deadline expires while its row is DECODING is
    evicted at the next step boundary: DeadlineExceeded, eviction
    counters bumped, blocks freed — and a later identical request still
    decodes token-identically (the arena was not poisoned)."""
    from paddlefleetx_tpu.core.continuous_batching import ContinuousScheduler
    from paddlefleetx_tpu.core.request_queue import DeadlineExceeded

    eng = _engine(server)
    sched = ContinuousScheduler(eng, max_depth=8)
    ev0 = sched.stats["evictions"]
    # admit by hand so the deadline can expire deterministically between
    # steps (no thread yet): entry deadline already in the past once the
    # scheduler starts iterating
    fut_doomed = sched.submit([PROMPTS[1]], 64, deadline_s=0.05)
    fut_ok = sched.submit([PROMPTS[0]], 6, deadline_s=120)
    time.sleep(0.1)  # doomed request expires while queued OR mid-decode
    sched.start()
    assert fut_ok.result(timeout=300)[0] == sequential[0]
    with pytest.raises(DeadlineExceeded):
        fut_doomed.result(timeout=60)
    assert sched.stats["shed_deadline"] >= 1
    # a fresh identical request after the shed: token-identical
    fut2 = sched.submit([PROMPTS[1]], 6, deadline_s=120)
    assert fut2.result(timeout=300)[0] == sequential[1]
    assert sched.stats["evictions"] >= ev0
    assert eng.cache.stats()["kv_blocks_used"] == 0
    assert sched.shutdown(timeout=30)


def test_scheduler_true_mid_decode_eviction_via_release(server, sequential):
    """Deterministic mid-decode eviction at the ENGINE level: evict after
    k steps, assert the survivor's final tokens equal the sequential
    reference and the evicted row's partial prefix was correct so far."""
    eng = _engine(server)
    s0 = eng.admit(PROMPTS[0], 6)
    s1 = eng.admit(PROMPTS[1], 6)
    eng.step()
    eng.step()
    partial = list(eng.slots[s1].tokens)
    assert partial == sequential[1][:len(partial)]  # correct prefix so far
    eng.release(s1)  # mid-decode eviction
    _drain(eng)
    assert eng.slots[s0].tokens == sequential[0]


def test_arena_reset_fails_live_rows_and_recovers(server, sequential, monkeypatch):
    """An injected crash during a prefill dispatch: ArenaReset fails the
    affected entry, the arena is rebuilt, and the next request decodes
    token-identically on fresh pools (the drop-donated-state contract)."""
    from paddlefleetx_tpu.core.continuous_batching import ContinuousScheduler
    from paddlefleetx_tpu.utils import resilience

    eng = _engine(server)
    sched = ContinuousScheduler(eng, max_depth=8)
    sched.start()
    ok = sched.submit([PROMPTS[0]], 6, deadline_s=120)
    assert ok.result(timeout=300)[0] == sequential[0]

    resilience.reset_fault_state()
    monkeypatch.setenv("PFX_FAULT", "gen_crash:2")  # next admission crashes
    errs0 = sched.stats["gen_errors"]
    doomed = sched.submit([PROMPTS[1]], 6, deadline_s=120)
    with pytest.raises(RuntimeError, match="gen_crash"):
        doomed.result(timeout=60)
    assert sched.stats["gen_errors"] == errs0 + 1
    monkeypatch.delenv("PFX_FAULT")
    resilience.reset_fault_state()

    again = sched.submit([PROMPTS[1]], 6, deadline_s=120)
    assert again.result(timeout=300)[0] == sequential[1]
    assert sched.shutdown(timeout=30)


# ---------------------------------------------------------------------------
# shared-prefix KV reuse + chunked prefill (docs/serving.md "Prefix
# cache"): greedy output with the cache ON must stay token-identical
# (f32 exact) to the cache-off sequential path for prefix hits, COW
# divergence, and chunked long-prompt admission mid-decode — while
# prefill-token accounting proves only the unmatched suffix computed.
# ---------------------------------------------------------------------------

import numpy as _np

_prng = _np.random.default_rng(7)
PFX_SHARED = _prng.integers(1, 95, 36).tolist()          # 2 full blocks + 4
LONG_A = PFX_SHARED + _prng.integers(1, 95, 4).tolist()  # 40 tokens
LONG_B = PFX_SHARED + _prng.integers(1, 95, 6).tolist()  # 42, diverges at 36
LONG_C = _prng.integers(1, 95, 64).tolist()              # unrelated, 4 chunks


def _ref(server, prompt):
    return server.generate_ids([prompt], max_dec_len=6)[0]


def test_prefix_hit_prefills_only_the_suffix_and_parity(server):
    """THE reuse acceptance: request B shares A's 36-token prefix (two
    full blocks + 4 tokens into A's partial tail block).  After A
    publishes, B's admission maps the full blocks SHARED, takes a COW
    copy of the partial, and computes exactly plen-36 suffix tokens —
    token-identical to the cache-off path."""
    eng = _engine(server, prefix_cache_blocks=32)
    sA = eng.admit(LONG_A, 6)
    _drain(eng)
    assert eng.slots[sA].tokens == _ref(server, LONG_A)
    eng.release(sA)  # publishes 2 full blocks + 1 partial tail
    assert eng.cache.prefix.cached_blocks() == 3
    assert eng.cache.stats()["kv_blocks_used"] == 3  # index refs only

    tok0 = eng.stats["prefill_tokens"]
    sB = eng.admit(LONG_B, 6)
    assert eng.slots[sB].prefix_hit == 36
    assert eng.stats["prefill_tokens"] - tok0 == len(LONG_B) - 36
    _drain(eng)
    assert eng.slots[sB].tokens == _ref(server, LONG_B)
    eng.release(sB)
    assert eng.cache.prefix.stats["hits"] == 1
    assert eng.cache.prefix.stats["hit_tokens"] == 36

    # a repeat of A itself: full-prompt hit capped at plen-1 (the last
    # token always recomputes — admission needs its logits)
    tok0 = eng.stats["prefill_tokens"]
    sA2 = eng.admit(LONG_A, 6)
    assert eng.slots[sA2].prefix_hit == len(LONG_A) - 1
    assert eng.stats["prefill_tokens"] - tok0 == 1
    _drain(eng)
    assert eng.slots[sA2].tokens == _ref(server, LONG_A)
    eng.release(sA2)


def test_cow_divergence_never_corrupts_the_cached_prefix(server):
    """Copy-on-write both ways — inside a partially-filled tail block
    (LONG_B at token 36) and inside a FULL cached block (divergence at
    token 20) — and the cached original stays intact: A re-requested
    AFTER both divergent rows decoded is still token-identical."""
    eng = _engine(server, prefix_cache_blocks=32)
    sA = eng.admit(LONG_A, 6)
    _drain(eng)
    eng.release(sA)

    s1 = eng.admit(LONG_B, 6)  # diverges inside the partial tail
    # guaranteed divergence at token 20, inside full block 1
    div = [(t % 93) + 1 for t in LONG_A[20:26]]
    mid = LONG_A[:20] + div
    s2 = eng.admit(mid, 6)
    assert eng.slots[s1].prefix_hit == 36
    assert eng.slots[s2].prefix_hit == 20  # block 0 shared + 4-token COW
    _drain(eng)
    assert eng.slots[s1].tokens == _ref(server, LONG_B)
    assert eng.slots[s2].tokens == _ref(server, mid)
    eng.release(s1)
    eng.release(s2)

    sA2 = eng.admit(LONG_A, 6)  # the cached blocks must be unmodified
    _drain(eng)
    assert eng.slots[sA2].tokens == _ref(server, LONG_A)
    eng.release(sA2)


def test_shared_block_accounting_counts_physical_once(server):
    """Two live rows sharing one cached prefix: pfx_kv_blocks_used /
    pfx_kv_bytes count each physical block ONCE (a per-row summation
    would overstate occupancy and trip the controller's occupancy>0.9
    scale-up spuriously), and no gauge can exceed the arena."""
    eng = _engine(server, prefix_cache_blocks=32)
    sA = eng.admit(LONG_A, 6)
    _drain(eng)
    eng.release(sA)  # 3 cached blocks

    s1 = eng.admit(LONG_A, 6)  # shares 2 full + COW of the tail
    s2 = eng.admit(LONG_A, 6)
    per_row = len(eng.slots[s1].table)
    naive = eng.cache.prefix.cached_blocks() + 2 * per_row
    used = eng.cache.stats()["kv_blocks_used"]
    # physical: 3 cached + one fresh COW block per row
    assert used == 5 < naive
    usable = eng.cache.allocator.num_blocks - 1
    assert used + eng.cache.stats()["kv_blocks_free"] == usable
    assert eng.cache.stats()["prefix_cached_blocks"] == 3
    _drain(eng)
    assert eng.slots[s1].tokens == _ref(server, LONG_A)
    assert eng.slots[s2].tokens == _ref(server, LONG_A)
    eng.release(s1)
    eng.release(s2)
    assert eng.cache.stats()["kv_blocks_used"] == \
        eng.cache.prefix.cached_blocks()


def test_chunked_prefill_interleaves_with_decode_and_parity(server):
    """A 64-token prompt admitted with --prefill-chunk 16 streams in one
    chunk per step while an already-active row keeps decoding: the
    decode row's output is untouched, the chunked prompt's output is
    token-identical, and exactly ceil(64/16) chunks ran."""
    eng = _engine(server, prefill_chunk=16)
    s0 = eng.admit(PROMPTS[0], 6)  # short row, starts decoding at once
    eng.step()
    pos_before = int(eng.positions[s0])
    c0 = eng.stats["prefill_chunks"]
    sC = eng.admit(LONG_C, 6)  # long prompt: mid-prefill on return
    assert not eng.slots[sC].prefill_done
    assert not eng.active[sC]
    eng.step()  # one chunk for C AND one decode step for row 0
    assert int(eng.positions[s0]) == pos_before + 1  # decode never stalled
    _drain(eng)
    assert eng.stats["prefill_chunks"] - c0 == 4
    assert eng.slots[s0].tokens == _ref(server, PROMPTS[0])
    assert eng.slots[sC].tokens == _ref(server, LONG_C)
    eng.release(s0)
    eng.release(sC)


@pytest.mark.slow  # composition coverage: the prefix CLI drill boots
# --prefix-cache-blocks + --prefill-chunk together and asserts hit +
# chunk counters with token-identical output, and the hit-side
# suffix-only accounting stays tier-1 via
# test_prefix_hit_prefills_only_the_suffix_and_parity; this variant's
# fresh 64/72-token buckets are the costly part — runs in
# make test-prefix / test-paged / test-all
def test_chunked_prefill_with_prefix_hit_computes_suffix_chunks_only(server):
    """Prefix cache + chunked prefill composed: a prompt extending a
    cached one chunk-prefills ONLY the unmatched suffix."""
    eng = _engine(server, prefix_cache_blocks=32, prefill_chunk=16)
    sC = eng.admit(LONG_C, 6)
    _drain(eng)
    assert eng.slots[sC].tokens == _ref(server, LONG_C)
    eng.release(sC)  # publishes 4 full blocks
    assert eng.cache.prefix.cached_blocks() == 4

    ext = LONG_C + _prng.integers(1, 95, 8).tolist()  # 72 tokens, hit 64
    tok0 = eng.stats["prefill_tokens"]
    sE = eng.admit(ext, 6)
    assert eng.slots[sE].prefix_hit == 64
    _drain(eng)
    assert eng.stats["prefill_tokens"] - tok0 == len(ext) - 64
    assert eng.slots[sE].tokens == _ref(server, ext)
    eng.release(sE)


def test_arena_reset_rebuilds_prefix_index_empty(server):
    """ArenaReset invariant: rebuilt pools hold none of the old KV, so
    donation-invalidated blocks must never resurface as cache hits —
    the index comes back EMPTY and the next identical request is an
    honest miss that still decodes token-identically."""
    eng = _engine(server, prefix_cache_blocks=32)
    sA = eng.admit(LONG_A, 6)
    _drain(eng)
    eng.release(sA)
    assert eng.cache.prefix.cached_blocks() == 3
    dead = eng.reset()
    assert dead == []
    assert eng.cache.prefix.cached_blocks() == 0
    assert eng.cache.stats()["kv_blocks_used"] == 0
    m0 = eng.cache.prefix.stats["misses"]
    sA2 = eng.admit(LONG_A, 6)
    assert eng.slots[sA2].prefix_hit == 0
    assert eng.cache.prefix.stats["misses"] == m0 + 1
    _drain(eng)
    assert eng.slots[sA2].tokens == _ref(server, LONG_A)
    eng.release(sA2)


@pytest.mark.slow  # the tiny 8-block arena keys fresh pool-shape
# compiles; the eviction-never-reclaims-a-live-block contract stays
# tier-1 via the host units (test_prefix_cache.py: refcounted evict_for
# + manager evict-on-demand + atomic exhaustion) — this device-parity
# variant runs in make test-prefix / test-paged / test-all
def test_allocation_pressure_evicts_cache_but_never_live_blocks(server):
    """With the pool nearly full of cached prefixes, a new admission
    evicts unreferenced cached blocks instead of failing — and blocks a
    live row still shares survive the eviction (its decode stays
    token-identical)."""
    # 7 usable blocks: A caches 3, B shares 2 of them + 1 fresh
    eng = _engine(server, num_blocks=8, prefix_cache_blocks=8)
    sA = eng.admit(LONG_A, 6)
    _drain(eng)
    eng.release(sA)
    sB = eng.admit(LONG_A, 6)  # holds refs on the 2 shared blocks
    eng.step()
    # C needs 4 blocks; free = 7 - 3(cached) - 1(B fresh) = 3 -> must evict
    big = _prng.integers(1, 95, 52).tolist()
    ev0 = eng.cache.prefix.stats["evictions"]
    sC = eng.admit(big, 6)
    assert eng.cache.prefix.stats["evictions"] > ev0
    usable = eng.cache.allocator.num_blocks - 1
    assert eng.cache.stats()["kv_blocks_used"] <= usable
    _drain(eng)
    assert eng.slots[sB].tokens == _ref(server, LONG_A)  # survived eviction
    assert eng.slots[sC].tokens == _ref(server, big)
    eng.release(sB)
    eng.release(sC)


def test_scheduler_prefix_replay_contract_and_counters(server):
    """The decision-log replay contract, prefix edition: an untruncated
    log reproduces pfx_prefix_hits_total exactly alongside the PR 8
    trio, and the registry counter matches the per-instance stats."""
    from paddlefleetx_tpu.core.continuous_batching import ContinuousScheduler
    from paddlefleetx_tpu.utils.telemetry import get_registry
    from paddlefleetx_tpu.utils.tracing import replay_decision_log

    reg = get_registry()
    h0 = reg.value("pfx_prefix_hits_total") or 0
    eng = _engine(server, prefix_cache_blocks=32)
    sched = ContinuousScheduler(eng, max_depth=8)
    sched.start()
    assert sched.submit([LONG_A], 6, deadline_s=120).result(timeout=300)[0] \
        == _ref(server, LONG_A)
    assert sched.submit([LONG_B], 6, deadline_s=120).result(timeout=300)[0] \
        == _ref(server, LONG_B)
    assert sched.shutdown(timeout=30)

    replay = replay_decision_log(sched.decision_log)
    assert replay["prefix_hits"] == eng.cache.prefix.stats["hits"] == 1
    assert replay["prefix_hit_tokens"] == \
        eng.cache.prefix.stats["hit_tokens"] == 36
    assert (reg.value("pfx_prefix_hits_total") or 0) - h0 == 1
    assert replay["prefill_admits"] == sched.stats["prefill_admits"] == 2
    # chunk rows: LONG_B's suffix rode the chunk family (one dispatch)
    assert replay["chunks"] == eng.stats["prefill_chunks"] >= 1


@pytest.mark.slow  # two fresh sampling-path compiles; tier-1 keeps the
# greedy acceptance suite, make test-paged / test-all run this
def test_sampling_path_runs_and_is_deterministic(server):
    """Sampling rows draw from per-step engine subkeys: not the
    contiguous path's stream, but fully deterministic given the seed —
    two fresh engines produce identical tokens."""
    import dataclasses

    outs = []
    for _ in range(2):
        eng = _engine(server)
        eng.gen = dataclasses.replace(
            server.gen, decode_strategy="sampling", top_p=0.9
        )
        eng._gen_key = dataclasses.replace(eng.gen, max_dec_len=0)
        s = eng.admit([1, 2, 3, 4], 8)
        _drain(eng)
        outs.append(list(eng.slots[s].tokens))
    assert outs[0] == outs[1]
    assert 1 <= len(outs[0]) <= 8
