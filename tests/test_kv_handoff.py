"""KV-handoff serialization + disaggregated prefill/decode adoption
(`core/paged_cache.pack_handoff`/`unpack_handoff`,
`PagedDecodeEngine.prefill_export`/`adopt`,
`ContinuousScheduler.submit_handoff`).

The acceptance contracts, in-process and deterministic:

  - the payload codec round-trips BIT-exactly (bf16/native and int8 with
    its scale planes) and is loud on truncation/corruption;
  - an incompatible payload (block size, kv dtype, pool shape) is
    rejected loudly BEFORE touching a live arena;
  - export-on-one-engine -> adopt-on-another continues the decode
    token-identically to a single-process `admit` (f32 exact) — the
    multi-host disaggregation's parity spine (the subprocess drill in
    tests/test_router_drills.py proves the same thing through the real
    CLIs).
"""

import numpy as np
import pytest

TINY = {
    "Global": {"global_batch_size": 8, "seed": 3},
    "Engine": {"mix_precision": {"enable": False},
               "save_load": {"save_steps": 0}},
    "Model": {
        "module": "GPTModule",
        "vocab_size": 96,
        "hidden_size": 32,
        "num_layers": 2,
        "num_attention_heads": 4,
        "max_position_embeddings": 128,
        "dtype": "float32",
    },
    "Distributed": {},
    "Optimizer": {"name": "FusedAdamW",
                  "lr": {"name": "Constant", "learning_rate": 1e-3}},
    "Generation": {"max_dec_len": 8, "decode_strategy": "greedy_search",
                   "pad_to_multiple": 16, "eos_token_id": 95,
                   "pad_token_id": 0},
}

PROMPTS = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 10], [11, 12, 13, 14]]


@pytest.fixture(scope="module")
def server():
    import jax

    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict.from_nested(TINY)
    cfg = process_configs(cfg, num_devices=jax.device_count())
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    return GenerationServer(cfg, mesh, module)


def _engine(server, **kw):
    from paddlefleetx_tpu.core.continuous_batching import PagedDecodeEngine

    kw.setdefault("max_batch", 4)
    return PagedDecodeEngine(server, **kw)


def _drain(engine, max_steps=64):
    for _ in range(max_steps):
        engine.step()
        if not engine.active.any():
            return
    raise AssertionError("engine never drained")


@pytest.fixture(scope="module")
def sequential(server):
    """Reference outputs: each request served alone on the coalesce path."""
    return [server.generate_ids([p], max_dec_len=6)[0] for p in PROMPTS]


# ---------------------------------------------------------------------------
# payload codec (pure host)
# ---------------------------------------------------------------------------


def test_pack_unpack_roundtrip_bit_exact():
    from paddlefleetx_tpu.core.paged_cache import pack_handoff, unpack_handoff

    rng = np.random.default_rng(0)
    arrays = {
        "k": rng.standard_normal((2, 3, 4, 16, 8)).astype(np.float32),
        "v": rng.standard_normal((2, 3, 4, 16, 8)).astype(np.float32),
        "k_scale": rng.standard_normal((2, 3, 4, 16)).astype(np.float32),
        "q": rng.integers(-127, 128, (2, 3, 4, 16, 8)).astype(np.int8),
        "logits": rng.standard_normal((96,)).astype(np.float32),
        "counts": rng.integers(0, 5, (96,)).astype(np.int32),
    }
    meta = {"prompt_ids": [1, 2, 3], "prompt_len": 3, "max_new": 6,
            "block": 16, "kv_dtype": "int8", "pool_sig": [2, 4, 16, 8]}
    payload = pack_handoff(meta, arrays)
    meta2, arrays2 = unpack_handoff(payload)
    assert meta2 == meta
    assert set(arrays2) == set(arrays)
    for name, a in arrays.items():
        assert arrays2[name].dtype == a.dtype, name
        assert arrays2[name].shape == a.shape, name
        # BIT-exact: the decode replica adopts the same bytes the prefill
        # replica exported — quantized values never re-quantize
        assert arrays2[name].tobytes() == a.tobytes(), name


def test_unpack_rejects_corruption_loudly():
    from paddlefleetx_tpu.core.paged_cache import pack_handoff, unpack_handoff

    payload = pack_handoff(
        {"block": 16}, {"k": np.ones((2, 2), np.float32)}
    )
    with pytest.raises(ValueError, match="magic"):
        unpack_handoff(b"NOPE" + payload[4:])
    with pytest.raises(ValueError, match="truncated"):
        unpack_handoff(payload[:7])
    with pytest.raises(ValueError, match="truncated"):
        unpack_handoff(payload[:-3])  # torn array bytes
    with pytest.raises(ValueError, match="trailing"):
        unpack_handoff(payload + b"xx")


def test_unpack_truncation_at_every_section_boundary():
    """Cut the payload at EVERY section boundary — inside the magic, at
    the header-length word, inside the JSON header, at each inter-array
    boundary, mid-buffer, and past the end — and demand a ValueError
    that names what is missing.  A migration receiver sees exactly these
    shapes when a drain-time transfer is torn mid-flight."""
    import struct

    from paddlefleetx_tpu.core.paged_cache import (
        HANDOFF_MAGIC,
        pack_handoff,
        unpack_handoff,
    )

    rng = np.random.default_rng(7)
    arrays = {
        "k": rng.standard_normal((2, 3, 4)).astype(np.float32),
        "v": rng.standard_normal((2, 3, 4)).astype(np.float32),
    }
    payload = pack_handoff({"block": 16, "kv_dtype": "bf16"}, arrays)
    (hlen,) = struct.unpack("<I", payload[5:9])
    first_end = 9 + hlen + arrays["k"].nbytes  # end of first buffer

    cuts = {
        0: "magic",                  # empty payload
        3: "magic",                  # inside the magic
        5: "header length",          # magic only, no length word
        7: "header length",          # torn uint32
        9: "header wants",           # length word, zero header bytes
        9 + hlen // 2: "header wants",          # mid-JSON
        9 + hlen: "truncated",       # header complete, zero array bytes
        first_end - 2: "truncated",  # mid first buffer
        first_end: "'v' wants",      # exactly between the two buffers
        first_end + 2: "truncated",  # mid second buffer
    }
    for cut, needle in cuts.items():
        with pytest.raises(ValueError, match=needle):
            unpack_handoff(payload[:cut])
        # prefix-of-garbage variant: same cut with trailing junk bytes
        # must not be accepted either (the length checks are per-section)
    with pytest.raises(ValueError, match="trailing"):
        unpack_handoff(payload + b"\x00")
    # sanity: the intact payload still round-trips after all that
    meta2, arrays2 = unpack_handoff(payload)
    assert arrays2["v"].tobytes() == arrays["v"].tobytes()


def test_unpack_rejects_future_codec_version():
    """A PFXH2 payload (future codec rev) is refused with the magic
    error, not misparsed: mixed-version fleets during a rolling upgrade
    degrade to recompute instead of adopting bytes they cannot read."""
    from paddlefleetx_tpu.core.paged_cache import pack_handoff, unpack_handoff

    payload = pack_handoff({"block": 16}, {"k": np.ones((2, 2), np.float32)})
    bumped = b"PFXH2" + payload[5:]
    with pytest.raises(ValueError, match="PFXH1"):
        unpack_handoff(bumped)


def test_check_handoff_meta_names_malformed_fields():
    """A malformed signature value (string block size, pool_sig of
    dicts) lands as a NAMED problem in the incompatibility error — never
    a bare TypeError that hides which field was wrong."""
    from paddlefleetx_tpu.core.paged_cache import check_handoff_meta

    with pytest.raises(ValueError, match="block size 'sixteen' is not"):
        check_handoff_meta(
            {"block": "sixteen", "kv_dtype": "bf16",
             "pool_sig": [2, 4, 16, 8]},
            block=16, kv_dtype="bf16", pool_sig=[2, 4, 16, 8])
    with pytest.raises(ValueError, match="pool_sig .* not a list of int"):
        check_handoff_meta(
            {"block": 16, "kv_dtype": "bf16", "pool_sig": [{"layers": 2}]},
            block=16, kv_dtype="bf16", pool_sig=[2, 4, 16, 8])
    # several problems at once: ALL named in the one error
    with pytest.raises(ValueError) as ei:
        check_handoff_meta(
            {"block": None, "kv_dtype": "int8", "pool_sig": "nope"},
            block=16, kv_dtype="bf16", pool_sig=[2, 4, 16, 8])
    msg = str(ei.value)
    assert "block size" in msg and "kv dtype" in msg and "pool_sig" in msg


def test_check_handoff_meta_names_every_mismatch():
    from paddlefleetx_tpu.core.paged_cache import check_handoff_meta

    meta = {"block": 16, "kv_dtype": "bf16", "pool_sig": [2, 4, 16, 8]}
    check_handoff_meta(meta, block=16, kv_dtype="bf16",
                       pool_sig=[2, 4, 16, 8])  # compatible: no raise
    with pytest.raises(ValueError, match="block size 16 != arena block 32"):
        check_handoff_meta(meta, block=32, kv_dtype="bf16",
                           pool_sig=[2, 4, 16, 8])
    with pytest.raises(ValueError, match="kv dtype"):
        check_handoff_meta(meta, block=16, kv_dtype="int8",
                           pool_sig=[2, 4, 16, 8])
    with pytest.raises(ValueError, match="pool shape"):
        check_handoff_meta(meta, block=16, kv_dtype="bf16",
                           pool_sig=[4, 4, 16, 8])


# ---------------------------------------------------------------------------
# export -> adopt parity (the disaggregation spine)
# ---------------------------------------------------------------------------


def test_export_adopt_parity_native(server, sequential):
    """Prefill on engine A, serialize, adopt on engine B (a separate
    arena), decode to completion: token-identical to the sequential
    reference, including adoptions landing MID-decode of other rows."""
    from paddlefleetx_tpu.core.paged_cache import pack_handoff, unpack_handoff

    exporter = _engine(server)
    decoder = _engine(server)

    def handoff(i):
        meta, arrays = exporter.prefill_export(PROMPTS[i], 6)
        # through the real payload bytes, not object handles
        meta2, arrays2 = unpack_handoff(pack_handoff(meta, arrays))
        return decoder.adopt(meta2, arrays2)

    s0 = handoff(0)
    s1 = handoff(1)
    decoder.step()
    decoder.step()
    s2 = handoff(2)  # adopted mid-decode of rows 0/1
    decoder.step()
    s3 = handoff(3)
    _drain(decoder)
    got = [decoder.slots[s].tokens for s in (s0, s1, s2, s3)]
    assert got == sequential
    for s in (s0, s1, s2, s3):
        decoder.release(s)
    assert decoder.cache.stats()["kv_blocks_used"] == 0
    # the exporter held blocks only for the duration of each export
    assert exporter.cache.stats()["kv_blocks_used"] == 0
    assert exporter.stats["exports"] == 4
    assert decoder.stats["adopts"] == 4


def test_export_adopt_int8_blocks_and_scales_bit_exact(server):
    """An int8 arena's handoff ships the quantized blocks AND their
    per-(slot, head) scale planes; gathering the adopted row back out of
    the decode arena reproduces the payload bit-for-bit (no second
    quantization), and the continued decode matches the single-process
    int8 engine token-for-token."""
    from paddlefleetx_tpu.core.paged_cache import (
        blocks_for,
        pack_handoff,
        unpack_handoff,
    )
    from paddlefleetx_tpu.models.gpt.generation import (
        bucket_len,
        gather_kv_blocks,
    )

    exporter = _engine(server, kv_dtype="int8")
    decoder = _engine(server, kv_dtype="int8")
    reference = _engine(server, kv_dtype="int8")

    meta, arrays = exporter.prefill_export(PROMPTS[0], 6)
    assert {"k", "v", "k_scale", "v_scale"} <= set(arrays)
    assert arrays["k"].dtype == np.int8
    assert arrays["k_scale"].dtype == np.float32
    meta2, arrays2 = unpack_handoff(pack_handoff(meta, arrays))
    slot = decoder.adopt(meta2, arrays2)

    # adopted row's first PB blocks == the exported payload, bit-exact
    row = decoder.slots[slot]
    PB = blocks_for(bucket_len(len(PROMPTS[0]), decoder.bucket),
                    decoder.block)
    adopted = gather_kv_blocks(decoder.pools, row.table[:PB])
    for name in ("k", "v", "k_scale", "v_scale"):
        assert adopted[name].tobytes() == arrays[name].tobytes(), name

    ref_slot = reference.admit(PROMPTS[0], 6)
    _drain(reference)
    _drain(decoder)
    assert decoder.slots[slot].tokens == reference.slots[ref_slot].tokens


def test_adopt_rejects_incompatible_payload_loudly(server):
    """Dtype and block-size mismatches fail BEFORE touching the arena:
    the decode engine keeps serving and its pool stays clean."""
    from paddlefleetx_tpu.core.paged_cache import pack_handoff, unpack_handoff

    exporter = _engine(server, kv_dtype="int8")
    meta, arrays = unpack_handoff(
        pack_handoff(*exporter.prefill_export(PROMPTS[0], 6))
    )

    bf16_engine = _engine(server)  # native arena
    with pytest.raises(ValueError, match="kv dtype"):
        bf16_engine.adopt(meta, arrays)
    assert bf16_engine.cache.stats()["kv_blocks_used"] == 0

    wide = _engine(server, kv_dtype="int8", block=32)
    with pytest.raises(ValueError, match="block size"):
        wide.adopt(meta, arrays)
    assert wide.cache.stats()["kv_blocks_used"] == 0

    # a lying header (right signature, wrong payload bytes) is caught by
    # the scatter-side shape check, and the allocation is rolled back
    ok_engine = _engine(server, kv_dtype="int8")
    bad = dict(arrays)
    bad["k"] = arrays["k"][:, :0]  # right dtype, empty blocks
    with pytest.raises(Exception, match="shape|cover"):
        ok_engine.adopt(meta, bad)
    assert ok_engine.cache.stats()["kv_blocks_used"] == 0


def test_scheduler_submit_handoff_end_to_end(server, sequential):
    """`ContinuousScheduler.submit_handoff`: a payload rides the same
    bounded-queue/deadline surface as submit() and resolves to the
    sequential-reference tokens; an incompatible payload is rejected
    pre-admission with ValueError (HTTP 400), never queued."""
    from paddlefleetx_tpu.core.continuous_batching import ContinuousScheduler
    from paddlefleetx_tpu.core.paged_cache import pack_handoff, unpack_handoff

    exporter = _engine(server)
    sched = ContinuousScheduler(_engine(server), max_depth=8,
                                name="handoff-test")
    sched.start()
    try:
        futs = []
        for p in PROMPTS:
            meta, arrays = unpack_handoff(
                pack_handoff(*exporter.prefill_export(p, 6))
            )
            futs.append(sched.submit_handoff(meta, arrays, deadline_s=60))
        got = [f.result(timeout=120)[0] for f in futs]
        assert got == sequential

        # pre-admission rejection: wrong-dtype payload never takes a slot
        bad_meta, bad_arrays = _engine(
            server, kv_dtype="int8"
        ).prefill_export(PROMPTS[0], 6)
        with pytest.raises(ValueError, match="kv dtype"):
            sched.submit_handoff(bad_meta, bad_arrays, deadline_s=60)
        assert sched.depth() == 0
    finally:
        sched.shutdown(drain=False, timeout=30)


# ---------------------------------------------------------------------------
# prefix reuse on the disaggregated prefill pool (ROADMAP remainder)
# ---------------------------------------------------------------------------


def test_prefill_export_prefix_reuse_computes_suffix_only(server):
    """With ``prefix_cache_blocks`` on a prefill-pool engine, a shared
    system prefix is computed ONCE per replica: the first export
    publishes its prompt blocks into the radix index, the second
    same-prefix export maps the matched span SHARED (plus a COW block
    for the mid-block divergence) and runs only the suffix through the
    chunk family — with the adopted decode still token-identical to the
    cache-off path."""
    from paddlefleetx_tpu.core.paged_cache import pack_handoff, unpack_handoff

    sys_prefix = list(range(1, 35))             # 34 tokens: 2 full blocks + tail
    p1 = sys_prefix + [40, 41, 42]              # 37 tokens
    p2 = sys_prefix + [50, 51]                  # 36 tokens, diverges mid-block
    ref = [server.generate_ids([p], max_dec_len=6)[0] for p in (p1, p2)]

    exporter = _engine(server, prefix_cache_blocks=16)
    decoder = _engine(server)

    def handoff(p):
        meta, arrays = exporter.prefill_export(p, 6)
        meta2, arrays2 = unpack_handoff(pack_handoff(meta, arrays))
        return decoder.adopt(meta2, arrays2)

    t0 = exporter.stats["prefill_tokens"]
    s1 = handoff(p1)
    assert exporter.stats["prefill_tokens"] - t0 == len(p1)  # full compute
    assert exporter.cache.prefix.stats["misses"] == 1
    assert exporter.cache.prefix.cached_blocks() > 0  # published

    t1 = exporter.stats["prefill_tokens"]
    c1 = exporter.stats["prefill_chunks"]
    s2 = handoff(p2)
    # the shared 34-token span (2 full blocks + a 2-token COW overlap)
    # was NOT recomputed: only the 2-token suffix ran, via the chunk fn
    assert exporter.cache.prefix.stats["hits"] == 1
    assert exporter.cache.prefix.stats["hit_tokens"] == 34
    assert exporter.stats["prefill_tokens"] - t1 == len(p2) - 34
    assert exporter.stats["prefill_chunks"] > c1

    _drain(decoder)
    got = [decoder.slots[s].tokens for s in (s1, s2)]
    assert got == ref  # f32 exact, COW never corrupted the cached copy
    for s in (s1, s2):
        decoder.release(s)
    assert decoder.cache.stats()["kv_blocks_used"] == 0
    # the exporter's remaining allocation is exactly the cached index
    assert (exporter.cache.stats()["kv_blocks_used"]
            == exporter.cache.prefix.cached_blocks())
