"""CLIP tests: tower shapes, EOT pooling, contrastive loss properties,
overfit, dp sharding."""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from paddlefleetx_tpu.models.multimodal import clip
from paddlefleetx_tpu.models.multimodal.clip import CLIPConfig

TINY = CLIPConfig(
    projection_dim=16,
    image_size=32,
    patch_size=8,
    vision_hidden_size=32,
    vision_layers=2,
    vision_heads=4,
    vocab_size=96,
    max_text_len=16,
    text_hidden_size=32,
    text_layers=2,
    text_heads=4,
    dtype="float32",
)


def _batch(cfg, b=4, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(2, cfg.vocab_size, (b, 12))
    ids[:, -3:] = cfg.pad_token_id
    return {
        "images": jnp.asarray(rng.normal(size=(b, cfg.image_size, cfg.image_size, 3)), jnp.float32),
        "input_ids": jnp.asarray(ids),
    }


def test_tower_shapes_normalized():
    params = clip.init(TINY, jax.random.key(0))
    batch = _batch(TINY)
    img = clip.encode_image(params, batch["images"], TINY)
    txt = clip.encode_text(params, batch["input_ids"], TINY)
    assert img.shape == (4, 16) and txt.shape == (4, 16)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(img), axis=1), 1.0, rtol=1e-4)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(txt), axis=1), 1.0, rtol=1e-4)


def test_eot_pooling_ignores_pad_tail():
    """The pad tail must not affect the text embedding: encoding the
    unpadded prefix gives the same features (causal attention + EOT
    pooling at the last non-pad position)."""
    params = clip.init(TINY, jax.random.key(1))
    ids = _batch(TINY)["input_ids"]  # 9 real tokens + 3 pad
    a = clip.encode_text(params, ids, TINY)
    b = clip.encode_text(params, ids[:, :9], TINY)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_loss_level_and_symmetry():
    params = clip.init(TINY, jax.random.key(2))
    batch = _batch(TINY)
    loss = clip.clip_loss(params, batch, TINY, train=False)
    # random towers: positive, finite, same ballpark as ln(b) (the
    # 1/0.07 initial temperature amplifies random cosine sims, so the
    # spread around ln(b) is wide at tiny embedding dims)
    assert np.isfinite(float(loss)) and 0.0 < float(loss) < 6.0


def test_overfit_tiny():
    import optax

    params = clip.init(TINY, jax.random.key(3))
    batch = _batch(TINY)
    tx = optax.adam(1e-2)
    opt = tx.init(params)

    @jax.jit
    def step(p, o):
        loss, g = jax.value_and_grad(
            lambda pp: clip.clip_loss(pp, batch, TINY, train=True)
        )(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    # the loss plateaus at ln(b) (uniform logits) around step 10-40 before
    # the towers align; 80 steps breaks through on this seed
    for _ in range(80):
        params, opt, loss = step(params, opt)
    assert float(loss) < 0.1


@pytest.mark.slow  # ~19s grad compile; CLIP tier-1 keeps tower_shapes +
# overfit_tiny (forward+grad paths); runs in make test-all (PR 8 budget)
def test_logit_scale_clamped():
    params = clip.init(TINY, jax.random.key(4))
    params["logit_scale"] = jnp.asarray(10.0)  # exp(10) >> 100
    _, _, scale = clip.forward(params, _batch(TINY), TINY)
    assert abs(float(scale) - 100.0) < 1e-3
    # straight-through: gradient still reaches logit_scale past the clamp
    g = jax.grad(
        lambda p: clip.clip_loss(p, _batch(TINY), TINY, train=False)
    )(params)["logit_scale"]
    assert float(jnp.abs(g)) > 0.0


def test_module_and_dp_engine(devices8, tmp_path):
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict

    cfg = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": 8, "micro_batch_size": 8, "seed": 3},
            "Engine": {
                "max_steps": 4, "eval_freq": 0, "logging_freq": 2,
                "mix_precision": {"enable": False},
                "save_load": {"save_steps": 0, "output_dir": str(tmp_path)},
            },
            "Model": dict(module="CLIPModule", projection_dim=16, image_size=32,
                          patch_size=8, vision_hidden_size=32, vision_layers=2,
                          vision_heads=4, vocab_size=96, max_text_len=16,
                          text_hidden_size=32, text_layers=2, text_heads=4,
                          dtype="float32"),
            "Distributed": {"dp_degree": 4, "mp_degree": 2},
            "Data": {},
            "Optimizer": {
                "name": "FusedAdamW", "weight_decay": 0.01,
                "lr": {"name": "CosineAnnealingWithWarmupDecay", "decay_steps": 100,
                       "warmup_rate": 0.1, "max_lr": 1e-3, "min_lr": 1e-4},
            },
        }
    )
    mesh = init_dist_env(cfg)
    eng = Engine(cfg, build_module(cfg), mesh)
    rng = np.random.default_rng(0)

    def loader():
        while True:
            ids = rng.integers(2, 96, (8, 12))
            yield {
                "images": rng.normal(size=(8, 32, 32, 3)).astype(np.float32),
                "input_ids": ids,
            }

    eng.fit(loader())
