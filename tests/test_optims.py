"""Optimizer / LR schedule tests."""

import jax
import jax.numpy as jnp
import numpy as np

from paddlefleetx_tpu.optims.lr_scheduler import build_lr_scheduler
from paddlefleetx_tpu.optims.optimizer import build_optimizer


def test_cosine_warmup_shape():
    sch = build_lr_scheduler(
        dict(
            name="CosineAnnealingWithWarmupDecay",
            max_lr=1e-3,
            min_lr=1e-5,
            warmup_rate=0.1,
            decay_steps=1000,
        )
    )
    assert float(sch(0)) == 0.0
    assert abs(float(sch(100)) - 1e-3) < 1e-6  # end of warmup
    assert float(sch(50)) < 1e-3
    assert abs(float(sch(1000)) - 1e-5) < 1e-6
    assert float(sch(2000)) == float(sch(1000))  # clamps at min


def test_linear_decay():
    sch = build_lr_scheduler(
        dict(name="LinearDecayWithWarmup", learning_rate=1e-2, total_steps=100, warmup=0.1)
    )
    assert abs(float(sch(10)) - 1e-2) < 1e-6
    assert abs(float(sch(100))) < 1e-6


def test_multistep():
    sch = build_lr_scheduler(dict(name="MultiStepDecay", learning_rate=1.0, milestones=[5, 10]))
    assert float(sch(0)) == 1.0
    assert abs(float(sch(5)) - 0.1) < 1e-6
    assert abs(float(sch(10)) - 0.01) < 1e-6


def test_adamw_decay_mask_and_step():
    tx, sch = build_optimizer(
        dict(
            name="FusedAdamW",
            weight_decay=0.5,
            beta1=0.9,
            beta2=0.999,
            epsilon=1e-8,
            lr={"name": "Constant", "learning_rate": 0.1},
            grad_clip={"name": "ClipGradByGlobalNorm", "clip_norm": 1.0},
        )
    )
    params = {"w": jnp.ones((4, 4)), "b": jnp.ones((4,))}
    state = tx.init(params)
    grads = {"w": jnp.zeros((4, 4)), "b": jnp.zeros((4,))}
    upd, state = tx.update(grads, state, params)
    # zero grad: decayed weights move, bias (1-D, masked) does not
    assert float(jnp.abs(upd["w"]).sum()) > 0
    assert float(jnp.abs(upd["b"]).sum()) == 0


def test_grad_clip_applied():
    tx, _ = build_optimizer(
        dict(
            name="AdamW",
            lr={"name": "Constant", "learning_rate": 1.0},
            grad_clip={"name": "ClipGradByGlobalNorm", "clip_norm": 1e-6},
            weight_decay=0.0,
        )
    )
    params = {"w": jnp.ones((2,))}
    state = tx.init(params)
    g1 = {"w": jnp.array([1000.0, 0.0])}
    u1, _ = tx.update(g1, state, params)
    # tiny clip norm -> tiny effective grads -> update ~ lr * sign only after
    # adam normalization; just check finite + bounded
    assert np.all(np.isfinite(np.asarray(u1["w"])))


def test_adamw_bf16_moments():
    """moment_dtype=bfloat16 stores mu in bf16 and still trains sanely."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddlefleetx_tpu.optims.optimizer import build_optimizer

    cfg = {
        "name": "FusedAdamW",
        "weight_decay": 0.0,
        "moment_dtype": "bfloat16",
        "lr": {"name": "Constant", "learning_rate": 0.1},
    }
    tx, _ = build_optimizer(cfg)
    params = {"w": jnp.ones((4, 4))}
    st = tx.init(params)
    mus = [x for x in jax.tree.leaves(st) if getattr(x, "dtype", None) == jnp.bfloat16]
    assert mus, "no bf16 moment found in optimizer state"
    g = {"w": jnp.full((4, 4), 0.5)}
    upd, st = tx.update(g, st, params)
    p2 = jax.tree.map(lambda p, u: p + u, params, upd)
    assert np.all(np.asarray(p2["w"]) < 1.0)


def test_grad_clip_scalar_shorthand():
    """`grad_clip: 1.0` (T5 base yaml form) == ClipGradByGlobalNorm."""
    from paddlefleetx_tpu.optims.optimizer import build_optimizer
    from paddlefleetx_tpu.utils.config import AttrDict

    tx, _ = build_optimizer(AttrDict.from_nested({
        "name": "AdamW",
        "lr": {"name": "Constant", "learning_rate": 1e-3},
        "grad_clip": 1.0,
    }))
    import jax.numpy as jnp

    params = {"w": jnp.full((4,), 100.0)}
    state = tx.init(params)
    grads = {"w": jnp.full((4,), 100.0)}  # norm 200 >> 1 -> clipped
    updates, _ = tx.update(grads, state, params)
    # with clipping active the update magnitude is bounded by lr
    assert float(jnp.abs(updates["w"]).max()) <= 1.1e-3
