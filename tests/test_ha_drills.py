"""Control-plane survivability chaos drills through the real CLIs
(`make test-ha`, docs/serving.md "Control-plane recovery"): SIGKILL the
SUPERVISING tools/router.py itself and prove its death is a non-event.

  router-kill   SIGKILL the router mid-two-tenant-flood -> restart on
                the same ports + PFX_FLIGHT_DIR: every live replica is
                RE-ADOPTED into its slot (zero respawns, zero flap
                budget, pids unchanged), the flooding tenant's quota
                bucket restores from the journal (no free burst window
                — its first post-restart over-quota request still
                429s), post-recovery greedy output is token-identical,
                recovery-time-to-first-200 is printed, and
                replay_fleet_state over the journal agrees with the
                recovered /replicas + controller views
  journal-loss  the journal is DELETED between router incarnations:
                --router-url self-registration heartbeats alone rebuild
                the registry, and a drained replica's deregister
                goodbye walks it to gone immediately instead of
                waiting out --eject-after failed polls

Follows tests/test_elastic_drills.py conventions: `fault`-marked,
subprocess-driven, tiny synthetic GPT, persistent XLA compile cache
shared through the environment (tests/conftest.py)."""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest
import yaml

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CACHE_DIR = os.environ.get(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)

TINY = {
    "Global": {"global_batch_size": 8, "seed": 11},
    "Engine": {"mix_precision": {"enable": False},
               "save_load": {"save_steps": 0}},
    "Model": {
        "module": "GPTModule",
        "vocab_size": 96,
        "hidden_size": 32,
        "num_layers": 2,
        "num_attention_heads": 4,
        "max_position_embeddings": 64,
        "dtype": "float32",
    },
    "Optimizer": {"name": "FusedAdamW",
                  "lr": {"name": "Constant", "learning_rate": 1e-3}},
    "Generation": {"max_dec_len": 8, "decode_strategy": "greedy_search",
                   "pad_to_multiple": 8, "eos_token_id": 95,
                   "pad_token_id": 0},
}

# flood refills one token every 20s: the seconds-long death window can
# never refill its burst, so a restored bucket MUST still reject
TENANTS = {
    "default": {"weight": 1.0},
    "tenants": {
        "flood": {"weight": 1, "rps": 0.05, "burst": 2},
        "gold": {"weight": 4},
    },
}


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _env(extra=None):
    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PFX_FAULT", None)
    env.pop("PFX_ADMIN_TOKEN", None)
    env.update(extra or {})
    return env


def _req(port, path, data=None, headers=None, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=None if data is None else json.dumps(data).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _metrics(port, timeout=10):
    from test_telemetry import parse_prometheus

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=timeout
    ) as r:
        metrics, _ = parse_prometheus(r.read().decode())
    return metrics


def _finish(proc, timeout=30):
    if proc is None:
        return ""
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
    return proc.stdout.read() if proc.stdout else ""


def _wait(predicate, timeout, what):
    end = time.time() + timeout
    last = None
    while time.time() < end:
        try:
            last = predicate()
            if last:
                return last
        except Exception as e:  # noqa: BLE001 — listener still booting
            last = e
        time.sleep(0.3)
    raise AssertionError(f"timeout waiting for {what}: {last!r}")


def _serve_cmd(cfg_path, *extra):
    return " ".join([
        sys.executable, os.path.join(REPO, "tools", "serve.py"),
        "-c", str(cfg_path), "--port", "{port}",
        "--replica-id", "{replica_id}",
        "--warmup-buckets", "4", "--warmup-batches", "1",
        "--deadline", "60", *extra,
    ])


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


# ---------------------------------------------------------------------------
# THE acceptance drill: SIGKILL the supervising router mid-flood
# ---------------------------------------------------------------------------


def _spawn_router(rport, bport, cfg_path, tmp_path, flight_dir, ten_path):
    """A supervised 2-replica router on FIXED ports (the restart must
    find the same slots) with the fleet journal in ``flight_dir``."""
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "router.py"),
         "--port", str(rport), "--poll-interval", "0.2",
         "--supervise", "--replica-cmd", _serve_cmd(cfg_path),
         "--base-port", str(bport),
         "--compile-cache-dir", CACHE_DIR,
         "--replica-log-dir", str(tmp_path / "replica-logs"),
         "--control-interval", "0.5",
         "--min-replicas", "2", "--max-replicas", "2",
         "--tenants", str(ten_path)],
        env=_env({"PFX_FLIGHT_DIR": str(flight_dir)}), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


def test_sigkill_router_readopts_fleet_and_restores_quotas(tmp_path):
    """THE control-plane survivability acceptance drill: SIGKILL the
    supervising router mid-two-tenant-flood, restart it on the same
    ports + flight dir, and prove router death is a non-event —
    every live replica re-adopted (zero respawns, zero flap-budget
    spend, pids unchanged), tenant 429 quotas resuming from restored
    buckets, greedy output token-identical, and the journal replaying
    to exact agreement with the recovered views."""
    from paddlefleetx_tpu.core.router import (
        read_fleet_journal,
        replay_fleet_state,
    )

    cfg_path = tmp_path / "tiny.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    ten_path = tmp_path / "tenants.json"
    ten_path.write_text(json.dumps(TENANTS))
    flight_dir = tmp_path / "router-artifacts"
    journal_path = flight_dir / "fleet_state.jsonl"
    rport, bport = _free_port(), _free_port()
    gold = {"X-Tenant": "gold"}
    fl = {"X-Tenant": "flood"}
    body = {"prompt_ids": [1, 2, 3], "max_tokens": 8, "deadline_s": 60}

    router = _spawn_router(rport, bport, cfg_path, tmp_path, flight_dir,
                           ten_path)
    router2 = None
    stop = threading.Event()
    flood_codes, lock = [], threading.Lock()
    try:
        _wait(lambda: _req(rport, "/healthz")[1].get("eligible", 0) >= 2,
              600, "two supervised replicas serving")
        code, ref = _req(rport, "/generate", data=body, headers=gold,
                         timeout=90)
        assert code == 200, ref
        views = {v["key"]: v for v in _req(rport, "/replicas")[1]["replicas"]}
        pids_before = {k: v["pid"] for k, v in views.items()}
        assert len(pids_before) == 2
        assert all(isinstance(p, int) for p in pids_before.values())

        # the two-tenant flood: gold trickles, flood burns its burst
        # and keeps hammering into 429s (the mid-429-storm state the
        # restart must NOT hand a fresh burst allowance)
        def flood_loop():
            while not stop.is_set():
                try:
                    c, _r = _req(rport, "/generate", data=body,
                                 headers=fl, timeout=90)
                except Exception:  # noqa: BLE001 — router is dead/rebooting
                    c = None
                with lock:
                    flood_codes.append((time.time(), c))
                time.sleep(0.1)

        flooder = threading.Thread(target=flood_loop)
        flooder.start()
        _wait(lambda: any(c == 429 for _, c in flood_codes),
              90, "flood tenant over quota (429)")
        _req(rport, "/generate", data=body, headers=gold, timeout=90)

        # the drained flood bucket must be IN the journal before the
        # kill (the poll thread journals tenants at most once a second)
        def bucket_journaled():
            recs, _ = read_fleet_journal(str(journal_path))
            buckets = replay_fleet_state(recs)["tenants"]["buckets"]
            b = buckets.get("flood")
            return b is not None and b["tokens"] < 1.0
        _wait(bucket_journaled, 30, "drained flood bucket journaled")

        # ---- SIGKILL the control plane mid-flood ----
        t_kill = time.time()
        router.kill()
        router.wait(timeout=30)
        # the fleet outlives its router: both replicas still running
        assert all(_pid_alive(p) for p in pids_before.values())

        router2 = _spawn_router(rport, bport, cfg_path, tmp_path,
                                flight_dir, ten_path)

        def first_200():
            c, _r = _req(rport, "/generate", data=body, headers=gold,
                         timeout=90)
            return c == 200
        _wait(first_200, 120, "first post-restart 200")
        print(f"recovery-time-to-first-200: "
              f"{time.time() - t_kill:.2f}s", flush=True)

        # restored buckets: the flooding tenant's first post-restart
        # over-quota request still 429s — no free burst window (rps
        # 0.05 cannot refill the burst across a seconds-long death)
        code, rej = _req(rport, "/generate", data=body, headers=fl)
        assert code == 429, (code, rej)
        stop.set()
        flooder.join(timeout=120)
        assert not flooder.is_alive()
        with lock:
            post = [c for t, c in flood_codes if t > t_kill and c]
        assert 200 not in post, post  # the 429 storm RESUMED, no hole

        # re-adoption: same keys, same pids, serving — zero respawns
        def readopted():
            vs = {v["key"]: v for v in
                  _req(rport, "/replicas")[1]["replicas"]}
            return vs if (
                set(vs) == set(pids_before)
                and all(v["state"] == "serving" for v in vs.values())
            ) else None
        vs = _wait(readopted, 120, "both replicas re-adopted + serving")
        assert {k: v["pid"] for k, v in vs.items()} == pids_before

        m = _metrics(rport)
        assert m["pfx_router_recoveries_total"][frozenset()] == 1.0
        for rid in ("m0", "m1"):
            assert m["pfx_router_adopted_replicas_total"][
                frozenset({("replica", rid)})
            ] == 1.0
        # zero respawns, zero flap-budget spend
        assert "pfx_replica_restarts_total" not in m
        assert "pfx_replica_quarantines_total" not in m
        assert m["pfx_router_journal_records"][frozenset()] >= 1.0

        # post-recovery greedy output is token-identical
        code, resp = _req(rport, "/generate", data=body, headers=gold,
                          timeout=90)
        assert code == 200
        assert resp["completion_ids"] == ref["completion_ids"]

        # replay_fleet_state over the journal == the recovered views
        # (quiesce-retry: scale records land every control tick, so
        # agreement is gated on the REPLICA record count holding still)
        def replica_records(recs):
            return [r for r in recs
                    if r["kind"] in ("replica", "snapshot")]
        for _ in range(10):
            recs, note = read_fleet_journal(str(journal_path))
            assert note is None
            live = {v["key"]: v for v in
                    _req(rport, "/replicas")[1]["replicas"]}
            _, hz = _req(rport, "/healthz")
            recs2, _ = read_fleet_journal(str(journal_path))
            if len(replica_records(recs)) != len(replica_records(recs2)):
                continue  # a transition landed mid-read; retry
            st = replay_fleet_state(recs)
            assert set(st["replicas"]) == set(live)
            for key, v in live.items():
                fold = st["replicas"][key]
                assert fold["state"] == v["state"], key
                assert fold["url"] == v["url"], key
            ctl = st["controller"]["monolith"]
            assert ctl["target"] == hz["controller"]["target"]
            assert st["tenants"]["buckets"]["flood"]["tokens"] < 1.0
            break
        else:
            raise AssertionError("registry never quiesced between reads")

        router2.send_signal(signal.SIGTERM)
        assert router2.wait(timeout=120) == 0
    finally:
        stop.set()
        log1 = _finish(router)
        log2 = _finish(router2)
    assert "re-adopted 2 live replica(s)" in log2, log2[-3000:]
    assert "restored" in log2 and "tenant bucket" in log2, log2[-3000:]
    assert "Traceback" not in log1, log1[-3000:]
    assert "Traceback" not in log2, log2[-3000:]


# ---------------------------------------------------------------------------
# journal deleted -> self-registration heartbeats rebuild the registry
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~2 replica boots (~60s warm); tier-1 keeps the
# SIGKILL-router acceptance drill above.  Replacement coverage: the
# /admin/register contract (idempotent register, identity refresh,
# deregister-walks-gone, stale-goodbye rejection) stays tier-1 via the
# test_fleet_journal.py register_replica units; still in make test-ha /
# test-all.
def test_journal_deleted_heartbeats_rebuild_registry(tmp_path):
    """THE journal-loss drill: two --router-url replicas heartbeat into
    a static router.  The router dies AND its journal is deleted; the
    restarted router rediscovers the fleet from the heartbeats alone —
    and a drained replica's deregister goodbye walks it to gone
    immediately, not after --eject-after failed polls."""
    cfg_path = tmp_path / "tiny.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    flight_dir = tmp_path / "router-artifacts"
    rport = _free_port()
    pa, pb = _free_port(), _free_port()
    body = {"prompt_ids": [1, 2, 3], "max_tokens": 8, "deadline_s": 60}

    def spawn_replica(port):
        return subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tools", "serve.py"),
             "-c", str(cfg_path), "--port", str(port),
             "--replica-id", f"hb-{port}",
             "--warmup-buckets", "4", "--warmup-batches", "1",
             "--deadline", "60",
             "--router-url", f"http://127.0.0.1:{rport}",
             "--compile-cache-dir", CACHE_DIR],
            env=_env({"PFX_REGISTER_INTERVAL_S": "0.5"}), cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

    def spawn_router():
        # replica A is configured statically; B exists ONLY through its
        # /admin/register heartbeats.  --eject-after 100 @ 0.2s polls =
        # a 20s failed-poll eject window, so a fast gone proves the
        # deregister path, not the poller
        return subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tools", "router.py"),
             "--port", str(rport), "--poll-interval", "0.2",
             "--replica", f"http://127.0.0.1:{pa}",
             "--eject-after", "100"],
            env=_env({"PFX_FLIGHT_DIR": str(flight_dir)}), cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

    ra, rb = spawn_replica(pa), spawn_replica(pb)
    router = spawn_router()
    router2 = None
    try:
        _wait(lambda: _req(rport, "/healthz")[1].get("eligible", 0) >= 2,
              600, "static A + heartbeat-registered B both serving")
        m = _metrics(rport)
        assert m["pfx_replica_registrations_total"][
            frozenset({("outcome", "register")})
        ] >= 1.0
        code, ref = _req(rport, "/generate", data=body, timeout=90)
        assert code == 200

        # ---- kill the router AND delete its journal ----
        router.kill()
        router.wait(timeout=30)
        shutil.rmtree(flight_dir)
        router2 = spawn_router()
        # the heartbeats alone rebuild the registry: B re-appears
        # within a couple of 0.5s heartbeat intervals
        _wait(lambda: _req(rport, "/healthz")[1].get("eligible", 0) >= 2,
              120, "registry rebuilt from heartbeats after journal loss")
        m = _metrics(rport)
        assert "pfx_router_recoveries_total" not in m  # nothing replayed
        code, resp = _req(rport, "/generate", data=body, timeout=90)
        assert code == 200
        assert resp["completion_ids"] == ref["completion_ids"]

        # ---- drained replica deregisters on exit (no eject wait) ----
        code, _ = _req(pb, "/admin/drain", data={})
        assert code == 200
        assert rb.wait(timeout=60) == 0
        t0 = time.time()

        def b_gone():
            vs = _req(rport, "/replicas")[1]["replicas"]
            b = next(v for v in vs if v["url"].endswith(str(pb)))
            return b["state"] == "gone"
        _wait(b_gone, 15, "deregistered replica walked to gone")
        # far inside the 20s failed-poll eject window: the goodbye did it
        assert time.time() - t0 < 10.0
        m = _metrics(rport)
        assert m["pfx_replica_registrations_total"][
            frozenset({("outcome", "deregister")})
        ] >= 1.0

        router2.send_signal(signal.SIGTERM)
        assert router2.wait(timeout=60) == 0
        code, _ = _req(pa, "/admin/drain", data={})
        assert code == 200
        assert ra.wait(timeout=60) == 0
    finally:
        loga = _finish(ra)
        logb = _finish(rb)
        log1 = _finish(router)
        log2 = _finish(router2)
    assert "deregistered from router" in logb, logb[-3000:]
    for log in (loga, logb, log1, log2):
        assert "Traceback" not in log, log[-3000:]
