"""tools/bench_check.py units (`make bench-check`): the newest-two
BENCH_r*.json comparison flags >10% regressions of shared metrics, skips
backend-unreachable rows loudly with rc 0, and never silently passes a
short history."""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import bench_check  # noqa: E402


def _write(d, n, rows, rc=0):
    path = d / f"BENCH_r{n:02d}.json"
    path.write_text(json.dumps({
        "n": n, "cmd": "bench", "rc": rc, "tail": "",
        "parsed": rows,
    }))
    return path


def _row(metric, value, unit="tokens/s/chip"):
    return {"metric": metric, "value": value, "unit": unit, "vs_baseline": 1.0}


def test_regression_over_threshold_fails(tmp_path, capsys):
    _write(tmp_path, 1, _row("tp", 1000.0))
    _write(tmp_path, 2, _row("tp", 850.0))  # -15%
    assert bench_check.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "tp" in out


def test_within_threshold_and_improvement_pass(tmp_path, capsys):
    _write(tmp_path, 1, [_row("tp", 1000.0), _row("p99", 2.0)])
    _write(tmp_path, 2, [_row("tp", 950.0), _row("p99", 3.0)])  # -5%, +50%
    assert bench_check.main(["--dir", str(tmp_path)]) == 0
    assert "2 shared metric(s) within threshold" in capsys.readouterr().out


def test_unreachable_backend_rows_skip_loudly_rc0(tmp_path, capsys):
    """The honest-skip contract: a dead-backend 0.0 is not a regression;
    the comparison falls back to the last two COMPARABLE snapshots."""
    _write(tmp_path, 1, _row("tp", 1000.0))
    _write(tmp_path, 2, _row("tp", 990.0))
    _write(tmp_path, 3, _row("tp", 0.0, unit="tokens/s/chip (tpu backend unreachable)"))
    assert bench_check.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "unreachable" in out and "SKIP" in out
    # and it compared r1 vs r2, not the dead r3
    assert "r1=1000" in out and "r2=990" in out


def test_failed_lap_spellings_skip_not_regress(tmp_path, capsys):
    """bench.py's honest-fallback rows (deadline exceeded, killed by
    signal, no JSON) are value-0 rows with the reason in the unit — they
    must SKIP, never read as a 100% regression."""
    _write(tmp_path, 1, _row("tp", 1000.0))
    _write(tmp_path, 2, _row("tp", 990.0))
    _write(tmp_path, 3, _row("tp", 0.0, unit="new tokens/s/chip (self-deadline 1200s exceeded)"))
    _write(tmp_path, 4, _row("tp", 0.0, unit="tokens/s/chip (killed by signal 15 before completion)"))
    assert bench_check.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "REGRESSION" not in out
    assert "r1=1000" in out and "r2=990" in out  # compared the live laps


def test_corrupt_snapshot_skips_loudly_instead_of_crashing(tmp_path, capsys):
    _write(tmp_path, 1, _row("tp", 1000.0))
    _write(tmp_path, 2, _row("tp", 990.0))
    (tmp_path / "BENCH_r03.json").write_text('{"n": 3, "parsed": {"met')
    assert bench_check.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "unparseable JSON" in out and "r2=990" in out


def test_unparsed_lap_and_short_history_pass_loudly(tmp_path, capsys):
    _write(tmp_path, 1, None, rc=124)  # timed-out lap: parsed null
    assert bench_check.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "PASS by default (loudly)" in out
    assert bench_check.main(["--dir", str(tmp_path / "empty")]) == 0


def test_disjoint_metrics_pass_loudly(tmp_path, capsys):
    _write(tmp_path, 1, _row("old_metric", 10.0))
    _write(tmp_path, 2, _row("new_metric", 10.0))
    assert bench_check.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "disjoint metric" in out and "PASS by default (loudly)" in out


def test_platform_change_not_compared(tmp_path, capsys):
    """SATELLITE (dead-backend fallback): a cpu fallback lap after tpu
    laps is a platform change, not a 98% regression — the comparison
    skips it loudly and never flags nonsense."""
    _write(tmp_path, 1, dict(_row("tp", 18981.0), platform="tpu"))
    _write(tmp_path, 2, dict(_row("tp", 300.0), platform="cpu"))
    assert bench_check.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "REGRESSION" not in out
    assert "platform changed" in out and "tpu -> cpu" in out


def test_platform_fallback_compares_same_platform_laps(tmp_path, capsys):
    """cpu fallback laps compare against the previous cpu lap (walking
    past an interleaved tpu lap), and a real cpu regression still
    flags."""
    _write(tmp_path, 1, dict(_row("tp", 300.0), platform="cpu"))
    _write(tmp_path, 2, dict(_row("tp", 19000.0), platform="tpu"))
    _write(tmp_path, 3, dict(_row("tp", 200.0), platform="cpu"))  # -33% vs r1
    assert bench_check.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "[cpu]" in out
    assert "r1=300" in out and "r3=200" in out


def test_cpu_fallback_unit_suffix_rows_are_comparable(tmp_path, capsys):
    """The exact row bench.py's dead-backend fallback emits — value > 0
    with a '(cpu-fallback shape)' unit suffix — must COMPARE against
    other fallback laps (the parenthetical-skip rule only fires on
    value 0)."""
    row = dict(_row("tp", 420.0, unit="tokens/s/chip (cpu-fallback shape)"),
               platform="cpu")
    _write(tmp_path, 1, row)
    _write(tmp_path, 2, dict(row, value=300.0))  # -29%: a real cpu drop
    assert bench_check.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "SKIP" not in out


def test_legacy_rows_without_platform_only_match_each_other(tmp_path, capsys):
    """Pre-PR 5 rows carry no platform field; a platform-labeled lap
    must not be compared against them (r1 ran on a real chip but its
    row cannot prove it)."""
    _write(tmp_path, 1, _row("tp", 18981.0))  # legacy: no platform
    _write(tmp_path, 2, dict(_row("tp", 300.0), platform="cpu"))
    assert bench_check.main(["--dir", str(tmp_path)]) == 0
    assert "REGRESSION" not in capsys.readouterr().out


def test_real_repo_history_is_parseable():
    """The committed BENCH_r*.json trajectory must run clean (rc 0: the
    reachable-backend rows are r1-only, so there is at most one
    comparable snapshot)."""
    assert bench_check.main(["--dir", REPO]) == 0
