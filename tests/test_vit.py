"""ViT model + vision data tests."""

import jax
import jax.numpy as jnp
import numpy as np

from paddlefleetx_tpu.data.vision_dataset import SyntheticClsDataset
from paddlefleetx_tpu.models import vit
from paddlefleetx_tpu.models.vit.model import ViTConfig, patchify, top_k_accuracy
from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
from paddlefleetx_tpu.parallel.sharding import make_rules, tree_logical_to_sharding

TINY = ViTConfig(
    image_size=32,
    patch_size=8,
    num_classes=8,
    hidden_size=64,
    num_layers=2,
    num_attention_heads=8,
    hidden_dropout_prob=0.0,
    attention_probs_dropout_prob=0.0,
    dtype="float32",
)


def test_forward_shape():
    params = vit.init(TINY, jax.random.key(0))
    imgs = jnp.ones((2, 32, 32, 3))
    logits = vit.forward(params, imgs, TINY)
    assert logits.shape == (2, 8)


def test_patchify_roundtrip_values():
    imgs = jnp.arange(2 * 32 * 32 * 3, dtype=jnp.float32).reshape(2, 32, 32, 3)
    x = patchify(imgs, 8)
    assert x.shape == (2, 16, 8 * 8 * 3)
    # first patch first row equals original top-left pixels
    np.testing.assert_array_equal(np.asarray(x[0, 0, :24]).reshape(8, 3), np.asarray(imgs[0, 0, :8]))


def test_pos_embed_interpolation():
    params = vit.init(TINY, jax.random.key(0))
    imgs = jnp.ones((1, 64, 64, 3))  # 2x resolution -> 64 patches vs 16
    logits = vit.forward(params, imgs, TINY)
    assert logits.shape == (1, 8)


def test_vit_learns_synthetic():
    import optax

    params = vit.init(TINY, jax.random.key(0))
    ds = SyntheticClsDataset(num_samples=64, image_size=32, num_classes=8)
    batch = {
        "images": jnp.stack([ds[i]["images"] for i in range(32)]),
        "labels": jnp.asarray([ds[i]["labels"] for i in range(32)]),
    }
    tx = optax.adam(3e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt):
        loss, g = jax.value_and_grad(
            lambda p: vit.cls_loss(vit.forward(p, batch["images"], TINY, train=False), batch["labels"])
        )(params)
        upd, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, upd), opt, loss

    losses = []
    for _ in range(15):
        params, opt, loss = step(params, opt)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.5


def test_vit_tp_parity(devices8):
    params = vit.init(TINY, jax.random.key(0))
    imgs = jnp.ones((4, 32, 32, 3))
    ref = vit.forward(params, imgs, TINY)
    mesh = build_mesh(MeshConfig(dp_degree=2, mp_degree=4), devices8)
    rules = make_rules()
    shardings = tree_logical_to_sharding(vit.vit_logical_axes(TINY), mesh, rules)
    from paddlefleetx_tpu.models.gpt.model import ShardingCtx

    ctx = ShardingCtx(mesh, rules)
    with mesh:
        got = jax.jit(lambda p, x: vit.forward(p, x, TINY, ctx=ctx))(
            jax.device_put(params, shardings), imgs
        )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_topk_accuracy():
    logits = jnp.asarray([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]])
    labels = jnp.asarray([1, 2])
    assert float(top_k_accuracy(logits, labels, 1)) == 0.5
    assert float(top_k_accuracy(logits, labels, 3)) == 1.0
