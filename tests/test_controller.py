"""Elastic-control-plane units (`core/controller.py`): scale policy
validation, the controller's breach/depth/occupancy decisions with
hysteresis + cooldowns + min/max bounds, the bounded decision log and
its counter-replay contract, and the replica supervisor's crash-restart
backoff + flap-budget quarantine — all against stub cores / injected
clocks / tiny real subprocesses (no jax, no model): the multi-process
chaos drills live in tests/test_elastic_drills.py.
"""

import subprocess
import sys
import time

import pytest

from paddlefleetx_tpu.core.controller import (
    ElasticController,
    ManagedReplica,
    ReplicaSupervisor,
    ScalePolicy,
    replay_controller_log,
)
from paddlefleetx_tpu.utils.telemetry import Registry


class StubCore:
    """RouterCore stand-in: mutable replica views + call recording."""

    def __init__(self):
        self.views = []
        self.added = []
        self.drained = []
        self._next = 0

    def replica_views(self):
        return [dict(v) for v in self.views]

    def add_replica(self, url, role="monolith"):
        key = f"r{self._next}"
        self._next += 1
        self.added.append((key, url, role))
        return key

    def drain(self, key):
        self.drained.append(key)
        return {"replica": key}


def _view(key, *, state="serving", depth=0, in_flight=0, occupancy=0.0,
          breach=False, draining=False):
    return {
        "key": key, "role": "monolith", "state": state, "depth": depth,
        "in_flight": in_flight, "occupancy": occupancy,
        "slo_breach": breach, "draining": draining,
    }


class FakeProc:
    """Popen stand-in with a scriptable exit code."""

    def __init__(self):
        self.rc = None
        self.pid = 4242
        self.terminated = False

    def poll(self):
        return self.rc

    def terminate(self):
        self.terminated = True
        self.rc = 0

    def wait(self, timeout=None):
        if self.rc is None:
            raise subprocess.TimeoutExpired("fake", timeout)
        return self.rc

    def kill(self):
        self.rc = -9


def _supervisor(reg, **kw):
    kw.setdefault("base_port", 9500)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("spawn_fn", lambda m: FakeProc())
    kw.setdefault("registry", reg)
    return ReplicaSupervisor(
        "python serve.py --port {port} --replica-id {replica_id}", **kw
    )


def _controller(core, sup, reg, **policy_kw):
    policy_kw.setdefault("min_replicas", 1)
    policy_kw.setdefault("max_replicas", 3)
    policy_kw.setdefault("up_cooldown_s", 5.0)
    policy_kw.setdefault("down_cooldown_s", 60.0)
    policy_kw.setdefault("idle_s", 30.0)
    return ElasticController(
        core, sup, ScalePolicy(**policy_kw), registry=reg
    )


# ---------------------------------------------------------------------------
# policy validation
# ---------------------------------------------------------------------------


def test_scale_policy_validates_loudly():
    ScalePolicy().validate()
    with pytest.raises(ValueError, match="min_replicas"):
        ScalePolicy(min_replicas=0).validate()
    with pytest.raises(ValueError, match="max_replicas"):
        ScalePolicy(min_replicas=3, max_replicas=2).validate()
    with pytest.raises(ValueError, match="hysteresis"):
        ScalePolicy(low_depth=5.0, high_depth=4.0).validate()
    with pytest.raises(ValueError, match="hysteresis"):
        ScalePolicy(low_occupancy=0.95, high_occupancy=0.9).validate()
    with pytest.raises(ValueError, match="idle_s"):
        ScalePolicy(idle_s=0).validate()


def test_supervisor_template_requires_port_placeholder():
    with pytest.raises(ValueError, match="{port}"):
        ReplicaSupervisor("python serve.py", base_port=9500,
                          max_replicas=2, registry=Registry())


# ---------------------------------------------------------------------------
# scale-up: breach-driven fast path, watermarks, cooldown, max bound
# ---------------------------------------------------------------------------


def test_breach_drives_scale_up_and_registers_replica():
    reg, core = Registry(), StubCore()
    sup = _supervisor(reg)
    ctl = _controller(core, sup, reg)
    ctl._register(sup.ensure(ctl.target, now=0.0))
    assert [k for k, _, _ in core.added] == ["r0"]
    core.views = [_view("r0", breach=True)]
    row = ctl.tick(now=10.0)
    assert row["action"] == "scale_up" and "breach" in row["reason"]
    assert ctl.target == 2
    # the new slot was spawned AND registered with the router core
    assert len(core.added) == 2
    assert sup.slots[1].desired and sup.slots[1].key == core.added[1][0]


def test_depth_and_occupancy_watermarks_drive_scale_up():
    reg, core = Registry(), StubCore()
    ctl = _controller(core, _supervisor(reg), reg, high_depth=4.0)
    core.views = [_view("r0", depth=3, in_flight=2)]  # avg 5 > 4
    assert ctl.tick(now=10.0)["action"] == "scale_up"
    reg2, core2 = Registry(), StubCore()
    ctl2 = _controller(core2, _supervisor(reg2), reg2)
    core2.views = [_view("r0", occupancy=0.95)]
    row = ctl2.tick(now=10.0)
    assert row["action"] == "scale_up" and "occupancy" in row["reason"]


def test_up_cooldown_and_warming_replicas_bound_scale_rate():
    reg, core = Registry(), StubCore()
    ctl = _controller(core, _supervisor(reg), reg, up_cooldown_s=5.0)
    core.views = [_view("r0", breach=True)]
    assert ctl.tick(now=10.0)["action"] == "scale_up"
    # still breaching, but the spawned replica is warming: hold
    core.views = [_view("r0", breach=True), _view("r1", state="booting")]
    row = ctl.tick(now=10.5)
    assert row["action"] == "hold" and "warming" in row["reason"]
    # warming replica landed but the up-cooldown still gates
    core.views = [_view("r0", breach=True), _view("r1")]
    row = ctl.tick(now=12.0)
    assert row["action"] == "hold" and "cooldown" in row["reason"]
    # past the cooldown: the breach scales again
    assert ctl.tick(now=20.0)["action"] == "scale_up"
    assert ctl.target == 3


def test_max_replicas_bounds_scale_up_loudly():
    reg, core = Registry(), StubCore()
    ctl = _controller(core, _supervisor(reg), reg, max_replicas=1)
    core.views = [_view("r0", breach=True)]
    row = ctl.tick(now=10.0)
    assert row["action"] == "hold" and "max_replicas" in row["reason"]
    assert ctl.target == 1
    assert reg.value("pfx_controller_breach") == 1.0


# ---------------------------------------------------------------------------
# scale-down: idle dwell + cooldown hysteresis, min bound, remote drain
# ---------------------------------------------------------------------------


def test_idle_dwell_and_cooldown_gate_scale_down():
    reg, core = Registry(), StubCore()
    sup = _supervisor(reg)
    ctl = _controller(core, sup, reg, idle_s=30.0, down_cooldown_s=60.0)
    ctl._register(sup.ensure(2, now=0.0))
    ctl.target = 2
    core.views = [_view("r0"), _view("r1")]  # idle fleet
    assert ctl.tick(now=10.0)["action"] == "hold"   # dwell starts
    assert ctl.tick(now=35.0)["action"] == "hold"   # dwell met, but the
    # last scale action was... never: -inf, so cooldown passes; dwell is
    # measured from the FIRST idle tick (10.0): 35-10=25 < 30
    row = ctl.tick(now=41.0)  # 31s of sustained idle
    assert row["action"] == "scale_down"
    assert ctl.target == 1
    # the drain went through the core (remote authenticated transport)
    # and retired the HIGHEST slot
    assert core.drained == [sup.slots[1].key]
    assert not sup.slots[1].desired
    # min bound: still idle, but the floor holds
    core.views = [_view("r0")]
    for t in (120.0, 200.0, 300.0):
        assert ctl.tick(now=t)["action"] == "hold"
    assert ctl.target == 1


def test_scale_up_with_no_spawnable_slot_holds_and_keeps_books_honest():
    """Pressure at a fleet whose remaining slots are all quarantined
    must NOT move the target or the scale_ups counter — a scale-up that
    spawns nothing would make the decision log 'replay exactly' while
    recording spawns that never happened."""
    reg, core = Registry(), StubCore()
    # the supervisor shares the policy's ceiling (tools/router.py wires
    # both from --max-replicas)
    sup = _supervisor(reg, max_replicas=2)
    ctl = _controller(core, sup, reg, max_replicas=2, up_cooldown_s=1.0)
    ctl._register(sup.ensure(1, now=0.0))
    sup._slot(1).quarantined = True  # the only headroom slot is dead
    core.views = [_view("r0", breach=True)]
    for t in (10.0, 20.0, 30.0):
        row = ctl.tick(now=t)
        assert row["action"] == "hold", row
        assert "no spawnable slot" in row["reason"], row
    assert ctl.target == 1
    assert reg.value("pfx_controller_scale_ups_total") == 0.0
    replay = replay_controller_log(list(ctl.decision_log))
    assert replay["scale_ups"] == 0 and replay["ticks"] == 3


def test_total_outage_is_not_idle_and_never_scales_down():
    """Zero serving replicas (all crashed / restart-pending) reads as
    depth 0 and occupancy 0 — but it is an OUTAGE, not idleness: the
    controller must hold, never retire capacity mid-outage."""
    reg, core = Registry(), StubCore()
    sup = _supervisor(reg)
    ctl = _controller(core, sup, reg, idle_s=5.0, down_cooldown_s=5.0,
                      max_replicas=3)
    ctl._register(sup.ensure(2, now=0.0))
    ctl.target = 2
    core.views = [_view("r0", state="gone"), _view("r1", state="gone")]
    for t in (10.0, 20.0, 40.0, 80.0):  # far past every dwell/cooldown
        row = ctl.tick(now=t)
        assert row["action"] == "hold", row
    assert ctl.target == 2 and core.drained == []
    assert all(m.desired for m in sup.slots.values())


def test_traffic_blip_resets_idle_dwell():
    reg, core = Registry(), StubCore()
    sup = _supervisor(reg)
    ctl = _controller(core, sup, reg, idle_s=30.0)
    ctl._register(sup.ensure(2, now=0.0))
    ctl.target = 2
    core.views = [_view("r0"), _view("r1")]
    ctl.tick(now=10.0)
    # a depth blip above low_depth (but under high) resets the dwell
    core.views = [_view("r0", depth=2), _view("r1")]
    assert ctl.tick(now=25.0)["action"] == "hold"
    core.views = [_view("r0"), _view("r1")]
    assert ctl.tick(now=41.0)["action"] == "hold"  # dwell restarted at 41
    assert ctl.tick(now=72.0)["action"] == "scale_down"


# ---------------------------------------------------------------------------
# decision log: bounded, replayable to exact counter agreement
# ---------------------------------------------------------------------------


def test_decision_log_replays_to_exact_counter_agreement():
    reg, core = Registry(), StubCore()
    sup = _supervisor(reg)
    ctl = _controller(core, sup, reg, up_cooldown_s=1.0, idle_s=5.0,
                      down_cooldown_s=5.0)
    ctl._register(sup.ensure(ctl.target, now=0.0))
    t = 10.0
    core.views = [_view("r0", breach=True)]
    ctl.tick(now=t)                                   # scale_up
    core.views = [_view("r0", breach=True), _view("r1")]
    ctl.tick(now=t + 2)                               # scale_up (cooldown ok)
    core.views = [_view("r0"), _view("r1"), _view("r2")]
    for dt in (3, 4, 5, 6, 7, 8, 9):
        ctl.tick(now=t + dt)                          # holds, then downs
    replay = replay_controller_log(list(ctl.decision_log))
    assert replay["ticks"] == len(ctl.decision_log) == 9
    assert replay["scale_ups"] == 2
    assert replay["scale_downs"] >= 1
    # THE agreement contract: the untruncated log reproduces the
    # pfx_controller_* counters exactly
    assert reg.value("pfx_controller_ticks_total") == replay["ticks"]
    assert reg.value("pfx_controller_scale_ups_total") == replay["scale_ups"]
    assert (reg.value("pfx_controller_scale_downs_total")
            == replay["scale_downs"])
    assert reg.value("pfx_controller_target_replicas") == ctl.target


def test_decision_log_is_bounded(monkeypatch):
    monkeypatch.setenv("PFX_CONTROLLER_LOG_CAP", "8")
    reg, core = Registry(), StubCore()
    ctl = _controller(core, _supervisor(reg), reg)
    core.views = [_view("r0")]
    for i in range(20):
        ctl.tick(now=float(i))
    assert len(ctl.decision_log) == 8
    assert ctl.decision_log[-1]["tick"] == 20  # newest kept, oldest evicted


# ---------------------------------------------------------------------------
# supervisor: spawn, crash-restart backoff, flap quarantine, warm boot
# ---------------------------------------------------------------------------


def test_supervisor_restarts_crash_with_backoff():
    reg = Registry()
    spawned = []

    def spawn(m):
        p = FakeProc()
        spawned.append((m.slot, p))
        return p

    sup = _supervisor(reg, spawn_fn=spawn, backoff_base_s=2.0,
                      flap_budget=5)
    sup.ensure(1, now=0.0)
    assert len(spawned) == 1
    spawned[0][1].rc = 1  # crash
    sup.poll(now=10.0)
    assert len(spawned) == 1  # backoff pending, not yet respawned
    sup.poll(now=11.0)
    assert len(spawned) == 1  # 10 + 2.0 backoff not reached
    sup.poll(now=12.5)
    assert len(spawned) == 2  # respawned
    assert sup.slots[0].restarts == 1
    assert reg.value("pfx_replica_restarts_total", replica="m0") == 1.0


def test_supervisor_quarantines_crash_loop_within_flap_budget():
    reg = Registry()
    procs = []

    def spawn(m):
        p = FakeProc()
        p.rc = 23  # dies instantly: the crash-loop case
        procs.append(p)
        return p

    sup = _supervisor(reg, spawn_fn=spawn, backoff_base_s=0.01,
                      flap_budget=3, flap_window_s=60.0)
    sup.ensure(1, now=0.0)
    t = 1.0
    for _ in range(40):
        sup.poll(now=t)
        t += 1.0
        if sup.slots[0].quarantined:
            break
    m = sup.slots[0]
    assert m.quarantined, "crash-looper was never quarantined"
    # quarantine fired WITHIN the flap budget: exactly budget restarts,
    # then no more spawns ever
    assert m.restarts == 3 and len(procs) == 4
    assert reg.value("pfx_replica_quarantines_total", replica="m0") == 1.0
    for _ in range(5):
        sup.poll(now=t)
        t += 1.0
    assert len(procs) == 4  # quarantined means QUARANTINED
    # ensure() skips the quarantined slot and desires the next one
    started = sup.ensure(1, now=t)
    assert [m2.slot for m2 in started] == [1]


def test_supervisor_clean_exit_while_desired_respawns_without_flap_spend():
    """An out-of-band drain of a supervised replica (manual POST
    /admin/drain) exits 0 while the slot is still desired: the fleet
    self-heals by respawning, but a deploy is not a crash — no crash
    warning, no flap-budget spend, never a quarantine."""
    reg = Registry()
    procs = []

    def spawn(m):
        p = FakeProc()
        procs.append(p)
        return p

    sup = _supervisor(reg, spawn_fn=spawn, backoff_base_s=0.5,
                      flap_budget=3, flap_window_s=1e9)
    sup.ensure(1, now=0.0)
    t = 1.0
    for _ in range(6):  # twice the flap budget of clean exits
        procs[-1].rc = 0  # drained out from under the supervisor
        sup.poll(now=t)           # reap: schedules a flap-exempt respawn
        sup.poll(now=t + 0.6)     # past the backoff: respawn
        t += 1.0
    m = sup.slots[0]
    assert len(procs) == 7 and m.restarts == 6
    assert not m.quarantined, "clean exits spent the flap budget"
    assert m.restart_times == []  # the flap window never saw them
    assert reg.value("pfx_replica_restarts_total", replica="m0") == 6.0


def test_supervisor_expected_exit_is_not_restarted():
    reg = Registry()
    spawned = []

    def spawn(m):
        p = FakeProc()
        spawned.append(p)
        return p

    sup = _supervisor(reg, spawn_fn=spawn)
    sup.ensure(1, now=0.0)
    sup.drain_slot(0)
    spawned[0].rc = 0  # the drained replica exits 0
    for t in (1.0, 2.0, 3.0):
        sup.poll(now=t)
    assert len(spawned) == 1
    assert sup.slots[0].restarts == 0


def test_supervisor_warm_boot_appends_compile_cache_flag(tmp_path):
    sup = ReplicaSupervisor(
        "python serve.py --port {port} --replica-id {replica_id}",
        base_port=9600, max_replicas=2,
        compile_cache_dir=str(tmp_path / "cache"),
        spawn_fn=lambda m: FakeProc(), registry=Registry(),
    )
    sup.ensure(2, now=0.0)
    for slot, m in sup.slots.items():
        assert m.cmd[-2:] == ["--compile-cache-dir",
                              str(tmp_path / "cache")]
        assert f"--port 960{slot}" in " ".join(m.cmd)
        assert f"--replica-id m{slot}" in " ".join(m.cmd)


def test_supervisor_real_subprocess_lifecycle():
    """One real child end-to-end: spawn, SIGKILL -> crash seen ->
    restart, stop_all tears down cleanly."""
    reg = Registry()
    sup = ReplicaSupervisor(
        f"{sys.executable} -c 'import time; time.sleep({{port}})'",
        base_port=300, max_replicas=1, backoff_base_s=0.05,
        registry=reg,
    )
    try:
        sup.ensure(1)
        m = sup.slots[0]
        assert m.proc.poll() is None
        m.proc.kill()
        m.proc.wait(timeout=10)
        deadline = time.time() + 10
        while m.restarts == 0 and time.time() < deadline:
            sup.poll()
            time.sleep(0.02)
        assert m.restarts == 1 and m.proc is not None
        assert m.proc.poll() is None  # the replacement is alive
    finally:
        sup.stop_all(timeout=10)
    assert all(m.proc is None for m in sup.slots.values())


def test_managed_replica_view_shape():
    m = ManagedReplica(slot=0, port=9500, url="http://127.0.0.1:9500",
                       cmd=["x"])
    v = m.view()
    assert v["slot"] == 0 and v["pid"] is None and not v["quarantined"]


# ---------------------------------------------------------------------------
# role-aware pool supervision (docs/serving.md "Disaggregated operations")
# ---------------------------------------------------------------------------


def _pool_view(key, role, available_blocks=None, **kw):
    v = _view(key, **kw)
    v["role"] = role
    v["available_blocks"] = available_blocks
    return v


def _pool_controller(core, sup, reg, role, **policy_kw):
    policy_kw.setdefault("min_replicas", 1)
    policy_kw.setdefault("max_replicas", 3)
    policy_kw.setdefault("up_cooldown_s", 5.0)
    policy_kw.setdefault("down_cooldown_s", 60.0)
    policy_kw.setdefault("idle_s", 30.0)
    return ElasticController(
        core, sup, ScalePolicy(**policy_kw), role=role, registry=reg
    )


def test_scale_policy_validates_low_blocks():
    with pytest.raises(ValueError, match="low_blocks"):
        ScalePolicy(low_blocks=-1).validate()
    ScalePolicy(low_blocks=8, use_depth=False).validate()


def test_scale_policy_rejects_all_signals_off():
    """With every load signal disabled, 'idle' degenerates to 'no SLO
    breach' and a slammed pool would be drained mid-load — a
    self-contradictory policy is a config error, loudly."""
    with pytest.raises(ValueError, match="load signal"):
        ScalePolicy(use_depth=False, use_occupancy=False,
                    low_blocks=0).validate()
    # one signal is enough on its own
    ScalePolicy(use_depth=False, use_occupancy=False,
                low_blocks=4).validate()
    ScalePolicy(use_depth=True, use_occupancy=False).validate()


def test_decode_pool_scales_on_available_blocks_not_depth():
    """The decode pool watches arena signals: a deep queue alone never
    scales it (use_depth=False — decode queues drain at step
    boundaries), but a serving replica whose admissible blocks fall to
    the watermark is pressure."""
    reg, core = Registry(), StubCore()
    sup = _supervisor(reg, slot_prefix="d", role="decode")
    ctl = _pool_controller(core, sup, reg, "decode",
                           use_depth=False, low_blocks=4)
    # deep queue, healthy arena: hold (depth is not a decode signal)
    core.views = [_pool_view("r0", "decode", available_blocks=64,
                             depth=50)]
    assert ctl.tick(now=10.0)["action"] == "hold"
    # arena pressure: the WORST serving replica is at the watermark
    core.views = [
        _pool_view("r0", "decode", available_blocks=64),
        _pool_view("r1", "decode", available_blocks=3),
    ]
    row = ctl.tick(now=20.0)
    assert row["action"] == "scale_up", row
    assert "available blocks" in row["reason"], row
    assert row["min_blocks"] == 3 and row["pool"] == "decode"
    # occupancy stays live as a decode signal
    reg2, core2 = Registry(), StubCore()
    ctl2 = _pool_controller(core2, _supervisor(reg2), reg2, "decode",
                            use_depth=False)
    core2.views = [_pool_view("r0", "decode", occupancy=0.95)]
    assert ctl2.tick(now=10.0)["action"] == "scale_up"


def test_decode_pool_block_pressure_blocks_idle_scale_down():
    """An arena hovering just above the watermark is not 'idle': the
    scale-down needs comfortable headroom (> 2x low_blocks)."""
    reg, core = Registry(), StubCore()
    sup = _supervisor(reg, slot_prefix="d", role="decode")
    ctl = _pool_controller(core, sup, reg, "decode",
                           use_depth=False, low_blocks=4,
                           idle_s=5.0, down_cooldown_s=5.0)
    ctl._register(sup.ensure(2, now=0.0))
    ctl.target = 2
    core.views = [
        _pool_view("r0", "decode", available_blocks=7),
        _pool_view("r1", "decode", available_blocks=64),
    ]
    for t in (10.0, 20.0, 40.0):
        assert ctl.tick(now=t)["action"] == "hold"
    # headroom restored: the idle dwell may finally run down
    core.views = [
        _pool_view("r0", "decode", available_blocks=60),
        _pool_view("r1", "decode", available_blocks=64),
    ]
    ctl.tick(now=50.0)
    assert ctl.tick(now=56.0)["action"] == "scale_down"


def test_prefill_pool_ignores_occupancy_scales_on_depth_and_breach():
    """The prefill pool watches queue depth + TTFT burn; occupancy is
    meaningless there (no decode arena) and must not trip it."""
    reg, core = Registry(), StubCore()
    sup = _supervisor(reg, slot_prefix="p", role="prefill")
    ctl = _pool_controller(core, sup, reg, "prefill",
                           use_occupancy=False, high_depth=4.0)
    core.views = [_pool_view("r0", "prefill", occupancy=1.0)]
    assert ctl.tick(now=10.0)["action"] == "hold"
    core.views = [_pool_view("r0", "prefill", depth=3, in_flight=2)]
    row = ctl.tick(now=20.0)
    assert row["action"] == "scale_up" and "depth" in row["reason"]
    reg2, core2 = Registry(), StubCore()
    ctl2 = _pool_controller(core2, _supervisor(reg2), reg2, "prefill",
                            use_occupancy=False)
    core2.views = [_pool_view("r0", "prefill", breach=True)]
    assert ctl2.tick(now=10.0)["action"] == "scale_up"


def test_prefill_pool_depth_can_exclude_router_inflight():
    """count_in_flight=False (the direct-transport prefill policy):
    router-side in-flight spans the whole prefill->decode relay there,
    so only replica-REPORTED queue depth may trip the scale-up — five
    slow decodes in relay are not prefill pressure."""
    reg, core = Registry(), StubCore()
    sup = _supervisor(reg, slot_prefix="p", role="prefill")
    ctl = _pool_controller(core, sup, reg, "prefill",
                           use_occupancy=False, high_depth=4.0,
                           count_in_flight=False)
    core.views = [_pool_view("r0", "prefill", depth=0, in_flight=5)]
    assert ctl.tick(now=10.0)["action"] == "hold"
    core.views = [_pool_view("r0", "prefill", depth=5, in_flight=0)]
    assert ctl.tick(now=20.0)["action"] == "scale_up"


def test_pool_controllers_keep_labeled_counters_and_per_pool_replay():
    """Two pool controllers over one registry: each pool's rows replay
    into ITS pool-labeled pfx_controller_* counters exactly (the PR 11
    replay contract, per-pool edition), and the monolith spelling stays
    unlabeled."""
    reg, core = Registry(), StubCore()
    pre = _pool_controller(
        core, _supervisor(reg, slot_prefix="p", role="prefill"), reg,
        "prefill", use_occupancy=False, up_cooldown_s=1.0,
    )
    dec = _pool_controller(
        core, _supervisor(reg, base_port=9700, slot_prefix="d",
                          role="decode"), reg,
        "decode", use_depth=False, low_blocks=4, up_cooldown_s=1.0,
    )
    core.views = [
        _pool_view("r0", "prefill", depth=9),
        _pool_view("r1", "decode", available_blocks=2),
    ]
    pre.tick(now=10.0)   # prefill scale_up (depth)
    dec.tick(now=10.0)   # decode scale_up (blocks)
    core.views = [
        _pool_view("r0", "prefill", depth=9),
        _pool_view("r2", "prefill", state="booting"),
        _pool_view("r1", "decode", available_blocks=50),
        _pool_view("r3", "decode", state="booting"),
    ]
    pre.tick(now=11.0)   # hold: warming
    dec.tick(now=11.0)   # hold
    rows = list(pre.decision_log) + list(dec.decision_log)
    for pool, ctl in (("prefill", pre), ("decode", dec)):
        replay = replay_controller_log(rows, pool=pool)
        assert replay["ticks"] == 2
        assert replay["scale_ups"] == 1
        assert reg.value("pfx_controller_ticks_total",
                         pool=pool) == replay["ticks"]
        assert reg.value("pfx_controller_scale_ups_total",
                         pool=pool) == replay["scale_ups"]
        assert reg.value("pfx_controller_target_replicas",
                         pool=pool) == ctl.target
    # the monolith spelling stays UNLABELED (PR 11 drill contract)
    assert reg.value("pfx_controller_ticks_total") == 0.0


def test_supervisor_slot_prefix_names_pool_replicas():
    reg = Registry()
    sup = _supervisor(reg, slot_prefix="d", role="decode")
    sup.ensure(2, now=0.0)
    assert [m.rid for m in sup._snapshot()] == ["d0", "d1"]
    assert sup.views()[0]["replica_id"] == "d0"
