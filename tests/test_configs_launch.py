"""Every registered module has >=1 config YAML that tools/train.py can
drive (VERDICT r1 item 7): cheap validation (config -> process -> module
build) for all family configs, plus real 2-3 step CLI-equivalent training
for the synthetic-data families on the 8-device CPU mesh."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALL_CONFIGS = [
    # (config path, num_devices)
    ("configs/gpt/pretrain_gpt_345M_single.yaml", 1),
    ("configs/gpt/pretrain_gpt_1.3B_mp8.yaml", 8),
    ("configs/gpt/pretrain_gpt_6.7B_sharding16.yaml", 16),
    ("configs/gpt/pretrain_gpt_175B_mp8_pp16.yaml", 128),
    ("configs/gpt/finetune_gpt_345M_glue.yaml", 1),
    ("configs/gpt/qat_gpt_345M_mp8.yaml", 8),
    ("configs/ernie/pretrain_ernie_base.yaml", 1),
    ("configs/ernie/pretrain_ernie_175B_mp8_pp16.yaml", 128),
    ("configs/t5/pretrain_t5_base.yaml", 1),
    ("configs/debertav2/pretrain_debertav2_base.yaml", 1),
    ("configs/imagen/imagen_text2im_64_base.yaml", 1),
    ("configs/protein/helixfold_initial.yaml", 1),
    ("configs/protein/helixfold_tiny_smoke.yaml", 1),
    ("configs/vis/vit/ViT_base_patch16_224_pt_in1k_1n8c_dp.yaml", 8),
    ("configs/vis/vit/ViT_tiny_ci_synthetic_1n8c_dp.yaml", 8),
    ("configs/vis/moco/mocov1_pt_in1k_1n8c.yaml", 8),
    ("configs/vis/moco/mocov2_pt_in1k_1n8c.yaml", 8),
    ("configs/vis/moco/moco_lincls_in1k_1n8c.yaml", 8),
    ("configs/vis/resnet/resnet50_in1k_1n8c.yaml", 8),
    ("configs/multimodal/clip/clip_vitb16_pt_1n8c.yaml", 8),
]


def test_project_launchers_reference_real_files():
    """Every projects/*.sh launcher points at a config and tool that exist
    (reference ships projects/<model>/*.sh wrappers, SURVEY.md §1.1)."""
    import glob
    import re

    scripts = glob.glob(os.path.join(REPO, "projects", "*", "*.sh"))
    assert len(scripts) >= 15
    for sh in scripts:
        with open(sh) as f:
            text = f.read()
        m = re.search(r"python (\S+)(?:\s+-c\s+(\S+))?", text)
        assert m, f"{sh}: no python invocation"
        assert os.path.exists(os.path.join(REPO, m.group(1))), f"{sh}: {m.group(1)}"
        if m.group(2):
            assert os.path.exists(os.path.join(REPO, m.group(2))), f"{sh}: {m.group(2)}"


@pytest.mark.parametrize("path,ndev", ALL_CONFIGS)
def test_config_loads_and_module_builds(path, ndev):
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.utils.config import get_config

    cfg = get_config(os.path.join(REPO, path), num_devices=ndev)
    module = build_module(cfg)
    assert hasattr(module, "loss_fn")


@pytest.mark.parametrize("path,ndev", ALL_CONFIGS)
def test_config_optimizer_builds(path, ndev):
    """build_optimizer accepts every shipped Optimizer block — catches
    config-schema drift the module-build smoke can't (the T5 scalar
    grad_clip crash lived here undetected until round 4)."""
    from paddlefleetx_tpu.optims.optimizer import build_optimizer
    from paddlefleetx_tpu.utils.config import get_config

    cfg = get_config(os.path.join(REPO, path), num_devices=ndev)
    tx, schedule = build_optimizer(cfg.Optimizer)
    assert tx is not None and callable(schedule)


def _run_train(config, overrides, timeout=540):
    env = dict(os.environ)
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    env["PFX_PLATFORM"] = "cpu"
    cmd = [sys.executable, os.path.join(REPO, "tools", "train.py"), "-c",
           os.path.join(REPO, config)]
    for o in overrides:
        cmd += ["-o", o]
    out = subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "step " in out.stderr or "step " in out.stdout


@pytest.mark.slow
def test_moco_synthetic_trains_via_cli():
    _run_train(
        "configs/vis/moco/mocov2_pt_in1k_1n8c.yaml",
        [
            "Global.global_batch_size=16", "Global.local_batch_size=2",
            "Global.micro_batch_size=2",
            "Engine.max_steps=2", "Engine.logging_freq=1", "Engine.eval_freq=0",
            "Engine.save_load.save_steps=0", "Engine.mix_precision.enable=False",
            "Model.K=64", "Model.dim=16", "Model.base_encoder=resnet18",
            "Data.Train.dataset.name=ContrastiveLearningDataset",
            "Data.Train.dataset.cls_label_path=null",
            "Data.Train.dataset.root=null",
            "Data.Train.dataset.num_samples=32",
            "Data.Train.dataset.image_size=32",
        ],
    )


@pytest.mark.slow
def test_clip_synthetic_trains_via_cli(tmp_path):
    from paddlefleetx_tpu.data.multimodal_dataset import (
        write_synthetic_image_text_corpus,
    )
    from paddlefleetx_tpu.data.tokenizers.t5_tokenizer import T5Tokenizer

    corpus = write_synthetic_image_text_corpus(
        str(tmp_path / "corpus.jsonl"), n=16, image_size=32
    )
    tok = T5Tokenizer.from_tiny_corpus(["a tiny synthetic image"])
    tok.save(str(tmp_path / "vocab.json"))
    _run_train(
        "configs/multimodal/clip/clip_vitb16_pt_1n8c.yaml",
        [
            "Global.global_batch_size=8", "Global.local_batch_size=1",
            "Global.micro_batch_size=1",
            "Engine.max_steps=2", "Engine.logging_freq=1", "Engine.eval_freq=0",
            "Engine.save_load.save_steps=0", "Engine.mix_precision.enable=False",
            "Model.projection_dim=16", "Model.image_size=32", "Model.patch_size=8",
            "Model.vision_hidden_size=32", "Model.vision_layers=2",
            "Model.vision_heads=4", "Model.text_hidden_size=32",
            "Model.text_layers=2", "Model.text_heads=4", "Model.max_text_len=16",
            f"Model.vocab_size={max(tok.vocab_size, 64)}",
            f"Data.Train.dataset.input_path={corpus}",
            "Data.Train.dataset.image_size=32",
            "Data.Train.dataset.max_seq_len=16",
            f"Data.Train.dataset.tokenizer_vocab={tmp_path}/vocab.json",
        ],
    )


@pytest.mark.slow
def test_resnet_synthetic_trains_via_cli():
    _run_train(
        "configs/vis/resnet/resnet50_in1k_1n8c.yaml",
        [
            "Global.global_batch_size=16", "Global.local_batch_size=2",
            "Global.micro_batch_size=2",
            "Engine.max_steps=2", "Engine.logging_freq=1", "Engine.eval_freq=0",
            "Engine.save_load.save_steps=0", "Engine.mix_precision.enable=False",
            "Model.depth=18", "Model.num_classes=8",
            "Data.Train.dataset.name=SyntheticClsDataset",
            "Data.Train.dataset.num_samples=32",
            "Data.Train.dataset.image_size=32",
            "Data.Train.dataset.num_classes=8",
            "Data.Eval.dataset.name=SyntheticClsDataset",
            "Data.Eval.dataset.num_samples=8",
            "Data.Eval.dataset.image_size=32",
            "Data.Eval.dataset.num_classes=8",
        ],
    )


# ---------------------------------------------------------------------------
# download utils + no-engine examples
# ---------------------------------------------------------------------------


def test_cached_path_local_and_md5(tmp_path):
    from paddlefleetx_tpu.utils.download import cached_path, check_md5, md5file

    f = tmp_path / "artifact.bin"
    f.write_bytes(b"hello weights")
    p = cached_path(str(f))
    assert p == str(f)
    digest = md5file(p)
    assert check_md5(p, digest)
    assert not check_md5(p, "0" * 32)
    with pytest.raises(IOError):
        cached_path(str(f), md5sum="0" * 32)
    with pytest.raises(FileNotFoundError):
        cached_path(str(tmp_path / "missing.bin"))


def test_download_retries_and_atomic(tmp_path, monkeypatch):
    """A flaky 'transport' fails twice then succeeds; the cache file appears
    atomically with the right contents."""
    import io
    import urllib.request

    from paddlefleetx_tpu.utils import download as dl

    calls = {"n": 0}

    def fake_urlopen(url):
        calls["n"] += 1
        if calls["n"] < 3:
            raise IOError("flaky network")

        class Ctx:
            def __enter__(self):
                return io.BytesIO(b"payload")

            def __exit__(self, *a):
                return False

        return Ctx()

    monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
    out = dl.cached_path(
        "http://example.invalid/weights.bin", cache_dir=str(tmp_path)
    )
    assert open(out, "rb").read() == b"payload"
    assert calls["n"] == 3
    # cached: no further transport calls
    out2 = dl.cached_path(
        "http://example.invalid/weights.bin", cache_dir=str(tmp_path)
    )
    assert out2 == out and calls["n"] == 3


def test_download_sha256_quarantines_and_refetches(tmp_path, monkeypatch):
    """A cached artifact whose sha256 stops matching is quarantined
    (*.corrupt) and re-fetched; a mirror that keeps serving a bad body
    exhausts the retry loudly naming the download."""
    import hashlib
    import io
    import urllib.request

    from paddlefleetx_tpu.utils import download as dl

    good = b"good weights"
    good_sha = hashlib.sha256(good).hexdigest()
    serve = {"body": good, "n": 0}

    def fake_urlopen(url):
        serve["n"] += 1

        class Ctx:
            def __enter__(self):
                return io.BytesIO(serve["body"])

            def __exit__(self, *a):
                return False

        return Ctx()

    monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
    monkeypatch.setenv("PFX_RETRY_BACKOFF", "0.0")
    url = "http://example.invalid/model.bin"
    out = dl.cached_path(url, cache_dir=str(tmp_path), sha256sum=good_sha)
    assert open(out, "rb").read() == good and serve["n"] == 1

    # rot the cached file: next resolve quarantines + re-fetches
    with open(out, "wb") as f:
        f.write(b"bit-rotted")
    out2 = dl.cached_path(url, cache_dir=str(tmp_path), sha256sum=good_sha)
    assert out2 == out and open(out, "rb").read() == good
    assert serve["n"] == 2
    assert (tmp_path / "model.bin.corrupt").exists()

    # mirror serves garbage forever: retry exhausts LOUDLY, nothing lands
    serve["body"] = b"always wrong"
    with open(out, "wb") as f:
        f.write(b"bit-rotted again")
    with pytest.raises(RuntimeError, match="download"):
        dl.cached_path(url, cache_dir=str(tmp_path), sha256sum=good_sha)
    assert not (tmp_path / "model.bin").exists()  # bad body never cached


@pytest.mark.slow
def test_no_engine_examples_run():
    env = dict(os.environ)
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    env["PFX_PLATFORM"] = "cpu"
    for script, extra in (
        ("examples/transformer/train_no_engine.py", []),
        ("examples/transformer/generate_no_engine.py", []),
        ("examples/transformer/long_context_ring.py",
         ["--seq", "512", "--steps", "1", "--hidden", "64"]),
    ):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, script)] + extra,
            capture_output=True, text=True, timeout=420, cwd=REPO, env=env,
        )
        assert out.returncode == 0, (script, out.stderr[-1500:])


def test_file_utils_roundtrip(tmp_path):
    import tarfile
    import zipfile

    from paddlefleetx_tpu.utils.file import parse_csv, untar, unzip

    (tmp_path / "a.txt").write_text("hello")
    zp = str(tmp_path / "arch.zip")
    with zipfile.ZipFile(zp, "w") as z:
        z.write(tmp_path / "a.txt", "a.txt")
    out = unzip(zp, out_dir=str(tmp_path / "unz"))
    assert (tmp_path / "unz" / "a.txt").read_text() == "hello"

    tp = str(tmp_path / "arch.tar.gz")
    with tarfile.open(tp, "w:gz") as t:
        t.add(tmp_path / "a.txt", "a.txt")
    untar(tp, out_dir=str(tmp_path / "unt"))
    assert (tmp_path / "unt" / "a.txt").read_text() == "hello"

    (tmp_path / "t.csv").write_text("k,v\nx,1\ny,2\n")
    rows = parse_csv(str(tmp_path / "t.csv"))
    assert rows == [{"k": "x", "v": "1"}, {"k": "y", "v": "2"}]


def test_check_version_passes_here():
    from paddlefleetx_tpu.utils.check import check_device, check_version

    check_version()
    check_device("cpu")


@pytest.mark.slow
def test_export_then_inference_cli(tmp_path):
    """tools/export.py -> tools/inference.py chain on the CPU mesh
    (reference deploy path: export -> InferenceEngine predict)."""
    from paddlefleetx_tpu.data.gpt_dataset import write_synthetic_corpus

    data = tmp_path / "data"
    data.mkdir()
    write_synthetic_corpus(str(data / "corp"), vocab_size=128, num_docs=16)
    common = [
        "Model.num_layers=2", "Model.hidden_size=64",
        "Model.num_attention_heads=4", "Model.vocab_size=128",
        "Model.max_position_embeddings=32",
        "Global.global_batch_size=16", "Global.local_batch_size=2",
        "Global.micro_batch_size=2",
        f"Data.Train.dataset.input_dir={data}", "Data.Train.dataset.max_seq_len=32",
        f"Engine.save_load.output_dir={tmp_path / 'out'}",
    ]
    env = dict(os.environ)
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    env["PFX_PLATFORM"] = "cpu"

    def run(tool, extra):
        cmd = [sys.executable, os.path.join(REPO, "tools", tool),
               "-c", os.path.join(REPO, "configs/gpt/pretrain_gpt_345M_single.yaml")]
        for o in common + extra:
            cmd += ["-o", o]
        out = subprocess.run(cmd, capture_output=True, text=True, timeout=540,
                             cwd=REPO, env=env)
        assert out.returncode == 0, (tool, out.stderr[-2000:])
        return out.stdout + out.stderr

    run("export.py", [])
    assert (tmp_path / "out" / "inference" / "model.stablehlo").exists()
    log = run("inference.py", [
        f"Inference.model_dir={tmp_path / 'out' / 'inference'}",
        "Inference.max_seq_len=32",
    ])
    assert "inference ok" in log


@pytest.mark.slow
def test_gpt_task_clis(tmp_path):
    """tasks/gpt/{generation,inference}.py run end-to-end on the tiny
    config (reference tasks/gpt parity: no-engine generation demo +
    engine-mode inference demo)."""
    cfg = tmp_path / "tiny.yaml"
    cfg.write_text(
        """Global:
  global_batch_size: 8
  seed: 3
Engine:
  mix_precision:
    enable: False
  save_load:
    save_steps: 0
Model:
  module: GPTModule
  vocab_size: 96
  hidden_size: 32
  num_layers: 2
  num_attention_heads: 4
  max_position_embeddings: 128
  dtype: float32
Distributed: {}
Optimizer:
  name: FusedAdamW
  lr:
    name: Constant
    learning_rate: 0.001
Generation:
  max_dec_len: 8
  decode_strategy: greedy_search
  pad_to_multiple: 16
  eos_token_id: 95
  pad_token_id: 0
"""
    )
    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    for script in ("tasks/gpt/generation.py", "tasks/gpt/inference.py"):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, script), "-c", str(cfg)],
            capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
        )
        assert out.returncode == 0, (script, out.stderr[-2000:])
        assert "enerat" in out.stdout + out.stderr, script  # Generated/generation


@pytest.mark.slow
def test_crash_and_auto_resume_e2e(tmp_path):
    """Fault injection through the real CLI (SURVEY §5.3: recovery =
    checkpoint/resume): SIGKILL tools/train.py mid-run after a checkpoint
    lands, relaunch with auto_resume — training continues from the newest
    complete step dir and finishes."""
    import signal
    import time as _time

    from paddlefleetx_tpu.data.gpt_dataset import write_synthetic_corpus

    data = tmp_path / "data"
    data.mkdir()
    write_synthetic_corpus(str(data / "corp"), vocab_size=128, num_docs=16)
    out = tmp_path / "out"
    common = [
        "Model.num_layers=2", "Model.hidden_size=32",
        "Model.num_attention_heads=4", "Model.vocab_size=128",
        "Model.max_position_embeddings=32",
        "Global.global_batch_size=8", "Global.local_batch_size=8",
        "Global.micro_batch_size=8",
        "Engine.max_steps=16", "Engine.logging_freq=1", "Engine.eval_freq=0",
        "Engine.mix_precision.enable=False",
        "Engine.save_load.save_steps=2",
        "Engine.save_load.auto_resume=True",
        f"Engine.save_load.output_dir={out}",
        f"Data.Train.dataset.input_dir={data}", "Data.Train.dataset.max_seq_len=32",
    ]
    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    cmd = [sys.executable, os.path.join(REPO, "tools", "train.py"), "-c",
           os.path.join(REPO, "configs/gpt/pretrain_gpt_345M_single.yaml")]
    for o in common:
        cmd += ["-o", o]

    # run 1: kill -9 once the first checkpoint is complete
    proc = subprocess.Popen(cmd, cwd=REPO, env=env,
                            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    deadline = _time.time() + 300
    try:
        while _time.time() < deadline:
            if (out / "step_2" / "meta.json").exists():
                break
            if proc.poll() is not None:
                raise AssertionError(f"train exited early rc={proc.returncode}")
            # tight poll: the kill must land well before the remaining 14
            # steps (+7 checkpoint saves) finish
            _time.sleep(0.05)
        else:
            raise AssertionError("no checkpoint appeared before the deadline")
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        # the kill must interrupt a LIVE run: if all 16 steps already
        # finished, run 2 would resume at step_16, train zero steps, and
        # this test would pass without exercising the crash path
        assert not (out / "step_16" / "meta.json").exists(), (
            "run 1 completed before the kill — crash path not exercised; "
            "slow the run down (more steps or a bigger model)"
        )
    finally:
        if proc.poll() is None:
            proc.kill()

    # run 2: auto-resume from the newest complete checkpoint, finish
    run2 = subprocess.run(cmd, capture_output=True, text=True, timeout=540,
                          cwd=REPO, env=env)
    assert run2.returncode == 0, run2.stderr[-2000:]
    log = run2.stdout + run2.stderr
    assert "auto_resume: found" in log
    assert (out / "step_16" / "meta.json").exists(), os.listdir(out)
