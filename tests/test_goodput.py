"""Serving goodput ledger closure (`make test-goodput`,
docs/observability.md "Goodput ledger" + "On-demand profiling"):

  in-process   the scheduler time ledger's six exhaustive buckets close
               against scheduler-thread wall within 1%, and the token
               ledger closes EXACTLY (admitted == delivered +
               evicted_lost + preempt_refunded + shed_after_admit +
               in_flight) under a seeded mix of admissions, a true
               mid-decode eviction, a partial-admission expiry, a
               forced preemption, deadline sheds, and streaming —
               with the decision-log replay folding every disposition
               to the same totals
  cli-ledger   the same closure drilled through the REAL tools/serve.py
               CLI: a preempt-storm replica and a step-hang replica
               together produce >=1 eviction, >=1 preemption and >=1
               post-admission shed; each replica's /metrics books close
               exactly at quiescence, its time buckets close within 1%,
               and GET /debug/state's decision log replays to the same
               token totals
  cli-profile  POST /admin/profile through tools/router.py captures a
               live jax.profiler trace on a decoding replica and
               returns the merged op summary; no token -> 401, a
               concurrent capture -> 409, over PFX_PROFILE_MAX_SECONDS
               -> 400

Follows tests/test_tenant_drills.py conventions for the drills
(`fault`-marked, subprocess-driven, tiny synthetic GPT, warm XLA
compile cache via tests/conftest.py).
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = {
    "Global": {"global_batch_size": 8, "seed": 5},
    "Engine": {"mix_precision": {"enable": False},
               "save_load": {"save_steps": 0}},
    "Model": {
        "module": "GPTModule",
        "vocab_size": 96,
        "hidden_size": 32,
        "num_layers": 2,
        "num_attention_heads": 4,
        "max_position_embeddings": 128,
        "dtype": "float32",
    },
    "Distributed": {},
    "Optimizer": {"name": "FusedAdamW",
                  "lr": {"name": "Constant", "learning_rate": 1e-3}},
    "Generation": {"max_dec_len": 8, "decode_strategy": "greedy_search",
                   "pad_to_multiple": 16, "eos_token_id": 95,
                   "pad_token_id": 0},
}

PROMPTS = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 10], [11, 12, 13, 14]]

BUCKETS = {"device_decode", "device_prefill", "host_sched",
           "readback", "stream_flush", "idle"}
TERMINAL = ("delivered", "evicted_lost", "preempt_refunded",
            "shed_after_admit")


@pytest.fixture(scope="module")
def server():
    import jax

    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict.from_nested(TINY)
    cfg = process_configs(cfg, num_devices=jax.device_count())
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    return GenerationServer(cfg, mesh, module)


def _engine(server, **kw):
    from paddlefleetx_tpu.core.continuous_batching import PagedDecodeEngine

    kw.setdefault("max_batch", 4)
    return PagedDecodeEngine(server, **kw)


def _assert_time_closure(ledger, max_drift=0.01):
    """The exhaustiveness contract: bucket names exactly, every bucket
    non-negative, and the sum closes against wall within 1%."""
    assert set(ledger["buckets"]) == BUCKETS, ledger
    assert all(v >= 0.0 for v in ledger["buckets"].values()), ledger
    wall = ledger["wall_s"]
    assert wall > 0.0, ledger
    drift = abs(sum(ledger["buckets"].values()) - wall)
    assert drift <= max(max_drift * wall, 1e-6), (drift, ledger)


def _assert_token_closure(ledger):
    """The bank contract, EXACT: every admitted token has a terminal
    disposition (or sits on a live row, counted in_flight)."""
    assert ledger["admitted"] == sum(
        ledger[d] for d in TERMINAL
    ) + ledger["in_flight"], ledger


# ---------------------------------------------------------------------------
# in-process: time-ledger closure
# ---------------------------------------------------------------------------


def test_time_ledger_buckets_close_against_wall(server):
    """A plain served batch: the six buckets are exhaustive and
    mutually exclusive, so their sum closes against the scheduler
    thread's own wall clock within 1% — and real decode work lands in
    the device buckets, not in a catch-all."""
    from paddlefleetx_tpu.core.continuous_batching import ContinuousScheduler

    eng = _engine(server)
    sched = ContinuousScheduler(eng, max_depth=16)
    sched.warmup([4])
    sched.start()
    futs = [sched.submit([p], 6, deadline_s=120) for p in PROMPTS]
    outs = [f.result(timeout=300)[0] for f in futs]
    assert all(len(o) >= 1 for o in outs)
    assert sched.shutdown(timeout=60)

    tl = sched.time_ledger()
    _assert_time_closure(tl)
    assert tl["buckets"]["device_decode"] > 0.0, tl
    # readback + host bookkeeping happened and was attributed somewhere
    assert tl["buckets"]["readback"] > 0.0, tl
    # the metrics families mirror the accessor exactly (per-instance
    # collect(), no registry round-trip to conflate instances)
    mets = {(name, frozenset(labels.items())): v
            for name, labels, v in sched.collect()}
    for b, v in tl["buckets"].items():
        assert mets[
            ("pfx_sched_time_seconds_total", frozenset({("bucket", b)}))
        ] == pytest.approx(v, abs=2e-6)
    assert mets[
        ("pfx_sched_wall_seconds_total", frozenset())
    ] == pytest.approx(tl["wall_s"], abs=2e-6)


# ---------------------------------------------------------------------------
# in-process: token-ledger exact closure under a seeded adversarial mix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [3, 11])
def test_token_ledger_exact_closure_seeded_mix(server, monkeypatch, seed):
    """THE closure property test: one scheduler's books survive a true
    mid-decode eviction, a partial-admission expiry (shed_after_admit),
    a forced preemption (preempt_storm), queue-level deadline sheds,
    streaming, and a seeded random traffic tail — and close EXACTLY,
    with the decision-log replay reproducing every disposition and the
    time buckets closing within 1%."""
    from paddlefleetx_tpu.core.continuous_batching import ContinuousScheduler
    from paddlefleetx_tpu.core.request_queue import DeadlineExceeded
    from paddlefleetx_tpu.utils import resilience
    from paddlefleetx_tpu.utils.tracing import replay_decision_log

    resilience.reset_fault_state()
    eng = _engine(server, max_batch=4)
    sched = ContinuousScheduler(eng, max_depth=32, preempt_min_tokens=2)

    # -- phase 1 (hand-driven): a TRUE mid-decode eviction of a fully
    # admitted row — force its deadline into the past AFTER it decoded
    doomed = sched.submit([PROMPTS[1]], 64, deadline_s=60)
    sched._iterate()
    assert eng.active_rows() == 1
    row = next(r for r in eng.slots if r is not None)
    row.entry.deadline = time.monotonic() - 1.0
    sched._iterate()
    assert sched.stats["evictions"] == 1
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=10)
    ledger = sched.token_ledger()
    assert ledger["evicted_lost"] >= 1, ledger
    _assert_token_closure(ledger)

    # -- phase 2 (hand-driven): partial-admission expiry — two more
    # prompts than the engine has slots seats a full batch and leaves
    # the remainder pending; expiring the entry while split books its
    # on-board tokens as shed_after_admit
    rng = np.random.default_rng(seed)
    over = [rng.integers(1, 90, int(n)).tolist()
            for n in rng.integers(2, 8, eng.capacity + 2)]
    partial = sched.submit(over, 64, deadline_s=60)
    sched._iterate()
    assert 0 < eng.active_rows() <= eng.capacity
    entry = next(r for r in eng.slots if r is not None).entry
    assert entry.next_row < len(entry.prompts), "not partially admitted"
    entry.deadline = time.monotonic() - 1.0
    sched._iterate()
    with pytest.raises(DeadlineExceeded):
        partial.result(timeout=10)
    ledger = sched.token_ledger()
    assert ledger["shed_after_admit"] >= 1, ledger
    _assert_token_closure(ledger)

    # -- phase 3 (threaded): forced preemption two iterations out, plus
    # a streaming + plain seeded tail and a queue-level deadline shed
    # (never admitted -> must NOT touch the token books)
    # fire after the wave's rows have >= preempt_min_tokens committed
    # (admission at +1, so +5 leaves ~4 decode steps of progress)
    monkeypatch.setenv(
        "PFX_FAULT", f"preempt_storm:{sched._iter_counter + 5}"
    )
    resilience.reset_fault_state()
    streams = {i: [] for i in range(len(PROMPTS))}
    sched.start()
    futs = [
        sched.submit(
            [p], 6, deadline_s=120,
            stream=(lambda i: lambda r, s, t:
                    streams[i].append((s, list(t))))(i),
        )
        for i, p in enumerate(PROMPTS)
    ]
    tail = [
        sched.submit(
            [rng.integers(1, 90, int(rng.integers(1, 12))).tolist()],
            int(rng.integers(1, 8)), deadline_s=120,
        )
        for _ in range(6)
    ]
    outs = [f.result(timeout=300)[0] for f in futs]
    tail_outs = [f.result(timeout=300)[0] for f in tail]
    monkeypatch.delenv("PFX_FAULT")
    resilience.reset_fault_state()
    assert sched.stats["preemptions"] == 1

    shed0 = sched.token_ledger()["admitted"]
    late = sched.submit([PROMPTS[0]], 4, deadline_s=0.00001)
    with pytest.raises(DeadlineExceeded):
        late.result(timeout=30)
    assert sched.stats["shed_deadline"] >= 1
    assert sched.shutdown(timeout=60)

    # -- the books, at quiescence: EXACT closure, nothing in flight,
    # every disposition exercised at least once in this mix
    ledger = sched.token_ledger()
    assert ledger["in_flight"] == 0
    _assert_token_closure(ledger)
    for d in TERMINAL:
        assert ledger[d] >= 1, (d, ledger)
    delivered = sum(len(o) for o in outs) + sum(len(o) for o in tail_outs)
    assert ledger["delivered"] == delivered, (ledger, delivered)
    # the queue-level shed never admitted a token
    assert ledger["admitted"] >= shed0  # monotone...
    # streams reassemble into exactly the delivered outputs (offsets
    # survived the preempt-resume rebase)
    for i in range(len(PROMPTS)):
        acc = []
        for start, toks in streams[i]:
            assert start == len(acc), f"row {i}: hole/overlap at {start}"
            acc.extend(toks)
        assert acc == outs[i]

    # -- replay agreement: the decision log folds to the same totals
    replay = replay_decision_log(sched.decision_log)
    assert replay["tok_admitted"] == ledger["admitted"]
    for d in TERMINAL:
        assert replay[f"tok_{d}"] == ledger[d], (d, replay, ledger)

    # -- and the time books on the same instance close within 1%
    _assert_time_closure(sched.time_ledger())


def test_tenant_occupancy_books_accrue(server):
    """Cost attribution: decode-slot seconds and KV-block seconds
    accrue under the request's tenant label and surface both in the
    collect() families and the /debug/state goodput block."""
    from paddlefleetx_tpu.core.continuous_batching import ContinuousScheduler

    eng = _engine(server)
    sched = ContinuousScheduler(eng, max_depth=16)
    sched.start()
    futs = [sched.submit([p], 6, deadline_s=120, tenant="acme")
            for p in PROMPTS[:2]]
    for f in futs:
        f.result(timeout=300)
    assert sched.shutdown(timeout=60)

    occ = {
        labels["tenant"]: v
        for name, labels, v in sched.collect()
        if name == "pfx_tenant_slot_seconds_total"
    }
    assert occ.get("acme", 0.0) > 0.0, occ
    kv = {
        labels["tenant"]: v
        for name, labels, v in sched.collect()
        if name == "pfx_tenant_kv_block_seconds_total"
    }
    assert kv.get("acme", 0.0) > 0.0, kv
    dbg = sched._engine_debug_view()
    ten = dbg["goodput"]["tenant_occupancy"]
    assert ten["acme"]["slot_s"] > 0.0 and ten["acme"]["kv_block_s"] > 0.0


# ---------------------------------------------------------------------------
# CLI drills (fault-marked): real serve.py / router.py subprocesses
# ---------------------------------------------------------------------------


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _env(extra=None):
    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PFX_FAULT", None)
    env.update(extra or {})
    return env


def _post(port, body, *, headers=None, timeout=90, path="/generate"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r), dict(r.headers.items())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers.items())


def _get(port, path, timeout=10, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", headers=headers or {}
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.load(r)


def _metrics(port, timeout=10):
    from test_telemetry import parse_prometheus

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=timeout
    ) as r:
        metrics, _ = parse_prometheus(r.read().decode())
    return metrics


def _fam(mets, name):
    """{label_value_or_(): value} for one family, single-label."""
    out = {}
    for labels, v in mets.get(name, {}).items():
        key = dict(labels)
        out[tuple(sorted(key.values()))[0] if key else ""] = v
    return out


def _spawn_replica(cfg_path, port, *extra, extra_env=None):
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-c", str(cfg_path), "--port", str(port),
         "--queue-depth", "32", "--deadline", "60",
         "--warmup-buckets", "4", "--warmup-batches", "1",
         "--scheduler", "continuous", "--cb-batch", "4",
         "--kv-blocks", "16", *extra],
        env=_env(extra_env), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )


def _wait_healthy(procs_ports, timeout=300):
    end = time.time() + timeout
    pending = dict(procs_ports)
    while pending and time.time() < end:
        for port, proc in list(pending.items()):
            if proc.poll() is not None:
                raise AssertionError(
                    f"process on {port} died at boot: "
                    f"{proc.stdout.read()[-3000:]}"
                )
            try:
                if _get(port, "/healthz", timeout=5).get("ok"):
                    del pending[port]
            except Exception:
                pass
        time.sleep(0.3)
    assert not pending, f"never healthy: {sorted(pending)}"


def _finish(proc, timeout=30):
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
    return proc.stdout.read()


def _write_cfg(tmp_path):
    import yaml

    cfg_path = tmp_path / "tiny_serve.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    return cfg_path


def _assert_cli_books_close(port):
    """Exact token closure + 1%-time closure off a live /metrics scrape,
    then decision-log replay agreement off /debug/state.  Returns the
    token family for mix-wide disposition asserts."""
    from paddlefleetx_tpu.utils.tracing import replay_decision_log

    mets = _metrics(port)
    tok = _fam(mets, "pfx_token_ledger_total")
    in_flight = _fam(mets, "pfx_token_ledger_in_flight").get("", 0)
    assert in_flight == 0, mets.get("pfx_token_ledger_in_flight")
    assert tok.get("admitted", 0) == sum(
        tok.get(d, 0) for d in TERMINAL
    ), tok
    assert tok.get("admitted", 0) > 0, tok

    buckets = _fam(mets, "pfx_sched_time_seconds_total")
    wall = _fam(mets, "pfx_sched_wall_seconds_total").get("", 0.0)
    assert set(buckets) == BUCKETS, buckets
    assert wall > 0.0
    drift = abs(sum(buckets.values()) - wall)
    assert drift <= max(0.01 * wall, 1e-4), (drift, buckets, wall)

    dbg = _get(port, "/debug/state")
    replay = replay_decision_log(dbg["decisions"])
    assert replay["tok_admitted"] == tok.get("admitted", 0), (replay, tok)
    for d in TERMINAL:
        assert replay[f"tok_{d}"] == tok.get(d, 0), (d, replay, tok)
    return tok


@pytest.mark.fault
def test_token_ledger_closes_through_real_cli(tmp_path):
    """Closure end-to-end through the real CLI under a faulted mix:
    replica A rides a preempt storm (>=1 preemption), replica B wedges
    mid-decode twice past client deadlines (>=1 full-row eviction, then
    >=1 partial-admission shed).  Each replica's books close EXACTLY on
    /metrics at quiescence, its time buckets close within 1%, and the
    /debug/state decision log replays to the same totals."""
    cfg_path = _write_cfg(tmp_path)
    aport, bport = _free_port(), _free_port()
    rep_a = _spawn_replica(
        cfg_path, aport, "--preempt-min-tokens", "2",
        extra_env={"PFX_FAULT": "preempt_storm:6"},
    )
    rep_b = _spawn_replica(
        cfg_path, bport, "--shed-slack", "1",
        extra_env={"PFX_FAULT": "cb_step_hang:2:2",
                   "PFX_FAULT_HANG_S": "3.0"},
    )
    try:
        _wait_healthy({aport: rep_a, bport: rep_b})

        # -- replica A: concurrent wave through the storm, all 200
        results = [None] * 3
        prompts = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 10]]

        def worker(i):
            results[i] = _post(
                aport, {"prompt_ids": prompts[i], "max_tokens": 8},
                timeout=120,
            )

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=150)
            assert not t.is_alive(), "request hung across the storm"
        assert all(r is not None and r[0] == 200 for r in results), results

        # -- replica B, wave 1: one fully-admitted row wedged 3s past
        # its 2s deadline -> honest 503, evicted_lost on the books
        code, body, _ = _post(
            bport, {"prompt_ids": [1, 2, 3], "max_tokens": 8,
                    "deadline_s": 2.0}, timeout=60,
        )
        assert code == 503, (code, body)

        # -- replica B, wave 2: 6 prompts into 4 slots seats 4 and
        # leaves 2 pending; the second wedge expires the entry while
        # PARTIALLY admitted -> shed_after_admit
        code, body, _ = _post(
            bport, {"prompts_ids": [[i + 1, i + 2] for i in range(6)],
                    "max_tokens": 8, "deadline_s": 2.0}, timeout=60,
        )
        assert code == 503, (code, body)

        # -- replica B delivers again after the wedges drain
        code, body, _ = _post(
            bport, {"prompt_ids": [7, 8, 9], "max_tokens": 4}, timeout=120
        )
        assert code == 200 and body["completion_ids"], body

        tok_a = _assert_cli_books_close(aport)
        tok_b = _assert_cli_books_close(bport)
        # the drill's mix-wide guarantee: every disposition happened
        assert tok_a.get("preempt_refunded", 0) >= 1, tok_a
        assert tok_b.get("evicted_lost", 0) >= 1, tok_b
        assert tok_b.get("shed_after_admit", 0) >= 1, tok_b
        assert tok_a.get("delivered", 0) >= 1
        assert tok_b.get("delivered", 0) >= 1
    finally:
        log_a = _finish(rep_a)
        log_b = _finish(rep_b)
    assert rep_a.returncode == 0, log_a[-3000:]
    assert rep_b.returncode == 0, log_b[-3000:]
    assert "Traceback" not in log_a, log_a[-3000:]
    assert "Traceback" not in log_b, log_b[-3000:]


@pytest.mark.fault
def test_fleet_profile_capture_through_router(tmp_path):
    """On-demand fleet profiling end-to-end: POST /admin/profile on the
    router fans out to the live replica mid-decode and answers with a
    merged op summary; a missing admin token is 401, a concurrent
    capture is a loud 409, and a request over PFX_PROFILE_MAX_SECONDS
    is 400 at the replica."""
    cfg_path = _write_cfg(tmp_path)
    sport, rport = _free_port(), _free_port()
    token = "drill-profile-token"
    env = {"PFX_ADMIN_TOKEN": token, "PFX_PROFILE_MAX_SECONDS": "10",
           "PFX_FLIGHT_DIR": str(tmp_path / "flight")}
    replica = _spawn_replica(cfg_path, sport, extra_env=env)
    router = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "router.py"),
         "--port", str(rport), "--poll-interval", "0.2",
         "--replica", f"http://127.0.0.1:{sport}"],
        env=_env(env), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    auth = {"Authorization": f"Bearer {token}"}
    stop = threading.Event()

    def decode_load():
        while not stop.is_set():
            _post(sport, {"prompt_ids": [1, 2, 3, 4], "max_tokens": 8},
                  timeout=60)
            # keep the replica decoding THROUGHOUT the capture without
            # starving the profile handler's trace parse of the GIL
            time.sleep(0.05)

    load = threading.Thread(target=decode_load, daemon=True)
    try:
        _wait_healthy({sport: replica, rport: router})
        end = time.time() + 30
        while time.time() < end:
            if _get(rport, "/healthz").get("eligible", 0) >= 1:
                break
            time.sleep(0.2)

        # 401 first: no admin token, nothing captured
        code, body, _ = _post(rport, {"seconds": 1.0},
                              path="/admin/profile", timeout=30)
        assert code == 401, (code, body)

        # the real capture, from a replica decoding THROUGHOUT it
        load.start()
        time.sleep(0.5)
        code, body, _ = _post(
            rport, {"seconds": 1.5}, headers=auth,
            path="/admin/profile", timeout=420,
        )
        assert code == 200, (code, body)
        assert body["captured"] == 1 and body["requested"] == 1, body
        (rep,) = body["replicas"].values()
        assert rep["status"] == 200 and rep["replica_id"], rep
        assert rep["op_count"] >= 1 and rep["source"], rep
        # the merged fleet table carries real ops with durations
        assert body["top_ops"], body
        assert all(op["self_us"] >= 0 and op["op"]
                   for op in body["top_ops"]), body["top_ops"]
        assert body["device_us"] + body["host_us"] > 0.0, body
        # the durable summary landed under the flight dir for report.py
        found = []
        for root, _dirs, files in os.walk(tmp_path / "flight"):
            found += [os.path.join(root, f) for f in files
                      if f == "profile_summary.json"]
        assert found, "profile_summary.json not written to flight dir"
        disk = json.load(open(found[0]))
        assert disk["replica_id"] == rep["replica_id"], disk

        # overlap guard: a second operator mid-capture is refused loudly
        first = {}

        def long_capture():
            first["resp"] = _post(
                rport, {"seconds": 4.0}, headers=auth,
                path="/admin/profile", timeout=420,
            )

        t = threading.Thread(target=long_capture)
        t.start()
        time.sleep(1.0)
        code, body, _ = _post(
            rport, {"seconds": 1.0}, headers=auth,
            path="/admin/profile", timeout=30,
        )
        assert code == 409, (code, body)
        assert "active" in body["error"], body
        t.join(timeout=430)
        assert not t.is_alive(), "long capture never returned"
        assert first["resp"][0] == 200, first["resp"][:2]

        # duration cap: over PFX_PROFILE_MAX_SECONDS is an honest 400
        code, body, _ = _post(
            sport, {"seconds": 60.0}, headers=auth,
            path="/admin/profile", timeout=30,
        )
        assert code == 400, (code, body)
        assert "PFX_PROFILE_MAX_SECONDS" in body["error"], body
    finally:
        stop.set()
        if load.is_alive():
            load.join(timeout=70)
        rlog = _finish(router)
        slog = _finish(replica)
    assert replica.returncode == 0, slog[-3000:]
    assert "Traceback" not in slog, slog[-3000:]
    assert "Traceback" not in rlog, rlog[-3000:]
