"""HF GPT-2 checkpoint import: logits parity against the transformers
implementation (an external oracle for the whole GPT forward), plus the
params-only warm-start path through the Engine."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402

from paddlefleetx_tpu.models.gpt import model as gpt  # noqa: E402
from paddlefleetx_tpu.models.gpt.convert import (  # noqa: E402
    convert_hf_gpt2_state_dict,
    hf_gpt2_config,
)


@pytest.fixture(scope="module")
def hf_model():
    from transformers import GPT2Config, GPT2LMHeadModel

    hf_cfg = GPT2Config(
        vocab_size=96, n_positions=32, n_embd=32, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(0)
    return GPT2LMHeadModel(hf_cfg).eval()


def test_logits_match_transformers(hf_model):
    cfg = hf_gpt2_config(
        hf_model.config,
        hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
        dtype="float32",
    )
    params = convert_hf_gpt2_state_dict(hf_model.state_dict(), cfg)
    tokens = np.random.default_rng(0).integers(0, 96, (2, 16))
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(gpt.forward(params, tokens, cfg, train=False))
    np.testing.assert_allclose(ours, ref, atol=2e-5, rtol=1e-5)


def test_vocab_padding(hf_model):
    cfg = hf_gpt2_config(
        hf_model.config, vocab_size=128,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0, dtype="float32",
    )
    params = convert_hf_gpt2_state_dict(hf_model.state_dict(), cfg, pad_vocab_to=128)
    assert params["embeddings"]["word"].shape == (128, 32)
    # real-token logits unchanged by padding
    cfg0 = hf_gpt2_config(
        hf_model.config,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0, dtype="float32",
    )
    p0 = convert_hf_gpt2_state_dict(hf_model.state_dict(), cfg0)
    tokens = np.random.default_rng(1).integers(0, 96, (1, 8))
    a = np.asarray(gpt.forward(p0, tokens, cfg0, train=False))
    b = np.asarray(gpt.forward(params, tokens, cfg, train=False))[..., :96]
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_engine_pretrained_warm_start(hf_model, tmp_path, devices8):
    """Converted checkpoint -> Engine.save_load.pretrained_params: the
    engine starts from the imported weights on a sharded mesh."""
    import orbax.checkpoint as ocp

    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = hf_gpt2_config(
        hf_model.config,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0, dtype="float32",
    )
    params = convert_hf_gpt2_state_dict(hf_model.state_dict(), cfg)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(str(tmp_path / "conv" / "params"), params, force=True)
    ckptr.wait_until_finished()

    ecfg = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": 8, "seed": 5},
            "Engine": {
                "max_steps": 1,
                "eval_freq": 0,
                "logging_freq": 100,
                "mix_precision": {"enable": False},
                "save_load": {
                    "save_steps": 0,
                    "pretrained_params": str(tmp_path / "conv"),
                },
            },
            "Model": {
                "module": "GPTModule",
                "vocab_size": 96,
                "hidden_size": 32,
                "num_layers": 2,
                "num_attention_heads": 4,
                "max_position_embeddings": 32,
                "hidden_dropout_prob": 0.0,
                "attention_probs_dropout_prob": 0.0,
                "dtype": "float32",
            },
            "Distributed": {"mp_degree": 2},
            "Optimizer": {"name": "FusedAdamW", "lr": {"name": "Constant", "learning_rate": 1e-3}},
        }
    )
    ecfg = process_configs(ecfg, num_devices=8)
    mesh = init_dist_env(ecfg)
    module = build_module(ecfg)
    with mesh:
        engine = Engine(ecfg, module, mesh)
        got = np.asarray(jax.device_get(engine.state.params["embeddings"]["word"]))
    np.testing.assert_allclose(got, params["embeddings"]["word"], atol=1e-6)


def test_unsupported_variants_rejected(hf_model):
    from transformers import GPT2Config

    bad = GPT2Config(vocab_size=96, n_embd=32, n_layer=2, n_head=4,
                     activation_function="gelu")
    with pytest.raises(ValueError, match="activation_function"):
        hf_gpt2_config(bad)
    bad = GPT2Config(vocab_size=96, n_embd=32, n_layer=2, n_head=4,
                     layer_norm_epsilon=1e-6)
    with pytest.raises(ValueError, match="layer_norm_epsilon"):
        hf_gpt2_config(bad)


# ---------------------------------------------------------------------------
# T5 (same external-oracle pattern; gated + untied variants)
# ---------------------------------------------------------------------------


def _t5_parity(feed_forward_proj, tie):
    from transformers import T5Config as HFT5Config, T5ForConditionalGeneration

    from paddlefleetx_tpu.models.t5 import model as t5
    from paddlefleetx_tpu.models.t5.convert import (
        convert_hf_t5_state_dict,
        hf_t5_config,
    )

    hf_cfg = HFT5Config(
        vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_decoder_layers=2, num_heads=4, relative_attention_num_buckets=8,
        dropout_rate=0.0, feed_forward_proj=feed_forward_proj,
        tie_word_embeddings=tie, decoder_start_token_id=0,
    )
    torch.manual_seed(0)
    m = T5ForConditionalGeneration(hf_cfg).eval()
    cfg = hf_t5_config(hf_cfg, dropout_rate=0.0, dtype="float32")
    params = convert_hf_t5_state_dict(m.state_dict(), cfg)
    rng = np.random.default_rng(0)
    inp = rng.integers(3, 96, (2, 10))
    dec = rng.integers(3, 96, (2, 6))
    with torch.no_grad():
        ref = m(input_ids=torch.tensor(inp), decoder_input_ids=torch.tensor(dec)).logits.numpy()
    ours = np.asarray(t5.forward(params, inp, dec, cfg, train=False))
    np.testing.assert_allclose(ours, ref, atol=3e-5, rtol=1e-5)


def test_t5_logits_match_transformers_gated_tied():
    _t5_parity("gated-gelu", True)


def test_t5_logits_match_transformers_relu_untied():
    _t5_parity("relu", False)


# ---------------------------------------------------------------------------
# DebertaV2 (disentangled attention; parity at valid positions — HF applies
# a q-side pad mask so pad-row outputs differ, and nothing reads them)
# ---------------------------------------------------------------------------


def test_debertav2_hidden_states_match_transformers():
    from transformers import DebertaV2Config as HFCfg, DebertaV2Model

    from paddlefleetx_tpu.models.debertav2 import model as dv2
    from paddlefleetx_tpu.models.debertav2.convert import (
        convert_hf_debertav2_state_dict,
        hf_debertav2_config,
    )

    hf = HFCfg(
        vocab_size=96, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        intermediate_size=64, max_position_embeddings=64, relative_attention=True,
        position_buckets=8, norm_rel_ebd="layer_norm", pos_att_type=["p2c", "c2p"],
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        share_att_key=True, position_biased_input=False,
    )
    torch.manual_seed(0)
    m = DebertaV2Model(hf).eval()
    cfg = hf_debertav2_config(
        hf, hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0, dtype="float32"
    )
    params = convert_hf_debertav2_state_dict(m.state_dict(), cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(3, 96, (2, 12))
    mask = np.ones((2, 12), np.int64)
    mask[1, 9:] = 0
    with torch.no_grad():
        ref = m(
            input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask)
        ).last_hidden_state.numpy()
    ours = np.asarray(dv2.encode(params, ids, cfg, attention_mask=mask, train=False))
    np.testing.assert_allclose(ours[mask.astype(bool)], ref[mask.astype(bool)],
                               atol=3e-5, rtol=1e-5)


def test_debertav2_unsupported_variants_rejected():
    from transformers import DebertaV2Config as HFCfg

    from paddlefleetx_tpu.models.debertav2.convert import hf_debertav2_config

    with pytest.raises(ValueError, match="norm_rel_ebd"):
        hf_debertav2_config(HFCfg(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                                  num_attention_heads=4, intermediate_size=64,
                                  norm_rel_ebd="none", position_biased_input=False,
                                  share_att_key=True))
    with pytest.raises(ValueError, match="share_att_key"):
        hf_debertav2_config(HFCfg(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                                  num_attention_heads=4, intermediate_size=64,
                                  norm_rel_ebd="layer_norm", position_biased_input=False,
                                  share_att_key=False))


def test_debertav2_conv_variant_matches_transformers():
    """xlarge-style ConvLayer (conv_kernel_size=3): valid-position parity,
    including the pad-row zeroing that keeps conv from leaking pad garbage."""
    from transformers import DebertaV2Config as HFCfg, DebertaV2Model

    from paddlefleetx_tpu.models.debertav2 import model as dv2
    from paddlefleetx_tpu.models.debertav2.convert import (
        convert_hf_debertav2_state_dict,
        hf_debertav2_config,
    )

    hf = HFCfg(
        vocab_size=96, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        intermediate_size=64, max_position_embeddings=64, relative_attention=True,
        position_buckets=8, norm_rel_ebd="layer_norm", pos_att_type=["p2c", "c2p"],
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        share_att_key=True, position_biased_input=False,
        conv_kernel_size=3, conv_act="gelu",
    )
    torch.manual_seed(0)
    m = DebertaV2Model(hf).eval()
    cfg = hf_debertav2_config(
        hf, hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0, dtype="float32"
    )
    params = convert_hf_debertav2_state_dict(m.state_dict(), cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(3, 96, (2, 12))
    mask = np.ones((2, 12), np.int64)
    mask[1, 9:] = 0
    with torch.no_grad():
        ref = m(
            input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask)
        ).last_hidden_state.numpy()
    ours = np.asarray(dv2.encode(params, ids, cfg, attention_mask=mask, train=False))
    np.testing.assert_allclose(ours[mask.astype(bool)], ref[mask.astype(bool)],
                               atol=5e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# ViT (vision family oracle)
# ---------------------------------------------------------------------------


def test_vit_logits_match_transformers():
    from transformers import ViTConfig as HFVitCfg, ViTForImageClassification

    from paddlefleetx_tpu.models.vit import model as vit
    from paddlefleetx_tpu.models.vit.convert import (
        convert_hf_vit_state_dict,
        hf_vit_config,
    )

    hf = HFVitCfg(
        image_size=32, patch_size=8, num_channels=3, hidden_size=24,
        num_hidden_layers=2, num_attention_heads=2, intermediate_size=48,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0, num_labels=10,
    )
    torch.manual_seed(0)
    m = ViTForImageClassification(hf).eval()
    cfg = hf_vit_config(
        hf, num_classes=10, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, dtype="float32",
    )
    params = convert_hf_vit_state_dict(m.state_dict(), cfg)
    rng = np.random.default_rng(0)
    img = rng.normal(0, 1, (2, 32, 32, 3)).astype(np.float32)
    with torch.no_grad():
        ref = m(pixel_values=torch.tensor(img).permute(0, 3, 1, 2)).logits.numpy()
    ours = np.asarray(vit.forward(params, img, cfg, train=False))
    np.testing.assert_allclose(ours, ref, atol=2e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# ERNIE (post-LN encoder family oracle, incl. MLM/NSP pretrain heads)
# ---------------------------------------------------------------------------


def _hf_ernie_cfg():
    from transformers import ErnieConfig as HFCfg

    return HFCfg(
        vocab_size=96, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        intermediate_size=64, max_position_embeddings=32, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0, pad_token_id=0,
    )


def test_ernie_hidden_and_pooled_match_transformers():
    from transformers import ErnieModel

    from paddlefleetx_tpu.models.ernie import model as ernie
    from paddlefleetx_tpu.models.ernie.convert import (
        convert_hf_ernie_state_dict,
        hf_ernie_config,
    )

    hf = _hf_ernie_cfg()
    torch.manual_seed(0)
    m = ErnieModel(hf).eval()
    cfg = hf_ernie_config(
        hf, hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0, dtype="float32"
    )
    params = convert_hf_ernie_state_dict(m.state_dict(), cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(3, 96, (2, 12))
    tt = np.zeros((2, 12), np.int64)
    mask = np.ones((2, 12), np.int64)
    mask[1, 9:] = 0
    with torch.no_grad():
        out = m(input_ids=torch.tensor(ids), token_type_ids=torch.tensor(tt),
                attention_mask=torch.tensor(mask))
    seq, pooled = ernie.encode(
        params, ids, cfg, token_type_ids=tt, attention_mask=mask, train=False
    )
    v = mask.astype(bool)
    np.testing.assert_allclose(np.asarray(seq)[v], out.last_hidden_state.numpy()[v],
                               atol=2e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pooled), out.pooler_output.numpy(),
                               atol=2e-5, rtol=1e-5)


def test_ernie_pretrain_heads_match_transformers():
    from transformers import ErnieForPreTraining

    from paddlefleetx_tpu.models.ernie import model as ernie
    from paddlefleetx_tpu.models.ernie.convert import (
        convert_hf_ernie_state_dict,
        hf_ernie_config,
    )

    hf = _hf_ernie_cfg()
    torch.manual_seed(1)
    m = ErnieForPreTraining(hf).eval()
    cfg = hf_ernie_config(
        hf, hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0, dtype="float32"
    )
    params = convert_hf_ernie_state_dict(m.state_dict(), cfg)
    rng = np.random.default_rng(2)
    ids = rng.integers(3, 96, (2, 12))
    tt = np.zeros((2, 12), np.int64)
    mask = np.ones((2, 12), np.int64)
    with torch.no_grad():
        out = m(input_ids=torch.tensor(ids), token_type_ids=torch.tensor(tt),
                attention_mask=torch.tensor(mask))
    seq, pooled = ernie.encode(
        params, ids, cfg, token_type_ids=tt, attention_mask=mask, train=False
    )
    mlm_logits, nsp_logits = ernie.pretrain_logits(params, seq, pooled, cfg)
    np.testing.assert_allclose(np.asarray(mlm_logits), out.prediction_logits.numpy(),
                               atol=3e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(nsp_logits), out.seq_relationship_logits.numpy(),
                               atol=3e-5, rtol=1e-5)


def test_converted_gpt2_serves_identical_greedy_tokens(hf_model, tmp_path, devices8):
    """End-to-end deploy chain: HF checkpoint -> converter -> params-only
    artifact -> TP-sharded GenerationServer produces token-identical greedy
    continuations to transformers' own generate()."""
    import jax as _jax
    import orbax.checkpoint as ocp

    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = hf_gpt2_config(
        hf_model.config,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0, dtype="float32",
    )
    params = convert_hf_gpt2_state_dict(hf_model.state_dict(), cfg)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(str(tmp_path / "conv" / "params"), params, force=True)
    ckptr.wait_until_finished()

    scfg = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": 8, "seed": 3},
            "Engine": {"mix_precision": {"enable": False},
                       "save_load": {"save_steps": 0, "ckpt_dir": str(tmp_path / "conv")}},
            "Model": {"module": "GPTModule", "vocab_size": 96, "hidden_size": 32,
                      "num_layers": 2, "num_attention_heads": 4,
                      "max_position_embeddings": 32, "dtype": "float32"},
            "Distributed": {"mp_degree": 2},
            "Optimizer": {"name": "FusedAdamW", "lr": {"name": "Constant", "learning_rate": 1e-3}},
            "Generation": {"max_dec_len": 6, "decode_strategy": "greedy_search",
                           "pad_to_multiple": 8, "eos_token_id": 95, "pad_token_id": 0},
        }
    )
    scfg = process_configs(scfg, num_devices=8)
    mesh = init_dist_env(scfg)
    module = build_module(scfg)
    from paddlefleetx_tpu.utils.checkpoint import load_pretrained_params

    server = GenerationServer(
        scfg, mesh, module, params=load_pretrained_params(scfg)
    )
    prompt = [5, 6, 7]
    ours = server.generate_ids([prompt])[0]

    hf_out = hf_model.generate(
        torch.tensor([prompt]), max_new_tokens=6, do_sample=False, pad_token_id=0
    )[0, len(prompt):].tolist()
    # compare up to our (possibly eos-truncated) length
    assert ours == hf_out[: len(ours)] and len(ours) > 0, (ours, hf_out)
