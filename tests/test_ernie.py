"""ERNIE family tests: model numerics, TP parity, dataset invariants."""

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from paddlefleetx_tpu.data.ernie_dataset import (
    ErnieDataset,
    write_synthetic_sentence_corpus,
)
from paddlefleetx_tpu.models.ernie import model as ernie
from paddlefleetx_tpu.models.ernie.config import ErnieConfig
from paddlefleetx_tpu.models.gpt.model import ShardingCtx
from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
from paddlefleetx_tpu.parallel.sharding import make_rules, tree_logical_to_sharding

TINY = ErnieConfig(
    vocab_size=128,
    hidden_size=32,
    num_layers=2,
    num_attention_heads=4,
    ffn_hidden_size=64,
    max_position_embeddings=64,
    dtype="float32",
)


def _batch(cfg, b=2, s=16, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(4, cfg.vocab_size, (b, s))
    labels = np.full((b, s), -1, np.int64)
    labels[:, 3:6] = ids[:, 3:6]  # pretend these were masked
    return {
        "input_ids": jnp.asarray(ids),
        "token_type_ids": jnp.asarray((np.arange(s)[None] > s // 2).astype(np.int64) * np.ones((b, 1), np.int64)),
        "attention_mask": jnp.ones((b, s), jnp.float32),
        "masked_lm_labels": jnp.asarray(labels),
        "next_sentence_label": jnp.asarray(rng.integers(0, 2, (b,))),
    }


def test_encode_shapes_and_loss():
    params = ernie.init(TINY, jax.random.key(0))
    batch = _batch(TINY)
    seq, pooled = ernie.encode(params, batch["input_ids"], TINY)
    assert seq.shape == (2, 16, 32)
    assert pooled.shape == (2, 32)
    mlm, nsp = ernie.pretrain_logits(params, seq, pooled, TINY)
    assert mlm.shape == (2, 16, 128) and nsp.shape == (2, 2)
    loss = ernie.pretrain_loss(params, batch, TINY)
    assert np.isfinite(float(loss))
    # random init, uniformish logits: MLM CE ~ ln(V), NSP ~ ln 2
    assert abs(float(loss) - (np.log(128) + np.log(2))) < 1.0


def test_padding_mask_invariance():
    """Padding tokens must not change unpadded positions' outputs."""
    params = ernie.init(TINY, jax.random.key(0))
    rng = np.random.default_rng(1)
    ids = rng.integers(4, TINY.vocab_size, (1, 12))
    short, _ = ernie.encode(params, jnp.asarray(ids), TINY)
    padded = np.concatenate([ids, np.zeros((1, 4), np.int64)], axis=1)
    mask = np.concatenate([np.ones((1, 12)), np.zeros((1, 4))], axis=1).astype(np.float32)
    long, _ = ernie.encode(
        params, jnp.asarray(padded), TINY, attention_mask=jnp.asarray(mask)
    )
    np.testing.assert_allclose(np.asarray(short[0]), np.asarray(long[0, :12]), atol=1e-5)


def test_cls_loss_decreases_under_grad():
    cfg = TINY
    params = ernie.init(cfg, jax.random.key(1))
    batch = {
        "input_ids": jnp.asarray(np.random.default_rng(0).integers(4, 128, (4, 16))),
        "labels": jnp.asarray([0, 1, 0, 1]),
    }

    def loss(p):
        return ernie.cls_loss(ernie.cls_forward(p, batch, cfg), batch["labels"])

    l0, g = jax.value_and_grad(loss)(params)
    params2 = jax.tree.map(lambda p, gg: p - 0.1 * gg, params, g)
    l1 = loss(params2)
    assert float(l1) < float(l0)


def test_tp_parity(devices8):
    """mp=4 sharded pretrain loss matches single-device loss."""
    cfg = TINY
    params = ernie.init(cfg, jax.random.key(0))
    batch = _batch(cfg)
    ref = float(ernie.pretrain_loss(params, batch, cfg))

    mesh = build_mesh(MeshConfig(dp_degree=2, mp_degree=4), jax.devices()[:8])
    rules = make_rules(mesh=mesh)
    shardings = tree_logical_to_sharding(ernie.ernie_logical_axes(cfg), mesh, rules)
    sharded = jax.device_put(params, shardings)
    ctx = ShardingCtx(mesh, rules)
    batch_sharding = NamedSharding(mesh, P(("data", "fsdp")))
    dev_batch = jax.tree.map(lambda x: jax.device_put(x, batch_sharding), batch)

    with mesh:
        got = float(
            jax.jit(lambda p, b: ernie.pretrain_loss(p, b, cfg, ctx=ctx))(
                sharded, dev_batch
            )
        )
    assert abs(got - ref) < 1e-4


def test_ernie_dataset(tmp_path):
    prefix = write_synthetic_sentence_corpus(str(tmp_path / "corpus"), vocab_size=2000)
    ds = ErnieDataset(input_dir=prefix, max_seq_len=128, vocab_size=2000, seed=7)
    assert len(ds) > 0
    item = ds[0]
    L = 128
    assert item["input_ids"].shape == (L,)
    assert item["token_type_ids"].shape == (L,)
    assert item["masked_lm_labels"].shape == (L,)
    assert item["next_sentence_label"] in (0, 1)
    # structure: starts with CLS, contains exactly two SEPs in the live region
    live = int(item["attention_mask"].sum())
    assert item["input_ids"][0] == ds.cls_id
    assert (item["input_ids"][:live] == ds.sep_id).sum() == 2
    # masking: some positions have labels; every labeled position was a real
    # token (label >= 4); at least one [MASK] token present
    labeled = item["masked_lm_labels"] >= 0
    assert 0 < labeled.sum() <= ds.max_predictions
    assert (item["masked_lm_labels"][labeled] >= 4).all()
    # padding region fully dead
    assert (item["masked_lm_labels"][live:] == -1).all()
    assert (item["input_ids"][live:] == ds.pad_id).all()
    # deterministic per (index, visit): a fresh dataset replays the stream
    ds2 = ErnieDataset(input_dir=prefix, max_seq_len=128, vocab_size=2000, seed=7)
    np.testing.assert_array_equal(item["input_ids"], ds2[0]["input_ids"])
    # the second epoch visit re-masks (fresh augmentation draw)
    assert not np.array_equal(item["input_ids"], ds[0]["input_ids"])
    # different indices differ
    assert not np.array_equal(ds2[0]["input_ids"], ds2[1]["input_ids"])


def test_build_mapping_cpp_matches_structure(tmp_path):
    """C++ and numpy build_mapping agree on sample structure (not RNG)."""
    from paddlefleetx_tpu.data.indexed import build_mapping

    rng = np.random.default_rng(0)
    counts = rng.integers(2, 8, 16).astype(np.int32)
    docs = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    sizes = rng.integers(5, 40, int(counts.sum())).astype(np.int32)
    # short_seq_prob=0 removes RNG from the walk: outputs must be identical
    a = build_mapping(docs, sizes, 128, short_seq_prob=0.0, seed=3, use_cpp=True)
    b = build_mapping(docs, sizes, 128, short_seq_prob=0.0, seed=3, use_cpp=False)
    np.testing.assert_array_equal(a, b)
    assert len(a) > 0
    # sample sentence ranges are within bounds and non-empty
    assert (a[:, 0] < a[:, 1]).all()
    assert (a[:, 1] <= docs[-1]).all()


def test_ernie_module_registered():
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.utils.config import get_config

    cfg = get_config(
        os.path.join(os.path.dirname(__file__), "..", "configs/ernie/pretrain_ernie_base.yaml"),
        overrides=[
            "Global.global_batch_size=8",
            "Global.local_batch_size=1",
            "Global.micro_batch_size=1",
            "Model.num_layers=2",
            "Model.hidden_size=32",
            "Model.num_attention_heads=4",
            "Model.ffn_hidden_size=64",
            "Model.vocab_size=128",
            "Model.max_position_embeddings=64",
        ],
    )
    module = build_module(cfg)
    params = module.init_params(jax.random.key(0))
    loss = module.loss_fn(params, _batch(module.config), train=False)
    assert np.isfinite(float(loss))


def test_pipeline_pretrain_parity(devices8):
    """pp2 x mp2 1F1B pretrain loss matches the single-device value
    (reference ErnieForPretrainingPipe capability, hybrid_model.py:796)."""
    from paddlefleetx_tpu.parallel.pipeline import PipelineConfig

    cfg = TINY
    params = ernie.init(cfg, jax.random.key(0))
    batch = _batch(cfg, b=4)
    ref = float(ernie.pretrain_loss(params, batch, cfg))

    mesh = build_mesh(MeshConfig(dp_degree=2, mp_degree=2, pp_degree=2), jax.devices()[:8])
    rules = make_rules(mesh=mesh)
    shardings = tree_logical_to_sharding(ernie.ernie_logical_axes(cfg), mesh, rules)
    sharded = jax.device_put(params, shardings)
    # M=2 microbatches of 2 over dp2
    ctx = ShardingCtx(mesh, rules, pipeline=PipelineConfig(num_stages=2, num_microbatches=2))
    batch_sharding = NamedSharding(mesh, P(("data", "fsdp")))
    dev_batch = jax.tree.map(lambda x: jax.device_put(x, batch_sharding), batch)

    with mesh:
        got = float(
            jax.jit(lambda p, b: ernie.pretrain_loss(p, b, cfg, ctx=ctx, train=True))(
                sharded, dev_batch
            )
        )
    assert abs(got - ref) < 2e-4, (got, ref)

    # gradients flow end to end and stay finite
    with mesh:
        g = jax.jit(
            jax.grad(lambda p, b: ernie.pretrain_loss(p, b, cfg, ctx=ctx, train=True))
        )(sharded, dev_batch)
    for leaf in jax.tree.leaves(g):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_raw_text_to_pretrain_step_e2e(tmp_path):
    """Raw jsonl -> tools/preprocess_data.py --tokenizer ernie (sentence
    splitting + wordpiece) -> ErnieDataset -> finite pretrain loss: the
    reference's full ERNIE preprocessing chain
    (data_tools/ernie/preprocess/create_pretraining_data.py) end to end."""
    import json

    import tools.preprocess_data as pp
    from paddlefleetx_tpu.data.tokenizers.ernie_tokenizer import ErnieTokenizer

    docs = [
        "The quick brown fox jumps over the lazy dog. A second sentence here! "
        "And a third one follows? Finally the fourth sentence ends.",
        "Training data pipelines need tests. Sentence splitting must work. "
        "Wordpiece ids go into the stream. Mapping builds pairs.",
        "Short doc one sentence only.",
        "Alpha beta gamma delta. Epsilon zeta eta theta. Iota kappa lambda mu.",
    ] * 4
    tok = ErnieTokenizer.from_tiny_corpus(docs)
    vocab_file = str(tmp_path / "vocab.txt")
    tok.save(vocab_file)
    corpus = tmp_path / "raw.jsonl"
    with open(corpus, "w") as f:
        for d in docs:
            f.write(json.dumps({"text": d}) + "\n")
        f.write("\n")  # blank + textless lines are skipped
        f.write(json.dumps({"meta": "no text"}) + "\n")

    prefix = str(tmp_path / "ernie_corpus")
    pp.main([
        "--input", str(corpus), "--output_prefix", prefix,
        "--tokenizer", "ernie", "--vocab_file", vocab_file,
    ])

    idx = np.load(prefix + "_idx.npz")
    assert idx["doc_sent_counts"].sum() == len(idx["sent_lens"])
    assert idx["doc_sent_counts"].shape[0] == len(docs)  # empty lines dropped
    assert (idx["sent_lens"] > 0).all()
    # 4-sentence docs actually got split
    assert idx["doc_sent_counts"].max() >= 4

    ds = ErnieDataset(
        input_dir=prefix,
        max_seq_len=64,
        vocab_size=tok.vocab_size,
        cls_id=tok.cls_token_id,
        sep_id=tok.sep_token_id,
        mask_id=tok.mask_token_id,
        pad_id=tok.pad_token_id,
        seed=11,
    )
    assert len(ds) > 0
    item = ds[0]
    assert item["input_ids"][0] == tok.cls_token_id
    # round-trip: live unmasked ids decode back into vocab words
    live = int(item["attention_mask"].sum())
    assert (item["input_ids"][:live] < tok.vocab_size).all()

    # the preprocessed corpus trains: one pretrain loss on a real batch
    cfg = ErnieConfig(
        vocab_size=max(128, tok.vocab_size),
        hidden_size=32,
        num_layers=2,
        num_attention_heads=4,
        ffn_hidden_size=64,
        max_position_embeddings=64,
        dtype="float32",
    )
    params = ernie.init(cfg, jax.random.key(0))
    batch = {
        k: jnp.asarray(np.stack([ds[i][k] for i in range(min(4, len(ds)))]))
        for k in ("input_ids", "token_type_ids", "attention_mask",
                  "masked_lm_labels", "next_sentence_label")
    }
    loss = ernie.pretrain_loss(params, batch, cfg)
    assert np.isfinite(float(loss))
