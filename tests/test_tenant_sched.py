"""Scheduler-level multi-tenant behavior (`core/continuous_batching.py`
+ `core/request_queue.py` with a `core/tenancy.py` config): weighted-fair
admission parity, priority preemption with token-identical preempt-resume
(f32 exact), stream-offset rebasing across a preemption, and the
decision-log replay contract extended to the per-tenant counters.

In-process against the TINY CPU model — the multi-process flood and
storm drills through the real CLIs live in tests/test_tenant_drills.py.
"""

import pytest

TINY = {
    "Global": {"global_batch_size": 8, "seed": 3},
    "Engine": {"mix_precision": {"enable": False},
               "save_load": {"save_steps": 0}},
    "Model": {
        "module": "GPTModule",
        "vocab_size": 96,
        "hidden_size": 32,
        "num_layers": 2,
        "num_attention_heads": 4,
        "max_position_embeddings": 128,
        "dtype": "float32",
    },
    "Distributed": {},
    "Optimizer": {"name": "FusedAdamW",
                  "lr": {"name": "Constant", "learning_rate": 1e-3}},
    "Generation": {"max_dec_len": 8, "decode_strategy": "greedy_search",
                   "pad_to_multiple": 16, "eos_token_id": 95,
                   "pad_token_id": 0},
}

PROMPTS = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 10], [11, 12, 13, 14]]


@pytest.fixture(scope="module")
def server():
    import jax

    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict.from_nested(TINY)
    cfg = process_configs(cfg, num_devices=jax.device_count())
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    return GenerationServer(cfg, mesh, module)


@pytest.fixture(scope="module")
def sequential(server):
    """Reference outputs: each request served alone on the coalesce path."""
    return [server.generate_ids([p], max_dec_len=6)[0] for p in PROMPTS]


def _engine(server, **kw):
    from paddlefleetx_tpu.core.continuous_batching import PagedDecodeEngine

    kw.setdefault("max_batch", 4)
    return PagedDecodeEngine(server, **kw)


def _tenant_cfg(**weights):
    from paddlefleetx_tpu.core.tenancy import TenantConfig

    return TenantConfig.from_obj(
        {"tenants": {t: {"weight": w} for t, w in weights.items()}}
    )


def _ctr(name, **labels):
    from paddlefleetx_tpu.utils.telemetry import get_registry

    return get_registry().value(name, **labels) or 0


# ---------------------------------------------------------------------------
# RequestQueue: weighted-fair pick + tenant-pure coalescing
# ---------------------------------------------------------------------------


def test_request_queue_drr_weighted_order():
    """With a 3:1 weight config and both tenants backlogged, the batch
    pick interleaves ~3 gold per brz instead of draining gold first;
    FCFS order holds within each tenant."""
    from paddlefleetx_tpu.core.request_queue import RequestQueue

    order = []

    def recording_runner(prompts, max_new):
        order.extend(p[0] for p in prompts)
        return [list(p) for p in prompts]

    q = RequestQueue(recording_runner, max_depth=32, max_coalesce=1,
                     tenant_config=_tenant_cfg(gold=3, brz=1))
    futs = []
    for i in range(6):
        futs.append(q.submit([[10 + i]], 2, tenant="gold"))
    for i in range(2):
        futs.append(q.submit([[20 + i]], 2, tenant="brz"))
    q.start()  # everything queued first: picks are pure DRR
    for f in futs:
        f.result(timeout=10)
    # brz's first entry is served before gold's backlog drains (weighted
    # fair, not FCFS-by-arrival), and within each tenant order is FCFS
    assert order.index(20) < order.index(15)
    assert [x for x in order if x >= 20] == [20, 21]
    assert [x for x in order if x < 20] == [10, 11, 12, 13, 14, 15]
    q.shutdown(timeout=5)


def test_request_queue_coalesce_is_tenant_pure():
    """Coalescing merges same-key entries of the SAME tenant only — one
    tenant's flood cannot ride another tenant's batch."""
    from paddlefleetx_tpu.core.request_queue import RequestQueue

    batches = []

    def recording_runner(prompts, max_new):
        batches.append([p[0] for p in prompts])
        return [list(p) for p in prompts]

    q = RequestQueue(recording_runner, max_depth=16, max_coalesce=4)
    f1 = q.submit([[1]], 2, coalesce_key=("k",), tenant="a")
    f2 = q.submit([[2]], 2, coalesce_key=("k",), tenant="b")
    f3 = q.submit([[3]], 2, coalesce_key=("k",), tenant="a")
    q.start()
    for f in (f1, f2, f3):
        f.result(timeout=10)
    assert sorted(sorted(b) for b in batches) == [[1, 3], [2]]
    q.shutdown(timeout=5)


def test_request_queue_debug_state_has_tenant_rows():
    from paddlefleetx_tpu.core.request_queue import RequestQueue

    q = RequestQueue(lambda p, m: [list(x) for x in p], max_depth=8)
    q.submit([[1]], 2, tenant="gold")
    q.submit([[2]], 2, tenant="gold")
    dbg = q.debug_state()
    assert dbg["tenants"] == {"gold": 2}
    assert all(w["tenant"] == "gold" for w in dbg["waiting"])
    q.start()
    q.shutdown(timeout=5)


# ---------------------------------------------------------------------------
# entry-level units: stream rebase + finished_tokens
# ---------------------------------------------------------------------------


def test_entry_stream_rebase_and_finished_tokens():
    from paddlefleetx_tpu.core.continuous_batching import _CBEntry

    pushes = []
    e = _CBEntry(prompts=[[1, 2]], max_new=8, deadline=1e9,
                 future=None, enqueued_at=0.0,
                 stream=lambda r, s, t: pushes.append((r, s, list(t))))
    e.emit_stream(0, 0, [5, 6])          # pre-preemption commits
    e.row_prefill[0] = [5, 6]            # preempted with 2 committed
    e.emit_stream(0, 0, [7])             # resumed decode restarts at 0...
    assert pushes == [(0, 0, [5, 6]), (0, 2, [7])]  # ...client sees 2
    assert e.finished_tokens(0, [7, 8]) == [5, 6, 7, 8]
    assert e.finished_tokens(1, [9]) == [9]  # untouched row: passthrough


# ---------------------------------------------------------------------------
# scheduler: weighted-fair admission parity
# ---------------------------------------------------------------------------


def test_scheduler_drr_two_tenants_parity_and_counters(server, sequential):
    """Two tenants with 4:1 weights through a capacity-constrained
    engine: every output stays token-identical to the sequential
    reference (fairness reorders admission, never corrupts decode), and
    the per-tenant admitted counters land labeled."""
    from paddlefleetx_tpu.core.continuous_batching import ContinuousScheduler

    g0 = _ctr("pfx_tenant_admitted_total", tenant="gold")
    b0 = _ctr("pfx_tenant_admitted_total", tenant="brz")
    eng = _engine(server, max_batch=2, num_blocks=5)
    sched = ContinuousScheduler(eng, max_depth=16,
                                tenant_config=_tenant_cfg(gold=4, brz=1))
    sched.start()
    futs = []
    for i, p in enumerate(PROMPTS):
        tn = "gold" if i % 2 == 0 else "brz"
        futs.append(sched.submit([p], 6, deadline_s=120, tenant=tn))
    got = [f.result(timeout=300)[0] for f in futs]
    assert got == sequential
    dbg = sched.debug_state()
    assert dbg["tenants"]["gold"]["admitted_rows"] == 2
    assert dbg["tenants"]["brz"]["admitted_rows"] == 2
    assert _ctr("pfx_tenant_admitted_total", tenant="gold") == g0 + 2
    assert _ctr("pfx_tenant_admitted_total", tenant="brz") == b0 + 2
    assert sched.shutdown(timeout=30)


# ---------------------------------------------------------------------------
# preemption: storm fault, priority arrival, replay contract
# ---------------------------------------------------------------------------


def test_preempt_storm_resume_is_token_identical(server, sequential,
                                                 monkeypatch):
    """The resilience drill site: `preempt_storm:3` force-preempts the
    lowest-priority active row at iteration 3.  The victim re-enters its
    tenant queue as a re-prefill continuation and every output — victim
    included — stays token-identical to the undisturbed sequential run
    (f32 exact).  Stream offsets stay monotone across the preemption,
    and the decision-log replay folds the preemption counters exactly."""
    from paddlefleetx_tpu.core.continuous_batching import ContinuousScheduler
    from paddlefleetx_tpu.utils import resilience
    from paddlefleetx_tpu.utils.tracing import replay_decision_log

    resilience.reset_fault_state()
    monkeypatch.setenv("PFX_FAULT", "preempt_storm:3")
    p0 = _ctr("pfx_tenant_preemptions_total", tenant="anon")
    a0 = _ctr("pfx_tenant_admitted_total", tenant="anon")
    streams = {i: [] for i in range(len(PROMPTS))}
    eng = _engine(server)
    sched = ContinuousScheduler(eng, max_depth=16, preempt_min_tokens=2)
    sched.start()
    futs = [
        sched.submit(
            [p], 6, deadline_s=120,
            stream=(lambda i: lambda r, s, t: streams[i].append((s, list(t))))(i),
        )
        for i, p in enumerate(PROMPTS)
    ]
    got = [f.result(timeout=300)[0] for f in futs]
    monkeypatch.delenv("PFX_FAULT")
    resilience.reset_fault_state()
    assert got == sequential
    assert sched.stats["preemptions"] == 1
    assert _ctr("pfx_tenant_preemptions_total", tenant="anon") == p0 + 1
    # a resume is an admission: 4 rows + 1 re-prefill continuation
    assert _ctr("pfx_tenant_admitted_total", tenant="anon") == a0 + 5
    # stream offsets: each row's pushes reassemble contiguously into
    # EXACTLY its final output — no duplicate, no hole, across the
    # preempt-resume rebase
    for i, pushes in enumerate(streams.items()):
        acc = []
        for start, toks in streams[i]:
            assert start == len(acc), f"row {i}: hole/overlap at {start}"
            acc.extend(toks)
        assert acc == got[i]
    # replay contract: an untruncated log reproduces the tenant trio
    replay = replay_decision_log(sched.decision_log)
    assert replay["preempted"] == 1
    assert replay["preempted_tenants"] == {"anon": 1}
    assert replay["tenants"]["anon"] == 5
    assert sched.shutdown(timeout=30)


def test_priority_arrival_preempts_lowest_past_threshold(server):
    """A high-priority arrival that cannot be admitted for lack of
    slots preempts the lowest-priority active row once it is past the
    protected minimum progress — and every row, victim included, still
    finishes token-identically (never a dead 503)."""
    from paddlefleetx_tpu.core.continuous_batching import (
        ContinuousScheduler, PagedDecodeEngine,
    )

    import threading

    seq = [server.generate_ids([p], max_dec_len=20)[0] for p in PROMPTS[:3]]
    # the batch may be padded up to the data-parallel world, so the
    # scarce resource here is BLOCKS: 5 usable, 2 per 20-token row —
    # two bulk rows leave 1 free, the vip's 2-block ask cannot seat
    eng = PagedDecodeEngine(server, max_batch=2, num_blocks=6)
    sched = ContinuousScheduler(eng, max_depth=8, preempt_min_tokens=2)
    sched.start()
    # event-driven (not sleep-based): submit the vip only once BOTH bulk
    # rows are provably mid-decode past the protected threshold, so the
    # arrival always finds a full batch with eligible victims
    ready = [threading.Event(), threading.Event()]

    def _progress(ev):
        return lambda r, s, toks: (s + len(toks) >= 2) and ev.set()

    f0 = sched.submit([PROMPTS[0]], 20, deadline_s=120,
                      tenant="bulk", priority=-1, stream=_progress(ready[0]))
    f1 = sched.submit([PROMPTS[1]], 20, deadline_s=120,
                      tenant="bulk", priority=-1, stream=_progress(ready[1]))
    assert ready[0].wait(60) and ready[1].wait(60)
    f2 = sched.submit([PROMPTS[2]], 20, deadline_s=120,
                      tenant="vip", priority=10)
    got = [f.result(timeout=300)[0] for f in (f0, f1, f2)]
    assert got == seq
    assert sched.stats["preemptions"] >= 1
    dbg = sched.debug_state()
    assert dbg["tenants"]["bulk"]["preempted_rows"] >= 1
    assert "preempted_rows" not in dbg["tenants"]["vip"]
    assert sched.shutdown(timeout=30)


def test_equal_priority_never_preempts(server):
    """Preemption needs a STRICTLY lower-priority victim: an equal-
    priority backlog waits its turn (FCFS within the class) instead of
    thrashing the running rows — same block-constrained arena as the
    preempting test above, but nobody outranks anybody."""
    from paddlefleetx_tpu.core.continuous_batching import (
        ContinuousScheduler, PagedDecodeEngine,
    )

    seq = [server.generate_ids([p], max_dec_len=20)[0] for p in PROMPTS]
    eng = PagedDecodeEngine(server, max_batch=2, num_blocks=6)
    sched = ContinuousScheduler(eng, max_depth=8, preempt_min_tokens=2)
    sched.start()
    futs = [sched.submit([p], 20, deadline_s=120, priority=5)
            for p in PROMPTS]
    got = [f.result(timeout=300)[0] for f in futs]
    assert got == seq
    assert sched.stats["preemptions"] == 0
    assert sched.shutdown(timeout=30)
