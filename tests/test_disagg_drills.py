"""Fault-tolerant disaggregated serving fabric drills through the real
CLIs (`make test-disagg`): direct prefill->decode transfer, handoff
failover, and role-aware pool supervision (docs/serving.md
"Disaggregated operations").

  direct      the placement-ticket topology: handoff payload bytes flow
              prefill -> decode DIRECTLY (router byte counters stay
              flat while pfx_handoff_bytes_total on the replicas
              accounts the transfer), output token-identical to the
              proxy transport, prefix reuse live on the prefill replica.
  failover    PFX_FAULT=handoff_drop (direct send dropped -> proxy
              fallback) and PFX_FAULT=adopt_crash (decode replica dies
              at adoption -> bounded re-prefill through the surviving
              pair): every request exactly one honest outcome, greedy
              output token-identical across every leg.
  supervision SIGKILL a prefill replica AND a decode replica holding
              adopted rows under flood: zero hangs, honest 200/503
              accounting, the role-aware pool supervisor respawns both
              corpses, per-pool decision logs replay into the
              pool-labeled pfx_controller_* counters exactly.

Follows tests/test_router_drills.py conventions: `fault`-marked,
subprocess-driven, tiny synthetic GPT, persistent XLA compile cache
shared through the environment (tests/conftest.py)."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest
import yaml

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CACHE_DIR = os.environ.get(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)

TINY = {
    "Global": {"global_batch_size": 8, "seed": 11},
    "Engine": {"mix_precision": {"enable": False},
               "save_load": {"save_steps": 0}},
    "Model": {
        "module": "GPTModule",
        "vocab_size": 96,
        "hidden_size": 32,
        "num_layers": 2,
        "num_attention_heads": 4,
        "max_position_embeddings": 64,
        "dtype": "float32",
    },
    "Optimizer": {"name": "FusedAdamW",
                  "lr": {"name": "Constant", "learning_rate": 1e-3}},
    "Generation": {"max_dec_len": 8, "decode_strategy": "greedy_search",
                   "pad_to_multiple": 8, "eos_token_id": 95,
                   "pad_token_id": 0},
}

# a fleet-shared "system prompt" two requests share: 34 tokens = 2 full
# KV blocks (PFX_KV_BLOCK=16) + a 2-token overlap in the tail block, so
# the second request exercises shared-block mapping AND the COW copy on
# the prefill replica
SYS = list(range(1, 35))


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _env(extra=None):
    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PFX_FAULT", None)
    env.pop("PFX_ADMIN_TOKEN", None)
    env.update(extra or {})
    return env


def _post(port, body, timeout=90, path="/generate"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return json.load(r)


def _metrics(port, timeout=10):
    from test_telemetry import parse_prometheus

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=timeout
    ) as r:
        metrics, _ = parse_prometheus(r.read().decode())
    return metrics


def _lab(m, name, **labels):
    """One labeled series out of a parsed /metrics dump (0.0 absent)."""
    want = frozenset((k, str(v)) for k, v in labels.items())
    return m.get(name, {}).get(want, 0.0)


def _spawn_replica(cfg_path, port, *extra, env_extra=None):
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-c", str(cfg_path), "--port", str(port),
         "--queue-depth", "32", "--deadline", "60",
         "--warmup-buckets", "4", "--warmup-batches", "1", *extra],
        env=_env(env_extra), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )


def _spawn_router(port, *args):
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "router.py"),
         "--port", str(port), "--poll-interval", "0.2",
         "--eject-after", "3", *args],
        env=_env(), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )


def _wait_healthy(procs_ports, timeout=300):
    end = time.time() + timeout
    pending = dict(procs_ports)
    while pending and time.time() < end:
        for port, proc in list(pending.items()):
            if proc.poll() is not None:
                raise AssertionError(
                    f"replica on {port} died at boot: "
                    f"{proc.stdout.read()[-3000:]}"
                )
            try:
                if _get(port, "/healthz", timeout=5).get("ok"):
                    del pending[port]
            except Exception:
                pass
        time.sleep(0.3)
    assert not pending, f"never healthy: {sorted(pending)}"


def _wait_eligible(router_port, n, timeout=300, proc=None):
    end = time.time() + timeout
    h = {}
    while time.time() < end:
        if proc is not None and proc.poll() is not None:
            raise AssertionError(
                f"router died: {proc.stdout.read()[-3000:]}"
            )
        try:
            h = _get(router_port, "/healthz")
        except Exception:
            h = {}
        if h.get("eligible", 0) >= n:
            return h
        time.sleep(0.2)
    raise AssertionError(f"router never saw {n} eligible replicas: {h}")


def _finish(proc, timeout=30):
    if proc is None:
        return ""
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
    return proc.stdout.read() if proc.stdout else ""


def _serve_cmd(cfg_path, *extra):
    return " ".join([
        sys.executable, os.path.join(REPO, "tools", "serve.py"),
        "-c", str(cfg_path), "--port", "{port}",
        "--replica-id", "{replica_id}",
        "--warmup-buckets", "4", "--warmup-batches", "1",
        "--deadline", "60", *extra,
    ])


# ---------------------------------------------------------------------------
# direct transfer: bytes bypass the router; transport parity; prefix
# reuse live on the prefill replica
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~15s warm; tier-1 budget funding for the PR 15
# fleet-observability drill.  Replacement coverage: the byte-bypass
# (router pfx_router_handoff_bytes_total flat + replica-side direct
# bytes accounted), export/adopt counter accounting, the 3-process
# direct-topology boot, and repeat-request token-identical determinism
# all stay tier-1-drilled by tests/test_fleet_obs_drills.py (same
# replicas, same transport, plus the stitched-trace + federation
# agreement asserts); the direct-vs-proxy transport PARITY and prefill
# prefix reuse remain covered here in make test-disagg / test-all.
def test_direct_transfer_bypasses_router_and_matches_proxy(tmp_path):
    """THE direct-transfer acceptance drill: under ``--handoff direct``
    the payload provably does not transit the router (its byte counter
    stays flat while the replicas' pfx_handoff_bytes_total accounts the
    transfer), greedy output is token-identical to the proxy transport
    on the SAME replicas, and ``--prefix-cache-blocks`` on the prefill
    replica computes a shared system prefix once, not once per
    request."""
    cfg_path = tmp_path / "tiny_direct.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    pre_p, dec_p = _free_port(), _free_port()
    pre = _spawn_replica(cfg_path, pre_p, "--role", "prefill",
                         "--replica-id", "pre0",
                         "--prefix-cache-blocks", "16")
    dec = _spawn_replica(cfg_path, dec_p, "--role", "decode",
                         "--cb-batch", "4", "--replica-id", "dec0")
    ra_port, rb_port = _free_port(), _free_port()
    router_a = router_b = None
    try:
        _wait_healthy([(pre_p, pre), (dec_p, dec)])
        # /healthz satellite: the decode replica reports its admissible
        # blocks (the decode-pool scale + routing signal)
        assert _get(dec_p, "/healthz")["available_blocks"] > 0
        assert "available_blocks" not in _get(pre_p, "/healthz")

        router_a = _spawn_router(
            ra_port,
            "--prefill", f"http://127.0.0.1:{pre_p}",
            "--decode", f"http://127.0.0.1:{dec_p}",
            "--handoff", "direct",
        )
        _wait_eligible(ra_port, 2, proc=router_a)

        body1 = {"prompt_ids": SYS + [40, 41, 42], "max_tokens": 6,
                 "deadline_s": 60}
        body2 = {"prompt_ids": SYS + [50, 51], "max_tokens": 6,
                 "deadline_s": 60}
        c1, direct1 = _post(ra_port, body1)
        c2, direct2 = _post(ra_port, body2)
        c3, repeat1 = _post(ra_port, body1)
        assert (c1, c2, c3) == (200, 200, 200), (direct1, direct2, repeat1)
        assert repeat1["completion_ids"] == direct1["completion_ids"]

        # THE byte-bypass assert: the router never carried the payload
        m = _metrics(ra_port)
        assert m["pfx_router_handoff_bytes_total"][frozenset()] == 0.0
        assert m["pfx_router_handoff_seconds_count"][frozenset()] == 3.0
        pre_m = _metrics(pre_p)
        assert _lab(pre_m, "pfx_handoff_direct_total", outcome="ok") == 3.0
        assert _lab(pre_m, "pfx_handoff_bytes_total",
                    transport="direct") > 0
        dec_m = _metrics(dec_p)
        assert _lab(dec_m, "pfx_handoff_bytes_total",
                    transport="direct") > 0
        assert _lab(dec_m, "pfx_handoff_bytes_total",
                    transport="proxy") == 0.0
        assert dec_m["pfx_handoff_adopts_total"][frozenset()] == 3.0
        # prefix reuse on the prefill pool: request 1 published, 2 and
        # 3 hit the shared system prefix (34 tokens each)
        assert pre_m["pfx_prefix_misses_total"][frozenset()] == 1.0
        assert pre_m["pfx_prefix_hits_total"][frozenset()] == 2.0
        assert pre_m["pfx_prefix_hit_tokens_total"][frozenset()] >= 68.0
        assert pre_m["pfx_handoff_exports_total"][frozenset()] == 3.0

        # swap the transport on the SAME replicas: proxy parity
        router_a.send_signal(signal.SIGTERM)
        assert router_a.wait(timeout=60) == 0
        router_b = _spawn_router(
            rb_port,
            "--prefill", f"http://127.0.0.1:{pre_p}",
            "--decode", f"http://127.0.0.1:{dec_p}",
            "--handoff", "proxy",
        )
        _wait_eligible(rb_port, 2, proc=router_b)
        c4, proxied = _post(rb_port, body1)
        assert c4 == 200
        # token-identical across transports (f32 greedy)
        assert proxied["completion_ids"] == direct1["completion_ids"]
        mb = _metrics(rb_port)
        assert mb["pfx_router_handoff_bytes_total"][frozenset()] > 0
        assert _lab(_metrics(dec_p), "pfx_handoff_bytes_total",
                    transport="proxy") > 0

        # arena accounting closes on the decode replica
        assert _metrics(dec_p)["pfx_kv_blocks_used"][frozenset()] == 0.0
        for proc in (router_b, pre, dec):
            proc.send_signal(signal.SIGTERM)
        for proc in (router_b, pre, dec):
            assert proc.wait(timeout=60) == 0
    finally:
        logs = [_finish(p) for p in (pre, dec)]
        logs += [_finish(router_a), _finish(router_b)]
    for log in logs:
        assert "Traceback" not in log, log[-3000:]


# ---------------------------------------------------------------------------
# failure legs: handoff_drop -> proxy fallback; adopt_crash -> bounded
# re-prefill failover through the surviving pair
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~18s; tier-1 budget funding for the shard_map-port
# tests.  Replacement coverage: the failover ladder (stateless prefill
# retry, dirty-ticket avoidance, bounded re-prefill, never-replay-after-
# bytes) stays tier-1 via the test_router unit suite, and the direct
# transport's byte-bypass + parity stays tier-1-drilled by
# test_direct_transfer_bypasses_router_and_matches_proxy; still in
# make test-disagg / test-all.
def test_handoff_drop_and_adopt_crash_failover_token_identical(tmp_path):
    """Every failure leg of the direct topology, deterministically:

    - PFX_FAULT=handoff_drop:1:2 on the prefill replica drops BOTH
      attempts of the first direct send -> the payload degrades to the
      router proxy leg (router byte counter moves, outcome=fallback);
    - PFX_FAULT=adopt_crash:2 on decode replica d1 hard-exits it at its
      second adoption while the transport waits -> the router's bounded
      re-prefill failover answers through the surviving pair;
    - every request gets exactly one honest 200, token-identical
      throughout; the corpse is ejected and the survivor serves on."""
    cfg_path = tmp_path / "tiny_failover.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    pre_p, d1_p, d2_p = (_free_port() for _ in range(3))
    pre = _spawn_replica(cfg_path, pre_p, "--role", "prefill",
                         "--replica-id", "pre0",
                         env_extra={"PFX_FAULT": "handoff_drop:1:2"})
    d1 = _spawn_replica(cfg_path, d1_p, "--role", "decode",
                        "--cb-batch", "4", "--replica-id", "d1",
                        env_extra={"PFX_FAULT": "adopt_crash:2"})
    d2 = _spawn_replica(cfg_path, d2_p, "--role", "decode",
                        "--cb-batch", "4", "--replica-id", "d2")
    rport = _free_port()
    router = None
    try:
        _wait_healthy([(pre_p, pre), (d1_p, d1), (d2_p, d2)])
        router = _spawn_router(
            rport,
            "--prefill", f"http://127.0.0.1:{pre_p}",
            "--decode", f"http://127.0.0.1:{d1_p}",
            "--decode", f"http://127.0.0.1:{d2_p}",
            "--handoff", "direct",
        )
        _wait_eligible(rport, 3, proc=router)

        body = {"prompt_ids": SYS + [40, 41, 42], "max_tokens": 6,
                "deadline_s": 60}
        codes, outs = [], []
        for _ in range(12):
            c, resp = _post(rport, body)
            codes.append(c)
            outs.append(resp.get("completion_ids"))
            if d1.poll() is not None and len(codes) >= 3:
                break  # the fatal adoption landed (and failed over)
        # zero hangs, every request exactly one honest outcome — and
        # the failovers made every one of them a 200
        assert all(c == 200 for c in codes), codes
        assert all(o == outs[0] for o in outs), outs

        # d1 died at its second adoption (os._exit(29)) and the router
        # ejected it; the survivor keeps serving
        assert d1.wait(timeout=30) == 29
        end = time.time() + 20
        while time.time() < end:
            states = _get(rport, "/healthz")["replicas"]
            if states["r1"] == "gone":
                break
            time.sleep(0.3)
        assert _get(rport, "/healthz")["replicas"]["r1"] == "gone"
        assert _get(rport, "/healthz")["replicas"]["r2"] == "serving"

        m = _metrics(rport)
        # the dropped direct send degraded to the proxy leg: the router
        # carried at least one payload
        assert m["pfx_router_handoff_bytes_total"][frozenset()] > 0
        # the decode death ran the bounded re-prefill failover
        assert _lab(m, "pfx_handoff_failovers_total", leg="decode") >= 1.0
        pre_m = _metrics(pre_p)
        assert _lab(pre_m, "pfx_handoff_direct_total",
                    outcome="fallback") >= 1.0
        assert _lab(pre_m, "pfx_handoff_direct_total", outcome="ok") >= 1.0

        # post-failover steady state: token-identical on the survivors
        c, resp = _post(rport, body)
        assert c == 200 and resp["completion_ids"] == outs[0]
        # arena accounting closes on the survivor (no orphaned refs)
        assert _metrics(d2_p)["pfx_kv_blocks_used"][frozenset()] == 0.0

        for proc in (router, pre, d2):
            proc.send_signal(signal.SIGTERM)
        for proc in (router, pre, d2):
            assert proc.wait(timeout=60) == 0
    finally:
        logs = [_finish(p) for p in (pre, d1, d2)]
        logs += [_finish(router)]
    for log in logs:
        assert "Traceback" not in log, log[-3000:]


# ---------------------------------------------------------------------------
# role-aware pool supervision: SIGKILL both corpses under flood
# ---------------------------------------------------------------------------


def _pool_replay_agrees(rport):
    """Per-pool replay contract: each pool's decision rows fold into
    ITS pool-labeled pfx_controller_* counters exactly (retry until no
    tick lands between the two reads)."""
    from paddlefleetx_tpu.core.controller import replay_controller_log

    for _ in range(10):
        dbg = _get(rport, "/debug/controller")
        m = _metrics(rport)
        dbg2 = _get(rport, "/debug/controller")
        if any(
            len(dbg["pools"][p]["decisions"])
            != len(dbg2["pools"][p]["decisions"])
            for p in dbg["pools"]
        ):
            continue
        assert set(dbg["pools"]) == {"prefill", "decode"}
        for pool, view in dbg["pools"].items():
            replay = replay_controller_log(view["decisions"], pool=pool)
            assert replay["ticks"] > 0
            assert _lab(m, "pfx_controller_ticks_total",
                        pool=pool) == replay["ticks"]
            assert _lab(m, "pfx_controller_scale_ups_total",
                        pool=pool) == replay["scale_ups"]
            assert _lab(m, "pfx_controller_scale_downs_total",
                        pool=pool) == replay["scale_downs"]
        return dbg
    raise AssertionError("pool controllers never quiesced between reads")


@pytest.mark.slow  # ~4 supervised jax boots + respawns; covered by
# make test-disagg / test-all (the failure-leg contracts stay tier-1
# via the direct/failover drills above + the router/controller units)
def test_pool_supervisor_restarts_both_corpses_under_flood(tmp_path):
    """THE chaos acceptance drill: a supervised disaggregated fleet
    (2 prefill + 2 decode) under flood, SIGKILL one prefill replica
    AND one decode replica holding adopted rows — zero hangs, every
    request exactly one of 200/503, the role-aware pool supervisor
    respawns BOTH corpses (router walks them gone -> warm -> serving
    on new pids), post-failover output token-identical, per-pool
    decision logs replay into the pool-labeled counters exactly."""
    cfg_path = tmp_path / "tiny_pools.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    rport = _free_port()
    router = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "router.py"),
         "--port", str(rport), "--poll-interval", "0.2",
         "--eject-after", "3",
         "--supervise",
         "--prefill-cmd", _serve_cmd(cfg_path, "--role", "prefill"),
         "--decode-cmd", _serve_cmd(cfg_path, "--role", "decode",
                                    "--cb-batch", "4"),
         "--min-prefill", "2", "--max-prefill", "2",
         "--min-decode", "2", "--max-decode", "2",
         "--prefill-base-port", str(_free_port()),
         "--decode-base-port", str(_free_port()),
         "--restart-backoff", "0.2",
         "--control-interval", "0.3",
         "--compile-cache-dir", CACHE_DIR,
         "--replica-log-dir", str(tmp_path / "replica-logs")],
        env=_env(), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    try:
        h = _wait_eligible(rport, 4, timeout=300, proc=router)
        assert h["mode"] == "disaggregated", h
        assert set(h["controller"]["pools"]) == {"prefill", "decode"}

        body = {"prompt_ids": SYS + [40, 41, 42], "max_tokens": 6,
                "deadline_s": 60}
        code, ref = _post(rport, body)
        assert code == 200, (code, ref)

        views = _get(rport, "/replicas")["replicas"]
        pre_victim = next(v for v in views if v["role"] == "prefill")
        dec_victim = next(v for v in views if v["role"] == "decode")

        stop = threading.Event()
        results, lock = [], threading.Lock()

        def flood():
            while not stop.is_set():
                c, _r = _post(rport, body, timeout=90)
                with lock:
                    results.append(c)

        threads = [threading.Thread(target=flood) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(1.0)  # adopted rows live on the decode pool
        os.kill(pre_victim["pid"], signal.SIGKILL)
        os.kill(dec_victim["pid"], signal.SIGKILL)
        time.sleep(3.0)  # traffic through the failover window
        stop.set()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "hung connection through the kills"
        with lock:
            codes = list(results)
        # zero hangs, honest accounting: exactly one of 200/503 each
        assert codes and all(c in (200, 503) for c in codes), codes
        assert codes.count(200) >= 1, codes

        # the pool supervisor respawns both corpses; the router walks
        # them gone -> warm -> serving on NEW pids
        def _respawned():
            vs = _get(rport, "/replicas")["replicas"]
            by_key = {v["key"]: v for v in vs}
            a = by_key[pre_victim["key"]]
            b = by_key[dec_victim["key"]]
            return (a["state"] == "serving" and a["pid"] != pre_victim["pid"]
                    and b["state"] == "serving"
                    and b["pid"] != dec_victim["pid"])

        end = time.time() + 180
        while time.time() < end and not _respawned():
            time.sleep(0.5)
        assert _respawned(), _get(rport, "/replicas")

        m = _metrics(rport)
        restarts = {
            dict(k)["replica"]: v
            for k, v in m.get("pfx_replica_restarts_total", {}).items()
        }
        assert any(r.startswith("p") for r in restarts), restarts
        assert any(r.startswith("d") for r in restarts), restarts

        # post-failover: token-identical through the healed fleet
        for _ in range(3):
            code, resp = _post(rport, body)
            assert code == 200
            assert resp["completion_ids"] == ref["completion_ids"]

        _pool_replay_agrees(rport)

        # graceful teardown: the router drains its children, exit 0
        router.send_signal(signal.SIGTERM)
        assert router.wait(timeout=120) == 0
    finally:
        rlog = _finish(router)
    assert "Traceback" not in rlog, rlog[-3000:]
