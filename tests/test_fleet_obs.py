"""Fleet observability units (`make test-fleet-obs`): wall-clock anchor
math, span summaries + the envelope skew rule, remote-parent forced
sampling, the exposition parser, the federation store (staleness +
cardinality cap), the fleet log, and the `--fleet` report renderer —
all host-only, no jax (the cross-process stitch itself is drilled
through the real CLIs in tests/test_fleet_obs_drills.py)."""

import json
import os
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils import tracing as TR
from paddlefleetx_tpu.utils.telemetry import (
    Registry,
    get_registry,
    parse_exposition,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# wall-clock anchor math
# ---------------------------------------------------------------------------


def test_anchor_roundtrip_and_constant_offset():
    m = time.monotonic()
    # float64 at epoch scale resolves ~0.2us — the documented precision
    assert abs(TR.epoch_to_mono(TR.mono_to_epoch(m)) - m) < 1e-5
    # ONE anchor per process: the conversion is a constant offset, so
    # span-relative durations survive the epoch trip exactly
    d1 = TR.mono_to_epoch(m + 1.25) - TR.mono_to_epoch(m)
    assert d1 == pytest.approx(1.25, abs=1e-5)
    a1 = TR.clock_anchor()
    assert TR.clock_anchor() is a1  # captured once


def test_anchored_epoch_is_near_wall_clock():
    now_m, now_e = time.monotonic(), time.time()
    assert abs(TR.mono_to_epoch(now_m) - now_e) < 1.0


# ---------------------------------------------------------------------------
# span summaries: bounding, aggregation, redaction-at-the-boundary
# ---------------------------------------------------------------------------


def _request_trace(t0, chunks=10):
    tc = TR.TraceContext("t-sum", "request", t0=t0, scheduler="unit")
    tc.span("queue_wait", t0=t0, t1=t0 + 0.1)
    tc.span("prefill", t0=t0 + 0.1, t1=t0 + 0.2, prompt_len=8)
    for i in range(chunks):
        tc.event("decode_chunk", t=t0 + 0.2 + 0.01 * i,
                 committed=2, accepted=1)
    tc.event("respond", t=t0 + 0.5, code=200,
             structured={"not": "scalar"}, note="x" * 100)
    tc.finish(t=t0 + 0.5)
    return tc


def test_span_summary_aggregates_dense_instants_and_bounds():
    t0 = time.monotonic()
    s = TR.span_summary(_request_trace(t0))
    names = [x["name"] for x in s["spans"]]
    # dense decode_chunk instants collapsed into ONE span...
    assert names.count("decode_chunk") == 1
    dc = next(x for x in s["spans"] if x["name"] == "decode_chunk")
    # ...with count + numeric args SUMMED and the window covered
    assert dc["args"]["count"] == 10
    assert dc["args"]["committed"] == 20 and dc["args"]["accepted"] == 10
    assert dc["dur"] == pytest.approx(0.09, abs=1e-4)
    # sparse spans survive individually, epoch-anchored
    pf = next(x for x in s["spans"] if x["name"] == "prefill")
    assert pf["t0"] == pytest.approx(TR.mono_to_epoch(t0 + 0.1), abs=1e-4)
    assert pf["dur"] == pytest.approx(0.1, abs=1e-4)
    # redaction at the boundary: non-scalar / long-string args dropped
    resp = next(x for x in s["spans"] if x["name"] == "respond")
    assert "structured" not in resp["args"] and "note" not in resp["args"]
    assert resp["args"]["code"] == 200
    assert s["dropped"] == 0 and s["proc"]["pid"] == os.getpid()


def test_span_summary_cap_drops_middle_keeps_last():
    t0 = time.monotonic()
    tc = TR.TraceContext("t-cap", "request", t0=t0)
    for i in range(20):
        tc.span(f"phase_{i}", t0=t0 + i, t1=t0 + i + 0.5)
    s = TR.span_summary(tc, cap=8)
    assert len(s["spans"]) == 8 and s["dropped"] == 12
    assert s["spans"][-1]["name"] == "phase_19"  # last kept


def test_parse_span_summaries_tolerates_garbage():
    assert TR.parse_span_summaries("not json") == []
    assert TR.parse_span_summaries("[1, 2]") == []
    assert TR.parse_span_summaries(json.dumps({"spans": []})) != []
    two = json.dumps([{"trace_id": "a"}, {"trace_id": "b"}])
    assert len(TR.parse_span_summaries(two)) == 2


# ---------------------------------------------------------------------------
# the skew rule: remote spans bounded by the request/response envelope
# ---------------------------------------------------------------------------


def _summary(spans, proc=None):
    return {
        "trace_id": "child-1",
        "proc": proc or {"pid": 4242, "replica_id": "d0", "role": "decode"},
        "spans": spans,
        "dropped": 0,
    }


def test_remote_summary_synced_clocks_zero_skew():
    t0 = time.monotonic()
    parent = TR.TraceContext("p", "route", t0=t0)
    spans = [{"name": "decode", "t0": TR.mono_to_epoch(t0 + 0.2),
              "dur": 0.3, "args": {"tokens": 6}}]
    skew = parent.add_remote_summary(_summary(spans),
                                     t_send=t0 + 0.1, t_recv=t0 + 0.6)
    assert skew == pytest.approx(0.0, abs=1e-4)
    evs = parent.timeline()["events"]
    remote = [e for e in evs if e.get("proc")]
    # an enclosing hop bar (named after the process) + the span
    assert {e["name"] for e in remote} == {"d0 (decode)", "decode"}
    assert all(e["proc"]["pid"] == 4242 for e in remote)


def test_remote_summary_skew_clamps_into_envelope_preserving_order():
    t0 = time.monotonic()
    parent = TR.TraceContext("p", "route", t0=t0)
    # a child whose clock runs 100s BEHIND: its anchored spans land
    # before the request was even sent
    spans = [
        {"name": "a", "t0": TR.mono_to_epoch(t0 - 100.0), "dur": 0.1,
         "args": {}},
        {"name": "b", "t0": TR.mono_to_epoch(t0 - 99.8), "dur": 0.1,
         "args": {}},
    ]
    skew = parent.add_remote_summary(_summary(spans),
                                     t_send=t0 + 0.1, t_recv=t0 + 1.0)
    assert skew == pytest.approx(100.1, abs=1e-3)
    remote = [e for e in parent.timeline()["events"]
              if e.get("proc") and e["name"] in ("a", "b")]
    ats = {e["name"]: e["at_s"] for e in remote}
    # pulled inside the envelope, relative order + spacing preserved
    assert ats["a"] >= 0.1 - 1e-3
    assert ats["b"] - ats["a"] == pytest.approx(0.2, abs=1e-3)

    # a child whose clock runs AHEAD shifts backward, bounded at t_send
    parent2 = TR.TraceContext("p2", "route", t0=t0)
    spans2 = [{"name": "c", "t0": TR.mono_to_epoch(t0 + 50.0),
               "dur": 0.2, "args": {}}]
    skew2 = parent2.add_remote_summary(_summary(spans2),
                                       t_send=t0 + 0.1, t_recv=t0 + 0.9)
    assert skew2 < 0
    ev = next(e for e in parent2.timeline()["events"] if e["name"] == "c")
    assert t0 + ev["at_s"] + ev["dur_s"] <= t0 + 0.9 + 1e-3


def test_remote_summary_empty_is_noop():
    parent = TR.TraceContext("p", "route", t0=1.0)
    assert parent.add_remote_summary(_summary([]), 1.0, 2.0) == 0.0
    assert parent.timeline()["events"] == []


def test_chrome_trace_gives_remote_spans_their_own_pid_lane():
    t0 = time.monotonic()
    parent = TR.TraceContext("p", "route", t0=t0)
    parent.event("route", t=t0 + 0.01, replica="r0")
    spans = [{"name": "decode", "t0": TR.mono_to_epoch(t0 + 0.2),
              "dur": 0.3, "args": {}}]
    parent.add_remote_summary(_summary(spans), t_send=t0 + 0.1,
                              t_recv=t0 + 0.6)
    parent.finish(t=t0 + 0.7)
    from test_tracing import validate_chrome_trace

    doc = TR.chrome_trace([parent])
    lanes = validate_chrome_trace(doc)
    pids = {pid for pid, _ in lanes}
    assert {os.getpid(), 4242} <= pids  # one lane per process
    metas = {e["pid"]: e["args"]["name"]
             for e in doc["traceEvents"] if e["ph"] == "M"}
    assert metas[4242] == "d0 (decode)"
    # wall-clock anchored: ts is epoch us, not monotonic us
    first_x = next(e for e in doc["traceEvents"] if e["ph"] == "X")
    assert abs(first_x["ts"] / 1e6 - time.time()) < 60.0


# ---------------------------------------------------------------------------
# propagation: headers + remote-parent forced sampling
# ---------------------------------------------------------------------------


def test_outbound_and_parse_headers_roundtrip():
    tc = TR.TraceContext("abc-1", "route")
    h = TR.outbound_trace_headers(tc, "/generate")
    assert h == {"X-Trace-Id": "abc-1", "X-Parent-Span": "/generate"}
    parent = TR.remote_parent_from_headers(h)
    assert parent == {"trace_id": "abc-1", "span": "/generate"}
    assert TR.outbound_trace_headers(None, "x") == {}
    assert TR.remote_parent_from_headers({}) is None


def test_remote_parent_forces_sampling_past_the_accumulator():
    buf = TR.TraceBuffer(sample=0.001, cap=16)
    assert buf.maybe_start("request") is None  # sampler skips
    tc = buf.start("request", parent_trace="abc-1")
    assert tc is not None and buf.get(tc.trace_id) is tc
    # sample=0 still disables everything (zero-work outranks stitching)
    off = TR.TraceBuffer(sample=0.0)
    assert off.start("request") is None


def test_attach_request_trace_binds_parent_meta(monkeypatch):
    from paddlefleetx_tpu.core.request_queue import RequestFuture

    monkeypatch.setattr(TR, "_buffer", TR.TraceBuffer(sample=0.001, cap=16))
    fut = RequestFuture()
    with TR.remote_parent({"trace_id": "rt-9", "span": "/prefill"}):
        TR.attach_request_trace(fut, t0=time.monotonic(),
                                scheduler="unit", prompts=1, max_new=4)
    assert fut.trace is not None, "remote-parent hops must force-sample"
    assert fut.trace.meta["parent_trace"] == "rt-9"
    assert fut.trace.meta["parent_span"] == "/prefill"
    # without a parent the 0.001 sampler skips as before
    fut2 = RequestFuture()
    TR.attach_request_trace(fut2, t0=time.monotonic(),
                            scheduler="unit", prompts=1, max_new=4)
    assert fut2.trace is None
    # the binding is scoped: no leak into later submits
    assert TR.current_remote_parent() is None


# ---------------------------------------------------------------------------
# exposition parser
# ---------------------------------------------------------------------------


def test_parse_exposition_names_labels_escapes():
    text = (
        "# HELP pfx_x_total help text\n"
        "# TYPE pfx_x_total counter\n"
        "pfx_x_total 3\n"  # noqa — fixture exposition, not a registry name
        'pfx_y{code="200",msg="a\\"b,c"} 1.5\n'  # noqa — fixture
        'pfx_hist_bucket{le="+Inf"} 7\n'  # noqa — fixture
        "malformed line !!\n"
        "pfx_bad_value nope\n"
    )
    rows = parse_exposition(text)
    d = {(n, tuple(sorted(l.items()))): v for n, l, v in rows}
    assert d[("pfx_x_total", ())] == 3.0  # noqa — fixture name
    assert d[("pfx_y", (("code", "200"), ("msg", 'a"b,c')))] == 1.5  # noqa
    assert d[("pfx_hist_bucket", (("le", "+Inf"),))] == 7.0  # noqa
    assert len(rows) == 3  # malformed lines skipped, never raised


def test_parse_exposition_roundtrips_the_real_renderer():
    reg = Registry()
    reg.counter("pfx_http_responses_total", code="200").inc(2)
    reg.histogram("pfx_request_latency_seconds").observe(0.05)
    # label values with backslash-letter sequences must survive the
    # escape round trip: a sequential \n-then-\\ unescape would turn
    # the rendered 'C:\\new' back into backslash+newline, not 'C:\new'
    reg.counter("pfx_http_responses_total", code="C:\\new").inc()
    reg.counter("pfx_http_responses_total", code="a\nb").inc()
    rows = parse_exposition(reg.render_prometheus())
    names = {n for n, _, _ in rows}
    assert "pfx_http_responses_total" in names
    assert "pfx_request_latency_seconds_bucket" in names
    assert "pfx_request_latency_seconds_count" in names
    codes = {l["code"] for n, l, _ in rows
             if n == "pfx_http_responses_total"}
    assert "C:\\new" in codes and "a\nb" in codes, codes


# ---------------------------------------------------------------------------
# federation store
# ---------------------------------------------------------------------------


def _exposition(n_extra=0, value=3.0):
    lines = [
        "# TYPE pfx_serving_tokens_out_total counter",
        f"pfx_serving_tokens_out_total {value}",
        'pfx_http_responses_total{code="200"} 5',
        # a replica-side label that collides with a federation label
        'pfx_router_replica_depth{replica="inner"} 2',
        # federation must not recurse
        'pfx_fleet_series 99',
        # non-pfx samples are not federated
        "python_gc_collections_total 7",
    ]
    for i in range(n_extra):
        lines.append(f"pfx_x_{i} 1")  # noqa — fixture exposition name
    return "\n".join(lines) + "\n"


def test_federation_ingest_collect_and_agreement():
    from paddlefleetx_tpu.core.router import FleetFederation

    fed = FleetFederation(series_cap=100)
    kept = fed.ingest("r0", "decode", _exposition())
    assert kept == 3  # pfx_* only, pfx_fleet_* and foreign names excluded
    rows = fed.collect()
    by = {}
    for name, labels, value in rows:
        by.setdefault(name, []).append((labels, value))
    # the agreement contract: re-export == the replica's own sample
    fleet = {
        (l["name"], tuple(sorted(
            (k, v) for k, v in l.items()
            if k not in ("replica", "pool", "name")
        ))): v
        for l, v in by["pfx_fleet_metric"]
    }
    assert fleet[("pfx_serving_tokens_out_total", ())] == 3.0
    assert fleet[("pfx_http_responses_total", (("code", "200"),))] == 5.0
    # label collision preserved under src_, never overwritten
    assert fleet[("pfx_router_replica_depth",
                  (("src_replica", "inner"),))] == 2.0
    assert all(l["replica"] == "r0" and l["pool"] == "decode"
               for l, _ in by["pfx_fleet_metric"])
    assert by["pfx_fleet_series"][0][1] == 3.0
    assert by["pfx_fleet_series_dropped"][0][1] == 0.0
    # value() accessor (the fleet log's reader)
    assert fed.value("r0", "pfx_serving_tokens_out_total") == 3.0
    assert fed.value("r0", "pfx_http_responses_total", code="200") == 5.0
    assert fed.value("nope", "pfx_serving_tokens_out_total") is None


def test_federation_staleness_gauge_grows_until_next_scrape():
    from paddlefleetx_tpu.core.router import FleetFederation

    fed = FleetFederation(series_cap=100)
    fed.ingest("r0", "monolith", _exposition())
    age0 = dict(
        ((n, l.get("replica")), v) for n, l, v in fed.collect()
    )[("pfx_fleet_scrape_age_seconds", "r0")]
    time.sleep(0.05)
    age1 = dict(
        ((n, l.get("replica")), v) for n, l, v in fed.collect()
    )[("pfx_fleet_scrape_age_seconds", "r0")]
    assert age1 > age0
    fed.ingest("r0", "monolith", _exposition(value=4.0))
    age2 = dict(
        ((n, l.get("replica")), v) for n, l, v in fed.collect()
    )[("pfx_fleet_scrape_age_seconds", "r0")]
    assert age2 < age1
    # the newest scrape's value won
    assert fed.value("r0", "pfx_serving_tokens_out_total") == 4.0
    fed.forget("r0")
    assert fed.value("r0", "pfx_serving_tokens_out_total") is None


def test_federation_cardinality_cap_warns_and_counts(caplog):
    from paddlefleetx_tpu.core.router import FleetFederation

    fed = FleetFederation(series_cap=4)
    fed.ingest("r0", "decode", _exposition(n_extra=10))
    rows = fed.collect()
    fleet = [r for r in rows if r[0] == "pfx_fleet_metric"]
    dropped = next(v for n, _, v in rows if n == "pfx_fleet_series_dropped")
    kept = next(v for n, _, v in rows if n == "pfx_fleet_series")
    assert len(fleet) == 4 and kept == 4.0
    assert dropped == 9.0  # 13 pfx samples - 4 kept
    # the loud warning names the cap, once
    fed.collect()
    # deterministic: the SAME series survive across collects
    assert [r[1]["name"] for r in fleet] == [
        r[1]["name"] for r in fed.collect() if r[0] == "pfx_fleet_metric"
    ]


def test_federation_scrape_outcome_counters():
    from paddlefleetx_tpu.core.router import FleetFederation

    reg = get_registry()
    base_ok = reg.value("pfx_fleet_scrapes_total",
                        replica="ru-1", outcome="ok")
    base_miss = reg.value("pfx_fleet_scrapes_total",
                          replica="ru-1", outcome="missing")
    fed = FleetFederation(series_cap=10)
    fed.ingest("ru-1", "monolith", _exposition())
    fed.note_miss("ru-1", "missing")
    assert reg.value("pfx_fleet_scrapes_total", replica="ru-1",
                     outcome="ok") == base_ok + 1
    assert reg.value("pfx_fleet_scrapes_total", replica="ru-1",
                     outcome="missing") == base_miss + 1


def test_gone_replica_series_leave_the_federated_scrape():
    """A replica ejected to `gone` must not keep re-exporting its last
    samples forever (under supervisor churn the stale series would
    crowd LIVE replicas out of the cardinality cap); a redeploy that
    re-enters via warm -> serving repopulates on its next poll."""
    from test_router import StubReplica

    from paddlefleetx_tpu.core.router import RouterCore

    stub = StubReplica(depth=1)
    stub.health["metrics_text"] = "pfx_serving_tokens_out_total 7\n"
    try:
        core = RouterCore([(stub.url, "monolith")], poll_interval_s=60,
                          eject_after=2)
        r = core.replicas["r0"]
        core.poll_replica(r)
        assert core.federation.value(
            "r0", "pfx_serving_tokens_out_total") == 7.0
        stub.stop()
        for _ in range(2):
            core.poll_replica(r)
        assert r.state == "gone"
        assert core.federation.value(
            "r0", "pfx_serving_tokens_out_total") is None
    finally:
        stub.stop()


def test_router_poll_ingests_metrics_text_from_one_healthz(monkeypatch):
    """The satellite contract end-to-end at the unit level: ONE
    /healthz?metrics=1 response feeds both the scoring fields and the
    federated samples — the router's stored depth and its re-exported
    queue-depth sample come from the same replica snapshot."""
    from test_router import StubReplica

    from paddlefleetx_tpu.core.router import RouterCore

    stub = StubReplica(depth=3)
    stub.health["metrics_text"] = (
        "pfx_queue_depth 3\npfx_serving_tokens_out_total 12\n"
    )
    stub.health["ttft_p99_s"] = 0.25
    try:
        core = RouterCore([(stub.url, "monolith")], poll_interval_s=60)
        r = core.replicas["r0"]
        core.poll_replica(r)
        assert r.depth == 3 and r.ttft_p99_s == 0.25
        assert core.federation.value("r0", "pfx_queue_depth") == 3.0
        assert core.federation.value(
            "r0", "pfx_serving_tokens_out_total") == 12.0
        # a pre-federation replica (no metrics_text) still polls fine
        del stub.health["metrics_text"]
        core.poll_replica(r)
        assert r.healthy
    finally:
        stub.stop()


# ---------------------------------------------------------------------------
# fleet log + report renderer
# ---------------------------------------------------------------------------


def _views(i):
    return [{
        "key": k, "role": pool, "state": "serving", "depth": i % 3,
        "occupancy": 0.1 * i, "in_flight": 1, "ttft_p99_s": 0.05 + 0.01 * i,
        "latency_p50_s": 0.1, "latency_p99_s": 0.3,
    } for k, pool in (("p0", "prefill"), ("d0", "decode"))]


def test_fleet_log_rate_limit_and_row_shape(tmp_path):
    from paddlefleetx_tpu.core.router import FleetLog

    path = tmp_path / "fleet_metrics.jsonl"
    log = FleetLog(str(path), min_interval_s=30.0)
    assert log.sample(_views(1), None, router_extra={"in_flight": 2})
    assert not log.sample(_views(2), None)  # rate-limited
    log.event({"event": "scale", "pool": "decode", "action": "scale_up",
               "reason": "occupancy", "target": 2})
    rows = [json.loads(ln) for ln in path.read_text().splitlines()]
    kinds = [r["event"] for r in rows]
    assert kinds == ["replica_sample", "replica_sample", "router_sample",
                     "scale"]
    assert rows[0]["replica"] == "p0" and rows[0]["pool"] == "prefill"
    assert rows[2]["in_flight"] == 2
    assert all("ts" in r for r in rows)


def test_fleet_log_copies_federated_handoff_fields(tmp_path):
    from paddlefleetx_tpu.core.router import FleetFederation, FleetLog

    fed = FleetFederation(series_cap=100)
    fed.ingest("d0", "decode", (
        'pfx_handoff_bytes_total{transport="direct"} 4096\n'
        "pfx_handoff_adopts_total 3\npfx_kv_blocks_used 7\n"
    ))
    path = tmp_path / "f.jsonl"
    FleetLog(str(path), min_interval_s=0.0).sample(_views(1), fed)
    d0 = next(json.loads(ln) for ln in path.read_text().splitlines()
              if json.loads(ln).get("replica") == "d0")
    assert d0["handoff_bytes_direct"] == 4096
    assert d0["handoff_adopts_total"] == 3 and d0["kv_blocks_used"] == 7


def _synthetic_fleet(tmp_path, torn=True):
    path = tmp_path / "fleet_metrics.jsonl"
    t = time.time()
    with open(path, "w") as f:
        for i in range(6):
            for rep, pool in (("p0", "prefill"), ("d0", "decode")):
                f.write(json.dumps({
                    "ts": t + i, "event": "replica_sample", "replica": rep,
                    "pool": pool, "state": "serving", "depth": i % 3,
                    "occupancy": 0.1 * i, "in_flight": 1,
                    "ttft_p99_s": 0.05 + 0.01 * i, "latency_p50_s": 0.1,
                    "latency_p99_s": 0.3, "kv_blocks_used": 4 + i,
                    "handoff_bytes_direct": 1000 * i,
                    "handoff_exports_total": i, "handoff_adopts_total": i,
                }) + "\n")
            f.write(json.dumps({
                "ts": t + i, "event": "router_sample", "in_flight": 2,
                "handoff_bytes_proxied": 0, "handoff_count": i,
                "handoff_seconds_sum": 0.2 * i,
                # per-tenant front-door snapshot (core/router.py
                # tenant_snapshot): the --fleet renderers table this
                "tenants": {
                    "gold": {"weight": 4.0, "rps": None,
                             "max_inflight": 8, "in_flight": i % 2},
                    "bulk": {"weight": 1.0, "rps": 2.0,
                             "max_inflight": None, "in_flight": 1},
                },
            }) + "\n")
        f.write(json.dumps({
            "ts": t + 3, "event": "scale", "pool": "decode",
            "action": "scale_up", "reason": "occupancy 0.95", "target": 2,
        }) + "\n")
        # a SECOND pool scaling in the same tick: both markers must
        # render (a time-keyed marker dict kept only one)
        f.write(json.dumps({
            "ts": t + 3, "event": "scale", "pool": "prefill",
            "action": "scale_up", "reason": "depth 6.0", "target": 2,
        }) + "\n")
        if torn:
            f.write('{"ts": 1, "event": "replica_sam')  # crashed mid-append
    return path


def test_fleet_report_renders_validated_html_from_torn_artifact(tmp_path):
    from test_model_stats import _validate_html

    import report

    path = _synthetic_fleet(tmp_path)
    out = tmp_path / "fleet.html"
    assert report.main(["--fleet", str(path), "-o", str(out)]) == 0
    doc = out.read_text()
    _validate_html(doc)
    assert "TTFT p99" in doc and "scale_up" in doc
    assert "unparseable" in doc or "partial" in doc  # the torn-tail note
    # per-replica curves name both replicas; markers carry the reason,
    # and BOTH same-tick scale events render (not last-writer-wins)
    assert "p0" in doc and "d0" in doc and "occupancy 0.95" in doc
    assert "depth 6.0" in doc
    # per-tenant front-door table off the last router sample: declared
    # quota knobs render, None renders as unlimited (not a blank cell)
    assert "Tenants (front door)" in doc
    assert "gold" in doc and "bulk" in doc and "unlimited" in doc


def test_fleet_report_markdown_and_run_dir_scan(tmp_path):
    import report

    _synthetic_fleet(tmp_path, torn=False)
    out = tmp_path / "fleet.md"
    # --fleet with no path scans --run-dir for the conventional name
    assert report.main(["--fleet", "--run-dir", str(tmp_path),
                        "-o", str(out), "--format", "md"]) == 0
    doc = out.read_text()
    assert "| p0 |" in doc and "scale_up" in doc
    assert "| gold | 4.0 | unlimited | 8 |" in doc


def test_fleet_report_absent_artifact_is_rc2(tmp_path, capsys):
    import report

    rc = report.main(["--fleet", str(tmp_path / "nope.jsonl"),
                      "-o", str(tmp_path / "x.html")])
    assert rc == 2
    assert "no readable fleet artifact" in capsys.readouterr().err


def test_fleet_report_cli_subprocess(tmp_path):
    """The exact operator command line works end-to-end (stdlib-only,
    no jax import — it must run on a laptop off CI artifacts)."""
    path = _synthetic_fleet(tmp_path, torn=False)
    out = tmp_path / "fleet.html"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "report.py"),
         "--fleet", str(path), "-o", str(out)],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr
    assert out.exists() and "replica samples" in r.stdout
