"""Unit tests for the version-split shard_map adapter (shard_map_compat).

These pin the 0.4.x full-manual branch so a future jax bump cannot
silently break either routing: the adapter must (a) run manual bodies
whose collectives match the equivalent pjit/GSPMD computation, (b) expose
the manual axis set to in-body code via the thread-local, and (c) strip
manual axes from logical sharding constraints instead of tripping the
0.4.x "axis also found in manual_axes" error."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddlefleetx_tpu.parallel import shard_map_compat as smc
from paddlefleetx_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_MODEL,
    AXIS_SEP,
    AXIS_STAGES,
    MeshConfig,
    build_mesh,
)


def _mesh(devices8, **kw):
    return build_mesh(MeshConfig(**kw), devices8)


def test_branch_detection_matches_installed_jax():
    """The adapter and the conftest gate must agree on which jax this is."""
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        assert not smc.HAS_JAX09_SHARD_MAP
    else:
        import inspect

        assert smc.HAS_JAX09_SHARD_MAP == (
            "check_vma" in inspect.signature(fn).parameters
        )


def test_manual_axes_thread_local_scoping(devices8):
    """current_manual_axes(): empty outside, the body's set inside (all
    mesh axes on the 0.4.x full-manual branch), restored after."""
    mesh = _mesh(devices8, pp_degree=2, dp_degree=4)
    seen = {}

    def body(x):
        seen["inside"] = smc.current_manual_axes()
        return x

    assert smc.current_manual_axes() == frozenset()
    f = smc.shard_map(body, mesh, P(AXIS_STAGES), P(AXIS_STAGES), {AXIS_STAGES})
    with mesh:
        jax.jit(f)(jnp.arange(8.0).reshape(2, 4))
    if smc.HAS_JAX09_SHARD_MAP:
        assert seen["inside"] == frozenset({AXIS_STAGES})
    else:
        assert seen["inside"] == frozenset(mesh.axis_names)
    assert smc.current_manual_axes() == frozenset()


def test_unknown_manual_axis_raises(devices8):
    mesh = _mesh(devices8, pp_degree=2, dp_degree=4)
    with pytest.raises(ValueError, match="not in mesh axes"):
        smc.shard_map(lambda x: x, mesh, P(), P(), {"nonexistent"})


def test_ppermute_psum_body_matches_pjit(devices8):
    """A manual ring-shift + psum body must equal the same computation
    spelled as plain (pjit-able) array ops on the global view."""
    mesh = _mesh(devices8, pp_degree=4, dp_degree=2)
    S = 4
    x = jnp.arange(4.0 * 6).reshape(4, 6) + 1.0

    def body(xs):  # xs: [1, 6] local stage shard
        s = jax.lax.axis_index(AXIS_STAGES)
        y = xs * (s + 1).astype(xs.dtype)
        y = jax.lax.ppermute(y, AXIS_STAGES, [(i, (i + 1) % S) for i in range(S)])
        total = jax.lax.psum(y, AXIS_STAGES)
        return y + 0.25 * total

    f = smc.shard_map(body, mesh, P(AXIS_STAGES), P(AXIS_STAGES), {AXIS_STAGES})
    with mesh:
        got = jax.jit(f)(x)

    # global-view reference: scale row i by (i+1), roll rows by one, add
    # a quarter of the row-sum broadcast
    y = x * jnp.arange(1.0, S + 1)[:, None]
    y = jnp.roll(y, 1, axis=0)
    ref = y + 0.25 * y.sum(axis=0, keepdims=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)


def test_grad_through_manual_body_matches_pjit(devices8):
    mesh = _mesh(devices8, pp_degree=2, dp_degree=4)
    x = jnp.arange(8.0).reshape(2, 4)

    def body(xs):
        y = jnp.sin(xs)
        y = jax.lax.ppermute(y, AXIS_STAGES, [(i, (i + 1) % 2) for i in range(2)])
        return y * 3.0

    f = smc.shard_map(body, mesh, P(AXIS_STAGES), P(AXIS_STAGES), {AXIS_STAGES})
    ref_g = jax.grad(lambda x: jnp.sum(jnp.roll(jnp.sin(x), 1, 0) * 3.0))(x)
    with mesh:
        got_g = jax.jit(jax.grad(lambda x: jnp.sum(f(x))))(x)
    np.testing.assert_allclose(np.asarray(got_g), np.asarray(ref_g), rtol=1e-6)


@pytest.mark.skipif(
    smc.HAS_JAX09_SHARD_MAP, reason="full_specs is a 0.4.x-branch feature"
)
def test_full_specs_keep_extra_axes_sharded(devices8):
    """On the full-manual branch, full_specs may shard axes the body is
    elementwise-independent over; numerics must be unchanged and the
    output must land sharded along them."""
    mesh = _mesh(devices8, sep_degree=2, dp_degree=4)
    x = jnp.arange(8.0 * 6).reshape(8, 6)

    def body(xs):
        y = jax.lax.ppermute(xs, AXIS_SEP, [(i, (i + 1) % 2) for i in range(2)])
        return y + xs

    base = smc.shard_map(body, mesh, P(None, AXIS_SEP), P(None, AXIS_SEP), {AXIS_SEP})
    rich = smc.shard_map(
        body,
        mesh,
        P(None, AXIS_SEP),
        P(None, AXIS_SEP),
        {AXIS_SEP},
        full_specs=(P(AXIS_DATA, AXIS_SEP), P(AXIS_DATA, AXIS_SEP)),
    )
    with mesh:
        a = jax.jit(base)(x)
        b = jax.jit(rich)(x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    assert AXIS_DATA in str(b.sharding.spec)


def test_logical_constraint_stripped_inside_manual_region(devices8):
    """with_logical_constraint inside a manual body must not name manual
    axes (0.4.x rejects them); the constraint is stripped/no-op'd and the
    values flow through unchanged."""
    from paddlefleetx_tpu.parallel.sharding import make_rules, with_logical_constraint

    mesh = _mesh(devices8, pp_degree=2, mp_degree=2, dp_degree=2)
    rules = make_rules()
    x = jnp.arange(8.0 * 4).reshape(8, 4)

    def body(xs):
        y = with_logical_constraint(xs, ("batch", "mlp"), rules, mesh)
        return jax.lax.ppermute(y, AXIS_STAGES, [(i, (i + 1) % 2) for i in range(2)])

    f = smc.shard_map(body, mesh, P(AXIS_STAGES), P(AXIS_STAGES), {AXIS_STAGES})
    with mesh:
        got = jax.jit(f)(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(jnp.roll(x, 4, 0)), rtol=1e-6)


def test_strip_manual_axes_keeps_free_axes():
    from paddlefleetx_tpu.parallel.sharding import _strip_manual_axes

    spec = P((AXIS_DATA, AXIS_SEP), AXIS_MODEL, None)
    out = _strip_manual_axes(spec, {AXIS_SEP})
    assert tuple(out) == (AXIS_DATA, AXIS_MODEL, None)
    out = _strip_manual_axes(spec, {AXIS_DATA, AXIS_SEP, AXIS_MODEL})
    assert all(e is None for e in out)


def test_pytree_specs_and_multiple_outputs(devices8):
    """Tuple in_specs/out_specs over a pytree of args round-trip (the
    1F1B signature shape)."""
    mesh = _mesh(devices8, pp_degree=2, dp_degree=4)
    params = {"w": jnp.arange(4.0), "b": jnp.ones((2, 2))}
    x = jnp.arange(8.0).reshape(2, 4)

    def body(p, xs):
        y = xs + p["w"]
        partial = jnp.sum(y) + jnp.sum(p["b"])
        return y, partial[None]

    f = smc.shard_map(
        body,
        mesh,
        in_specs=(P(), P(AXIS_STAGES)),
        out_specs=(P(AXIS_STAGES), P(AXIS_STAGES)),
        manual_axes={AXIS_STAGES},
    )
    with mesh:
        y, partials = jax.jit(f)(params, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x + params["w"]), rtol=1e-6)
    # stage partials concatenate on the stage axis; their sum is the total
    np.testing.assert_allclose(
        float(jnp.sum(partials)),
        float(jnp.sum(x + params["w"]) + 2 * jnp.sum(params["b"])),
        rtol=1e-6,
    )
