"""Worker process for the 2-process jax.distributed e2e test.

Not a pytest file (no test_ prefix): launched by tests/test_distributed.py
as `python distributed_worker.py <proc_id> <nproc> <port> <outdir>`.

This is the repo's analogue of the reference's multi-node TIPC evidence
(/root/reference/benchmarks/test_tipc/ N4C32 cases, SURVEY §4.1): the real
multi-host code paths — jax.distributed bootstrap (parallel/env.py),
cross-process collectives from a sharded train step, the process_allgather
branch of check_replica_consistency (parallel/check.py), and distributed
orbax save/load — exercised on a 2-process × 4-virtual-CPU-device cluster.
"""

import os
import sys


def main() -> None:
    proc_id, nproc, port, outdir = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
    )
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    )
    import jax

    # env var alone does not survive the axon sitecustomize: pin in-process
    jax.config.update("jax_platforms", "cpu")
    # jax 0.4.x: cross-process computations on the CPU backend need the
    # gloo collectives implementation selected BEFORE backend init (the
    # default errors "Multiprocess computations aren't implemented on the
    # CPU backend"); >= 0.9 wires cross-process CPU by default and drops
    # the knob, hence the guard
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except AttributeError:
        pass
    os.environ["PFX_COORDINATOR_ADDRESS"] = f"localhost:{port}"
    os.environ["PFX_NUM_PROCESSES"] = str(nproc)
    os.environ["PFX_PROCESS_ID"] = str(proc_id)

    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.check import check_replica_consistency
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    batch, seq = 8, 32
    cfg = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": batch, "micro_batch_size": 2, "seed": 7},
            "Engine": {
                "max_steps": 2,
                "eval_freq": 0,
                "logging_freq": 10**9,
                "mix_precision": {"enable": False},
                "save_load": {"save_steps": 0, "output_dir": outdir},
            },
            "Model": {
                "module": "GPTModule",
                "vocab_size": 64,
                "hidden_size": 32,
                "num_layers": 2,
                "num_attention_heads": 4,
                "max_position_embeddings": seq,
                "hidden_dropout_prob": 0.0,
                "attention_probs_dropout_prob": 0.0,
                "dtype": "float32",
            },
            # data axis (2) spans the process boundary; model axis (2) and
            # fsdp axis (2) stay intra-process: grad psum + fsdp
            # all-gather/reduce-scatter cross hosts every step
            "Distributed": {
                "dp_degree": 2,
                "mp_degree": 2,
                "sharding": {"sharding_degree": 2, "sharding_stage": 3,
                             "min_shard_size": 0},
            },
            "Optimizer": {
                "name": "FusedAdamW",
                "weight_decay": 0.01,
                "lr": {"name": "Constant", "learning_rate": 1e-3},
                "grad_clip": {"name": "ClipGradByGlobalNorm", "clip_norm": 1.0},
            },
        }
    )
    cfg = process_configs(cfg, num_devices=8)
    mesh = init_dist_env(cfg)
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    module = build_module(cfg)

    # identical host batch on every process (global arrays are laid out by
    # sharding; each process transfers its addressable shards)
    rng = np.random.default_rng(0)
    host_batch = {
        "tokens": rng.integers(0, 64, (batch, seq)).astype(np.int64),
        "labels": rng.integers(0, 64, (batch, seq)).astype(np.int64),
        "loss_mask": np.ones((batch, seq), np.float32),
        "position_ids": np.tile(np.arange(seq), (batch, 1)),
    }

    with mesh:
        engine = Engine(cfg, module, mesh)
        dev = engine._put_batch(host_batch)
        losses = []
        for _ in range(2):
            engine.state, m = engine.train_step(engine.state, dev)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(x) for x in losses), losses

        # the process_allgather branch (parallel/check.py:96-105): every
        # process must fingerprint the sharded params identically
        fp = check_replica_consistency(engine.state.params)
        print(f"worker {proc_id}: losses {losses} fp {fp:#010x}", flush=True)

        # a deliberately host-divergent tree must be detected on EVERY rank
        import jax.numpy as jnp

        diverged = {"x": jnp.full((8,), float(proc_id))}
        try:
            check_replica_consistency(diverged, name="diverged")
        except RuntimeError:
            print(f"worker {proc_id}: divergence detected OK", flush=True)
        else:
            raise AssertionError("host-divergent tree passed the check")

        # distributed checkpoint: all processes save their shards; only
        # process 0 writes the completeness marker
        path = engine.save()
        engine.wait_for_save()
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("ckpt_written")
        assert os.path.exists(os.path.join(path, "meta.json"))

        # load back and verify the restored tree fingerprints identically
        engine.load(path)
        fp2 = check_replica_consistency(engine.state.params, name="restored")
        assert fp2 == fp, (hex(fp2), hex(fp))

    # ---- phase 2: ring attention + zigzag with the sep axis SPANNING the
    # process boundary (sep8 over 2x4 devices: K/V ppermute hops cross
    # hosts every ring step — the multi-host long-context path)
    cfg2 = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": 2, "micro_batch_size": 2, "seed": 7},
            "Engine": {
                "max_steps": 1,
                "eval_freq": 0,
                "logging_freq": 10**9,
                "mix_precision": {"enable": False},
                "save_load": {"save_steps": 0},
            },
            "Model": {
                "module": "GPTModule",
                "vocab_size": 64,
                "hidden_size": 32,
                "num_layers": 2,
                "num_attention_heads": 8,
                "max_position_embeddings": 64,
                "hidden_dropout_prob": 0.0,
                "attention_probs_dropout_prob": 0.0,
                "attn_impl": "ring",
                "dtype": "float32",
            },
            "Distributed": {"dp_degree": 1, "sep_degree": 8, "sep_zigzag": True},
            "Optimizer": {
                "name": "FusedAdamW",
                "lr": {"name": "Constant", "learning_rate": 1e-4},
            },
        }
    )
    cfg2 = process_configs(cfg2, num_devices=8)
    mesh2 = init_dist_env(cfg2)
    module2 = build_module(cfg2)
    batch2 = {
        "tokens": rng.integers(0, 64, (2, 64)).astype(np.int64),
        "labels": rng.integers(0, 64, (2, 64)).astype(np.int64),
        "loss_mask": np.ones((2, 64), np.float32),
        "position_ids": np.tile(np.arange(64), (2, 1)),
    }
    with mesh2:
        engine2 = Engine(cfg2, module2, mesh2)
        dev2 = engine2._put_batch(batch2)
        engine2.state, m2 = engine2.train_step(engine2.state, dev2)
        loss2 = float(m2["loss"])
        assert np.isfinite(loss2), loss2
        fp3 = check_replica_consistency(engine2.state.params, name="ring_zz")
    print(f"worker {proc_id}: ring_zz loss {loss2:.5f} fp {fp3:#010x}", flush=True)

    print(f"DIST_WORKER_OK {proc_id}", flush=True)


if __name__ == "__main__":
    main()
