"""2-process jax.distributed e2e: train + replica-consistency check +
distributed checkpoint save/load on a local CPU cluster (2 processes x 4
virtual devices).  The multi-host analogue of the reference's N4C32 TIPC
cases — the only way to exercise process_count()>1 branches without a pod."""

import os
import socket
import pytest
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow  # ~42s (two fresh jax processes, gloo bootstrap, 4
# virtual devices each); tier-1 budget funding for the shard_map-port
# tests that re-opened this very file on jax 0.4.37.  Replacement
# coverage: every collective/mesh schedule it exercises runs tier-1 on
# the 8-virtual-device single-process harness (pipeline/ring/layout
# parity, zero-offload), and distributed orbax save/restore rides the
# single-process ckpt suites; the jax.distributed bootstrap + cross-
# process gloo path itself has no cheaper spelling, so this exact test
# runs in `make test-parallel` and test-all.
def test_two_process_train_check_ckpt(tmp_path):
    port = _free_port()
    nproc = 2
    env = dict(os.environ)
    # the workers pin their own platform/device count; scrub any pytest-
    # session XLA_FLAGS so the 4-device override is what lands
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = ""
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), str(nproc), str(port), str(tmp_path)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=REPO,
            env=env,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=540)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} rc={p.returncode}\n{out[-3000:]}"
        assert f"DIST_WORKER_OK {i}" in out, out[-3000:]
        assert "divergence detected OK" in out, out[-3000:]

    # the two processes must agree on the params fingerprint line
    import re

    fps = {re.search(r"fp (0x[0-9a-f]+)", o).group(1) for o in outs}
    assert len(fps) == 1, fps
