"""RequestQueue unit tests (core/request_queue.py): admission control,
deadlines, coalescing, drain — all with a fake runner, no jax involved.
The end-to-end traffic drills live in tests/test_serve_drills.py."""

import threading
import time

import pytest

from paddlefleetx_tpu.core.request_queue import (
    DeadlineExceeded,
    QueueClosed,
    QueueFull,
    RequestQueue,
)


def echo_runner(prompts, max_new):
    """Rows echo their prompt plus the batch decode cap."""
    return [list(p) + [max_new] for p in prompts]


def test_submit_result_roundtrip():
    q = RequestQueue(echo_runner, max_depth=4).start()
    fut = q.submit([[1, 2]], 8, coalesce_key=("k",))
    assert fut.result(timeout=5) == [[1, 2, 8]]
    assert q.stats["submitted"] == 1 and q.stats["completed"] == 1
    assert q.depth() == 0
    q.shutdown(timeout=5)


def test_queue_full_rejection():
    """Admission is bounded: requests beyond max_depth are rejected
    synchronously with QueueFull (HTTP 429), not parked."""
    release = threading.Event()

    def slow_runner(prompts, max_new):
        release.wait(10)
        return [list(p) for p in prompts]

    q = RequestQueue(slow_runner, max_depth=2, max_coalesce=1).start()
    first = q.submit([[0]], 4)  # scheduler picks this up
    time.sleep(0.05)  # let it leave the queue and block in the runner
    futs = [q.submit([[i]], 4) for i in range(2)]  # fills the queue
    with pytest.raises(QueueFull):
        q.submit([[9]], 4)
    assert q.stats["rejected_full"] == 1
    release.set()
    for f in [first] + futs:
        f.result(timeout=5)
    assert q.shutdown(timeout=5)


def test_deadline_shed_before_decode():
    """An entry whose deadline passes while queued is shed with
    DeadlineExceeded and never reaches the runner."""
    release = threading.Event()
    served = []

    def slow_runner(prompts, max_new):
        release.wait(10)
        served.extend(prompts)
        return [list(p) for p in prompts]

    q = RequestQueue(slow_runner, max_depth=8, max_coalesce=1).start()
    a = q.submit([[1]], 4)
    time.sleep(0.05)  # a is now running (blocked)
    b = q.submit([[2]], 4, deadline_s=0.01)
    time.sleep(0.1)  # b expires while a occupies the scheduler
    release.set()
    assert a.result(timeout=5) == [[1]]
    with pytest.raises(DeadlineExceeded):
        b.result(timeout=5)
    assert q.stats["shed_deadline"] == 1
    assert [2] not in served  # no decode wasted on the expired entry
    q.shutdown(timeout=5)


def test_try_remove_sheds_queued_entry_only():
    release = threading.Event()

    def slow_runner(prompts, max_new):
        release.wait(10)
        return [list(p) for p in prompts]

    q = RequestQueue(slow_runner, max_depth=8, max_coalesce=1).start()
    a = q.submit([[1]], 4)
    time.sleep(0.05)
    b = q.submit([[2]], 4)
    assert q.try_remove(b) is True  # still queued: shed
    assert q.try_remove(a) is False  # already running: scheduler resolves
    with pytest.raises(DeadlineExceeded):
        b.result(timeout=5)
    release.set()
    assert a.result(timeout=5) == [[1]]
    q.shutdown(timeout=5)


def test_coalescing_groups_by_key_and_splits_results():
    """Same-key waiting requests merge into one runner call (batch sizes
    recorded); results split back per entry; different keys never mix."""
    batches = []

    def recording_runner(prompts, max_new):
        # rows decode to the BATCH cap, like a real coalesced generation
        batches.append(len(prompts))
        return [[p[0]] * max_new for p in prompts]

    q = RequestQueue(recording_runner, max_depth=16, max_coalesce=4)
    f1 = q.submit([[1]], 3, coalesce_key=("a",))
    f2 = q.submit([[2]], 7, coalesce_key=("a",))
    f3 = q.submit([[3]], 7, coalesce_key=("b",))  # different bucket
    f4 = q.submit([[4]], 7, coalesce_key=("a",))
    q.start()  # everything queued first: one scan coalesces a-keys
    # batch cap honored, per-entry trim honored: f1 asked for 3 tokens
    # but the coalesced batch decodes to max_new=7 — its row is trimmed
    assert f1.result(timeout=5) == [[1] * 3]
    assert f2.result(timeout=5) == [[2] * 7]
    assert f3.result(timeout=5) == [[3] * 7]
    assert f4.result(timeout=5) == [[4] * 7]
    assert sorted(batches) == [1, 3]  # [a,a,a] coalesced, [b] alone
    assert q.stats["coalesced_batches"] == 1
    assert q.stats["coalesced_requests"] == 3
    q.shutdown(timeout=5)


def test_max_coalesce_caps_batch_and_none_opts_out():
    batches = []

    def recording_runner(prompts, max_new):
        batches.append(len(prompts))
        return [list(p) for p in prompts]

    q = RequestQueue(recording_runner, max_depth=16, max_coalesce=2)
    futs = [q.submit([[i]], 4, coalesce_key=("k",)) for i in range(5)]
    solo = q.submit([[9]], 4, coalesce_key=None)  # opted out
    q.start()
    for f in futs + [solo]:
        f.result(timeout=5)
    assert max(batches) <= 2
    assert batches.count(1) >= 1  # the opted-out entry ran alone
    q.shutdown(timeout=5)


def test_client_batch_stays_atomic_through_coalescing():
    """A multi-prompt client request coalesces as a unit and its rows
    come back together, in order."""
    q = RequestQueue(echo_runner, max_depth=8, max_coalesce=4)
    pair = q.submit([[1], [2]], 5, coalesce_key=("k",))
    one = q.submit([[3]], 5, coalesce_key=("k",))
    q.start()
    assert pair.result(timeout=5) == [[1, 5], [2, 5]]
    assert one.result(timeout=5) == [[3, 5]]
    assert q.stats["coalesced_requests"] == 2
    q.shutdown(timeout=5)


def test_runner_error_fans_out_and_queue_survives():
    """A generation failure resolves every coalesced future with the
    error; the scheduler thread survives and serves the next request."""
    calls = {"n": 0}

    def flaky_runner(prompts, max_new):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected decode failure")
        return [list(p) for p in prompts]

    q = RequestQueue(flaky_runner, max_depth=8, max_coalesce=4)
    f1 = q.submit([[1]], 4, coalesce_key=("k",))
    f2 = q.submit([[2]], 4, coalesce_key=("k",))
    q.start()
    for f in (f1, f2):
        with pytest.raises(RuntimeError, match="injected"):
            f.result(timeout=5)
    assert q.stats["gen_errors"] == 1
    f3 = q.submit([[3]], 4)
    assert f3.result(timeout=5) == [[3]]
    q.shutdown(timeout=5)


def test_close_drains_admitted_work_then_rejects():
    """The graceful-drain contract: close() stops admission immediately,
    already-admitted entries still complete, join() observes the drain."""
    release = threading.Event()

    def gated_runner(prompts, max_new):
        release.wait(10)
        return [list(p) for p in prompts]

    q = RequestQueue(gated_runner, max_depth=8, max_coalesce=1).start()
    futs = [q.submit([[i]], 4) for i in range(3)]
    q.close()
    with pytest.raises(QueueClosed):
        q.submit([[9]], 4)
    assert q.stats["rejected_closed"] == 1
    assert not q.join(timeout=0.1)  # still draining (runner gated)
    release.set()
    assert q.join(timeout=5)  # drained: queue empty, scheduler exited
    for f in futs:
        assert f.result(timeout=1)  # every admitted request was answered


def test_forced_shutdown_flushes_waiting_entries():
    release = threading.Event()

    def gated_runner(prompts, max_new):
        release.wait(10)
        return [list(p) for p in prompts]

    q = RequestQueue(gated_runner, max_depth=8, max_coalesce=1).start()
    running = q.submit([[1]], 4)
    time.sleep(0.05)
    waiting = q.submit([[2]], 4)
    t = threading.Thread(target=q.shutdown,
                         kwargs={"drain": False, "timeout": 5})
    t.start()
    with pytest.raises(QueueClosed):
        waiting.result(timeout=5)  # flushed, not run
    release.set()
    t.join(timeout=5)
    assert running.result(timeout=5) == [[1]]  # in-flight still finishes


def test_busy_seconds_tracks_inflight_generation():
    release = threading.Event()

    def gated_runner(prompts, max_new):
        release.wait(10)
        return [list(p) for p in prompts]

    q = RequestQueue(gated_runner, max_depth=4).start()
    assert q.busy_seconds() == 0.0
    fut = q.submit([[1]], 4)
    time.sleep(0.2)
    assert q.busy_seconds() >= 0.1  # the watchdog's wedged-decode probe
    release.set()
    fut.result(timeout=5)
    time.sleep(0.05)
    assert q.busy_seconds() == 0.0
    q.shutdown(timeout=5)


def test_runner_row_count_mismatch_is_an_error():
    q = RequestQueue(lambda prompts, max_new: [], max_depth=4).start()
    fut = q.submit([[1]], 4)
    with pytest.raises(RuntimeError, match="0 rows for 1 prompts"):
        fut.result(timeout=5)
    q.shutdown(timeout=5)


def test_invalid_construction_and_submit():
    with pytest.raises(ValueError, match="max_depth"):
        RequestQueue(echo_runner, max_depth=0)
    with pytest.raises(ValueError, match="max_coalesce"):
        RequestQueue(echo_runner, max_coalesce=0)
    q = RequestQueue(echo_runner)
    with pytest.raises(ValueError, match="non-empty"):
        q.submit([], 4)
