"""Control-plane survivability units (`core/router.py` FleetJournal +
`core/controller.py` adoption, docs/serving.md "Control-plane
recovery"): the crash-consistent fleet journal (append / torn-tail
read / compaction / replay exact-fold), tenant bucket snapshot-restore
(no free burst window across a router death), supervisor re-adoption
by identity triple (replica_id + pid + boot_id — never bare pid),
controller clock restore, pre-spawn journaling order, and the
/admin/register self-registration surface — all in-process (no jax, no
model): the SIGKILL-the-router chaos drills live in
tests/test_ha_drills.py.
"""

import json
import logging
import os
import subprocess
import sys
import threading
import time
from contextlib import contextmanager
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from paddlefleetx_tpu.core.controller import (
    ElasticController,
    ReplicaSupervisor,
    ScalePolicy,
    _cmd_hash,
)
from paddlefleetx_tpu.core.router import (
    FleetJournal,
    RouterCore,
    read_fleet_journal,
    replay_fleet_state,
)
from paddlefleetx_tpu.core.tenancy import TenantAdmission, TenantConfig
from paddlefleetx_tpu.utils.telemetry import Registry


def _journal(tmp_path, **kw):
    return FleetJournal(str(tmp_path / "fleet_state.jsonl"), **kw)


@contextmanager
def _log_lines():
    """Capture repo-logger messages (it prints, propagate=False — a
    side handler is the only reliable tap under pytest's capture)."""
    from paddlefleetx_tpu.utils.log import logger as pfx_logger

    lines = []

    class Sink(logging.Handler):
        def emit(self, record):
            lines.append(record.getMessage())

    sink = Sink()
    pfx_logger.addHandler(sink)
    try:
        yield lines
    finally:
        pfx_logger.removeHandler(sink)


def _seed_records(j):
    """A representative record mix (every kind the router writes)."""
    j.record("replica", key="r0", url="http://127.0.0.1:9500",
             role="monolith", state="booting", why="registered")
    j.record("slot", pool="monolith", slot=0, port=9500,
             url="http://127.0.0.1:9500", rid="m0", cmd_hash="abc123def456",
             phase="spawning", pid=None, boot_id=None)
    j.record("slot", pool="monolith", slot=0, port=9500,
             url="http://127.0.0.1:9500", rid="m0", cmd_hash="abc123def456",
             phase="spawned", pid=4242, boot_id=None)
    j.record("replica", key="r0", url="http://127.0.0.1:9500",
             role="monolith", state="serving", why="healthy",
             replica_id="m0", pid=4242, boot_id="b0b0")
    j.record("scale", pool="monolith", action="hold", reason="steady",
             target=1, tick=3, serving=1, up_age_s=5.0, scale_age_s=5.0,
             idle_for_s=None)
    j.record("tenants",
             buckets={"gold": {"tokens": 1.5, "rate": 2.0, "burst": 4.0}},
             in_flight={"gold": 2})


# ---------------------------------------------------------------------------
# journal: append / read / replay round trip
# ---------------------------------------------------------------------------


def test_record_read_replay_roundtrip(tmp_path):
    j = _journal(tmp_path)
    _seed_records(j)
    records, note = read_fleet_journal(j.path)
    assert note is None and len(records) == 6
    st = replay_fleet_state(records)
    assert st["records"] == 6
    r0 = st["replicas"]["r0"]
    assert r0["state"] == "serving" and r0["pid"] == 4242
    assert r0["boot_id"] == "b0b0" and r0["replica_id"] == "m0"
    slot = st["slots"]["monolith"]["0"]
    assert slot["phase"] == "spawned" and slot["pid"] == 4242
    assert slot["cmd_hash"] == "abc123def456"
    ctl = st["controller"]["monolith"]
    assert ctl["target"] == 1 and ctl["tick"] == 3
    assert ctl["up_age_s"] == 5.0
    assert st["tenants"]["buckets"]["gold"]["tokens"] == 1.5
    assert st["tenants"]["in_flight"]["gold"] == 2
    # wall clock advances with the records (recovery ages buckets by it)
    assert st["wall"] == pytest.approx(time.time(), abs=30)


def test_missing_journal_is_empty_not_an_error(tmp_path):
    records, note = read_fleet_journal(str(tmp_path / "absent.jsonl"))
    assert records == [] and note is None
    assert replay_fleet_state([])["replicas"] == {}


def test_journal_gauges_ride_collect(tmp_path):
    j = _journal(tmp_path)
    _seed_records(j)
    got = dict((name, val) for name, _labels, val in j.collect())
    assert got["pfx_router_journal_records"] == 6.0
    assert got["pfx_router_journal_bytes"] == os.path.getsize(j.path)


def test_compaction_preserves_replay_equivalence(tmp_path):
    """THE compaction contract: replacing the append tail with one
    snapshot line must replay to the identical control-plane view."""
    j = _journal(tmp_path, snapshot_every=4)
    _seed_records(j)
    before = replay_fleet_state(read_fleet_journal(j.path)[0])
    # the snapshot_fn hands back live state; here: the folded view
    j.set_snapshot_fn(lambda: {
        "replicas": before["replicas"], "slots": before["slots"],
        "controller": before["controller"], "tenants": before["tenants"],
    })
    assert j.maybe_compact()  # 6 records >= snapshot_every=4 -> due
    records, note = read_fleet_journal(j.path)
    assert note is None
    assert len(records) == 1 and records[0]["kind"] == "snapshot"
    after = replay_fleet_state(records)
    for part in ("replicas", "slots", "controller", "tenants"):
        assert after[part] == before[part], part
    # the append counter reset; the next compaction is not due yet
    got = dict((name, val) for name, _labels, val in j.collect())
    assert got["pfx_router_journal_records"] == 0.0
    assert not j.maybe_compact()
    assert j.maybe_compact(force=True)  # force ignores the cadence


def test_compaction_without_snapshot_fn_is_a_noop(tmp_path):
    j = _journal(tmp_path, snapshot_every=1)
    _seed_records(j)
    assert not j.maybe_compact(force=True)
    assert len(read_fleet_journal(j.path)[0]) == 6


def test_record_survives_unwritable_path(tmp_path):
    """A dead disk must not take the control plane with it: record()
    warns once and keeps serving."""
    j = FleetJournal(str(tmp_path))  # a DIRECTORY: open(..., "a") fails
    with _log_lines() as lines:
        j.record("replica", key="r0", state="serving")
        j.record("replica", key="r0", state="gone")
    warns = [ln for ln in lines if "fleet journal write" in ln]
    assert len(warns) == 1  # once, not per-record
    assert "/admin/register" in warns[0]


# ---------------------------------------------------------------------------
# torn-tail + corruption fuzz (the PFXH1 idiom, control-plane edition)
# ---------------------------------------------------------------------------


def test_torn_tail_fuzz_truncation_at_every_byte(tmp_path):
    """Truncate the journal at EVERY byte offset: the read never
    raises, the recovered records are always a clean prefix, a torn
    tail is a loud note — and a half-written record never becomes a
    phantom replica."""
    j = _journal(tmp_path)
    _seed_records(j)
    data = open(j.path, "rb").read()
    full, _ = read_fleet_journal(j.path)
    full_keys = set(replay_fleet_state(full)["replicas"])
    torn = tmp_path / "torn.jsonl"
    for cut in range(len(data) + 1):
        torn.write_bytes(data[:cut])
        records, note = read_fleet_journal(str(torn))
        # a prefix, record-for-record — never a reordered or invented one
        assert records == full[:len(records)], cut
        # torn mid-record (some bytes past the last full line) -> loud
        consumed = sum(
            len(json.dumps(r, default=str)) + 1 for r in records)
        if cut > consumed and data[consumed:cut].strip():
            assert note is not None and "torn/corrupt" in note, cut
        # no phantom replicas out of half-written JSON
        assert set(replay_fleet_state(records)["replicas"]) <= full_keys


def test_mid_file_corruption_truncates_at_the_tear(tmp_path):
    """Bytes flipped MID-file: everything before the tear is trusted,
    everything after it is dropped (ordering past a corrupt line cannot
    be trusted), and the note says how much was lost."""
    j = _journal(tmp_path)
    _seed_records(j)
    lines = open(j.path, "rb").read().splitlines(keepends=True)
    lines[2] = b'{"kind": "slot", "pool": \xff\xfe GARBAGE\n'
    open(j.path, "wb").write(b"".join(lines))
    records, note = read_fleet_journal(j.path)
    assert len(records) == 2
    assert note is not None and "line 3" in note
    assert "dropped 4" in note  # the corrupt line + the 3 after it
    # a record that parses but is not a journal record is also a tear
    lines[2] = b'[1, 2, 3]\n'
    open(j.path, "wb").write(b"".join(lines))
    records, note = read_fleet_journal(j.path)
    assert len(records) == 2 and note is not None


# ---------------------------------------------------------------------------
# replay exact-fold against a LIVE RouterCore (the PR 8/11/12 contract)
# ---------------------------------------------------------------------------


def _core(tmp_path, **kw):
    kw.setdefault("allow_empty", True)
    core = RouterCore([], **kw)
    core.journal = _journal(tmp_path)
    return core


def test_replay_folds_registry_transitions_exactly(tmp_path):
    core = _core(tmp_path)
    k0 = core.add_replica("http://127.0.0.1:9500")
    k1 = core.add_replica("http://127.0.0.1:9501")
    with core._lock:
        r0 = core.replicas[k0]
        r0.replica_id, r0.pid, r0.boot_id = "m0", 111, "boot-a"
        core._transition(r0, "serving", "healthy")
        r1 = core.replicas[k1]
        core._transition(r1, "gone", "poll failures")
    st = replay_fleet_state(read_fleet_journal(core.journal.path)[0])
    views = {v["key"]: v for v in core.replica_views()}
    assert set(st["replicas"]) == set(views) == {k0, k1}
    for key, view in views.items():
        fold = st["replicas"][key]
        assert fold["state"] == view["state"], key
        assert fold["url"] == view["url"], key
    assert st["replicas"][k0]["pid"] == 111
    assert st["replicas"][k0]["boot_id"] == "boot-a"


def test_replay_folds_tenant_snapshot_and_restore_agrees(tmp_path):
    cfg = TenantConfig.from_obj(
        {"tenants": {"flood": {"rps": 2, "burst": 4}}})
    core = _core(tmp_path, tenant_config=cfg)
    for _ in range(3):
        core.acquire("flood")
        core.release("flood")
    core.journal.record("tenants", **core.tenant_journal_snapshot())
    st = replay_fleet_state(read_fleet_journal(core.journal.path)[0])
    snap = st["tenants"]["buckets"]["flood"]
    assert snap["rate"] == 2.0 and snap["burst"] == 4.0
    assert snap["tokens"] < 4.0  # the spend is in the journal
    # a fresh router restores the spend (age 0: no free refill)
    core2 = _core(tmp_path, tenant_config=cfg, name="router2")
    assert core2.restore_tenant_buckets(st["tenants"]["buckets"]) == 1
    got = core2.tenant_journal_snapshot()["buckets"]["flood"]
    assert got["tokens"] == pytest.approx(snap["tokens"], abs=0.1)


# ---------------------------------------------------------------------------
# tenant bucket restore semantics (the free-burst-window hole)
# ---------------------------------------------------------------------------


def test_restored_bucket_denies_the_free_burst_window():
    """A flooding tenant drained to zero tokens must still be rejected
    by the RESTARTED router: restore with age 0 resumes the drained
    bucket, it does not mint a fresh burst allowance."""
    cfg = TenantConfig.from_obj(
        {"tenants": {"flood": {"rps": 1, "burst": 2}}})
    clock = [100.0]
    adm = TenantAdmission(cfg, clock=lambda: clock[0])
    assert adm.admit("flood")[0] and adm.admit("flood")[0]
    ok, why, _retry = adm.admit("flood")
    assert not ok and why == "rate"  # the bucket is drained
    snap = adm.bucket_snapshot()
    # the restarted router, same instant: still over quota
    adm2 = TenantAdmission(cfg, clock=lambda: clock[0])
    assert adm2.restore_buckets(snap, age_s=0.0) == 1
    ok, why, _retry = adm2.admit("flood")
    assert not ok and why == "rate"
    # the death window earns EXACTLY its refill: 1s at 1 rps -> 1 admit
    adm3 = TenantAdmission(cfg, clock=lambda: clock[0])
    adm3.restore_buckets(snap, age_s=1.0)
    assert adm3.admit("flood")[0]
    assert not adm3.admit("flood")[0]


def test_restore_skips_tenants_the_current_config_freed():
    """The operator's NEW config wins: a journaled bucket for a tenant
    no longer rate-limited is skipped, and rate/burst always come from
    the current policy, not the journal."""
    old = TenantConfig.from_obj(
        {"tenants": {"a": {"rps": 1, "burst": 1}, "b": {"rps": 1}}})
    adm = TenantAdmission(old)
    adm.admit("a")
    adm.admit("b")
    snap = adm.bucket_snapshot()
    new = TenantConfig.from_obj({"tenants": {"a": {"rps": 5, "burst": 9}}})
    adm2 = TenantAdmission(new)
    assert adm2.restore_buckets(snap) == 1  # "b" skipped: unlimited now
    got = adm2.bucket_snapshot()
    assert set(got) == {"a"}
    assert got["a"]["rate"] == 5.0 and got["a"]["burst"] == 9.0


# ---------------------------------------------------------------------------
# supervisor re-adoption (identity triple, never bare pid)
# ---------------------------------------------------------------------------


class StubHealthz:
    """A /healthz-only replica stand-in publishing a mutable identity."""

    def __init__(self, identity):
        self.identity = dict(identity)
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = json.dumps(
                    {"ok": True, "state": "ok",
                     "identity": stub.identity}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _supervisor(base_port, reg=None, **kw):
    kw.setdefault("max_replicas", 2)
    return ReplicaSupervisor(
        "python serve.py --port {port} --replica-id {replica_id}",
        base_port=base_port, registry=reg or Registry(), **kw)


def test_adopt_full_identity_triple_match(tmp_path):
    stub = StubHealthz({"replica_id": "m0", "pid": os.getpid(),
                        "boot_id": "live-boot"})
    try:
        reg = Registry()
        sup = _supervisor(stub.port, reg)
        sup.journal = _journal(tmp_path)
        fact = {"pid": os.getpid(), "boot_id": "live-boot",
                "rid": "m0", "cmd_hash": "x"}
        adopted = sup.adopt({"0": fact, "1": {}})
        assert [m.slot for m in adopted] == [0]
        m = sup.slots[0]
        assert m.desired and not m.quarantined
        assert m.adopted_pid == os.getpid()
        assert m.adopted_boot_id == "live-boot"
        assert m.proc is None and m.restarts == 0  # zero restarts
        # the adoption is counted and journaled
        assert reg.counter("pfx_router_adopted_replicas_total",
                           replica="m0").get() == 1.0
        records, _ = read_fleet_journal(sup.journal.path)
        assert records[-1]["phase"] == "adopted"
        assert records[-1]["pid"] == os.getpid()
        # poll(): the adopted pid is alive -> nothing to do, no flap
        sup.poll()
        assert sup.slots[0].adopted_pid == os.getpid()
        assert not sup.slots[0].flap_exempt
    finally:
        stub.stop()


def test_adopt_wrong_boot_id_quarantines_never_bare_pid():
    """Same pid, DIFFERENT boot_id: the pid was recycled into a new
    process — adoption must refuse (bare-pid matching is the PR 11
    hole this closes) and quarantine the slot loudly rather than spawn
    into a bind collision."""
    stub = StubHealthz({"replica_id": "m0", "pid": os.getpid(),
                        "boot_id": "new-incarnation"})
    try:
        sup = _supervisor(stub.port)
        fact = {"pid": os.getpid(), "boot_id": "journaled-boot",
                "rid": "m0", "cmd_hash": "x"}
        with _log_lines() as lines:
            assert sup.adopt({"0": fact}) == []
        assert sup.slots[0].quarantined
        assert sup.slots[0].adopted_pid is None
        assert any("QUARANTINE" in ln for ln in lines)  # LOUD
    finally:
        stub.stop()


def test_adopt_wrong_replica_id_quarantines():
    stub = StubHealthz({"replica_id": "imposter", "pid": os.getpid(),
                        "boot_id": "b"})
    try:
        sup = _supervisor(stub.port)
        with _log_lines() as lines:
            assert sup.adopt({"0": {}}) == []
        assert sup.slots[0].quarantined
        assert any("QUARANTINE" in ln for ln in lines)
    finally:
        stub.stop()


def test_adopt_empty_fact_matches_on_replica_id(tmp_path):
    """The journal-lost path (self-registration rebuild): with no
    journaled identity facts, a process answering on OUR slot's port
    with OUR replica_id is the identity match."""
    stub = StubHealthz({"replica_id": "m0", "pid": 777, "boot_id": "b"})
    try:
        sup = _supervisor(stub.port)
        adopted = sup.adopt({"0": {}})
        assert [m.slot for m in adopted] == [0]
        assert sup.slots[0].adopted_pid == 777
    finally:
        stub.stop()


def test_adopt_silent_port_leaves_slot_for_ensure(tmp_path):
    """Nothing answering and no provably-ours corpse: the slot stays
    empty (not quarantined) for the normal ensure() respawn path."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    sup = _supervisor(dead_port)
    # a fact whose pid is long dead and whose cmd_hash matches nothing
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    assert sup.adopt(
        {"0": {"pid": proc.pid, "cmd_hash": "notourhash", "rid": "m0"}}
    ) == []
    m = sup.slots[0]
    assert not m.quarantined and not m.desired and m.adopted_pid is None


def test_adopted_exit_is_flap_exempt(tmp_path):
    """An adopted replica is not our child: its exit rc is
    unobservable, so its death schedules a flap-EXEMPT respawn — a
    router restart can never spend the fleet's flap budget."""
    sup = _supervisor(19999)
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()  # reaped: the pid is dead
    m = sup._slot(0)
    m.desired = True
    m.adopted_pid = proc.pid
    m.adopted_boot_id = "gone-boot"
    sup.poll(now=1000.0)
    assert m.adopted_pid is None and m.flap_exempt
    assert m.next_restart_t == 1000.0 + sup.backoff_base_s
    # an UNDESIRED adopted exit is just a drain completing
    m2 = sup._slot(1)
    m2.desired = False
    m2.adopted_pid = proc.pid
    sup.poll(now=1000.0)
    assert m2.adopted_pid is None and not m2.flap_exempt


# ---------------------------------------------------------------------------
# pre-spawn journaling order (no untracked-child window)
# ---------------------------------------------------------------------------


def test_spawning_record_lands_before_the_child_exists(tmp_path):
    """If the router dies between journaling and Popen returning, the
    next boot must still know the slot: the 'spawning' record is
    REQUIRED to be on disk before the child process is created."""
    j = _journal(tmp_path)
    seen_at_spawn = []

    class FakeProc:
        pid = 4242

        def poll(self):
            return None

    def spawn_fn(m):
        seen_at_spawn.append(read_fleet_journal(j.path)[0])
        return FakeProc()

    sup = _supervisor(9500, spawn_fn=spawn_fn, max_replicas=1)
    sup.journal = j
    sup.ensure(1)
    (at_spawn,) = seen_at_spawn
    assert [r["phase"] for r in at_spawn] == ["spawning"]
    assert at_spawn[0]["pid"] is None  # no child yet, by construction
    assert at_spawn[0]["cmd_hash"] == _cmd_hash(sup.slots[0].cmd)
    records, _ = read_fleet_journal(j.path)
    assert [r["phase"] for r in records] == ["spawning", "spawned"]
    assert records[1]["pid"] == 4242


# ---------------------------------------------------------------------------
# controller clock restore
# ---------------------------------------------------------------------------


class _StubCore:
    def replica_views(self):
        return []

    def add_replica(self, url, role="monolith"):
        return "r0"


def test_restore_clocks_holds_cooldowns_and_resets_idle(tmp_path):
    reg = Registry()
    sup = _supervisor(9500, reg, spawn_fn=lambda m: None)
    ctl = ElasticController(
        _StubCore(), sup,
        ScalePolicy(min_replicas=1, max_replicas=3, up_cooldown_s=30.0,
                    down_cooldown_s=60.0, idle_s=30.0),
        registry=reg)
    ctl.restore_clocks(target=2, tick=17, up_age_s=5.0, scale_age_s=5.0,
                       extra_age_s=2.0)
    now = time.monotonic()
    assert ctl.target == 2 and ctl._seq == 17
    # cooldown clocks rebased by journaled age + death window: 7s into
    # a 30s cooldown -> a restart can NOT insta-rescale
    assert now - ctl._last_up_t == pytest.approx(7.0, abs=0.5)
    assert now - ctl._last_scale_t == pytest.approx(7.0, abs=0.5)
    # idle dwell deliberately NOT restored: idleness was never observed
    # across the death window -> a restart can never open scale-down
    assert ctl._idle_since is None
    st = ctl.journal_state()
    assert st["target"] == 2 and st["tick"] == 17
    assert st["idle_for_s"] is None
    # target clamps into the CURRENT policy bounds; tick never rewinds
    ctl.restore_clocks(target=99, tick=3)
    assert ctl.target == 3 and ctl._seq == 17


# ---------------------------------------------------------------------------
# replica self-registration (POST /admin/register core surface)
# ---------------------------------------------------------------------------


def test_register_replica_idempotent_with_identity_refresh(tmp_path):
    core = _core(tmp_path)
    body = {"url": "http://127.0.0.1:9500/", "role": "monolith",
            "identity": {"replica_id": "m0", "pid": 321,
                         "boot_id": "bb", "started_at": 1700000000.0}}
    out = core.register_replica(body)
    assert out["key"] == "r0" and out["state"] == "booting"
    # the heartbeat is idempotent: same url -> same key, no second slot
    assert core.register_replica(body)["key"] == "r0"
    assert len(core.replica_views()) == 1
    v = core.replica_views()[0]
    assert v["pid"] == 321 and v["boot_id"] == "bb"
    assert v["replica_id"] == "m0"
    # the registration landed in the journal too (belt and braces)
    st = replay_fleet_state(read_fleet_journal(core.journal.path)[0])
    assert "r0" in st["replicas"]


def test_register_replica_rejects_malformed_urls(tmp_path):
    core = _core(tmp_path)
    for bad in ({}, {"url": ""}, {"url": "not a url"}):
        with pytest.raises(ValueError, match="url"):
            core.register_replica(bad)


def test_deregister_walks_gone_and_rejects_stale_goodbyes(tmp_path):
    core = _core(tmp_path)
    core.register_replica(
        {"url": "http://127.0.0.1:9500",
         "identity": {"replica_id": "m0", "boot_id": "current"}})
    # a STALE goodbye (previous incarnation's boot_id) must not eject
    # the current process
    with pytest.raises(ValueError, match="stale goodbye"):
        core.register_replica(
            {"deregister": True, "url": "http://127.0.0.1:9500",
             "identity": {"replica_id": "m0", "boot_id": "previous"}})
    assert core.replica_views()[0]["state"] != "gone"
    # an unknown url is a no-op answer, not an error (the replica may
    # have been ejected already)
    out = core.register_replica(
        {"deregister": True, "url": "http://127.0.0.1:9999"})
    assert out == {"key": None, "state": "unknown"}
    # the honest goodbye walks the replica to gone IMMEDIATELY — no
    # eject_after failed-poll wait
    out = core.register_replica(
        {"deregister": True, "url": "http://127.0.0.1:9500",
         "identity": {"replica_id": "m0", "boot_id": "current"}})
    assert out["state"] == "gone"
    assert core.replica_views()[0]["state"] == "gone"
