"""Config system tests (behavioral parity with reference utils/config.py)."""

import textwrap

import pytest

from paddlefleetx_tpu.utils.config import (
    AttrDict,
    get_config,
    override_config,
    parse_config,
    process_configs,
)


def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(textwrap.dedent(text))
    return str(p)


def test_attrdict_access():
    d = AttrDict.from_nested({"a": {"b": 1}, "c": [1, {"d": 2}]})
    assert d.a.b == 1
    assert d.c[1].d == 2
    d.a.e = 5
    assert d["a"]["e"] == 5


def test_base_inheritance(tmp_path):
    _write(tmp_path, "base.yaml", """
        Global:
          seed: 42
          global_batch_size: 8
        Model:
          hidden_size: 128
          num_layers: 2
    """)
    child = _write(tmp_path, "child.yaml", """
        _base_: ./base.yaml
        Model:
          num_layers: 4
    """)
    cfg = parse_config(child)
    assert cfg.Global.seed == 42          # inherited
    assert cfg.Model.hidden_size == 128   # inherited
    assert cfg.Model.num_layers == 4      # overridden


def test_inherited_optout(tmp_path):
    _write(tmp_path, "base.yaml", """
        Profiler:
          enable: true
        Global:
          seed: 1
    """)
    child = _write(tmp_path, "child.yaml", """
        _base_: ./base.yaml
        Profiler:
          _inherited_: False
    """)
    cfg = parse_config(child)
    assert "Profiler" not in cfg
    assert cfg.Global.seed == 1


def test_overrides():
    cfg = AttrDict.from_nested({"Model": {"hidden_size": 10}})
    override_config(cfg, ["Model.hidden_size=64", "Engine.max_steps=5", "Global.flag=true"])
    assert cfg.Model.hidden_size == 64
    assert cfg.Engine.max_steps == 5
    assert cfg.Global.flag is True


def test_dist_degree_inference():
    cfg = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": 16},
            "Distributed": {"mp_degree": 2, "pp_degree": 2},
        }
    )
    cfg = process_configs(cfg, num_devices=8)
    assert cfg.Distributed.dp_degree == 2  # 8 / (2*2)
    assert cfg.Global.local_batch_size == 8  # 16 / dp_world(2)
    assert cfg.Engine.accumulate_steps == 1


def test_batch_reconciliation_error():
    cfg = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": 16, "local_batch_size": 4},
            "Distributed": {},
        }
    )
    with pytest.raises(ValueError):
        process_configs(cfg, num_devices=2)  # 4*2 != 16


def test_micro_batch_accumulate():
    cfg = AttrDict.from_nested(
        {
            "Global": {"local_batch_size": 8, "micro_batch_size": 2},
            "Distributed": {},
        }
    )
    cfg = process_configs(cfg, num_devices=1)
    assert cfg.Engine.accumulate_steps == 4
    assert cfg.Global.global_batch_size == 8


def test_get_config_with_override(tmp_path):
    path = _write(tmp_path, "c.yaml", """
        Global:
          global_batch_size: 4
        Distributed:
          mp_degree: 1
    """)
    cfg = get_config(path, overrides=["Global.seed=7"], num_devices=1)
    assert cfg.Global.seed == 7
    assert cfg.Engine.mix_precision.dtype == "bfloat16"
