"""Pipeline-parallel tests: stage schedule output/grads match plain scan."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.models.gpt import model as gpt
from paddlefleetx_tpu.models.gpt.config import GPTConfig
from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
from paddlefleetx_tpu.parallel.pipeline import PipelineConfig
from paddlefleetx_tpu.parallel.sharding import make_rules, tree_logical_to_sharding

TINY = GPTConfig(
    vocab_size=128,
    hidden_size=64,
    num_layers=4,
    num_attention_heads=8,
    max_position_embeddings=32,
    hidden_dropout_prob=0.0,
    attention_probs_dropout_prob=0.0,
    dtype="float32",
)


def _ctx(devices, pp, extra=None, microbatches=None):
    mesh = build_mesh(
        MeshConfig(pp_degree=pp, **(extra or {"dp_degree": 8 // pp})), devices
    )
    rules = make_rules()
    ctx = gpt.ShardingCtx(
        mesh,
        rules,
        pipeline=PipelineConfig(num_stages=pp, num_microbatches=microbatches or pp),
    )
    return mesh, rules, ctx


@pytest.mark.parametrize("pp,extra", [
    (2, {"dp_degree": 4}),
    (4, {"dp_degree": 2}),
    (2, {"mp_degree": 2, "dp_degree": 2}),
])
def test_pipeline_loss_matches_scan(devices8, pp, extra):
    params = gpt.init(TINY, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, TINY.vocab_size)
    batch = {
        "tokens": tokens,
        "labels": jnp.roll(tokens, -1, 1),
        "loss_mask": jnp.ones((8, 16), jnp.float32),
    }
    ref = float(gpt.loss_fn(params, batch, TINY, train=False))

    mesh, rules, ctx = _ctx(devices8, pp, extra)
    shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(TINY), mesh, rules)
    p_sharded = jax.device_put(params, shardings)

    @jax.jit
    def f(p, b):
        return gpt.loss_fn(p, b, TINY, ctx=ctx, train=False)

    with mesh:
        got = float(f(p_sharded, batch))
    np.testing.assert_allclose(got, ref, rtol=2e-5)


def test_pipeline_grads_match_scan(devices8):
    params = gpt.init(TINY, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, TINY.vocab_size)
    batch = {
        "tokens": tokens,
        "labels": jnp.roll(tokens, -1, 1),
        "loss_mask": jnp.ones((8, 16), jnp.float32),
    }
    g_ref = jax.grad(lambda p: gpt.loss_fn(p, batch, TINY, train=False))(params)

    mesh, rules, ctx = _ctx(devices8, 2, {"dp_degree": 4})
    shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(TINY), mesh, rules)
    p_sharded = jax.device_put(params, shardings)

    with mesh:
        g = jax.jit(jax.grad(lambda p, b: gpt.loss_fn(p, b, TINY, ctx=ctx, train=False)))(
            p_sharded, batch
        )
    flat_ref = jax.tree.leaves(g_ref)
    flat = jax.tree.leaves(g)
    for a, b in zip(flat_ref, flat):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=5e-4, atol=1e-5)


def test_pipeline_more_microbatches(devices8):
    """M > S exercises the fill/steady/drain phases properly."""
    params = gpt.init(TINY, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, TINY.vocab_size)
    batch = {
        "tokens": tokens,
        "labels": jnp.roll(tokens, -1, 1),
        "loss_mask": jnp.ones((8, 16), jnp.float32),
    }
    ref = float(gpt.loss_fn(params, batch, TINY, train=False))
    mesh, rules, ctx = _ctx(devices8, 2, {"dp_degree": 4}, microbatches=4)
    shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(TINY), mesh, rules)
    with mesh:
        got = float(
            jax.jit(lambda p, b: gpt.loss_fn(p, b, TINY, ctx=ctx, train=False))(
                jax.device_put(params, shardings), batch
            )
        )
    np.testing.assert_allclose(got, ref, rtol=2e-5)


@pytest.mark.parametrize("pp,extra,mb,vpp", [
    (2, {"dp_degree": 4}, 2, 1),
    (2, {"dp_degree": 4}, 4, 1),          # M > S: steady-state 1F1B
    (4, {"dp_degree": 2}, 4, 1),
    (2, {"mp_degree": 2, "dp_degree": 2}, 2, 1),   # TP inside stages
    (2, {"dp_degree": 4}, 4, 2),          # interleaved virtual stages
])
def test_pipeline_1f1b_train_loss_and_grads(devices8, pp, extra, mb, vpp):
    """Training path: 1F1B schedule (grads computed inside the forward
    schedule via custom_vjp) matches single-device loss AND grads."""
    params = gpt.init(TINY, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, TINY.vocab_size)
    batch = {
        "tokens": tokens,
        "labels": jnp.roll(tokens, -1, 1),
        "loss_mask": jnp.ones((8, 16), jnp.float32),
    }
    ref_loss, g_ref = jax.value_and_grad(
        lambda p: gpt.loss_fn(p, batch, TINY, train=True)
    )(params)

    mesh, rules, ctx = _ctx(devices8, pp, extra, microbatches=mb)
    ctx = gpt.ShardingCtx(
        mesh, rules, pipeline=PipelineConfig(pp, mb, num_virtual_stages=vpp)
    )
    shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(TINY), mesh, rules)
    p_sharded = jax.device_put(params, shardings)
    with mesh:
        loss, g = jax.jit(
            jax.value_and_grad(
                lambda p, b: gpt.loss_fn(p, b, TINY, ctx=ctx, train=True)
            )
        )(p_sharded, batch)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=5e-4, atol=1e-5)


def test_pipeline_1f1b_bf16_params_grads(devices8):
    """bf16 params (multi_precision=False pairing): the 1F1B schedule must
    return bf16 cotangents matching the param dtype — the fp32 liveness
    mask and fp32 gbar scalar would otherwise promote the scan's grad
    carry and kill the compile (found by the 6.7B fit check, r5)."""
    import dataclasses

    cfg = dataclasses.replace(TINY, dtype="bfloat16")
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16), gpt.init(TINY, jax.random.key(0))
    )
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, cfg.vocab_size)
    batch = {
        "tokens": tokens,
        "labels": jnp.roll(tokens, -1, 1),
        "loss_mask": jnp.ones((8, 16), jnp.float32),
    }
    ref_loss = gpt.loss_fn(params, batch, cfg, train=True)

    mesh, rules, ctx = _ctx(devices8, 2, {"dp_degree": 4}, microbatches=2)
    shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(cfg), mesh, rules)
    p_sharded = jax.device_put(params, shardings)
    with mesh:
        loss, g = jax.jit(
            jax.value_and_grad(
                lambda p, b: gpt.loss_fn(p, b, cfg, ctx=ctx, train=True)
            )
        )(p_sharded, batch)
    # bf16 fwd: schedules agree to bf16 tolerance
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-2)
    for leaf in jax.tree.leaves(g):
        assert leaf.dtype == jnp.bfloat16
        assert bool(jnp.isfinite(leaf.astype(jnp.float32)).all())


def test_pipeline_1f1b_masked_loss(devices8):
    """Partial loss_mask: the in-schedule numerator / global denominator
    decomposition must reproduce the global masked mean."""
    params = gpt.init(TINY, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, TINY.vocab_size)
    mask = (jax.random.uniform(jax.random.key(3), (8, 16)) > 0.4).astype(jnp.float32)
    batch = {"tokens": tokens, "labels": jnp.roll(tokens, -1, 1), "loss_mask": mask}
    ref = float(gpt.loss_fn(params, batch, TINY, train=True))
    mesh, rules, ctx = _ctx(devices8, 2, {"dp_degree": 4}, microbatches=4)
    shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(TINY), mesh, rules)
    with mesh:
        got = float(
            jax.jit(lambda p, b: gpt.loss_fn(p, b, TINY, ctx=ctx, train=True))(
                jax.device_put(params, shardings), batch
            )
        )
    np.testing.assert_allclose(got, ref, rtol=2e-5)


def test_indivisible_layers_raises(devices8):
    cfg = GPTConfig(**{**TINY.__dict__, "num_layers": 3})
    params = gpt.init(cfg, jax.random.key(0))
    mesh, rules, ctx = _ctx(devices8, 2, {"dp_degree": 4})
    batch = {
        "tokens": jnp.zeros((8, 16), jnp.int32),
        "labels": jnp.zeros((8, 16), jnp.int32),
    }
    with pytest.raises(ValueError, match="not divisible"):
        with mesh:
            gpt.loss_fn(params, batch, cfg, ctx=ctx, train=False)
