"""THE fleet-observability acceptance drill (`make test-fleet-obs`):
one request driven through router -> prefill -> direct handoff ->
decode through the REAL CLIs yields

  - ONE stitched, Perfetto-loadable timeline at the router containing
    spans from all three processes, correctly ordered after wall-clock
    anchoring (remote spans inside the request window, prefill leg
    before decode leg, per-lane nesting strict-validated);
  - a federated `pfx_fleet_*` scrape on the router that agrees EXACTLY
    with each replica's own `/metrics` for spot-checked counters, with
    a live staleness gauge per replica;
  - a `tools/report.py --fleet` render off the router's artifacts alone.

Reuses tests/test_disagg_drills' tiny config + helpers so the jax
compiles ride the shared persistent cache."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest
import yaml

pytestmark = pytest.mark.fault

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from test_disagg_drills import (  # noqa: E402 — shared drill helpers
    SYS,
    TINY,
    _env,
    _finish,
    _free_port,
    _get,
    _lab,
    _metrics,
    _post,
    _spawn_replica,
    _wait_eligible,
    _wait_healthy,
)
from test_tracing import validate_chrome_trace  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_router(port, *args, env_extra=None):
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "router.py"),
         "--port", str(port), "--poll-interval", "0.2",
         "--eject-after", "3", *args],
        env=_env(env_extra), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )


def _fed_value(m, sample_name, **labels):
    """One federated sample off a parsed router /metrics dump (the
    original sample name rides the `name` label)."""
    want = frozenset(
        [("name", sample_name)]
        + [(k, str(v)) for k, v in labels.items()]
    )
    return m.get("pfx_fleet_metric", {}).get(want, 0.0)


def test_stitched_trace_and_federated_scrape_through_real_clis(tmp_path):
    cfg_path = tmp_path / "tiny_fleet.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    pre_p, dec_p = _free_port(), _free_port()
    pre = _spawn_replica(cfg_path, pre_p, "--role", "prefill",
                         "--replica-id", "pre0")
    dec = _spawn_replica(cfg_path, dec_p, "--role", "decode",
                         "--cb-batch", "4", "--replica-id", "dec0")
    rport = _free_port()
    flight_dir = tmp_path / "router-artifacts"
    router = None
    try:
        _wait_healthy([(pre_p, pre), (dec_p, dec)])
        # satellite: /healthz?metrics=1 renders the exposition from the
        # SAME snapshot as the scoring fields — and the TTFT/latency
        # fields the fleet log records are on the plain view too
        h = _get(dec_p, "/healthz?metrics=1")
        assert "metrics_text" in h and "pfx_queue_depth" in h["metrics_text"]
        assert "ttft_p99_s" in h and "latency_p99_s" in h
        assert "metrics_text" not in _get(dec_p, "/healthz")

        router = _spawn_router(
            rport,
            "--prefill", f"http://127.0.0.1:{pre_p}",
            "--decode", f"http://127.0.0.1:{dec_p}",
            "--handoff", "direct",
            env_extra={"PFX_FLIGHT_DIR": str(flight_dir)},
        )
        _wait_eligible(rport, 2, proc=router)

        body = {"prompt_ids": SYS + [40, 41, 42], "max_tokens": 6,
                "deadline_s": 60}
        code, resp = _post(rport, body)
        assert code == 200, resp
        trace_id = resp.get("trace_id")
        assert trace_id, "the router's 200 must carry the stitched handle"
        # direct-transfer determinism stays tier-1-drilled here (the
        # disagg byte-bypass drill is slow-marked against this one):
        # a repeat request through the same chain is token-identical
        code2, repeat = _post(rport, body)
        assert code2 == 200
        assert repeat["completion_ids"] == resp["completion_ids"]

        # ---- ONE stitched timeline with spans from all three
        # processes, ordered after wall-clock anchoring ----
        tl = _get(rport, f"/debug/trace?id={trace_id}")
        assert tl["trace_id"] == trace_id
        names = [e["name"] for e in tl["events"]]
        assert "route" in names and "routed" in names  # the router's leg
        by_role = {}
        for e in tl["events"]:
            proc = e.get("proc")
            if proc:
                by_role.setdefault(proc["role"], []).append(e)
        assert set(by_role) == {"prefill", "decode"}, names
        assert by_role["prefill"][0]["proc"]["replica_id"] == "pre0"
        assert by_role["decode"][0]["proc"]["replica_id"] == "dec0"
        # distinct real pids: three processes on one timeline
        pids = {e["proc"]["pid"] for evs in by_role.values() for e in evs}
        assert len(pids) == 2 and os.getpid() not in pids
        # anchored ordering: every remote span inside the request
        # window (the envelope skew rule's guarantee)...
        total = tl["total_s"]
        for evs in by_role.values():
            for e in evs:
                assert -1e-3 <= e["at_s"], e
                assert e["at_s"] + e["dur_s"] <= total + 1e-3, (e, total)
        # ...and the prefill leg STARTS before the decode leg (the
        # direct handoff hands off after the export)
        t_pre = min(e["at_s"] for e in by_role["prefill"])
        t_dec = min(e["at_s"] for e in by_role["decode"])
        assert t_pre <= t_dec, (t_pre, t_dec)
        # the four runbook questions are answerable off this ONE
        # timeline: queue-at-router (router route gap), prefill compute
        # (the export span), handoff transfer (the lane gap), decode
        # adoption + chunks (adopt span + chunk instants)
        pre_names = {e["name"] for e in by_role["prefill"]}
        dec_names = {e["name"] for e in by_role["decode"]}
        assert "queue_wait" in pre_names and "prefill_export" in pre_names
        assert "adopt" in dec_names, dec_names
        assert "decode_chunk" in dec_names, dec_names
        # summaries are bounded + aggregated: dense chunk instants
        # arrive aggregated (count + summed committed) past the
        # threshold, individual below it — either way the committed
        # sum covers every delivered token
        chunks = [e for e in by_role["decode"]
                  if e["name"] == "decode_chunk"]
        committed = sum(e["args"].get("committed", 0) for e in chunks)
        assert committed >= len(resp["completion_ids"]), (
            committed, len(resp["completion_ids"]), chunks,
        )

        # the whole window is Perfetto-loadable with one pid lane per
        # process (per-lane nesting strict-validated)
        req = urllib.request.Request(
            f"http://127.0.0.1:{rport}/debug/traces")
        with urllib.request.urlopen(req, timeout=10) as r:
            doc = json.load(r)
        lanes = validate_chrome_trace(doc)
        span_pids = {pid for pid, _ in lanes}
        assert len(span_pids) >= 3, span_pids  # router + both replicas
        meta_names = {e["args"]["name"] for e in doc["traceEvents"]
                      if e["ph"] == "M"}
        assert any("pre0" in n for n in meta_names), meta_names
        assert any("dec0" in n for n in meta_names), meta_names

        # ---- federation agreement: the router's pfx_fleet_* scrape
        # == each replica's own /metrics for spot-checked counters ----
        views = _get(rport, "/replicas")["replicas"]
        key_by_role = {v["role"]: v["key"] for v in views}
        deadline = time.time() + 30
        while True:
            rm = _metrics(rport)
            pre_m, dec_m = _metrics(pre_p), _metrics(dec_p)
            want = [
                (key_by_role["prefill"], "prefill",
                 "pfx_handoff_exports_total",
                 pre_m.get("pfx_handoff_exports_total",
                           {}).get(frozenset(), 0.0)),
                (key_by_role["decode"], "decode",
                 "pfx_handoff_adopts_total",
                 dec_m.get("pfx_handoff_adopts_total",
                           {}).get(frozenset(), 0.0)),
                (key_by_role["decode"], "decode",
                 "pfx_serving_tokens_out_total",
                 dec_m.get("pfx_serving_tokens_out_total",
                           {}).get(frozenset(), 0.0)),
            ]
            if all(
                _fed_value(rm, name, replica=key, pool=pool) == own
                for key, pool, name, own in want
            ):
                break
            assert time.time() < deadline, (
                "federated scrape never agreed with the replicas",
                want,
                {k: v for k, v in rm.get("pfx_fleet_metric", {}).items()
                 if dict(k).get("name", "").startswith("pfx_handoff")},  # noqa — prefix filter, not a metric name
            )
            time.sleep(0.3)
        assert want[0][3] >= 1.0 and want[1][3] >= 1.0  # non-vacuous
        # staleness gauge: fresh for both replicas; scrape outcomes ok
        for key in key_by_role.values():
            age = _lab(rm, "pfx_fleet_scrape_age_seconds", replica=key)
            assert 0.0 <= age < 10.0, (key, age)
            assert _lab(rm, "pfx_fleet_scrapes_total",
                        replica=key, outcome="ok") >= 1.0
        # the cap did not bite at this fleet size
        assert rm["pfx_fleet_series_dropped"][frozenset()] == 0.0
        assert rm["pfx_fleet_series"][frozenset()] > 50.0
        # direct transport cross-check off the SAME scrape: the payload
        # provably bypassed the router (its own byte counter flat, the
        # replicas' direct-transport bytes federated non-zero)
        assert rm["pfx_router_handoff_bytes_total"][frozenset()] == 0.0
        assert _fed_value(
            rm, "pfx_handoff_bytes_total",
            replica=key_by_role["decode"], pool="decode",
            transport="direct",
        ) > 0.0

        # ---- fleet report renders from the router's artifacts alone ----
        fleet_jsonl = flight_dir / "fleet_metrics.jsonl"
        deadline = time.time() + 15
        while not fleet_jsonl.exists() and time.time() < deadline:
            time.sleep(0.3)
        assert fleet_jsonl.exists(), list(flight_dir.glob("*"))
        out = tmp_path / "fleet.html"
        rep = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "report.py"),
             "--fleet", str(fleet_jsonl), "-o", str(out)],
            capture_output=True, text=True, timeout=60,
        )
        assert rep.returncode == 0, rep.stderr
        doc = out.read_text()
        assert key_by_role["prefill"] in doc and key_by_role["decode"] in doc
        assert "TTFT p99" in doc

        for proc in (router, pre, dec):
            proc.send_signal(signal.SIGTERM)
        for proc in (router, pre, dec):
            assert proc.wait(timeout=60) == 0
    finally:
        logs = [_finish(p) for p in (pre, dec)]
        logs += [_finish(router)]
    for log in logs:
        assert "Traceback" not in log, log[-3000:]
