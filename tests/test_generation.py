"""Generation tests: cached decode == uncached forward; sampling ops."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.models.gpt import model as gpt
from paddlefleetx_tpu.models.gpt.config import GPTConfig
from paddlefleetx_tpu.models.gpt.generation import (
    GenerationConfig,
    forward_cached,
    generate,
    init_cache,
)
from paddlefleetx_tpu.ops.sampling import sample_top_p, top_k_filter, top_p_filter

TINY = GPTConfig(
    vocab_size=97,
    hidden_size=64,
    num_layers=2,
    num_attention_heads=8,
    max_position_embeddings=64,
    hidden_dropout_prob=0.0,
    attention_probs_dropout_prob=0.0,
    dtype="float32",
)


def test_cached_prefill_matches_forward():
    params = gpt.init(TINY, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, TINY.vocab_size)
    ref = gpt.forward(params, tokens, TINY, train=False)
    cache = init_cache(TINY, 2, 32)
    got, _ = forward_cached(params, tokens, cache, jnp.int32(0), TINY)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_incremental_decode_matches_full_forward():
    """Token-by-token cached decode must equal the full uncached forward."""
    params = gpt.init(TINY, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (1, 12), 0, TINY.vocab_size)

    ref = gpt.forward(params, tokens, TINY, train=False)

    cache = init_cache(TINY, 1, 16)
    logits_steps = []
    for t in range(12):
        lg, cache = forward_cached(params, tokens[:, t : t + 1], cache, jnp.int32(t), TINY)
        logits_steps.append(lg[:, 0])
    got = jnp.stack(logits_steps, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=3e-4, atol=3e-4)


def test_greedy_generation_deterministic():
    params = gpt.init(TINY, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, TINY.vocab_size)
    gen = GenerationConfig(max_dec_len=10, decode_strategy="greedy_search", eos_token_id=-1)
    out1 = generate(params, prompt, TINY, gen)
    out2 = generate(params, prompt, TINY, gen)
    assert out1.shape == (2, 10)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


@pytest.mark.slow  # ~9s (unjitted per-step full-forward python rollout);
# tier-1 budget funding for the shard_map-port tests.  Replacement
# coverage: cached-vs-uncached logits parity stays tier-1 at every decode
# step via test_incremental_decode_matches_full_forward, and greedy
# token-level parity stays tier-1 via test_bucketed_greedy_matches_unpadded
# + test_tp_generation_parity; still in make test-all.
def test_greedy_matches_uncached_argmax_rollout():
    params = gpt.init(TINY, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (1, 6), 0, TINY.vocab_size)
    gen = GenerationConfig(max_dec_len=6, decode_strategy="greedy_search", eos_token_id=-1)
    out = np.asarray(generate(params, prompt, TINY, gen))[0]

    # slow rollout with full forward each step
    seq = np.asarray(prompt)[0].tolist()
    for _ in range(6):
        logits = gpt.forward(params, jnp.asarray([seq]), TINY, train=False)
        seq.append(int(jnp.argmax(logits[0, -1])))
    np.testing.assert_array_equal(out, np.asarray(seq[6:]))


def test_eos_stops_and_pads():
    params = gpt.init(TINY, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (1, 4), 0, TINY.vocab_size)
    # force eos = the greedy-argmax first token -> everything after is pad
    gen0 = GenerationConfig(max_dec_len=5, decode_strategy="greedy_search", eos_token_id=-1)
    first = int(np.asarray(generate(params, prompt, TINY, gen0))[0, 0])
    gen = GenerationConfig(
        max_dec_len=5, decode_strategy="greedy_search", eos_token_id=first, pad_token_id=0,
        min_dec_len=0,
    )
    out = np.asarray(generate(params, prompt, TINY, gen))[0]
    assert out[0] == first
    assert np.all(out[1:] == 0)


def test_top_k_filter():
    logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0]])
    f = top_k_filter(logits, 2)
    assert float(f[0, 1]) == 5.0 and float(f[0, 2]) == 3.0
    assert float(f[0, 0]) < -1e9 and float(f[0, 3]) < -1e9


def test_top_p_filter_keeps_nucleus():
    probs = jnp.asarray([[0.5, 0.3, 0.15, 0.05]])
    logits = jnp.log(probs)
    f = top_p_filter(logits, 0.7)
    # 0.5 alone < 0.7, 0.5+0.3 crosses -> keep first two
    assert np.isfinite(np.asarray(f)[0, :2]).all()
    assert np.asarray(f)[0, 2] < -1e9 and np.asarray(f)[0, 3] < -1e9


def test_sample_top_p_distribution():
    probs = jnp.tile(jnp.asarray([[0.6, 0.25, 0.1, 0.05]]), (2000, 1))
    ids = sample_top_p(jax.random.key(0), probs, jnp.full((2000,), 0.7))
    vals, counts = np.unique(np.asarray(ids), return_counts=True)
    assert set(vals.tolist()) <= {0, 1}  # nucleus = {0.6, 0.25}
    frac0 = counts[vals.tolist().index(0)] / 2000
    assert abs(frac0 - 0.6 / 0.85) < 0.05


# ---------------------------------------------------------------------------
# Beam search + processors + TP serving
# ---------------------------------------------------------------------------


def test_beam_search_shapes_and_determinism():
    params = gpt.init(TINY, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, TINY.vocab_size)
    gen = GenerationConfig(
        max_dec_len=8, decode_strategy="beam_search", num_beams=4, eos_token_id=96
    )
    out1 = generate(params, prompt, TINY, gen)
    out2 = generate(params, prompt, TINY, gen)
    assert out1.shape == (2, 8)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_beam1_matches_greedy_prefix():
    """num_beams=1 beam search follows the same argmax path as greedy while
    EOS is suppressed (min_dec_len)."""
    params = gpt.init(TINY, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(2), (2, 6), 0, TINY.vocab_size)
    n = 8
    g_greedy = GenerationConfig(
        max_dec_len=n, min_dec_len=n, decode_strategy="greedy_search",
        eos_token_id=96,
    )
    g_beam = GenerationConfig(
        max_dec_len=n, min_dec_len=n, decode_strategy="beam_search",
        num_beams=1, eos_token_id=96,
    )
    a = np.asarray(generate(params, prompt, TINY, g_greedy))
    b = np.asarray(generate(params, prompt, TINY, g_beam))
    np.testing.assert_array_equal(a[:, : n - 1], b[:, : n - 1])


def test_beam_score_improves_on_greedy():
    """Beam search's chosen sequence log-prob >= the greedy path's.

    NB: beam search does not guarantee this in general (the greedy prefix
    can be evicted from the top-K mid-decode); the fixed seed/model here is
    known to keep the property — if a numeric change flips it, check the
    eviction explanation before suspecting the beam code."""
    params = gpt.init(TINY, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(3), (1, 6), 0, TINY.vocab_size)
    n = 6

    def score(seq):
        """Sum log p of continuation `seq` after `prompt` (teacher forced)."""
        full = jnp.concatenate([prompt, seq[None]], axis=1)
        logits = gpt.forward(params, full, TINY, train=False)
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
        cont = lp[0, prompt.shape[1] - 1 :]
        return float(
            sum(cont[t, int(seq[t])] for t in range(n))
        )

    g_greedy = GenerationConfig(
        max_dec_len=n, min_dec_len=n, decode_strategy="greedy_search", eos_token_id=96
    )
    g_beam = GenerationConfig(
        max_dec_len=n, min_dec_len=n, decode_strategy="beam_search",
        num_beams=4, eos_token_id=96,
    )
    s_greedy = score(np.asarray(generate(params, prompt, TINY, g_greedy))[0])
    s_beam = score(np.asarray(generate(params, prompt, TINY, g_beam))[0])
    assert s_beam >= s_greedy - 1e-4


def test_forced_bos_eos_tokens():
    params = gpt.init(TINY, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(4), (2, 4), 0, TINY.vocab_size)
    gen = GenerationConfig(
        max_dec_len=6, decode_strategy="greedy_search", eos_token_id=-1,
        forced_bos_token_id=11, forced_eos_token_id=13,
    )
    out = np.asarray(generate(params, prompt, TINY, gen))
    np.testing.assert_array_equal(out[:, 0], 11)
    np.testing.assert_array_equal(out[:, -1], 13)


def test_hamming_diversity_penalizes_decided_tokens():
    """Tokens chosen by earlier groups this step must be penalized out of
    the argmax for the current group (HammingDiversityLogitsProcessor)."""
    from paddlefleetx_tpu.models.gpt.generation import apply_hamming_diversity

    vocab = 16
    logits = jnp.zeros((2, vocab)).at[:, 5].set(1.0).at[:, 7].set(0.9)
    # groups 0..1 (beams 0,1) already chose token 5 this step; beam 2+ TBD
    current = jnp.array([5, 5, -1, -1], jnp.int32)
    out = apply_hamming_diversity(logits, current, group_start=2, penalty=10.0)
    # token 5 penalized twice -> argmax moves to 7
    assert int(jnp.argmax(out[0])) == 7
    # penalty counts only DECIDED beams (indices < group_start)
    np.testing.assert_allclose(float(logits[0, 5]) - float(out[0, 5]), 20.0)
    # undecided sentinel (-1) contributes nothing
    np.testing.assert_allclose(np.asarray(out[:, :vocab - 1][:, 6:]),
                               np.asarray(logits[:, 6:vocab - 1]), atol=1e-6)


def test_diverse_beam_search_runs_e2e():
    params = gpt.init(TINY, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(5), (1, 4), 0, TINY.vocab_size)
    n = 4
    gen = GenerationConfig(
        max_dec_len=n, min_dec_len=n, decode_strategy="beam_search",
        num_beams=4, num_beam_groups=4, diversity_penalty=1.5, eos_token_id=96,
    )
    out = np.asarray(generate(params, prompt, TINY, gen))
    assert out.shape == (1, n)


TINY_TP = GPTConfig(
    vocab_size=96,  # divisible by mp=2 (the vocab axis is model-sharded)
    hidden_size=64,
    num_layers=2,
    num_attention_heads=8,
    max_position_embeddings=64,
    hidden_dropout_prob=0.0,
    attention_probs_dropout_prob=0.0,
    dtype="float32",
)


def test_tp_generation_parity(devices8):
    """generate() on a dp4 x mp2 mesh (heads-sharded KV cache) must equal
    the single-device greedy rollout (VERDICT r1 item 5)."""
    from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
    from paddlefleetx_tpu.parallel.sharding import make_rules, tree_logical_to_sharding

    params = gpt.init(TINY_TP, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(6), (2, 8), 0, TINY_TP.vocab_size)
    gen = GenerationConfig(max_dec_len=8, decode_strategy="greedy_search", eos_token_id=-1)
    ref = np.asarray(generate(params, prompt, TINY_TP, gen))

    mesh = build_mesh(MeshConfig(dp_degree=4, mp_degree=2), devices8)
    rules = make_rules(mesh=mesh)
    ctx = gpt.ShardingCtx(mesh, rules)
    shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(TINY_TP), mesh, rules)
    p_sh = jax.device_put(params, shardings)
    with mesh:
        got = np.asarray(
            jax.jit(lambda p, x: generate(p, x, TINY_TP, gen, ctx=ctx))(p_sh, prompt)
        )
    np.testing.assert_array_equal(got, ref)


def test_tp_beam_search_parity(devices8):
    """Beam search on a TP mesh equals single-device beam search.

    Was xfailed since PR 1 as a "jax-0.4.37 TP numerics divergence" —
    root-caused in the shard_map-port PR: GSPMD left the beam scan's
    bookkeeping carry marked partial-over-`model` (every emitted token id
    came back exactly mp_degree x the true value); generation.beam_search
    now pins the carry sharding each step (`_pin_beam`)."""
    from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
    from paddlefleetx_tpu.parallel.sharding import make_rules, tree_logical_to_sharding

    params = gpt.init(TINY_TP, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(7), (1, 6), 0, TINY_TP.vocab_size)
    gen = GenerationConfig(
        max_dec_len=6, decode_strategy="beam_search", num_beams=4, eos_token_id=96
    )
    ref = np.asarray(generate(params, prompt, TINY_TP, gen))
    mesh = build_mesh(MeshConfig(dp_degree=4, mp_degree=2), devices8)
    rules = make_rules(mesh=mesh)
    ctx = gpt.ShardingCtx(mesh, rules)
    shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(TINY_TP), mesh, rules)
    p_sh = jax.device_put(params, shardings)
    with mesh:
        got = np.asarray(
            jax.jit(lambda p, x: generate(p, x, TINY_TP, gen, ctx=ctx))(p_sh, prompt)
        )
    np.testing.assert_array_equal(got, ref)


# ---------------------------------------------------------------------------
# Bucketed serving: left-padded prompts (VERDICT r1 weak #4)
# ---------------------------------------------------------------------------


def test_bucketed_greedy_matches_unpadded():
    """Left-padded bucketed prompts must generate exactly what each prompt
    generates unpadded (mask + position-id correctness)."""
    from paddlefleetx_tpu.models.gpt.generation import pad_prompts

    params = gpt.init(TINY, jax.random.key(0))
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, TINY.vocab_size, n).tolist() for n in (5, 9, 12)
    ]
    gen = GenerationConfig(
        max_dec_len=8, decode_strategy="greedy_search", eos_token_id=-1,
        pad_token_id=0,
    )
    # reference: each prompt alone, unpadded
    refs = [
        np.asarray(generate(params, jnp.asarray([p]), TINY, gen))[0]
        for p in prompts
    ]
    padded, lens = pad_prompts(prompts, pad_token_id=0, multiple=16)
    assert padded.shape[1] == 16  # one bucket
    out = np.asarray(
        generate(params, padded, TINY, gen, prompt_lens=lens)
    )
    for i, r in enumerate(refs):
        np.testing.assert_array_equal(out[i], r)


def test_bucketed_beam_matches_unpadded():
    from paddlefleetx_tpu.models.gpt.generation import pad_prompts

    params = gpt.init(TINY, jax.random.key(0))
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, TINY.vocab_size, n).tolist() for n in (4, 7)]
    gen = GenerationConfig(
        max_dec_len=6, decode_strategy="beam_search", num_beams=4,
        eos_token_id=96, pad_token_id=0,
    )
    refs = [
        np.asarray(generate(params, jnp.asarray([p]), TINY, gen))[0]
        for p in prompts
    ]
    padded, lens = pad_prompts(prompts, pad_token_id=0, multiple=8)
    out = np.asarray(generate(params, padded, TINY, gen, prompt_lens=lens))
    for i, r in enumerate(refs):
        np.testing.assert_array_equal(out[i], r)


def test_pad_prompts_bucket_width():
    from paddlefleetx_tpu.models.gpt.generation import pad_prompts

    padded, lens = pad_prompts([[1, 2, 3], [4] * 70], pad_token_id=0, multiple=64)
    assert padded.shape == (2, 128)
    assert lens.tolist() == [3, 70]
    assert padded[0, :125].sum() == 0  # left padding
    assert padded[0, 125:].tolist() == [1, 2, 3]
