"""Generation tests: cached decode == uncached forward; sampling ops."""

import jax
import jax.numpy as jnp
import numpy as np

from paddlefleetx_tpu.models.gpt import model as gpt
from paddlefleetx_tpu.models.gpt.config import GPTConfig
from paddlefleetx_tpu.models.gpt.generation import (
    GenerationConfig,
    forward_cached,
    generate,
    init_cache,
)
from paddlefleetx_tpu.ops.sampling import sample_top_p, top_k_filter, top_p_filter

TINY = GPTConfig(
    vocab_size=97,
    hidden_size=64,
    num_layers=2,
    num_attention_heads=8,
    max_position_embeddings=64,
    hidden_dropout_prob=0.0,
    attention_probs_dropout_prob=0.0,
    dtype="float32",
)


def test_cached_prefill_matches_forward():
    params = gpt.init(TINY, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, TINY.vocab_size)
    ref = gpt.forward(params, tokens, TINY, train=False)
    cache = init_cache(TINY, 2, 32)
    got, _ = forward_cached(params, tokens, cache, jnp.int32(0), TINY)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_incremental_decode_matches_full_forward():
    """Token-by-token cached decode must equal the full uncached forward."""
    params = gpt.init(TINY, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (1, 12), 0, TINY.vocab_size)

    ref = gpt.forward(params, tokens, TINY, train=False)

    cache = init_cache(TINY, 1, 16)
    logits_steps = []
    for t in range(12):
        lg, cache = forward_cached(params, tokens[:, t : t + 1], cache, jnp.int32(t), TINY)
        logits_steps.append(lg[:, 0])
    got = jnp.stack(logits_steps, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=3e-4, atol=3e-4)


def test_greedy_generation_deterministic():
    params = gpt.init(TINY, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, TINY.vocab_size)
    gen = GenerationConfig(max_dec_len=10, decode_strategy="greedy_search", eos_token_id=-1)
    out1 = generate(params, prompt, TINY, gen)
    out2 = generate(params, prompt, TINY, gen)
    assert out1.shape == (2, 10)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_greedy_matches_uncached_argmax_rollout():
    params = gpt.init(TINY, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (1, 6), 0, TINY.vocab_size)
    gen = GenerationConfig(max_dec_len=6, decode_strategy="greedy_search", eos_token_id=-1)
    out = np.asarray(generate(params, prompt, TINY, gen))[0]

    # slow rollout with full forward each step
    seq = np.asarray(prompt)[0].tolist()
    for _ in range(6):
        logits = gpt.forward(params, jnp.asarray([seq]), TINY, train=False)
        seq.append(int(jnp.argmax(logits[0, -1])))
    np.testing.assert_array_equal(out, np.asarray(seq[6:]))


def test_eos_stops_and_pads():
    params = gpt.init(TINY, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (1, 4), 0, TINY.vocab_size)
    # force eos = the greedy-argmax first token -> everything after is pad
    gen0 = GenerationConfig(max_dec_len=5, decode_strategy="greedy_search", eos_token_id=-1)
    first = int(np.asarray(generate(params, prompt, TINY, gen0))[0, 0])
    gen = GenerationConfig(
        max_dec_len=5, decode_strategy="greedy_search", eos_token_id=first, pad_token_id=0,
        min_dec_len=0,
    )
    out = np.asarray(generate(params, prompt, TINY, gen))[0]
    assert out[0] == first
    assert np.all(out[1:] == 0)


def test_top_k_filter():
    logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0]])
    f = top_k_filter(logits, 2)
    assert float(f[0, 1]) == 5.0 and float(f[0, 2]) == 3.0
    assert float(f[0, 0]) < -1e9 and float(f[0, 3]) < -1e9


def test_top_p_filter_keeps_nucleus():
    probs = jnp.asarray([[0.5, 0.3, 0.15, 0.05]])
    logits = jnp.log(probs)
    f = top_p_filter(logits, 0.7)
    # 0.5 alone < 0.7, 0.5+0.3 crosses -> keep first two
    assert np.isfinite(np.asarray(f)[0, :2]).all()
    assert np.asarray(f)[0, 2] < -1e9 and np.asarray(f)[0, 3] < -1e9


def test_sample_top_p_distribution():
    probs = jnp.tile(jnp.asarray([[0.6, 0.25, 0.1, 0.05]]), (2000, 1))
    ids = sample_top_p(jax.random.key(0), probs, jnp.full((2000,), 0.7))
    vals, counts = np.unique(np.asarray(ids), return_counts=True)
    assert set(vals.tolist()) <= {0, 1}  # nucleus = {0.6, 0.25}
    frac0 = counts[vals.tolist().index(0)] / 2000
    assert abs(frac0 - 0.6 / 0.85) < 0.05
