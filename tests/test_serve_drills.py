"""Serving traffic drills through the real CLI (`make test-serve-drill`):
a tiny CPU server is flooded, drained, and fault-injected, and the
admission-controlled pipeline (core/request_queue.py wired into
tools/serve.py) must keep every contract:

  flood       under a concurrent burst with a full queue, every request
              gets exactly one of {200, 429, 503} within its deadline +
              scheduling slack — no hung connections
  drain       SIGTERM mid-traffic: /healthz reports draining, every
              admitted request is answered, the process exits 0
  gen_crash   an injected generation crash returns 500 (structured
              gen_error stats on /healthz) while the server keeps serving
  gen_hang    a wedged decode: the watchdog flips /healthz to degraded,
              queued requests shed honestly, a second SIGTERM force-quits,
              and a flight_recorder.jsonl postmortem (watchdog event +
              recent request spans) lands on disk
  metrics     GET /metrics returns valid Prometheus text exposition
              (strict parser, tests/test_telemetry.py) that agrees with
              /healthz counters taken from the same registry snapshot

Follows tests/test_fault_injection.py conventions: `fault`-marked,
subprocess-driven, one synthetic tiny-GPT config, persistent XLA compile
cache shared through the environment (tests/conftest.py)."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest
import yaml

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = {
    "Global": {"global_batch_size": 8, "seed": 11},
    "Engine": {"mix_precision": {"enable": False},
               "save_load": {"save_steps": 0}},
    "Model": {
        "module": "GPTModule",
        "vocab_size": 96,
        "hidden_size": 32,
        "num_layers": 2,
        "num_attention_heads": 4,
        "max_position_embeddings": 64,
        "dtype": "float32",
    },
    "Optimizer": {"name": "FusedAdamW",
                  "lr": {"name": "Constant", "learning_rate": 1e-3}},
    "Generation": {"max_dec_len": 8, "decode_strategy": "greedy_search",
                   "pad_to_multiple": 8, "eos_token_id": 95,
                   "pad_token_id": 0},
}


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _post(port, body, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _healthz(port, timeout=10):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz", timeout=timeout
    ) as r:
        return json.load(r)


def _start_server(tmp_path, *, deadline=45.0, depth=32, coalesce=2,
                  watchdog=300.0, shed_slack=3.0, warmup_batches="1",
                  extra_env=None, extra_args=()):
    """Boot tools/serve.py on the tiny config; wait until /healthz is up
    (warmup compiles ride the persistent XLA cache).  Returns (proc, port).

    ``warmup_batches`` is pinned to "1" by default so warmup issues
    exactly ONE generation request — the `gen_crash:<n>`/`gen_hang:<n>`
    sites count generation requests, and the drills rely on "warmup is
    request 1, first traffic is request 2"."""
    cfg_path = tmp_path / "tiny_serve.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    port = _free_port()
    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PFX_FAULT", None)
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-c", str(cfg_path), "--port", str(port),
         "--queue-depth", str(depth), "--max-coalesce", str(coalesce),
         "--deadline", str(deadline), "--shed-slack", str(shed_slack),
         "--watchdog", str(watchdog), "--warmup-buckets", "4",
         "--warmup-batches", warmup_batches, *extra_args],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    deadline_t = time.time() + 300
    while time.time() < deadline_t:
        if proc.poll() is not None:
            raise AssertionError(
                f"server died at boot: {proc.stdout.read()[-3000:]}"
            )
        try:
            h = _healthz(port, timeout=5)
            if h.get("ok"):
                return proc, port
        except Exception:
            time.sleep(0.5)
    proc.kill()
    raise AssertionError("server never became healthy")


def _finish(proc, timeout=30):
    """Terminate (graceful first) and return the full captured log."""
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
    return proc.stdout.read()


@pytest.mark.slow  # ~18s flood boot; tier-1 budget funding for the
# shard_map-port tests.  Replacement coverage: the 429-on-full / 503 /
# exactly-one-response admission contract stays tier-1 via the
# test_request_queue units (QueueFull/QueueClosed/deadline shed) and the
# serving coalesce/warmup tests; the server still boots under traffic
# tier-1 in the metrics-exposition and gen_hang drills; still in
# make test-serve-drill / test-all.
def test_flood_every_request_answered_or_honestly_shed(tmp_path):
    """Concurrent flood against a depth-3 queue: exactly one response per
    request, each in {200, 429, 503}, each within deadline + slack; the
    bounded queue really rejected (429 seen), and /healthz accounting
    (rejects, latency reservoir, drained queue) adds up.

    The first traffic batch is wedged for a few seconds via the gen_hang
    site (warmup_batches="1,2" spends generation requests 1-2, so first
    traffic is request 3): with the scheduler deterministically busy
    while the flood lands, the queue MUST fill and reject — without the
    wedge, a fast warm-cache decode can drain 12 requests through a
    depth-3 queue without ever refusing one, and the 429 assertion
    becomes a coin flip (observed flaky at seed)."""
    deadline = 45.0
    proc, port = _start_server(tmp_path, deadline=deadline, depth=3,
                               coalesce=2, shed_slack=3.0,
                               warmup_batches="1,2",
                               extra_env={"PFX_FAULT": "gen_hang:3",
                                          "PFX_FAULT_HANG_S": "4.0"})
    try:
        n = 12
        results = [None] * n

        def worker(i):
            t0 = time.monotonic()
            code, body = _post(
                port,
                {"prompt_ids": [1, 2, 3], "max_tokens": 4,
                 "deadline_s": deadline},
                timeout=deadline + 20,
            )
            results[i] = (code, time.monotonic() - t0)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=deadline + 30)
            assert not t.is_alive(), "hung connection in the flood"
        assert all(r is not None for r in results), results
        codes = [c for c, _ in results]
        assert all(c in (200, 429, 503) for c in codes), codes
        assert codes.count(200) >= 1, codes  # traffic was actually served
        assert 429 in codes, codes  # bounded admission really rejected
        slack = 15.0  # scheduling slack + HTTP overhead
        assert all(dt <= deadline + slack for _, dt in results), results

        h = _healthz(port)
        assert h["queue"]["rejected_full"] >= 1, h
        assert h["counters"].get("http_200", 0) >= 1, h
        assert h["counters"].get("http_429", 0) >= 1, h
        assert h["state"] == "ok" and h["queue_depth"] == 0, h
        assert h["latency_p50_s"] > 0 and h["latency_p99_s"] > 0, h
        # coalescing engaged under the burst (same-bucket prompts)
        assert h["queue"]["coalesced_requests"] >= 2, h

        # one request cannot smuggle an unbounded batch past admission:
        # a 100-prompt entry would occupy one queue slot yet key a giant
        # padded-batch compile on the single scheduler thread
        code, resp = _post(
            port,
            {"prompts_ids": [[1, 2]] * 100, "max_tokens": 4},
            timeout=30,
        )
        assert code == 400 and "too many prompts" in resp["error"], (code, resp)
    finally:
        log = _finish(proc)
    assert "Traceback" not in log, log[-3000:]


def test_metrics_exposition_parses_and_agrees_with_healthz(tmp_path):
    """GET /metrics on a live server is valid Prometheus text exposition
    (counter/gauge/histogram lines under the strict parser) and its
    serving/queue counters agree with /healthz — both endpoints render
    the SAME locked registry snapshot, so with no traffic between the two
    scrapes the numbers must be identical.  Rides the same boot:
    /debug/state agrees with the /metrics gauges, a 200's trace_id
    resolves on /debug/trace with the full coalesce-path timeline, and
    /debug/traces is Perfetto-loadable Chrome-trace JSON."""
    from test_telemetry import parse_prometheus
    from test_tracing import validate_chrome_trace

    proc, port = _start_server(tmp_path)
    try:
        last = None
        for ids in ([1, 2, 3], [4, 5]):
            code, last = _post(port, {"prompt_ids": ids, "max_tokens": 4},
                               timeout=120)
            assert code == 200
        h = _healthz(port)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()

        metrics, types = parse_prometheus(text)  # strict: raises on any bad line
        # all three metric kinds present and well-formed
        assert types["pfx_serving_requests_total"] == "counter"
        assert types["pfx_http_requests_in_flight"] == "gauge"
        assert types["pfx_request_latency_seconds"] == "histogram"

        def val(name, **labels):
            return metrics[name][frozenset(labels.items())]

        # /metrics agrees with the /healthz snapshot taken just before it
        # (no traffic in between; the scrapes themselves only bump http_*)
        assert val("pfx_serving_requests_total") == h["requests"]
        assert val("pfx_serving_tokens_out_total") == h["tokens_out"]
        assert val("pfx_queue_submitted_total") == h["queue"]["submitted"]
        assert val("pfx_queue_completed_total") == h["queue"]["completed"]
        assert val("pfx_queue_depth") == h["queue_depth"] == 0
        assert val("pfx_http_responses_total", code="200") >= h["counters"]["http_200"]
        # both POSTs flowed through the span pipeline
        assert val("pfx_request_latency_seconds_count") == 2
        assert val("pfx_request_ttft_seconds_count") == 2
        assert val("pfx_request_decode_seconds_count") == 2
        assert val("pfx_request_per_token_seconds_count") == 2
        assert val("pfx_request_latency_seconds_sum") > 0
        # warmup registered on the shared registry, not a private dict
        assert val("pfx_serving_warmup_seconds_total") > 0

        # ---- /debug/state: the live-introspection snapshot agrees with
        # the /metrics gauges (quiesced server, one snapshot) ----
        def _get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ) as r:
                assert r.status == 200, path
                return json.load(r)

        dbg = _get("/debug/state")
        assert dbg["scheduler"] == "coalesce" and not dbg["closed"]
        assert dbg["depth"] == 0 == val("pfx_queue_depth")
        assert dbg["waiting"] == []
        assert dbg["metrics"]["pfx_queue_depth"] == val("pfx_queue_depth")
        assert dbg["serving"]["traces"] == val("pfx_serving_traces_total")
        assert dbg["serving"]["compiled_families"] >= 1
        assert dbg["trace_buffer"]["retained"] >= 2  # both POSTs sampled

        # ---- /debug/trace: the 200's trace_id replays its timeline ----
        assert "trace_id" in last, last
        tl = _get(f"/debug/trace?id={last['trace_id']}")
        names = [e["name"] for e in tl["events"]]
        assert {"admission", "queue_wait", "decode", "respond"} <= set(names)
        respond = next(e for e in tl["events"] if e["name"] == "respond")
        assert respond["args"]["code"] == 200
        # redaction: args carry counts only, never token ids
        decode = next(e for e in tl["events"] if e["name"] == "decode")
        assert isinstance(decode["args"]["tokens"], int)

        # ---- /debug/traces: Perfetto-loadable window ----
        validate_chrome_trace(_get("/debug/traces"))

        # unknown id / path: honest 4xx, not a traceback
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/debug/trace?id=nope"
        )
        try:
            urllib.request.urlopen(req, timeout=10)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        log = _finish(proc)
    assert "Traceback" not in log, log[-3000:]


@pytest.mark.slow  # ~17s; tier-1 budget funding for the shard_map-port
# tests.  Replacement coverage: multi-window burn-rate/breach/recovery
# logic stays tier-1 via the telemetry SLOTracker units, and the wedged-
# decode path (gen_hang -> degraded /healthz -> shed -> force-quit) stays
# tier-1-drilled by test_gen_hang_watchdog_degrades_sheds_and_force_quits;
# still in make test-serve-drill / test-all.
def test_slo_breach_flips_on_wedged_decode_and_recovers(tmp_path):
    """The SLO acceptance drill: with a 0.2s p99-TTFT objective over
    short rolling windows, a decode wedged for ~2s (gen_hang, shorter
    than the deadline so the request still succeeds) burns the whole
    budget — /healthz grows an `slo` block whose breach flag flips with
    a reason naming ttft_p99 and pfx_slo_* gauges land in /metrics —
    and once the bad window rolls past, the flag recovers on its own."""
    from test_telemetry import parse_prometheus

    proc, port = _start_server(
        tmp_path, deadline=45.0,
        extra_env={"PFX_FAULT": "gen_hang:2", "PFX_FAULT_HANG_S": "2.0"},
        extra_args=("--slo-ttft-p99", "0.2", "--slo-windows", "3,6"),
    )
    try:
        h = _healthz(port)
        assert h["slo"]["enabled"] and not h["slo"]["breach"], h["slo"]
        assert h["slo"]["objectives"] == {"ttft_p99": 0.2}, h["slo"]

        # first traffic request (generation request 2) hangs 2s, then
        # SUCCEEDS: a slow 200, i.e. a TTFT-budget burn, not an error
        code, _ = _post(port, {"prompt_ids": [1, 2, 3], "max_tokens": 4,
                               "deadline_s": 40}, timeout=90)
        assert code == 200

        h = _healthz(port)
        slo = h["slo"]
        assert slo["breach"], slo
        assert "ttft_p99" in slo["reason"], slo
        assert all(b > 1.0 for b in slo["burn"]["ttft_p99"].values()), slo
        assert slo["ttft_p99_s"] > 0.2, slo

        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as r:
            metrics, types = parse_prometheus(r.read().decode())
        assert types["pfx_slo_burn_rate"] == "gauge"
        key = frozenset({("objective", "ttft_p99"), ("window", "3s")})
        assert metrics["pfx_slo_burn_rate"][key] > 1.0
        assert metrics["pfx_slo_breach"][
            frozenset({("objective", "ttft_p99")})
        ] == 1.0
        # ONE objective label across objective/burn/breach gauges, so a
        # PromQL join on {objective=} actually matches
        assert metrics["pfx_slo_objective"][
            frozenset({("objective", "ttft_p99")})
        ] == 0.2

        # recovery: the bad observation ages out of the windows (the
        # short one first — breach clears the moment ANY window stops
        # burning — then the long one drains too); fresh fast requests
        # stay under the objective
        recovered = drained = False
        t_end = time.time() + 25
        while time.time() < t_end:
            code, _ = _post(port, {"prompt_ids": [4, 5], "max_tokens": 2,
                                   "deadline_s": 30}, timeout=60)
            assert code == 200
            slo = _healthz(port)["slo"]
            if not slo["breach"]:
                recovered = True
            if all(b <= 1.0 for b in slo["burn"]["ttft_p99"].values()):
                drained = True
                break
            time.sleep(1.0)
        assert recovered and drained, slo
        assert not slo["breach"], slo
    finally:
        log = _finish(proc)
    assert "Traceback" not in log, log[-3000:]


@pytest.mark.slow  # ~10s boot; the drain contract stays tier-1-drilled by
# the gen_hang drill (drain state + second-signal escalation) and the paged
# drill's SIGTERM exit-0; still in make test-serve-drill / test-all (PR 8
# tier-1 budget convention)
def test_sigterm_mid_traffic_drains_and_exits_zero(tmp_path):
    """SIGTERM with a queued backlog: admission closes (/healthz reports
    draining), every admitted request is answered, exit code 0."""
    proc, port = _start_server(tmp_path, deadline=90.0, depth=32,
                               coalesce=2)
    try:
        n = 10
        results = [None] * n

        def worker(i):
            results[i] = _post(
                port,
                {"prompt_ids": [2, 3, 4], "max_tokens": 8,
                 "deadline_s": 90},
                timeout=120,
            )

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # let the burst be admitted
        proc.send_signal(signal.SIGTERM)

        # the server must report draining while the backlog finishes
        saw_draining = False
        t_end = time.time() + 30
        while time.time() < t_end and proc.poll() is None:
            try:
                h = _healthz(port, timeout=5)
            except Exception:
                break  # drain finished and the listener went away
            if h.get("state") == "draining":
                saw_draining = True
                assert h.get("ok"), h  # draining is healthy, not degraded
                break
            time.sleep(0.02)
        assert saw_draining, "healthz never reported draining"

        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "request hung through the drain"
        codes = [c for c, _ in results]
        # admitted -> 200; a straggler that raced the close -> 503; but
        # traffic this early is admitted, so most must be real answers
        assert all(c in (200, 503) for c in codes), codes
        assert codes.count(200) >= n - 2, codes
        rc = proc.wait(timeout=120)
        assert rc == 0, rc
    finally:
        log = _finish(proc)
    assert "draining" in log and "drained cleanly" in log, log[-3000:]
    assert "Traceback" not in log, log[-3000:]


@pytest.mark.slow  # ~12s boot; crash recovery is unit-covered (test_serving
# pool-not-poisoned, continuous ArenaReset recovery) and the SLO drill
# exercises a fault boot through the same CLI; still in make
# test-serve-drill / test-all (PR 8 tier-1 budget convention)
def test_gen_crash_returns_500_server_keeps_serving(tmp_path):
    """PFX_FAULT=gen_crash:2 (warmup is request 1): the first traffic
    request gets a 500 with the injected error, the cache pool is not
    poisoned, and the server keeps serving token-identical answers."""
    proc, port = _start_server(
        tmp_path, extra_env={"PFX_FAULT": "gen_crash:2"}
    )
    try:
        body = {"prompt_ids": [1, 2, 3], "max_tokens": 4, "deadline_s": 60}
        code, resp = _post(port, body, timeout=90)
        assert code == 500 and "gen_crash" in resp["error"], (code, resp)

        code2, resp2 = _post(port, body, timeout=90)
        assert code2 == 200, (code2, resp2)
        code3, resp3 = _post(port, body, timeout=90)
        assert code3 == 200, (code3, resp3)
        # greedy determinism across the crash: the recycled pool entry
        # was dropped, not donation-poisoned
        assert resp2["completion_ids"] == resp3["completion_ids"]

        h = _healthz(port)
        assert h["gen_errors"] == 1, h
        assert "gen_crash" in h["last_error"], h
        assert h["counters"].get("http_500", 0) == 1, h
        assert h["counters"].get("http_200", 0) >= 2, h
        assert h["state"] == "ok", h
    finally:
        log = _finish(proc)
    assert "Traceback" not in log, log[-3000:]


@pytest.mark.slow  # ~16s; tier-1 budget funding for the shard_map-port
# tests.  Replacement coverage: deadline shed + busy_seconds wedge-probe
# logic stays tier-1 via the test_request_queue units, and the drill
# itself still runs on every `make test-obs` (the -k "metrics or
# gen_hang" line selects it regardless of marker) plus
# make test-serve-drill / test-all.
def test_gen_hang_watchdog_degrades_sheds_and_force_quits(tmp_path):
    """PFX_FAULT=gen_hang:2 wedges the scheduler: the hanging client is
    shed at its deadline (no hung connection), the watchdog flips
    /healthz to degraded, a queued request sheds before any decode,
    SIGTERM escalation (drain, then force-quit) works, and the flight
    recorder leaves a postmortem on disk — the watchdog-degraded event
    plus the recent request spans — without the server ever having a
    metrics file configured."""
    flight_path = str(tmp_path / "flight_recorder.jsonl")
    proc, port = _start_server(
        tmp_path, watchdog=2.0, shed_slack=2.0,
        extra_env={"PFX_FAULT": "gen_hang:2", "PFX_FAULT_HANG_S": "600",
                   "PFX_FLIGHT_RECORDER": flight_path},
    )
    try:
        t0 = time.monotonic()
        code, resp = _post(
            port,
            {"prompt_ids": [1, 2, 3], "max_tokens": 4, "deadline_s": 3},
            timeout=60,
        )
        # wedged decode: honest 503 at deadline + slack, not a hang
        assert code == 503, (code, resp)
        assert time.monotonic() - t0 < 20

        degraded = False
        t_end = time.time() + 20
        while time.time() < t_end:
            h = _healthz(port)
            if not h.get("ok") and h.get("state") == "degraded":
                degraded = True
                break
            time.sleep(0.25)
        assert degraded, h
        assert h["busy_s"] > 2, h  # the wedge is visible

        # a request queued behind the wedge is shed without a decode
        code2, _ = _post(
            port,
            {"prompt_ids": [4, 5, 6], "max_tokens": 4, "deadline_s": 1},
            timeout=30,
        )
        assert code2 == 503
        assert _healthz(port)["queue"]["shed_deadline"] >= 1

        # graceful drain can never finish (scheduler wedged): first
        # signal drains, second force-quits — the PR 2 escalation
        # contract.  The second signal here is SIGINT, the harder case:
        # its default action raises KeyboardInterrupt in serve_forever,
        # which must NOT fall through to server_close's join of
        # non-daemon handler threads (that would hold the process for up
        # to max_deadline + slack behind the wedged decode).
        proc.send_signal(signal.SIGTERM)
        t_end = time.time() + 15
        draining = False
        while time.time() < t_end:
            h = _healthz(port)
            if h.get("state") == "draining":
                draining = True
                break
            time.sleep(0.1)
        assert draining, h
        t0 = time.monotonic()
        proc.send_signal(signal.SIGINT)
        rc = proc.wait(timeout=30)
        assert rc == 130, rc  # force-quit exit, not a clean drain
        assert time.monotonic() - t0 < 15  # immediate, no thread joins

        # flight-recorder postmortem: the watchdog degrade was dumped
        # while the wedge was live, and the force-quit re-dumped the ring
        # with everything since — the degrade event AND the shed request
        # spans must be on disk even though no metrics stream was set
        events = [json.loads(line) for line in open(flight_path)]
        assert events[0]["event"] == "flight_recorder_dump"
        assert events[0]["reason"] == "force_quit", events[0]
        kinds = [e.get("event") for e in events]
        assert "watchdog_degraded" in kinds, kinds
        assert "force_quit" in kinds, kinds
        spans = [e for e in events if e.get("event") == "span"]
        assert len(spans) >= 2, events  # both shed requests left spans
        assert all(e.get("code") == 503 for e in spans), spans
        assert any("shed" in e.get("phases", {}) for e in spans), spans
    finally:
        _finish(proc)
