"""Multi-tenant isolation drills through the real CLIs
(`make test-tenant`): tools/serve.py (continuous scheduler) behind
tools/router.py with a --tenants quota/weight file, driven as real
subprocesses over HTTP:

  flood        tenant A floods at ~10x its configured rate quota while
               tenant B trickles: B's latency stays within slack of its
               solo baseline, A's overage is refused with 429s carrying
               the token bucket's HONEST finite Retry-After, headers
               reach the replica (per-tenant TTFT series exist), and a
               SIGTERM drain exits 0 on every process
  storm        PFX_FAULT=preempt_storm:K force-preempts a mid-decode row
               on the live server; the victim resumes as a re-prefill
               continuation and every response is TOKEN-IDENTICAL to the
               same server's undisturbed sequential answers (f32 exact)
  sse-evict    an SSE stream whose row is wedged past its deadline
               mid-decode closes with the honest terminal error frame
               (status + tokens_committed == tokens on the wire), never
               a silent hang — and the server keeps serving after

Follows tests/test_serve_drills.py conventions: `fault`-marked,
subprocess-driven, one synthetic tiny-GPT config, persistent XLA compile
cache shared through the environment (tests/conftest.py)."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest
import yaml

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = {
    "Global": {"global_batch_size": 8, "seed": 11},
    "Engine": {"mix_precision": {"enable": False},
               "save_load": {"save_steps": 0}},
    "Model": {
        "module": "GPTModule",
        "vocab_size": 96,
        "hidden_size": 32,
        "num_layers": 2,
        "num_attention_heads": 4,
        "max_position_embeddings": 64,
        "dtype": "float32",
    },
    "Optimizer": {"name": "FusedAdamW",
                  "lr": {"name": "Constant", "learning_rate": 1e-3}},
    "Generation": {"max_dec_len": 8, "decode_strategy": "greedy_search",
                   "pad_to_multiple": 8, "eos_token_id": 95,
                   "pad_token_id": 0},
}

TENANTS = {
    "default": {"weight": 1.0},
    "tenants": {
        "flood": {"weight": 1, "rps": 2, "burst": 2, "max_inflight": 2},
        "prio": {"weight": 4},
    },
}


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _env(extra=None):
    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PFX_FAULT", None)
    env.update(extra or {})
    return env


def _post(port, body, *, headers=None, timeout=90, path="/generate"):
    """POST returning (status, parsed body, response headers)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r), dict(r.headers.items())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers.items())


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return json.load(r)


def _metrics(port, timeout=10):
    from test_telemetry import parse_prometheus

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=timeout
    ) as r:
        metrics, _ = parse_prometheus(r.read().decode())
    return metrics


def _spawn_replica(cfg_path, port, *extra, extra_env=None):
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-c", str(cfg_path), "--port", str(port),
         "--queue-depth", "32", "--deadline", "60",
         "--warmup-buckets", "4", "--warmup-batches", "1",
         "--scheduler", "continuous", "--cb-batch", "4",
         "--kv-blocks", "16", *extra],
        env=_env(extra_env), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )


def _wait_healthy(procs_ports, timeout=300):
    end = time.time() + timeout
    pending = dict(procs_ports)
    while pending and time.time() < end:
        for port, proc in list(pending.items()):
            if proc.poll() is not None:
                raise AssertionError(
                    f"process on {port} died at boot: "
                    f"{proc.stdout.read()[-3000:]}"
                )
            try:
                if _get(port, "/healthz", timeout=5).get("ok"):
                    del pending[port]
            except Exception:
                pass
        time.sleep(0.3)
    assert not pending, f"never healthy: {sorted(pending)}"


def _finish(proc, timeout=30):
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
    return proc.stdout.read()


def _write_cfgs(tmp_path):
    cfg_path = tmp_path / "tiny_serve.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    ten_path = tmp_path / "tenants.json"
    ten_path.write_text(json.dumps(TENANTS))
    return cfg_path, ten_path


def test_two_tenant_flood_isolation_and_drain(tmp_path):
    """THE isolation acceptance drill: tenant `flood` fires ~10x its
    2 rps / 2-burst / 2-in-flight quota at the router while tenant
    `prio` trickles sequential requests.  The trickle's latency stays
    within slack of its solo baseline (the flood's backlog lives in the
    flood's own bucket, not in front of everyone), the overage is
    refused with 429 + the bucket's finite Retry-After, the labels
    provably reached the replica (per-tenant TTFT series), and SIGTERM
    drains both processes to exit 0."""
    cfg_path, ten_path = _write_cfgs(tmp_path)
    sport, rport = _free_port(), _free_port()
    replica = _spawn_replica(cfg_path, sport,
                             "--tenants", str(ten_path))
    router = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "router.py"),
         "--port", str(rport), "--poll-interval", "0.2",
         "--replica", f"http://127.0.0.1:{sport}",
         "--tenants", str(ten_path)],
        env=_env(), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    try:
        _wait_healthy({sport: replica, rport: router})
        end = time.time() + 30
        while time.time() < end:
            if _get(rport, "/healthz").get("eligible", 0) >= 1:
                break
            time.sleep(0.2)

        def prio_request(timeout=60):
            t0 = time.monotonic()
            code, body, _ = _post(
                rport, {"prompt_ids": [9, 10, 11], "max_tokens": 4},
                headers={"X-Tenant": "prio", "X-Priority": "5"},
                timeout=timeout,
            )
            return code, time.monotonic() - t0, body

        # solo baseline: the trickle tenant alone on the fabric
        solo = []
        for _ in range(5):
            code, dt, _body = prio_request()
            assert code == 200
            solo.append(dt)
        solo_p99 = max(solo)

        # the flood: 20 concurrent requests ~at once against rps=2
        flood_results = [None] * 20

        def flood_worker(i):
            flood_results[i] = _post(
                rport, {"prompt_ids": [1, 2, 3], "max_tokens": 4},
                headers={"X-Tenant": "flood"}, timeout=90,
            )

        threads = [threading.Thread(target=flood_worker, args=(i,))
                   for i in range(len(flood_results))]
        for t in threads:
            t.start()
        trickle = []
        for _ in range(5):
            code, dt, _body = prio_request(timeout=90)
            assert code == 200, "trickle tenant starved by the flood"
            trickle.append(dt)
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "hung flood connection"

        # isolation: the trickle's worst latency under flood stays
        # within slack of its solo p99 (generous bound — CPU CI jitter
        # dwarfs scheduling effects; the contract is "bounded", not
        # "identical")
        assert max(trickle) <= solo_p99 * 5.0 + 2.0, (solo, trickle)

        codes = [c for c, _b, _h in flood_results]
        assert all(c in (200, 429) for c in codes), codes
        assert codes.count(200) >= 1, codes   # under-quota traffic served
        assert codes.count(429) >= 10, codes  # the overage was refused
        for code, body, hdrs in flood_results:
            if code != 429:
                continue
            # honest Retry-After: finite, positive, from the bucket
            retry = float(hdrs.get("Retry-After"))
            assert 0.0 < retry <= 30.0, hdrs
            assert body["tenant"] == "flood", body
            assert body["reason"] in ("rate", "inflight"), body
            assert body["retry_after_s"] > 0.0, body

        # the router's own accounting: rejected counter + tenant view
        m = _metrics(rport)
        rej = sum(v for k, v in m["pfx_tenant_rejected_total"].items()
                  if ("tenant", "flood") in k)
        assert rej >= 10, m["pfx_tenant_rejected_total"]
        snap = _get(rport, "/replicas")
        assert snap["tenants"]["flood"]["in_flight"] == 0, snap
        assert snap["tenants"]["prio"]["weight"] == 4, snap

        # the labels crossed the hop: the REPLICA observed per-tenant
        # TTFT for both tenants (satellite: headers ride every leg)
        rm = _metrics(sport)
        ttft_tenants = {dict(k).get("tenant")
                        for k in rm["pfx_request_ttft_seconds_count"]
                        } if "pfx_request_ttft_seconds_count" in rm else set()
        tt = {dict(k).get("tenant")
              for k in rm.get("pfx_tenant_ttft_seconds_count", {})}
        assert {"flood", "prio"} <= tt, (tt, ttft_tenants)
        # label cardinality stayed bounded: every tenant label on the
        # replica is a declared tenant, anon, or the overflow bucket
        assert tt <= {"flood", "prio", "anon", "__other__"}, tt
    finally:
        # graceful drain: ONE SIGTERM each (a second would force-quit
        # the router mid-drain), both must exit 0
        router.send_signal(signal.SIGTERM)
        replica.send_signal(signal.SIGTERM)
        try:
            router.wait(timeout=30)
            replica.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass
        rlog = _finish(router)
        slog = _finish(replica)
    assert router.returncode == 0, rlog[-3000:]
    assert replica.returncode == 0, slog[-3000:]
    assert "Traceback" not in slog, slog[-3000:]


def test_preempt_storm_cli_token_identity(tmp_path):
    """Preempt-resume parity through the real CLI: `preempt_storm:6`
    force-preempts one mid-decode row at scheduler iteration 6 (warmup
    never touches the continuous scheduler, so the threshold lands
    inside the first traffic wave deterministically).  The preempted
    row re-enters as a re-prefill continuation and EVERY concurrent
    response must equal the same server's sequential answers after the
    storm is spent — greedy f32 token-identity end-to-end."""
    cfg_path, _ = _write_cfgs(tmp_path)
    sport = _free_port()
    replica = _spawn_replica(
        cfg_path, sport, "--preempt-min-tokens", "2",
        extra_env={"PFX_FAULT": "preempt_storm:6"},
    )
    try:
        _wait_healthy({sport: replica})
        prompts = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 10]]
        results = [None] * len(prompts)

        def worker(i):
            results[i] = _post(
                sport, {"prompt_ids": prompts[i], "max_tokens": 16},
                timeout=120,
            )

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=150)
            assert not t.is_alive(), "request hung across the storm"
        assert all(r is not None and r[0] == 200 for r in results), results

        # the storm really fired (never a green test off a dud drill)
        m = _metrics(sport)
        pre = sum(m.get("pfx_tenant_preemptions_total", {}).values())
        assert pre == 1, m.get("pfx_tenant_preemptions_total")

        # sequential references from the SAME live server (storm spent:
        # count=1) — preempt-resume must be invisible in the tokens
        for i, p in enumerate(prompts):
            code, body, _ = _post(
                sport, {"prompt_ids": p, "max_tokens": 16}, timeout=120
            )
            assert code == 200
            assert results[i][1]["completion_ids"] == body["completion_ids"], (
                f"prompt {i}: preempt-resume diverged from the "
                f"undisturbed decode"
            )
    finally:
        log = _finish(replica)
    assert replica.returncode == 0, log[-3000:]
    assert "Traceback" not in log, log[-3000:]


def test_sse_evicted_stream_closes_with_honest_frame(tmp_path):
    """Satellite (a): an SSE client whose row is shed past its deadline
    MID-decode gets a terminal ``event: error`` frame carrying the
    status and exactly the token count already put on the wire, then a
    closed connection — never a silent hang.  `cb_step_hang:10` wedges
    the decode after ~9 streamed steps (warmup bypasses the scheduler,
    so the step counter is all traffic), the 2s deadline + 1s slack
    expires inside the 8s wedge, and the server keeps serving after."""
    cfg_path, _ = _write_cfgs(tmp_path)
    sport = _free_port()
    replica = _spawn_replica(
        cfg_path, sport, "--shed-slack", "1",
        extra_env={"PFX_FAULT": "cb_step_hang:10",
                   "PFX_FAULT_HANG_S": "8.0"},
    )
    try:
        _wait_healthy({sport: replica})
        req = urllib.request.Request(
            f"http://127.0.0.1:{sport}/generate?stream=1",
            data=json.dumps({"prompt_ids": [1, 2, 3], "max_tokens": 40,
                             "deadline_s": 2.0}).encode(),
            headers={"Content-Type": "application/json"},
        )
        t0 = time.monotonic()
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.status == 200  # SSE reality: status line says 200
            raw = r.read().decode()  # blocks until the server CLOSES
        elapsed = time.monotonic() - t0
        # closed promptly after deadline+slack, not after the 8s wedge
        # (generous bound: boot-adjacent CPU scheduling jitter)
        assert elapsed < 30.0, elapsed

        frames = [f for f in raw.split("\n\n") if f.strip()]
        events = []
        for f in frames:
            lines = dict(
                ln.split(": ", 1) for ln in f.splitlines() if ": " in ln
            )
            events.append((lines["event"], json.loads(lines["data"])))
        streamed = sum(len(d["tokens"]) for ev, d in events
                       if ev == "token")
        assert streamed >= 1, raw  # it WAS mid-decode, tokens flowed
        ev, data = events[-1]
        assert ev == "error", events
        assert data["code"] == 503, data
        assert data["tokens_committed"] == streamed, (data, streamed)

        # the wedge was the row's problem, not the server's: next
        # request (after the hang drains) answers 200
        code, body, _ = _post(
            sport, {"prompt_ids": [4, 5], "max_tokens": 4}, timeout=120
        )
        assert code == 200 and body["completion_ids"], body
    finally:
        log = _finish(replica)
    assert replica.returncode == 0, log[-3000:]
    assert "Traceback" not in log, log[-3000:]
