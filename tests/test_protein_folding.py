"""Protein folding stack tests: geometry, template/structure modules, and
DAP (sep) parity of the full HelixFold loss."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.data.protein_dataset import synthesize_protein
from paddlefleetx_tpu.models.protein import all_atom, folding, rigid
from paddlefleetx_tpu.models.protein import structure as struct
from paddlefleetx_tpu.models.protein.structure import StructureConfig

TINY = folding.FoldingConfig(
    msa_channel=32,
    pair_channel=16,
    seq_channel=32,
    extra_msa_channel=16,
    evoformer_num_blocks=2,
    extra_msa_num_blocks=1,
    template_num_blocks=1,
    dropout_rate=0.0,
    structure=StructureConfig(
        single_channel=32, pair_channel=16, num_iterations=2, num_heads=4,
        torsion_channel=16, dropout_rate=0.0,
    ),
)


def _batch(num_res=12, num_msa=4, num_extra=4, num_templates=2, seed=0):
    ex = synthesize_protein(
        np.random.default_rng(seed), num_res, num_msa, num_extra, num_templates
    )
    return {k: jnp.asarray(v)[None] for k, v in ex.items()}


# ---------------------------------------------------------------------------
# geometry
# ---------------------------------------------------------------------------


def test_torsion_known_dihedral():
    """A planted 4-atom chain with a known dihedral angle must round-trip."""
    for angle in (0.3, -1.2, 2.9):
        a1 = jnp.array([-0.5, 1.0, 0.0])
        a2 = jnp.array([0.0, 0.0, 0.0])
        a3 = jnp.array([1.5, 0.0, 0.0])
        # a4 rotated by `angle` about the a2->a3 axis from the a1 half-plane
        a4 = a3 + jnp.array([0.5, float(np.cos(angle)), float(np.sin(angle))])
        # torsion frame convention (all_atom.py / reference :189-197):
        # neg-x = a2, origin = a3, xy half-plane = a1
        frames = rigid.rigids_from_3_points(a2[None], a3[None], a1[None])
        local = rigid.rigid_invert_apply(frames, a4[None])
        got = float(jnp.arctan2(local[0, 2], local[0, 1]))
        np.testing.assert_allclose(got, angle, atol=1e-5)


def test_atom37_torsions_shapes_and_masks():
    ex = synthesize_protein(np.random.default_rng(0), 10, 2, 2, 0)
    out = all_atom.atom37_to_torsion_angles(
        jnp.asarray(ex["aatype"])[None],
        jnp.asarray(ex["all_atom_positions"])[None],
        jnp.asarray(ex["all_atom_mask"])[None],
    )
    sc = out["torsion_angles_sin_cos"]
    assert sc.shape == (1, 10, 7, 2)
    # backbone torsions exist from residue 1 on; sidechain atoms absent
    mask = out["torsion_angles_mask"]
    assert float(mask[0, 0, 0]) == 0.0  # pre-omega needs the previous residue
    np.testing.assert_allclose(np.asarray(mask[0, 1:, 2]), 1.0)  # psi
    np.testing.assert_allclose(np.asarray(mask[0, :, 3:]), 0.0)  # no chis
    # normalized where defined
    norms = np.asarray(jnp.sum(sc**2, -1))[0][np.asarray(mask[0]) > 0]
    np.testing.assert_allclose(norms, 1.0, atol=1e-4)


def test_fape_zero_for_identical():
    ex = synthesize_protein(np.random.default_rng(1), 8, 2, 2, 0)
    pos = jnp.asarray(ex["all_atom_positions"])[None]
    rot, trans = rigid.rigids_from_3_points(
        pos[..., 0, :], pos[..., 1, :], pos[..., 2, :]
    )
    quat = rigid.rot_to_quat(rot)
    mask = jnp.ones((1, 8))
    loss = struct.backbone_fape_loss(
        quat[None], trans[None], quat, trans, mask
    )
    assert float(loss) < 1e-3


def test_fape_invariant_to_global_transform():
    """FAPE must be invariant when pred = rigid transform of target."""
    ex = synthesize_protein(np.random.default_rng(2), 8, 2, 2, 0)
    pos = jnp.asarray(ex["all_atom_positions"])[None]
    rot, trans = rigid.rigids_from_3_points(
        pos[..., 0, :], pos[..., 1, :], pos[..., 2, :]
    )
    quat = rigid.rot_to_quat(rot)
    g = rigid.quat_to_rot(rigid.quat_normalize(jnp.array([0.9, 0.1, -0.3, 0.2])))
    shift = jnp.array([5.0, -3.0, 2.0])
    rot2 = jnp.einsum("ij,brjk->brik", g, rot)
    trans2 = jnp.einsum("ij,brj->bri", g, trans) + shift
    quat2 = rigid.rot_to_quat(rot2)
    mask = jnp.ones((1, 8))
    loss = struct.backbone_fape_loss(quat2[None], trans2[None], quat, trans, mask)
    assert float(loss) < 1e-3


# ---------------------------------------------------------------------------
# structure module
# ---------------------------------------------------------------------------


def test_ipa_se3_invariance():
    """IPA output must not change under a global rotation+translation of
    the input frames (the invariance that makes it an IPA)."""
    cfg = TINY.structure
    key = jax.random.key(0)
    params = struct.init(cfg, key)
    b, R = 1, 6
    single = jax.random.normal(jax.random.fold_in(key, 1), (b, R, cfg.single_channel))
    pair = jax.random.normal(jax.random.fold_in(key, 2), (b, R, R, cfg.pair_channel))
    quat = rigid.quat_normalize(jax.random.normal(jax.random.fold_in(key, 3), (b, R, 4)))
    trans = jax.random.normal(jax.random.fold_in(key, 4), (b, R, 3))
    mask = jnp.ones((b, R))

    out1 = struct.invariant_point_attention(
        params["ipa"], single, pair, (rigid.quat_to_rot(quat), trans), mask, cfg
    )
    g = rigid.quat_to_rot(rigid.quat_normalize(jnp.array([1.0, 0.4, -0.2, 0.7])))
    shift = jnp.array([3.0, 1.0, -2.0])
    rot2 = jnp.einsum("ij,brjk->brik", g, rigid.quat_to_rot(quat))
    trans2 = jnp.einsum("ij,brj->bri", g, trans) + shift
    out2 = struct.invariant_point_attention(
        params["ipa"], single, pair, (rot2, trans2), mask, cfg
    )
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=2e-4)


def test_structure_module_outputs():
    cfg = TINY.structure
    params = struct.init(cfg, jax.random.key(0))
    b, R = 1, 6
    single = jax.random.normal(jax.random.key(1), (b, R, cfg.single_channel))
    pair = jax.random.normal(jax.random.key(2), (b, R, R, cfg.pair_channel))
    out = struct.structure_module(params, single, pair, jnp.ones((b, R)), cfg)
    assert out["traj_quat"].shape == (cfg.num_iterations, b, R, 4)
    assert out["torsions"].shape == (b, R, 7, 2)
    assert out["backbone_atoms"].shape == (b, R, 5, 3)
    # quats stay normalized
    np.testing.assert_allclose(
        np.asarray(jnp.sum(out["final_quat"] ** 2, -1)), 1.0, atol=1e-5
    )


# ---------------------------------------------------------------------------
# full model
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~26s full-model compile; the folding stack stays
# tier-1 via the torsion/FAPE/IPA-invariance/structure-module units
# above; still in make test-all (PR 8 tier-1 budget convention)
def test_folding_loss_finite_and_template_gating():
    batch = _batch()
    params = folding.init(TINY, jax.random.key(0))
    loss = float(jax.jit(lambda p, b: folding.loss_fn(p, b, TINY, train=False))(params, batch))
    assert np.isfinite(loss)
    # zero template_mask must produce the identical pair contribution as
    # template-disabled (no-template gating, reference template.py:367)
    batch2 = dict(batch)
    batch2["template_mask"] = jnp.zeros_like(batch["template_mask"])
    loss2 = float(
        jax.jit(lambda p, b: folding.loss_fn(p, b, TINY, train=False))(params, batch2)
    )
    assert np.isfinite(loss2)


@pytest.mark.slow
def test_folding_dap_parity(devices8):
    """Full HelixFold loss identical between single-device and a dp2 x sep2
    (DAP) mesh layout."""
    from paddlefleetx_tpu.models.gpt.model import ShardingCtx
    from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
    from paddlefleetx_tpu.parallel.sharding import make_rules, tree_logical_to_sharding

    batch = _batch(num_res=8, num_msa=4)
    params = folding.init(TINY, jax.random.key(0))
    ref = float(jax.jit(lambda p, b: folding.loss_fn(p, b, TINY, train=False))(params, batch))

    mesh = build_mesh(MeshConfig(dp_degree=4, sep_degree=2), devices8)
    rules = make_rules(sequence_parallel=True, mesh=mesh)
    ctx = ShardingCtx(mesh, rules)
    shardings = tree_logical_to_sharding(
        folding.folding_logical_axes(TINY), mesh, rules
    )
    p_sh = jax.device_put(params, shardings)
    with mesh:
        got = float(
            jax.jit(lambda p, b: folding.loss_fn(p, b, TINY, ctx=ctx, train=False))(
                p_sh, batch
            )
        )
    np.testing.assert_allclose(got, ref, rtol=2e-4)


def test_protein_dataset_npz_pad_crop(tmp_path):
    """Loaded .npz records are padded/cropped to the configured shapes."""
    import os

    from paddlefleetx_tpu.data.protein_dataset import ProteinDataset

    ex = synthesize_protein(np.random.default_rng(3), 10, 3, 5, 1)
    np.savez(os.path.join(tmp_path, "p0.npz"), **ex)
    ds = ProteinDataset(
        input_dir=str(tmp_path), num_res=16, num_msa=4, num_extra_msa=4,
        num_templates=2,
    )
    rec = ds[0]
    assert rec["aatype"].shape == (16,)
    assert rec["msa_feat"].shape == (4, 16, 49)
    assert rec["extra_msa"].shape == (4, 16)
    assert rec["template_all_atom_positions"].shape == (2, 16, 37, 3)
    assert rec["template_mask"].shape == (2,)
    # padded region is masked out
    assert float(rec["seq_mask"][10:].sum()) == 0.0
