"""Data-pipeline fault drills through the real CLI (`make test-data-drill`):
PFX_FAULT data sites + the concurrent index-map build race.

  corrupt_sample   a rotten record mid-run: skipped under the
                   data.max_skips budget (data_skip event in the metrics
                   stream, deterministic substitute -> two identical runs
                   produce identical loss streams), loud failure naming
                   the budget once it is exhausted
  io_stall         a hung storage read during sample fetch: the prefetch
                   starvation watchdog warns and data_wait_s accounts the
                   stall in the metrics stream; the run completes
  build race       two processes building the same index-map cache on a
                   fresh corpus: the cross-process lock + atomic writes
                   leave exactly one valid, untorn map set

Shares the tiny-CPU-run shape (and the persistent XLA compile cache) with
tests/test_fault_injection.py so the whole file fits the tier-1 budget.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MAX_STEPS = 4


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    from paddlefleetx_tpu.data.gpt_dataset import write_synthetic_corpus

    data = tmp_path_factory.mktemp("data_drill_corpus")
    write_synthetic_corpus(str(data / "corp"), vocab_size=128, num_docs=16)
    return str(data)


def _run(corpus, out_dir, metrics, fault=None, extra=(), check=True,
         max_steps=MAX_STEPS):
    overrides = [
        "Model.num_layers=2", "Model.hidden_size=32",
        "Model.num_attention_heads=4", "Model.vocab_size=128",
        "Model.max_position_embeddings=32",
        "Global.global_batch_size=8", "Global.local_batch_size=8",
        "Global.micro_batch_size=8",
        f"Engine.max_steps={max_steps}", "Engine.logging_freq=1",
        "Engine.eval_freq=0", "Engine.mix_precision.enable=False",
        "Engine.save_load.save_steps=0",
        f"Engine.save_load.output_dir={out_dir}",
        f"Engine.metrics_file={metrics}",
        f"Data.Train.dataset.input_dir={corpus}",
        "Data.Train.dataset.max_seq_len=32",
    ] + list(extra)
    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PFX_FAULT", None)
    if fault:
        env["PFX_FAULT"] = fault
    cmd = [sys.executable, os.path.join(REPO, "tools", "train.py"), "-c",
           os.path.join(REPO, "configs/gpt/pretrain_gpt_345M_single.yaml")]
    for o in overrides:
        cmd += ["-o", o]
    out = subprocess.run(
        cmd, capture_output=True, text=True, timeout=420, cwd=REPO, env=env
    )
    if check:
        assert out.returncode == 0, (out.returncode, out.stderr[-2000:])
    return out


def _records(metrics_path):
    with open(metrics_path) as f:
        return [json.loads(line) for line in f]


def _loss_stream(metrics_path):
    return {
        r["step"]: r["loss"] for r in _records(metrics_path) if "loss" in r
    }


@pytest.mark.slow  # tier-1 budget: the skip-budget contract is covered
# by the test_data.py units; the two-run CLI stream-parity spelling
# rides `make test-data-drill` / test-all
def test_corrupt_sample_skip_and_parity(corpus, tmp_path):
    """A corrupt sample at fetch 10 (batch 2) is skipped under
    max_skips=2: the run completes, a structured data_skip event lands in
    the metrics stream, and — because the substitute is deterministic —
    a second identical run reproduces the loss stream token-for-token."""
    streams = []
    for name in ("a", "b"):
        metrics = str(tmp_path / f"metrics_{name}.jsonl")
        run = _run(
            corpus, str(tmp_path / f"out_{name}"), metrics,
            fault="corrupt_sample:10",
            extra=("Data.Train.loader.max_skips=2",),
        )
        log = run.stdout + run.stderr
        assert "DATA SKIP" in log, log[-2000:]
        events = [r for r in _records(metrics) if r.get("event") == "data_skip"]
        assert len(events) == 1, events
        ev = events[0]
        assert ev["skips"] == 1 and ev["max_skips"] == 2
        assert "corrupt_sample" in ev["error"]
        assert ev["substitute"] != ev["index"]
        stream = _loss_stream(metrics)
        assert sorted(stream) == list(range(1, MAX_STEPS + 1)), stream
        streams.append(stream)
    assert streams[0] == streams[1]  # skip parity: same fault, same stream


@pytest.mark.slow  # ~7s CLI boot; tier-1 budget funding for the
# shard_map-port tests.  Replacement coverage: the loud max_skips budget
# exhaustion (RuntimeError naming data.max_skips) stays tier-1 via the
# test_data.py skip-budget units; the corrupt-sample CLI parity drill was
# already slow-marked (PR 7) on the same grounds; still in
# make test-data-drill / test-all.
def test_corrupt_sample_budget_exceeded_fails_loudly(corpus, tmp_path):
    """Three corrupt fetches in a row against max_skips=1: the run must
    fail (non-zero exit) naming the data.max_skips budget."""
    run = _run(
        corpus, str(tmp_path / "out"), str(tmp_path / "metrics.jsonl"),
        fault="corrupt_sample:10:3",
        extra=("Data.Train.loader.max_skips=1",), check=False,
    )
    assert run.returncode != 0
    assert "data.max_skips" in run.stderr, run.stderr[-2000:]


@pytest.mark.slow  # ~10s 12-step CLI run; tier-1 budget funding for the
# shard_map-port tests.  Replacement coverage: io_stall seconds-parse
# stays tier-1 via test_fault_tolerance, and the prefetch starvation
# watchdog + data_wait_s accounting stay tier-1 via the test_data.py
# PrefetchLoader stats/stall units; still in make test-data-drill /
# test-all.
def test_io_stall_watchdog_and_wait_accounting(corpus, tmp_path):
    """A 1.5s storage stall in a late sample fetch of a 12-step run
    (early stalls hide behind the first-step compile — prefetch doing its
    job), behind a prefetch depth of 2 with a 0.3s starvation threshold:
    the watchdog warns, the stall is charged to data_wait_s in the
    metrics stream, and the run completes normally."""
    metrics = str(tmp_path / "metrics.jsonl")
    run = _run(
        corpus, str(tmp_path / "out"), metrics,
        fault="io_stall:90:1.5", max_steps=12,
        extra=(
            "Data.Train.loader.prefetch=2",
            "Data.Train.loader.stall_warn_s=0.3",
        ),
    )
    log = run.stdout + run.stderr
    assert "prefetch starved" in log, log[-2000:]
    last = [r for r in _records(metrics) if "loss" in r][-1]
    assert last["data_wait_s"] > 0.4, last
    assert last["stall_warnings"] >= 1, last
    assert sorted(_loss_stream(metrics)) == list(range(1, 13))


def test_concurrent_index_map_build_race(tmp_path):
    """Two processes building the same index-map cache on a fresh corpus:
    the cross-process lock + atomic tmp+rename writes must leave ONE valid
    map set — no torn .npy, no quarantine, both builders exit 0, and the
    cached maps equal an independent in-memory build."""
    from paddlefleetx_tpu.data.gpt_dataset import GPTDataset, write_synthetic_corpus

    data = tmp_path / "race"
    prefix = write_synthetic_corpus(
        str(data / "corp"), vocab_size=300, num_docs=200, mean_len=300
    )
    script = (
        "import sys; sys.path.insert(0, %r); "
        "from paddlefleetx_tpu.data.gpt_dataset import GPTDataset; "
        "ds = GPTDataset(data_prefix=%r, max_seq_len=32, num_samples=2000, "
        "split=[1, 0, 0]); print('BUILT', ds.doc_idx.shape)"
    ) % (REPO, prefix)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script], cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for _ in range(2)
    ]
    for p in procs:
        out, err = p.communicate(timeout=180)
        assert p.returncode == 0, (out, err[-2000:])
        assert "BUILT" in out

    leftovers = [
        f for f in os.listdir(data)
        if ".tmp" in f or ".corrupt" in f or f.endswith(".lock.tmp")
    ]
    assert leftovers == [], leftovers
    # exactly one map set, readable and identical to a fresh in-memory build
    cached = GPTDataset(
        data_prefix=prefix, max_seq_len=32, num_samples=2000, split=[1, 0, 0]
    )
    fresh = GPTDataset(
        data_prefix=prefix, max_seq_len=32, num_samples=2000, split=[1, 0, 0],
        build_cache=False,
    )
    np.testing.assert_array_equal(cached.doc_idx, fresh.doc_idx)
    np.testing.assert_array_equal(cached.sample_idx, fresh.sample_idx)
    np.testing.assert_array_equal(cached.shuffle_idx, fresh.shuffle_idx)
    np.testing.assert_array_equal(cached[17]["tokens"], fresh[17]["tokens"])
