"""tools/auto.py --tune sweep end-to-end (reference AutoEngine.tune,
core/engine/auto_engine.py:146 + Strategy tuning knobs utils/config.py:
515-590): candidates may vary recompute / accumulation / precision, not
just mesh layout."""

import json
import os
import subprocess
import sys

import pytest

from tools.auto import enumerate_layouts, overrides_for

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_enumerate_layouts_covers_non_layout_knobs():
    cands = enumerate_layouts(8)
    assert {"dp": 8, "mp": 1, "pp": 1} in cands
    assert any(c.get("recompute") == "selective" for c in cands)
    assert any(c.get("recompute") == "full" for c in cands)
    assert any(c.get("accumulate") == 2 for c in cands)
    assert any(c.get("amp") == "bf16" for c in cands)
    # precision-memory knobs (the 1.3B-fit levers)
    assert any(c.get("main_grad") is False for c in cands)
    assert any(c.get("multi_precision") is False for c in cands)
    # single device still tunes execution knobs
    assert len(enumerate_layouts(1)) >= 5


def test_overrides_for_execution_knobs():
    ov = overrides_for(
        {"dp": 2, "recompute": "selective", "accumulate": 2, "amp": "bf16"},
        global_batch=16,
    )
    assert "Global.local_batch_size=8" in ov
    assert "Global.micro_batch_size=4" in ov  # local / accumulate
    assert "Model.use_recompute=True" in ov
    assert "Model.recompute_granularity=selective" in ov
    assert "Engine.mix_precision.enable=True" in ov
    assert "Engine.mix_precision.dtype=bfloat16" in ov
    # off-switches
    ov = overrides_for({"recompute": "none", "amp": "fp32"}, global_batch=8)
    assert "Model.use_recompute=False" in ov
    assert "Engine.mix_precision.enable=False" in ov
    # precision-memory knobs
    ov = overrides_for(
        {"amp": "bf16", "main_grad": False, "multi_precision": False},
        global_batch=8,
    )
    assert "Engine.mix_precision.main_grad=False" in ov
    assert "Optimizer.multi_precision=False" in ov


@pytest.mark.slow
def test_tune_sweep_e2e(tmp_path):
    """Two-candidate sweep varying only execution knobs: results JSON has
    per-candidate ips and a best line is printed."""
    from paddlefleetx_tpu.data.gpt_dataset import write_synthetic_corpus

    data = tmp_path / "data"
    data.mkdir()
    write_synthetic_corpus(str(data / "corp"), vocab_size=128, num_docs=16)
    out_dir = tmp_path / "out"

    base = os.path.join(REPO, "configs/gpt/pretrain_gpt_345M_single.yaml")
    cfg_path = tmp_path / "tune_tiny.yaml"
    cfg_path.write_text(
        f"""_base_: {base}

Global:
  global_batch_size: 8
  local_batch_size: 8
  micro_batch_size: 8

Model:
  num_layers: 2
  hidden_size: 64
  num_attention_heads: 4
  vocab_size: 128
  max_position_embeddings: 32

Engine:
  mix_precision:
    enable: False
  save_load:
    output_dir: {out_dir}

Data:
  Train:
    dataset:
      input_dir: {data}
      max_seq_len: 32

Tuning:
  candidates:
    - {{dp: 1, mp: 1, pp: 1, recompute: selective, amp: bf16}}
    - {{dp: 1, mp: 1, pp: 1, accumulate: 2}}
"""
    )

    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    # single-device sweep: conftest's 8-device XLA flag would leak in and
    # change the inferred dp world
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "auto.py"),
         "-c", str(cfg_path), "--tune", "--tune-steps", "4"],
        capture_output=True, text=True, timeout=540, cwd=REPO, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "best layout:" in out.stdout

    results = json.load(open(out_dir / "auto_tune_results.json"))
    assert len(results) == 2
    assert all(r["ok"] and r["ips"] > 0 for r in results)
    assert results[0]["layout"]["recompute"] == "selective"
    assert results[0]["layout"]["amp"] == "bf16"
    assert results[1]["layout"]["accumulate"] == 2


def test_overrides_for_attn_knobs():
    ov = overrides_for({"sep": 2, "attn": "ring", "zigzag": True}, global_batch=8)
    assert "Model.attn_impl=ring" in ov
    assert "Distributed.sep_zigzag=True" in ov
    assert "Distributed.sep_degree=2" in ov
