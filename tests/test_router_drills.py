"""Multi-host serving drills through the real CLIs (`make test-router`):
N `tools/serve.py` replicas behind `tools/router.py`, driven as real
processes (docs/serving.md "Multi-host serving").

  drain+kill   rolling drain under flood (the deploy primitive): the
               `router.py drain` CLI takes one replica out while traffic
               flows — ZERO dropped admitted requests (every in-flight
               request answers 200), the replica exits 0, and the router
               walks it draining -> gone.  Then a SIGKILL of a second
               replica mid-traffic: in-flight requests get an honest 503
               (never a hang, never a silent replay), new traffic fails
               over to the survivor, and the router ejects the corpse.
  disagg       prefill/decode pools: greedy output through
               prefill -> KV-handoff -> decode is TOKEN-IDENTICAL to a
               single-process continuous replica (f32 exact), with
               handoff bytes/seconds accounted on the router and
               export/adopt counters on the replicas.

Follows tests/test_serve_drills.py conventions: `fault`-marked,
subprocess-driven, tiny synthetic GPT, persistent XLA compile cache
shared through the environment (tests/conftest.py)."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest
import yaml

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = {
    "Global": {"global_batch_size": 8, "seed": 11},
    "Engine": {"mix_precision": {"enable": False},
               "save_load": {"save_steps": 0}},
    "Model": {
        "module": "GPTModule",
        "vocab_size": 96,
        "hidden_size": 32,
        "num_layers": 2,
        "num_attention_heads": 4,
        "max_position_embeddings": 64,
        "dtype": "float32",
    },
    "Optimizer": {"name": "FusedAdamW",
                  "lr": {"name": "Constant", "learning_rate": 1e-3}},
    "Generation": {"max_dec_len": 8, "decode_strategy": "greedy_search",
                   "pad_to_multiple": 8, "eos_token_id": 95,
                   "pad_token_id": 0},
}


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _env(extra=None):
    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PFX_FAULT", None)
    env.update(extra or {})
    return env


def _post(port, body, timeout=90, path="/generate"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return json.load(r)


def _metrics(port, timeout=10):
    from test_telemetry import parse_prometheus

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=timeout
    ) as r:
        metrics, _ = parse_prometheus(r.read().decode())
    return metrics


def _spawn_replica(cfg_path, port, *extra):
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-c", str(cfg_path), "--port", str(port),
         "--queue-depth", "32", "--deadline", "60",
         "--warmup-buckets", "4", "--warmup-batches", "1", *extra],
        env=_env(), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )


def _spawn_router(port, *args):
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "router.py"),
         "--port", str(port), "--poll-interval", "0.2",
         "--eject-after", "3", *args],
        env=_env(), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )


def _wait_healthy(procs_ports, timeout=300):
    """Wait for every (proc, port) to answer /healthz ok (they warm
    their compile families in PARALLEL off the shared XLA cache)."""
    end = time.time() + timeout
    pending = dict(procs_ports)
    while pending and time.time() < end:
        for port, proc in list(pending.items()):
            if proc.poll() is not None:
                raise AssertionError(
                    f"replica on {port} died at boot: "
                    f"{proc.stdout.read()[-3000:]}"
                )
            try:
                if _get(port, "/healthz", timeout=5).get("ok"):
                    del pending[port]
            except Exception:
                pass
        time.sleep(0.3)
    assert not pending, f"never healthy: {sorted(pending)}"


def _wait_eligible(router_port, n, timeout=30):
    end = time.time() + timeout
    h = {}
    while time.time() < end:
        try:
            h = _get(router_port, "/healthz")
        except Exception:  # router listener still booting
            h = {}
        if h.get("eligible", 0) >= n:
            return h
        time.sleep(0.2)
    raise AssertionError(f"router never saw {n} eligible replicas: {h}")


def _finish(proc, timeout=30):
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
    return proc.stdout.read()


@pytest.mark.slow  # ~55s warm: redundant tier-1 coverage funding the
# PR 13 disaggregated drills (still in make test-router/test-all).
# Replacement coverage: the drain contract stays tier-1-drilled by the
# elastic authenticated-remote-drain drill + the router drain units;
# SIGKILL-death failover through the real CLIs stays tier-1 via
# tests/test_disagg_drills.py (adopt_crash decode death + honest
# 200/503 accounting); never-retry-partial stays unit-proven in
# tests/test_router.py.
def test_rolling_drain_then_replica_kill_under_flood(tmp_path):
    """THE multi-host acceptance drill, one 3-replica topology, two
    phases:

    1. rolling drain under flood: `tools/router.py drain` takes r0 out
       while traffic flows — every request in the drain window answers
       200 (zero dropped admitted requests), r0 exits 0, the router
       walks it draining -> gone, traffic continues on the survivors.
    2. replica-kill mid-request: SIGKILL r1 under flood — every
       response is exactly one of 200/503 (an in-flight request on the
       corpse gets an honest 503, never a hang, never a replay), the
       router ejects it, and follow-up traffic serves 200 on r2."""
    cfg_path = tmp_path / "tiny_router.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    ports = [_free_port() for _ in range(3)]
    replicas = [
        _spawn_replica(cfg_path, p, "--replica-id", f"rep{i}")
        for i, p in enumerate(ports)
    ]
    rport = _free_port()
    router = None
    try:
        _wait_healthy(list(zip(ports, replicas)))
        router = _spawn_router(
            rport, *[a for p in ports
                     for a in ("--replica", f"http://127.0.0.1:{p}")],
        )
        h = _wait_eligible(rport, 3)
        assert h["mode"] == "replicated", h
        # identity satellite: the router (and a human) can tell the
        # replicas apart — distinct ids, roles, pids on /replicas
        views = _get(rport, "/replicas")["replicas"]
        assert {v["replica_id"] for v in views} == {"rep0", "rep1", "rep2"}
        assert {v["role"] for v in views} == {"monolith"}
        assert len({v["pid"] for v in views}) == 3
        rep_id = {v["key"]: v["replica_id"] for v in views}

        body = {"prompt_ids": [1, 2, 3], "max_tokens": 8, "deadline_s": 60}
        code, ref = _post(rport, body)
        assert code == 200, (code, ref)

        # ---- phase 1: rolling drain under flood ----
        stop = threading.Event()
        results, lock = [], threading.Lock()

        def flood():
            while not stop.is_set():
                c, _r = _post(rport, body, timeout=90)
                with lock:
                    results.append(c)

        threads = [threading.Thread(target=flood) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(1.0)  # traffic flowing on all replicas
        drain = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "router.py"),
             "drain", "--admin", f"http://127.0.0.1:{rport}",
             "--replica-id", "r0", "--timeout", "120"],
            env=_env(), cwd=REPO, capture_output=True, text=True,
            timeout=180,
        )
        assert drain.returncode == 0, (drain.stdout, drain.stderr)
        assert "drained and exited" in drain.stdout, drain.stdout
        # the drained replica honored the SIGTERM contract: exit 0
        drained = replicas[ports.index(ports[0])]
        assert drained.wait(timeout=60) == 0
        time.sleep(1.0)  # a little post-drain traffic on the survivors
        stop.set()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "hung connection through the drain"
        with lock:
            drain_codes = list(results)
        # ZERO dropped admitted requests: nothing 5xx'd or hung through
        # the whole drain window, and traffic really flowed
        assert drain_codes and all(c == 200 for c in drain_codes), (
            drain_codes
        )
        assert _get(rport, "/healthz")["replicas"]["r0"] == "gone"

        # ---- phase 2: replica kill mid-request ----
        results.clear()
        stop.clear()
        threads = [threading.Thread(target=flood) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.6)  # requests in flight on both survivors
        victim = replicas[1]
        victim.kill()  # SIGKILL: no drain, sockets die mid-exchange
        time.sleep(2.0)  # traffic through the failover window
        stop.set()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "hung connection through the kill"
        with lock:
            kill_codes = list(results)
        # exactly one honest outcome per request, never a hang: a request
        # that died with the victim 503s; everything else keeps serving
        assert kill_codes and all(c in (200, 503) for c in kill_codes), (
            kill_codes
        )
        assert kill_codes.count(200) >= 1, kill_codes

        # the router ejected the corpse (refused dispatch or failed
        # polls) and the survivor keeps answering token-identically
        end = time.time() + 20
        while time.time() < end:
            if _get(rport, "/healthz")["replicas"]["r1"] == "gone":
                break
            time.sleep(0.3)
        assert _get(rport, "/healthz")["replicas"]["r1"] == "gone"
        for _ in range(3):
            code, resp = _post(rport, body)
            assert code == 200, (code, resp)
            assert resp["completion_ids"] == ref["completion_ids"]

        # router accounting: dispatches landed on every replica, the
        # kill surfaced as lost/refused outcomes, depth/state gauges up
        m = _metrics(rport)
        req_total = m["pfx_router_requests_total"]
        seen = {dict(k)["replica"] for k in req_total}
        assert seen == {"r0", "r1", "r2"}, req_total
        outcomes = {dict(k)["outcome"] for k in req_total}
        assert "200" in outcomes and (
            "lost" in outcomes or "refused" in outcomes
        ), outcomes
        state_by_replica = {
            dict(k)["replica"]: v
            for k, v in m["pfx_router_replica_state"].items()
        }
        assert state_by_replica["r0"] == 4.0  # gone
        assert state_by_replica["r1"] == 4.0  # gone
        assert state_by_replica["r2"] == 2.0  # serving
        assert rep_id["r2"] == "rep2"

        # router's own drain contract: SIGTERM -> exit 0
        router.send_signal(signal.SIGTERM)
        assert router.wait(timeout=60) == 0
    finally:
        logs = [_finish(p) for p in replicas]
        rlog = _finish(router) if router is not None else ""
    for log in logs + [rlog]:
        assert "Traceback" not in log, log[-3000:]


@pytest.mark.slow  # ~17s three-process boot; tier-1 budget funding for
# the shard_map-port tests.  Replacement coverage: disaggregated
# prefill->decode parity vs single-process continuous stays tier-1 via
# test_disagg_drills::test_direct_transfer_bypasses_router_and_matches_proxy
# (asserts BOTH transports token-identical to single-process) and the
# in-process test_kv_handoff export->adopt parity suite; still in
# make test-router / test-disagg / test-all.
def test_disaggregated_prefill_decode_parity_via_router(tmp_path):
    """THE disaggregation acceptance drill: the same prompts through
    (a) one single-process `--scheduler continuous` replica and
    (b) router -> prefill replica -> KV handoff -> decode replica
    produce IDENTICAL greedy token ids (f32 exact), with handoff bytes
    and seconds accounted on the router and export/adopt counters on
    the replicas' own /metrics."""
    cfg_path = tmp_path / "tiny_disagg.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    mono_p, pre_p, dec_p = (_free_port() for _ in range(3))
    mono = _spawn_replica(cfg_path, mono_p, "--scheduler", "continuous",
                          "--cb-batch", "4")
    pre = _spawn_replica(cfg_path, pre_p, "--role", "prefill",
                         "--replica-id", "pre0")
    dec = _spawn_replica(cfg_path, dec_p, "--role", "decode",
                         "--cb-batch", "4", "--replica-id", "dec0")
    rport = _free_port()
    router = None
    try:
        _wait_healthy([(mono_p, mono), (pre_p, pre), (dec_p, dec)])
        # identity satellite: the roles are self-reported and distinct
        assert _get(pre_p, "/healthz")["identity"]["role"] == "prefill"
        ident = _get(dec_p, "/healthz")["identity"]
        assert ident["role"] == "decode"
        assert ident["scheduler"] == "continuous"
        assert ident["pid"] == dec.pid

        # a prefill replica refuses /generate honestly
        code, resp = _post(pre_p, {"prompt_ids": [1, 2], "max_tokens": 4})
        assert code == 400 and "prefill" in resp["error"], (code, resp)

        router = _spawn_router(
            rport,
            "--prefill", f"http://127.0.0.1:{pre_p}",
            "--decode", f"http://127.0.0.1:{dec_p}",
            # the PROXY transport is this drill's subject (the direct
            # topology has its own drill in tests/test_disagg_drills.py;
            # proxy stays the drilled fallback a failed direct send
            # degrades to, and its byte accounting is asserted below)
            "--handoff", "proxy",
        )
        h = _wait_eligible(rport, 2)
        assert h["mode"] == "disaggregated", h

        prompts = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 10]]
        for ids in prompts:
            body = {"prompt_ids": ids, "max_tokens": 6, "deadline_s": 60}
            c_ref, ref = _post(mono_p, body)
            c_got, got = _post(rport, body)
            assert c_ref == 200 and c_got == 200, (c_ref, c_got, got)
            # THE acceptance assert: disaggregated greedy output is
            # token-identical to the single-process continuous path
            assert got["completion_ids"] == ref["completion_ids"], ids

        # multi-prompt requests hand off per prompt and stay atomic
        body = {"prompts_ids": prompts, "max_tokens": 6, "deadline_s": 60}
        c_ref, ref = _post(mono_p, body)
        c_got, got = _post(rport, body)
        assert c_ref == 200 and c_got == 200
        assert got["completions_ids"] == ref["completions_ids"]

        # a text-mode request is refused honestly (no tokenizer here)
        code, resp = _post(rport, {"prompt": "hi", "max_tokens": 4})
        assert code == 400 and "token-id" in resp["error"], (code, resp)

        # handoff accounting: bytes + seconds on the router, export/
        # adopt counters on the replicas (warmup exports excluded)
        n = len(prompts) * 2  # singles + the batch
        m = _metrics(rport)
        assert m["pfx_router_handoff_bytes_total"][frozenset()] > 0
        assert m["pfx_router_handoff_seconds_count"][frozenset()] == n
        pre_m = _metrics(pre_p)
        dec_m = _metrics(dec_p)
        assert pre_m["pfx_handoff_exports_total"][frozenset()] == n
        assert dec_m["pfx_handoff_adopts_total"][frozenset()] == n
        # adoption rides the admission path: admits counted, arena clean
        assert dec_m["pfx_prefill_admits_total"][frozenset()] >= n
        assert dec_m["pfx_kv_blocks_used"][frozenset()] == 0

        # every process honors the drain contract: SIGTERM -> exit 0
        for proc in (router, mono, pre, dec):
            proc.send_signal(signal.SIGTERM)
        for proc in (router, mono, pre, dec):
            assert proc.wait(timeout=60) == 0
    finally:
        logs = [_finish(p) for p in (mono, pre, dec)]
        rlog = _finish(router) if router is not None else ""
    for log in logs + [rlog]:
        assert "Traceback" not in log, log[-3000:]
