"""Fleet KV durability: the host-RAM spill tier, peer-to-peer prefix
migration, and prefix-affinity routing (`make test-kv-tier`,
docs/serving.md "KV lifecycle").

Cached KV must survive the three events that used to destroy it:

  spill tier     an LRU-evicted radix node demotes its block to a
                 bounded pinned-host store; a later prefix match
                 READMITS it instead of recomputing — checksum-verified,
                 degrade-to-recompute on every failure mode
                 (spill_corrupt, pool pressure, budget), ArenaReset
                 invalidates the whole store atomically;
  migration      a draining replica ships its hottest published
                 prefixes to a surviving peer (PFXH1 over
                 POST /admin/adopt_prefixes); the receiver validates
                 the payload in FULL before anything touches its arena
                 and never half-adopts; a wedged receiver can NEVER
                 stall the drain contract (hard PFX_MIGRATE_DEADLINE_S,
                 exit 0 regardless);
  affinity       the router folds cached-prefix overlap into the
                 least-loaded score — capped, so a warm cache breaks
                 ties but never overrides a deadline-infeasible or
                 blocks-exhausted replica.

In-process tests stay tier-1; the multi-process CLI drills are
slow+fault-marked (subprocess-driven, tests/test_router_drills.py
conventions)."""

import json
import os
import re
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = {
    "Global": {"global_batch_size": 8, "seed": 7},
    "Engine": {"mix_precision": {"enable": False},
               "save_load": {"save_steps": 0}},
    "Model": {
        "module": "GPTModule",
        "vocab_size": 96,
        "hidden_size": 32,
        "num_layers": 2,
        "num_attention_heads": 4,
        "max_position_embeddings": 128,
        "dtype": "float32",
    },
    "Distributed": {},
    "Optimizer": {"name": "FusedAdamW",
                  "lr": {"name": "Constant", "learning_rate": 1e-3}},
    "Generation": {"max_dec_len": 8, "decode_strategy": "greedy_search",
                   "pad_to_multiple": 8, "eos_token_id": 95,
                   "pad_token_id": 0},
}

# block=8 geometry: one-full-block families whose prefixes evict each
# other under a ONE-block index budget — the smallest trace that forces
# spill -> readmit (match() caps at len-1, so prompts exceed the block)
BLK = 8
PFX_A = list(range(1, 9))     # family A's shared full block
PFX_B = list(range(10, 18))   # family B's — evicts A under budget 1
A1 = PFX_A + [40, 41, 42]
A2 = PFX_A + [50, 51]
B1 = PFX_B + [60, 61, 62]


@pytest.fixture(scope="module")
def server():
    import jax

    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict.from_nested(TINY)
    cfg = process_configs(cfg, num_devices=jax.device_count())
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    return GenerationServer(cfg, mesh, module)


def _engine(server, **kw):
    from paddlefleetx_tpu.core.continuous_batching import PagedDecodeEngine

    kw.setdefault("max_batch", 4)
    kw.setdefault("block", BLK)
    return PagedDecodeEngine(server, **kw)


def _drain_engine(engine, max_steps=64):
    for _ in range(max_steps):
        engine.step()
        if not engine.active.any():
            return
    raise AssertionError("engine never drained")


def _serve_release(engine, prompt, max_new=6):
    """One request start-to-finish: admit -> decode -> release (release
    publishes the prompt's full blocks to the radix index)."""
    slot = engine.admit(prompt, max_new)
    _drain_engine(engine)
    tokens = engine.slots[slot].tokens
    engine.release(slot)
    return tokens


@pytest.fixture(scope="module")
def refs(server):
    """Greedy coalesce-path references — every cached/spilled/migrated
    path below must reproduce these EXACTLY (f32)."""
    return {tuple(p): server.generate_ids([p], max_dec_len=6)[0]
            for p in (A1, A2, B1)}


# ---------------------------------------------------------------------------
# PrefixSpillStore units (pure host)
# ---------------------------------------------------------------------------


def _arrs(rng, n=64):
    return {"k": rng.standard_normal((2, 1, 4, BLK, n)).astype(np.float32),
            "v": rng.standard_normal((2, 1, 4, BLK, n)).astype(np.float32)}


def test_spill_store_budget_lru_and_checksum():
    from paddlefleetx_tpu.core.paged_cache import PrefixSpillStore

    rng = np.random.default_rng(0)
    one = sum(a.nbytes for a in _arrs(rng).values())
    store = PrefixSpillStore(budget_bytes=2 * one)
    a0, a1, a2 = _arrs(rng), _arrs(rng), _arrs(rng)

    assert store.put((1,), a0) and store.put((2,), a1)
    assert store.bytes_used() == 2 * one and len(store) == 2
    # bit-exact round trip
    got = store.get((1,))
    assert got["k"].tobytes() == a0["k"].tobytes()
    # the get bumped (1,) most-recent: admitting a third LRU-evicts (2,)
    assert store.put((3,), a2)
    assert store.get((2,)) is None
    assert store.get((1,)) is not None
    assert store.stats["discards"] == 1  # the LRU eviction, counted
    # pop == successful readmit
    store.pop((1,))
    assert len(store) == 1 and store.stats["readmits"] == 1
    # checksum: a torn entry is dropped, never handed back
    store._entries[(3,)]["arrays"]["k"][0, 0, 0, 0, 0] += 1.0
    assert store.get((3,)) is None
    assert store.stats["discards"] == 2
    assert len(store) == 0 and store.bytes_used() == 0


def test_spill_store_disabled_oversize_and_clear():
    from paddlefleetx_tpu.core.paged_cache import PrefixSpillStore

    rng = np.random.default_rng(1)
    with pytest.raises(ValueError, match=">= 0"):
        PrefixSpillStore(budget_bytes=-1)
    off = PrefixSpillStore(budget_bytes=0)
    assert not off.enabled and not off.put((1,), _arrs(rng))
    # an entry that alone exceeds the budget is refused outright (loud)
    tiny = PrefixSpillStore(budget_bytes=16)
    assert not tiny.put((1,), _arrs(rng))
    assert tiny.stats["discards"] == 1 and len(tiny) == 0
    # clear() (ArenaReset) empties without counting pressure discards
    store = PrefixSpillStore(budget_bytes=1 << 30)
    store.put((1,), _arrs(rng))
    store.put((2,), _arrs(rng))
    d0 = store.stats["discards"]
    assert store.clear() == 2
    assert len(store) == 0 and store.bytes_used() == 0
    assert store.stats["discards"] == d0
    assert store.get((1,)) is None


# ---------------------------------------------------------------------------
# spill -> readmit on a live engine
# ---------------------------------------------------------------------------


def _spilled_engine(server, refs, **kw):
    """Build a spill-enabled engine and run the A -> B eviction trace:
    returns it with family A's full block demoted to the host store."""
    kw.setdefault("prefix_cache_blocks", 1)
    kw.setdefault("prefix_spill_bytes", 64 << 20)
    eng = _engine(server, **kw)
    assert _serve_release(eng, A1) == refs[tuple(A1)]  # publishes PFX_A
    assert _serve_release(eng, B1) == refs[tuple(B1)]  # evicts -> spills
    assert eng.cache.spill.stats["spills"] >= 1
    assert len(eng.cache.spill) >= 1
    return eng


def test_spill_then_readmit_round_trip(server, refs):
    """The tentpole contract: an evicted prefix comes back from host
    RAM — the readmitted request hits (prefill = suffix only) and its
    tokens are IDENTICAL to the uncached reference."""
    from paddlefleetx_tpu.utils.telemetry import get_registry

    reg = get_registry()
    eng = _spilled_engine(server, refs)
    sp0 = reg.value("pfx_prefix_spills_total") or 0
    rd0 = reg.value("pfx_prefix_readmits_total") or 0
    t0 = eng.stats["prefill_tokens"]
    h0 = eng.cache.prefix.stats["hits"]
    ht0 = eng.cache.prefix.stats["hit_tokens"]

    assert _serve_release(eng, A2) == refs[tuple(A2)]

    assert eng.cache.spill.stats["readmits"] == 1
    assert eng.cache.prefix.stats["hits"] - h0 == 1
    assert eng.cache.prefix.stats["hit_tokens"] - ht0 == BLK
    # only the 2-token suffix prefilled — the block came back from host
    assert eng.stats["prefill_tokens"] - t0 == len(A2) - BLK
    # registry counters moved in lockstep with the store's own stats
    assert (reg.value("pfx_prefix_readmits_total") or 0) - rd0 == 1
    assert (reg.value("pfx_prefix_spills_total") or 0) >= sp0
    # spill gauges report the store truthfully
    st = eng.cache.stats()
    assert st["prefix_spill_entries"] == len(eng.cache.spill)
    assert st["prefix_spill_bytes"] == eng.cache.spill.bytes_used()


def test_spill_corrupt_degrades_to_recompute(server, refs, monkeypatch):
    """docs/fault_tolerance.md spill_corrupt: a torn host entry is
    discarded LOUDLY and the request recomputes and SUCCEEDS — graceful
    degradation, never a failed request."""
    from paddlefleetx_tpu.utils.resilience import reset_fault_state
    from paddlefleetx_tpu.utils.telemetry import get_registry

    reg = get_registry()
    eng = _spilled_engine(server, refs)
    monkeypatch.setenv("PFX_FAULT", "spill_corrupt:1")
    reset_fault_state()
    try:
        dc0 = eng.cache.spill.stats["discards"]
        dcr0 = reg.value("pfx_prefix_spill_discards_total") or 0
        t0 = eng.stats["prefill_tokens"]
        h0 = eng.cache.prefix.stats["hits"]

        assert _serve_release(eng, A2) == refs[tuple(A2)]  # still right

        assert eng.cache.spill.stats["readmits"] == 0
        assert eng.cache.spill.stats["discards"] - dc0 == 1
        assert (reg.value("pfx_prefix_spill_discards_total") or 0) \
            - dcr0 == 1
        # full recompute: no hit, the whole prompt prefilled
        assert eng.cache.prefix.stats["hits"] == h0
        assert eng.stats["prefill_tokens"] - t0 == len(A2)
    finally:
        monkeypatch.delenv("PFX_FAULT", raising=False)
        reset_fault_state()


def test_arena_reset_invalidates_spilled_entries(server, refs):
    """ArenaReset atomicity: reset() drops the radix index AND the
    spill store in the same breath — a host copy of a dead arena's
    block must never readmit."""
    eng = _spilled_engine(server, refs)
    assert len(eng.cache.spill) >= 1
    eng.reset()
    assert len(eng.cache.spill) == 0
    assert eng.cache.spill.bytes_used() == 0
    assert eng.cache.prefix.cached_blocks() == 0
    # the rebuilt arena serves correctly and nothing stale resurfaces
    rd0 = eng.cache.spill.stats["readmits"]
    t0 = eng.stats["prefill_tokens"]
    assert _serve_release(eng, A2) == refs[tuple(A2)]
    assert eng.cache.spill.stats["readmits"] == rd0
    assert eng.stats["prefill_tokens"] - t0 == len(A2)  # full recompute


def test_spill_counters_replay_exactly(server, refs):
    """The exact-replay contract, spill edition: an untruncated
    decision log folds to the same spill/readmit totals the store and
    the registry report."""
    from paddlefleetx_tpu.core.continuous_batching import ContinuousScheduler
    from paddlefleetx_tpu.utils.telemetry import get_registry
    from paddlefleetx_tpu.utils.tracing import replay_decision_log

    reg = get_registry()
    sp0 = reg.value("pfx_prefix_spills_total") or 0
    rd0 = reg.value("pfx_prefix_readmits_total") or 0
    eng = _engine(server, prefix_cache_blocks=1,
                  prefix_spill_bytes=64 << 20)
    sched = ContinuousScheduler(eng, max_depth=8, name="kv-tier-test")
    sched.start()
    try:
        # sequential (result() between submits): publish order must be
        # A -> B(evicts A) -> A(readmits) for the trace to spill
        for p in (A1, B1, A2):
            assert sched.submit([p], 6, deadline_s=120).result(
                timeout=300)[0] == refs[tuple(p)]
    finally:
        assert sched.shutdown(timeout=30)

    replay = replay_decision_log(sched.decision_log)
    assert eng.cache.spill.stats["readmits"] == 1
    assert replay["spills"] == eng.cache.spill.stats["spills"] >= 1
    assert replay["readmits"] == 1
    assert replay["spill_discards"] == eng.cache.spill.stats["discards"]
    assert (reg.value("pfx_prefix_spills_total") or 0) - sp0 \
        == replay["spills"]
    assert (reg.value("pfx_prefix_readmits_total") or 0) - rd0 == 1


# ---------------------------------------------------------------------------
# peer-to-peer prefix migration (in-process halves)
# ---------------------------------------------------------------------------


def test_export_adopt_prefixes_cross_engine(server, refs):
    """Donor export -> PFXH1 bytes -> receiver adoption: the survivor
    answers the donor's traffic with HITS, token-identically; a re-send
    is idempotent."""
    from paddlefleetx_tpu.core.paged_cache import pack_handoff, unpack_handoff
    from paddlefleetx_tpu.utils.telemetry import get_registry

    reg = get_registry()
    donor = _engine(server, prefix_cache_blocks=8)
    assert _serve_release(donor, A1) == refs[tuple(A1)]
    assert _serve_release(donor, B1) == refs[tuple(B1)]

    export = donor.export_hot_prefixes(64)
    assert export is not None
    meta, arrays = unpack_handoff(pack_handoff(*export))
    paths = {tuple(p) for p in meta["prefixes"]}
    assert tuple(PFX_A) in paths and tuple(PFX_B) in paths

    receiver = _engine(server, prefix_cache_blocks=8)
    ad0 = reg.value("pfx_migrate_adopted_total") or 0
    n = receiver.adopt_prefixes(meta, arrays)
    assert n == len(meta["prefixes"]) >= 2
    assert receiver.cache.prefix.cached_blocks() == n
    assert receiver.stats["migrate_adopted"] == n
    assert (reg.value("pfx_migrate_adopted_total") or 0) - ad0 == n
    # idempotent: an already-cached path only bumps LRU
    assert receiver.adopt_prefixes(meta, arrays) == 0

    # the adopted KV is the real thing: hit-path decode == reference
    t0 = receiver.stats["prefill_tokens"]
    h0 = receiver.cache.prefix.stats["hits"]
    assert _serve_release(receiver, A2) == refs[tuple(A2)]
    assert receiver.cache.prefix.stats["hits"] - h0 == 1
    assert receiver.stats["prefill_tokens"] - t0 == len(A2) - BLK


def test_export_is_ancestor_closed_and_ordered(server, refs):
    """A deep chain exports parents-before-children (shortest path
    first) so the receiver can stop cleanly at ANY boundary and still
    hold a valid prefix."""
    deep = list(range(1, 17))  # 2 chained full blocks
    donor = _engine(server, prefix_cache_blocks=8)
    _serve_release(donor, deep + [40, 41])
    meta, _arrays = donor.export_hot_prefixes(1)  # ask for ONE block
    paths = [list(p) for p in meta["prefixes"]]
    # the hottest block is the 16-deep child: its 8-deep ancestor came
    # along, ordered first
    assert paths == [deep[:8], deep]


def test_adopt_rejects_torn_payload_whole(server, refs):
    """The adopt rule: a torn or incompatible migration payload is
    rejected WHOLE before anything touches the arena — never
    half-adopted."""
    from paddlefleetx_tpu.core.paged_cache import pack_handoff, unpack_handoff

    donor = _engine(server, prefix_cache_blocks=8)
    _serve_release(donor, A1)
    meta, arrays = unpack_handoff(pack_handoff(*donor.export_hot_prefixes(64)))

    receiver = _engine(server, prefix_cache_blocks=8)

    def untouched():
        assert receiver.cache.stats()["kv_blocks_used"] == 0
        assert receiver.cache.prefix.cached_blocks() == 0
        assert receiver.stats["migrate_adopted"] == 0

    missing = {n: a for n, a in arrays.items() if n != "v"}
    with pytest.raises(ValueError, match="missing arrays"):
        receiver.adopt_prefixes(meta, missing)
    untouched()

    torn = dict(arrays)
    torn["k"] = arrays["k"][:, :0]  # right dtype, zero blocks
    with pytest.raises(ValueError, match="does not carry"):
        receiver.adopt_prefixes(meta, torn)
    untouched()

    bad_meta = dict(meta)
    bad_meta["block"] = BLK * 2
    with pytest.raises(ValueError, match="block size"):
        receiver.adopt_prefixes(bad_meta, arrays)
    untouched()

    ragged = dict(meta)
    ragged["prefixes"] = [PFX_A[:5]]  # not a block multiple
    with pytest.raises(ValueError, match="multiple"):
        receiver.adopt_prefixes(ragged, arrays)
    untouched()

    empty = dict(meta)
    empty["prefixes"] = []
    with pytest.raises(ValueError, match="no prefixes"):
        receiver.adopt_prefixes(empty, arrays)
    untouched()


# ---------------------------------------------------------------------------
# prefix-affinity routing (stub replicas, no model)
# ---------------------------------------------------------------------------


def _replica(key="r0", role="monolith", **kw):
    from paddlefleetx_tpu.core.router import Replica

    r = Replica(key=key, url=f"http://x/{key}", role=role,
                state="serving")
    r.healthy = True
    for k, v in kw.items():
        setattr(r, k, v)
    return r


def test_affinity_counts_contiguous_overlap_only():
    from paddlefleetx_tpu.core.paged_cache import prefix_digest_hashes
    from paddlefleetx_tpu.core.router import RouterCore

    tokens = list(range(1, 25))  # 3 full 8-blocks
    hashes = prefix_digest_hashes(tokens, BLK)
    assert len(hashes) == 3

    warm = _replica(prefix_block=BLK, prefix_hashes=frozenset(hashes))
    cache = {}
    assert RouterCore._affinity(warm, tokens, cache) == 3.0
    assert BLK in cache  # memoised per advertised block size
    assert RouterCore._affinity(warm, tokens, cache) == 3.0

    partial = _replica(prefix_block=BLK,
                       prefix_hashes=frozenset(hashes[:2]))
    assert RouterCore._affinity(partial, tokens, {}) == 2.0
    # contiguity is the usability rule: a child block without its
    # ancestors is unreachable — missing root means ZERO overlap
    orphan = _replica(prefix_block=BLK,
                      prefix_hashes=frozenset(hashes[1:]))
    assert RouterCore._affinity(orphan, tokens, {}) == 0.0

    # degenerate advertisements never crash or score
    assert RouterCore._affinity(_replica(), tokens, {}) == 0.0
    assert RouterCore._affinity(
        _replica(prefix_block=0, prefix_hashes=frozenset(hashes)),
        tokens, {}) == 0.0
    assert RouterCore._affinity(warm, None, {}) == 0.0
    assert RouterCore._affinity(warm, [], {}) == 0.0
    assert RouterCore._affinity(
        _replica(prefix_block=BLK, prefix_hashes=frozenset({1, 2, 3})),
        tokens, {}) == 0.0


def test_affinity_is_capped_and_never_overrides_penalties():
    from paddlefleetx_tpu.core.router import _AFFINITY_CAP, RouterCore

    core = RouterCore([("http://127.0.0.1:1", "monolith")])
    r = _replica(depth=6)
    base = core._score(r, 60.0)
    # capped subtraction: a mile-deep warm cache is worth at most CAP
    assert core._score(r, 60.0, affinity=1e9) \
        == core._score(r, 60.0, affinity=_AFFINITY_CAP) \
        == base - _AFFINITY_CAP
    assert core._score(r, 60.0, affinity=-5.0) == base  # never a bonus

    # blocks-exhausted decode replica: affinity cannot buy it back
    ok = _replica("r1", role="decode", available_blocks=4)
    dry = _replica("r2", role="decode", available_blocks=0)
    assert core._score(dry, 60.0, affinity=1e9) \
        > core._score(ok, 60.0) + 1e4
    # deadline-infeasible: est wait >> remaining loses regardless
    late = _replica("r3", depth=100, last_latency_s=10.0)
    assert core._score(late, 5.0, affinity=1e9) \
        > core._score(_replica("r4"), 5.0) + 1e5


def test_pick_steers_ties_to_the_warm_replica():
    from paddlefleetx_tpu.core.paged_cache import prefix_digest_hashes
    from paddlefleetx_tpu.core.router import RouterCore

    tokens = list(range(1, 25))
    core = RouterCore([("http://127.0.0.1:1", "monolith"),
                       ("http://127.0.0.1:2", "monolith")])
    cold, warm = core.replicas["r0"], core.replicas["r1"]
    for r in (cold, warm):
        r.state, r.healthy = "serving", True
    warm.prefix_block = BLK
    warm.prefix_hashes = frozenset(prefix_digest_hashes(tokens, BLK))

    # equal load: affinity breaks the tie toward the warm replica,
    # beating the round-robin cursor every time
    for _ in range(4):
        picked = core.pick("monolith", 60.0, prefix_tokens=tokens)
        assert picked.key == "r1"
        picked.in_flight = 0
    # no prompt ids -> plain least-loaded (round-robin alternates)
    seen = set()
    for _ in range(4):
        p = core.pick("monolith", 60.0)
        seen.add(p.key)
        p.in_flight = 0
    assert seen == {"r0", "r1"}
    # a deadline-infeasible warm replica loses to the cold one: the cap
    # holds through pick(), not just _score()
    warm.depth, warm.last_latency_s = 100, 10.0
    assert core.pick("monolith", 5.0,
                     prefix_tokens=tokens).key == "r0"


# ---------------------------------------------------------------------------
# the rolling-drain CLI drills (slow+fault: make test-kv-tier)
# ---------------------------------------------------------------------------


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _env(extra=None):
    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PFX_FAULT", None)
    env.update(extra or {})
    return env


def _post(port, body, timeout=90, path="/generate"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return json.load(r)


def _metrics(port, timeout=10):
    from test_telemetry import parse_prometheus

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=timeout
    ) as r:
        metrics, _ = parse_prometheus(r.read().decode())
    return metrics


def _mval(metrics, name):
    return metrics.get(name, {}).get(frozenset(), 0.0)


def _spawn_replica(cfg_path, port, rid, extra_env=None, *extra):
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-c", str(cfg_path), "--port", str(port),
         "--scheduler", "continuous", "--cb-batch", "4",
         "--queue-depth", "32", "--deadline", "60",
         "--warmup-buckets", "4",
         "--prefix-cache-blocks", "32",
         "--prefix-spill-bytes", str(8 << 20),
         "--replica-id", rid],
        env=_env(extra_env), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )


def _wait_healthy(procs_ports, timeout=300):
    end = time.time() + timeout
    pending = dict(procs_ports)
    while pending and time.time() < end:
        for port, proc in list(pending.items()):
            if proc.poll() is not None:
                raise AssertionError(
                    f"replica on {port} died at boot: "
                    f"{proc.stdout.read()[-3000:]}"
                )
            try:
                if _get(port, "/healthz", timeout=5).get("ok"):
                    del pending[port]
            except Exception:
                pass
        time.sleep(0.3)
    assert not pending, f"never healthy: {sorted(pending)}"


def _finish(proc, timeout=30):
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
    return proc.stdout.read()


DRILL_PFX = list(range(1, 17))  # 2 full 8-blocks shared by the family


def _family(tail):
    return {"prompt_ids": DRILL_PFX + tail, "max_tokens": 6,
            "deadline_s": 60}


@pytest.mark.fault
@pytest.mark.slow  # ~2 CLI replica boots + router; make test-kv-tier
def test_drain_migrates_prefixes_to_survivor_under_stall(tmp_path):
    """THE KV-durability acceptance drill through the real CLIs: two
    prefix-cached replicas behind the router, sticky prefix-heavy
    traffic warm on r0; `router.py drain r0` under migrate_stall —

      - the drain exits 0 (the stall burns budget, never the contract),
      - the survivor adopts the donor's prefixes (zero half-adopted:
        every shipped block landed),
      - the survivor's post-drain hit rate on the donor's family beats
        its pre-drain baseline (cold: zero), token-identically."""
    cfg_path = tmp_path / "tiny_kv_tier.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    p0, p1 = _free_port(), _free_port()
    # the donor's receiver wedges ONCE at the send site, for 2s of a
    # 30s migration budget: delayed, then delivered
    r0 = _spawn_replica(cfg_path, p0, "rep0",
                        {"PFX_FAULT": "migrate_stall:1",
                         "PFX_FAULT_HANG_S": "2",
                         "PFX_MIGRATE_DEADLINE_S": "30"})
    r1 = _spawn_replica(cfg_path, p1, "rep1")
    rport = _free_port()
    router = None
    try:
        _wait_healthy([(p0, r0), (p1, r1)])
        router = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tools", "router.py"),
             "--port", str(rport), "--poll-interval", "0.2",
             "--replica", f"http://127.0.0.1:{p0}",
             "--replica", f"http://127.0.0.1:{p1}"],
            env=_env(), cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        end = time.time() + 30
        while time.time() < end:
            try:
                if _get(rport, "/healthz").get("eligible", 0) >= 2:
                    break
            except Exception:
                pass
            time.sleep(0.2)

        # warm the family on r0 DIRECTLY (publishes its 2 full blocks)
        code, ref = _post(p0, _family([40, 41, 42]))
        assert code == 200, ref
        code, hit = _post(p0, _family([40, 41, 42]))
        assert code == 200 and hit["completion_ids"] == ref["completion_ids"]
        m0 = _metrics(p0)
        assert _mval(m0, "pfx_prefix_hits_total") >= 1

        # the router polls r0's digest advertisement...
        end = time.time() + 20
        adv = 0
        while time.time() < end:
            views = _get(rport, "/replicas")["replicas"]
            adv = max(v.get("prefix_hashes_advertised", 0)
                      for v in views)
            if adv:
                break
            time.sleep(0.3)
        assert adv >= 1, views
        # ...and affinity steers the family to the warm replica: r0
        # hits again, the cold survivor sees none of it
        h0_pre = _mval(_metrics(p0), "pfx_prefix_hits_total")
        code, via = _post(rport, _family([40, 41, 42]))
        assert code == 200 and via["completion_ids"] == ref["completion_ids"]
        assert _mval(_metrics(p0), "pfx_prefix_hits_total") > h0_pre
        m1_pre = _metrics(p1)
        survivor_pre_hits = _mval(m1_pre, "pfx_prefix_hits_total")
        assert _mval(m1_pre, "pfx_migrate_adopted_total") == 0

        # drain the warm replica through the real CLI (the router hands
        # it the survivor list; the stall fires at the send site)
        drain = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "router.py"),
             "drain", "--admin", f"http://127.0.0.1:{rport}",
             "--replica-id", "r0", "--timeout", "120"],
            env=_env(), cwd=REPO, capture_output=True, text=True,
            timeout=180,
        )
        assert drain.returncode == 0, (drain.stdout, drain.stderr)
        assert r0.wait(timeout=60) == 0  # exit 0 despite the stall
        out0 = r0.stdout.read()
        m = re.search(r"adopted (\d+) of (\d+) prefix block", out0)
        assert m, out0[-2000:]
        # zero half-adopted: every block the donor shipped landed
        # (the 16-token family prefix is ONE default-block-16 block)
        assert int(m.group(1)) == int(m.group(2)) >= 1, out0[-2000:]
        m1 = _metrics(p1)
        assert _mval(m1, "pfx_migrate_adopted_total") \
            == int(m.group(1))

        # the survivor answers the dead replica's traffic with HITS:
        # post-drain hit rate beats the pre-drain baseline (cold), and
        # greedy tokens are IDENTICAL to the donor's (f32)
        code, after = _post(rport, _family([40, 41, 42]))
        assert code == 200, after
        assert after["completion_ids"] == ref["completion_ids"]
        m1_post = _metrics(p1)
        assert _mval(m1_post, "pfx_prefix_hits_total") \
            > survivor_pre_hits
        assert _mval(m1_post, "pfx_prefix_hit_tokens_total") \
            >= len(DRILL_PFX)
    finally:
        for proc in (router, r0, r1):
            if proc is not None:
                _finish(proc)


@pytest.mark.fault
@pytest.mark.slow  # 2 CLI replica boots; make test-kv-tier
def test_wedged_receiver_never_stalls_the_drain(tmp_path):
    """The failover ladder's hard floor: with the receiver wedged on
    EVERY attempt and a 3s migration deadline, the drain still
    completes and exits 0 promptly; the survivor adopted NOTHING (zero
    half-adopted prefixes) and keeps serving."""
    cfg_path = tmp_path / "tiny_kv_tier.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    p0, p1 = _free_port(), _free_port()
    r0 = _spawn_replica(cfg_path, p0, "rep0",
                        {"PFX_FAULT": "migrate_stall:1:99",
                         "PFX_FAULT_HANG_S": "60",
                         "PFX_MIGRATE_DEADLINE_S": "3"})
    r1 = _spawn_replica(cfg_path, p1, "rep1")
    try:
        _wait_healthy([(p0, r0), (p1, r1)])
        code, ref = _post(p0, _family([40, 41, 42]))
        assert code == 200, ref

        t0 = time.time()
        code, body = _post(
            p0, {"migrate_to": [f"http://127.0.0.1:{p1}"]},
            path="/admin/drain",
        )
        assert code == 200, body
        assert r0.wait(timeout=60) == 0
        # the whole drain (incl. the burned 3s migration budget) stayed
        # well inside the stall duration the fault asked for (60s)
        assert time.time() - t0 < 45
        out0 = r0.stdout.read()
        assert "no surviving peer adopted" in out0, out0[-2000:]

        m1 = _metrics(p1)
        assert _mval(m1, "pfx_migrate_adopted_total") == 0
        assert _mval(m1, "pfx_prefix_cached_blocks") == 0
        code, resp = _post(p1, _family([40, 41, 42]))
        assert code == 200 and resp["completion_ids"] == ref["completion_ids"]
    finally:
        for proc in (r0, r1):
            _finish(proc)
