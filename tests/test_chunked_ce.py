"""Chunked softmax-CE (ops/chunked_ce.py): exact value+grad parity with the
materialized-logits path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.ops.chunked_ce import chunked_cross_entropy


def _ref(hidden, word, labels, mask):
    logits = jnp.einsum("bsh,vh->bsv", hidden, word).astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    m = mask.astype(jnp.float32)
    return jnp.sum((lse - picked) * m) / jnp.maximum(jnp.sum(m), 1.0)


def test_value_and_grads_match_reference():
    key = jax.random.key(0)
    kh, kw, kl = jax.random.split(key, 3)
    b, s, h, v = 2, 8, 16, 96
    hidden = jax.random.normal(kh, (b, s, h), jnp.float32)
    word = jax.random.normal(kw, (v, h), jnp.float32) * 0.1
    labels = jax.random.randint(kl, (b, s), 0, v)
    mask = jnp.ones((b, s), jnp.float32).at[1, 5:].set(0.0)

    for chunk in (96, 32, 48):
        got = chunked_cross_entropy(hidden, word, labels, mask, chunk=chunk)
        ref = _ref(hidden, word, labels, mask)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)

        g_got = jax.grad(
            lambda hh, ww: chunked_cross_entropy(hh, ww, labels, mask, chunk=chunk),
            argnums=(0, 1),
        )(hidden, word)
        g_ref = jax.grad(lambda hh, ww: _ref(hh, ww, labels, mask), argnums=(0, 1))(
            hidden, word
        )
        for a, b_ in zip(g_got, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-5)


def test_bf16_hidden_and_nondivisible_chunk():
    key = jax.random.key(1)
    kh, kw, kl = jax.random.split(key, 3)
    hidden = jax.random.normal(kh, (1, 4, 8), jnp.bfloat16)
    word = (jax.random.normal(kw, (60, 8), jnp.float32) * 0.1).astype(jnp.bfloat16)
    labels = jax.random.randint(kl, (1, 4), 0, 60)
    got = chunked_cross_entropy(hidden, word, labels, chunk=64)  # falls to divisor
    ref = _ref(hidden.astype(jnp.float32), word.astype(jnp.float32), labels,
               jnp.ones((1, 4)))
    np.testing.assert_allclose(float(got), float(ref), rtol=2e-2)


def test_gpt_loss_fn_integration():
    """use_chunked_ce produces the same loss+grads as the default path."""
    import dataclasses

    from paddlefleetx_tpu.models.gpt import model as gpt
    from paddlefleetx_tpu.models.gpt.config import GPTConfig

    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=32,
                    hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
                    dtype="float32")
    ccfg = dataclasses.replace(cfg, use_chunked_ce=True, ce_chunk_size=32)
    params = gpt.init(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    batch = {
        "tokens": jnp.asarray(rng.integers(0, 96, (2, 16))),
        "labels": jnp.asarray(rng.integers(0, 96, (2, 16))),
        "loss_mask": jnp.ones((2, 16), jnp.float32),
    }
    ref, gref = jax.value_and_grad(lambda p: gpt.loss_fn(p, batch, cfg, train=False))(params)
    got, ggot = jax.value_and_grad(lambda p: gpt.loss_fn(p, batch, ccfg, train=False))(params)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
    for a, b_ in zip(jax.tree.leaves(ggot), jax.tree.leaves(gref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=2e-5)


def test_prime_vocab_padding():
    """GPT-2's actual vocab (50257, prime) must not degrade to chunk=1:
    the tail chunk is padded+masked. Scaled-down prime vocab here."""
    key = jax.random.key(2)
    kh, kw, kl = jax.random.split(key, 3)
    v = 97  # prime
    hidden = jax.random.normal(kh, (2, 4, 8), jnp.float32)
    word = jax.random.normal(kw, (v, 8), jnp.float32) * 0.1
    labels = jax.random.randint(kl, (2, 4), 0, v)
    mask = jnp.ones((2, 4), jnp.float32)
    got = chunked_cross_entropy(hidden, word, labels, mask, chunk=32)
    ref = _ref(hidden, word, labels, mask)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
    g = jax.grad(lambda ww: chunked_cross_entropy(hidden, ww, labels, mask, chunk=32))(word)
    gr = jax.grad(lambda ww: _ref(hidden, ww, labels, mask))(word)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=1e-5)


@pytest.mark.slow  # ~31s compile; cross-model plumb — the chunked kernel's
# value+grad parity (test_value_and_grads_match_reference) and the GPT
# integration stay tier-1, this T5 variant runs in make test-all (tier-1
# funds the PR 8 tracing/SLO coverage, the PR 6/7 budget convention)
def test_t5_seq2seq_loss_chunked_parity():
    """T5 use_chunked_ce matches the materialized path (tied + untied)."""
    import dataclasses

    from paddlefleetx_tpu.models.t5 import model as t5
    from paddlefleetx_tpu.models.t5.model import T5Config

    for tie in (True, False):
        cfg = T5Config(vocab_size=96, d_model=16, d_kv=4, d_ff=32, num_layers=2,
                       num_decoder_layers=2, num_heads=4,
                       relative_attention_num_buckets=8, dropout_rate=0.0,
                       tie_word_embeddings=tie, dtype="float32")
        ccfg = dataclasses.replace(cfg, use_chunked_ce=True, ce_chunk_size=32)
        params = t5.init(cfg, jax.random.key(0))
        rng = np.random.default_rng(0)
        batch = {
            "input_ids": jnp.asarray(rng.integers(3, 96, (2, 10))),
            "labels": jnp.asarray(rng.integers(3, 96, (2, 6))),
        }
        ref, gref = jax.value_and_grad(
            lambda p: t5.seq2seq_loss(p, batch, cfg, train=False)
        )(params)
        got, ggot = jax.value_and_grad(
            lambda p: t5.seq2seq_loss(p, batch, ccfg, train=False)
        )(params)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
        for a, b_ in zip(jax.tree.leaves(ggot), jax.tree.leaves(gref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=2e-5)


@pytest.mark.slow  # ~19s compile; same reasoning as the T5 variant above
def test_ernie_pretrain_loss_chunked_parity():
    """ERNIE use_chunked_ce (with the decoder-bias fold) matches the
    materialized MLM+NSP path."""
    import dataclasses

    from paddlefleetx_tpu.models.ernie import model as ernie
    from paddlefleetx_tpu.models.ernie.config import ErnieConfig

    cfg = ErnieConfig(vocab_size=96, hidden_size=32, num_layers=2,
                      num_attention_heads=4, ffn_hidden_size=64,
                      max_position_embeddings=32, dtype="float32")
    ccfg = dataclasses.replace(cfg, use_chunked_ce=True, ce_chunk_size=32)
    params = ernie.init(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    ids = rng.integers(4, 96, (2, 12))
    labels = np.full((2, 12), -1, np.int64)
    labels[:, 3:6] = ids[:, 3:6]
    batch = {
        "input_ids": jnp.asarray(ids),
        "masked_lm_labels": jnp.asarray(labels),
        "next_sentence_label": jnp.asarray([0, 1]),
    }
    ref, gref = jax.value_and_grad(
        lambda p: ernie.pretrain_loss(p, batch, cfg, train=False)
    )(params)
    got, ggot = jax.value_and_grad(
        lambda p: ernie.pretrain_loss(p, batch, ccfg, train=False)
    )(params)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
    for a, b_ in zip(jax.tree.leaves(ggot), jax.tree.leaves(gref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=2e-5)
