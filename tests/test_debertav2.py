"""DebertaV2 tests: log buckets, disentangled attention numerics, heads,
conv branch, TP parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.models.debertav2 import model as dbv2
from paddlefleetx_tpu.models.debertav2.config import DebertaV2Config
from paddlefleetx_tpu.models.gpt.model import ShardingCtx
from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
from paddlefleetx_tpu.parallel.sharding import make_rules, tree_logical_to_sharding

# Pallas interpret-mode / big-compile file: excluded from the fast
# subset (pytest -m 'not slow'); run the full suite for release checks
pytestmark = pytest.mark.slow

TINY = DebertaV2Config(
    vocab_size=120,
    hidden_size=32,
    num_layers=2,
    num_attention_heads=4,
    intermediate_size=48,
    max_position_embeddings=64,
    position_buckets=8,
    hidden_dropout_prob=0.0,
    attention_probs_dropout_prob=0.0,
    dtype="float32",
)


def _batch(cfg, b=2, s=16, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(3, cfg.vocab_size, (b, s))
    ids[:, -2:] = cfg.pad_token_id
    labels = np.full((b, s), -1, np.int64)
    labels[:, 2:5] = ids[:, 2:5]
    return {
        "input_ids": jnp.asarray(ids),
        "attention_mask": jnp.asarray((ids != cfg.pad_token_id).astype(np.int32)),
        "labels": jnp.asarray(labels),
    }


def test_log_bucket_positions():
    rel = jnp.arange(-60, 61)
    buck = dbv2.make_log_bucket_position(rel, bucket_size=8, max_position=64)
    # small offsets pass through
    np.testing.assert_array_equal(np.asarray(buck[57:64]), np.arange(-3, 4))
    # bounded by +-mid..ish (log region compresses to <= mid)
    assert int(jnp.max(jnp.abs(buck))) <= 8
    # monotone non-decreasing
    assert bool(jnp.all(jnp.diff(buck) >= 0))


def test_mlm_forward_and_loss_level():
    params = dbv2.init(TINY, jax.random.key(0), head="mlm")
    batch = _batch(TINY)
    hidden = dbv2.encode(params, batch["input_ids"], TINY, attention_mask=batch["attention_mask"])
    assert hidden.shape == (2, 16, 32)
    logits = dbv2.mlm_logits(params, hidden, TINY)
    assert logits.shape == (2, 16, 120)
    loss = dbv2.mlm_loss(params, batch, TINY, train=False)
    assert np.isfinite(float(loss))
    assert abs(float(loss) - np.log(TINY.vocab_size)) < 1.0


def test_pad_invariance():
    params = dbv2.init(TINY, jax.random.key(1), head="mlm")
    batch = _batch(TINY)
    a = dbv2.encode(params, batch["input_ids"], TINY, attention_mask=batch["attention_mask"])
    scrambled = batch["input_ids"].at[:, -2:].set(7)
    b = dbv2.encode(params, scrambled, TINY, attention_mask=batch["attention_mask"])
    np.testing.assert_allclose(np.asarray(a[:, :-2]), np.asarray(b[:, :-2]), rtol=1e-5, atol=1e-5)


def test_rel_attention_changes_scores():
    """Disentangled bias must actually contribute: zeroing rel_embeddings
    changes the output."""
    params = dbv2.init(TINY, jax.random.key(2), head="mlm")
    batch = _batch(TINY)
    a = dbv2.encode(params, batch["input_ids"], TINY)
    # same content weights, relative attention disabled -> different output
    # (rel_embeddings is LayerNormed, so scaling it is invisible; on/off is
    # the honest wiring check)
    cfg_off = DebertaV2Config(**{**TINY.__dict__, "relative_attention": False})
    b = dbv2.encode(params, batch["input_ids"], cfg_off)
    assert float(jnp.max(jnp.abs(a - b))) > 1e-4


def test_share_att_key_false_has_extra_params():
    cfg = DebertaV2Config(**{**TINY.__dict__, "share_att_key": False})
    params = dbv2.init(cfg, jax.random.key(3))
    attn = params["layers"]["attn"]
    assert "pos_k_kernel" in attn and "pos_q_kernel" in attn
    out = dbv2.encode(params, _batch(cfg)["input_ids"], cfg)
    assert np.all(np.isfinite(np.asarray(out)))


def test_conv_branch():
    cfg = DebertaV2Config(**{**TINY.__dict__, "conv_kernel_size": 3})
    params = dbv2.init(cfg, jax.random.key(4))
    assert "conv" in params
    out = dbv2.encode(params, _batch(cfg)["input_ids"], cfg)
    assert out.shape == (2, 16, 32)
    assert np.all(np.isfinite(np.asarray(out)))


def test_cls_head_and_overfit():
    import optax

    cfg = DebertaV2Config(**{**TINY.__dict__, "num_classes": 3})
    params = dbv2.init(cfg, jax.random.key(5), head="cls")
    batch = _batch(cfg)
    batch["labels"] = jnp.asarray([0, 2])
    logits = dbv2.cls_forward(params, batch, cfg)
    assert logits.shape == (2, 3)

    tx = optax.adam(5e-3)
    opt = tx.init(params)

    @jax.jit
    def step(p, o):
        def f(pp):
            lg = dbv2.cls_forward(pp, batch, cfg, train=True)
            return dbv2.cls_loss(lg, batch["labels"])

        loss, g = jax.value_and_grad(f)(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    first = None
    for _ in range(15):
        params, opt, loss = step(params, opt)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.5


def test_tp_parity(devices8):
    params = dbv2.init(TINY, jax.random.key(6), head="mlm")
    batch = _batch(TINY)
    ref = dbv2.encode(params, batch["input_ids"], TINY)

    mesh = build_mesh(MeshConfig(dp_degree=2, mp_degree=4))
    rules = make_rules()
    shardings = tree_logical_to_sharding(
        dbv2.debertav2_logical_axes(TINY, head="mlm"), mesh, rules
    )
    p_sharded = jax.tree.map(lambda x, s: jax.device_put(x, s), params, shardings)
    ctx = ShardingCtx(mesh=mesh, rules=rules)

    @jax.jit
    def fwd(p, ids):
        return dbv2.encode(p, ids, TINY, ctx=ctx)

    out = fwd(p_sharded, batch["input_ids"])
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-4, atol=2e-4)


def test_module_registry():
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.utils.config import AttrDict

    cfg = AttrDict(
        {
            "Model": dict(module="DebertaV2Module", vocab_size=120, hidden_size=32,
                          num_layers=2, num_attention_heads=4, intermediate_size=48,
                          max_position_embeddings=64, position_buckets=8,
                          hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
                          dtype="float32"),
            "Data": {},
        }
    )
    mod = build_module(cfg)
    params = mod.init_params(jax.random.key(0))
    loss = mod.loss_fn(params, _batch(mod.config), train=False)
    assert np.isfinite(float(loss))
