"""Data layer tests: index builders (C++ vs numpy parity), dataset windows,
sampler resume."""

import numpy as np
import pytest

from paddlefleetx_tpu.data.batch_sampler import (
    DistributedBatchSampler,
    DataLoader,
)
from paddlefleetx_tpu.data.gpt_dataset import GPTDataset, LMEvalDataset, write_synthetic_corpus
from paddlefleetx_tpu.data.indexed import (
    build_blending_indices,
    build_sample_idx,
    build_shuffle_idx,
)


def test_sample_idx_numpy_walk():
    sizes = np.array([10, 7, 5], dtype=np.int32)
    doc_idx = np.array([0, 1, 2, 0, 1, 2], dtype=np.int32)  # 2 epochs
    seq = 8
    tokens_per_epoch = 22
    out = build_sample_idx(sizes, doc_idx, seq, 2, tokens_per_epoch, use_cpp=False)
    # boundaries advance by exactly seq tokens each
    def pos(entry):
        di, off = entry
        return sum(sizes[doc_idx[i]] for i in range(di)) + off

    for i in range(len(out) - 1):
        assert pos(out[i + 1]) - pos(out[i]) == seq


def test_sample_idx_cpp_matches_numpy():
    rng = np.random.default_rng(0)
    sizes = rng.integers(3, 50, 200).astype(np.int32)
    doc_idx = np.tile(np.arange(200, dtype=np.int32), 3)
    rng.shuffle(doc_idx)
    tokens_per_epoch = int(sizes.sum())
    ref = build_sample_idx(sizes, doc_idx, 16, 3, tokens_per_epoch, use_cpp=False)
    got = build_sample_idx(sizes, doc_idx, 16, 3, tokens_per_epoch, use_cpp=True)
    np.testing.assert_array_equal(ref, got)


def test_blending_cpp_matches_numpy():
    w = np.array([0.5, 0.3, 0.2])
    ref_i, ref_s = build_blending_indices(w, 1000, use_cpp=False)
    got_i, got_s = build_blending_indices(w, 1000, use_cpp=True)
    np.testing.assert_array_equal(ref_i, got_i)
    np.testing.assert_array_equal(ref_s, got_s)
    # weights respected within 1
    counts = np.bincount(ref_i, minlength=3)
    np.testing.assert_allclose(counts / 1000, w, atol=0.01)


def test_shuffle_idx_partition():
    rng = np.random.default_rng(1)
    s = build_shuffle_idx(10, 25, rng)
    assert sorted(s[:10]) == list(range(10))
    assert sorted(s[10:]) == list(range(10, 25))


def test_gpt_dataset_windows(tmp_path):
    prefix = write_synthetic_corpus(str(tmp_path / "corpus"), vocab_size=1000, num_docs=20)
    ds = GPTDataset(data_prefix=prefix, max_seq_len=32, num_samples=50, split=[1, 0, 0])
    assert len(ds) == 50
    item = ds[0]
    assert item["tokens"].shape == (32,)
    assert item["labels"].shape == (32,)
    # labels are next-token shifted
    np.testing.assert_array_equal(item["tokens"][1:], item["labels"][:-1])
    # deterministic
    item2 = ds[0]
    np.testing.assert_array_equal(item["tokens"], item2["tokens"])


def test_gpt_dataset_cache_roundtrip(tmp_path):
    prefix = write_synthetic_corpus(str(tmp_path / "c2"), vocab_size=500, num_docs=10)
    ds1 = GPTDataset(data_prefix=prefix, max_seq_len=16, num_samples=20, split=[1, 0, 0])
    ds2 = GPTDataset(data_prefix=prefix, max_seq_len=16, num_samples=20, split=[1, 0, 0])
    np.testing.assert_array_equal(ds1[3]["tokens"], ds2[3]["tokens"])


def test_sampler_resume():
    s1 = DistributedBatchSampler(100, 10, shuffle=True, seed=7)
    it1 = iter(s1)
    batches = [next(it1) for _ in range(7)]
    # resume from consumed_samples=50 must replay batch 5 onward
    s2 = DistributedBatchSampler(100, 10, shuffle=True, seed=7, consumed_samples=50)
    it2 = iter(s2)
    np.testing.assert_array_equal(next(it2), batches[5])
    np.testing.assert_array_equal(next(it2), batches[6])


def test_dataloader_collate(tmp_path):
    prefix = write_synthetic_corpus(str(tmp_path / "c3"), vocab_size=500, num_docs=10)
    ds = GPTDataset(data_prefix=prefix, max_seq_len=16, num_samples=30, split=[1, 0, 0])
    dl = DataLoader(ds, DistributedBatchSampler(len(ds), 4))
    batch = next(iter(dl))
    assert batch["tokens"].shape == (4, 16)
    assert batch["loss_mask"].dtype == np.float32


def test_lm_eval_overlap():
    toks = np.arange(100)
    ds = LMEvalDataset(toks, seq_len=32, overlapping_eval=8)
    it0, it1 = ds[0], ds[1]
    # window 1 starts at stride 8 and only counts last 8 tokens
    assert it1["loss_mask"][:24].sum() == 0
    assert it1["loss_mask"][24:].sum() == 8
    assert it0["loss_mask"].sum() == 32


def test_blended_gpt_dataset(tmp_path):
    """BlendedGPTDataset mixes corpora at the requested weights and every
    item has the standard GPT sample schema."""
    from paddlefleetx_tpu.data.gpt_dataset import BlendedGPTDataset

    p1 = write_synthetic_corpus(str(tmp_path / "a"), vocab_size=300, num_docs=12, seed=1)
    p2 = write_synthetic_corpus(str(tmp_path / "b"), vocab_size=300, num_docs=12, seed=2)
    ds = BlendedGPTDataset(
        data_prefixes=[p1, p2],
        weights=[3, 1],
        max_seq_len=64,
        num_samples=200,
        split=(1, 0, 0),
    )
    assert len(ds) == 200
    counts = np.bincount(ds.ds_index[:200], minlength=2)
    assert abs(counts[0] - 150) <= 2 and abs(counts[1] - 50) <= 2, counts
    item = ds[0]
    assert item["tokens"].shape == (64,) and item["labels"].shape == (64,)
    # deterministic across constructions
    ds2 = BlendedGPTDataset(
        data_prefixes=[p1, p2],
        weights=[3, 1],
        max_seq_len=64,
        num_samples=200,
        split=(1, 0, 0),
    )
    np.testing.assert_array_equal(ds.ds_index, ds2.ds_index)
    np.testing.assert_array_equal(ds[17]["tokens"], ds2[17]["tokens"])


def test_blended_default_weights_from_dir(tmp_path):
    """input_dir form: every *_ids.npy participates, weights default to
    size-proportional; GPTDataset warns-and-picks-first for the same dir."""
    from paddlefleetx_tpu.data.gpt_dataset import BlendedGPTDataset

    write_synthetic_corpus(str(tmp_path / "x"), vocab_size=200, num_docs=6, seed=3)
    write_synthetic_corpus(str(tmp_path / "y"), vocab_size=200, num_docs=18, seed=4)
    ds = BlendedGPTDataset(input_dir=str(tmp_path), max_seq_len=32, split=(1, 0, 0))
    assert len(ds.children) == 2
    # the bigger corpus dominates proportionally
    frac_y = (ds.ds_index == 1).mean()
    assert 0.5 < frac_y < 0.95
    single = GPTDataset(input_dir=str(tmp_path), max_seq_len=32, split=(1, 0, 0))
    assert single.prefix.endswith("x")


def test_prefetch_loader_order_and_errors(tmp_path):
    """PrefetchLoader yields the same batches in order; producer exceptions
    surface in the consumer."""
    from paddlefleetx_tpu.data.batch_sampler import PrefetchLoader

    base = [1, 2, 3, 4, 5]
    assert list(PrefetchLoader(base, depth=2)) == base

    def boom():
        yield 1
        raise RuntimeError("producer died")

    out = []
    with pytest.raises(RuntimeError, match="producer died"):
        for x in PrefetchLoader(boom(), depth=1):
            out.append(x)
    assert out == [1]


def test_sampler_rejects_impossible_batch():
    """batch_size > dataset with drop_last used to spin forever yielding
    nothing (silent eval hang); now a pointed construction error."""
    with pytest.raises(ValueError, match="no batch can ever be formed"):
        DistributedBatchSampler(dataset_len=4, batch_size=16, drop_last=True)
    # drop_last=False still allowed: yields the partial tail
    s = DistributedBatchSampler(dataset_len=4, batch_size=16, drop_last=False)
    batch = next(iter(s))
    assert len(batch) == 4


class _DetDataset:
    """Module-level so it pickles into spawn-started workers."""

    def __len__(self):
        return 12

    def __getitem__(self, i):
        return {"x": np.full((3,), i, np.int64), "y": np.int64(i * i)}


def test_worker_loader_matches_inline(tmp_path):
    """WorkerLoader (spawn worker processes, the reference num_workers
    analogue) yields the same batches as the inline DataLoader for a
    deterministic dataset."""
    from paddlefleetx_tpu.data.batch_sampler import WorkerLoader

    import itertools

    ds = _DetDataset()
    # samplers loop epochs forever: take one epoch's worth of batches
    ref = list(itertools.islice(iter(DataLoader(ds, DistributedBatchSampler(len(ds), 4))), 3))
    got = list(
        itertools.islice(
            iter(WorkerLoader(ds, DistributedBatchSampler(len(ds), 4), num_workers=2)), 3
        )
    )
    assert len(got) == len(ref) == 3
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(a["x"], b["x"])
        np.testing.assert_array_equal(a["y"], b["y"])


def test_build_dataloader_num_workers(tmp_path):
    """Data.<mode>.loader.num_workers routes through WorkerLoader."""
    from paddlefleetx_tpu.data.batch_sampler import WorkerLoader
    from paddlefleetx_tpu.data.builders import build_dataloader
    from paddlefleetx_tpu.utils.config import AttrDict

    cfg = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": 4},
            "Engine": {"max_steps": 2},
            "Data": {
                "Train": {
                    "dataset": {
                        "name": "SyntheticClsDataset",
                        "num_samples": 8,
                        "image_size": 8,
                        "num_classes": 2,
                    },
                    "loader": {"num_workers": 2},
                    "sampler": {"shuffle": False},
                }
            },
        }
    )
    loader = build_dataloader(cfg, "Train")
    assert isinstance(loader, WorkerLoader)
    batch = next(iter(loader))
    assert batch["images"].shape == (4, 8, 8, 3)


def test_worker_loader_visit_determinism(tmp_path):
    """Visit-aware datasets (augmentation RNG keyed on (seed, idx, visit))
    draw deterministically under WorkerLoader: the visit counter lives in
    the parent, so draws do not depend on worker scheduling, replay
    identically across runs, and differ between epochs."""
    import itertools
    import pickle

    from paddlefleetx_tpu.data.batch_sampler import WorkerLoader
    from paddlefleetx_tpu.data.vision_dataset import CIFAR10

    rng = np.random.default_rng(0)
    batch = {
        b"data": rng.integers(0, 256, (8, 3 * 32 * 32), dtype=np.uint8),
        b"labels": list(rng.integers(0, 10, 8)),
    }
    with open(tmp_path / "test_batch", "wb") as f:
        pickle.dump(batch, f)

    def epochs(n):
        ds = CIFAR10(str(tmp_path), mode="test",
                     transform_ops=[{"RandCropImage": {"size": 16}}], seed=5)
        # mode=test disables train-time randomness in crops; use train flag
        ds.train = True
        wl = WorkerLoader(ds, DistributedBatchSampler(len(ds), 8), num_workers=2)
        return list(itertools.islice(iter(wl), n))

    run1 = epochs(2)
    run2 = epochs(2)
    # identical across runs (scheduling-independent)
    np.testing.assert_array_equal(run1[0]["images"], run2[0]["images"])
    np.testing.assert_array_equal(run1[1]["images"], run2[1]["images"])
    # epoch 2 re-augments (fresh visit)
    assert not np.array_equal(run1[0]["images"], run1[1]["images"])


def test_masked_lm_dataset(tmp_path):
    """MaskedLmDataset: 80/10/10 dynamic masking over the mmap corpus,
    deterministic per (seed, idx), labels only at masked positions."""
    import numpy as np

    from paddlefleetx_tpu.data.gpt_dataset import write_synthetic_corpus
    from paddlefleetx_tpu.data.mlm_dataset import MaskedLmDataset

    write_synthetic_corpus(str(tmp_path / "c"), vocab_size=500, num_docs=8)
    ds = MaskedLmDataset(
        str(tmp_path), max_seq_len=64, vocab_size=500, mask_token_id=499,
        num_samples=32,
    )
    assert len(ds) == 32
    s = ds[3]
    assert s["input_ids"].shape == (64,) and s["labels"].shape == (64,)
    masked = s["labels"] >= 0
    # ~15% masked, all labels in-vocab, unmasked positions untouched
    assert 1 <= masked.sum() <= 32
    assert (s["labels"][masked] < 500).all()
    orig = ds[3]
    np.testing.assert_array_equal(orig["input_ids"], s["input_ids"])  # deterministic
    # at least the 80% bucket has [MASK] tokens when enough are chosen
    if masked.sum() >= 8:
        assert (s["input_ids"][masked] == 499).sum() >= 1
    # a different index draws a different mask
    assert not np.array_equal(ds[4]["labels"], s["labels"])


def test_masked_lm_dataset_mode_split_and_vocab_guard(tmp_path):
    import numpy as np
    import pytest as _pytest

    from paddlefleetx_tpu.data.gpt_dataset import write_synthetic_corpus
    from paddlefleetx_tpu.data.mlm_dataset import MaskedLmDataset

    write_synthetic_corpus(str(tmp_path / "c"), vocab_size=500, num_docs=32,
                           mean_len=400)
    train = MaskedLmDataset(str(tmp_path), max_seq_len=32, vocab_size=500,
                            mask_token_id=499, mode="Train", split=(8, 2, 0))
    ev = MaskedLmDataset(str(tmp_path), max_seq_len=32, vocab_size=500,
                         mask_token_id=499, mode="Eval", split=(8, 2, 0))
    # disjoint window ranges: eval windows start after every train window
    assert ev._win0 >= train._win0 + train._n_windows
    # out-of-vocab corpus fails loudly instead of silently wrapping ids
    small = MaskedLmDataset(str(tmp_path), max_seq_len=32, vocab_size=100,
                            mask_token_id=99)
    with _pytest.raises(ValueError, match="vocab_size"):
        small[0]
