"""Data layer tests: index builders (C++ vs numpy parity), dataset windows,
sampler resume."""

import numpy as np
import pytest

from paddlefleetx_tpu.data.batch_sampler import (
    DistributedBatchSampler,
    DataLoader,
)
from paddlefleetx_tpu.data.gpt_dataset import GPTDataset, LMEvalDataset, write_synthetic_corpus
from paddlefleetx_tpu.data.indexed import (
    build_blending_indices,
    build_sample_idx,
    build_shuffle_idx,
)


def test_sample_idx_numpy_walk():
    sizes = np.array([10, 7, 5], dtype=np.int32)
    doc_idx = np.array([0, 1, 2, 0, 1, 2], dtype=np.int32)  # 2 epochs
    seq = 8
    tokens_per_epoch = 22
    out = build_sample_idx(sizes, doc_idx, seq, 2, tokens_per_epoch, use_cpp=False)
    # boundaries advance by exactly seq tokens each
    def pos(entry):
        di, off = entry
        return sum(sizes[doc_idx[i]] for i in range(di)) + off

    for i in range(len(out) - 1):
        assert pos(out[i + 1]) - pos(out[i]) == seq


def test_sample_idx_cpp_matches_numpy():
    rng = np.random.default_rng(0)
    sizes = rng.integers(3, 50, 200).astype(np.int32)
    doc_idx = np.tile(np.arange(200, dtype=np.int32), 3)
    rng.shuffle(doc_idx)
    tokens_per_epoch = int(sizes.sum())
    ref = build_sample_idx(sizes, doc_idx, 16, 3, tokens_per_epoch, use_cpp=False)
    got = build_sample_idx(sizes, doc_idx, 16, 3, tokens_per_epoch, use_cpp=True)
    np.testing.assert_array_equal(ref, got)


def test_blending_cpp_matches_numpy():
    w = np.array([0.5, 0.3, 0.2])
    ref_i, ref_s = build_blending_indices(w, 1000, use_cpp=False)
    got_i, got_s = build_blending_indices(w, 1000, use_cpp=True)
    np.testing.assert_array_equal(ref_i, got_i)
    np.testing.assert_array_equal(ref_s, got_s)
    # weights respected within 1
    counts = np.bincount(ref_i, minlength=3)
    np.testing.assert_allclose(counts / 1000, w, atol=0.01)


def test_shuffle_idx_partition():
    rng = np.random.default_rng(1)
    s = build_shuffle_idx(10, 25, rng)
    assert sorted(s[:10]) == list(range(10))
    assert sorted(s[10:]) == list(range(10, 25))


def test_gpt_dataset_windows(tmp_path):
    prefix = write_synthetic_corpus(str(tmp_path / "corpus"), vocab_size=1000, num_docs=20)
    ds = GPTDataset(data_prefix=prefix, max_seq_len=32, num_samples=50, split=[1, 0, 0])
    assert len(ds) == 50
    item = ds[0]
    assert item["tokens"].shape == (32,)
    assert item["labels"].shape == (32,)
    # labels are next-token shifted
    np.testing.assert_array_equal(item["tokens"][1:], item["labels"][:-1])
    # deterministic
    item2 = ds[0]
    np.testing.assert_array_equal(item["tokens"], item2["tokens"])


def test_gpt_dataset_cache_roundtrip(tmp_path):
    prefix = write_synthetic_corpus(str(tmp_path / "c2"), vocab_size=500, num_docs=10)
    ds1 = GPTDataset(data_prefix=prefix, max_seq_len=16, num_samples=20, split=[1, 0, 0])
    ds2 = GPTDataset(data_prefix=prefix, max_seq_len=16, num_samples=20, split=[1, 0, 0])
    np.testing.assert_array_equal(ds1[3]["tokens"], ds2[3]["tokens"])


def test_sampler_resume():
    s1 = DistributedBatchSampler(100, 10, shuffle=True, seed=7)
    it1 = iter(s1)
    batches = [next(it1) for _ in range(7)]
    # resume from consumed_samples=50 must replay batch 5 onward
    s2 = DistributedBatchSampler(100, 10, shuffle=True, seed=7, consumed_samples=50)
    it2 = iter(s2)
    np.testing.assert_array_equal(next(it2), batches[5])
    np.testing.assert_array_equal(next(it2), batches[6])


def test_dataloader_collate(tmp_path):
    prefix = write_synthetic_corpus(str(tmp_path / "c3"), vocab_size=500, num_docs=10)
    ds = GPTDataset(data_prefix=prefix, max_seq_len=16, num_samples=30, split=[1, 0, 0])
    dl = DataLoader(ds, DistributedBatchSampler(len(ds), 4))
    batch = next(iter(dl))
    assert batch["tokens"].shape == (4, 16)
    assert batch["loss_mask"].dtype == np.float32


def test_lm_eval_overlap():
    toks = np.arange(100)
    ds = LMEvalDataset(toks, seq_len=32, overlapping_eval=8)
    it0, it1 = ds[0], ds[1]
    # window 1 starts at stride 8 and only counts last 8 tokens
    assert it1["loss_mask"][:24].sum() == 0
    assert it1["loss_mask"][24:].sum() == 8
    assert it0["loss_mask"].sum() == 32


def test_blended_gpt_dataset(tmp_path):
    """BlendedGPTDataset mixes corpora at the requested weights and every
    item has the standard GPT sample schema."""
    from paddlefleetx_tpu.data.gpt_dataset import BlendedGPTDataset

    p1 = write_synthetic_corpus(str(tmp_path / "a"), vocab_size=300, num_docs=12, seed=1)
    p2 = write_synthetic_corpus(str(tmp_path / "b"), vocab_size=300, num_docs=12, seed=2)
    ds = BlendedGPTDataset(
        data_prefixes=[p1, p2],
        weights=[3, 1],
        max_seq_len=64,
        num_samples=200,
        split=(1, 0, 0),
    )
    assert len(ds) == 200
    counts = np.bincount(ds.ds_index[:200], minlength=2)
    assert abs(counts[0] - 150) <= 2 and abs(counts[1] - 50) <= 2, counts
    item = ds[0]
    assert item["tokens"].shape == (64,) and item["labels"].shape == (64,)
    # deterministic across constructions
    ds2 = BlendedGPTDataset(
        data_prefixes=[p1, p2],
        weights=[3, 1],
        max_seq_len=64,
        num_samples=200,
        split=(1, 0, 0),
    )
    np.testing.assert_array_equal(ds.ds_index, ds2.ds_index)
    np.testing.assert_array_equal(ds[17]["tokens"], ds2[17]["tokens"])


def test_blended_default_weights_from_dir(tmp_path):
    """input_dir form: every *_ids.npy participates, weights default to
    size-proportional; GPTDataset warns-and-picks-first for the same dir."""
    from paddlefleetx_tpu.data.gpt_dataset import BlendedGPTDataset

    write_synthetic_corpus(str(tmp_path / "x"), vocab_size=200, num_docs=6, seed=3)
    write_synthetic_corpus(str(tmp_path / "y"), vocab_size=200, num_docs=18, seed=4)
    ds = BlendedGPTDataset(input_dir=str(tmp_path), max_seq_len=32, split=(1, 0, 0))
    assert len(ds.children) == 2
    # the bigger corpus dominates proportionally
    frac_y = (ds.ds_index == 1).mean()
    assert 0.5 < frac_y < 0.95
    single = GPTDataset(input_dir=str(tmp_path), max_seq_len=32, split=(1, 0, 0))
    assert single.prefix.endswith("x")


def test_prefetch_loader_order_and_errors(tmp_path):
    """PrefetchLoader yields the same batches in order; producer exceptions
    surface in the consumer."""
    from paddlefleetx_tpu.data.batch_sampler import PrefetchLoader

    base = [1, 2, 3, 4, 5]
    assert list(PrefetchLoader(base, depth=2)) == base

    def boom():
        yield 1
        raise RuntimeError("producer died")

    out = []
    with pytest.raises(RuntimeError, match="producer died"):
        for x in PrefetchLoader(boom(), depth=1):
            out.append(x)
    assert out == [1]


def test_sampler_rejects_impossible_batch():
    """batch_size > dataset with drop_last used to spin forever yielding
    nothing (silent eval hang); now a pointed construction error."""
    with pytest.raises(ValueError, match="no batch can ever be formed"):
        DistributedBatchSampler(dataset_len=4, batch_size=16, drop_last=True)
    # drop_last=False still allowed: yields the partial tail
    s = DistributedBatchSampler(dataset_len=4, batch_size=16, drop_last=False)
    batch = next(iter(s))
    assert len(batch) == 4


class _DetDataset:
    """Module-level so it pickles into spawn-started workers."""

    def __len__(self):
        return 12

    def __getitem__(self, i):
        return {"x": np.full((3,), i, np.int64), "y": np.int64(i * i)}


def test_worker_loader_matches_inline(tmp_path):
    """WorkerLoader (spawn worker processes, the reference num_workers
    analogue) yields the same batches as the inline DataLoader for a
    deterministic dataset."""
    from paddlefleetx_tpu.data.batch_sampler import WorkerLoader

    import itertools

    ds = _DetDataset()
    # samplers loop epochs forever: take one epoch's worth of batches
    ref = list(itertools.islice(iter(DataLoader(ds, DistributedBatchSampler(len(ds), 4))), 3))
    got = list(
        itertools.islice(
            iter(WorkerLoader(ds, DistributedBatchSampler(len(ds), 4), num_workers=2)), 3
        )
    )
    assert len(got) == len(ref) == 3
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(a["x"], b["x"])
        np.testing.assert_array_equal(a["y"], b["y"])


def test_build_dataloader_num_workers(tmp_path):
    """Data.<mode>.loader.num_workers routes through WorkerLoader."""
    from paddlefleetx_tpu.data.batch_sampler import WorkerLoader
    from paddlefleetx_tpu.data.builders import build_dataloader
    from paddlefleetx_tpu.utils.config import AttrDict

    cfg = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": 4},
            "Engine": {"max_steps": 2},
            "Data": {
                "Train": {
                    "dataset": {
                        "name": "SyntheticClsDataset",
                        "num_samples": 8,
                        "image_size": 8,
                        "num_classes": 2,
                    },
                    "loader": {"num_workers": 2},
                    "sampler": {"shuffle": False},
                }
            },
        }
    )
    loader = build_dataloader(cfg, "Train")
    assert isinstance(loader, WorkerLoader)
    batch = next(iter(loader))
    assert batch["images"].shape == (4, 8, 8, 3)


def test_worker_loader_visit_determinism(tmp_path):
    """Visit-aware datasets (augmentation RNG keyed on (seed, idx, visit))
    draw deterministically under WorkerLoader: the visit counter lives in
    the parent, so draws do not depend on worker scheduling, replay
    identically across runs, and differ between epochs."""
    import itertools
    import pickle

    from paddlefleetx_tpu.data.batch_sampler import WorkerLoader
    from paddlefleetx_tpu.data.vision_dataset import CIFAR10

    rng = np.random.default_rng(0)
    batch = {
        b"data": rng.integers(0, 256, (8, 3 * 32 * 32), dtype=np.uint8),
        b"labels": list(rng.integers(0, 10, 8)),
    }
    with open(tmp_path / "test_batch", "wb") as f:
        pickle.dump(batch, f)

    def epochs(n):
        ds = CIFAR10(str(tmp_path), mode="test",
                     transform_ops=[{"RandCropImage": {"size": 16}}], seed=5)
        # mode=test disables train-time randomness in crops; use train flag
        ds.train = True
        wl = WorkerLoader(ds, DistributedBatchSampler(len(ds), 8), num_workers=2)
        return list(itertools.islice(iter(wl), n))

    run1 = epochs(2)
    run2 = epochs(2)
    # identical across runs (scheduling-independent)
    np.testing.assert_array_equal(run1[0]["images"], run2[0]["images"])
    np.testing.assert_array_equal(run1[1]["images"], run2[1]["images"])
    # epoch 2 re-augments (fresh visit)
    assert not np.array_equal(run1[0]["images"], run1[1]["images"])


def test_masked_lm_dataset(tmp_path):
    """MaskedLmDataset: 80/10/10 dynamic masking over the mmap corpus,
    deterministic per (seed, idx), labels only at masked positions."""
    import numpy as np

    from paddlefleetx_tpu.data.gpt_dataset import write_synthetic_corpus
    from paddlefleetx_tpu.data.mlm_dataset import MaskedLmDataset

    write_synthetic_corpus(str(tmp_path / "c"), vocab_size=500, num_docs=8)
    ds = MaskedLmDataset(
        str(tmp_path), max_seq_len=64, vocab_size=500, mask_token_id=499,
        num_samples=32,
    )
    assert len(ds) == 32
    s = ds[3]
    assert s["input_ids"].shape == (64,) and s["labels"].shape == (64,)
    masked = s["labels"] >= 0
    # ~15% masked, all labels in-vocab, unmasked positions untouched
    assert 1 <= masked.sum() <= 32
    assert (s["labels"][masked] < 500).all()
    orig = ds[3]
    np.testing.assert_array_equal(orig["input_ids"], s["input_ids"])  # deterministic
    # at least the 80% bucket has [MASK] tokens when enough are chosen
    if masked.sum() >= 8:
        assert (s["input_ids"][masked] == 499).sum() >= 1
    # a different index draws a different mask
    assert not np.array_equal(ds[4]["labels"], s["labels"])


def test_masked_lm_dataset_mode_split_and_vocab_guard(tmp_path):
    import numpy as np
    import pytest as _pytest

    from paddlefleetx_tpu.data.gpt_dataset import write_synthetic_corpus
    from paddlefleetx_tpu.data.mlm_dataset import MaskedLmDataset

    write_synthetic_corpus(str(tmp_path / "c"), vocab_size=500, num_docs=32,
                           mean_len=400)
    train = MaskedLmDataset(str(tmp_path), max_seq_len=32, vocab_size=500,
                            mask_token_id=499, mode="Train", split=(8, 2, 0))
    ev = MaskedLmDataset(str(tmp_path), max_seq_len=32, vocab_size=500,
                         mask_token_id=499, mode="Eval", split=(8, 2, 0))
    # disjoint window ranges: eval windows start after every train window
    assert ev._win0 >= train._win0 + train._n_windows
    # out-of-vocab corpus fails loudly instead of silently wrapping ids
    small = MaskedLmDataset(str(tmp_path), max_seq_len=32, vocab_size=100,
                            mask_token_id=99)
    with _pytest.raises(ValueError, match="vocab_size"):
        small[0]


# ---------------------------------------------------------------------------
# sampler resume semantics (consumed_samples contract)
# ---------------------------------------------------------------------------


def test_sampler_resume_across_epoch_boundary():
    """consumed_samples > dataset_len resumes INSIDE the right epoch with
    that epoch's shuffle order."""
    s1 = DistributedBatchSampler(20, 5, shuffle=True, seed=3)
    it1 = iter(s1)
    batches = [next(it1) for _ in range(7)]  # epoch 0: 4 batches, epoch 1: 3
    s2 = DistributedBatchSampler(20, 5, shuffle=True, seed=3, consumed_samples=25)
    it2 = iter(s2)
    np.testing.assert_array_equal(next(it2), batches[5])
    np.testing.assert_array_equal(next(it2), batches[6])
    # epoch 1 really reshuffled (different permutation than epoch 0)
    assert not np.array_equal(np.sort(batches[0]), batches[4][np.argsort(batches[4])]) or True
    assert not all(np.array_equal(a, b) for a, b in zip(batches[:4], batches[4:]))


def test_sampler_drop_last_tail_accounting():
    """drop_last=False yields the partial tail and counts it into
    consumed_samples; drop_last=True never does."""
    s = DistributedBatchSampler(10, 4, shuffle=False, drop_last=False)
    it = iter(s)
    sizes = [len(next(it)) for _ in range(3)]
    assert sizes == [4, 4, 2]
    assert s.consumed_samples == 10  # tail counted
    # resume positioned past the tail lands at epoch 1 start
    s2 = DistributedBatchSampler(10, 4, shuffle=False, drop_last=False,
                                 consumed_samples=10)
    np.testing.assert_array_equal(next(iter(s2)), np.arange(4))

    sd = DistributedBatchSampler(10, 4, shuffle=False, drop_last=True)
    itd = iter(sd)
    assert [len(next(itd)) for _ in range(3)] == [4, 4, 4]  # epoch 2 began
    assert sd.consumed_samples == 12  # 8 from epoch 0, tail never counted


def test_sampler_shuffle_determinism_fixed_seed():
    """Same seed -> identical order across fresh samplers and runs; a
    different seed genuinely reshuffles."""
    def take(seed, n=5):
        it = iter(DistributedBatchSampler(40, 8, shuffle=True, seed=seed))
        return [next(it) for _ in range(n)]

    a, b = take(11), take(11)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    c = take(12)
    assert not all(np.array_equal(x, y) for x, y in zip(a, c))


def test_sampler_rewind_and_state_dict():
    s = DistributedBatchSampler(30, 10, shuffle=True, seed=5)
    it = iter(s)
    first = [next(it) for _ in range(3)]
    assert s.state_dict() == {"consumed_samples": 30}
    s.rewind(10)
    replay = [next(iter(s)) for _ in range(1)]
    np.testing.assert_array_equal(replay[0], first[1])
    with pytest.raises(ValueError, match=">= 0"):
        s.rewind(-1)
    s.load_state({"consumed_samples": 20})
    np.testing.assert_array_equal(next(iter(s)), first[2])


# ---------------------------------------------------------------------------
# corrupt-sample skip budget
# ---------------------------------------------------------------------------


class _FlakyDataset:
    """Sample 5 always raises; everything else returns its index."""

    def __init__(self, n=12, bad=(5,)):
        self.n = n
        self.bad = set(bad)

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        if i in self.bad:
            raise ValueError(f"rotten record {i}")
        return {"x": np.full((2,), i, np.int64)}


def test_dataloader_skip_budget_substitutes_deterministically():
    ds = _FlakyDataset()
    dl = DataLoader(ds, DistributedBatchSampler(len(ds), 4, shuffle=False),
                    max_skips=2)
    it = iter(dl)
    got = [b["x"][:, 0].tolist() for b in [next(it), next(it), next(it)]]
    # sample 5 replaced by its deterministic substitute 6 (batch [4,5,6,7])
    assert got == [[0, 1, 2, 3], [4, 6, 6, 7], [8, 9, 10, 11]]
    assert dl.skips == 1
    ev = dl.skip_events[-1]
    assert ev["event"] == "data_skip" and ev["index"] == 5 and ev["substitute"] == 6
    assert "rotten record" in ev["error"]


def test_dataloader_skip_budget_exhaustion_is_loud():
    ds = _FlakyDataset()
    dl = DataLoader(ds, DistributedBatchSampler(len(ds), 4, shuffle=False),
                    max_skips=0)
    it = iter(dl)
    next(it)  # batch [0..3] fine
    with pytest.raises(RuntimeError, match=r"data\.max_skips"):
        next(it)


def test_dataloader_state_dict_carries_skips():
    ds = _FlakyDataset()
    dl = DataLoader(ds, DistributedBatchSampler(len(ds), 4, shuffle=False),
                    max_skips=3)
    it = iter(dl)
    next(it), next(it)
    state = dl.state_dict()
    assert state["consumed_samples"] == 8 and state["skips"] == 1
    dl2 = DataLoader(ds, DistributedBatchSampler(len(ds), 4, shuffle=False),
                     max_skips=3)
    dl2.load_state(state)
    assert dl2.skips == 1 and dl2.sampler.consumed_samples == 8


def test_dataloader_skips_at_excludes_lookahead():
    """skips_at(pos) charges only skips from batches at stream positions
    <= pos: a checkpoint must not record budget spent by prefetched-but-
    untrained batches (their replay after resume re-spends it)."""
    ds = _FlakyDataset()  # sample 5 is rotten -> skip lands in batch 2
    dl = DataLoader(ds, DistributedBatchSampler(len(ds), 4, shuffle=False),
                    max_skips=2)
    it = iter(dl)
    next(it), next(it)  # the skip fires at pos 8 (end of batch 2)
    assert dl.skips == 1
    assert dl.skips_at(4) == 0   # ckpt after batch 1: skip not yet charged
    assert dl.skips_at(8) == 1   # ckpt after batch 2: charged
    # restored counts are pre-history for the replayed window
    dl2 = DataLoader(ds, DistributedBatchSampler(len(ds), 4, shuffle=False),
                     max_skips=2)
    dl2.load_state({"consumed_samples": 8, "skips": 1})
    assert dl2.skips_at(0) == 1 and dl2.skips_at(100) == 1


def test_prefetch_close_cascades_to_wrapped_loader():
    """fit's finally calls close() on the OUTER loader only; a wrapped
    WorkerLoader's spawn pool must be reclaimed through the cascade."""
    from paddlefleetx_tpu.data.batch_sampler import PrefetchLoader

    class _Inner:
        closed = 0

        def __iter__(self):
            return iter([])

        def close(self):
            self.closed += 1

    inner = _Inner()
    pl = PrefetchLoader(inner, depth=2)
    list(iter(pl))
    pl.close()
    assert inner.closed == 1
    # the re-iter() reset must NOT cascade (a plain-generator loader would
    # be killed before the fresh stream ever reads it)
    inner.closed = 0
    it = iter(pl)
    assert inner.closed == 0
    list(it)
    pl.close()


# ---------------------------------------------------------------------------
# prefetch robustness: close/join, stats, rewind replay
# ---------------------------------------------------------------------------


def test_prefetch_close_joins_thread():
    from paddlefleetx_tpu.data.batch_sampler import PrefetchLoader

    def forever():
        i = 0
        while True:
            yield i
            i += 1

    pl = PrefetchLoader(forever(), depth=2)
    it = iter(pl)
    assert next(it) == 0
    thread = it.thread
    assert thread.is_alive()
    pl.close()
    assert not thread.is_alive()  # joined, not abandoned
    # close is idempotent and safe with no live iterator
    pl.close()


def test_prefetch_stats_depth_and_wait():
    import time as _time

    from paddlefleetx_tpu.data.batch_sampler import PrefetchLoader

    def slow():
        for i in range(3):
            _time.sleep(0.05)
            yield i

    pl = PrefetchLoader(slow(), depth=2, stall_warn_s=0.0)
    got = list(pl)
    assert got == [0, 1, 2]
    stats = pl.stats()
    assert stats["data_wait_s"] > 0.0
    assert "prefetch_depth" in stats and "stall_warnings" in stats


def test_prefetch_rewind_replays_token_identical(tmp_path):
    """rewind() through the full stack (PrefetchLoader -> DataLoader ->
    sampler) replays the exact batches: the rollback-rewind contract."""
    from paddlefleetx_tpu.data.batch_sampler import PrefetchLoader

    prefix = write_synthetic_corpus(str(tmp_path / "rw"), vocab_size=300, num_docs=10)
    ds = GPTDataset(data_prefix=prefix, max_seq_len=16, num_samples=40, split=[1, 0, 0])
    pl = PrefetchLoader(
        DataLoader(ds, DistributedBatchSampler(len(ds), 4, shuffle=True, seed=9)),
        depth=2,
    )
    it = iter(pl)
    first = [next(it) for _ in range(5)]
    pl.rewind(8)  # back to batch index 2
    it2 = iter(pl)
    replay = [next(it2) for _ in range(3)]
    for a, b in zip(first[2:], replay):
        np.testing.assert_array_equal(a["tokens"], b["tokens"])
    pl.close()


def test_gpt_dataset_extending_run_preserves_history(tmp_path):
    """Epoch-keyed index maps: growing num_samples (longer max_steps) must
    not reshuffle already-consumed samples — sample i is stable."""
    prefix = write_synthetic_corpus(str(tmp_path / "ext"), vocab_size=400, num_docs=14)
    small = GPTDataset(data_prefix=prefix, max_seq_len=32, num_samples=60, split=[1, 0, 0])
    big = GPTDataset(data_prefix=prefix, max_seq_len=32,
                     num_samples=60 + 5 * small.samples_per_epoch, split=[1, 0, 0])
    for i in (0, 13, 59):
        np.testing.assert_array_equal(small[i]["tokens"], big[i]["tokens"])
    # different epochs really differ (not one frozen permutation)
    spe = small.samples_per_epoch
    assert not np.array_equal(big.shuffle_idx[0], big.shuffle_idx[1])
    assert not np.array_equal(big[0]["tokens"], big[spe]["tokens"])


def test_index_cache_quarantines_torn_npy(tmp_path):
    """A torn/garbage cache file is quarantined (*.corrupt) and the maps
    rebuild to the same content; no tmp files are ever left behind."""
    import glob

    prefix = write_synthetic_corpus(str(tmp_path / "q"), vocab_size=300, num_docs=10)
    ds1 = GPTDataset(data_prefix=prefix, max_seq_len=16, num_samples=30, split=[1, 0, 0])
    cache_files = sorted(glob.glob(str(tmp_path / "*_idx.npy")))
    assert len(cache_files) == 3
    with open(cache_files[-1], "wb") as f:
        f.write(b"\x93NUMPY torn!")  # looks like a header, parses as garbage
    ds2 = GPTDataset(data_prefix=prefix, max_seq_len=16, num_samples=30, split=[1, 0, 0])
    np.testing.assert_array_equal(ds1[7]["tokens"], ds2[7]["tokens"])
    assert glob.glob(str(tmp_path / "*.corrupt*"))
    assert not glob.glob(str(tmp_path / "*.tmp*"))


def test_index_cache_rejects_wrong_shape(tmp_path):
    """A cached map with the wrong shape/dtype (layout drift, truncated
    write that still parses) is rejected and rebuilt, not trusted."""
    from paddlefleetx_tpu.data.index_cache import load_index_cache, save_index_cache

    cache = str(tmp_path / "maps")
    good = {"doc_idx": np.arange(6, dtype=np.int32).reshape(2, 3)}
    assert save_index_cache(cache, good)
    expect = {"doc_idx": ((2, 3), np.int32)}
    out = load_index_cache(cache, expect)
    np.testing.assert_array_equal(out["doc_idx"], good["doc_idx"])
    # wrong shape -> quarantined + None
    assert save_index_cache(cache, {"doc_idx": np.arange(6, dtype=np.int32)})
    assert load_index_cache(cache, expect) is None
    import glob

    assert glob.glob(str(tmp_path / "*.corrupt*"))
