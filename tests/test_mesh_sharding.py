"""Mesh builder + sharding-rule tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh, data_parallel_world
from paddlefleetx_tpu.parallel.seed import SeedTracker
from paddlefleetx_tpu.parallel.sharding import (
    logical_to_spec,
    make_rules,
    tree_logical_to_sharding,
)


def test_mesh_shapes(devices8):
    mesh = build_mesh(MeshConfig(dp_degree=2, mp_degree=4), devices8)
    assert mesh.shape["data"] == 2
    assert mesh.shape["model"] == 4
    assert mesh.shape["stages"] == 1
    assert data_parallel_world(mesh) == 2

    mesh = build_mesh(MeshConfig(dp_degree=2, sharding_degree=2, pp_degree=2), devices8)
    assert data_parallel_world(mesh) == 4


def test_mesh_degree_mismatch(devices8):
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(dp_degree=3), devices8)


def test_logical_to_spec_tp():
    rules = make_rules()
    # column-parallel kernel [embed, mlp] -> (None, 'model')
    assert logical_to_spec(("embed", "mlp"), rules) == P(None, "model")
    # row-parallel kernel [mlp, embed] -> ('model', None)
    assert logical_to_spec(("mlp", "embed"), rules) == P("model", None)
    # vocab embedding [vocab, embed]
    assert logical_to_spec(("vocab", "embed"), rules) == P("model", None)
    # activations [batch, seq, embed]
    assert logical_to_spec(("batch", "seq", "embed"), rules) == P(("data", "fsdp"), "sep", None)


def test_logical_to_spec_fsdp_sp():
    rules = make_rules(fsdp_enabled=True, sequence_parallel=True)
    assert logical_to_spec(("embed", "mlp"), rules) == P("fsdp", "model")
    assert logical_to_spec(("batch", "seq", "embed"), rules) == P(
        ("data", "fsdp"), ("sep", "model"), None
    )


def test_duplicate_mesh_axis_dropped():
    # seq uses model under SP; heads also wants model -> second use must drop
    rules = make_rules(sequence_parallel=True)
    spec = logical_to_spec(("seq", "heads"), rules)
    assert spec == P(("sep", "model"), None)


def test_tree_sharding_and_matmul(devices8):
    mesh = build_mesh(MeshConfig(dp_degree=2, mp_degree=4), devices8)
    rules = make_rules()
    logical = {"w": ("embed", "mlp"), "b": ("mlp",)}
    shardings = tree_logical_to_sharding(logical, mesh, rules)
    assert shardings["w"].spec == P(None, "model")

    w = jax.device_put(jnp.ones((16, 32)), shardings["w"])
    x = jax.device_put(
        jnp.ones((8, 16)), NamedSharding(mesh, P(("data", "fsdp"), None))
    )
    y = jax.jit(lambda a, b: a @ b)(x, w)
    np.testing.assert_allclose(np.asarray(y), 16.0)


def test_seed_tracker_streams():
    t = SeedTracker(1234)
    k1 = t.key("params")
    k2 = t.key("global")
    assert not np.array_equal(
        jax.random.key_data(k1), jax.random.key_data(k2)
    )
    # deterministic
    t2 = SeedTracker(1234)
    assert np.array_equal(
        jax.random.key_data(t2.key("params")), jax.random.key_data(k1)
    )
    # per-step folds differ
    assert not np.array_equal(
        jax.random.key_data(t.dropout_key(1)), jax.random.key_data(t.dropout_key(2))
    )


# ---------------------------------------------------------------------------
# ZeRO stage semantics (reference group_sharded_parallel, eager_engine.py:
# 281-307): stage 1 = opt state sharded, 2 = +grads, 3 = +params; offload
# places optimizer moments in pinned host memory.
# ---------------------------------------------------------------------------


def _tiny_cfg(stage, offload=False):
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": 8, "micro_batch_size": 1, "seed": 7},
            "Engine": {
                "max_steps": 1,
                "eval_freq": 0,
                "logging_freq": 10**9,
                "mix_precision": {"enable": False},
                "save_load": {"save_steps": 0},
            },
            "Model": {
                "module": "GPTModule",
                "vocab_size": 64,
                "hidden_size": 32,
                "num_layers": 2,
                "num_attention_heads": 4,
                "max_position_embeddings": 16,
                "dtype": "float32",
            },
            "Distributed": {
                "dp_degree": 2,
                "sharding": {
                    "sharding_degree": 4,
                    "sharding_stage": stage,
                    "offload": offload,
                    # tiny model: keep matmul kernels above the whole-param
                    # threshold so ZeRO semantics are actually exercised
                    "min_shard_size": 1024,
                },
            },
            "Optimizer": {
                "name": "FusedAdamW",
                "weight_decay": 0.01,
                "lr": {"name": "Constant", "learning_rate": 1e-4},
            },
        }
    )
    return process_configs(cfg, num_devices=8)


def _make_engine(devices8, stage, offload=False):
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env

    cfg = _tiny_cfg(stage, offload)
    mesh = init_dist_env(cfg, devices=devices8)
    module = build_module(cfg)
    with mesh:
        return Engine(cfg, module, mesh)


def _specs(tree):
    return {str(s.spec) for s in jax.tree.leaves(tree)}


def test_zero_stage1_opt_only(devices8):
    eng = _make_engine(devices8, stage=1)
    # params NOT fsdp-sharded at stage 1
    assert not any("fsdp" in s for s in _specs(eng.param_shardings))
    # adam moments ARE
    assert any("fsdp" in s for s in _specs(eng.opt_shardings))
    assert eng._grad_shardings is None


def test_zero_stage2_grads_sharded(devices8):
    eng = _make_engine(devices8, stage=2)
    assert not any("fsdp" in s for s in _specs(eng.param_shardings))
    assert eng._grad_shardings is not None
    assert any("fsdp" in s for s in _specs(eng._grad_shardings))


def test_zero_stage3_params_sharded(devices8):
    eng = _make_engine(devices8, stage=3)
    assert any("fsdp" in s for s in _specs(eng.param_shardings))
    assert any("fsdp" in s for s in _specs(eng.opt_shardings))
    # lookup tables fsdp-shard their TABLE dim, never the feature dim: a
    # feature-dim target would force replicate-then-repartition of the
    # batch-sharded scatter-add in their backward (Megatron vocab sharding)
    emb = eng.param_shardings["embeddings"]
    assert "fsdp" in str(emb["word"].spec[0]) and emb["word"].spec[1] is None
    # position table ([16,32] = 512 elems) is below min_shard_size: whole
    assert emb["position"].spec == P(None, None) or emb["position"].spec == P()
    # sub-threshold params (LayerNorm vectors) stay whole on the fsdp axis
    ln = eng.param_shardings["final_ln"]["scale"]
    assert "fsdp" not in str(ln.spec)


def test_drop_small_fsdp_threshold():
    from jax.sharding import Mesh
    from paddlefleetx_tpu.parallel.sharding import drop_small_fsdp

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "fsdp"))
    shardings = {
        "big": NamedSharding(mesh, P("fsdp", None)),
        "small": NamedSharding(mesh, P("fsdp", None)),
        "mixed": NamedSharding(mesh, P(("data", "fsdp"), None)),
    }
    shapes = {
        "big": jax.ShapeDtypeStruct((1024, 64), jnp.float32),
        "small": jax.ShapeDtypeStruct((8, 8), jnp.float32),
        "mixed": jax.ShapeDtypeStruct((4, 4), jnp.float32),
    }
    out = drop_small_fsdp(shardings, shapes, min_size=1024)
    assert out["big"].spec == P("fsdp", None)  # above threshold: untouched
    assert out["small"].spec == P(None, None)
    assert out["mixed"].spec == P("data", None)  # fsdp removed, data kept


def test_zero_offload_host_memory_and_step(devices8):
    """offload=True: pinned-host moments where the backend can compile the
    placement (TPU), graceful device fallback where it cannot (XLA CPU's
    SPMD partitioner rejects placement custom-calls) — either way one real
    train step must run."""
    import numpy as np

    eng = _make_engine(devices8, stage=2, offload=True)
    kinds = {
        s.memory_kind
        for s in jax.tree.leaves(eng.opt_shardings)
        if "fsdp" in str(s.spec)
    }
    if eng.offload_active:
        assert kinds == {"pinned_host"}
    else:
        assert "pinned_host" not in kinds  # fell back, documented by warning
    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(0, 64, (8, 16)),
        "labels": rng.integers(0, 64, (8, 16)),
        "loss_mask": np.ones((8, 16), np.float32),
    }
    with eng.mesh:
        dev = eng._put_batch(batch)
        eng.state, metrics = eng.train_step(eng.state, dev)
    assert np.isfinite(float(metrics["loss"]))


def test_6_7b_sharding16_config_validates():
    from paddlefleetx_tpu.utils.config import get_config

    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = get_config(
        os.path.join(repo, "configs/gpt/pretrain_gpt_6.7B_sharding16.yaml"),
        num_devices=16,
    )
    assert int(cfg.Distributed.sharding.sharding_degree) == 16
    assert int(cfg.Distributed.sharding.sharding_stage) == 2


def test_dcn_shape_factoring():
    """Host count lands on the outer (DCN-tolerant) axes only."""
    from paddlefleetx_tpu.parallel.mesh import _dcn_shape

    # 2 hosts, dp 2: hosts span data
    assert _dcn_shape((2, 1, 2, 1, 2), 2) == [2, 1, 1, 1, 1]
    # 4 hosts, dp2 x pp2: data takes 2, stages takes 2
    assert _dcn_shape((2, 1, 2, 1, 2), 4) == [2, 1, 2, 1, 1]
    # 4 hosts over dp2 x fsdp2
    assert _dcn_shape((2, 2, 1, 1, 4), 4) == [2, 2, 1, 1, 1]
    # impossible: hosts cannot factor into outer axes -> None (fallback)
    assert _dcn_shape((1, 1, 1, 2, 4), 2) is None
