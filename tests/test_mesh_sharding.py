"""Mesh builder + sharding-rule tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh, data_parallel_world
from paddlefleetx_tpu.parallel.seed import SeedTracker
from paddlefleetx_tpu.parallel.sharding import (
    logical_to_spec,
    make_rules,
    tree_logical_to_sharding,
)


def test_mesh_shapes(devices8):
    mesh = build_mesh(MeshConfig(dp_degree=2, mp_degree=4), devices8)
    assert mesh.shape["data"] == 2
    assert mesh.shape["model"] == 4
    assert mesh.shape["stages"] == 1
    assert data_parallel_world(mesh) == 2

    mesh = build_mesh(MeshConfig(dp_degree=2, sharding_degree=2, pp_degree=2), devices8)
    assert data_parallel_world(mesh) == 4


def test_mesh_degree_mismatch(devices8):
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(dp_degree=3), devices8)


def test_logical_to_spec_tp():
    rules = make_rules()
    # column-parallel kernel [embed, mlp] -> (None, 'model')
    assert logical_to_spec(("embed", "mlp"), rules) == P(None, "model")
    # row-parallel kernel [mlp, embed] -> ('model', None)
    assert logical_to_spec(("mlp", "embed"), rules) == P("model", None)
    # vocab embedding [vocab, embed]
    assert logical_to_spec(("vocab", "embed"), rules) == P("model", None)
    # activations [batch, seq, embed]
    assert logical_to_spec(("batch", "seq", "embed"), rules) == P(("data", "fsdp"), "sep", None)


def test_logical_to_spec_fsdp_sp():
    rules = make_rules(fsdp_enabled=True, sequence_parallel=True)
    assert logical_to_spec(("embed", "mlp"), rules) == P("fsdp", "model")
    assert logical_to_spec(("batch", "seq", "embed"), rules) == P(
        ("data", "fsdp"), ("sep", "model"), None
    )


def test_duplicate_mesh_axis_dropped():
    # seq uses model under SP; heads also wants model -> second use must drop
    rules = make_rules(sequence_parallel=True)
    spec = logical_to_spec(("seq", "heads"), rules)
    assert spec == P(("sep", "model"), None)


def test_tree_sharding_and_matmul(devices8):
    mesh = build_mesh(MeshConfig(dp_degree=2, mp_degree=4), devices8)
    rules = make_rules()
    logical = {"w": ("embed", "mlp"), "b": ("mlp",)}
    shardings = tree_logical_to_sharding(logical, mesh, rules)
    assert shardings["w"].spec == P(None, "model")

    w = jax.device_put(jnp.ones((16, 32)), shardings["w"])
    x = jax.device_put(
        jnp.ones((8, 16)), NamedSharding(mesh, P(("data", "fsdp"), None))
    )
    y = jax.jit(lambda a, b: a @ b)(x, w)
    np.testing.assert_allclose(np.asarray(y), 16.0)


def test_seed_tracker_streams():
    t = SeedTracker(1234)
    k1 = t.key("params")
    k2 = t.key("global")
    assert not np.array_equal(
        jax.random.key_data(k1), jax.random.key_data(k2)
    )
    # deterministic
    t2 = SeedTracker(1234)
    assert np.array_equal(
        jax.random.key_data(t2.key("params")), jax.random.key_data(k1)
    )
    # per-step folds differ
    assert not np.array_equal(
        jax.random.key_data(t.dropout_key(1)), jax.random.key_data(t.dropout_key(2))
    )
