"""Bench contract tests: bench.py is the driver's ONLY interface to this
repo's performance story, so its two promises get a pytest lock:

  1. happy path — exactly one parseable JSON line on stdout with the
     required keys (metric/value/unit/vs_baseline) and the platform tag;
  2. deadline path — a child that cannot finish inside BENCH_DEADLINE_S
     still yields rc=0 and an honest value-0.0 row (the round-3 failure
     mode was rc=124 with NO output, which scored as a broken bench).

Both run the real parent/child split as a subprocess pinned to CPU via
PFX_PLATFORM (the conftest's in-process jax config does not reach a
subprocess) at shrink shapes.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SHRINK = {
    "PFX_PLATFORM": "cpu",
    "BENCH_VOCAB": "256",
    "BENCH_HIDDEN": "64",
    "BENCH_LAYERS": "2",
    "BENCH_HEADS": "4",
    "BENCH_SEQ": "128",
    "BENCH_BATCH": "2",
    "BENCH_STEPS": "2",
}


def _run_bench(extra_env, timeout):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update(SHRINK)
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=timeout,
    )


def _json_lines(stdout):
    rows = []
    for line in stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            rows.append(json.loads(line))
    return rows


@pytest.mark.slow
def test_bench_happy_path_contract():
    out = _run_bench({"BENCH_DEADLINE_S": "240"}, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    rows = _json_lines(out.stdout)
    assert len(rows) == 1, out.stdout
    row = rows[0]
    assert set(row) >= {"metric", "value", "unit", "vs_baseline"}
    assert row["metric"] == "gpt345m_pretrain_throughput_per_chip"
    assert row["value"] > 0
    assert row["platform"] == "cpu"
    # hardware-normalized fields from the shared telemetry estimator
    # (6·N per token vs the per-device-kind peak — docs/observability.md)
    assert row["tokens_per_sec"] > 0
    assert 0 < row["mfu"] < 1, row


@pytest.mark.slow
def test_bench_deadline_emits_honest_zero():
    # a 1-second deadline cannot fit the compile: the parent must still
    # exit 0 with one honest 0.0 row, never rc=124/no-output
    out = _run_bench({"BENCH_DEADLINE_S": "1"}, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    rows = _json_lines(out.stdout)
    assert len(rows) == 1, out.stdout
    assert rows[0]["value"] == 0.0
    assert "deadline" in rows[0]["unit"], rows[0]


# ---------------------------------------------------------------------------
# Decode bench (benchmarks/bench_decode.py): same parent/child honest-zero
# contract, exercised at the CPU tiny case pinned in
# benchmarks/cases/decode_tiny_cpu.json so the chip-day smoke case and the
# pytest lock can never drift apart.
# ---------------------------------------------------------------------------

DECODE_CASE = os.path.join(REPO, "benchmarks", "cases", "decode_tiny_cpu.json")


def _decode_case():
    with open(DECODE_CASE) as f:
        return json.load(f)


def _run_bench_decode(extra_env, timeout, tmp_path):
    case = _decode_case()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update(case["env"])
    # keep CPU contract rows out of the tracked results_decode.jsonl
    env["PFX_DECODE_RESULTS"] = str(tmp_path / "results_decode.jsonl")
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "bench_decode.py"),
         *case["args"]],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=timeout,
    ), case


@pytest.mark.slow
def test_bench_decode_happy_path_contract(tmp_path):
    out, case = _run_bench_decode(
        {"BENCH_DECODE_DEADLINE_S": "400"}, timeout=460, tmp_path=tmp_path
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rows = {r["metric"]: r for r in _json_lines(out.stdout)}
    assert set(rows) == set(case["expect_metrics"]), out.stdout
    for row in rows.values():
        assert set(row) >= {"metric", "value", "unit", "vs_baseline"}
        assert row["value"] > 0
        assert row["platform"] == "cpu"
        # decode rows are hardware-normalized by the same estimator as
        # bench.py/the engine, on the forward-only (2·N) basis
        assert row["tokens_per_sec"] > 0
        assert 0 < row["mfu"] < 1, row
    # the A/B pair: one overhauled row, one legacy row, same shape keys
    paths = {r["decode_path"] for r in rows.values()}
    assert paths == {"overhauled", "legacy(dense+scan)"}, rows

    # speculative A/B row: the CPU contract regime (f32, repetitive
    # prompt, self-draft) must show the win honestly — greedy spec is
    # token-identical BY CONSTRUCTION (divergent rows exactly zero at
    # f32), acceptance is high in the repetitive steady state, and the
    # committed-token accounting agrees with the counters
    spec = rows["gpt345m_decode_b8_greedy_spec4"]
    assert spec["draft_k"] == 4 and spec["drafter"] == "ngram"
    assert spec["greedy_divergent_rows"] == 0, spec
    assert spec["accept_rate"] >= 0.5, spec
    assert spec["value"] >= spec["baseline_tokens_per_s"], spec
    assert spec["spec_proposed"] > 0
    assert abs(
        spec["accept_rate"]
        - spec["spec_accepted"] / spec["spec_proposed"]
    ) < 1e-3, spec

    # int8-KV A/B row: the bytes win is chip evidence (CPU pays dequant
    # multiplies with no bandwidth relief), so the contract pins only
    # the row shape + honest divergence accounting at f32
    q8 = rows["gpt345m_decode_b8_greedy_kvint8"]
    assert q8["kv_dtype"] == "int8"
    assert q8["baseline_tokens_per_s"] > 0
    assert "divergent_rows" in q8, q8

    # staggered-arrival continuous-vs-coalesce A/B pair: same fixed-seed
    # arrival trace, both rows report delivered tokens/s + TTFT
    # percentiles.  The CPU smoke asserts the ROW CONTRACT and the
    # fairness/boundedness invariants; the p99-TTFT ordering itself is
    # chip evidence (a host-driven step loop cannot beat a fused
    # while_loop on CPU tiny shapes — dispatch overhead dominates; on
    # TPU the batched step rides the MXU for free), read off the same
    # keys on a chip-window row.
    cont = rows["gpt345m_decode_staggered_continuous"]
    coal = rows["gpt345m_decode_staggered_coalesce"]
    for row in (cont, coal):
        assert {"p50_ttft_s", "p99_ttft_s", "arrivals", "mean_gap_s",
                "single_decode_s", "scheduler"} <= set(row), row
        assert row["p99_ttft_s"] >= row["p50_ttft_s"] > 0, row
    assert cont["scheduler"] == "continuous"
    assert coal["scheduler"] == "coalesce"
    # identical trace on both sides or the A/B is meaningless
    assert cont["arrivals"] == coal["arrivals"]
    assert cont["mean_gap_s"] == coal["mean_gap_s"]
    # fairness: token-count-equal delivery was asserted in-child (a
    # diverging path raises into an honest-zero row, caught above by
    # value > 0); the smoke case pins BENCH_DEC_DTYPE=float32, where
    # greedy is deterministic — divergence must be exactly zero (bf16
    # chip rows may carry argmax near-tie flips, counted not hidden)
    assert cont["greedy_divergent_rows"] == 0, cont
    # bounded retraces: one prefill bucket + one step width bucket (+1
    # slack for a mixed width during drain)
    assert cont["jit_traces"] <= 3, cont

    # prefix-cache A/B pair: same prefix-heavy staggered trace through
    # identical continuous engines, cache ON vs OFF.  The contract pins
    # the reuse evidence — admissions HIT, the cached side computed
    # STRICTLY fewer prompt tokens, and at the f32 smoke dtype the two
    # sides' greedy outputs are token-identical (divergence counted,
    # must be zero).  The TTFT-p99 WIN is chip evidence (CPU tiny shapes
    # are dispatch-dominated), read off the same keys on a chip row.
    pc = rows["gpt345m_decode_prefix_cached"]
    pn = rows["gpt345m_decode_prefix_nocache"]
    for row in (pc, pn):
        assert {"p50_ttft_s", "p99_ttft_s", "prefill_tokens", "hit_rate",
                "shared_prefix_len", "arrivals"} <= set(row), row
        assert row["p99_ttft_s"] >= row["p50_ttft_s"] > 0, row
    assert pc["arrivals"] == pn["arrivals"]
    assert pc["mean_gap_s"] == pn["mean_gap_s"]  # identical trace
    assert pc["hit_rate"] > 0, pc
    assert pc["prefix_hit_tokens"] > 0, pc
    assert pn["hit_rate"] == 0 and pn["prefix_hits"] == 0, pn
    assert pc["prefill_tokens"] < pn["prefill_tokens"], (pc, pn)
    assert pc["greedy_divergent_rows"] == 0, pc

    # dispatch-ahead A/B pair: the SAME greedy batch through two
    # continuous schedulers differing only in dispatch_ahead.  The
    # overlapped side only pays a host gap on admission boundaries
    # (chained dispatches land while the previous step is in flight),
    # so its per-step host_gap_ms must be STRICTLY below the
    # synchronous side's even on CPU — host-side bookkeeping is what
    # the gap measures, not device speed.  Token identity at f32.
    oa = rows["gpt345m_decode_overlap_ahead"]
    os_ = rows["gpt345m_decode_overlap_sync"]
    for row in (oa, os_):
        # the overlap row's key set is pinned in the case file itself
        # (expect_overlap_keys) so chip-day tooling and this lock can't
        # drift apart
        assert set(case["expect_overlap_keys"]) <= set(row), row
        assert row["device_steps"] > 0, row
    assert oa["dispatch_ahead"] is True and os_["dispatch_ahead"] is False
    assert oa["batch"] == os_["batch"]  # identical traffic
    assert oa["host_gap_ms"] < os_["host_gap_ms"], (oa, os_)
    # the sync side pays the gap on (nearly) every step; the ahead side
    # skips it on every chained dispatch
    assert oa["gap_steps"] < os_["gap_steps"], (oa, os_)
    # goodput ledger view of the same window: the overlapped side keeps
    # the device productive for a STRICTLY larger fraction of non-idle
    # scheduler wall — the host_gap win restated in closed-ledger terms
    for row in (oa, os_):
        assert 0.0 < row["goodput_frac"] <= 1.0 + 1e-6, row
        assert 0.0 < row["device_util"] <= row["goodput_frac"] + 1e-6, row
    assert oa["goodput_frac"] > os_["goodput_frac"], (oa, os_)
    assert oa["greedy_divergent_rows"] == 0, oa

    # spill-tier A/B pair: the SAME prefix-heavy staggered trace with a
    # prefix budget too small for two prefix families, host-RAM spill
    # ON vs OFF.  The contract pins the durability evidence — the ON
    # side READMITTED evicted prefixes from host RAM (readmit hit rate
    # above zero) and therefore computed STRICTLY fewer prompt tokens,
    # while the OFF side recomputed everything; a readmitted block is
    # the bit-exact KV that was evicted, so greedy outputs must be
    # token-identical across the sides at the f32 smoke dtype.
    so = rows["gpt345m_decode_spill_on"]
    sf = rows["gpt345m_decode_spill_off"]
    for row in (so, sf):
        assert {"p50_ttft_s", "p99_ttft_s", "prefill_tokens", "spills",
                "readmits", "readmit_hit_rate", "spill_budget_bytes",
                "arrivals"} <= set(row), row
        assert row["p99_ttft_s"] >= row["p50_ttft_s"] > 0, row
    assert so["arrivals"] == sf["arrivals"]
    assert so["mean_gap_s"] == sf["mean_gap_s"]  # identical trace
    assert so["spill_budget_bytes"] > 0 and sf["spill_budget_bytes"] == 0
    assert so["spills"] > 0 and so["readmits"] > 0, so
    assert so["readmit_hit_rate"] > 0, so
    assert sf["spills"] == 0 and sf["readmits"] == 0, sf
    assert so["prefill_tokens"] < sf["prefill_tokens"], (so, sf)
    assert so["greedy_divergent_rows"] == 0, so

    # two-tenant isolation A/B pair: the SAME flood+trickle arrival
    # trace through a slot-starved continuous engine, weighted-fair DRR
    # vs single-class FCFS.  The contract pins the isolation evidence —
    # the fair side's trickle-tenant p99 TTFT is no worse than FCFS's
    # (DRR hands the weighted tenant the next free slot instead of
    # parking it behind the burst; measured margin on this smoke shape
    # is ~2x) — and exact greedy token identity at the f32 smoke dtype:
    # scheduling order must never change what a row decodes
    # (docs/serving.md "Multi-tenant isolation").  With 3 trickle
    # arrivals p99 is the max, and the max is decided by WHERE the last
    # arrival lands relative to a slot release — one decode-step of
    # granularity either side — so the comparison carries one
    # single-request decode of slack (the row's own calibration,
    # single_decode_s) instead of a bare <= that flakes on slot phase.
    tf = rows["gpt345m_decode_tenant_fair"]
    tn = rows["gpt345m_decode_tenant_fcfs"]
    for row in (tf, tn):
        assert {"flood_p50_ttft_s", "flood_p99_ttft_s",
                "trickle_p50_ttft_s", "trickle_p99_ttft_s",
                "arrivals", "scheduler"} <= set(row), row
        assert row["trickle_p99_ttft_s"] >= row["trickle_p50_ttft_s"] > 0, row
    assert tf["scheduler"] == "fair-drr" and tn["scheduler"] == "fcfs"
    # identical trace on both sides or the A/B is meaningless
    assert tf["arrivals"] == tn["arrivals"]
    assert tf["mean_gap_s"] == tn["mean_gap_s"]
    assert tf["weights"] == {"flood": 1, "trickle": 8}, tf
    slack = tf["single_decode_s"]
    assert tf["trickle_p99_ttft_s"] <= tn["trickle_p99_ttft_s"] + slack, (
        tf, tn)
    assert tf["greedy_divergent_rows"] == 0, tf
    assert tn["greedy_divergent_rows"] == 0, tn


@pytest.mark.slow
def test_bench_decode_deadline_emits_honest_zero(tmp_path):
    out, case = _run_bench_decode(
        {"BENCH_DECODE_DEADLINE_S": "1"}, timeout=120, tmp_path=tmp_path
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rows = _json_lines(out.stdout)
    assert {r["metric"] for r in rows} == set(case["expect_metrics"]), out.stdout
    for row in rows:
        assert row["value"] == 0.0
        assert "deadline" in row["unit"] or "did not" in row["unit"], row
