"""Flash-decode attention + decode-loop + fused-sampler tests.

Covers the ISSUE decode-overhaul acceptance criteria:
  - blocked kernel parity vs the dense attend-over-everything path
    (prefill, single-token decode at odd pos, t>1 chunked prefill,
    left-padded buckets) on BOTH the lax and pallas spellings;
  - the decode step never touches cache blocks beyond ceil((pos+t)/block)
    (NaN-poison proof + blocks_visited formula);
  - top-k-prefilter nucleus sampler exactness vs the full-sort
    sample_top_p under fixed keys, incl. the nucleus-overflow fallback,
    and a jaxpr assertion that the fast branch has no full-vocab sort;
  - while_loop vs scan decode token-for-token parity and the dense-vs-
    blocked end-to-end generation parity;
  - knob hygiene: PFX_DECODE_BLOCK / PFX_DECODE_ATTN / PFX_DECODE_SCAN /
    PFX_TOPP_K fail loudly on invalid values.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.models.gpt import model as gpt
from paddlefleetx_tpu.models.gpt.config import GPTConfig
from paddlefleetx_tpu.models.gpt.generation import (
    GenerationConfig,
    decode_loop_mode,
    generate,
    init_cache,
    pad_prompts,
)
from paddlefleetx_tpu.ops.decode_attention import (
    blocks_visited,
    decode_attention,
    decode_attn_mode,
    decode_block,
    dense_cache_attention,
)
from paddlefleetx_tpu.ops.sampling import (
    sample_logits,
    sample_top_p,
    sample_top_p_topk,
)

TINY = GPTConfig(
    vocab_size=97,
    hidden_size=64,
    num_layers=2,
    num_attention_heads=8,
    max_position_embeddings=64,
    hidden_dropout_prob=0.0,
    attention_probs_dropout_prob=0.0,
    dtype="float32",
)


def _rand_case(rng, b, t, n, d, L):
    q = jnp.asarray(rng.normal(size=(b, t, n, d)), jnp.float32)
    kc = jnp.asarray(rng.normal(size=(b, n, L, d)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(b, n, L, d)), jnp.float32)
    return q, kc, vc


# ---------------------------------------------------------------------------
# Kernel parity vs the dense path
# ---------------------------------------------------------------------------


# pallas-interpret variants follow the repo convention for kernel tests
# (test_flash_attention.py): slow suite — interpret-mode compiles dominate
# the tier-1 wall clock; the lax spelling shares all mask/online-softmax
# logic and stays in the fast subset
PALLAS = pytest.param("pallas", marks=pytest.mark.slow)


@pytest.mark.parametrize("impl", ["lax", PALLAS])
@pytest.mark.parametrize(
    "pos,t",
    [(0, 16), (13, 1), (7, 5), (39, 1)],  # prefill, odd-pos decode, chunked
)
def test_blocked_matches_dense(impl, pos, t):
    rng = np.random.default_rng(0)
    q, kc, vc = _rand_case(rng, 2, t, 4, 16, 40)
    ref = dense_cache_attention(q, kc, vc, jnp.int32(pos))
    got = decode_attention(q, kc, vc, jnp.int32(pos), impl=impl, block=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("impl", ["lax", PALLAS])
def test_unaligned_cache_length_parity(impl):
    """max_len 20 with the default block: decode_block rounds the clamp
    down to 16 and the clamped-start last block covers the 4-slot tail —
    parity must hold (the Mosaic-unaligned-block regression case)."""
    rng = np.random.default_rng(5)
    q, kc, vc = _rand_case(rng, 2, 1, 4, 16, 20)
    ref = dense_cache_attention(q, kc, vc, jnp.int32(19))
    got = decode_attention(q, kc, vc, jnp.int32(19), impl=impl)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("impl", ["lax", PALLAS])
def test_blocked_matches_dense_left_padded(impl):
    """kv_valid_from masks pre-prompt slots identically to the dense bias;
    compare only query rows at/after each row's first real token (fully
    masked pad rows are 0 on the blocked path, garbage-uniform on dense —
    neither is ever consumed downstream)."""
    rng = np.random.default_rng(1)
    pos, t = 0, 12
    q, kc, vc = _rand_case(rng, 2, t, 4, 16, 24)
    vf = jnp.asarray([5, 0], jnp.int32)
    ref = np.asarray(dense_cache_attention(q, kc, vc, jnp.int32(pos), kv_valid_from=vf))
    got = np.asarray(
        decode_attention(q, kc, vc, jnp.int32(pos), kv_valid_from=vf, impl=impl, block=8)
    )
    gp = pos + np.arange(t)
    for bi in range(2):
        rows = gp >= int(vf[bi])
        np.testing.assert_allclose(got[bi][rows], ref[bi][rows], rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("impl", ["lax", PALLAS])
def test_decode_never_visits_blocks_beyond_pos(impl):
    """NaN-poison everything past ceil((pos+t)/block)*block: a kernel that
    touches those slots propagates NaN through 0*NaN; the blocked path must
    stay finite (it never loads them), the dense path must NOT (it loads
    the whole buffer — the poison proves the probe works)."""
    rng = np.random.default_rng(2)
    pos, t, block = 13, 1, 16
    q, kc, vc = _rand_case(rng, 2, t, 4, 16, 48)
    lim = -(-(pos + t) // block) * block
    kc = kc.at[:, :, lim:, :].set(jnp.nan)
    vc = vc.at[:, :, lim:, :].set(jnp.nan)
    out = decode_attention(q, kc, vc, jnp.int32(pos), impl=impl, block=block)
    assert np.isfinite(np.asarray(out)).all()
    dense = dense_cache_attention(q, kc, vc, jnp.int32(pos))
    assert not np.isfinite(np.asarray(dense)).all()


def test_blocks_visited_formula():
    assert int(blocks_visited(1, 16, 64)) == 1
    assert int(blocks_visited(16, 16, 64)) == 1
    assert int(blocks_visited(17, 16, 64)) == 2
    assert int(blocks_visited(64, 16, 64)) == 4
    # clamped to the cache's total block count
    assert int(blocks_visited(64, 48, 64)) == 2
    # traced limit (the decode loop's pos + t) works too
    ns = jax.jit(lambda lim: blocks_visited(lim, 16, 64))(jnp.int32(33))
    assert int(ns) == 3


def test_decode_block_knob_loud(monkeypatch):
    assert decode_block(1024) == 256
    # clamping to a short cache must keep the multiple-of-8 tiling
    # invariant (round down), not hand Mosaic an unaligned block
    assert decode_block(100) == 96
    assert decode_block(20) == 16
    assert decode_block(1024, block=256) == 256
    assert decode_block(20, block=256) == 16
    # only a degenerate sub-8 cache yields a sub-8 block (lax-only path)
    assert decode_block(5) == 5
    assert decode_block(1024, block=128) == 128
    monkeypatch.setenv("PFX_DECODE_BLOCK", "64")
    assert decode_block(1024) == 64
    monkeypatch.setenv("PFX_DECODE_BLOCK", "twelve")
    with pytest.raises(ValueError, match="PFX_DECODE_BLOCK"):
        decode_block(1024)
    monkeypatch.setenv("PFX_DECODE_BLOCK", "100")  # not a multiple of 8
    with pytest.raises(ValueError, match="multiple of 8"):
        decode_block(1024)
    monkeypatch.delenv("PFX_DECODE_BLOCK")
    with pytest.raises(ValueError, match="impl"):
        decode_attention(
            jnp.zeros((1, 1, 1, 8)), jnp.zeros((1, 1, 8, 8)),
            jnp.zeros((1, 1, 8, 8)), jnp.int32(0), impl="cuda",
        )


def test_decode_attn_mode_loud(monkeypatch):
    assert decode_attn_mode() == "blocked"
    monkeypatch.setenv("PFX_DECODE_ATTN", "dense")
    assert decode_attn_mode() == "dense"
    monkeypatch.setenv("PFX_DECODE_ATTN", "danse")
    with pytest.raises(ValueError, match="PFX_DECODE_ATTN"):
        decode_attn_mode()


# ---------------------------------------------------------------------------
# End-to-end generation parity: blocked vs dense, while vs scan
# ---------------------------------------------------------------------------


@pytest.mark.slow  # two full e2e retraces; the op-level parity tests above
# cover the same kernel in the fast subset
def test_generate_blocked_matches_dense_e2e(monkeypatch):
    params = gpt.init(TINY, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 9), 0, TINY.vocab_size)
    gen = GenerationConfig(max_dec_len=8, decode_strategy="greedy_search", eos_token_id=-1)
    blocked = np.asarray(generate(params, prompt, TINY, gen))
    monkeypatch.setenv("PFX_DECODE_ATTN", "dense")
    jax.clear_caches()
    dense = np.asarray(generate(params, prompt, TINY, gen))
    monkeypatch.delenv("PFX_DECODE_ATTN")
    jax.clear_caches()
    np.testing.assert_array_equal(blocked, dense)


@pytest.mark.slow  # four full decode retraces (2 strategies x 2 loop modes);
# test_while_loop_early_exit_pads_after_eos keeps the fast-subset lock on
# the while-loop semantics
def test_while_loop_matches_scan_tokens(monkeypatch):
    """Token-for-token parity between the early-exit while_loop and the
    PFX_DECODE_SCAN=1 scan, for greedy AND sampling under one key."""
    params = gpt.init(TINY, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(2), (3, 6), 0, TINY.vocab_size)
    for strategy, kw in [
        ("greedy_search", {}),
        ("sampling", {"top_p": 0.9, "temperature": 0.8}),
    ]:
        gen = GenerationConfig(
            max_dec_len=7, decode_strategy=strategy, eos_token_id=96, **kw
        )
        key = jax.random.key(5)
        whiled = np.asarray(generate(params, prompt, TINY, gen, key=key))
        monkeypatch.setenv("PFX_DECODE_SCAN", "1")
        jax.clear_caches()
        scanned = np.asarray(generate(params, prompt, TINY, gen, key=key))
        monkeypatch.delenv("PFX_DECODE_SCAN")
        jax.clear_caches()
        np.testing.assert_array_equal(whiled, scanned, err_msg=strategy)


def test_while_loop_early_exit_pads_after_eos():
    """Force EOS on the first step: the while loop must stop and the
    remaining slots must be pad-filled exactly like the scan's."""
    params = gpt.init(TINY, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(3), (2, 4), 0, TINY.vocab_size)
    gen0 = GenerationConfig(max_dec_len=6, decode_strategy="greedy_search", eos_token_id=-1)
    firsts = np.asarray(generate(params, prompt, TINY, gen0))[:, 0]
    # eos = row 0's first greedy token: row 0 finishes at step 0
    gen = GenerationConfig(
        max_dec_len=6, decode_strategy="greedy_search",
        eos_token_id=int(firsts[0]), pad_token_id=0, min_dec_len=0,
    )
    out = np.asarray(generate(params, prompt, TINY, gen))
    assert out[0, 0] == int(firsts[0])
    assert np.all(out[0, 1:] == 0)


def test_decode_loop_mode_loud(monkeypatch):
    assert decode_loop_mode() == "while"
    monkeypatch.setenv("PFX_DECODE_SCAN", "1")
    assert decode_loop_mode() == "scan"
    monkeypatch.setenv("PFX_DECODE_SCAN", "yes")
    with pytest.raises(ValueError, match="PFX_DECODE_SCAN"):
        decode_loop_mode()


def test_generate_with_donated_cache_matches_internal():
    """generate(cache=..., return_cache=True) (the serving donation path)
    must equal the internally-allocated path; the donated buffer is
    consumed (aliased to the returned final cache), and RECYCLING the
    returned cache into a second request — stale tail slots and all —
    still produces identical tokens (the blocked kernel never visits
    blocks beyond pos+t, so stale data is unreachable)."""
    params = gpt.init(TINY, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(4), (2, 8), 0, TINY.vocab_size)
    gen = GenerationConfig(max_dec_len=6, decode_strategy="greedy_search", eos_token_id=-1)
    ref = np.asarray(generate(params, prompt, TINY, gen))
    cache = init_cache(TINY, 2, 8 + 6)
    fn = jax.jit(
        lambda p, x, c: generate(p, x, TINY, gen, cache=c, return_cache=True),
        donate_argnums=(2,),
    )
    got, cache_out = fn(params, prompt, cache)
    np.testing.assert_array_equal(np.asarray(got), ref)
    assert cache.k.is_deleted(), "donated cache must be consumed"
    # recycle the returned (non-zero, stale-tailed) cache
    got2, _ = fn(params, prompt, cache_out)
    np.testing.assert_array_equal(np.asarray(got2), ref)
    with pytest.raises(ValueError, match="cache shape"):
        generate(params, prompt, TINY, gen, cache=init_cache(TINY, 2, 4))
    with pytest.raises(ValueError, match="beam_search"):
        generate(
            params, prompt, TINY,
            GenerationConfig(max_dec_len=6, decode_strategy="beam_search"),
            cache=init_cache(TINY, 2, 8 + 6),
        )


@pytest.mark.slow  # three per-prompt reference retraces; the same
# kv_valid_from fold is locked fast by test_blocked_matches_dense_left_padded
# and tests/test_generation.py::test_bucketed_greedy_matches_unpadded
def test_bucketed_generation_still_matches_unpadded():
    """Left-padded buckets through the BLOCKED kernel + while loop match
    per-prompt unpadded generation (the kv_valid_from fold is exercised
    end-to-end, not just at the op level)."""
    params = gpt.init(TINY, jax.random.key(0))
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, TINY.vocab_size, n).tolist() for n in (3, 11)]
    gen = GenerationConfig(
        max_dec_len=6, decode_strategy="greedy_search", eos_token_id=-1, pad_token_id=0
    )
    refs = [
        np.asarray(generate(params, jnp.asarray([p]), TINY, gen))[0] for p in prompts
    ]
    padded, lens = pad_prompts(prompts, pad_token_id=0, multiple=16)
    out = np.asarray(generate(params, padded, TINY, gen, prompt_lens=lens))
    for i, r in enumerate(refs):
        np.testing.assert_array_equal(out[i], r)


# ---------------------------------------------------------------------------
# Fused nucleus sampling
# ---------------------------------------------------------------------------


def test_topk_prefilter_exact_vs_full_sort():
    """When every row's nucleus fits in the prefilter, the fast path must
    reproduce sample_top_p draw-for-draw (same key, same uniform, same
    prefix sums)."""
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(scale=3.0, size=(64, 1000)), jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top_ps = jnp.full((64,), 0.9)
    for seed in range(3):
        key = jax.random.key(seed)
        ref = np.asarray(sample_top_p(key, probs, top_ps))
        got = np.asarray(sample_top_p_topk(key, probs, top_ps, k=64))
        np.testing.assert_array_equal(got, ref)


def test_topk_prefilter_overflow_falls_back():
    """A near-flat distribution overflows a small prefilter (cum_k < p):
    the guarded fallback must route to the full sort and still match."""
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(scale=0.01, size=(8, 512)), jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top_ps = jnp.full((8,), 0.99)
    k = 16
    # sanity: the top-16 of a ~uniform 512-way dist covers ~3%, not 99%
    assert float(jnp.cumsum(jax.lax.top_k(probs, k)[0], -1)[:, -1].max()) < 0.99
    for seed in range(3):
        key = jax.random.key(seed)
        ref = np.asarray(sample_top_p(key, probs, top_ps))
        got = np.asarray(sample_top_p_topk(key, probs, top_ps, k=k))
        np.testing.assert_array_equal(got, ref)


def _sort_eqns(jaxpr, min_operand_len):
    """Recursively collect sort/argsort eqns whose operand trailing dim is
    >= min_operand_len (i.e. full-vocab sorts; lax.top_k is its own
    primitive and does not count)."""
    found = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "sort" and any(
            v.aval.shape and v.aval.shape[-1] >= min_operand_len
            for v in eqn.invars
        ):
            found.append(eqn)
        for sub in eqn.params.values():
            vals = sub if isinstance(sub, (list, tuple)) else [sub]
            for s in vals:
                if hasattr(s, "jaxpr"):
                    inner = s.jaxpr if hasattr(s.jaxpr, "eqns") else s
                    found += _sort_eqns(
                        inner if hasattr(inner, "eqns") else inner.jaxpr,
                        min_operand_len,
                    )
    return found


def test_fast_path_has_no_full_vocab_sort():
    """Acceptance: sample_logits(top_p<1) no longer argsorts the whole
    vocab on the fast path.  The cond's fast branch must contain no sort
    over a vocab-sized operand; the slow (fallback) branch keeps one."""
    vocab = 50257
    key = jax.random.key(0)
    logits = jnp.zeros((2, vocab))
    jaxpr = jax.make_jaxpr(
        lambda k, lg: sample_logits(k, lg, top_p=0.9)
    )(key, logits)
    conds = [e for e in jaxpr.jaxpr.eqns if e.primitive.name == "cond"]
    assert conds, "expected the prefilter lax.cond in the sampling jaxpr"
    branches = conds[-1].params["branches"]
    per_branch = [
        len(_sort_eqns(br.jaxpr, vocab)) for br in branches
    ]
    # one branch (the fallback) sorts the vocab, the other must not
    assert sorted(per_branch) == [0, 1], per_branch
    # and the pipeline OUTSIDE the guarded cond introduces no full sort
    # (top-level eqns only — the recursive walk would re-find the
    # fallback branch's sort inside the cond)
    top_level = [
        e for e in jaxpr.jaxpr.eqns
        if e.primitive.name == "sort" and any(
            v.aval.shape and v.aval.shape[-1] >= vocab for v in e.invars
        )
    ]
    assert not top_level


def test_topp_k_env_knob(monkeypatch):
    key = jax.random.key(0)
    logits = jnp.asarray(np.random.default_rng(2).normal(size=(4, 128)), jnp.float32)
    base = np.asarray(sample_logits(key, logits, top_p=0.9))
    monkeypatch.setenv("PFX_TOPP_K", "0")  # disable fast path -> full sort
    full = np.asarray(sample_logits(key, logits, top_p=0.9))
    np.testing.assert_array_equal(base, full)
    monkeypatch.setenv("PFX_TOPP_K", "not-an-int")
    with pytest.raises(ValueError, match="PFX_TOPP_K"):
        sample_logits(key, logits, top_p=0.9)
    monkeypatch.setenv("PFX_TOPP_K", "-3")
    with pytest.raises(ValueError, match="PFX_TOPP_K"):
        sample_logits(key, logits, top_p=0.9)
