"""Speculative decoding + int8 KV-cache quantization
(`ops/speculative.py`, the spec spellings in `models/gpt/generation.py`,
engine wiring in `core/continuous_batching.py`).

The acceptance criteria, in-process and deterministic:

  - GREEDY speculative output is TOKEN-IDENTICAL (f32 exact assert) to
    the non-speculative path on BOTH decode paths — the contiguous
    while-loop and the paged/continuous engine — including mid-decode
    admission/eviction and full-rejection iterations;
  - SAMPLED speculation preserves the target distribution (statistical
    test on a tiny vocab — the Leviathan residual rule);
  - int8 KV decode matches the unquantized kernels within quantization
    tolerance, and arena payload bytes HALVE vs bf16 (block bytes x
    pfx_kv_blocks_used is the evidence `pfx_kv_bytes` reports);
  - accepted-length variation is runtime data: repeating spec traffic
    keys ZERO extra compiles (the bounded-retrace contract).

Heavy suites are slow-marked and ride `make test-spec`; tier-1 keeps the
lean acceptance core (870s budget — see the Makefile tiering notes).
"""

import numpy as np
import pytest

# same tiny shapes as test_continuous_batching so the persistent compile
# cache is shared across files
TINY = {
    "Global": {"global_batch_size": 8, "seed": 3},
    "Engine": {"mix_precision": {"enable": False},
               "save_load": {"save_steps": 0}},
    "Model": {
        "module": "GPTModule",
        "vocab_size": 96,
        "hidden_size": 32,
        "num_layers": 2,
        "num_attention_heads": 4,
        "max_position_embeddings": 128,
        "dtype": "float32",
    },
    "Distributed": {},
    "Optimizer": {"name": "FusedAdamW",
                  "lr": {"name": "Constant", "learning_rate": 1e-3}},
    "Generation": {"max_dec_len": 8, "decode_strategy": "greedy_search",
                   "pad_to_multiple": 16, "eos_token_id": 95,
                   "pad_token_id": 0},
}

PROMPTS = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 10], [11, 12, 13, 14]]


# ---------------------------------------------------------------------------
# pure units: drafters, config parsing, multi-position sampling
# ---------------------------------------------------------------------------


def test_ngram_propose_host_lookup_and_fallback():
    from paddlefleetx_tpu.ops.speculative import ngram_propose_host

    # needle [2, 3]: last earlier occurrence ends at index 2 -> continue 4, 1, 2
    assert ngram_propose_host([1, 2, 3, 4, 1, 2, 3], 3, n=2) == [4, 1, 2]
    # continuation shorter than k: the last proposed token repeats
    # (needle [7, 8] ends at index 1 -> continuation [7, 8], padded)
    assert ngram_propose_host([7, 8, 7, 8], 3, n=2) == [7, 8, 8]
    # no match: repeat the last token
    assert ngram_propose_host([5, 6, 7], 3, n=2) == [7, 7, 7]
    with pytest.raises(ValueError, match="k >= 1"):
        ngram_propose_host([1], 0)


def test_ngram_propose_in_graph_matches_host_semantics():
    import jax.numpy as jnp

    from paddlefleetx_tpu.ops.speculative import ngram_propose

    ctx = jnp.asarray([[1, 2, 3, 4, 1, 2, 0, 0, 0, 0],
                       [9, 9, 9, 9, 9, 9, 0, 0, 0, 0]])
    known = jnp.int32(6)
    # row 0: needle (2, 3) ends an occurrence at p=2 -> draft 4, 1, 2;
    # row 1: needle (9, 9) matches everywhere, LAST valid end p=4 ->
    # draft ctx[5] = 9 then clamps to the fallback (pending) past known
    draft = ngram_propose(ctx, known, jnp.asarray([3, 9]), 3, n=2)
    assert draft.tolist()[0] == [4, 1, 2]
    assert draft.tolist()[1] == [9, 9, 9]
    # no match anywhere: fallback repeats pending
    fb = ngram_propose(ctx, known, jnp.asarray([42, 42]), 3, n=2)
    assert fb.tolist() == [[42, 42, 42], [42, 42, 42]]


def test_spec_config_parse_and_loud_errors():
    from paddlefleetx_tpu.ops.speculative import SpecConfig, spec_config_from

    assert spec_config_from({}) is None
    assert spec_config_from(None) is None
    sc = spec_config_from({"draft_k": 3, "ngram": 2})
    assert sc == SpecConfig(draft_k=3, ngram=2)
    with pytest.raises(ValueError, match="drafter"):
        spec_config_from({"draft_k": 2, "drafter": "medusa"})
    with pytest.raises(ValueError, match="draft_k"):
        SpecConfig(draft_k=0)


def test_sample_logits_multi_position_and_single_position_pin():
    """The satellite refactor: [b, k, vocab] verify logits sample with
    per-position subkeys; the original [b, vocab] contract is pinned
    (deterministic draw for a fixed key, one-hot logits force their
    token through every filter combination)."""
    import jax
    import jax.numpy as jnp

    from paddlefleetx_tpu.ops.sampling import sample_logits

    key = jax.random.key(7)
    # old single-position behavior: degenerate one-hot always samples it
    one_hot = jnp.full((4, 32), -1e9).at[jnp.arange(4), [3, 9, 21, 30]].set(0.0)
    for kw in ({}, {"top_k": 4}, {"top_p": 0.9}, {"temperature": 0.5}):
        got = sample_logits(key, one_hot, **kw)
        assert got.shape == (4,)
        assert got.tolist() == [3, 9, 21, 30], kw
    # and the draw for a fixed key is deterministic
    soft = jax.random.normal(key, (4, 32))
    a = sample_logits(key, soft, top_p=0.9)
    b = sample_logits(key, soft, top_p=0.9)
    assert a.tolist() == b.tolist()

    # multi-position: [b, k, v] -> [b, k]; each position draws its OWN
    # forced token (per-position subkeys, independent positions)
    forced = jnp.stack([
        jnp.full((4, 32), -1e9).at[jnp.arange(4), [1, 2, 3, 4]].set(0.0),
        jnp.full((4, 32), -1e9).at[jnp.arange(4), [5, 6, 7, 8]].set(0.0),
    ], axis=1)  # [4, 2, 32]
    got = sample_logits(key, forced, top_p=0.9)
    assert got.shape == (4, 2)
    assert got[:, 0].tolist() == [1, 2, 3, 4]
    assert got[:, 1].tolist() == [5, 6, 7, 8]


# ---------------------------------------------------------------------------
# contiguous-path greedy parity (raw generate(), no server)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    import jax

    from paddlefleetx_tpu.models.gpt import model as gpt
    from paddlefleetx_tpu.models.gpt.config import GPTConfig

    cfg = GPTConfig(
        vocab_size=96, hidden_size=32, num_layers=2, num_attention_heads=4,
        max_position_embeddings=128, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, dtype="float32",
    )
    return cfg, gpt.init(cfg, jax.random.key(0))


def test_contiguous_greedy_spec_token_identical(tiny_model):
    """THE contiguous acceptance parity (f32 exact): random prompts (low
    acceptance — rejection/correction exercised) and a repetitive prompt
    (high acceptance — multi-token commits exercised), plus the
    committed-vs-proposed accounting."""
    import jax
    import jax.numpy as jnp

    from paddlefleetx_tpu.models.gpt.generation import GenerationConfig, generate
    from paddlefleetx_tpu.ops.speculative import SpecConfig

    cfg, params = tiny_model
    gen = GenerationConfig(
        decode_strategy="greedy_search", max_dec_len=20, eos_token_id=95
    )
    rng = np.random.default_rng(0)
    prompts = jnp.asarray(rng.integers(1, 96, size=(3, 8)), jnp.int32)
    rep = jnp.asarray(np.tile([11, 23, 7, 41], (3, 2)), jnp.int32)
    for ids in (prompts, rep):
        base = generate(params, ids, cfg, gen, key=jax.random.key(1))
        toks, (prop, acc) = generate(
            params, ids, cfg, gen, key=jax.random.key(1),
            spec=SpecConfig(draft_k=4), return_spec_stats=True,
        )
        np.testing.assert_array_equal(np.asarray(base), np.asarray(toks))
        assert int(prop) > 0 and 0 <= int(acc) <= int(prop)


def test_contiguous_spec_full_rejection_still_token_identical(tiny_model, monkeypatch):
    """Every draft wrong on every iteration (the drafter is forced to a
    token the target never argmaxes): the loop degrades to one committed
    token per verify — output must STILL be token-identical, with zero
    accepted drafts."""
    import jax
    import jax.numpy as jnp

    from paddlefleetx_tpu.models.gpt import generation
    from paddlefleetx_tpu.models.gpt.generation import GenerationConfig, generate
    from paddlefleetx_tpu.ops.speculative import SpecConfig

    cfg, params = tiny_model
    gen = GenerationConfig(
        decode_strategy="greedy_search", max_dec_len=10, eos_token_id=95
    )
    ids = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
    base = generate(params, ids, cfg, gen, key=jax.random.key(1))
    # verified below: 77 never appears in the baseline output, so a
    # constant-77 draft is rejected at every slot
    assert 77 not in np.asarray(base)
    monkeypatch.setattr(
        generation, "ngram_propose",
        lambda ctx, known, pending, k, n=2: jnp.full(
            (ctx.shape[0], k), 77, jnp.int32
        ),
    )
    toks, (prop, acc) = generate(
        params, ids, cfg, gen, key=jax.random.key(1),
        spec=SpecConfig(draft_k=3), return_spec_stats=True,
    )
    np.testing.assert_array_equal(np.asarray(base), np.asarray(toks))
    assert int(acc) == 0 and int(prop) == 3 * 10 * 2  # k * steps * rows


def test_contiguous_spec_eos_and_left_padding_parity(tiny_model):
    """EOS mid-decode (early-exit + pad fill) and left-padded serving
    buckets both stay token-identical under speculation."""
    import jax
    import jax.numpy as jnp

    from paddlefleetx_tpu.models.gpt.generation import (
        GenerationConfig,
        generate,
        pad_prompts,
    )
    from paddlefleetx_tpu.ops.speculative import SpecConfig

    cfg, params = tiny_model
    spec = SpecConfig(draft_k=4)
    # forced EOS fires mid-window: exercises eos_hit truncation + pads
    gen = GenerationConfig(
        decode_strategy="greedy_search", max_dec_len=12, eos_token_id=95,
        forced_eos_token_id=95,
    )
    ids = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
    base = generate(params, ids, cfg, gen, key=jax.random.key(1))
    toks = generate(params, ids, cfg, gen, key=jax.random.key(1), spec=spec)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(toks))

    gen2 = GenerationConfig(
        decode_strategy="greedy_search", max_dec_len=8, eos_token_id=95
    )
    padded, lens = pad_prompts(PROMPTS[:3], 0, multiple=16)
    base2 = generate(params, padded, cfg, gen2, key=jax.random.key(1),
                     prompt_lens=lens)
    toks2 = generate(params, padded, cfg, gen2, key=jax.random.key(1),
                     prompt_lens=lens, spec=spec)
    np.testing.assert_array_equal(np.asarray(base2), np.asarray(toks2))


@pytest.mark.slow  # two extra compiles; make test-spec / test-all
def test_contiguous_spec_repetition_penalty_parity(tiny_model):
    """repetition_penalty != 1 routes the verify through the sequential
    counts-aware processor chain — still token-identical."""
    import jax
    import jax.numpy as jnp

    from paddlefleetx_tpu.models.gpt.generation import GenerationConfig, generate
    from paddlefleetx_tpu.ops.speculative import SpecConfig

    cfg, params = tiny_model
    gen = GenerationConfig(
        decode_strategy="greedy_search", max_dec_len=14, eos_token_id=95,
        repetition_penalty=1.3, min_dec_len=3,
    )
    ids = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
    base = generate(params, ids, cfg, gen, key=jax.random.key(1))
    toks = generate(params, ids, cfg, gen, key=jax.random.key(1),
                    spec=SpecConfig(draft_k=3))
    np.testing.assert_array_equal(np.asarray(base), np.asarray(toks))


# ---------------------------------------------------------------------------
# paged / continuous engine parity
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    import jax

    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict.from_nested(TINY)
    cfg = process_configs(cfg, num_devices=jax.device_count())
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    return GenerationServer(cfg, mesh, module)


@pytest.fixture(scope="module")
def sequential(server):
    return [server.generate_ids([p], max_dec_len=6)[0] for p in PROMPTS]


def _engine(server, **kw):
    from paddlefleetx_tpu.core.continuous_batching import PagedDecodeEngine

    kw.setdefault("max_batch", 4)
    return PagedDecodeEngine(server, **kw)


def _drain(engine, max_steps=64):
    for _ in range(max_steps):
        engine.step()
        if not engine.active.any():
            return
    raise AssertionError("engine never drained")


def test_paged_spec_parity_with_admission_eviction_and_retrace_bound(
    server, sequential
):
    """THE paged acceptance parity (f32 exact): speculative rows admitted
    mid-decode of the running batch AND a mid-decode eviction decode
    token-identically to the sequential coalesce path; per-row accepted
    lengths vary per iteration yet repeating the traffic adds ZERO
    compiles (accepted length is runtime data, never a compile key)."""
    from paddlefleetx_tpu.ops.speculative import SpecConfig

    eng = _engine(server, spec=SpecConfig(draft_k=3))
    s0 = eng.admit(PROMPTS[0], 6)
    s1 = eng.admit(PROMPTS[1], 6)
    eng.step()
    s2 = eng.admit(PROMPTS[2], 6)   # mid-decode admission
    eng.release(s1)                 # mid-decode eviction
    s3 = eng.admit(PROMPTS[3], 6)
    _drain(eng)
    assert eng.slots[s0].tokens == sequential[0]
    assert eng.slots[s2].tokens == sequential[2]
    assert eng.slots[s3].tokens == sequential[3]
    for s in (s0, s2, s3):
        eng.release(s)
    assert eng.cache.stats()["kv_blocks_used"] == 0
    assert eng.stats["spec_proposed"] > 0

    # retrace bound: the same traffic mix again — and the evicted prompt
    # alone — keys zero fresh compiles even though accepted lengths and
    # batch composition differ per iteration
    traces = eng.stats["traces"]
    slots = [eng.admit(p, 6) for p in PROMPTS]
    _drain(eng)
    assert [eng.slots[s].tokens for s in slots] == sequential
    assert eng.stats["traces"] == traces, eng.stats


def test_paged_spec_full_rejection_iterations(server, sequential, monkeypatch):
    """Forced all-wrong drafts: every iteration commits exactly one
    token per row (ncommit degenerates to the baseline), output stays
    token-identical and the acceptance counter reads zero."""
    from paddlefleetx_tpu.core import continuous_batching as cb
    from paddlefleetx_tpu.ops.speculative import SpecConfig

    flat = [t for row in sequential for t in row]
    assert 77 not in flat  # the forced draft token never argmaxes
    monkeypatch.setattr(
        cb, "ngram_propose_host", lambda seq, k, n=2: [77] * k
    )
    eng = _engine(server, spec=SpecConfig(draft_k=3))
    slots = [eng.admit(p, 6) for p in PROMPTS[:2]]
    _drain(eng)
    assert [eng.slots[s].tokens for s in slots] == sequential[:2]
    assert eng.stats["spec_accepted"] == 0
    assert eng.stats["spec_proposed"] > 0


def test_paged_spec_scheduler_end_to_end(server, sequential):
    """The threaded ContinuousScheduler over a speculative engine
    resolves futures with the sequential-path tokens and exports the
    acceptance metrics through its collector."""
    from paddlefleetx_tpu.core.continuous_batching import ContinuousScheduler
    from paddlefleetx_tpu.ops.speculative import SpecConfig
    from paddlefleetx_tpu.utils.telemetry import get_registry

    eng = _engine(server, spec=SpecConfig(draft_k=3))
    sched = ContinuousScheduler(eng, max_depth=8)
    sched.start()
    futs = [sched.submit([p], 6, deadline_s=120) for p in PROMPTS]
    got = [f.result(timeout=300)[0] for f in futs]
    assert got == sequential
    snap = {
        name: vals for name, _, vals in (
            (n, l, v) for n, l, v in sched.collect()
        )
    }
    assert "pfx_spec_accept_rate" in snap
    assert snap["pfx_kv_bytes"] >= 0
    reg = get_registry()
    assert reg.counter("pfx_spec_proposed_total").get() > 0
    assert sched.shutdown(timeout=30)


# ---------------------------------------------------------------------------
# int8 KV-cache quantization
# ---------------------------------------------------------------------------


def test_int8_attention_matches_native_within_tolerance():
    """Both spellings of both kernels: quantize a random cache/arena and
    compare against the unquantized math — per-(slot, head) amax/127
    symmetric quantization bounds the attention-output error far below
    the parity tolerance."""
    import jax.numpy as jnp

    from paddlefleetx_tpu.ops.decode_attention import (
        decode_attention,
        paged_decode_attention,
        quantize_kv,
    )

    rng = np.random.default_rng(0)
    b, n, d, L = 2, 2, 8, 32
    q = jnp.asarray(rng.normal(size=(b, 3, n, d)).astype(np.float32))
    kc = jnp.asarray(rng.normal(size=(b, n, L, d)).astype(np.float32))
    vc = jnp.asarray(rng.normal(size=(b, n, L, d)).astype(np.float32))
    base = np.asarray(decode_attention(q, kc, vc, jnp.int32(12), impl="lax"))
    kq, ks = quantize_kv(kc)
    vq, vs = quantize_kv(vc)
    assert kq.dtype == jnp.int8 and ks.shape == (b, n, L)
    for impl in ("lax", "pallas"):
        got = np.asarray(decode_attention(
            q, kq, vq, jnp.int32(12), impl=impl, k_scale=ks, v_scale=vs
        ))
        np.testing.assert_allclose(got, base, atol=0.05)

    bs, nb, M = 8, 10, 3
    kp = jnp.asarray(rng.normal(size=(nb, n, bs, d)).astype(np.float32))
    vp = jnp.asarray(rng.normal(size=(nb, n, bs, d)).astype(np.float32))
    tables = jnp.asarray([[1, 2, 3], [4, 5, 0]], jnp.int32)
    positions = jnp.asarray([10, 5], jnp.int32)
    pbase = np.asarray(paged_decode_attention(
        q, kp, vp, tables, positions, impl="lax"
    ))
    kpq, kps = quantize_kv(kp)
    vpq, vps = quantize_kv(vp)
    for impl in ("lax", "pallas"):
        got = np.asarray(paged_decode_attention(
            q, kpq, vpq, tables, positions, impl=impl,
            k_scale=kps, v_scale=vps,
        ))
        np.testing.assert_allclose(got, pbase, atol=0.05)
    # scales travel in pairs — loud otherwise
    with pytest.raises(ValueError, match="both"):
        decode_attention(q, kq, vq, jnp.int32(12), k_scale=ks)


def test_int8_arena_bytes_halve_and_e2e_parity(server, sequential):
    """The acceptance evidence: per-block K+V payload bytes under int8
    are exactly HALF the bf16 arena's (pfx_kv_bytes = blocks_used x
    block bytes), and an int8 engine still serves the parity prompts
    within tolerance (token-identical on this tiny f32 model)."""
    import jax.numpy as jnp

    from paddlefleetx_tpu.models.gpt.generation import init_paged_pools

    eng8 = _engine(server, kv_dtype="int8")
    assert eng8.pools.k.dtype == jnp.int8
    assert eng8.pools.k_scale is not None
    # bf16 reference arena of the same geometry: int8 payload is half
    bf16 = init_paged_pools(
        eng8.mcfg, eng8.cache.allocator.num_blocks, eng8.block,
        dtype=jnp.bfloat16, kv_dtype="bf16",
    )
    layers, _, heads, bs, d = bf16.k.shape
    bf16_block_bytes = 2 * layers * heads * bs * d * bf16.k.dtype.itemsize
    assert eng8.kv_block_bytes() * 2 == bf16_block_bytes

    slots = [eng8.admit(p, 6) for p in PROMPTS]
    used = eng8.cache.stats()["kv_blocks_used"]
    assert used > 0
    _drain(eng8)
    got = [eng8.slots[s].tokens for s in slots]
    # tolerance contract: identical lengths always; this tiny f32 model
    # is argmax-stable under the ~1/127 quantization error, so assert
    # token identity outright (a real bf16 model counts divergences in
    # the bench row instead)
    assert got == sequential


@pytest.mark.slow  # extra engine compiles; make test-spec / test-all
def test_int8_plus_speculation_compose(server, sequential):
    from paddlefleetx_tpu.ops.speculative import SpecConfig

    eng = _engine(server, spec=SpecConfig(draft_k=3), kv_dtype="int8")
    slots = [eng.admit(p, 6) for p in PROMPTS]
    _drain(eng)
    assert [eng.slots[s].tokens for s in slots] == sequential
    assert eng.stats["spec_proposed"] > 0


# ---------------------------------------------------------------------------
# sampled mode: distribution preservation (tiny vocab, statistical)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # statistical batch is its own compile; make test-spec
def test_sampled_spec_preserves_distribution_tiny_vocab():
    """Leviathan residual rule end-to-end: 1024 identical rows decode 4
    tokens with and without speculation; the per-position empirical
    token distributions must agree within sampling noise (calibrated by
    a baseline-vs-baseline control at a different key).  Runs the
    filtered (temperature + top-p) pipeline so the residual math is
    exercised where it is subtle."""
    import jax
    import jax.numpy as jnp

    from paddlefleetx_tpu.models.gpt import model as gpt
    from paddlefleetx_tpu.models.gpt.config import GPTConfig
    from paddlefleetx_tpu.models.gpt.generation import GenerationConfig, generate
    from paddlefleetx_tpu.ops.speculative import SpecConfig

    cfg = GPTConfig(
        vocab_size=16, hidden_size=16, num_layers=1, num_attention_heads=2,
        max_position_embeddings=32, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, dtype="float32",
    )
    params = gpt.init(cfg, jax.random.key(0))
    gen = GenerationConfig(
        decode_strategy="sampling", max_dec_len=4, temperature=0.9,
        top_p=0.8, eos_token_id=15, pad_token_id=0,
    )
    B = 1024
    ids = jnp.tile(jnp.asarray([[3, 7, 2, 9]], jnp.int32), (B, 1))

    def marginals(tokens):
        t = np.asarray(tokens)
        return np.stack([
            np.bincount(t[:, j], minlength=16) / t.shape[0]
            for j in range(t.shape[1])
        ])

    base = marginals(generate(params, ids, cfg, gen, key=jax.random.key(1)))
    ctrl = marginals(generate(params, ids, cfg, gen, key=jax.random.key(2)))
    spec = marginals(generate(
        params, ids, cfg, gen, key=jax.random.key(3),
        spec=SpecConfig(draft_k=2),
    ))

    # total-variation distance per position: spec-vs-base must sit in
    # the same noise band as base-vs-base (2x margin + epsilon)
    tv_ctrl = 0.5 * np.abs(base - ctrl).sum(axis=1)
    tv_spec = 0.5 * np.abs(base - spec).sum(axis=1)
    assert (tv_spec <= 2.0 * tv_ctrl + 0.06).all(), (tv_spec, tv_ctrl)


# ---------------------------------------------------------------------------
# serving-layer wiring
# ---------------------------------------------------------------------------


@pytest.mark.slow  # fresh server boot + compiles; make test-spec / test-all
def test_serving_config_routes_speculation_and_counts():
    """Generation.speculative.draft_k in the config routes generate_ids
    through the spec loop: output token-identical to a plain server,
    acceptance counters live on stats/registry, and repeat traffic keys
    no extra traces."""
    import copy

    import jax

    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    tiny = copy.deepcopy(TINY)
    tiny["Generation"]["speculative"] = {"draft_k": 3}
    cfg = process_configs(AttrDict.from_nested(tiny),
                          num_devices=jax.device_count())
    mesh = init_dist_env(cfg)
    srv = GenerationServer(cfg, mesh, build_module(cfg))
    assert srv.spec is not None and srv.spec.draft_k == 3

    plain = copy.deepcopy(TINY)
    cfg2 = process_configs(AttrDict.from_nested(plain),
                           num_devices=jax.device_count())
    mesh2 = init_dist_env(cfg2)
    ref_srv = GenerationServer(cfg2, mesh2, build_module(cfg2))

    for p in PROMPTS[:2]:
        assert (srv.generate_ids([p], max_dec_len=6)
                == ref_srv.generate_ids([p], max_dec_len=6))
    assert srv.stats["spec_proposed"] > 0
    assert srv.stats["spec_accepted"] >= 0
    traces = srv.stats["traces"]
    srv.generate_ids([PROMPTS[0]], max_dec_len=6)
    assert srv.stats["traces"] == traces


@pytest.mark.slow
@pytest.mark.fault  # subprocess drill conventions; make test-spec
def test_spec_serve_drill_cli_roundtrip(tmp_path):
    """Through the real CLI: tools/serve.py --scheduler continuous
    --draft-k 3 --kv-dtype int8 serves token-stable greedy output, the
    acceptance counters reach /metrics, and SIGTERM drain still exits
    0 — the speculative engine honors every serving contract."""
    import signal

    from test_paged_drills import (
        _finish,
        _healthz,
        _metrics,
        _post,
        _start_server,
    )

    proc, port = _start_server(
        tmp_path, extra_args=("--draft-k", "3", "--kv-dtype", "int8"),
    )
    try:
        body = {"prompt_ids": [1, 2, 3], "max_tokens": 8, "deadline_s": 45}
        code1, r1 = _post(port, body, timeout=90)
        assert code1 == 200, (code1, r1)
        code2, r2 = _post(port, body, timeout=90)
        assert code2 == 200, (code2, r2)
        assert r1["completion_ids"] == r2["completion_ids"]
        m = _metrics(port)
        assert m.get("pfx_spec_proposed_total", 0) > 0, m
        assert m.get("pfx_spec_accepted_total", -1) >= 0, m
        assert "pfx_spec_accept_rate" in m, m
        assert m.get("pfx_kv_bytes", -1) >= 0, m
        assert m["pfx_kv_blocks_used"] == 0, m  # all rows retired
        h = _healthz(port)
        assert h["state"] == "ok", h
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0
    finally:
        log = _finish(proc)
    assert "Traceback" not in log, log[-3000:]
