"""Fused LayerNorm kernel: forward/grad parity vs naive XLA, plus the
ERNIE WordPiece tokenizer and small utils."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.models.gpt.model import layer_norm
from paddlefleetx_tpu.ops.fused_layernorm import fused_layer_norm


def _naive(x, scale, bias, residual=None, eps=1e-5):
    if residual is not None:
        x = x + residual
    return layer_norm(x, scale, bias, eps)


@pytest.mark.parametrize("shape", [(4, 16, 64), (2, 128)])
@pytest.mark.parametrize("with_res", [False, True])
def test_fused_ln_forward_parity(shape, with_res):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=shape), jnp.float32)
    res = jnp.asarray(rng.normal(size=shape), jnp.float32) if with_res else None
    scale = jnp.asarray(rng.normal(size=shape[-1:]), jnp.float32)
    bias = jnp.asarray(rng.normal(size=shape[-1:]), jnp.float32)
    got = fused_layer_norm(x, scale, bias, residual=res)
    want = _naive(x, scale, bias, residual=res)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("with_res", [False, True])
def test_fused_ln_grad_parity(with_res):
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 8, 32)), jnp.float32)
    res = jnp.asarray(rng.normal(size=(4, 8, 32)), jnp.float32) if with_res else None
    scale = jnp.asarray(rng.normal(size=(32,)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(32,)), jnp.float32)

    def loss_fused(x, scale, bias, res):
        return jnp.sum(jnp.sin(fused_layer_norm(x, scale, bias, residual=res)))

    def loss_naive(x, scale, bias, res):
        return jnp.sum(jnp.sin(_naive(x, scale, bias, residual=res)))

    argnums = (0, 1, 2) if res is None else (0, 1, 2, 3)
    gf = jax.grad(loss_fused, argnums)(x, scale, bias, res)
    gn = jax.grad(loss_naive, argnums)(x, scale, bias, res)
    for a, b in zip(gf, gn):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_fused_ln_bf16():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 64)), jnp.bfloat16)
    scale = jnp.ones((64,), jnp.float32)
    bias = jnp.zeros((64,), jnp.float32)
    out = fused_layer_norm(x, scale, bias)
    assert out.dtype == jnp.bfloat16
    want = _naive(x.astype(jnp.float32), scale, bias)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(want), rtol=2e-2, atol=2e-2
    )


# ---------------------------------------------------------------------------
# ERNIE WordPiece tokenizer
# ---------------------------------------------------------------------------


def test_ernie_tokenizer_roundtrip(tmp_path):
    from paddlefleetx_tpu.data.tokenizers.ernie_tokenizer import ErnieTokenizer

    tok = ErnieTokenizer.from_tiny_corpus(["the quick brown fox jumps", "hello world"])
    enc = tok.encode("the quick fox", "hello world", max_seq_len=16)
    ids, types = enc["input_ids"], enc["token_type_ids"]
    assert ids[0] == tok.cls_token_id and ids.count(tok.sep_token_id) == 2
    assert len(ids) == len(types)
    assert set(types) == {0, 1}
    assert tok.decode(ids) == "the quick fox hello world"

    # wordpiece splits unseen compounds into known pieces
    pieces = tok.tokenize("foxworld")
    assert all(p in tok.vocab for p in pieces) and len(pieces) > 1
    assert tok.decode(tok.convert_tokens_to_ids(pieces)) == "foxworld"

    # save/load
    path = str(tmp_path / "vocab.txt")
    tok.save(path)
    tok2 = ErnieTokenizer.from_file(path)
    assert tok2.encode("the quick fox")["input_ids"] == tok.encode("the quick fox")["input_ids"]

    # punctuation is split into its own token (here OOV -> [UNK]); unknown
    # words collapse to [UNK]
    out = tok.tokenize("the fox, x9z!")
    assert out[0] == "the" and out[1] == "fox"
    assert len(out) == 5  # the, fox, ',', x9z, '!'
    assert tok.unk_token in out


def test_device_and_version_utils():
    from paddlefleetx_tpu.utils import device, version

    assert device.get_device_type() in ("cpu", "tpu", "gpu", "axon")
    assert device.device_count() >= 1
    device.synchronize()  # must not raise
    assert isinstance(device.memory_stats(), dict)
    assert "paddlefleetx-tpu" in version.show()
