"""Fault-tolerance subsystem units + in-process engine drills: retry,
fault-spec parsing, checkpoint validation/quarantine/retention, anomaly
guard, preemption handling, exit-after-save, and the async-save atexit
join.  Cross-process crash-resume parity lives in test_fault_injection.py.
"""

import json
import os

import numpy as np
import pytest

from paddlefleetx_tpu.utils import resilience as R
from paddlefleetx_tpu.utils.checkpoint import (
    gc_checkpoints,
    latest_checkpoint,
    quarantine_checkpoint,
    restore_params,
    validate_checkpoint,
)

from test_engine import (  # noqa: F401 — shared tiny GPT cfg + fake-ckpt builder
    _fake_ckpt,
    _losses_from_run,
    tiny_cfg,
)


@pytest.fixture(autouse=True)
def _clean_fault_state():
    R.reset_fault_state()
    yield
    R.reset_fault_state()


# ---------------------------------------------------------------------------
# retry + env knobs
# ---------------------------------------------------------------------------


def test_retry_backoff_and_success():
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("flake")
        return "ok"

    out = R.retry(
        flaky, attempts=4, backoff=0.1, jitter=0.0, sleep=sleeps.append
    )
    assert out == "ok" and calls["n"] == 3
    assert sleeps == [pytest.approx(0.1), pytest.approx(0.2)]  # exponential


def test_retry_exhaustion_wraps_with_context():
    with pytest.raises(RuntimeError, match="orbax write: failed after 2"):
        R.retry(
            lambda: (_ for _ in ()).throw(OSError("disk")),
            attempts=2, backoff=0.0, jitter=0.0, desc="orbax write",
            sleep=lambda _s: None,
        )


def test_retry_non_retryable_propagates_immediately():
    calls = {"n": 0}

    def corrupt():
        calls["n"] += 1
        raise ValueError("bit rot")

    with pytest.raises(ValueError, match="bit rot"):
        R.retry(corrupt, attempts=5, backoff=0.0, jitter=0.0)
    assert calls["n"] == 1  # corruption must not be re-read 5 times


def test_retry_env_knobs_loud_parse(monkeypatch):
    monkeypatch.setenv("PFX_RETRY_ATTEMPTS", "2")
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise OSError("x")

    with pytest.raises(RuntimeError):
        R.retry(always, backoff=0.0, jitter=0.0, sleep=lambda _s: None)
    assert calls["n"] == 2  # env knob reached the helper

    monkeypatch.setenv("PFX_RETRY_ATTEMPTS", "lots")
    with pytest.raises(ValueError, match="PFX_RETRY_ATTEMPTS"):
        R.retry(always)
    monkeypatch.setenv("PFX_RETRY_ATTEMPTS", "0")
    with pytest.raises(ValueError, match=">= 1"):
        R.retry(always)
    monkeypatch.delenv("PFX_RETRY_ATTEMPTS")
    monkeypatch.setenv("PFX_RETRY_BACKOFF", "fast")
    with pytest.raises(ValueError, match="PFX_RETRY_BACKOFF"):
        R.retry(always)


# ---------------------------------------------------------------------------
# fault-injection spec
# ---------------------------------------------------------------------------


def test_fault_spec_parse(monkeypatch):
    monkeypatch.delenv("PFX_FAULT", raising=False)
    assert R.fault_spec() is None
    monkeypatch.setenv("PFX_FAULT", "sigterm:7")
    assert R.fault_spec() == ("sigterm", 7, 1)
    monkeypatch.setenv("PFX_FAULT", "nan_grads:5:3")
    assert R.fault_spec() == ("nan_grads", 5, 3)
    for bad in ("typo_site:1", "sigterm", "sigterm:x", "sigterm:1:0", "a:b:c:d"):
        monkeypatch.setenv("PFX_FAULT", bad)
        with pytest.raises(ValueError, match="PFX_FAULT"):
            R.fault_spec()


def test_fault_spec_data_sites(monkeypatch):
    """corrupt_sample parses like every other site; io_stall's third field
    is SECONDS (fractional allowed), never a count."""
    monkeypatch.setenv("PFX_FAULT", "corrupt_sample:9:4")
    assert R.fault_spec() == ("corrupt_sample", 9, 4)
    monkeypatch.setenv("PFX_FAULT", "io_stall:3:0.5")
    assert R.fault_spec() == ("io_stall", 3, 1)
    assert R.io_stall_seconds() == 0.5
    monkeypatch.setenv("PFX_FAULT", "io_stall:3")
    assert R.fault_spec() == ("io_stall", 3, 1)
    assert R.io_stall_seconds() == 2.0  # default stall
    monkeypatch.setenv("PFX_FAULT", "io_stall:3:zzz")
    with pytest.raises(ValueError, match="PFX_FAULT"):
        R.fault_spec()


def test_corrupt_sample_fire_raises(monkeypatch):
    monkeypatch.setenv("PFX_FAULT", "corrupt_sample:2")
    R.reset_fault_state()
    assert not R.maybe_fire("corrupt_sample", 1)
    with pytest.raises(R.DataCorruptionError, match="corrupt_sample"):
        R.maybe_fire("corrupt_sample", 2)
    assert not R.maybe_fire("corrupt_sample", 3)  # count spent on the raise
    R.reset_fault_state()


def test_maybe_fire_counts_and_threshold(monkeypatch):
    monkeypatch.setenv("PFX_FAULT", "nan_grads:5:2")
    assert not R.maybe_fire("nan_grads", 4)   # before the step threshold
    assert not R.maybe_fire("sigterm", 9)     # wrong site never fires
    assert R.maybe_fire("nan_grads", 5)
    assert R.maybe_fire("nan_grads", 6)
    assert not R.maybe_fire("nan_grads", 7)   # count exhausted
    R.reset_fault_state()
    assert R.maybe_fire("nan_grads", 8)       # fresh process semantics


def test_poison_batch():
    batch = {
        "tokens": np.ones((2, 4), np.int32),
        "loss_mask": np.ones((2, 4), np.float32),
    }
    out = R.poison_batch(batch)
    assert np.isnan(out["loss_mask"]).all()
    assert out["tokens"].dtype == np.int32  # int leaves untouched
    with pytest.raises(ValueError, match="float batch leaf"):
        R.poison_batch({"tokens": np.ones((2,), np.int32)})


# ---------------------------------------------------------------------------
# anomaly guard
# ---------------------------------------------------------------------------


def test_anomaly_guard_skip_streak_budget():
    g = R.AnomalyGuard(max_skip_streak=3)
    assert g.observe(2.0, False) is None
    for _ in range(2):
        assert g.observe(float("nan"), True) is None
    reason = g.observe(float("nan"), True)
    assert reason and "3 consecutive" in reason
    g.reset()
    assert g.observe(float("nan"), True) is None  # streak forgotten
    # a finite step in between resets the streak
    g2 = R.AnomalyGuard(max_skip_streak=2)
    assert g2.observe(1.0, True) is None
    assert g2.observe(1.0, False) is None
    assert g2.observe(1.0, True) is None  # streak back to 1: no trip


def test_anomaly_guard_loss_spike_zscore():
    g = R.AnomalyGuard(
        max_skip_streak=0, spike_zscore=4.0, spike_streak=2,
        window=32, min_window=8,
    )
    for i in range(12):  # establish a tight baseline around 2.0
        assert g.observe(2.0 + 0.01 * (i % 3), False) is None
    assert g.observe(9.0, False) is None        # first spike: streak 1
    reason = g.observe(9.0, False)              # second consecutive: trip
    assert reason and "spike" in reason
    # spiking losses stayed out of the window: baseline mean is still ~2
    assert float(np.mean(g.losses)) < 2.1
    # disabled detectors never trip
    g_off = R.AnomalyGuard(max_skip_streak=0, spike_zscore=0.0)
    for _ in range(50):
        assert g_off.observe(1e9, False) is None
        assert g_off.observe(float("nan"), True) is None


# ---------------------------------------------------------------------------
# checkpoint validation / quarantine / retention GC
# ---------------------------------------------------------------------------


def test_validate_checkpoint_reasons(tmp_path):
    ok = _fake_ckpt(tmp_path, 1)
    assert validate_checkpoint(str(ok)) is None
    assert "meta.json" in validate_checkpoint(str(_fake_ckpt(tmp_path, 2, meta=False)))
    assert "payload" in validate_checkpoint(str(_fake_ckpt(tmp_path, 3, payload=None)))
    assert "_METADATA" in validate_checkpoint(
        str(_fake_ckpt(tmp_path, 4, metadata=False))
    )
    assert "no array data" in validate_checkpoint(
        str(_fake_ckpt(tmp_path, 5, data=False, metadata=True))
    )
    # params-only layout (HF convert output) validates too
    assert validate_checkpoint(str(_fake_ckpt(tmp_path, 6, payload="params"))) is None


def test_latest_checkpoint_quarantine_and_fallback_order(tmp_path):
    """The newest structurally-broken checkpoint is quarantined (renamed
    *.corrupt) and selection falls back to the previous good one — over
    empty dirs, meta-only stubs, and non-checkpoint noise."""
    assert latest_checkpoint(str(tmp_path)) is None  # empty output dir
    (tmp_path / "noise").mkdir()
    (tmp_path / "step_nan").mkdir()
    _fake_ckpt(tmp_path, 2)
    _fake_ckpt(tmp_path, 4)
    stub = _fake_ckpt(tmp_path, 9, payload=None)  # meta-only partial
    best = latest_checkpoint(str(tmp_path))
    assert best is not None and best.endswith("step_4")
    assert not stub.exists() and (tmp_path / "step_9.corrupt").is_dir()
    # validate=False restores the raw newest-complete-meta behavior
    _fake_ckpt(tmp_path, 11, payload=None)
    raw = latest_checkpoint(str(tmp_path), validate=False)
    assert raw is not None and raw.endswith("step_11")
    # quarantine=False reports the fallback without renaming
    assert latest_checkpoint(str(tmp_path), quarantine=False).endswith("step_4")
    assert (tmp_path / "step_11").is_dir()


def test_quarantine_name_collisions(tmp_path):
    a = _fake_ckpt(tmp_path, 7)
    first = quarantine_checkpoint(str(a))
    assert first.endswith("step_7.corrupt")
    b = _fake_ckpt(tmp_path, 7)
    second = quarantine_checkpoint(str(b))
    assert second.endswith("step_7.corrupt.1")


def test_gc_checkpoints_keep_last_n_never_deletes_last_good(tmp_path):
    for s in (1, 2, 3, 4, 5):
        _fake_ckpt(tmp_path, s)
    protect = str(tmp_path / "step_1")  # oldest, but it is the last GOOD one
    removed = gc_checkpoints(str(tmp_path), keep_last_n=2, protect=protect)
    left = sorted(p.name for p in tmp_path.iterdir())
    assert left == ["step_1", "step_4", "step_5"], (left, removed)
    # keep_last_n=0 disables GC entirely
    assert gc_checkpoints(str(tmp_path), keep_last_n=0) == []
    # broken dirs don't count toward the quota and are not deleted here
    _fake_ckpt(tmp_path, 9, payload=None)
    gc_checkpoints(str(tmp_path), keep_last_n=2, protect=None)
    assert (tmp_path / "step_9").is_dir()


def test_resume_with_fallback_bounds_quarantines(tmp_path):
    """Only corruption-class load failures quarantine, and at most
    max_quarantines dirs per resume attempt — a systemic failure (storage
    outage, config mismatch breaking EVERY restore) must not eat the
    whole checkpoint history."""
    from paddlefleetx_tpu.utils.checkpoint import resume_with_fallback

    class CorruptEveryTime:
        def load(self, path):
            raise ValueError("DATA_LOSS: rotten bytes")

    for s in range(1, 6):
        _fake_ckpt(tmp_path, s)
    with pytest.raises(RuntimeError, match="systemic"):
        resume_with_fallback(CorruptEveryTime(), str(tmp_path), max_quarantines=2)
    corrupt = sorted(p.name for p in tmp_path.iterdir() if ".corrupt" in p.name)
    assert len(corrupt) == 2, corrupt  # bounded: 3 good dirs survive

    # NON-corruption failures propagate untouched and quarantine nothing:
    # an exhausted transient retry, and a restore-target mismatch whose
    # ValueError lacks the tensorstore corruption markers (config typo —
    # it would condemn EVERY dir, not this one)
    class OutageEveryTime:
        def load(self, path):
            raise RuntimeError("restore: failed after 3 attempt(s)")

    class MismatchEveryTime:
        def load(self, path):
            raise ValueError("user tree and restore target have different structures")

    before = sorted(p.name for p in tmp_path.iterdir())
    with pytest.raises(RuntimeError, match="failed after"):
        resume_with_fallback(OutageEveryTime(), str(tmp_path))
    with pytest.raises(ValueError, match="different structures"):
        resume_with_fallback(MismatchEveryTime(), str(tmp_path))
    assert sorted(p.name for p in tmp_path.iterdir()) == before

    # and a load that succeeds returns the newest good path
    class FineEngine:
        def load(self, path):
            self.loaded = path

    eng = FineEngine()
    got = resume_with_fallback(eng, str(tmp_path))
    assert got is not None and got.endswith("step_3") and eng.loaded == got


def test_restore_params_truncated_quarantines_with_actionable_error(tmp_path):
    """restore_params on a bit-rotted array file raises an error naming the
    quarantined path (satellite: utils/checkpoint.py coverage)."""
    from paddlefleetx_tpu.utils.checkpoint import save_params_checkpoint

    out = save_params_checkpoint(
        str(tmp_path / "ck"),
        {"w": np.ones((8, 8), np.float32)},
        source="unit-test",
        model_fields={"vocab_size": 8},
    )
    assert restore_params(out)["w"].shape == (8, 8)  # sane before rot
    R.truncate_checkpoint_payload(out)
    with pytest.raises(RuntimeError, match=r"quarantined") as ei:
        restore_params(out)
    assert ".corrupt" in str(ei.value)
    assert os.path.isdir(out + ".corrupt")
    assert not os.path.isdir(out)


# ---------------------------------------------------------------------------
# engine drills (8-device CPU mesh, tiny GPT from test_engine.tiny_cfg)
# ---------------------------------------------------------------------------


def test_engine_preemption_sigterm_saves_marker(tmp_path, devices8, monkeypatch):
    """Injected SIGTERM after step 2: the loop finishes the in-flight step,
    writes a final checkpoint with the `preempted` marker, and fit returns
    with engine.preempted set (the launcher then exits 0)."""
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.data.builders import build_dataloader
    from paddlefleetx_tpu.parallel.env import init_dist_env

    monkeypatch.setenv("PFX_FAULT", "sigterm:2")
    cfg = tiny_cfg(tmp_path)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    loader = build_dataloader(cfg, "Train")
    with mesh:
        engine = Engine(cfg, module, mesh)
        state = engine.fit(loader)
    assert engine.preempted
    assert int(state.step) == 2  # stopped right after the in-flight step
    ckpt = os.path.join(cfg.Engine.save_load.output_dir, "step_2")
    meta = json.load(open(os.path.join(ckpt, "meta.json")))
    assert meta.get("preempted") is True and meta["step"] == 2


def test_engine_exit_after_save(tmp_path, devices8):
    """exit_after_save: clean stop right after the first periodic save."""
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.data.builders import build_dataloader
    from paddlefleetx_tpu.parallel.env import init_dist_env

    cfg = tiny_cfg(tmp_path)
    cfg.Engine.save_load.save_steps = 3
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    loader = build_dataloader(cfg, "Train")
    with mesh:
        engine = Engine(cfg, module, mesh)
        engine.exit_after_save = True
        state = engine.fit(loader)
    assert engine.preempted and int(state.step) == 3
    assert os.path.exists(
        os.path.join(cfg.Engine.save_load.output_dir, "step_3", "meta.json")
    )


@pytest.mark.slow  # ~19s engine boot; anomaly rollback stays
# tier-1-drilled through the real CLI by BOTH
# test_fault_injection.py::test_nan_rollback_rewind_replay_parity and
# test_model_stats.py::test_nan_rollback_drill_names_group_in_event_flight_and_report,
# and the rollback skip-budget contract by
# test_engine_rollback_restores_skip_budget; still in make test-fault /
# test-all (PR 8 tier-1 budget convention)
def test_engine_anomaly_rollback_reenters_loop(tmp_path, devices8, monkeypatch):
    """A NaN streak past the skip budget rolls params+opt-state back to the
    last checkpoint, emits a structured rollback event, and training
    re-enters the loop and completes."""
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.data.builders import build_dataloader
    from paddlefleetx_tpu.parallel.env import init_dist_env

    monkeypatch.setenv("PFX_FAULT", "nan_grads:5:3")
    cfg = tiny_cfg(tmp_path)
    cfg.Engine.max_steps = 10
    cfg.Engine.logging_freq = 1
    cfg.Engine.save_load.save_steps = 4
    cfg.Engine.metrics_file = str(tmp_path / "metrics.jsonl")
    cfg.Engine.resilience = {"max_skip_streak": 3, "max_rollbacks": 1}
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    loader = build_dataloader(cfg, "Train")
    with mesh:
        engine = Engine(cfg, module, mesh)
        state = engine.fit(loader)
    assert int(state.step) == 10  # rolled back, then finished the run
    lines = [json.loads(x) for x in open(cfg.Engine.metrics_file)]
    events = [l for l in lines if l.get("event") == "rollback"]
    assert len(events) == 1, lines
    assert events[0]["ckpt"].endswith("step_4")
    assert "consecutive non-finite" in events[0]["reason"]
    # post-rollback steps are healthy again
    steps = [l for l in lines if "loss" in l]
    assert np.isfinite(steps[-1]["loss"])


def test_engine_rollback_restores_skip_budget(tmp_path, devices8, monkeypatch):
    """The rollback-rewind replay re-hits any corrupt sample in the failed
    window; the budget must be restored to the CHECKPOINT's value (via the
    ckpt's loader state) or max_skips is charged twice for one record and
    a run the replay contract says survives dies budget-exhausted."""
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.data.batch_sampler import (
        DataLoader,
        DistributedBatchSampler,
        collate_stack,
    )
    from paddlefleetx_tpu.data.builders import build_dataset
    from paddlefleetx_tpu.parallel.env import init_dist_env

    monkeypatch.setenv("PFX_FAULT", "nan_grads:5:3")
    cfg = tiny_cfg(tmp_path)
    cfg.Engine.max_steps = 10
    cfg.Engine.logging_freq = 1
    cfg.Engine.save_load.save_steps = 4
    cfg.Engine.metrics_file = str(tmp_path / "metrics.jsonl")
    cfg.Engine.resilience = {"max_skip_streak": 3, "max_rollbacks": 1}
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    gbs = int(cfg.Global.global_batch_size)
    ds = build_dataset(cfg, "Train", num_samples=cfg.Engine.max_steps * gbs)

    # poison a sample served in the post-checkpoint window (batch 5, the
    # first batch the rollback replays): probe an identical sampler
    probe = iter(DistributedBatchSampler(len(ds), gbs, shuffle=True, seed=11))
    bad = int([next(probe) for _ in range(5)][4][3])

    class _Poisoned:
        def __len__(self):
            return len(ds)

        def __getitem__(self, i):
            if int(i) == bad:
                raise ValueError(f"rotten record {i}")
            return ds[int(i)]

    loader = DataLoader(
        _Poisoned(),
        DistributedBatchSampler(len(ds), gbs, shuffle=True, seed=11),
        collate_stack,
        max_skips=1,  # ONE budget: double-charging the replay would raise
    )
    with mesh:
        engine = Engine(cfg, module, mesh)
        state = engine.fit(loader)
    assert int(state.step) == 10  # rolled back, replayed the skip, finished
    lines = [json.loads(x) for x in open(cfg.Engine.metrics_file)]
    events = [l for l in lines if l.get("event") == "rollback"]
    assert len(events) == 1 and events[0]["rewound"] is True
    # the same record was skipped once per pass under the restored budget
    skips = [l for l in lines if l.get("event") == "data_skip"]
    assert len(skips) == 2
    assert all(s["index"] == bad and s["skips"] == 1 for s in skips)


def test_engine_anomaly_without_checkpoint_fails_loudly(
    tmp_path, devices8, monkeypatch
):
    """Budget exceeded with nothing to roll back to: a loud RuntimeError,
    not an infinite skip loop."""
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.data.builders import build_dataloader
    from paddlefleetx_tpu.parallel.env import init_dist_env

    monkeypatch.setenv("PFX_FAULT", "nan_grads:1:8")
    cfg = tiny_cfg(tmp_path)
    cfg.Engine.resilience = {"max_skip_streak": 2, "max_rollbacks": 1}
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    loader = build_dataloader(cfg, "Train")
    with mesh:
        engine = Engine(cfg, module, mesh)
        with pytest.raises(RuntimeError, match="anomaly budget"):
            engine.fit(loader)


def test_engine_keep_last_n_retention(tmp_path, devices8):
    """save_load.keep_last_n bounds the checkpoint footprint during fit."""
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.data.builders import build_dataloader
    from paddlefleetx_tpu.parallel.env import init_dist_env

    cfg = tiny_cfg(tmp_path)
    cfg.Engine.max_steps = 5
    cfg.Engine.save_load.save_steps = 1
    cfg.Engine.save_load.keep_last_n = 2
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    loader = build_dataloader(cfg, "Train")
    with mesh:
        engine = Engine(cfg, module, mesh)
        engine.fit(loader)
    out = cfg.Engine.save_load.output_dir
    left = sorted(n for n in os.listdir(out) if n.startswith("step_"))
    assert left == ["step_4", "step_5"], left


def test_async_save_atexit_join_registered(tmp_path, devices8):
    """The first async save registers the interpreter-exit join so a
    started save either completes or is cleanly absent (satellite bugfix:
    SIGTERM/exit while _save_thread is in flight)."""
    from paddlefleetx_tpu.utils.config import AttrDict

    cfg = tiny_cfg(tmp_path)
    cfg.Engine.save_load = AttrDict.from_nested(
        {"save_steps": 0, "output_dir": str(tmp_path / "out"), "async_save": True}
    )
    _losses, engine = _losses_from_run(cfg, steps=1)
    assert not engine._atexit_registered
    path = engine.save(str(tmp_path / "ackpt"))
    assert engine._atexit_registered
    engine._atexit_join()  # what atexit will run: joins + surfaces durably
    assert os.path.exists(os.path.join(path, "meta.json"))
    assert engine._save_thread is None
