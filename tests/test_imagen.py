"""Imagen tests: diffusion schedule identities, unet shapes (base + SR),
CFG wiring, loss training step, cascade sampling smoke, dataset."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.models.multimodal.imagen import diffusion as diff
from paddlefleetx_tpu.models.multimodal.imagen import imagen, unet as unet_lib
from paddlefleetx_tpu.models.multimodal.imagen.imagen import ImagenConfig
from paddlefleetx_tpu.models.multimodal.imagen.unet import UnetConfig

# Pallas interpret-mode / big-compile file: excluded from the fast
# subset (pytest -m 'not slow'); run the full suite for release checks
pytestmark = pytest.mark.slow

TINY_UNET = dict(
    dim=16, dim_mults=(1, 2), layer_attns=(False, True),
    layer_cross_attns=(False, True), num_resnet_blocks=1,
    attn_heads=2, attn_head_dim=8, num_time_tokens=2,
)

TINY = ImagenConfig(
    unets=(TINY_UNET,),
    image_sizes=(16,),
    text_embed_dim=24,
    timesteps=8,
    dtype="float32",
)

TINY_SR = ImagenConfig(
    unets=(TINY_UNET, TINY_UNET),
    image_sizes=(8, 16),
    text_embed_dim=24,
    timesteps=8,
    unet_number=2,
    dtype="float32",
)


def _batch(b=2, size=16, L=5, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "images": jnp.asarray(rng.uniform(size=(b, size, size, 3)), jnp.float32),
        "text_embeds": jnp.asarray(rng.normal(size=(b, L, 24)), jnp.float32),
        "text_mask": jnp.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], jnp.int32),
    }


def test_schedule_identities():
    sched = diff.GaussianDiffusionContinuousTimes("cosine", 10)
    t = jnp.asarray([0.0, 0.25, 0.5, 0.75, 1.0])
    alpha, sigma = diff.log_snr_to_alpha_sigma(sched.log_snr(t))
    # variance preserving: alpha^2 + sigma^2 == 1
    np.testing.assert_allclose(np.asarray(alpha**2 + sigma**2), 1.0, atol=1e-5)
    # t=0 nearly clean, t=1 nearly pure noise
    assert float(alpha[0]) > 0.99 and float(alpha[-1]) < 0.05

    # q_sample -> predict_start_from_noise round-trips x0 (t < 1: at t=1
    # alpha ~ 4e-8 and the fp32 subtraction cancels catastrophically)
    t = jnp.asarray([0.0, 0.25, 0.5, 0.75, 0.9])
    x0 = jnp.ones((5, 4, 4, 3)) * 0.3
    noise = jax.random.normal(jax.random.key(0), x0.shape)
    x_t, _, _ = sched.q_sample(x0, t, noise)
    rec = sched.predict_start_from_noise(x_t, t, noise)
    np.testing.assert_allclose(np.asarray(rec), np.asarray(x0), atol=1e-3)

    # v parameterization round-trip
    v = sched.calculate_v(x0, t, noise)
    rec_v = sched.predict_start_from_v(x_t, t, v)
    np.testing.assert_allclose(np.asarray(rec_v), np.asarray(x0), atol=1e-3)


def test_unet_base_shapes():
    ucfg = UnetConfig.from_config({**TINY_UNET, "text_embed_dim": 24, "dtype": "float32"})
    params = unet_lib.init(ucfg, jax.random.key(0))
    b = _batch()
    x = jnp.zeros((2, 16, 16, 3))
    out = unet_lib.forward(
        params, x, jnp.asarray([0.1, 0.9]), ucfg,
        text_embeds=b["text_embeds"], text_mask=b["text_mask"],
    )
    assert out.shape == (2, 16, 16, 3)
    assert np.all(np.isfinite(np.asarray(out)))


def test_unet_sr_lowres_cond():
    ucfg = UnetConfig.from_config(
        {**TINY_UNET, "text_embed_dim": 24, "lowres_cond": True, "dtype": "float32"}
    )
    params = unet_lib.init(ucfg, jax.random.key(1))
    x = jnp.zeros((2, 16, 16, 3))
    out = unet_lib.forward(
        params, x, jnp.asarray([0.5, 0.5]), ucfg,
        text_embeds=_batch()["text_embeds"],
        lowres_cond_img=jnp.ones_like(x) * 0.1,
        lowres_aug_time=jnp.asarray([0.2, 0.2]),
    )
    assert out.shape == (2, 16, 16, 3)


def test_cfg_drop_changes_output():
    """Dropping text cond must route through the null embeddings."""
    ucfg = UnetConfig.from_config({**TINY_UNET, "text_embed_dim": 24, "dtype": "float32"})
    params = unet_lib.init(ucfg, jax.random.key(2))
    b = _batch()
    x = jnp.ones((2, 16, 16, 3)) * 0.1
    t = jnp.asarray([0.5, 0.5])
    kept = unet_lib.forward(params, x, t, ucfg, text_embeds=b["text_embeds"],
                            text_mask=b["text_mask"],
                            cond_drop_mask=jnp.asarray([False, False]))
    dropped = unet_lib.forward(params, x, t, ucfg, text_embeds=b["text_embeds"],
                               text_mask=b["text_mask"],
                               cond_drop_mask=jnp.asarray([True, True]))
    assert float(jnp.max(jnp.abs(kept - dropped))) > 1e-4
    # dropped output is text-independent
    b2 = _batch(seed=9)
    dropped2 = unet_lib.forward(params, x, t, ucfg, text_embeds=b2["text_embeds"],
                                text_mask=b2["text_mask"],
                                cond_drop_mask=jnp.asarray([True, True]))
    np.testing.assert_allclose(np.asarray(dropped), np.asarray(dropped2), atol=1e-5)


def test_p_losses_and_grad_step():
    import optax

    params = imagen.init(TINY, jax.random.key(3))
    batch = _batch()
    loss = imagen.p_losses(params, batch, TINY, jax.random.key(0), train=True)
    assert np.isfinite(float(loss))
    # ~unit-variance noise target at random init -> loss near 1
    assert 0.2 < float(loss) < 5.0

    tx = optax.adam(1e-3)
    opt = tx.init(params)

    @jax.jit
    def step(p, o, k):
        loss, g = jax.value_and_grad(
            lambda pp: imagen.p_losses(pp, batch, TINY, k, train=True)
        )(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    losses = []
    for i in range(10):
        params, opt, loss = step(params, opt, jax.random.key(i))
        losses.append(float(loss))
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def test_p_losses_bf16_compute():
    """AMP path: fp32 master params + bfloat16 compute dtype.  The unet
    casts its fp32 params per use (unet.py forward entry), so the conv
    lhs/rhs dtypes agree — regression for the bench_extra imagen case,
    which trains under Engine mix_precision bf16."""
    import dataclasses

    cfg = dataclasses.replace(TINY, dtype="bfloat16")
    params = imagen.init(TINY, jax.random.key(3))  # fp32 masters
    loss = imagen.p_losses(params, _batch(), cfg, jax.random.key(0), train=True)
    assert np.isfinite(float(loss))
    g = jax.grad(
        lambda p: imagen.p_losses(p, _batch(), cfg, jax.random.key(0), train=True)
    )(params)
    # grads arrive in the master dtype (fp32) and are finite
    leaves = jax.tree.leaves(g)
    assert all(x.dtype == jnp.float32 for x in leaves)
    assert all(bool(jnp.isfinite(x).all()) for x in leaves)


def test_sr_unet_p_losses():
    params = imagen.init(TINY_SR, jax.random.key(4))
    loss = imagen.p_losses(params, _batch(), TINY_SR, jax.random.key(0), train=True)
    assert np.isfinite(float(loss))


def test_cascade_sample_smoke():
    p0 = imagen.init(TINY, jax.random.key(5))
    sr_params = imagen.init(TINY_SR, jax.random.key(6))
    b = _batch()
    out = imagen.sample(
        [p0, sr_params], TINY_SR, jax.random.key(7),
        text_embeds=b["text_embeds"], text_mask=b["text_mask"],
        guidance_scale=3.0,
    )
    assert out.shape == (2, 16, 16, 3)
    assert np.all(np.isfinite(np.asarray(out)))
    assert 0.0 <= float(out.min()) and float(out.max()) <= 1.0


def test_imagen_dataset(tmp_path):
    from paddlefleetx_tpu.data.multimodal_dataset import (
        ImagenDataset,
        write_synthetic_image_text_corpus,
    )
    from paddlefleetx_tpu.data.tokenizers.t5_tokenizer import T5Tokenizer

    path = write_synthetic_image_text_corpus(str(tmp_path / "corpus.jsonl"), n=4)
    tok = T5Tokenizer.from_tiny_corpus(["red green cat dog sky tree sun sea"])
    ds = ImagenDataset(path, image_size=16, max_seq_len=8, tokenizer=tok)
    assert len(ds) == 4
    item = ds[0]
    assert item["images"].shape == (16, 16, 3)
    assert 0.0 <= item["images"].min() and item["images"].max() <= 1.0
    assert item["input_ids"].shape == (8,)

    # tokenizer from a saved vocab (the config-yaml path) + resize of a
    # FLOAT npy image must not truncate to black
    import base64 as b64
    import io
    import json

    vocab_path = str(tmp_path / "vocab.json")
    tok.save(vocab_path)
    buf = io.BytesIO()
    np.save(buf, np.full((24, 24, 3), 0.6, np.float32))
    float_corpus = str(tmp_path / "float.jsonl")
    with open(float_corpus, "w") as f:
        f.write(json.dumps({
            "image_npy_base64": b64.b64encode(buf.getvalue()).decode(),
            "caption": "red cat",
        }) + "\n")
    ds2 = ImagenDataset(float_corpus, image_size=16, max_seq_len=8,
                        tokenizer_vocab=vocab_path)
    item2 = ds2[0]
    assert item2["images"].shape == (16, 16, 3)
    np.testing.assert_allclose(item2["images"], 0.6, atol=1e-3)
    assert item2["input_ids"].shape == (8,)


def test_imagen_module_with_frozen_t5(tmp_path):
    """ImagenModule end-to-end with a frozen T5 text encoder in extra."""
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.utils.config import AttrDict

    cfg = AttrDict.from_nested(
        {
            "Model": dict(
                module="ImagenModule",
                unets=[dict(TINY_UNET)],
                image_sizes=[16],
                text_embed_dim=32,  # == t5 d_model
                timesteps=8,
                dtype="float32",
                text_encoder=dict(name="t5", vocab_size=96, d_model=32, d_kv=8,
                                  d_ff=48, num_layers=1, num_decoder_layers=1,
                                  num_heads=4, dtype="float32", dropout_rate=0.0),
            ),
            "Data": {},
        }
    )
    mod = build_module(cfg)
    params = mod.init_params(jax.random.key(0))
    extra = mod.init_extra(jax.random.key(1), params)
    rng = np.random.default_rng(0)
    batch = {
        "images": jnp.asarray(rng.uniform(size=(2, 16, 16, 3)), jnp.float32),
        "input_ids": jnp.asarray(rng.integers(2, 96, (2, 6))),
    }
    loss, _ = mod.loss_fn(params, batch, extra=extra, train=True)
    assert np.isfinite(float(loss))
    # frozen encoder: no gradient reaches extra
    g = jax.grad(
        lambda p, e: mod.loss_fn(p, batch, extra=e, train=False)[0],
        argnums=1,
    )(params, extra)
    assert max(
        (float(jnp.max(jnp.abs(x))) for x in jax.tree.leaves(g)), default=0.0
    ) == 0.0
