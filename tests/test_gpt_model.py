"""GPT model unit tests: shapes, init-loss sanity, determinism, recompute,
and TP/SP/FSDP layout parity on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.models.gpt import model as gpt
from paddlefleetx_tpu.models.gpt.config import GPTConfig, preset
from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
from paddlefleetx_tpu.parallel.sharding import make_rules, tree_logical_to_sharding

TINY = GPTConfig(
    vocab_size=128,
    hidden_size=64,
    num_layers=2,
    num_attention_heads=8,
    max_position_embeddings=64,
    dtype="float32",
)


def _batch(key, cfg, b=2, s=16):
    tokens = jax.random.randint(key, (b, s), 0, cfg.vocab_size)
    return {
        "tokens": tokens,
        "labels": jnp.roll(tokens, -1, axis=1),
        "loss_mask": jnp.ones((b, s), jnp.float32),
    }


def test_forward_shapes():
    params = gpt.init(TINY, jax.random.key(0))
    logits = gpt.forward(params, jnp.zeros((2, 16), jnp.int32), TINY)
    assert logits.shape == (2, 16, TINY.vocab_size)


def test_param_count_345m():
    cfg = preset("gpt-345M", vocab_size=51200)
    import paddlefleetx_tpu.models.common as common

    specs = gpt.gpt_specs(cfg)
    n = sum(np.prod(s.shape) for s in jax.tree.leaves(specs, is_leaf=lambda x: hasattr(x, "shape")))
    # ~355M params for GPT-medium with vocab 51200
    assert 330e6 < n < 420e6


def test_init_loss_near_log_vocab():
    """Reference sanity anchor: step-0 loss ~ ln(vocab) (SURVEY §6: 10.99 for
    51200 ≈ ln(51200)=10.84 + init noise)."""
    params = gpt.init(TINY, jax.random.key(0))
    batch = _batch(jax.random.key(1), TINY)
    loss = gpt.loss_fn(params, batch, TINY, train=False)
    assert abs(float(loss) - np.log(TINY.vocab_size)) < 0.5


def test_dropout_determinism_and_train_eval():
    params = gpt.init(TINY, jax.random.key(0))
    batch = _batch(jax.random.key(1), TINY)
    k = jax.random.key(2)
    l1 = gpt.loss_fn(params, batch, TINY, dropout_key=k, train=True)
    l2 = gpt.loss_fn(params, batch, TINY, dropout_key=k, train=True)
    assert float(l1) == float(l2)
    l3 = gpt.loss_fn(params, batch, TINY, dropout_key=jax.random.key(3), train=True)
    assert float(l1) != float(l3)


@pytest.mark.parametrize("gran", ["full", "full_attn", "core_attn"])
def test_recompute_matches(gran):
    cfg_rc = GPTConfig(**{**TINY.__dict__, "use_recompute": True, "recompute_granularity": gran})
    params = gpt.init(TINY, jax.random.key(0))
    batch = _batch(jax.random.key(1), TINY)

    g0 = jax.grad(lambda p: gpt.loss_fn(p, batch, TINY, train=False))(params)
    g1 = jax.grad(lambda p: gpt.loss_fn(p, batch, cfg_rc, train=False))(params)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def _sharded_loss(devices, mesh_cfg, rules_kwargs, params, batch):
    mesh = build_mesh(mesh_cfg, devices)
    rules = make_rules(**rules_kwargs)
    logical = gpt.gpt_logical_axes(TINY)
    shardings = tree_logical_to_sharding(logical, mesh, rules)
    p_sharded = jax.device_put(params, shardings)
    ctx = gpt.ShardingCtx(mesh, rules)

    @jax.jit
    def f(p, b):
        return gpt.loss_fn(p, b, TINY, ctx=ctx, train=False)

    return float(f(p_sharded, batch))


def test_layout_parity(devices8):
    """Loss identical across parallel layouts (the reference's 'precision
    validation across layouts' guarantee, env.py:62-71)."""
    params = gpt.init(TINY, jax.random.key(0))
    batch = _batch(jax.random.key(1), TINY)
    ref = float(gpt.loss_fn(params, batch, TINY, train=False))

    layouts = [
        (MeshConfig(dp_degree=8), {}),
        (MeshConfig(mp_degree=8), {}),
        (MeshConfig(dp_degree=2, mp_degree=4), {}),
        (MeshConfig(mp_degree=4, dp_degree=2), {"sequence_parallel": True}),
        (MeshConfig(sharding_degree=4, mp_degree=2), {"fsdp_enabled": True}),
        (MeshConfig(dp_degree=2, sharding_degree=2, mp_degree=2), {"fsdp_enabled": True}),
    ]
    for mesh_cfg, rk in layouts:
        got = _sharded_loss(devices8, mesh_cfg, rk, params, batch)
        np.testing.assert_allclose(got, ref, rtol=2e-5, err_msg=f"{mesh_cfg} {rk}")


def test_grad_layout_parity(devices8):
    params = gpt.init(TINY, jax.random.key(0))
    batch = _batch(jax.random.key(1), TINY)
    g_ref = jax.grad(lambda p: gpt.loss_fn(p, batch, TINY, train=False))(params)

    mesh = build_mesh(MeshConfig(dp_degree=2, mp_degree=4), devices8)
    rules = make_rules()
    shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(TINY), mesh, rules)
    p_sharded = jax.device_put(params, shardings)
    ctx = gpt.ShardingCtx(mesh, rules)
    g = jax.jit(jax.grad(lambda p, b: gpt.loss_fn(p, b, TINY, ctx=ctx, train=False)))(
        p_sharded, batch
    )
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_selective_remat_parity():
    """'selective' remat (named save-set, default qkv+attn_out) never changes
    values — loss and grads match the no-remat graph exactly."""
    import dataclasses

    params = gpt.init(TINY, jax.random.key(0))
    batch = _batch(jax.random.key(1), TINY)
    sel = dataclasses.replace(TINY, use_recompute=True, recompute_granularity="selective")

    ref = jax.value_and_grad(lambda p: gpt.loss_fn(p, batch, TINY, train=False))(params)
    got = jax.jit(jax.value_and_grad(lambda p: gpt.loss_fn(p, batch, sel, train=False)))(
        params
    )
    np.testing.assert_allclose(float(got[0]), float(ref[0]), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(ref[1]), jax.tree.leaves(got[1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)

    # train=True with dropout: the recomputed mask in the backward pass must
    # match the forward mask (bench.py's default config runs exactly this),
    # for both threefry and rbg key impls
    drop = dataclasses.replace(
        TINY, hidden_dropout_prob=0.3, use_recompute=True, recompute_granularity="selective"
    )
    nore = dataclasses.replace(TINY, hidden_dropout_prob=0.3)
    for impl in (None, "rbg"):
        key = jax.random.key(42, impl=impl)
        ref = jax.value_and_grad(
            lambda p: gpt.loss_fn(p, batch, nore, dropout_key=key, train=True)
        )(params)
        got = jax.jit(
            jax.value_and_grad(
                lambda p: gpt.loss_fn(p, batch, drop, dropout_key=key, train=True)
            )
        )(params)
        np.testing.assert_allclose(float(got[0]), float(ref[0]), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(ref[1]), jax.tree.leaves(got[1])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_fused_ln_model_parity():
    """use_fused_ln swaps every LayerNorm for the Pallas kernel (interpret
    mode off-TPU); forward must match the jnp composite."""
    import dataclasses

    params = gpt.init(TINY, jax.random.key(0))
    batch = _batch(jax.random.key(1), TINY)
    fused = dataclasses.replace(TINY, use_fused_ln=True)

    ref = gpt.forward(params, batch["tokens"], TINY)
    got = gpt.forward(params, batch["tokens"], fused)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)

    g_ref = jax.grad(lambda p: gpt.loss_fn(p, batch, TINY, train=False))(params)
    g = jax.grad(lambda p: gpt.loss_fn(p, batch, fused, train=False))(params)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)
