"""Compression (prune/quant) + profiler hook + MoE grad-clip parity tests."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlefleetx_tpu.utils import compression as comp


def _params(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w1": jnp.asarray(rng.normal(size=(16, 32)), jnp.float32),
        "b1": jnp.asarray(rng.normal(size=(32,)), jnp.float32),
        "block": {"w2": jnp.asarray(rng.normal(size=(32, 8)), jnp.float32)},
    }


def test_prune_per_tensor_ratio():
    p = _params()
    pruned, masks = comp.prune_params(p, ratio=0.5, criterion="l1")
    sp = comp.sparsity(pruned)
    assert 0.45 < sp < 0.55
    # biases untouched
    np.testing.assert_array_equal(np.asarray(pruned["b1"]), np.asarray(p["b1"]))
    # masks reapply idempotently
    again = comp.apply_masks(pruned, masks)
    np.testing.assert_array_equal(np.asarray(again["w1"]), np.asarray(pruned["w1"]))
    # surviving entries are the largest-magnitude ones
    w = np.asarray(p["w1"]).ravel()
    kept = np.asarray(masks["w1"]).ravel()
    assert np.abs(w[kept]).min() >= np.abs(w[~kept]).max() - 1e-6


def test_prune_global_ranking():
    p = {
        "small": jnp.ones((4, 4)) * 0.01,
        "big": jnp.ones((4, 4)) * 10.0,
    }
    pruned, _ = comp.prune_params(p, ratio=0.5, global_ranking=True)
    # global ranking kills the small tensor entirely, keeps the big one
    assert float(jnp.sum(pruned["small"] == 0)) == 16
    assert float(jnp.sum(pruned["big"] == 0)) == 0


def test_quant_roundtrip_error():
    p = _params()
    assert comp.quant_error(p) < 0.02  # int8 per-channel: <2% of absmax
    q, s = comp.quantize_params(p)
    assert q["w1"].dtype == jnp.int8
    assert q["b1"].dtype == jnp.float32  # non-weight untouched
    deq = comp.dequantize_params(q, s)
    assert deq["w1"].dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(deq["w1"]), np.asarray(p["w1"]), atol=float(jnp.max(jnp.abs(p["w1"]))) / 100
    )


def test_fake_quant_straight_through():
    w = jnp.asarray(np.random.default_rng(1).normal(size=(8, 8)), jnp.float32)
    out = comp.fake_quant(w)
    assert float(jnp.max(jnp.abs(out - w))) < float(jnp.max(jnp.abs(w))) / 100
    # straight-through: gradient of sum(fake_quant(w)) is all ones
    g = jax.grad(lambda x: comp.fake_quant(x).sum())(w)
    np.testing.assert_allclose(np.asarray(g), 1.0)


@pytest.mark.slow  # ~14s real profiler window; trace-row aggregation,
# hlo_stats fallback, and memory-summary branches stay tier-1 via the
# PR 5 profiler units in this file and test_telemetry's trace-window
# wiring; still in make test-mid / test-all (PR 8 tier-1 budget
# convention)
def test_profiler_hook_writes_trace(tmp_path):
    from paddlefleetx_tpu.utils.profiler import ProfilerHook

    log_dir = str(tmp_path / "prof")
    hook = ProfilerHook({"enable": True, "scheduler": [1, 3], "log_dir": log_dir})
    for step in range(1, 5):
        jnp.ones((8, 8)) @ jnp.ones((8, 8))  # some device work
        hook.step(step)
    hook.close()
    files = [os.path.join(r, f) for r, _, fs in os.walk(log_dir) for f in fs]
    assert any("xplane" in f or "trace" in f for f in files), files


@pytest.mark.slow  # ~15s real profiler window; tier-1 budget funding for
# the shard_map-port tests.  Replacement coverage: summary/op-row
# aggregation, the hlo_stats-failure fallback, memory-summary branches,
# and the telemetry wiring stay tier-1 via the synthetic-row units below
# (test_profiler_trace_event_rows_aggregation / _memory_summary_branches /
# _trace_window_feeds_telemetry); the other real-window test
# (test_profiler_hook_writes_trace) has been slow-marked since PR 10 on
# the same grounds; still in make test-all.
def test_profiler_summary_views(tmp_path):
    """Trace close emits the reference's sorted op/memory summary views
    (eager_engine.py:866-925): summary_ops.txt ranked by self time + raw
    hlo_stats.json + summary_memory.txt."""
    from paddlefleetx_tpu.utils.profiler import ProfilerHook

    log_dir = str(tmp_path / "prof")
    hook = ProfilerHook(
        {"enable": True, "scheduler": [1, 2], "log_dir": log_dir, "summary_top": 5}
    )
    for step in range(1, 4):
        (jnp.ones((128, 128)) @ jnp.ones((128, 128))).block_until_ready()
        hook.step(step)
    hook.close()

    ops = os.path.join(log_dir, "summary_ops.txt")
    assert os.path.exists(ops), os.listdir(log_dir)
    text = open(ops).read()
    assert "self %" in text and "source:" in text
    # source line + header + at least one ranked row
    assert len(text.splitlines()) >= 3, text
    # ranked by self time, descending
    rows = text.splitlines()[2:]
    times = [float(r.split()[-2]) for r in rows]
    assert times == sorted(times, reverse=True)
    # raw per-HLO table is exported alongside when the xprof toolchain is
    # importable (rows populate on real accelerator traces; without xprof
    # the hook degrades to trace-event aggregation only)
    import importlib.util

    if importlib.util.find_spec("xprof") is not None:
        assert os.path.exists(os.path.join(log_dir, "hlo_stats.json"))
    assert os.path.exists(os.path.join(log_dir, "summary_memory.txt"))

    # summaries are config-gated off
    log2 = str(tmp_path / "prof2")
    hook2 = ProfilerHook(
        {"enable": True, "scheduler": [1, 2], "log_dir": log2, "summary": False}
    )
    for step in range(1, 4):
        (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
        hook2.step(step)
    hook2.close()
    assert not os.path.exists(os.path.join(log2, "summary_ops.txt"))


def test_profiler_trace_event_rows_aggregation(tmp_path):
    """The chrome-trace fallback aggregation (`_trace_event_rows`, the
    live path on backends with no per-HLO device stats and no xprof):
    complete 'X' events aggregate per op name with occurrence counts and
    summed durations, and the op summary names the fallback source even
    when the hlo_stats path raises."""
    from paddlefleetx_tpu.utils.profiler import ProfilerHook

    log_dir = str(tmp_path / "prof")
    hook = ProfilerHook({"enable": True, "scheduler": [1, 2], "log_dir": log_dir})
    for step in range(1, 4):
        (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
        hook.step(step)
    # rows straight off the captured CPU trace
    rows = hook._trace_event_rows()
    assert rows, "CPU trace produced no complete events"
    for r in rows:
        assert set(r) == {"op", "category", "occurrences", "total_us", "self_us"}
        assert r["occurrences"] >= 1 and r["total_us"] >= 0
        assert r["category"] == "trace" and r["self_us"] == r["total_us"]
    # force the fallback branch explicitly: hlo_stats raising must degrade
    # to trace events, never kill the close
    hook._hlo_stats_rows = lambda: (_ for _ in ()).throw(RuntimeError("no xprof"))
    hook.close()
    text = open(os.path.join(log_dir, "summary_ops.txt")).read()
    assert "trace events" in text.splitlines()[0], text.splitlines()[0]


def test_profiler_memory_summary_branches(tmp_path, monkeypatch):
    """`_write_memory_summary`: the no-`memory_stats()` branch writes the
    honest pointer at the trace's memory_profile tool; a backend WITH
    stats writes the sorted per-device key table."""
    import jax as _jax

    from paddlefleetx_tpu.utils.profiler import ProfilerHook

    class _Dev:
        def __init__(self, stats):
            self._stats = stats

        def memory_stats(self):
            return self._stats

        def __repr__(self):
            return "StubDevice(cpu:0)"

    hook = ProfilerHook({"enable": False, "log_dir": str(tmp_path / "p")})
    os.makedirs(hook.log_dir, exist_ok=True)

    monkeypatch.setattr(_jax, "local_devices", lambda: [_Dev(None)])
    hook._write_memory_summary()
    path = os.path.join(hook.log_dir, "summary_memory.txt")
    assert "no memory_stats()" in open(path).read()

    monkeypatch.setattr(
        _jax, "local_devices",
        lambda: [_Dev({"bytes_in_use": 123, "peak_bytes_in_use": 456})],
    )
    hook._write_memory_summary()
    text = open(path).read()
    assert "StubDevice(cpu:0)" in text
    assert "bytes_in_use" in text and "456" in text


def test_profiler_trace_window_feeds_telemetry(tmp_path):
    """A completed trace window lands on the registry (trace counter +
    window seconds) and in the flight recorder ring."""
    from paddlefleetx_tpu.utils import telemetry
    from paddlefleetx_tpu.utils.profiler import ProfilerHook

    reg = telemetry.get_registry()
    before = reg.value("pfx_profiler_traces_total")
    hook = ProfilerHook(
        {"enable": True, "scheduler": [1, 2], "log_dir": str(tmp_path / "p"),
         "summary": False}
    )
    for step in range(1, 4):
        (jnp.ones((32, 32)) @ jnp.ones((32, 32))).block_until_ready()
        hook.step(step)
    hook.close()
    assert reg.value("pfx_profiler_traces_total") == before + 1
    assert reg.value("pfx_profiler_trace_seconds") > 0
    kinds = [e.get("event") for e in telemetry.get_flight_recorder().events()]
    assert "profiler_trace_start" in kinds and "profiler_trace_stop" in kinds


def test_moe_grad_clip_parity(devices8):
    """GSPMD makes the reference ClipGradForMOEByGlobalNorm
    (optims/grad_clip.py:27-156) a plain global-norm clip: expert params
    are ONE sharded pytree, so optax.global_norm over sharded grads equals
    the single-device norm — the expert-group allreduce is implicit."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh

    rng = np.random.default_rng(2)
    grads = {
        "dense": jnp.asarray(rng.normal(size=(16, 16)), jnp.float32),
        "experts": jnp.asarray(rng.normal(size=(8, 16, 16)), jnp.float32),
    }
    ref_norm = float(optax.global_norm(grads))
    clip = optax.clip_by_global_norm(1.0)
    ref_clipped, _ = clip.update(grads, clip.init(grads))

    mesh = build_mesh(MeshConfig(dp_degree=8))
    sharded = {
        "dense": jax.device_put(grads["dense"], NamedSharding(mesh, P())),
        "experts": jax.device_put(grads["experts"], NamedSharding(mesh, P("data"))),
    }

    @jax.jit
    def clipped_norm(g):
        state = clip.init(g)
        out, _ = clip.update(g, state)
        return optax.global_norm(g), out

    norm, out = clipped_norm(sharded)
    assert abs(float(norm) - ref_norm) < 1e-4
    np.testing.assert_allclose(
        np.asarray(out["experts"]), np.asarray(ref_clipped["experts"]), rtol=1e-5
    )


def test_build_qat_transform_rules():
    from paddlefleetx_tpu.utils.compression import build_qat_transform, fake_quant

    assert build_qat_transform(None) is None
    assert build_qat_transform({"Quantization": {"enable": False}}) is None
    with pytest.raises(ValueError, match="weight_bits"):
        build_qat_transform({"Quantization": {"enable": True, "weight_bits": 4}})

    t = build_qat_transform(
        {"Quantization": {"enable": True, "skip_tensors": ["head"]}}
    )
    rng = np.random.default_rng(0)
    params = {
        "embeddings": {"word": jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)},
        "mlp": {"kernel": jnp.asarray(rng.normal(size=(8, 8)), jnp.float32),
                "bias": jnp.asarray(rng.normal(size=(8,)), jnp.float32)},
        "head": {"kernel": jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)},
    }
    out = t(params)
    # embeddings frozen, skip list honored, biases (ndim<2) untouched
    np.testing.assert_array_equal(out["embeddings"]["word"], params["embeddings"]["word"])
    np.testing.assert_array_equal(out["head"]["kernel"], params["head"]["kernel"])
    np.testing.assert_array_equal(out["mlp"]["bias"], params["mlp"]["bias"])
    # matmul kernel IS quantized, to exactly fake_quant's value
    assert not np.array_equal(out["mlp"]["kernel"], params["mlp"]["kernel"])
    np.testing.assert_array_equal(out["mlp"]["kernel"], fake_quant(params["mlp"]["kernel"]))
    # straight-through: grads flow unchanged through the transform
    g = jax.grad(lambda p: t(p)["mlp"]["kernel"].sum())(params)
    np.testing.assert_allclose(np.asarray(g["mlp"]["kernel"]), 1.0)


def test_qat_engine_train_step(devices8):
    """Compress.Quantization.enable wires QAT into the train step: loss
    differs from the fp32 engine (quantized forward) and stays finite."""
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    def run(compress):
        cfg = AttrDict.from_nested(
            {
                "Global": {"global_batch_size": 8, "micro_batch_size": 1, "seed": 7},
                "Engine": {
                    "max_steps": 1,
                    "eval_freq": 0,
                    "logging_freq": 10**9,
                    "mix_precision": {"enable": False},
                    "save_load": {"save_steps": 0},
                },
                "Model": {
                    "module": "GPTModule",
                    "vocab_size": 64,
                    "hidden_size": 32,
                    "num_layers": 2,
                    "num_attention_heads": 4,
                    "max_position_embeddings": 16,
                    "hidden_dropout_prob": 0.0,
                    "attention_probs_dropout_prob": 0.0,
                    "dtype": "float32",
                },
                "Distributed": {"mp_degree": 2},
                "Optimizer": {
                    "name": "FusedAdamW",
                    "lr": {"name": "Constant", "learning_rate": 1e-4},
                },
                **({"Compress": compress} if compress else {}),
            }
        )
        cfg = process_configs(cfg, num_devices=8)
        mesh = init_dist_env(cfg, devices=jax.devices()[:8])
        module = build_module(cfg)
        rng = np.random.default_rng(0)
        batch = {
            "tokens": rng.integers(0, 64, (8, 16)).astype(np.int64),
            "labels": rng.integers(0, 64, (8, 16)).astype(np.int64),
            "loss_mask": np.ones((8, 16), np.float32),
            "position_ids": np.tile(np.arange(16), (8, 1)),
        }
        with mesh:
            eng = Engine(cfg, module, mesh)
            dev = eng._put_batch(batch)
            eng.state, m = eng.train_step(eng.state, dev)
            return float(m["loss"])

    import warnings

    ref = run(None)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        qat = run({"Quantization": {"enable": True}})
    # the train step pins its output-state shardings to the input state's
    # (engine.state_shardings): under the mp=2 mesh here, leaving them to
    # propagation used to pick a different sharding and break donation —
    # "Some donated buffers were not usable" on every TP train step
    donation = [w for w in caught if "donated" in str(w.message)]
    assert not donation, [str(w.message)[:120] for w in donation]
    assert np.isfinite(qat)
    assert qat != ref  # the quantized forward really was different
