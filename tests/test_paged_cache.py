"""Paged KV cache units: the pure host-side block allocator/manager
(`core/paged_cache.py` — alloc/free/fragmentation/exhaustion, loud on
every corruption-shaped misuse) and the block-table-indexed attention
kernel (`ops/decode_attention.paged_decode_attention`, lax + pallas
spellings vs a dense gather reference).  `make test-paged` runs these
plus the continuous-batching suite and drill."""

import numpy as np
import pytest

from paddlefleetx_tpu.core.paged_cache import (
    BlockAllocator,
    BlockPoolExhausted,
    NULL_BLOCK,
    PagedCacheManager,
    blocks_for,
    kv_block_size,
)

# ---------------------------------------------------------------------------
# allocator (no jax: pure host bookkeeping)
# ---------------------------------------------------------------------------


def test_alloc_free_roundtrip():
    a = BlockAllocator(9)  # blocks 1..8 usable
    got = a.alloc(3)
    assert len(got) == 3 and NULL_BLOCK not in got
    assert a.used_count() == 3 and a.free_count() == 5
    a.free(got)
    assert a.used_count() == 0 and a.free_count() == 8
    # freed blocks are reusable
    again = a.alloc(8)
    assert sorted(again) == list(range(1, 9))


def test_null_block_never_allocated():
    a = BlockAllocator(4)
    assert NULL_BLOCK not in a.alloc(3)
    with pytest.raises(ValueError, match="null block"):
        a.free([NULL_BLOCK])


def test_exhaustion_is_loud_and_names_the_shortfall():
    a = BlockAllocator(5)
    a.alloc(3)
    with pytest.raises(BlockPoolExhausted, match="need 2, have 1"):
        a.alloc(2)
    # the failed alloc took nothing: the remaining block is still usable
    assert a.free_count() == 1
    assert len(a.alloc(1)) == 1


def test_double_free_and_bad_ids_raise():
    a = BlockAllocator(6)
    got = a.alloc(2)
    a.free(got)
    with pytest.raises(ValueError, match="double free"):
        a.free([got[0]])
    with pytest.raises(ValueError, match="out of range"):
        a.free([99])
    with pytest.raises(ValueError, match="out of range"):
        a.free([-1])
    # a duplicate id WITHIN one call is the same silent-aliasing hazard:
    # accepted, it would enter the free list twice and later hand one
    # block to two sequences
    got2 = a.alloc(2)
    with pytest.raises(ValueError, match="double free"):
        a.free([got2[0], got2[0]])
    # a loud free must be ATOMIC: nothing was freed by the failing calls
    assert a.used_count() == 2
    a.free(got2)
    assert a.free_count() == 5


def test_fragmentation_metric_and_defrag():
    a = BlockAllocator(9)
    rows = [a.alloc(2) for _ in range(4)]  # all 8 blocks out
    assert a.fragmentation() == 0.0  # empty free list counts as unfragmented
    a.free(rows[0])  # blocks 1,2
    a.free(rows[2])  # blocks 5,6 — two separate runs of 2
    assert a.fragmentation() == pytest.approx(0.5)
    a.free(rows[1])  # 3,4: free space becomes one run 1..6
    a.defrag()
    assert a.fragmentation() == 0.0
    # lowest-first handout keeps live allocations packed
    assert a.alloc(3) == [1, 2, 3]


def test_blocks_for_and_block_size_knob(monkeypatch):
    assert blocks_for(0, 16) == 0
    assert blocks_for(1, 16) == 1
    assert blocks_for(16, 16) == 1
    assert blocks_for(17, 16) == 2
    assert kv_block_size(32) == 32
    monkeypatch.setenv("PFX_KV_BLOCK", "24")
    assert kv_block_size() == 24
    monkeypatch.setenv("PFX_KV_BLOCK", "12")
    with pytest.raises(ValueError, match="multiple of 8"):
        kv_block_size()
    monkeypatch.setenv("PFX_KV_BLOCK", "lots")
    with pytest.raises(ValueError, match="not an integer"):
        kv_block_size()


def test_manager_admit_release_tables():
    m = PagedCacheManager(10, block=16)
    t1 = m.admit(1, 40)  # 3 blocks
    assert len(t1) == 3
    assert m.table(1, width=5) == t1 + [NULL_BLOCK, NULL_BLOCK]
    with pytest.raises(ValueError, match="already admitted"):
        m.admit(1, 16)
    with pytest.raises(ValueError, match="width"):
        m.table(1, width=2)
    assert m.stats()["kv_blocks_used"] == 3
    assert m.can_admit(16 * 6) and not m.can_admit(16 * 7)
    m.release(1)
    with pytest.raises(ValueError, match="no allocation"):
        m.release(1)
    assert m.stats()["kv_blocks_used"] == 0 and m.live_sequences() == 0


def test_manager_exhaustion_keeps_bookkeeping_consistent():
    m = PagedCacheManager(4, block=16)
    m.admit(1, 32)  # 2 of 3 usable
    with pytest.raises(BlockPoolExhausted):
        m.admit(2, 32)
    # the failed admission left no phantom sequence behind
    assert m.live_sequences() == 1
    m.release(1)
    assert len(m.admit(2, 48)) == 3


# ---------------------------------------------------------------------------
# paged attention kernel (lax CPU-mandatory; pallas interpret-mode, slow
# per the repo's interpret-compile convention)
# ---------------------------------------------------------------------------

LAX = pytest.param("lax", id="lax")
PALLAS = pytest.param("pallas", id="pallas", marks=pytest.mark.slow)


def _paged_case(rng, b, n, d, bs, M, nb):
    import jax.numpy as jnp

    k_pool = jnp.asarray(rng.normal(size=(nb, n, bs, d)), jnp.float32)
    v_pool = jnp.asarray(rng.normal(size=(nb, n, bs, d)), jnp.float32)
    q = jnp.asarray(rng.normal(size=(b, 1, n, d)), jnp.float32)
    # disjoint per-row tables, shuffled so pool order != logical order
    ids = rng.permutation(np.arange(1, nb))[: b * M].reshape(b, M)
    tables = jnp.asarray(ids, jnp.int32)
    return q, k_pool, v_pool, tables


def _dense_ref(q, k_pool, v_pool, tables, positions):
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    outs = []
    for r in range(q.shape[0]):
        ks = jnp.concatenate([k_pool[t] for t in np.asarray(tables[r])], axis=1)
        vs = jnp.concatenate([v_pool[t] for t in np.asarray(tables[r])], axis=1)
        lim = int(positions[r]) + 1
        s = jnp.einsum("nd,nkd->nk", q[r, 0], ks[:, :lim]) / np.sqrt(d)
        outs.append(jnp.einsum(
            "nk,nkd->nd", jax.nn.softmax(s, axis=-1), vs[:, :lim]
        ))
    return jnp.stack(outs)[:, None]


@pytest.mark.parametrize("impl", [LAX, PALLAS])
def test_paged_attention_matches_dense_gather(impl):
    import jax.numpy as jnp

    from paddlefleetx_tpu.ops.decode_attention import paged_decode_attention

    rng = np.random.default_rng(0)
    q, k_pool, v_pool, tables = _paged_case(rng, b=3, n=2, d=8, bs=8, M=4, nb=16)
    positions = jnp.asarray([17, 9, 30], jnp.int32)  # per-row lengths differ
    got = paged_decode_attention(q, k_pool, v_pool, tables, positions, impl=impl)
    want = _dense_ref(q, k_pool, v_pool, tables, positions)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("impl", [LAX, PALLAS])
def test_paged_attention_never_reads_past_a_rows_limit(impl):
    """NaN-poison proof (the PR 1 convention): every pool block WHOLLY
    beyond a row's visit bound ``ceil((pos+1)/bs)`` is poisoned with NaN
    — table padding a fori bound or a DMA clamp must never gather.  The
    kernel must stay finite AND equal the unpoisoned result, or it read
    blocks it has no business touching.  (Within a visited block, masked
    tail slots follow the stale-tail contract: they hold stale-but-
    finite values in real traffic, same as the contiguous kernel.)"""
    import jax.numpy as jnp

    from paddlefleetx_tpu.ops.decode_attention import paged_decode_attention

    rng = np.random.default_rng(1)
    bs, M = 8, 4
    q, k_pool, v_pool, tables = _paged_case(rng, b=2, n=2, d=8, bs=bs, M=M, nb=12)
    positions = jnp.asarray([10, 3], jnp.int32)
    clean = paged_decode_attention(q, k_pool, v_pool, tables, positions, impl=impl)

    kp, vp = np.array(k_pool), np.array(v_pool)
    for r, pos in enumerate([10, 3]):
        first_unvisited = -(-(pos + 1) // bs)
        for j in range(first_unvisited, M):
            blk = int(tables[r, j])
            kp[blk] = np.nan
            vp[blk] = np.nan
    poisoned = paged_decode_attention(
        q, jnp.asarray(kp), jnp.asarray(vp), tables, positions, impl=impl
    )
    assert np.all(np.isfinite(np.asarray(poisoned)))
    np.testing.assert_allclose(
        np.asarray(poisoned), np.asarray(clean), atol=1e-6
    )


@pytest.mark.parametrize("impl", [LAX, PALLAS])
def test_paged_attention_multi_token_chunk_is_causal(impl):
    """t > 1 (the speculative verify chunk): query qi of row r attends
    its logical slots [0, positions[r] + qi + 1) — each chunk query must
    equal a t=1 call at its own position (same cache, shifted limit)."""
    import jax.numpy as jnp

    from paddlefleetx_tpu.ops.decode_attention import paged_decode_attention

    rng = np.random.default_rng(2)
    t = 3
    q, k_pool, v_pool, tables = _paged_case(rng, b=2, n=2, d=8, bs=8, M=4, nb=12)
    qt = jnp.asarray(rng.normal(size=(2, t, 2, 8)).astype(np.float32))
    positions = jnp.asarray([9, 3], jnp.int32)
    got = paged_decode_attention(qt, k_pool, v_pool, tables, positions, impl=impl)
    for qi in range(t):
        one = paged_decode_attention(
            qt[:, qi : qi + 1], k_pool, v_pool, tables, positions + qi,
            impl=impl,
        )
        np.testing.assert_allclose(
            np.asarray(got[:, qi : qi + 1]), np.asarray(one), atol=2e-5
        )


def test_paged_attention_arg_validation():
    import jax.numpy as jnp

    from paddlefleetx_tpu.ops.decode_attention import paged_decode_attention

    rng = np.random.default_rng(2)
    q, k_pool, v_pool, tables = _paged_case(rng, b=1, n=1, d=8, bs=8, M=2, nb=4)
    with pytest.raises(ValueError, match="valid: auto"):
        paged_decode_attention(
            q, k_pool, v_pool, tables, jnp.asarray([3], jnp.int32), impl="cuda"
        )
