"""Evoformer tests: block shapes, mask invariance, triangle-mult direction,
extra-MSA global attention, DAP (sep-axis) parity, overfit."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.models.gpt.model import ShardingCtx
from paddlefleetx_tpu.models.protein import evoformer as evo
from paddlefleetx_tpu.models.protein.evoformer import EvoformerConfig
from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
from paddlefleetx_tpu.parallel.sharding import make_rules, tree_logical_to_sharding

# Pallas interpret-mode / big-compile file: excluded from the fast
# subset (pytest -m 'not slow'); run the full suite for release checks
pytestmark = pytest.mark.slow

TINY = EvoformerConfig(
    msa_channel=16,
    pair_channel=8,
    num_layers=2,
    msa_heads=4,
    pair_heads=2,
    transition_factor=2,
    outer_channel=4,
    dropout_rate=0.0,
    dtype="float32",
)


def _inputs(b=1, S=4, R=8, cfg=TINY, seed=0):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.normal(size=(b, S, R, cfg.msa_channel)), jnp.float32),
        jnp.asarray(rng.normal(size=(b, R, R, cfg.pair_channel)), jnp.float32),
        jnp.ones((b, S, R), jnp.float32),
        jnp.ones((b, R, R), jnp.float32),
    )


def test_forward_shapes():
    params = evo.init(TINY, jax.random.key(0))
    msa, pair, mm, pm = _inputs()
    m, z = evo.forward(params, msa, pair, mm, pm, TINY)
    assert m.shape == msa.shape and z.shape == pair.shape
    assert np.all(np.isfinite(np.asarray(m))) and np.all(np.isfinite(np.asarray(z)))


def test_zero_init_residual_identity():
    """Zero-init output projections: at init each block is near-identity in
    its attention/mult branches (transitions too) => outputs stay bounded."""
    params = evo.init(TINY, jax.random.key(1))
    msa, pair, mm, pm = _inputs()
    m, z = evo.forward(params, msa, pair, mm, pm, TINY)
    # every update branch is zero-init -> exact identity
    np.testing.assert_allclose(np.asarray(m), np.asarray(msa), atol=1e-5)
    np.testing.assert_allclose(np.asarray(z), np.asarray(pair), atol=1e-5)


def test_mask_invariance():
    """Masked MSA rows must not influence unmasked outputs."""
    cfg = TINY
    params = jax.tree.map(
        lambda x: x + 0.02 * np.random.default_rng(1).normal(size=x.shape).astype(np.float32),
        evo.init(cfg, jax.random.key(2)),
    )
    msa, pair, mm, pm = _inputs(S=4, R=6, cfg=cfg)
    mm = mm.at[:, -1, :].set(0.0)  # mask out last MSA row
    a_m, a_z = evo.forward(params, msa, pair, mm, pm, cfg)
    msa2 = msa.at[:, -1].set(msa[:, -1] * 3.0 + 1.0)
    b_m, b_z = evo.forward(params, msa2, pair, mm, pm, cfg)
    np.testing.assert_allclose(
        np.asarray(a_m[:, :-1]), np.asarray(b_m[:, :-1]), atol=2e-4
    )
    np.testing.assert_allclose(np.asarray(a_z), np.asarray(b_z), atol=2e-4)


def test_triangle_mult_directions_differ():
    cfg = TINY
    key = jax.random.key(3)
    specs = evo._tri_mult_specs(cfg.pair_channel)
    from paddlefleetx_tpu.models.common import init_params

    p = init_params(key, specs)
    # randomize the zero-init projections so directions are visible
    p = jax.tree.map(
        lambda x: x + 0.1 * np.random.default_rng(0).normal(size=x.shape).astype(np.float32), p
    )
    _, pair, _, pm = _inputs()
    out_o = evo._triangle_multiplication(p, pair, pm, outgoing=True)
    out_i = evo._triangle_multiplication(p, pair, pm, outgoing=False)
    assert float(jnp.max(jnp.abs(out_o - out_i))) > 1e-3


def test_extra_msa_global_attention():
    cfg = EvoformerConfig(**{**TINY.__dict__, "is_extra_msa": True})
    params = evo.init(cfg, jax.random.key(4))
    msa, pair, mm, pm = _inputs(cfg=cfg)
    m, z = evo.forward(params, msa, pair, mm, pm, cfg)
    assert np.all(np.isfinite(np.asarray(m)))


def test_dap_parity(devices8):
    """sep=4 (DAP) sharded forward == single-device forward.  The sharding
    constraints flipping rows<->residues across blocks are the reference's
    dap all_to_alls (dap.py:244-398); numerics must not change."""
    params = jax.tree.map(
        lambda x: x + 0.02 * np.random.default_rng(2).normal(size=x.shape).astype(np.float32),
        evo.init(TINY, jax.random.key(5)),
    )
    msa, pair, mm, pm = _inputs(b=2, S=4, R=8)
    ref_m, ref_z = evo.forward(params, msa, pair, mm, pm, TINY)

    # sep=2: the heads->(model,sep) rule also shards param head axes, and
    # the tiny pair track has only 2 heads
    mesh = build_mesh(MeshConfig(dp_degree=4, sep_degree=2))
    rules = make_rules()
    ctx = ShardingCtx(mesh=mesh, rules=rules)
    shardings = tree_logical_to_sharding(evo.evoformer_logical_axes(TINY), mesh, rules)
    p_sharded = jax.tree.map(lambda x, s: jax.device_put(x, s), params, shardings)

    @jax.jit
    def fwd(p, m, z):
        return evo.forward(p, m, z, mm, pm, TINY, ctx=ctx)

    out_m, out_z = fwd(p_sharded, msa, pair)
    np.testing.assert_allclose(np.asarray(ref_m), np.asarray(out_m), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(ref_z), np.asarray(out_z), rtol=2e-4, atol=2e-4)


def test_overfit_toy_objective():
    """Train the stack to push pair activations toward a random target."""
    import optax

    params = evo.init(TINY, jax.random.key(6))
    msa, pair, mm, pm = _inputs()
    target = jnp.asarray(
        np.random.default_rng(3).normal(size=pair.shape), jnp.float32
    )

    def loss_fn(p):
        _, z = evo.forward(p, msa, pair, mm, pm, TINY, train=True)
        return jnp.mean((z - target) ** 2)

    tx = optax.adam(3e-3)
    opt = tx.init(params)

    @jax.jit
    def step(p, o):
        loss, g = jax.value_and_grad(loss_fn)(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    first = None
    for _ in range(30):
        params, opt, loss = step(params, opt)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.7
