"""ResNet / MoCo / vision loss+metric tests (reference surface:
ppfleetx/models/vision_model/{resnet,moco,loss,metrics})."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.models.common import init_params
from paddlefleetx_tpu.models.vision import loss as vloss, metrics, moco, resnet

TINY_R18 = resnet.ResNetConfig(depth=18, num_classes=8)
TINY_MOCO = moco.MoCoConfig(depth=18, dim=16, K=64, T=0.07, v2=True)


def _resnet_state(cfg, key=0):
    k = jax.random.key(key)
    return (
        init_params(k, resnet.param_specs(cfg)),
        init_params(k, resnet.state_specs(cfg)),
    )


@pytest.mark.slow  # ~16s compile for a shape/BN-motion check; tier-1 keeps
# the resnet50 feature path + the MoCo end-to-end train (which compiles the
# resnet base); runs in make test-all (PR 8 tier-1 budget convention)
def test_resnet18_forward_shape():
    params, state = _resnet_state(TINY_R18)
    x = jnp.ones((2, 32, 32, 3))
    logits, new_state = resnet.forward(params, state, x, TINY_R18, train=True)
    assert logits.shape == (2, 8)
    # BN running stats moved during training
    before = state["stem"]["bn"]["mean"]
    after = new_state["stem"]["bn"]["mean"]
    assert not np.allclose(np.asarray(before), np.asarray(after))


def test_resnet50_bottleneck_features():
    cfg = resnet.ResNetConfig(depth=50, num_classes=0)
    params, state = _resnet_state(cfg)
    feats, _ = resnet.features(params, state, jnp.ones((1, 32, 32, 3)), cfg)
    assert feats.shape == (1, 2048)


def test_resnet_eval_uses_running_stats():
    params, state = _resnet_state(TINY_R18)
    x = jnp.ones((2, 32, 32, 3))
    _, s1 = resnet.forward(params, state, x, TINY_R18, train=False)
    assert jax.tree.all(
        jax.tree.map(lambda a, b: bool(jnp.all(a == b)), state, s1)
    )


def test_ce_loss_matches_manual():
    logits = jnp.asarray([[2.0, 0.0, -1.0]])
    labels = jnp.asarray([0])
    expect = -jax.nn.log_softmax(logits)[0, 0]
    np.testing.assert_allclose(vloss.ce_loss(logits, labels), expect, rtol=1e-6)
    # smoothing lowers confidence target
    smooth = vloss.ce_loss(logits, labels, epsilon=0.1)
    assert smooth > vloss.ce_loss(logits, labels)


def test_vit_ce_loss_sigmoid():
    logits = jnp.zeros((4, 8))
    labels = jnp.arange(4)
    # all-zero logits: BCE = 8 * log(2)
    np.testing.assert_allclose(
        vloss.vit_ce_loss(logits, labels), 8 * np.log(2.0), rtol=1e-5
    )


def test_topk_acc():
    logits = jnp.asarray([[0.1, 0.9, 0.0, 0.0], [0.9, 0.1, 0.0, 0.0]])
    labels = jnp.asarray([1, 2])
    out = metrics.topk_acc(logits, labels, topk=(1, 2))
    assert out["top1"] == 0.5
    # label 2 ranks 3rd in row 1 -> not in top2
    assert out["top2"] == 0.5


@pytest.fixture(scope="module")
def moco_bits():
    key = jax.random.key(0)
    params = moco.init(TINY_MOCO, key)
    extra = moco.init_extra(TINY_MOCO, key, params)
    return params, extra


def test_moco_momentum_starts_as_copy(moco_bits):
    params, extra = moco_bits
    assert jax.tree.all(
        jax.tree.map(lambda a, b: bool(jnp.all(a == b)), params, extra["momentum"])
    )


def test_moco_loss_and_queue_update(moco_bits):
    # NON-degenerate inputs: 8 identical constant images collapse to the
    # exact-zero feature (global-batch BN at 1x1 spatial sees zero
    # variance and emits its zero bias), whose keys CANNOT be unit-norm —
    # the invariant under test needs real images; the degenerate case has
    # its own finiteness regression below
    params, extra = moco_bits
    rng = np.random.default_rng(5)
    batch = {
        "img_q": jnp.asarray(rng.normal(0, 1, (8, 32, 32, 3)).astype(np.float32)),
        "img_k": jnp.asarray(rng.normal(0, 1, (8, 32, 32, 3)).astype(np.float32)),
    }
    loss, new_extra = jax.jit(
        lambda p, b, e: moco.loss_fn(
            p, b, TINY_MOCO, e, dropout_key=jax.random.key(1), train=True
        )
    )(params, batch, extra)
    assert np.isfinite(float(loss))
    # InfoNCE over 1+K classes starts near log(1+K)
    assert float(loss) < np.log(1 + TINY_MOCO.K) + 2.0
    assert int(new_extra["ptr"]) == 8
    # enqueued keys are L2-normalized columns at slots 0..7
    qcols = np.asarray(new_extra["queue"][:, :8])
    np.testing.assert_allclose(np.linalg.norm(qcols, axis=0), 1.0, rtol=1e-4)
    # momentum params moved toward base by (1-m)
    leaf = jax.tree.leaves(extra["momentum"])[0]
    new_leaf = jax.tree.leaves(new_extra["momentum"])[0]
    assert not np.allclose(np.asarray(leaf), np.asarray(new_leaf)) or np.allclose(
        np.asarray(jax.tree.leaves(params)[0]), np.asarray(leaf)
    )


def test_moco_ptr_wraps(moco_bits):
    params, extra = moco_bits
    batch = {
        "img_q": jnp.ones((32, 32, 32, 3)),
        "img_k": jnp.ones((32, 32, 32, 3)),
    }
    e = extra
    for _ in range(2):
        _, e = moco.loss_fn(
            params, batch, TINY_MOCO, e, dropout_key=jax.random.key(2), train=True
        )
    assert int(e["ptr"]) == 0  # 2*32 % 64


@pytest.mark.slow  # ~26s grad compile; MoCo tier-1 coverage stays via the
# end-to-end engine train, ptr-wrap, and degenerate-batch finiteness tests;
# runs in make test-all (PR 8 tier-1 budget convention)
def test_moco_grads_only_touch_base(moco_bits):
    params, extra = moco_bits
    batch = {
        "img_q": jnp.ones((8, 32, 32, 3)) * 0.1,
        "img_k": jnp.ones((8, 32, 32, 3)) * 0.3,
    }
    grads, extra_grads = jax.grad(
        lambda p, e: moco.loss_fn(
            p, batch, TINY_MOCO, e, dropout_key=jax.random.key(3), train=True
        )[0],
        argnums=(0, 1),
        allow_int=True,  # extra['ptr'] is an int32 buffer
    )(params, extra)
    gnorm = sum(float(jnp.sum(g**2)) for g in jax.tree.leaves(grads))
    assert gnorm > 0.0
    # momentum encoder / queue sit behind stop_gradient: zero cotangents
    for path in ("momentum", "queue"):
        for g in jax.tree.leaves(extra_grads[path]):
            if jnp.issubdtype(g.dtype, jnp.floating):
                assert float(jnp.max(jnp.abs(g))) == 0.0


def test_l2_normalize_zero_vector_grad_is_finite():
    """The ROOT CAUSE of the seed MoCo NaN pair, unit-sized: the old
    ``q / (||q|| + eps)`` has a 0/0 = NaN gradient exactly at the zero
    feature a degenerate batch produces; the safe-rsqrt spelling must
    give finite value AND gradient at zero (milliseconds — the
    replacement tier-1 coverage for the slow-marked full-model
    degenerate-batch test below)."""
    from paddlefleetx_tpu.models.vision.moco import _l2_normalize

    z = jnp.zeros((4, 16))
    out = _l2_normalize(z)
    assert np.all(np.isfinite(np.asarray(out)))
    g = jax.grad(lambda x: jnp.sum(_l2_normalize(x)))(z)
    assert np.all(np.isfinite(np.asarray(g))), "NaN gradient at zero"
    # non-degenerate vectors still unit-normalize
    v = jnp.ones((2, 8))
    n = np.linalg.norm(np.asarray(_l2_normalize(v)), axis=-1)
    np.testing.assert_allclose(n, 1.0, rtol=1e-5)


@pytest.mark.slow  # ~32s full-resnet grad compile; the NaN regression's
# root cause stays tier-1 via test_l2_normalize_zero_vector_grad_is_finite
# above (the exact 0/0 gradient, unit-sized) and the moco e2e engine test
# keeps the integration path; still in make test-mid / test-all (PR 8
# tier-1 budget convention)
def test_moco_degenerate_batch_stays_finite(moco_bits):
    """Regression for the seed NaN pair: a batch of identical constant
    images drives every stage-4 BatchNorm to zero variance (1x1 spatial,
    identical rows), so the encoder emits the EXACT zero feature.  The
    old ``q / (||q|| + eps)`` normalization has a 0/0 = NaN gradient at
    zero, which poisoned the whole batch's gradients; the safe-rsqrt
    normalization must keep both the loss and the full gradient finite
    (and nonzero — the classifier bias path still carries signal)."""
    params, extra = moco_bits
    batch = {
        "img_q": jnp.ones((8, 32, 32, 3)) * 0.1,
        "img_k": jnp.ones((8, 32, 32, 3)) * 0.3,
    }
    loss, _ = moco.loss_fn(
        params, batch, TINY_MOCO, extra, dropout_key=jax.random.key(1), train=True
    )
    assert np.isfinite(float(loss))
    grads = jax.grad(
        lambda p: moco.loss_fn(
            p, batch, TINY_MOCO, extra, dropout_key=jax.random.key(3), train=True
        )[0]
    )(params)
    flat = np.concatenate(
        [np.asarray(g).ravel() for g in jax.tree.leaves(grads)]
    )
    assert np.all(np.isfinite(flat)), "NaN/inf gradient on degenerate batch"
    assert float(np.sum(flat**2)) > 0.0


@pytest.mark.slow  # ~14s engine boot; tier-1 budget funding for the
# shard_map-port tests.  Replacement coverage: every MoCo contract stays
# tier-1 via the in-process units above (momentum-copy, loss+queue
# update, ptr wrap, NaN-safe l2 normalize) and the extra-state-through-
# jitted-train-step plumbing is exercised tier-1 by the other engine
# e2e suites; still in make test-mid / test-all.
def test_moco_engine_end_to_end(tmp_path):
    """MOCOModule through the Engine: extra state threads through the jitted
    train step, loss decreases direction-agnostic (finite)."""
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": 8, "micro_batch_size": 1, "seed": 7},
            "Engine": {
                "max_steps": 2,
                "logging_freq": 100,
                "eval_freq": 0,
                "mix_precision": {"enable": False},
                "save_load": {"save_steps": 0, "output_dir": str(tmp_path)},
            },
            "Model": {
                "module": "MOCOModule",
                "depth": 18,
                "dim": 16,
                "K": 32,
                "v2": False,
            },
            "Distributed": {},
            "Optimizer": {
                "name": "FusedAdamW",
                "lr": {"name": "Constant", "learning_rate": 1e-3},
            },
        }
    )
    cfg = process_configs(cfg, num_devices=jax.device_count())
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    with mesh:
        engine = Engine(cfg, module, mesh)
        batch = {
            "img_q": np.random.default_rng(0).normal(0, 1, (8, 32, 32, 3)).astype(np.float32),
            "img_k": np.random.default_rng(1).normal(0, 1, (8, 32, 32, 3)).astype(np.float32),
        }
        dev = engine._put_batch(batch)
        s0 = engine.state
        assert s0.extra is not None
        engine.state, m = engine.train_step(engine.state, dev)
        assert np.isfinite(float(m["loss"]))
        assert int(engine.state.extra["ptr"]) == 8


def test_contrastive_dataset_two_views():
    from paddlefleetx_tpu.data.vision_dataset import ContrastiveLearningDataset

    ds = ContrastiveLearningDataset(num_samples=4, image_size=16, num_classes=2)
    item = ds[0]
    assert item["img_q"].shape == (16, 16, 3)
    assert item["img_k"].shape == (16, 16, 3)
    # independent augmentation draws differ
    assert not np.allclose(item["img_q"], item["img_k"])


def test_cifar10_dataset(tmp_path):
    """CIFAR10 loads the standard pickle-batch layout (reference
    vision_dataset.py:302): train = data_batch_1..5, test = test_batch."""
    import pickle

    from paddlefleetx_tpu.data.vision_dataset import CIFAR10

    rng = np.random.default_rng(0)
    n = 4
    for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
        batch = {
            b"data": rng.integers(0, 256, (n, 3 * 32 * 32), dtype=np.uint8),
            b"labels": list(rng.integers(0, 10, n)),
        }
        with open(tmp_path / name, "wb") as f:
            pickle.dump(batch, f)

    train = CIFAR10(str(tmp_path), mode="train")
    test = CIFAR10(
        str(tmp_path),
        mode="test",
        transform_ops=[{"NormalizeImage": {}}],
    )
    assert len(train) == 5 * n and len(test) == n
    item = train[0]
    assert item["images"].shape == (32, 32, 3)
    assert item["labels"].dtype == np.int64
    # normalized test images are float and roughly centered
    assert test[0]["images"].dtype == np.float32
    assert train.class_num <= 10

    import pytest

    with pytest.raises(FileNotFoundError):
        CIFAR10(str(tmp_path / "missing"), mode="test")
    with pytest.raises(ValueError):
        CIFAR10(str(tmp_path), mode="val")
