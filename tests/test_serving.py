"""GenerationServer + tools/serve.py HTTP endpoint (reference deploy-path
parity: InferenceEngine predictor, inference_engine.py:104)."""

import json
import os
import sys
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY_OVERRIDES = {
    "Global": {"global_batch_size": 8, "seed": 3},
    "Engine": {"mix_precision": {"enable": False}, "save_load": {"save_steps": 0}},
    "Model": {
        "module": "GPTModule",
        "vocab_size": 96,
        "hidden_size": 32,
        "num_layers": 2,
        "num_attention_heads": 4,
        "max_position_embeddings": 128,
        "dtype": "float32",
    },
    "Distributed": {"mp_degree": 2},
    "Optimizer": {"name": "FusedAdamW", "lr": {"name": "Constant", "learning_rate": 1e-3}},
    "Generation": {"max_dec_len": 8, "decode_strategy": "greedy_search", "pad_to_multiple": 16,
                   "eos_token_id": 95, "pad_token_id": 0},
}


@pytest.fixture(scope="module")
def server():
    import jax

    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict.from_nested(TINY_OVERRIDES)
    cfg = process_configs(cfg, num_devices=jax.device_count())
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    return GenerationServer(cfg, mesh, module)


def test_generate_ids_bucket_reuse(server):
    outs = server.generate_ids([[1, 2, 3]])
    assert len(outs) == 1 and 0 < len(outs[0]) <= 8
    # different prompt length, same bucket -> no growth in stats weirdness,
    # deterministic greedy output for identical prompt
    a = server.generate_ids([[4, 5, 6, 7, 8]])
    b = server.generate_ids([[4, 5, 6, 7, 8]])
    assert a == b
    assert server.stats["requests"] == 3


def test_generate_ids_batch_and_maxlen(server):
    outs = server.generate_ids([[1, 2], [3, 4, 5, 6]], max_dec_len=4)
    assert len(outs) == 2
    assert all(len(o) <= 4 for o in outs)


@pytest.mark.slow
def test_http_endpoint(tmp_path):
    """tools/serve.py end-to-end over HTTP with prompt_ids."""
    import socket
    import subprocess
    import time

    import yaml

    cfg_path = tmp_path / "tiny.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY_OVERRIDES))
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    env["PFX_PLATFORM"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-c", str(cfg_path), "--port", str(port), "--no-warmup"],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        deadline = time.time() + 300
        last = None
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=5
                ) as r:
                    last = json.load(r)
                    break
            except Exception as e:
                last = e
                if proc.poll() is not None:
                    raise AssertionError(f"server died: {proc.stdout.read()[-2000:]}")
                time.sleep(2)
        assert isinstance(last, dict) and last.get("ok"), last
        # operability fields: queue depth + latency + retrace counter
        assert {"in_flight", "last_latency_s", "traces"} <= set(last), last

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt_ids": [1, 2, 3], "max_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            out = json.load(r)
        assert "completion_ids" in out and len(out["completion_ids"]) <= 4, out

        # batched ids request
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps(
                {"prompts_ids": [[1, 2], [3, 4, 5]], "max_tokens": 4}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            out = json.load(r)
        assert len(out["completions_ids"]) == 2, out

        # bad request -> 400, server keeps serving
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt_ids": []}).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req, timeout=60)
            raise AssertionError("expected HTTP 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_max_tokens_clamped_and_bucketed(server):
    """Client max_dec_len is clamped to the model context and bucketed so
    the jit-cache cardinality stays bounded."""
    outs = server.generate_ids([[1, 2]], max_dec_len=10**9)
    assert len(outs[0]) <= server.module.config.max_position_embeddings
    server.generate_ids([[1, 2]], max_dec_len=3)
    before = len(server._compiled)
    server.generate_ids([[1, 2]], max_dec_len=7)   # same 32-bucket: no new compile
    assert len(server._compiled) == before
    outs = server.generate_ids([[1, 2]], max_dec_len=3)
    assert len(outs[0]) <= 3


def test_empty_prompt_rejected(server):
    with pytest.raises(ValueError, match="non-empty"):
        server.generate_ids([])
    with pytest.raises(ValueError, match="non-empty"):
        server.generate_ids([[]])


def test_mixed_traffic_never_retraces_a_seen_bucket(server):
    """The per-(bucket_b, bucket_len, GenerationConfig) jit memo: repeated
    mixed-size traffic must stop tracing once each bucket has been seen —
    stats["traces"] counts trace-time entries of the decode fn."""
    reqs = [
        [[1, 2, 3]],                      # batch bucket 1, prompt bucket 16
        [[4, 5], [6, 7, 8], [9, 1]],      # batch bucket 4 (padded)
        [list(range(1, 20))],             # prompt bucket 32
    ]
    for r in reqs:  # populate every bucket
        server.generate_ids(r)
    seen = server.stats["traces"]
    assert seen >= len(reqs) - 1  # at least one trace per distinct bucket
    for _ in range(3):  # repeat traffic: NO new traces allowed
        for r in reqs:
            server.generate_ids(r)
    assert server.stats["traces"] == seen


def test_decode_cache_is_donated(server):
    """The jitted decode consumes the per-request KV cache buffer: the
    compiled fn reports the cache args as donated (in-place update, no
    per-step copy of the [layers,b,heads,max_len,dim] pair)."""
    server.generate_ids([[1, 2, 3]])
    gen_key = next(iter(server._compiled))
    fn = server._compiled[gen_key]
    import jax as _jax
    import jax.numpy as _jnp

    from paddlefleetx_tpu.models.gpt.generation import init_cache

    cfg = server.module.config
    prompt = _jnp.zeros((gen_key[1], gen_key[2]), _jnp.int32)
    lens = _jnp.ones((gen_key[1],), _jnp.int32)
    cache = init_cache(cfg, gen_key[1], gen_key[2] + gen_key[0].max_dec_len)
    lowered = fn.lower(
        server.params, prompt, lens, _jax.random.key(0), cache
    )
    donated = lowered.args_info  # pytree of ArgInfo with .donated
    flags = [a.donated for a in _jax.tree.leaves(donated)]
    assert sum(flags) == 2, flags  # exactly the cache k/v pair


def test_stats_expose_last_latency_and_traces(server):
    """/healthz operability fields: last-request latency and the retrace
    counter ride server.stats (tools/serve.py spreads them into the
    health payload)."""
    server.generate_ids([[1, 2, 3]])
    assert server.stats["last_latency_s"] > 0
    assert server.stats["traces"] >= 1
    assert {"requests", "tokens_out", "time_s"} <= set(server.stats)


def test_clamp_max_tokens():
    """Per-request max_tokens clamp (tools/serve.py): cap wins over both a
    huge client value and an over-cap configured default; floor at 1."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from serve import clamp_max_tokens

    assert clamp_max_tokens(None, 64, 0) == 64       # no cap: default
    assert clamp_max_tokens(10**9, 64, 128) == 128   # cap beats client
    assert clamp_max_tokens(None, 512, 128) == 128   # cap beats default
    assert clamp_max_tokens(16, 64, 128) == 16       # sane value untouched
    assert clamp_max_tokens(0, 64, 128) == 1         # floored
    with pytest.raises((ValueError, TypeError)):
        clamp_max_tokens("lots", 64, 128)


def test_coalescing_parity_with_sequential(server):
    """The acceptance drill, in-process: N single-prompt greedy requests
    coalesced into one batched decode are token-for-token identical to
    serving them sequentially, and repeated coalesced traffic adds ZERO
    retraces (the batch rides the existing power-of-two bucketing)."""
    from paddlefleetx_tpu.core.request_queue import RequestQueue

    prompts = [[7, 8, 9], [1, 2], [3, 4, 5, 6], [2, 9]]
    seq = [server.generate_ids([p], max_dec_len=6)[0] for p in prompts]

    def runner(ps, mx):
        return server.generate_ids(ps, max_dec_len=mx)

    q = RequestQueue(runner, max_depth=8, max_coalesce=4)
    futs = [q.submit([p], 6, coalesce_key=("parity",)) for p in prompts]
    q.start()  # submitted first: one scan coalesces all four
    got = [f.result(timeout=300)[0] for f in futs]
    assert got == seq
    assert q.stats["coalesced_batches"] == 1
    assert q.stats["coalesced_requests"] == len(prompts)
    q.shutdown(timeout=10)

    # repeat coalesced traffic: no new traces — the coalesced batch hits
    # an already-compiled (bucket_b, bucket_len) artifact
    before = server.stats["traces"]
    q2 = RequestQueue(runner, max_depth=8, max_coalesce=4)
    futs = [q2.submit([p], 6, coalesce_key=("parity",)) for p in prompts]
    q2.start()
    got2 = [f.result(timeout=300)[0] for f in futs]
    assert got2 == seq
    assert server.stats["traces"] == before
    q2.shutdown(timeout=10)


def test_warmup_buckets_and_stats(server):
    """warmup accepts a list of prompt-length buckets, reports per-bucket
    compile seconds in stats, and validates loudly up front."""
    per = server.warmup([4, 20])
    assert set(per) == {"4", "20"}
    assert server.stats["warmup_s"] == per
    assert all(v >= 0 for v in per.values())
    assert "4" in server.warmup(4)  # old warmup(prompt_len) shape
    with pytest.raises(ValueError, match="decode room"):
        server.warmup([10**6])
    with pytest.raises(ValueError, match="batch size"):
        server.warmup([4], batch_sizes=[0])
    with pytest.raises(ValueError, match=">= 1"):
        server.warmup([])


def test_warmup_fails_loudly_not_half_warmed(server, monkeypatch):
    """A bucket that cannot compile raises naming what did and did not
    warm, instead of leaving a silently half-warmed server."""
    from paddlefleetx_tpu.utils import resilience

    resilience.reset_fault_state()
    monkeypatch.setenv(
        "PFX_FAULT", f"gen_crash:{int(server.stats['requests']) + 1}"
    )
    with pytest.raises(RuntimeError, match="warmup failed at bucket"):
        server.warmup([4])
    monkeypatch.delenv("PFX_FAULT")
    resilience.reset_fault_state()
    server.warmup([4])  # recovers cleanly


def test_gen_error_does_not_poison_cache_pool(server, monkeypatch):
    """A generation failure after the donated cache was popped must drop
    the (possibly donation-invalidated) pair — not return it to the pool
    — and record structured gen_error stats for /healthz."""
    from paddlefleetx_tpu.utils import resilience

    prompt = [[5, 6, 7]]
    before_rows = server.generate_ids(prompt, max_dec_len=5)
    bucket_key = next(reversed(server._cache_pool))  # MRU = this bucket
    errs0 = server.stats["gen_errors"]

    resilience.reset_fault_state()
    monkeypatch.setenv(
        "PFX_FAULT", f"gen_crash:{int(server.stats['requests']) + 1}"
    )
    with pytest.raises(RuntimeError, match="injected gen_crash"):
        server.generate_ids(prompt, max_dec_len=5)
    monkeypatch.delenv("PFX_FAULT")
    resilience.reset_fault_state()

    assert server.stats["gen_errors"] == errs0 + 1
    assert "gen_crash" in server.stats["last_error"]
    # the bucket was dropped, not left pointing at a donated pair
    assert bucket_key not in server._cache_pool
    # and the pool recovers: same bucket serves again, token-identical
    assert server.generate_ids(prompt, max_dec_len=5) == before_rows


def test_cache_pool_is_lru_bounded(server):
    """Each pooled cache pins a device k/v pair; mixed traffic across
    many buckets must not retain more than Generation.cache_pool_size
    pairs (LRU eviction, default 4)."""
    for dec in (3, 2, 1):  # distinct gen configs -> distinct bucket keys
        for prompt in ([[1, 2]], [[1, 2], [3, 4], [5, 6]]):
            server.generate_ids(prompt, max_dec_len=dec)
    assert len(server._cache_pool) <= server._cache_pool_size
