"""Deep-dive tracing units + the decision-log agreement suite
(utils/tracing.py, docs/observability.md "Deep-dive tracing").

The strict Chrome-trace validator here (`validate_chrome_trace`) stands
in for a manual Perfetto load, the way test_telemetry's
`parse_prometheus` stands in for a Prometheus scrape: required keys
(ph/ts/dur/pid/tid/name), non-negative monotone-consistent durations,
and valid nesting per lane.  Reused by the serve drills against the
live `/debug/traces` endpoint.
"""

import json
import math
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils import tracing as TR


# ---------------------------------------------------------------------------
# strict Chrome-trace-event validator (the Perfetto-load stand-in)
# ---------------------------------------------------------------------------


def validate_chrome_trace(doc):
    """Assert `doc` is a loadable Chrome trace-event document: a
    ``traceEvents`` list whose spans (``ph="X"``) carry ph/ts/dur/pid/
    tid/name with non-negative numeric ts/dur, whose metadata rows
    (``ph="M"``, the pid-lane labels the wall-clock-anchored exporter
    emits) carry pid + a known metadata name, and — per (pid, tid)
    lane — valid nesting: any two spans are either disjoint or one
    strictly contains the other (Perfetto renders partial overlap as
    garbage).  Returns the span events grouped per lane."""
    assert isinstance(doc, dict) and isinstance(doc.get("traceEvents"), list), doc
    lanes = {}
    for i, ev in enumerate(doc["traceEvents"]):
        assert ev.get("ph") in ("X", "M"), f"event {i}: unknown ph: {ev}"
        if ev["ph"] == "M":
            # process/thread metadata label rows (no ts/dur)
            assert ev.get("name") in ("process_name", "thread_name"), ev
            assert isinstance(ev.get("pid"), int), ev
            assert isinstance(ev.get("args", {}).get("name"), str), ev
            continue
        for key in ("ph", "ts", "dur", "pid", "tid", "name"):
            assert key in ev, f"event {i} missing {key!r}: {ev}"
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0, ev
        assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0, ev
        assert isinstance(ev["name"], str) and ev["name"], ev
        lanes.setdefault((ev["pid"], ev["tid"]), []).append(ev)
    # tolerance: chrome_trace rounds ts and dur INDEPENDENTLY, and at
    # epoch-anchored magnitude (~2^50 µs) one float64 ulp is 0.25 µs —
    # round(x, 3) can no longer move a value there, and the ts+dur
    # arithmetic BELOW accumulates a few ulps of its own even on a
    # perfectly clamped document.  Scale the tolerance with the lane's
    # magnitude (4 ulps ≈ 1 µs at epoch scale; floor 0.01 µs for small
    # synthetic fixtures) — a real partial overlap is milliseconds.
    for lane, evs in lanes.items():
        eps = max(
            1e-2,
            4 * math.ulp(max((abs(e["ts"]) + e["dur"] for e in evs),
                             default=0.0)),
        )
        # sort like Perfetto: by start, widest first at equal starts
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []
        for ev in evs:
            start, end = ev["ts"], ev["ts"] + ev["dur"]
            while stack and start >= stack[-1][1] - eps:
                stack.pop()
            if stack:
                assert end <= stack[-1][1] + eps, (
                    f"lane {lane}: {ev['name']} [{start}, {end}] partially "
                    f"overlaps its enclosing span ending at {stack[-1][1]}"
                )
            stack.append((start, end))
    return lanes


# ---------------------------------------------------------------------------
# TraceContext
# ---------------------------------------------------------------------------


def test_trace_context_timeline_orders_and_redacts_nothing_it_isnt_given():
    tc = TR.TraceContext("t-1", "request", t0=100.0, scheduler="x")
    tc.span("queue_wait", t0=100.0, t1=100.5)
    tc.event("decode_chunk", t=101.0, committed=2, accepted=1)
    tc.span("prefill", t0=100.5, t1=100.9, prompt_len=3)
    tc.event("respond", t=101.2, code=200)
    tc.finish(t=101.2)
    tl = tc.timeline()
    assert tl["trace_id"] == "t-1" and tl["done"]
    assert tl["total_s"] == pytest.approx(1.2)
    names = [e["name"] for e in tl["events"]]
    assert names == ["queue_wait", "prefill", "decode_chunk", "respond"]
    assert tl["events"][0]["at_s"] == pytest.approx(0.0)
    assert tl["events"][1]["dur_s"] == pytest.approx(0.4)
    assert tl["events"][2]["args"] == {"committed": 2, "accepted": 1}


def test_trace_context_negative_duration_clamps():
    tc = TR.TraceContext("t-2", "x", t0=10.0)
    tc.span("weird", t0=11.0, t1=10.5)  # quantized injected stamps
    assert tc.events()[0]["dur"] == 0.0


# ---------------------------------------------------------------------------
# TraceBuffer: sampling, bounds, knobs
# ---------------------------------------------------------------------------


def test_buffer_sample_one_traces_everything_and_caps():
    buf = TR.TraceBuffer(sample=1.0, cap=3)
    ids = []
    for i in range(5):
        tc = buf.maybe_start("request", i=i)
        assert tc is not None
        ids.append(tc.trace_id)
    kept = [t.trace_id for t in buf.traces()]
    assert kept == ids[-3:]  # bounded: oldest evicted
    assert buf.get(ids[0]) is None and buf.get(ids[-1]) is not None


def test_buffer_sample_zero_is_disabled_and_free():
    buf = TR.TraceBuffer(sample=0.0)
    assert not buf.enabled
    assert buf.maybe_start("request") is None
    assert buf.traces() == []


def test_buffer_discard_drops_never_admitted_traces():
    buf = TR.TraceBuffer(sample=1.0, cap=8)
    tc = buf.maybe_start("request")
    buf.discard(tc.trace_id)
    assert buf.get(tc.trace_id) is None and buf.traces() == []
    buf.discard("not-there")  # idempotent


def test_rejected_admission_leaves_no_trace_in_the_window():
    """A 429'd submit must not leave an empty timeline in the sampled
    window (the buffer holds real units of work only)."""
    from paddlefleetx_tpu.core.request_queue import QueueFull, RequestQueue
    from paddlefleetx_tpu.utils import tracing

    before = {t.trace_id for t in tracing.get_trace_buffer().traces()}
    q = RequestQueue(lambda p, m: [[1]] * len(p), max_depth=1)
    q.submit([[1]], 4)  # not started: occupies the one slot
    with pytest.raises(QueueFull):
        q.submit([[2]], 4)
    after = tracing.get_trace_buffer().traces()
    new = [t for t in after if t.trace_id not in before]
    assert len(new) == 1  # the admitted one only; the rejected discarded
    q.shutdown(drain=False, timeout=10)


def test_buffer_fractional_sampling_is_deterministic():
    buf = TR.TraceBuffer(sample=0.5, cap=64)
    picks = [buf.maybe_start("r") is not None for _ in range(10)]
    assert picks == [False, True] * 5  # accumulator: every other request


def test_buffer_knobs_loud_parse(monkeypatch):
    monkeypatch.setenv("PFX_TRACE_SAMPLE", "nope")
    with pytest.raises(ValueError, match="PFX_TRACE_SAMPLE"):
        TR.TraceBuffer()
    monkeypatch.setenv("PFX_TRACE_SAMPLE", "1.5")
    with pytest.raises(ValueError, match=r"\[0, 1\]"):
        TR.TraceBuffer()
    monkeypatch.setenv("PFX_TRACE_SAMPLE", "0.25")
    monkeypatch.setenv("PFX_TRACE_CAP", "7")
    buf = TR.TraceBuffer()
    assert buf.sample == 0.25 and buf.cap == 7


# ---------------------------------------------------------------------------
# Chrome-trace export
# ---------------------------------------------------------------------------


def _traced_buffer():
    buf = TR.TraceBuffer(sample=1.0, cap=8)
    for k in range(2):
        tc = buf.maybe_start("request", t0=100.0 + k, kind="unit")
        tc.span("queue_wait", t0=100.0 + k, t1=100.2 + k)
        tc.span("decode", t0=100.2 + k, t1=100.9 + k, tokens=4)
        tc.event("respond", t=100.9 + k, code=200)
        tc.finish(t=100.95 + k)
    return buf


def test_chrome_trace_strict_parses_with_valid_nesting():
    doc = TR.chrome_trace(_traced_buffer().traces())
    lanes = validate_chrome_trace(doc)
    assert len(lanes) == 2  # one lane per trace
    for evs in lanes.values():
        names = [e["name"] for e in evs]
        # enclosing request bar first (widest), phases nested inside
        assert names[0] == "request"
        assert {"queue_wait", "decode", "respond"} <= set(names)
    # round-trips through json (what /debug/traces serves)
    validate_chrome_trace(json.loads(json.dumps(doc)))


def test_export_chrome_trace_lands_in_flight_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("PFX_FLIGHT_DIR", str(tmp_path / "arts"))
    path = TR.export_chrome_trace(buffer=_traced_buffer())
    assert path == str(tmp_path / "arts" / "trace.json")
    validate_chrome_trace(json.load(open(path)))
    # explicit path wins; unwritable target returns None, never raises
    p2 = TR.export_chrome_trace(path=str(tmp_path / "t.json"),
                                buffer=_traced_buffer())
    assert p2 == str(tmp_path / "t.json") and os.path.exists(p2)
    assert TR.export_chrome_trace(path="/proc/nope/t.json",
                                  buffer=_traced_buffer()) is None


def test_chrome_trace_nesting_tolerates_epoch_scale_rounding():
    """Regression pin for the PR 16/19 nesting flake: at epoch-anchored
    magnitude (~1.75e15 µs, between 2**50 and 2**51) one float64 ulp is
    0.25 µs — round(x, 3) can no longer move a value, and a child
    rounded independently of its parent can overshoot the parent's end
    by a few ulps.  This document replicates a captured flaky export
    (child end 0.25 µs past the bar end); the validator must accept it
    while still rejecting a REAL partial overlap at the same scale."""
    base = 1754500000000000.0  # epoch µs at the flake's magnitude
    assert math.ulp(base) == 0.25
    flaky = {"traceEvents": [
        {"ph": "X", "ts": base, "dur": 10.0, "pid": 7, "tid": 1,
         "name": "request", "cat": "trace", "args": {}},
        # ends one ulp past the bar: the rounding artifact, not overlap
        {"ph": "X", "ts": base + 8.0, "dur": 2.25, "pid": 7, "tid": 1,
         "name": "decode", "cat": "request", "args": {}},
    ]}
    validate_chrome_trace(flaky)
    real_overlap = {"traceEvents": [
        {"ph": "X", "ts": base, "dur": 10.0, "pid": 7, "tid": 1,
         "name": "request", "cat": "trace", "args": {}},
        # ends 5 µs (20 ulps) past the bar: a genuine partial overlap
        {"ph": "X", "ts": base + 8.0, "dur": 7.0, "pid": 7, "tid": 1,
         "name": "decode", "cat": "request", "args": {}},
    ]}
    with pytest.raises(AssertionError, match="partially"):
        validate_chrome_trace(real_overlap)


def test_chrome_trace_export_clamps_children_in_rounded_domain():
    """The exporter's post-rounding clamp: exported child endpoints
    never overshoot their enclosing bar, even though every ts is
    epoch-anchored (where independent rounding used to let them drift a
    few ulps past it — the nesting flake's source)."""
    buf = TR.TraceBuffer(sample=1.0, cap=64)
    t0 = time.monotonic()
    for k in range(32):
        # children ending exactly at the bar end, at awkward offsets —
        # the rounding-sensitive shape
        b = t0 + k * 0.010001
        tc = buf.maybe_start("request", t0=b, kind="unit")
        tc.span("decode", t0=b + 0.0012345, t1=b + 0.0098765)
        tc.finish(t=b + 0.0098765)
    doc = TR.chrome_trace(buf.traces())
    bars = {}
    for ev in doc["traceEvents"]:
        if ev.get("ph") == "X" and ev["cat"] == "trace":
            bars[ev["tid"]] = (ev["ts"], ev["ts"] + ev["dur"])
    assert len(bars) == 32
    children = 0
    for ev in doc["traceEvents"]:
        if ev.get("ph") != "X" or ev["cat"] == "trace":
            continue
        children += 1
        bar_ts, bar_end = bars[ev["tid"]]
        assert ev["ts"] >= bar_ts
        # within one ulp of the bar end (the clamp's ts + (E - ts)
        # re-add is the only remaining float step)
        assert ev["ts"] + ev["dur"] <= bar_end + math.ulp(bar_end)
    assert children == 32
    validate_chrome_trace(doc)


# ---------------------------------------------------------------------------
# decision-log replay
# ---------------------------------------------------------------------------


def test_replay_decision_log_sums_rows():
    rows = [
        {"iter": 1, "admitted": 2, "evicted": 0, "shed": 1, "finished": 0,
         "spec_proposed": 6, "spec_accepted": 4},
        {"iter": 2, "admitted": 1, "evicted": 1, "shed": 0, "finished": 2,
         "spec_proposed": 9, "spec_accepted": 2},
    ]
    out = TR.replay_decision_log(rows)
    assert out == {
        "iterations": 2, "prefill_admits": 3, "evictions": 1, "shed": 1,
        "finished": 2, "spec_proposed": 15, "spec_accepted": 6,
        # prefix-reuse columns (PR 12) default to 0 on legacy rows
        "prefix_hits": 0, "prefix_hit_tokens": 0, "prefix_evictions": 0,
        "chunks": 0,
        # spill/migration columns (PR 17) default to 0 on legacy rows
        "spills": 0, "readmits": 0, "spill_discards": 0,
        "migrate_adopted": 0,
        # multi-tenant columns default to empty/0 on legacy rows
        "tenants": {}, "preempted": 0, "preempted_tenants": {},
        # token-ledger columns (PR 20) default to 0 on legacy rows
        "tok_admitted": 0, "tok_delivered": 0, "tok_evicted_lost": 0,
        "tok_preempt_refunded": 0, "tok_shed_after_admit": 0,
    }


# ---------------------------------------------------------------------------
# the agreement suite: a REAL continuous-scheduler run's decision log
# replays to exactly the counters (same tiny shape as
# test_continuous_batching/test_speculative, so compiles ride the warm
# persistent cache)
# ---------------------------------------------------------------------------

TINY = {
    "Global": {"global_batch_size": 8, "seed": 3},
    "Engine": {"mix_precision": {"enable": False},
               "save_load": {"save_steps": 0}},
    "Model": {
        "module": "GPTModule",
        "vocab_size": 96,
        "hidden_size": 32,
        "num_layers": 2,
        "num_attention_heads": 4,
        "max_position_embeddings": 128,
        "dtype": "float32",
    },
    "Distributed": {},
    "Optimizer": {"name": "FusedAdamW",
                  "lr": {"name": "Constant", "learning_rate": 1e-3}},
    "Generation": {"max_dec_len": 8, "decode_strategy": "greedy_search",
                   "pad_to_multiple": 16, "eos_token_id": 95,
                   "pad_token_id": 0},
}

PROMPTS = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 10], [11, 12, 13, 14]]


@pytest.fixture(scope="module")
def server():
    import jax

    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict.from_nested(TINY)
    cfg = process_configs(cfg, num_devices=jax.device_count())
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    return GenerationServer(cfg, mesh, module)


def test_decision_log_replay_reproduces_counters_exactly(server):
    """THE agreement acceptance: admissions, a mid-decode eviction, and
    per-chunk speculative accepts all land in the decision log, and
    replaying it reproduces the per-instance counters the registry
    exports (pfx_prefill_admits_total / pfx_request_evictions_total /
    pfx_spec_accepted_total) EXACTLY — a silently dropped trace event
    would break the equality."""
    from paddlefleetx_tpu.core.continuous_batching import (
        ContinuousScheduler,
        PagedDecodeEngine,
    )
    from paddlefleetx_tpu.core.request_queue import DeadlineExceeded
    from paddlefleetx_tpu.ops.speculative import SpecConfig

    eng = PagedDecodeEngine(server, max_batch=4, spec=SpecConfig(draft_k=3))
    sched = ContinuousScheduler(eng, max_depth=8)
    # a TRUE mid-decode eviction, deterministically: admit the doomed
    # request by hand-driving one iteration, then force its deadline
    # into the past so the NEXT iteration must evict the ACTIVE row
    # (a deadline_s=tiny + sleep would shed it while still queued —
    # the _shed_locked path — and the eviction column would be a
    # vacuous 0 == 0)
    doomed = sched.submit([PROMPTS[1]], 64, deadline_s=60)
    sched._iterate()  # admit + first decode step
    assert eng.active_rows() == 1
    doomed_row = next(r for r in eng.slots if r is not None)
    doomed_row.entry.deadline = time.monotonic() - 1.0
    sched._iterate()  # eviction fires before this iteration's step
    assert sched.stats["evictions"] == 1  # really evicted mid-decode
    assert eng.active_rows() == 0
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=10)

    futs = [sched.submit([p], 6, deadline_s=120) for p in PROMPTS]
    sched.start()
    outs = [f.result(timeout=300)[0] for f in futs]
    assert all(len(o) >= 1 for o in outs)
    assert sched.shutdown(timeout=60)

    replay = TR.replay_decision_log(sched.decision_log)
    # the three acceptance counters, exactly (per-instance views == what
    # the registry exports for this scheduler/engine)
    assert replay["prefill_admits"] == sched.stats["prefill_admits"] \
        == eng.stats["prefills"]
    assert replay["evictions"] == sched.stats["evictions"]
    assert replay["spec_accepted"] == eng.stats["spec_accepted"]
    assert replay["spec_proposed"] == eng.stats["spec_proposed"]
    assert replay["shed"] == sched.stats["shed_deadline"]
    assert replay["prefill_admits"] >= len(PROMPTS)
    assert replay["spec_proposed"] > 0
    # block accounting closes: everything released, deltas net to zero
    assert eng.cache.stats()["kv_blocks_used"] == 0
    rows = list(sched.decision_log)
    assert rows[-1]["blocks_free"] == eng.cache.allocator.free_count()
    # width buckets recorded as positive pow2s
    assert all(r["width_bucket"] >= 1 for r in rows)
    # token-ledger agreement + closure (PR 20): the replay fold
    # reproduces every disposition exactly, and the drained books close
    # with nothing in flight — admitted == delivered + evicted_lost +
    # preempt_refunded + shed_after_admit
    ledger = sched.token_ledger()
    assert replay["tok_admitted"] == ledger["admitted"]
    assert replay["tok_delivered"] == ledger["delivered"]
    assert replay["tok_evicted_lost"] == ledger["evicted_lost"]
    assert replay["tok_preempt_refunded"] == ledger["preempt_refunded"]
    assert replay["tok_shed_after_admit"] == ledger["shed_after_admit"]
    assert ledger["in_flight"] == 0
    assert ledger["admitted"] == (
        ledger["delivered"] + ledger["evicted_lost"]
        + ledger["preempt_refunded"] + ledger["shed_after_admit"]
    )
    assert ledger["delivered"] == sum(len(o) for o in outs)
    assert ledger["evicted_lost"] >= 1  # the doomed row had decoded


def test_request_trace_carries_full_continuous_timeline(server):
    """A request served through the continuous scheduler can be fully
    reconstructed offline: admission -> queue_wait -> prefill ->
    decode_chunk* (with spec accepted counts summing to the delivered
    tokens' chunks) -> respond-able timeline, and the Chrome export of
    the window strict-parses."""
    from paddlefleetx_tpu.core.continuous_batching import (
        ContinuousScheduler,
        PagedDecodeEngine,
    )
    from paddlefleetx_tpu.ops.speculative import SpecConfig

    eng = PagedDecodeEngine(server, max_batch=4, spec=SpecConfig(draft_k=3))
    sched = ContinuousScheduler(eng, max_depth=8)
    sched.start()
    fut = sched.submit([PROMPTS[0]], 6, deadline_s=120)
    toks = fut.result(timeout=300)[0]
    assert sched.shutdown(timeout=60)

    tc = fut.trace
    assert tc is not None, "default sampling must trace the request"
    tc.finish()
    tl = tc.timeline()
    names = [e["name"] for e in tl["events"]]
    # admission + queue_wait share the enqueue instant (the span sorts
    # first as the wider event); prefill and every decode chunk follow
    assert {"admission", "queue_wait", "prefill"} <= set(names)
    assert max(names.index("admission"), names.index("queue_wait")) \
        < names.index("prefill")
    chunks = [e for e in tl["events"] if e["name"] == "decode_chunk"]
    assert chunks, names
    # committed counts cover every delivered token (EOS chunks may
    # commit tokens the row drops, hence >=)
    assert sum(c["args"]["committed"] for c in chunks) >= len(toks)
    assert all("accepted" in c["args"] for c in chunks)
    # phases are ordered and the prefill span has real width
    prefill = next(e for e in tl["events"] if e["name"] == "prefill")
    assert prefill["dur_s"] >= 0 and prefill["args"]["prompt_len"] == 3
    # redaction: no token values anywhere in the event args
    for e in tl["events"]:
        assert "tokens" not in e["args"] or isinstance(
            e["args"]["tokens"], int
        ), e
    # the whole window exports as strict-parsing Perfetto JSON
    validate_chrome_trace(
        TR.chrome_trace([TR.get_trace_buffer().get(tc.trace_id) or tc])
    )


def test_scheduler_does_no_tracing_work_when_sampled_out(server, monkeypatch):
    """With the buffer disabled (PFX_TRACE_SAMPLE=0 semantics), futures
    carry no trace and the decision log stays empty — the hot path does
    zero tracing work."""
    from paddlefleetx_tpu.core.continuous_batching import (
        ContinuousScheduler,
        PagedDecodeEngine,
    )
    from paddlefleetx_tpu.utils import tracing

    monkeypatch.setattr(
        tracing, "_buffer", tracing.TraceBuffer(sample=0.0)
    )
    eng = PagedDecodeEngine(server, max_batch=4, spec=None)
    sched = ContinuousScheduler(eng, max_depth=8)
    sched.start()
    fut = sched.submit([PROMPTS[0]], 6, deadline_s=120)
    assert len(fut.result(timeout=300)[0]) >= 1
    assert fut.trace is None
    assert list(sched.decision_log) == []
    # with tracing off the per-iteration debug publish is ALSO skipped
    # (zero observability work) until a /debug/state call latches
    # interest — the first call may see the boot view, views are fresh
    # from the next iteration on
    dbg = sched.debug_state()
    assert dbg["scheduler"] == "continuous"
    fut2 = sched.submit([PROMPTS[2]], 6, deadline_s=120)
    assert len(fut2.result(timeout=300)[0]) >= 1
    dbg2 = sched.debug_state()
    assert dbg2["compiled"]["prefill_families"] >= 1
    assert sched.shutdown(timeout=60)


def test_debug_state_snapshot_matches_live_engine(server):
    """debug_state() is published per iteration: after a drained run it
    agrees with the live engine/cache state and exposes per-row data
    while rows are live (positions, budgets, blocks — no token ids)."""
    from paddlefleetx_tpu.core.continuous_batching import PagedDecodeEngine, ContinuousScheduler

    eng = PagedDecodeEngine(server, max_batch=4)
    sched = ContinuousScheduler(eng, max_depth=8)
    # drive by hand: admit two rows, step once, publish
    s0 = eng.admit(PROMPTS[0], 6)
    eng.admit(PROMPTS[1], 6)
    eng.step()
    sched._publish_debug()
    dbg = sched.debug_state()
    rows = dbg["batch"]["rows"]
    assert {r["slot"] for r in rows} >= {s0}
    for r in rows:
        assert set(r) == {"slot", "seq_id", "prompt_len", "max_new",
                          "position", "gen_step", "tokens_out", "blocks",
                          "active", "prefix_hit_tokens", "prefill_pending"}
        assert r["position"] >= r["prompt_len"]
    assert dbg["arena"]["kv_blocks_used"] == eng.cache.stats()["kv_blocks_used"]
    assert dbg["batch"]["active_rows"] == eng.active_rows()
    assert dbg["batch"]["width_bucket"] == eng.table_width_bucket()
    for i, r in enumerate(list(eng.slots)):
        if r is not None:
            eng.release(i)
