"""Router core units (`core/router.py`): replica lifecycle state
machine, queue-aware scoring, bounded connection-refused retry, the
router-level admission surface, and drain bookkeeping — all against
in-process stub replicas (no jax, no model): the multi-process drills
live in tests/test_router_drills.py.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from paddlefleetx_tpu.core.request_queue import QueueClosed, QueueFull
from paddlefleetx_tpu.core.router import (
    NoReplicaAvailable,
    ReplicaUnavailable,
    RouterCore,
    STATE_CODE,
)


class StubReplica:
    """A canned tools/serve.py stand-in: /healthz serves a mutable dict,
    /generate|/prefill|/decode record the hit and answer (or abort,
    under ``fail_mode='reset'``); /admin/drain mimics the serve.py
    remote-drain contract (flip /healthz to draining, answer 200) and
    records the Authorization header it saw.  ``admin_expect`` makes it
    ENFORCE a bearer token (401 otherwise); ``legacy_admin`` makes it
    404 the whole /admin surface (a pre-PR 11 replica)."""

    def __init__(self, *, role="monolith", ok=True, depth=0,
                 state="ok", pid=None):
        self.hits = []
        self.post_headers = []  # one {header: value} dict per POST
        self.fail_mode = None
        self.admin_expect = None   # token string to enforce (None = open)
        self.legacy_admin = False  # 404 /admin/* (pre-remote-drain serve)
        self.admin_status = None   # force this status on /admin/* (e.g. 500)
        self.admin_auth_seen = []
        self.health = {
            "ok": ok, "state": state, "queue_depth": depth, "busy_s": 0.0,
            "identity": {
                "replica_id": f"stub-{id(self) % 997}", "role": role,
                "scheduler": "continuous", "listen": "stub",
                "pid": pid if pid is not None else os.getpid(),
            },
        }
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                # the poll loop GETs /healthz?metrics=1 (federation);
                # a stub without metrics_text is the pre-federation
                # replica case — the poller must still parse the health
                if self.path.split("?", 1)[0] == "/healthz":
                    return self._json(200, stub.health)
                return self._json(404, {"error": "nope"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                stub.hits.append((self.path, body))
                stub.post_headers.append(dict(self.headers.items()))
                if self.path.startswith("/admin/"):
                    if stub.legacy_admin:
                        return self._json(404, {"error": "unknown path"})
                    if stub.admin_status is not None:
                        return self._json(stub.admin_status,
                                          {"error": "forced"})
                    stub.admin_auth_seen.append(
                        self.headers.get("Authorization")
                    )
                    if stub.admin_expect is not None:
                        auth = self.headers.get("Authorization") or ""
                        if auth != f"Bearer {stub.admin_expect}":
                            return self._json(401, {"error": "bad token"})
                    if self.path == "/admin/drain":
                        stub.health["state"] = "draining"
                        return self._json(200, {"state": "draining"})
                    return self._json(404, {"error": "unknown admin path"})
                if stub.fail_mode == "reset":
                    # accept + read, then die without a response: the
                    # "partial exchange" class that must NOT be retried
                    self.connection.shutdown(socket.SHUT_RDWR)
                    self.connection.close()
                    return
                return self._json(200, {"completion_ids": [7, 8, 9]})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _dead_url():
    """A url nothing listens on (bound + closed so the port was ours)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return f"http://127.0.0.1:{s.getsockname()[1]}"


@pytest.fixture
def stub():
    s = StubReplica()
    yield s
    s.stop()


# ---------------------------------------------------------------------------
# lifecycle state machine
# ---------------------------------------------------------------------------


def test_lifecycle_booting_warm_serving(stub):
    core = RouterCore([(stub.url, "monolith")], serve_after=2)
    r = core.replicas["r0"]
    assert r.state == "booting" and not r.eligible()
    core.poll_replica(r)
    assert r.state == "warm"  # answered once, trust not yet earned
    assert not r.eligible()
    core.poll_replica(r)
    assert r.state == "serving" and r.eligible()
    # identity block landed: the router knows who this is
    assert r.pid == os.getpid()
    assert r.replica_id and r.scheduler == "continuous"


def test_lifecycle_degraded_is_ineligible_but_not_ejected(stub):
    core = RouterCore([(stub.url, "monolith")])
    r = core.replicas["r0"]
    core.poll_replica(r)
    core.poll_replica(r)
    assert r.eligible()
    stub.health["ok"] = False  # watchdog degraded
    core.poll_replica(r)
    assert r.state == "serving" and not r.eligible()
    stub.health["ok"] = True  # recovered
    core.poll_replica(r)
    assert r.eligible()


def test_lifecycle_draining_then_gone(stub):
    core = RouterCore([(stub.url, "monolith")])
    r = core.replicas["r0"]
    core.poll_replica(r)
    core.poll_replica(r)
    stub.health["state"] = "draining"  # SIGTERM landed replica-side
    core.poll_replica(r)
    assert r.state == "draining" and not r.eligible()
    stub.stop()  # drained process exited
    core.poll_replica(r)
    assert r.state == "gone"  # refused while draining = clean exit


def test_lifecycle_eject_after_consecutive_failures(stub):
    core = RouterCore([(stub.url, "monolith")], eject_after=3)
    r = core.replicas["r0"]
    core.poll_replica(r)
    core.poll_replica(r)
    assert r.state == "serving"
    stub.stop()  # crashed, not draining
    for _ in range(2):
        core.poll_replica(r)
        assert r.state == "serving"  # grace: transient blips tolerated
    core.poll_replica(r)
    assert r.state == "gone"


def test_role_mismatch_marks_ineligible(stub):
    # stub reports monolith but is configured into the prefill pool
    decode = StubReplica(role="decode")
    try:
        core = RouterCore(
            [(stub.url, "prefill"), (decode.url, "decode")]
        )
        r = core.replicas["r0"]
        core.poll_replica(r)
        core.poll_replica(r)
        assert r.role_mismatch and not r.eligible()
    finally:
        decode.stop()


# ---------------------------------------------------------------------------
# scoring
# ---------------------------------------------------------------------------


def _serving_pair():
    a, b = StubReplica(), StubReplica()
    core = RouterCore([(a.url, "monolith"), (b.url, "monolith")])
    for r in core.replicas.values():
        core.poll_replica(r)
        core.poll_replica(r)
    return a, b, core


def test_pick_least_loaded_by_depth():
    a, b, core = _serving_pair()
    try:
        core.replicas["r0"].depth = 5
        core.replicas["r1"].depth = 1
        picked = core.pick("monolith", remaining_s=60)
        assert picked.key == "r1"
        # the pick reserved router-side capacity on the winner
        assert picked.in_flight == 1
    finally:
        a.stop(), b.stop()


def test_pick_deadline_aware_penalty():
    """A shallower replica whose estimated wait blows the remaining
    deadline loses to a deeper-but-fast one."""
    a, b, core = _serving_pair()
    try:
        r0, r1 = core.replicas["r0"], core.replicas["r1"]
        r0.depth, r0.last_latency_s = 3, 2.0   # ~6s estimated wait
        r1.depth, r1.last_latency_s = 5, 0.01  # ~0.05s
        assert core.pick("monolith", remaining_s=1.0).key == "r1"
        # with a lax deadline the depth ordering rules again
        r1.in_flight = 0
        assert core.pick("monolith", remaining_s=60.0).key == "r0"
    finally:
        a.stop(), b.stop()


def test_pick_raises_when_pool_empty():
    a, b, core = _serving_pair()
    try:
        for r in core.replicas.values():
            r.drain_requested = True
        with pytest.raises(NoReplicaAvailable):
            core.pick("monolith", remaining_s=60)
    finally:
        a.stop(), b.stop()


# ---------------------------------------------------------------------------
# dispatch: bounded refused-retry, never-retry-partial
# ---------------------------------------------------------------------------


def test_dispatch_retries_refused_on_another_replica(stub):
    core = RouterCore(
        [(_dead_url(), "monolith"), (stub.url, "monolith")], retries=2
    )
    # force both serving; the dead one looks attractive (depth 0)
    for r in core.replicas.values():
        r.state, r.healthy = "serving", True
    core.replicas["r1"].depth = 9  # make the dead replica the first pick
    status, body, _ = core.dispatch(
        "POST", "/generate", b"{}", role="monolith", deadline_s=30
    )
    assert status == 200
    assert json.loads(body)["completion_ids"] == [7, 8, 9]
    assert core.replicas["r0"].state == "gone"  # refused = ejected now
    assert len(stub.hits) == 1


def test_dispatch_refused_everywhere_raises(stub):
    core = RouterCore([(_dead_url(), "monolith")], retries=2)
    core.replicas["r0"].state, core.replicas["r0"].healthy = "serving", True
    with pytest.raises(NoReplicaAvailable, match="failed attempt"):
        core.dispatch("POST", "/generate", b"{}", role="monolith",
                      deadline_s=10)


def test_dispatch_never_retries_partial_exchange():
    """A replica that dies AFTER reading the request (reset mid-reply)
    raises ReplicaUnavailable and the OTHER live replica never sees the
    request — the decode may have run, replays could double-generate."""
    bad, good = StubReplica(), StubReplica()
    bad.fail_mode = "reset"
    core = RouterCore(
        [(bad.url, "monolith"), (good.url, "monolith")], retries=2
    )
    for r in core.replicas.values():
        r.state, r.healthy = "serving", True
    core.replicas["r1"].depth = 9  # bad replica picked first
    try:
        with pytest.raises(ReplicaUnavailable):
            core.dispatch("POST", "/generate", b"{}", role="monolith",
                          deadline_s=30)
        assert len(bad.hits) == 1
        assert len(good.hits) == 0  # NOT replayed
    finally:
        bad.stop(), good.stop()


# ---------------------------------------------------------------------------
# router-level admission (the RequestQueue surface)
# ---------------------------------------------------------------------------


def test_admission_bounds_and_drain(stub):
    core = RouterCore([(stub.url, "monolith")], max_inflight=2)
    core.acquire()
    core.acquire()
    with pytest.raises(QueueFull):
        core.acquire()
    core.release()
    core.acquire()  # capacity came back
    core.close()  # draining: no new admissions, in-flight finish
    with pytest.raises(QueueClosed):
        core.acquire()
    assert not core.join(timeout=0.05)  # two still in flight
    core.release(), core.release()
    assert core.join(timeout=5)


def test_collect_exports_depth_and_state(stub):
    core = RouterCore([(stub.url, "monolith")])
    r = core.replicas["r0"]
    core.poll_replica(r)
    core.poll_replica(r)
    stub.health["queue_depth"] = 4
    core.poll_replica(r)
    rows = {(name, tuple(sorted(labels.items()))): v
            for name, labels, v in core.collect()}
    assert rows[("pfx_router_in_flight", ())] == 0
    assert rows[
        ("pfx_router_replica_depth", (("replica", "r0"),))
    ] == 4.0
    assert rows[
        ("pfx_router_replica_state", (("replica", "r0"),))
    ] == STATE_CODE["serving"]


# ---------------------------------------------------------------------------
# drain (rolling deploy primitive)
# ---------------------------------------------------------------------------


def test_drain_posts_admin_drain_and_walks_to_gone(stub):
    """drain() rides the REMOTE transport: the target stops receiving
    traffic immediately, gets an authenticated POST /admin/drain (no
    pid/SIGTERM — this is what makes rolling deploys work cross-host),
    reports draining on its own /healthz, and the poller marks it gone
    once its port refuses."""
    core = RouterCore([(stub.url, "monolith")])
    r = core.replicas["r0"]
    core.poll_replica(r)
    core.poll_replica(r)
    assert r.eligible()
    out = core.drain()  # unnamed: picks the serving replica
    assert out["replica"] == "r0"
    assert r.drain_requested and r.state == "draining"
    assert not r.eligible()
    # the drain arrived over HTTP, not a signal
    assert [p for p, _ in stub.hits] == ["/admin/drain"]
    assert stub.health["state"] == "draining"
    with pytest.raises(NoReplicaAvailable):
        core.pick("monolith", remaining_s=60)
    stub.stop()  # the real serve.py exits 0 after answering admitted work
    core.poll_replica(r)
    assert r.state == "gone"
    with pytest.raises(ValueError, match="already gone"):
        core.drain("r0")
    with pytest.raises(ValueError, match="no serving replica"):
        core.drain()
    with pytest.raises(ValueError, match="unknown replica"):
        core.drain("r9")


def test_drain_sends_shared_token_and_auth_reject_restores_rotation(
        stub, monkeypatch):
    """With PFX_ADMIN_TOKEN set the drain POST carries the bearer
    token; a replica that REJECTS the auth (mismatched fleet config)
    raises loudly AND the target returns to rotation — a misconfigured
    token must not blackhole a healthy replica."""
    monkeypatch.setenv("PFX_ADMIN_TOKEN", "fleet-secret")
    stub.admin_expect = "fleet-secret"
    core = RouterCore([(stub.url, "monolith")])
    r = core.replicas["r0"]
    core.poll_replica(r)
    core.poll_replica(r)
    core.drain("r0")
    assert stub.admin_auth_seen == ["Bearer fleet-secret"]
    assert r.state == "draining"
    # second replica, wrong token on the router side
    stub2 = StubReplica()
    stub2.admin_expect = "other-secret"
    try:
        core2 = RouterCore([(stub2.url, "monolith")])
        r2 = core2.replicas["r0"]
        core2.poll_replica(r2)
        core2.poll_replica(r2)
        with pytest.raises(ValueError, match="rejected the drain auth"):
            core2.drain("r0")
        assert r2.state == "serving" and not r2.drain_requested
        assert r2.eligible()  # restored to rotation
    finally:
        stub2.stop()


def test_drain_that_provably_did_not_land_restores_rotation(stub):
    """A 404 with no safe pid fallback, or any other non-200, means the
    drain did NOT happen: the target must return to rotation and the
    caller must hear about it — never a blackholed-but-'drained'
    replica."""
    # legacy replica that never reported a pid: no transport at all
    stub.legacy_admin = True
    stub.health["identity"]["pid"] = None
    core = RouterCore([(stub.url, "monolith")])
    r = core.replicas["r0"]
    core.poll_replica(r)
    core.poll_replica(r)
    with pytest.raises(ValueError, match="cannot be signalled"):
        core.drain("r0")
    assert r.state == "serving" and not r.drain_requested and r.eligible()
    # a replica whose /admin/drain 500s: left in rotation, loudly
    stub.legacy_admin = False
    stub.admin_status = 500
    with pytest.raises(ValueError, match="HTTP 500"):
        core.drain("r0")
    assert r.state == "serving" and r.eligible()
    # and once it behaves, the drain goes through
    stub.admin_status = None
    core.drain("r0")
    assert r.state == "draining"


def test_drain_request_not_sent_restores_rotation(stub, monkeypatch):
    """A connect stall (the request never went out) must NOT blackhole
    the target: nothing downstream saw the drain, so the replica goes
    back in rotation and the caller hears the failure — only a reply
    lost AFTER the exchange leaves it draining for the poller."""
    import paddlefleetx_tpu.core.router as router_mod
    from paddlefleetx_tpu.core.router import RequestNotSent

    core = RouterCore([(stub.url, "monolith")])
    r = core.replicas["r0"]
    core.poll_replica(r)
    core.poll_replica(r)
    real = router_mod._http_request

    def stalled(url, method, path, **kw):
        if path == "/admin/drain":
            raise RequestNotSent("send failed: timed out")
        return real(url, method, path, **kw)

    monkeypatch.setattr(router_mod, "_http_request", stalled)
    with pytest.raises(ValueError, match="could not be sent"):
        core.drain("r0")
    assert r.state == "serving" and not r.drain_requested and r.eligible()
    monkeypatch.setattr(router_mod, "_http_request", real)
    core.drain("r0")  # network settled: the drain goes through
    assert r.state == "draining"


def test_local_url_guard():
    """The SIGTERM-by-pid fallback is only safe for THIS host's
    loopback — a pid from another host names an unrelated local
    process."""
    from paddlefleetx_tpu.core.router import _local_url

    assert _local_url("http://127.0.0.1:8001")
    assert _local_url("http://localhost:8001")
    assert _local_url("http://[::1]:8001")
    assert not _local_url("http://10.0.0.9:8001")
    assert not _local_url("http://replica-host:8001")


def test_drain_falls_back_to_sigterm_for_legacy_replica(stub):
    """A replica that predates /admin/drain (404s it) still drains via
    the old same-host SIGTERM on its identity pid — a harmless sleeper
    subprocess stands in for the old serve.py."""
    proc = subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(120)"])
    try:
        stub.legacy_admin = True
        stub.health["identity"]["pid"] = proc.pid
        core = RouterCore([(stub.url, "monolith")])
        r = core.replicas["r0"]
        core.poll_replica(r)
        core.poll_replica(r)
        out = core.drain()
        assert out["pid"] == proc.pid
        assert proc.wait(timeout=10) == -signal.SIGTERM
        assert r.state == "draining"
        stub.stop()
        core.poll_replica(r)
        assert r.state == "gone"
    finally:
        if proc.poll() is None:
            proc.kill()


def test_drained_replica_redeployed_on_same_url_reenters_rotation(stub):
    """The rolling-deploy recipe's second half: after drain walks a
    replica to gone, a REDEPLOYED process answering on the same url must
    re-enter via warm -> serving — the drain flag belongs to the old
    process, not the slot (regression: drain_requested was never
    cleared, permanently blackholing the slot)."""
    core = RouterCore([(stub.url, "monolith")], serve_after=2)
    r = core.replicas["r0"]
    core.poll_replica(r)
    core.poll_replica(r)
    core.poll_replica(r)
    core.drain()
    stub.stop()
    core.poll_replica(r)
    assert r.state == "gone" and r.drain_requested
    # redeploy: a fresh process (new pid) binds the same port
    redeployed = StubReplica(pid=os.getpid())
    try:
        r2 = core.replicas["r0"]
        r2_url = r2.url
        # point the slot at the new listener (same-url in production;
        # the stub can't rebind the exact port portably, so rewrite)
        r2.url = redeployed.url
        core.poll_replica(r2)
        assert not r2.drain_requested, "drain flag survived redeploy"
        assert r2.state == "warm"
        core.poll_replica(r2)
        assert r2.state == "serving" and r2.eligible()
        assert core.pick("monolith", remaining_s=60).key == "r0"
        r2.url = r2_url
    finally:
        redeployed.stop()


def test_acquire_never_touches_registry_under_router_lock(stub, monkeypatch):
    """Lock-order regression: the registry snapshot holds the registry
    lock while calling RouterCore.collect() (which takes the router
    lock), so admission-rejection counters must be bumped OUTSIDE the
    router lock or a concurrent /metrics scrape deadlocks the router.
    Probed deterministically: a registry accessor that asserts the
    router lock is free at call time."""
    import paddlefleetx_tpu.core.router as router_mod

    core = RouterCore([(stub.url, "monolith")], max_inflight=1)
    real_get = router_mod.get_registry
    violations = []

    class Probe:
        def counter(self, name, **labels):
            if core._lock.acquire(blocking=False):
                core._lock.release()
            else:
                violations.append(name)
            return real_get().counter(name, **labels)

        def __getattr__(self, name):
            return getattr(real_get(), name)

    monkeypatch.setattr(router_mod, "get_registry", lambda: Probe())
    core.acquire()
    with pytest.raises(QueueFull):
        core.acquire()  # full -> rejected counter fires
    core.release()
    core.close()
    with pytest.raises(QueueClosed):
        core.acquire()  # draining -> rejected counter fires
    assert not violations, (
        f"registry touched under the router lock: {violations}"
    )


# ---------------------------------------------------------------------------
# configuration validation
# ---------------------------------------------------------------------------


def test_pool_configuration_is_validated():
    with pytest.raises(ValueError, match=">= 1 replica"):
        RouterCore([])
    with pytest.raises(ValueError, match="unknown replica role"):
        RouterCore([("http://x:1", "turbo")])
    with pytest.raises(ValueError, match="mixing monolith"):
        RouterCore([("http://x:1", "monolith"), ("http://x:2", "prefill")])
    with pytest.raises(ValueError, match="BOTH"):
        RouterCore([("http://x:1", "prefill")])
    core = RouterCore([("http://x:1", "prefill"), ("http://x:2", "decode")])
    assert core.disaggregated
    assert not RouterCore([("http://x:1", "monolith")]).disaggregated


def test_pool_port_ranges_must_not_overlap(capsys):
    """Overlapping slot port ranges (or a router --port inside one)
    are a config error at argparse time — NOT a bind-failure crash
    loop that burns the flap budget into a misleading quarantine."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "router_cli_under_test", os.path.join(repo, "tools", "router.py"))
    cli = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cli)

    base = ["--port", "9000", "--supervise",
            "--prefill-cmd", "serve {port} {replica_id}",
            "--decode-cmd", "serve {port} {replica_id}"]
    # prefill slots 8300..8303 swallow the decode base port
    with pytest.raises(SystemExit):
        cli.main(base + ["--prefill-base-port", "8300",
                         "--max-prefill", "4",
                         "--decode-base-port", "8301"])
    assert "overlap" in capsys.readouterr().err
    # the router's own listen port inside the decode range
    with pytest.raises(SystemExit):
        cli.main(base + ["--prefill-base-port", "8200",
                         "--decode-base-port", "8990",
                         "--max-decode", "16"])
    assert "falls inside" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# ejected-replica rejoin (the named lifecycle edge)
# ---------------------------------------------------------------------------


def test_ejected_replica_rejoins_booting_warm_serving(stub):
    """SATELLITE: a replica that comes back AFTER --eject-after failed
    polls marked it gone re-registers through the normal walk — gone ->
    warm -> serving — and receives traffic again (the supervisor's
    crash-restart path depends on exactly this rejoin)."""
    core = RouterCore([(stub.url, "monolith")], eject_after=2,
                      serve_after=2)
    r = core.replicas["r0"]
    core.poll_replica(r)
    core.poll_replica(r)
    assert r.state == "serving"
    stub.stop()  # crashed, not draining
    core.poll_replica(r)
    core.poll_replica(r)
    assert r.state == "gone"  # ejected after 2 failed polls
    assert r.failures >= 2
    # the replacement process answers on the same slot
    revived = StubReplica(pid=os.getpid())
    try:
        r.url = revived.url  # same-url in production; stub can't rebind
        core.poll_replica(r)
        assert r.state == "warm" and not r.eligible()
        assert r.failures == 0  # the eject counter reset on rejoin
        core.poll_replica(r)
        assert r.state == "serving" and r.eligible()
        # and it takes traffic again
        status, body, _ = core.dispatch(
            "POST", "/generate", b"{}", role="monolith", deadline_s=30
        )
        assert status == 200
        assert json.loads(body)["completion_ids"] == [7, 8, 9]
    finally:
        revived.stop()


# ---------------------------------------------------------------------------
# admin auth (PFX_ADMIN_TOKEN) + dynamic registration + control signals
# ---------------------------------------------------------------------------


def test_check_admin_token_and_localhost_rules(monkeypatch):
    from paddlefleetx_tpu.core import router as router_mod
    from paddlefleetx_tpu.core.router import check_admin

    # token unset: loopback allowed (loudly, once), remote refused 403
    monkeypatch.delenv("PFX_ADMIN_TOKEN", raising=False)
    monkeypatch.setattr(router_mod, "_LOCAL_ONLY_WARNED", [False])
    ok, code, msg = check_admin({}, ("127.0.0.1", 1234))
    assert ok and code is None
    ok, code, msg = check_admin({}, ("10.0.0.9", 1234), what="/debug")
    assert not ok and code == 403 and "localhost-only" in msg
    # token set: bearer match required regardless of source address
    monkeypatch.setenv("PFX_ADMIN_TOKEN", "s3cret")
    ok, code, _ = check_admin({}, ("127.0.0.1", 1234))
    assert not ok and code == 401
    ok, code, _ = check_admin(
        {"Authorization": "Bearer wrong"}, ("127.0.0.1", 1))
    assert not ok and code == 401
    ok, code, _ = check_admin(
        {"Authorization": "Bearer s3cret"}, ("10.0.0.9", 1))
    assert ok and code is None
    # a loopback client seen through a dual-stack bind (IPv4-mapped
    # IPv6) is still localhost when the token is unset
    monkeypatch.delenv("PFX_ADMIN_TOKEN")
    ok, _, _ = check_admin({}, ("::ffff:127.0.0.1", 1))
    assert ok


def test_add_replica_is_idempotent_and_polls_in(stub):
    core = RouterCore([], allow_empty=True)
    assert core.replicas == {} and not core.disaggregated
    key = core.add_replica(stub.url)
    assert key == "r0"
    assert core.add_replica(stub.url + "/") == "r0"  # idempotent on url
    other = StubReplica()
    try:
        assert core.add_replica(other.url) == "r1"
        with pytest.raises(ValueError, match="unknown replica role"):
            core.add_replica("http://x:1", "turbo")
        r = core.replicas["r0"]
        core.poll_replica(r)
        core.poll_replica(r)
        assert r.state == "serving"
    finally:
        other.stop()


def test_poll_reads_occupancy_and_slo_breach(stub):
    """The elastic-control signals ride the existing /healthz poll: the
    continuous scheduler's occupancy and the replica's own SLO breach
    verdict land on the replica view the controller consumes."""
    stub.health["occupancy"] = 0.75
    stub.health["slo"] = {"breach": True, "reason": "ttft_p99: burn 9x"}
    core = RouterCore([(stub.url, "monolith")])
    r = core.replicas["r0"]
    core.poll_replica(r)
    assert r.occupancy == 0.75 and r.slo_breach
    view = core.replica_views()[0]
    assert view["occupancy"] == 0.75 and view["slo_breach"]
    # absent fields (coalesce scheduler / SLO off) read as calm
    del stub.health["occupancy"], stub.health["slo"]
    core.poll_replica(r)
    assert r.occupancy == 0.0 and not r.slo_breach


# ---------------------------------------------------------------------------
# disaggregated fabric: decode-aware scoring, handoff failover, direct
# prefill->decode transfer (docs/serving.md "Disaggregated operations")
# ---------------------------------------------------------------------------


def _ctr(name, **labels):
    from paddlefleetx_tpu.utils.telemetry import get_registry

    return get_registry().value(name, **labels)


def _all_serving(core):
    for r in core.replicas.values():
        r.state, r.healthy = "serving", True


def test_handoff_transport_validated():
    with pytest.raises(ValueError, match="handoff"):
        RouterCore([("http://x:1", "monolith")], handoff="carrier-pigeon")


def test_add_replica_learns_pool_topology():
    """A pool-supervised router boots EMPTY and learns disaggregation
    from the registrations; mixing stays rejected dynamically."""
    core = RouterCore([], allow_empty=True)
    assert not core.disaggregated
    core.add_replica("http://127.0.0.1:7997", "prefill")
    core.add_replica("http://127.0.0.1:7998", "decode")
    assert core.disaggregated
    with pytest.raises(ValueError, match="mixing"):
        core.add_replica("http://127.0.0.1:7999", "monolith")


def test_decode_score_folds_arena_pressure():
    """Decode replicas are no longer scored by queue depth alone: at
    equal depth the emptier arena wins, and an arena with NO admissible
    blocks goes near last resort — it would bounce the adoption."""
    pre = StubReplica(role="prefill")
    d1, d2 = StubReplica(role="decode"), StubReplica(role="decode")
    core = RouterCore([(pre.url, "prefill"), (d1.url, "decode"),
                       (d2.url, "decode")])
    try:
        _all_serving(core)
        r1, r2 = core.replicas["r1"], core.replicas["r2"]
        r1.depth = r2.depth = 1
        r1.occupancy, r1.available_blocks = 0.95, 2
        r2.occupancy, r2.available_blocks = 0.10, 60
        assert core.pick("decode", remaining_s=60).key == "r2"
        r2.in_flight = 0
        # full arena: even a deeper queue with room beats it
        r1.depth, r1.occupancy, r1.available_blocks = 0, 0.5, 0
        r2.depth, r2.occupancy, r2.available_blocks = 3, 0.5, 40
        assert core.pick("decode", remaining_s=60).key == "r2"
    finally:
        pre.stop(), d1.stop(), d2.stop()


def test_prefill_lost_mid_exchange_fails_over_stateless():
    """The prefill leg is stateless (blocks free on export): a prefill
    replica lost MID-exchange is retried on another — unlike /generate,
    where a partial exchange is never replayed."""
    bad, good = StubReplica(role="prefill"), StubReplica(role="prefill")
    dec = StubReplica(role="decode")
    bad.fail_mode = "reset"
    core = RouterCore([(bad.url, "prefill"), (good.url, "prefill"),
                       (dec.url, "decode")])
    try:
        _all_serving(core)
        core.replicas["r1"].depth = 9  # the doomed replica picked first
        f0 = _ctr("pfx_handoff_failovers_total", leg="prefill")
        out = core.generate_disaggregated([[1, 2, 3]], 4, 30.0)
        assert out == [[7, 8, 9]]
        assert len(bad.hits) == 1 and len(good.hits) == 1
        assert _ctr("pfx_handoff_failovers_total", leg="prefill") == f0 + 1
    finally:
        bad.stop(), good.stop(), dec.stop()


def test_prefill_unsent_exhaustion_is_final_not_a_failover(monkeypatch):
    """RequestNotSent exhaustion inside dispatch() is FINAL: dispatch
    already ran the bounded retry-on-another-replica for provably-
    unsent sends, so the prefill failover ladder must not re-loop it
    (attempt multiplication) nor count sends that never went out as
    mid-exchange failovers."""
    from paddlefleetx_tpu.core import router as router_mod
    from paddlefleetx_tpu.core.router import RequestNotSent

    pre1, pre2 = StubReplica(role="prefill"), StubReplica(role="prefill")
    dec = StubReplica(role="decode")
    core = RouterCore([(pre1.url, "prefill"), (pre2.url, "prefill"),
                       (dec.url, "decode")], retries=1)
    sends = []
    real = router_mod._http_request

    def flaky(url, method, path, **kw):
        if path.startswith("/prefill"):
            sends.append(url)
            raise RequestNotSent("send failed: injected")
        return real(url, method, path, **kw)

    monkeypatch.setattr(router_mod, "_http_request", flaky)
    try:
        _all_serving(core)
        f0 = _ctr("pfx_handoff_failovers_total", leg="prefill")
        with pytest.raises(RequestNotSent):
            core.generate_disaggregated([[1, 2, 3]], 4, 30.0)
        # dispatch's own bounded retry only: retries + 1 attempts total
        assert len(sends) == 2, sends
        assert _ctr("pfx_handoff_failovers_total", leg="prefill") == f0
    finally:
        pre1.stop(), pre2.stop(), dec.stop()


def test_decode_death_triggers_bounded_reprefill_fallback():
    """A decode replica lost after the exchange started is NEVER
    replayed at (the PR 10 rule) — the whole chain re-runs ONCE through
    a healthy pair with the corpse excluded."""
    pre = StubReplica(role="prefill")
    bad, good = StubReplica(role="decode"), StubReplica(role="decode")
    bad.fail_mode = "reset"
    core = RouterCore([(pre.url, "prefill"), (bad.url, "decode"),
                       (good.url, "decode")])
    try:
        _all_serving(core)
        core.replicas["r2"].depth = 9  # the doomed decode picked first
        f0 = _ctr("pfx_handoff_failovers_total", leg="decode")
        out = core.generate_disaggregated([[1, 2, 3]], 4, 30.0)
        assert out == [[7, 8, 9]]
        # the chain re-ran end to end: prefill served twice, the corpse
        # saw exactly ONE /decode (no replay), the survivor one
        assert len(pre.hits) == 2
        assert len(bad.hits) == 1 and len(good.hits) == 1
        assert _ctr("pfx_handoff_failovers_total", leg="decode") == f0 + 1
    finally:
        pre.stop(), bad.stop(), good.stop()


def test_decode_death_fallback_exhaustion_is_honest_503():
    """With no healthy decode replica left for the fallback, the chain
    ends in an honest NoReplicaAvailable (HTTP 503) — the corpse saw
    exactly one exchange, and NO second prefill is burned proving the
    doomed decode pick (the eligibility pre-check fires first)."""
    pre = StubReplica(role="prefill")
    bad = StubReplica(role="decode")
    bad.fail_mode = "reset"
    core = RouterCore([(pre.url, "prefill"), (bad.url, "decode")])
    try:
        _all_serving(core)
        with pytest.raises(NoReplicaAvailable):
            core.generate_disaggregated([[1, 2, 3]], 4, 30.0)
        decode_hits = [h for h in bad.hits if h[0].startswith("/decode")]
        assert len(decode_hits) == 1
        prefill_hits = [h for h in pre.hits
                        if h[0].startswith("/prefill")]
        assert len(prefill_hits) == 1
    finally:
        pre.stop(), bad.stop()


class DirectPrefillStub:
    """A prefill replica that understands the direct-transfer placement
    ticket: on /prefill with a ``forward`` ticket it POSTs a payload
    STRAIGHT to the decode url and relays the JSON completion.
    ``script`` overrides responses per call: ``"fallback"`` returns the
    payload octet-stream (a direct send that degraded to the proxy
    leg), ``"decode_dead"`` reports a mid-exchange decode loss the way
    tools/serve.py does (structured 502 naming the leg)."""

    PAYLOAD = b"PFXH1-STUB-PAYLOAD"

    def __init__(self):
        self.hits = []
        self.script = []
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body, ctype, headers=None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                body = json.dumps({
                    "ok": True, "state": "ok", "queue_depth": 0,
                    "busy_s": 0.0,
                    "identity": {"replica_id": "dp0", "role": "prefill",
                                 "scheduler": "queue", "listen": "stub",
                                 "pid": os.getpid()},
                }).encode()
                return self._send(200, body, "application/json")

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                stub.hits.append(req)
                mode = stub.script.pop(0) if stub.script else "direct"
                if mode == "decode_dead":
                    return self._send(502, json.dumps({
                        "error": "injected decode death",
                        "handoff_leg": "decode",
                    }).encode(), "application/json")
                if mode == "fallback":
                    return self._send(
                        200, stub.PAYLOAD, "application/octet-stream",
                        {"X-Direct-Error": "injected drop"},
                    )
                if mode == "garbage":
                    # a 200 relay that carries no completion (truncated
                    # or corrupted body)
                    return self._send(200, b"not json",
                                      "application/json")
                fwd = req.get("forward")
                assert fwd, "direct mode request carried no ticket"
                import http.client as hc
                from urllib.parse import urlsplit
                u = urlsplit(fwd["url"])
                conn = hc.HTTPConnection(u.hostname, u.port, timeout=10)
                conn.request(
                    "POST", "/decode?deadline_s=5", body=stub.PAYLOAD,
                    headers={
                        "Content-Type": "application/octet-stream",
                        "X-Handoff-Transport": "direct",
                    },
                )
                resp = conn.getresponse()
                data = resp.read()
                conn.close()
                return self._send(resp.status, data, "application/json")

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_direct_handoff_bytes_bypass_router():
    """Direct transfer: the payload flows prefill -> decode while the
    router's handoff byte counter stays FLAT (the acceptance evidence),
    and the placement ticket's reservation is released."""
    pre, dec = DirectPrefillStub(), StubReplica(role="decode")
    core = RouterCore([(pre.url, "prefill"), (dec.url, "decode")],
                      handoff="direct")
    try:
        _all_serving(core)
        core.replicas["r1"].last_latency_s = 0.0
        b0 = _ctr("pfx_router_handoff_bytes_total")
        out = core.generate_disaggregated([[1, 2, 3]], 4, 30.0)
        assert out == [[7, 8, 9]]
        assert pre.hits[0]["forward"]["url"] == dec.url
        path, body = dec.hits[0]
        assert path.startswith("/decode") and body == pre.PAYLOAD
        assert dec.post_headers[0].get("X-Handoff-Transport") == "direct"
        assert _ctr("pfx_router_handoff_bytes_total") == b0
        assert core.replicas["r1"].in_flight == 0
        # the ticketed replica is never dispatched to under direct
        # transport: the chain stamps its latency so deadline-aware
        # scoring doesn't run on the initial floor forever
        assert core.replicas["r1"].last_latency_s > 0.0
    finally:
        pre.stop(), dec.stop()


def test_direct_malformed_200_relay_is_honest_502():
    """A direct-transport 200 relay whose body is unparseable (or has
    no completion_ids) must surface as a loud 502, never a silent
    wrong-success 200."""
    from paddlefleetx_tpu.core.router import _DownstreamError

    pre, dec = DirectPrefillStub(), StubReplica(role="decode")
    pre.script = ["garbage"]
    core = RouterCore([(pre.url, "prefill"), (dec.url, "decode")],
                      handoff="direct")
    try:
        _all_serving(core)
        with pytest.raises(_DownstreamError) as ei:
            core.generate_disaggregated([[1, 2, 3]], 4, 30.0)
        assert ei.value.status == 502
        assert b"completion_ids" in ei.value.body
    finally:
        pre.stop(), dec.stop()


def test_direct_handoff_degrades_to_proxy_leg():
    """A direct send that failed before the decode replica read it
    returns the payload to the router, which carries it itself — the
    drilled proxy fallback."""
    pre, dec = DirectPrefillStub(), StubReplica(role="decode")
    pre.script = ["fallback"]
    core = RouterCore([(pre.url, "prefill"), (dec.url, "decode")],
                      handoff="direct")
    try:
        _all_serving(core)
        b0 = _ctr("pfx_router_handoff_bytes_total")
        out = core.generate_disaggregated([[1, 2, 3]], 4, 30.0)
        assert out == [[7, 8, 9]]
        assert _ctr("pfx_router_handoff_bytes_total") == b0 + len(
            pre.PAYLOAD
        )
        assert dec.post_headers[0].get("X-Handoff-Transport") == "proxy"
    finally:
        pre.stop(), dec.stop()


def test_direct_decode_death_report_runs_reprefill_failover():
    """The prefill replica's structured decode-death report triggers
    the same bounded re-prefill fallback as a proxy-leg loss — the
    second attempt's ticket excludes the dead replica."""
    pre = DirectPrefillStub()
    pre.script = ["decode_dead"]
    d1, d2 = StubReplica(role="decode"), StubReplica(role="decode")
    core = RouterCore([(pre.url, "prefill"), (d1.url, "decode"),
                       (d2.url, "decode")], handoff="direct")
    try:
        _all_serving(core)
        core.replicas["r2"].depth = 9  # d1 gets the first ticket
        f0 = _ctr("pfx_handoff_failovers_total", leg="decode")
        out = core.generate_disaggregated([[1, 2, 3]], 4, 30.0)
        assert out == [[7, 8, 9]]
        assert _ctr("pfx_handoff_failovers_total", leg="decode") == f0 + 1
        assert len(pre.hits) == 2
        assert pre.hits[0]["forward"]["url"] == d1.url
        assert pre.hits[1]["forward"]["url"] == d2.url
        # the "dead" replica never saw a byte; the survivor saw one
        assert d1.hits == [] and len(d2.hits) == 1
    finally:
        pre.stop(), d1.stop(), d2.stop()


def test_prefill_retry_reissues_ticket_preferring_clean_decode():
    """A prefill replica lost mid-exchange may have already run its
    direct decode leg, so the retry's FRESH ticket prefers a decode
    replica the lost attempt was not pointed at — but never at the
    cost of availability: with only the dirty replica left, it is
    reused."""
    bad, good = StubReplica(role="prefill"), DirectPrefillStub()
    bad.fail_mode = "reset"
    d1, d2 = StubReplica(role="decode"), StubReplica(role="decode")
    core = RouterCore([(bad.url, "prefill"), (good.url, "prefill"),
                       (d1.url, "decode"), (d2.url, "decode")],
                      handoff="direct")
    try:
        _all_serving(core)
        core.replicas["r1"].depth = 9  # the doomed prefill picked first
        core.replicas["r3"].depth = 9  # d1 preferred for tickets
        out = core.generate_disaggregated([[1, 2, 3]], 4, 30.0)
        assert out == [[7, 8, 9]]
        # the retry went out with a ticket for the CLEAN replica d2,
        # even though d1 scores better
        assert good.hits[0]["forward"]["url"] == d2.url
        assert len(d2.hits) == 1 and d1.hits == []
        # every ticket reservation was released
        assert core.replicas["r2"].in_flight == 0
        assert core.replicas["r3"].in_flight == 0
    finally:
        bad.stop(), good.stop(), d1.stop(), d2.stop()

    # single-decode pool: the dirty replica is reused rather than 503ing
    bad, good = StubReplica(role="prefill"), DirectPrefillStub()
    bad.fail_mode = "reset"
    d1 = StubReplica(role="decode")
    core = RouterCore([(bad.url, "prefill"), (good.url, "prefill"),
                       (d1.url, "decode")], handoff="direct")
    try:
        _all_serving(core)
        core.replicas["r1"].depth = 9
        out = core.generate_disaggregated([[1, 2, 3]], 4, 30.0)
        assert out == [[7, 8, 9]]
        assert good.hits[0]["forward"]["url"] == d1.url
    finally:
        bad.stop(), good.stop(), d1.stop()


def test_poll_reads_available_blocks(stub):
    """The decode-pool scale/routing signal rides the existing poll."""
    stub.health["available_blocks"] = 17
    core = RouterCore([(stub.url, "monolith")])
    r = core.replicas["r0"]
    core.poll_replica(r)
    assert r.available_blocks == 17
    assert core.replica_views()[0]["available_blocks"] == 17
    del stub.health["available_blocks"]
    core.poll_replica(r)
    assert r.available_blocks is None
