"""Continuous-batching serve drills through the real CLI
(`make test-paged`): tools/serve.py with ``--scheduler continuous`` must
keep the PR 3 serving contracts on the paged engine, plus the new one —
a mid-decode deadline EVICTION frees the row's KV blocks and later
requests still produce token-identical greedy output.

Follows tests/test_serve_drills.py conventions: ``fault``-marked,
subprocess-driven, tiny synthetic GPT, persistent XLA compile cache
shared through the environment (tests/conftest.py)."""

import json
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request
import os

import pytest
import yaml

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = {
    "Global": {"global_batch_size": 8, "seed": 11},
    "Engine": {"mix_precision": {"enable": False},
               "save_load": {"save_steps": 0}},
    "Model": {
        "module": "GPTModule",
        "vocab_size": 96,
        "hidden_size": 32,
        "num_layers": 2,
        "num_attention_heads": 4,
        "max_position_embeddings": 64,
        "dtype": "float32",
    },
    "Optimizer": {"name": "FusedAdamW",
                  "lr": {"name": "Constant", "learning_rate": 1e-3}},
    "Generation": {"max_dec_len": 8, "decode_strategy": "greedy_search",
                   "pad_to_multiple": 8, "eos_token_id": 95,
                   "pad_token_id": 0},
}


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _post(port, body, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _healthz(port, timeout=10):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz", timeout=timeout
    ) as r:
        return json.load(r)


def _metrics(port, timeout=10):
    from test_telemetry import parse_prometheus

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=timeout
    ) as r:
        metrics, _ = parse_prometheus(r.read().decode())
    return {name: vals[frozenset()] for name, vals in metrics.items()
            if frozenset() in vals}


def _start_server(tmp_path, *, deadline=45.0, depth=32, shed_slack=3.0,
                  watchdog=300.0, extra_env=None, extra_args=()):
    cfg_path = tmp_path / "tiny_cb.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    port = _free_port()
    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PFX_FAULT", None)
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-c", str(cfg_path), "--port", str(port),
         "--scheduler", "continuous", "--cb-batch", "4",
         "--queue-depth", str(depth),
         "--deadline", str(deadline), "--shed-slack", str(shed_slack),
         "--watchdog", str(watchdog), "--warmup-buckets", "4",
         *extra_args],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    deadline_t = time.time() + 300
    while time.time() < deadline_t:
        if proc.poll() is not None:
            raise AssertionError(
                f"server died at boot: {proc.stdout.read()[-3000:]}"
            )
        try:
            h = _healthz(port, timeout=5)
            if h.get("ok"):
                return proc, port
        except Exception:
            time.sleep(0.5)
    proc.kill()
    raise AssertionError("server never became healthy")


def _finish(proc, timeout=30):
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
    return proc.stdout.read()


@pytest.mark.slow  # ~11s CLI boot; tier-1 budget funding for the
# shard_map-port tests.  Replacement coverage: mid-decode deadline
# eviction with blocks freed for the same iteration, eviction-parity, and
# ArenaReset recovery stay tier-1 via the in-process
# test_continuous_batching suite (the PR 12 precedent: in-process replay
# kept the contract when the prefix CLI drill was slow-marked); still in
# make test-paged / test-all.
def test_continuous_mid_decode_eviction_frees_blocks_token_identical(tmp_path):
    """THE paged-serving drill: a wedged decode step (cb_step_hang)
    carries a short-deadline request past its deadline MID-decode; the
    scheduler evicts the row (503, eviction + shed counters, blocks
    freed back to the pool), and the server then answers identical
    requests with token-identical greedy output — the arena was reused,
    not poisoned.  The continuous warmup consumes step 1, so the first
    traffic decode step is 2."""
    proc, port = _start_server(
        tmp_path, deadline=45.0, shed_slack=3.0,
        extra_env={"PFX_FAULT": "cb_step_hang:2",
                   "PFX_FAULT_HANG_S": "5"},
    )
    try:
        # doomed: expires inside the 5s wedge of its own first step
        t0 = time.monotonic()
        code, resp = _post(
            port,
            {"prompt_ids": [1, 2, 3], "max_tokens": 8, "deadline_s": 1.5},
            timeout=60,
        )
        assert code == 503, (code, resp)
        assert time.monotonic() - t0 < 20  # honest shed, not a hang

        # the eviction lands once the wedge clears: blocks return to the
        # pool and the scheduler keeps serving
        t_end = time.time() + 30
        m = {}
        while time.time() < t_end:
            m = _metrics(port)
            if m.get("pfx_request_evictions_total", 0) >= 1:
                break
            time.sleep(0.5)
        assert m.get("pfx_request_evictions_total", 0) >= 1, m
        assert m.get("pfx_queue_shed_deadline_total", 0) >= 1, m

        body = {"prompt_ids": [1, 2, 3], "max_tokens": 8, "deadline_s": 45}
        code2, resp2 = _post(port, body, timeout=90)
        assert code2 == 200, (code2, resp2)
        code3, resp3 = _post(port, body, timeout=90)
        assert code3 == 200, (code3, resp3)
        # token-identical greedy across the eviction: freed blocks were
        # recycled without cache corruption
        assert resp2["completion_ids"] == resp3["completion_ids"]

        m = _metrics(port)
        # all rows retired: arena fully free, batch empty
        assert m["pfx_kv_blocks_used"] == 0, m
        assert m["pfx_batch_occupancy"] == 0, m
        assert m["pfx_kv_blocks_free"] > 0, m
        # 3 traffic admits (doomed + 2 served); warmup is NOT traffic
        # and no longer inflates the counter
        assert m["pfx_prefill_admits_total"] >= 3, m
        h = _healthz(port)
        assert h["state"] == "ok" and h["queue_depth"] == 0, h
        assert h["queue"]["shed_deadline"] >= 1, h

        # ---- deep-dive acceptance: the served request reconstructs
        # offline from /debug/trace, the decision log replays to the
        # registry counters EXACTLY, and the trace window is
        # Perfetto-loadable (docs/observability.md runbook) ----
        from test_tracing import validate_chrome_trace

        from paddlefleetx_tpu.utils.tracing import replay_decision_log

        def _get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ) as r:
                assert r.status == 200, path
                return json.load(r)

        assert "trace_id" in resp2, resp2
        tl = _get(f"/debug/trace?id={resp2['trace_id']}")
        names = [e["name"] for e in tl["events"]]
        assert {"admission", "queue_wait", "prefill", "respond"} <= set(names)
        chunks = [e for e in tl["events"] if e["name"] == "decode_chunk"]
        assert chunks, names  # per-chunk decode timeline present
        assert sum(c["args"]["committed"] for c in chunks) >= len(
            resp2["completion_ids"]
        )
        assert all("accepted" in c["args"] for c in chunks)
        assert next(
            e for e in tl["events"] if e["name"] == "respond"
        )["args"]["code"] == 200

        dbg = _get("/debug/state")
        assert dbg["scheduler"] == "continuous"
        assert dbg["arena"]["kv_blocks_used"] == 0 == m["pfx_kv_blocks_used"]
        assert dbg["batch"]["active_rows"] == 0
        assert dbg["compiled"]["prefill_families"] >= 1
        assert dbg["metrics"]["pfx_kv_blocks_used"] == m["pfx_kv_blocks_used"]
        assert dbg["metrics"]["pfx_kv_blocks_free"] == m["pfx_kv_blocks_free"]
        replay = replay_decision_log(dbg["decisions"])
        assert replay["prefill_admits"] == m["pfx_prefill_admits_total"], (
            replay, m)
        assert replay["evictions"] == m["pfx_request_evictions_total"], (
            replay, m)
        assert replay["spec_accepted"] == m.get("pfx_spec_accepted_total", 0)
        # shed rows cover scheduler-side sheds (a handler-side try_remove
        # of a still-queued entry lands outside the iteration loop)
        assert replay["shed"] >= 1, replay

        validate_chrome_trace(_get("/debug/traces"))

        # graceful drain still holds on the continuous scheduler
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        assert rc == 0, rc
    finally:
        log = _finish(proc)
    assert "evicted" in log, log[-3000:]
    assert "Traceback" not in log, log[-3000:]


@pytest.mark.slow  # a second full server boot; the prefix replay/parity
# contracts stay tier-1 in-process via test_continuous_batching.py
# (test_scheduler_prefix_replay_contract_and_counters + the parity
# suite) — this CLI spelling runs in make test-prefix / test-paged /
# test-all
def test_prefix_cache_and_chunked_prefill_through_real_cli(tmp_path):
    """Shared-prefix reuse drill through the real serve.py: with
    ``--prefix-cache-blocks`` + ``--prefill-chunk`` on, a repeated
    prompt's second admission HITS the index (counters prove it), its
    greedy output is token-identical to the first (miss/chunked) pass,
    physical-block gauges stay deduped, the decision log replays
    pfx_prefix_hits_total exactly, and SIGTERM drain still exits 0."""
    proc, port = _start_server(
        tmp_path, deadline=60.0,
        extra_args=("--prefix-cache-blocks", "32", "--prefill-chunk", "16"),
    )
    try:
        prompt = [((7 * i) % 89) + 1 for i in range(20)]  # 1 full block + 4
        body = {"prompt_ids": prompt, "max_tokens": 8, "deadline_s": 60}
        code1, r1 = _post(port, body, timeout=90)
        assert code1 == 200, (code1, r1)
        code2, r2 = _post(port, body, timeout=90)
        assert code2 == 200, (code2, r2)
        # THE parity contract through the CLI: the prefix-hit admission
        # (shared blocks + COW + suffix-only compute) produced exactly
        # the tokens the cold path produced
        assert r2["completion_ids"] == r1["completion_ids"]

        m = _metrics(port)
        assert m["pfx_prefix_hits_total"] >= 1, m
        assert m["pfx_prefix_hit_tokens_total"] >= 16, m
        assert m["pfx_prefix_misses_total"] >= 1, m
        assert m["pfx_prefill_chunks_total"] >= 1, m  # chunked admission ran
        # rows retired: only the published prefix blocks stay resident,
        # and the physical accounting closes against the arena
        assert m["pfx_prefix_cached_blocks"] >= 1, m
        assert m["pfx_kv_blocks_used"] == m["pfx_prefix_cached_blocks"], m
        assert m["pfx_batch_occupancy"] == 0, m

        def _get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ) as r:
                assert r.status == 200, path
                return json.load(r)

        from paddlefleetx_tpu.utils.tracing import replay_decision_log

        dbg = _get("/debug/state")
        assert dbg["prefix_cache"]["enabled"] is True
        assert dbg["prefix_cache"]["hits"] == m["pfx_prefix_hits_total"]
        replay = replay_decision_log(dbg["decisions"])
        # the exact-replay contract, prefix edition (alongside the PR 8
        # trio, re-checked here on the same log)
        assert replay["prefix_hits"] == m["pfx_prefix_hits_total"], (replay, m)
        assert replay["chunks"] == m["pfx_prefill_chunks_total"], (replay, m)
        assert replay["prefill_admits"] == m["pfx_prefill_admits_total"]

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        assert rc == 0, rc
    finally:
        log = _finish(proc)
    assert "Traceback" not in log, log[-3000:]


@pytest.mark.slow  # a second full server boot; the mid-decode-eviction
# drill above is the ISSUE acceptance drill and stays in tier-1, this
# staggered-traffic variant runs in make test-paged / test-all
def test_continuous_staggered_arrivals_all_served_and_batched(tmp_path):
    """Requests arriving while the batch is mid-decode are admitted at
    step boundaries (prefill admits grow while earlier requests are
    still decoding) and every response is token-identical to the same
    prompt served alone."""
    proc, port = _start_server(tmp_path, deadline=60.0)
    try:
        import threading

        # reference: served alone
        body = {"prompt_ids": [5, 6, 7], "max_tokens": 8, "deadline_s": 60}
        code, ref = _post(port, body, timeout=90)
        assert code == 200, (code, ref)

        n = 6
        results = [None] * n

        def worker(i):
            time.sleep(0.05 * i)  # staggered arrivals
            results[i] = _post(port, body, timeout=120)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "hung connection"
        for code_i, resp_i in results:
            assert code_i == 200, (code_i, resp_i)
            assert resp_i["completion_ids"] == ref["completion_ids"]

        m = _metrics(port)
        assert m["pfx_prefill_admits_total"] >= n + 1, m
        assert m["pfx_kv_blocks_used"] == 0, m
        h = _healthz(port)
        assert h["queue"]["completed"] >= n + 1, h
    finally:
        log = _finish(proc)
    assert "Traceback" not in log, log[-3000:]
