"""Elastic-control-plane chaos drills through the real CLIs
(`make test-elastic`, docs/serving.md "Elastic control plane"):
`tools/router.py --supervise` spawning real `tools/serve.py` replicas.

  remote drain   POST /admin/drain on serve.py IS the SIGTERM drain
                 contract over authenticated HTTP: 401 without the
                 fleet token, drain + exit 0 with it; /debug/* rides
                 the same gate.
  crash loop     a replica that can never boot (PFX_FAULT=boot_crash)
                 is restarted with backoff then QUARANTINED loudly
                 within the flap budget — and the controller decision
                 log replays to exact agreement with the
                 pfx_controller_* counters.
  SIGKILL        a replica killed under flood is restarted by the
                 supervisor and re-admitted by the router (gone ->
                 warm -> serving, new pid) with zero dropped admitted
                 requests — every response an honest 200/503, no hangs.
  breach         a flood past one replica's capacity burns its
                 error-rate SLO -> breach -> the controller spawns a
                 warm-booted replica -> the breach recovers.

Follows tests/test_router_drills.py conventions: `fault`-marked,
subprocess-driven, tiny synthetic GPT, persistent XLA compile cache
shared through the environment (tests/conftest.py)."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest
import yaml

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CACHE_DIR = os.environ.get(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)

TINY = {
    "Global": {"global_batch_size": 8, "seed": 11},
    "Engine": {"mix_precision": {"enable": False},
               "save_load": {"save_steps": 0}},
    "Model": {
        "module": "GPTModule",
        "vocab_size": 96,
        "hidden_size": 32,
        "num_layers": 2,
        "num_attention_heads": 4,
        "max_position_embeddings": 64,
        "dtype": "float32",
    },
    "Optimizer": {"name": "FusedAdamW",
                  "lr": {"name": "Constant", "learning_rate": 1e-3}},
    "Generation": {"max_dec_len": 8, "decode_strategy": "greedy_search",
                   "pad_to_multiple": 8, "eos_token_id": 95,
                   "pad_token_id": 0},
}


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _env(extra=None):
    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PFX_FAULT", None)
    env.pop("PFX_ADMIN_TOKEN", None)
    env.update(extra or {})
    return env


def _req(port, path, data=None, headers=None, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=None if data is None else json.dumps(data).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _metrics(port, timeout=10):
    from test_telemetry import parse_prometheus

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=timeout
    ) as r:
        metrics, _ = parse_prometheus(r.read().decode())
    return metrics


def _finish(proc, timeout=30):
    if proc is None:
        return ""
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
    return proc.stdout.read() if proc.stdout else ""


def _serve_cmd(cfg_path, *extra):
    """A serve.py command TEMPLATE for --replica-cmd ({port} and
    {replica_id} stay as placeholders for the supervisor)."""
    return " ".join([
        sys.executable, os.path.join(REPO, "tools", "serve.py"),
        "-c", str(cfg_path), "--port", "{port}",
        "--replica-id", "{replica_id}",
        "--warmup-buckets", "4", "--warmup-batches", "1",
        "--deadline", "60", *extra,
    ])


def _spawn_supervised_router(rport, cfg_path, tmp_path, *, serve_extra=(),
                             router_extra=(), env_extra=None):
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "router.py"),
         "--port", str(rport), "--poll-interval", "0.2",
         "--supervise",
         "--replica-cmd", _serve_cmd(cfg_path, *serve_extra),
         "--base-port", str(_free_port()),
         "--compile-cache-dir", CACHE_DIR,
         "--replica-log-dir", str(tmp_path / "replica-logs"),
         "--control-interval", "0.5",
         *router_extra],
        env=_env(env_extra), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )


def _wait(predicate, timeout, what):
    end = time.time() + timeout
    last = None
    while time.time() < end:
        try:
            last = predicate()
            if last:
                return last
        except Exception as e:  # noqa: BLE001 — listener still booting
            last = e
        time.sleep(0.3)
    raise AssertionError(f"timeout waiting for {what}: {last!r}")


def _replay_agrees(rport):
    """Fetch the controller decision log and /metrics until no tick
    lands between the two reads, then assert the replay contract: the
    untruncated log reproduces the pfx_controller_* counters EXACTLY."""
    from paddlefleetx_tpu.core.controller import replay_controller_log

    for _ in range(10):
        _, dbg = _req(rport, "/debug/controller")
        m = _metrics(rport)
        _, dbg2 = _req(rport, "/debug/controller")
        if len(dbg["decisions"]) != len(dbg2["decisions"]):
            continue  # a tick landed mid-read; retry
        replay = replay_controller_log(dbg["decisions"])
        assert m["pfx_controller_ticks_total"][frozenset()] == replay["ticks"]
        assert (m.get("pfx_controller_scale_ups_total", {})
                .get(frozenset(), 0.0) == replay["scale_ups"])
        assert (m.get("pfx_controller_scale_downs_total", {})
                .get(frozenset(), 0.0) == replay["scale_downs"])
        return replay
    raise AssertionError("controller never quiesced between reads")


# ---------------------------------------------------------------------------
# authenticated remote drain (tools/serve.py /admin + /debug gating)
# ---------------------------------------------------------------------------


def test_remote_drain_is_authenticated_and_honors_drain_contract(tmp_path):
    """THE remote-drain acceptance drill on one real replica with
    PFX_ADMIN_TOKEN set: /debug/state and /admin/drain answer 401
    without the bearer token (even from localhost); with it, /debug
    serves and /admin/drain runs the PR 3 contract — draining state,
    admitted work answered, exit 0 — with no signal ever sent."""
    cfg_path = tmp_path / "tiny.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-c", str(cfg_path), "--port", str(port),
         "--warmup-buckets", "4", "--warmup-batches", "1",
         "--deadline", "60"],
        env=_env({"PFX_ADMIN_TOKEN": "fleet-secret"}), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    tok = {"Authorization": "Bearer fleet-secret"}
    try:
        _wait(lambda: _req(port, "/healthz")[1].get("ok"), 300,
              "replica healthy")
        # /healthz and /metrics stay open (the router polls them)
        code, h = _req(port, "/healthz")
        assert code == 200 and "occupancy" in h, h
        # /debug is gated: 401 naked, 200 with the token
        code, body = _req(port, "/debug/state")
        assert code == 401 and "PFX_ADMIN_TOKEN" in body["error"], body
        code, _ = _req(port, "/debug/state", headers=tok)
        assert code == 200
        # /admin/drain: 401 naked (the unauthenticated kill-switch must
        # not exist), wrong token 401 too
        code, _ = _req(port, "/admin/drain", data={})
        assert code == 401
        code, _ = _req(port, "/admin/drain", data={},
                       headers={"Authorization": "Bearer wrong"})
        assert code == 401
        assert _req(port, "/healthz")[1]["state"] == "ok"  # still serving
        # a request keeps working, then the authenticated drain fires
        code, ref = _req(port, "/generate",
                         data={"prompt_ids": [1, 2, 3], "max_tokens": 8})
        assert code == 200
        code, body = _req(port, "/admin/drain", data={}, headers=tok)
        assert code == 200 and body["state"] == "draining", body
        # the PR 3 contract, remote spelling: exit 0, clean drain
        assert proc.wait(timeout=60) == 0
    finally:
        log = _finish(proc)
    assert "draining" in log and "drained cleanly" in log, log[-3000:]
    assert "Traceback" not in log, log[-3000:]


# ---------------------------------------------------------------------------
# crash loop -> flap-budget quarantine (+ decision-log replay agreement)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~11s supervised fleet boot; tier-1 budget funding
# for the shard_map-port tests.  Replacement coverage: the flap-budget
# quarantine rule (restarts bounded, expected exits exempt, ensure()
# scales around the slot) stays tier-1 via the test_controller
# ReplicaSupervisor units, and the authenticated-drain drill keeps a
# supervised boot tier-1; still in make test-elastic / test-all.
def test_crash_loop_replica_is_quarantined_loudly(tmp_path):
    """THE crash-loop drill: every spawn of the replica dies at boot
    (PFX_FAULT=boot_crash:0 — a broken image).  The supervisor restarts
    it with backoff exactly flap-budget times, then QUARANTINES it
    loudly (ERROR log + pfx_replica_quarantines_total) and never spawns
    it again; the controller decision log replays to exact agreement
    with the pfx_controller_* counters."""
    cfg_path = tmp_path / "tiny.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    rport = _free_port()
    router = _spawn_supervised_router(
        rport, cfg_path, tmp_path,
        router_extra=("--min-replicas", "1", "--max-replicas", "1",
                      "--flap-budget", "3", "--flap-window", "300",
                      "--restart-backoff", "0.2"),
        env_extra={"PFX_FAULT": "boot_crash:0"},
    )
    try:
        quar = _wait(
            lambda: _req(rport, "/healthz")[1]
            .get("controller", {}).get("quarantined"),
            180, "quarantine",
        )
        assert quar == 1
        _, dbg = _req(rport, "/debug/controller")
        slot = dbg["replicas"][0]
        assert slot["quarantined"] and not slot["restart_pending"]
        # quarantine fired WITHIN the flap budget: exactly 3 restarts
        assert slot["restarts"] == 3, slot
        assert slot["last_exit_rc"] == 23  # the boot_crash exit code
        m = _metrics(rport)
        assert m["pfx_replica_quarantines_total"][
            frozenset({("replica", "m0")})
        ] == 1.0
        assert m["pfx_replica_restarts_total"][
            frozenset({("replica", "m0")})
        ] == 3.0
        # no replica ever served; the fleet is at min and becalmed
        assert _req(rport, "/healthz")[1]["eligible"] == 0
        # the decision-log replay contract through the real CLI
        replay = _replay_agrees(rport)
        assert replay["scale_ups"] == 0 and replay["scale_downs"] == 0
        # the crash-looping replica left evidence in its log
        log_file = tmp_path / "replica-logs" / "m0.log"
        assert log_file.exists() and "boot_crash" in log_file.read_text()
        router.send_signal(signal.SIGTERM)
        assert router.wait(timeout=60) == 0
    finally:
        rlog = _finish(router)
    # LOUD: the quarantine is unmissable in the control-plane log
    assert "QUARANTINE" in rlog, rlog[-3000:]
    assert "Traceback" not in rlog, rlog[-3000:]


# ---------------------------------------------------------------------------
# SIGKILL under flood -> supervisor restart + router re-admission
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~2 replica boots + flood (~90s warm); the router-side
# kill/failover contract stays tier-1-drilled by the disaggregated
# adopt_crash drill (tests/test_disagg_drills.py: replica death under
# traffic -> honest 200/503, no hangs, corpse ejected) — THIS drill
# adds the supervisor restart + rejoin on top (still in
# make test-elastic / test-all)
def test_sigkill_under_flood_supervisor_restarts_and_router_readmits(
        tmp_path):
    """THE supervised-failover drill: SIGKILL a managed replica under
    flood.  Every in-flight request gets exactly one honest 200/503 (no
    hangs, no replays), the supervisor restarts the corpse, and the
    router walks the SAME slot gone -> warm -> serving with a NEW pid —
    zero dropped admitted requests end to end."""
    cfg_path = tmp_path / "tiny.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    rport = _free_port()
    router = _spawn_supervised_router(
        rport, cfg_path, tmp_path,
        serve_extra=("--queue-depth", "32"),
        router_extra=("--min-replicas", "2", "--max-replicas", "2",
                      "--restart-backoff", "0.2"),
    )
    try:
        _wait(lambda: _req(rport, "/healthz")[1].get("eligible", 0) >= 2,
              600, "two supervised replicas serving")
        views = _req(rport, "/replicas")[1]["replicas"]
        pid_by_key = {v["key"]: v["pid"] for v in views}
        assert len(set(pid_by_key.values())) == 2

        body = {"prompt_ids": [1, 2, 3], "max_tokens": 8, "deadline_s": 60}
        code, ref = _req(rport, "/generate", data=body, timeout=90)
        assert code == 200, ref

        stop = threading.Event()
        results, lock = [], threading.Lock()

        def flood():
            while not stop.is_set():
                c, _r = _req(rport, "/generate", data=body, timeout=90)
                with lock:
                    results.append(c)

        threads = [threading.Thread(target=flood) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(1.0)  # requests in flight on both replicas
        victim_key = "r0"
        os.kill(pid_by_key[victim_key], signal.SIGKILL)

        # the supervisor restarts the slot and the router re-admits it:
        # same key, serving again, NEW pid
        def readmitted():
            vs = {v["key"]: v for v in
                  _req(rport, "/replicas")[1]["replicas"]}
            v = vs[victim_key]
            return (v["state"] == "serving"
                    and v["pid"] not in (None, pid_by_key[victim_key]))
        _wait(readmitted, 300, "victim restarted + re-admitted")
        time.sleep(1.0)  # post-rejoin traffic lands on the replacement
        stop.set()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "hung connection through the kill"
        with lock:
            codes = list(results)
        # zero dropped admitted requests: every response an honest
        # 200/503, traffic flowed, and the fleet kept serving
        assert codes and all(c in (200, 503) for c in codes), codes
        assert codes.count(200) >= 1, codes
        for _ in range(3):
            code, resp = _req(rport, "/generate", data=body, timeout=90)
            assert code == 200, (code, resp)
            assert resp["completion_ids"] == ref["completion_ids"]

        m = _metrics(rport)
        assert m["pfx_replica_restarts_total"][
            frozenset({("replica", "m0")})
        ] >= 1.0
        assert "pfx_replica_quarantines_total" not in m  # one crash != flap
        router.send_signal(signal.SIGTERM)
        assert router.wait(timeout=120) == 0
    finally:
        rlog = _finish(router)
    assert "Traceback" not in rlog, rlog[-3000:]


# ---------------------------------------------------------------------------
# SLO burn-rate breach -> scale-up -> recovery
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~2 replica boots + sustained flood (~100s warm); the
# controller's breach->scale_up decision itself stays tier-1-tested by
# test_controller.py units (still in make test-elastic / test-all)
def test_breach_drives_scale_up_and_burn_recovers(tmp_path):
    """THE autoscale acceptance drill: a flood past one replica's
    admission capacity (queue depth 1) burns its error-rate SLO ->
    breach on its /healthz -> the controller spawns a second warm-booted
    replica -> capacity doubles, the 429s stop, and the breach recovers
    — with the scale-up recorded in the decision log in exact agreement
    with pfx_controller_scale_ups_total."""
    cfg_path = tmp_path / "tiny.yaml"
    cfg_path.write_text(yaml.safe_dump(TINY))
    rport = _free_port()
    router = _spawn_supervised_router(
        rport, cfg_path, tmp_path,
        serve_extra=("--queue-depth", "1",
                     "--slo-error-rate", "0.05",
                     "--slo-windows", "4,12"),
        router_extra=("--min-replicas", "1", "--max-replicas", "2",
                      "--scale-up-cooldown", "2"),
    )
    try:
        _wait(lambda: _req(rport, "/healthz")[1].get("eligible", 0) >= 1,
              600, "first replica serving")
        body = {"prompt_ids": [1, 2, 3], "max_tokens": 8, "deadline_s": 60}
        code, _ = _req(rport, "/generate", data=body, timeout=90)
        assert code == 200

        stop = threading.Event()
        codes, lock = [], threading.Lock()

        def flood():
            while not stop.is_set():
                c, _r = _req(rport, "/generate", data=body, timeout=90)
                with lock:
                    codes.append(c)

        threads = [threading.Thread(target=flood) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            # breach -> scale_up lands in the decision log, and the
            # second replica reaches serving (warm boot: seconds)
            def scaled_up():
                _, dbg = _req(rport, "/debug/controller")
                ups = [d for d in dbg["decisions"]
                       if d["action"] == "scale_up"]
                return ups if (
                    ups and _req(rport, "/healthz")[1]["eligible"] >= 2
                ) else None
            ups = _wait(scaled_up, 300, "breach-driven scale-up")
            assert ups[0]["breach"] and "breach" in ups[0]["reason"], ups
            with lock:
                assert 429 in codes, "flood never overflowed the queue"

            # recovery: with doubled capacity the 429s stop and the
            # burn windows drain on every replica
            def recovered():
                vs = _req(rport, "/replicas")[1]["replicas"]
                serving = [v for v in vs if v["state"] == "serving"]
                return (len(serving) >= 2
                        and not any(v["slo_breach"] for v in serving))
            _wait(recovered, 120, "burn-rate recovery after scale-up")
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive(), "hung connection through the drill"

        replay = _replay_agrees(rport)
        assert replay["scale_ups"] >= 1
        m = _metrics(rport)
        assert m["pfx_controller_target_replicas"][frozenset()] == 2.0
        router.send_signal(signal.SIGTERM)
        assert router.wait(timeout=120) == 0
    finally:
        rlog = _finish(router)
    assert "Traceback" not in rlog, rlog[-3000:]
