"""Training-observatory tests (docs/observability.md "training
observatory"; `make test-obs`): the per-layer-group mapping is total and
stable across the zoo, the in-graph statistics match a numpy reference,
non-finite provenance names the poisoned group, `model_stats_every=0`
adds ZERO dispatches/host-syncs vs the pre-observatory loop (asserted,
not eyeballed), memory watermarks and the compile watcher export, and
`tools/report.py` renders valid self-contained reports from a real
12-step CLI run and from a crashed (preempted) run."""

import json
import math
import os
import subprocess
import sys
from html.parser import HTMLParser

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.utils import model_stats as MS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


# ---------------------------------------------------------------------------
# group mapping: total + stable over the zoo
# ---------------------------------------------------------------------------


def _param_shapes(model_cfg):
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.utils.config import AttrDict

    module = build_module(AttrDict({"Model": dict(model_cfg), "Data": {}}))
    return jax.eval_shape(module.init_params, jax.random.PRNGKey(0))


GPT_MODEL = {
    "module": "GPTModule", "vocab_size": 128, "hidden_size": 32,
    "num_layers": 3, "num_attention_heads": 4,
    "max_position_embeddings": 32, "dtype": "float32",
}


def _assert_total_and_stable(shapes):
    spec1 = MS.build_group_spec(shapes)
    spec2 = MS.build_group_spec(shapes)
    # stable: a pure function of the tree structure
    assert spec1.names == spec2.names
    assert spec1.assignments == spec2.assignments
    # total: every leaf assigned, every float element counted exactly once
    leaves = jax.tree_util.tree_leaves(shapes)
    assert len(spec1.assignments) == len(leaves)
    float_elems = sum(
        int(np.prod(x.shape))
        for x in leaves if np.issubdtype(np.dtype(x.dtype), np.inexact)
    )
    assert int(round(float(np.sum(spec1.sizes)))) == float_elems
    for g0, length in spec1.assignments:
        top = g0 + (length or 1)
        assert 0 <= g0 < spec1.num_groups and top <= spec1.num_groups
    return spec1


def test_group_mapping_gpt_total_stable_and_ordered():
    spec = _assert_total_and_stable(_param_shapes(GPT_MODEL))
    assert spec.names == ("embed", "block_0", "block_1", "block_2", "head")
    # embed first, head last: the provenance order
    assert spec.names[0] == "embed" and spec.names[-1] == "head"


def test_group_mapping_ernie_total():
    spec = _assert_total_and_stable(_param_shapes({
        "module": "ErnieModule", "vocab_size": 128, "hidden_size": 32,
        "num_layers": 2, "num_attention_heads": 4, "ffn_hidden_size": 64,
        "max_position_embeddings": 32, "dtype": "float32",
    }))
    assert any("block_" in n for n in spec.names), spec.names


def test_group_mapping_t5_total_splits_encoder_decoder():
    spec = _assert_total_and_stable(_param_shapes({
        "module": "T5Module", "vocab_size": 96, "d_model": 32, "d_kv": 8,
        "d_ff": 48, "num_layers": 2, "num_decoder_layers": 2,
        "num_heads": 4, "dtype": "float32", "dropout_rate": 0.0,
    }))
    assert any(n.startswith("encoder/block_") for n in spec.names), spec.names
    assert any(n.startswith("decoder/block_") for n in spec.names), spec.names


def test_group_mapping_total_on_arbitrary_tree():
    # the catch-all rule: an unknown structure still maps every leaf
    tree = {
        "weird": {"a": np.zeros((3, 2), np.float32)},
        "counts": np.zeros((4,), np.int32),  # non-float: assigned, size 0
    }
    spec = _assert_total_and_stable(tree)
    assert "weird" in spec.names


# ---------------------------------------------------------------------------
# in-graph statistics vs numpy reference
# ---------------------------------------------------------------------------


@pytest.fixture
def tiny_tree():
    rng = np.random.default_rng(3)
    return {
        "embeddings": {"word": rng.normal(size=(8, 4)).astype(np.float32)},
        "layers": {"w": rng.normal(size=(2, 4, 4)).astype(np.float32),
                   "b": rng.normal(size=(2, 4)).astype(np.float32)},
        "final_ln": {"scale": rng.normal(size=(4,)).astype(np.float32)},
    }


def test_group_sqsum_matches_numpy_and_global_norm(tiny_tree):
    spec = MS.build_group_spec(tiny_tree)
    assert spec.names == ("embed", "block_0", "block_1", "head")
    gsq = np.asarray(MS.group_sqsum(spec, tiny_tree))
    expect = [
        np.sum(tiny_tree["embeddings"]["word"] ** 2),
        np.sum(tiny_tree["layers"]["w"][0] ** 2) + np.sum(tiny_tree["layers"]["b"][0] ** 2),
        np.sum(tiny_tree["layers"]["w"][1] ** 2) + np.sum(tiny_tree["layers"]["b"][1] ** 2),
        np.sum(tiny_tree["final_ln"]["scale"] ** 2),
    ]
    np.testing.assert_allclose(gsq, expect, rtol=1e-5)
    # the engine contract: sqrt(sum(group sqsums)) IS the global norm
    from paddlefleetx_tpu.optims.optimizer import global_norm_f32

    assert float(jnp.sqrt(jnp.sum(MS.group_sqsum(spec, tiny_tree)))) == \
        pytest.approx(float(global_norm_f32(tiny_tree)), rel=1e-6)


def test_group_stats_and_nonfinite_provenance_name_the_poisoned_group(tiny_tree):
    spec = MS.build_group_spec(tiny_tree)
    grads = jax.tree.map(np.copy, tiny_tree)
    grads["layers"]["w"][1, 0, 0] = np.nan  # poison block_1 ONLY
    stats = jax.tree.map(
        np.asarray,
        MS.group_stats(
            spec,
            grad_sqsum=MS.group_sqsum(spec, grads),
            params=tiny_tree, updates=tiny_tree, grads=grads,
        ),
    )
    # only block_1 carries non-finite elements; exactly one of its 20
    frac = stats["nonfinite_frac"]
    assert frac[spec.names.index("block_1")] == pytest.approx(1 / 20)
    assert sum(f > 0 for f in frac) == 1
    flags = ~np.isfinite(np.asarray(MS.group_sqsum(spec, grads)))
    assert MS.nonfinite_group_names(spec, flags) == ["block_1"]
    # update/param ratio: norms of identical trees give ratio ~1
    finite = np.isfinite(stats["grad_norm"])
    np.testing.assert_allclose(
        stats["update_ratio"][finite],
        (stats["update_norm"] / stats["param_norm"])[finite], rtol=1e-5,
    )


def test_nonfinite_group_names_order_and_limit():
    spec = MS.GroupSpec(("embed", "block_0", "head"), (), np.ones(3), None)
    assert MS.nonfinite_group_names(spec, [1, 0, 1]) == ["embed", "head"]
    assert MS.nonfinite_group_names(spec, [1, 1, 1], limit=2) == [
        "embed", "block_0",
    ]
    assert MS.nonfinite_group_names(spec, [0, 0, 0]) == []


# ---------------------------------------------------------------------------
# memory watermarks
# ---------------------------------------------------------------------------


def test_memory_watermarks_host_fallback_and_gauges():
    from paddlefleetx_tpu.utils import telemetry as T

    wm = MS.memory_watermarks()
    # CPU backend: no device memory_stats, host RSS always present
    assert wm["host_rss_bytes"] and wm["host_rss_bytes"] > 1 << 20
    reg = T.Registry()
    MS.export_memory_gauges(reg, wm)
    assert reg.value("pfx_mem_host_rss_bytes") == wm["host_rss_bytes"]


def test_warn_headroom_threshold():
    wm = {"headroom_frac": 0.01,
          "devices": [{"id": 0, "bytes_in_use": 99, "bytes_limit": 100}]}
    assert MS.warn_headroom(wm, threshold=0.05) is True
    assert MS.warn_headroom(wm, threshold=0.005) is False
    assert MS.warn_headroom({"headroom_frac": None}, threshold=0.5) is False


# ---------------------------------------------------------------------------
# compile watcher: retrace attribution
# ---------------------------------------------------------------------------


def test_compile_watcher_names_fn_and_diffs_avals():
    watcher = MS.install_compile_watcher()
    assert watcher is not None

    def obsprobe_fn(x):
        return x * 2 + 1

    f = jax.jit(obsprobe_fn)
    f(jnp.ones((5,)))
    f(jnp.ones((9,)))  # retrace: shape change
    evs = [e for e in watcher.snapshot() if e["fn"] == "obsprobe_fn"]
    assert len(evs) >= 2
    assert evs[0]["diff"] == "first compile"
    assert "->" in evs[-1]["diff"] and evs[-1]["nth_for_fn"] >= 2
    assert evs[-1]["elapsed_s"] >= 0
    # the registry counters moved
    from paddlefleetx_tpu.utils.telemetry import get_registry

    assert get_registry().value("pfx_compile_events_total") >= 2


def test_diff_avals_shapes():
    assert MS.diff_avals(None, ["f32[4]"]) == "first compile"
    assert MS.diff_avals(["f32[4]"], ["f32[8]"]) == "arg0: f32[4] -> f32[8]"
    assert MS.diff_avals(["a"], ["a", "b"]) == "arg count 1 -> 2"
    assert "same avals" in MS.diff_avals(["a"], ["a"])
    many = MS.diff_avals(["a"] * 6, ["b"] * 6)
    assert "+3 more" in many


# ---------------------------------------------------------------------------
# engine integration: cadence, record shape, zero-extra-dispatch contract
# ---------------------------------------------------------------------------


def _engine_cfg(tmp_path, tag, **engine_overrides):
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    eng = {
        "max_steps": 4, "eval_freq": 0, "logging_freq": 2,
        "mix_precision": {"enable": False},
        "save_load": {"save_steps": 0, "output_dir": str(tmp_path / f"o{tag}")},
        "metrics_file": str(tmp_path / f"metrics{tag}.jsonl"),
    }
    eng.update(engine_overrides)
    cfg = AttrDict.from_nested({
        "Global": {"global_batch_size": 16, "micro_batch_size": 1, "seed": 7},
        "Engine": eng,
        # same tiny shape as tests/test_engine.py::tiny_cfg so compiles
        # ride the shared persistent cache
        "Model": {
            "module": "GPTModule", "vocab_size": 128, "hidden_size": 64,
            "num_layers": 2, "num_attention_heads": 8,
            "max_position_embeddings": 32, "hidden_dropout_prob": 0.0,
            "attention_probs_dropout_prob": 0.0, "dtype": "float32",
        },
        "Distributed": {},
        "Optimizer": {"name": "FusedAdamW",
                      "lr": {"name": "Constant", "learning_rate": 3e-3}},
    })
    return process_configs(cfg, num_devices=8)


def _batches(n, poison_at=None):
    rng = np.random.default_rng(0)
    out = []
    for i in range(n):
        mask = np.ones((16, 32), np.float32)
        if poison_at is not None and i == poison_at:
            mask = np.full((16, 32), np.nan, np.float32)
        out.append({
            "tokens": rng.integers(0, 128, (16, 32)).astype(np.int64),
            "labels": rng.integers(0, 128, (16, 32)).astype(np.int64),
            "loss_mask": mask,
            "position_ids": np.tile(np.arange(32), (16, 1)),
        })
    return out


@pytest.fixture
def engine_env(devices8):
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env

    def build(cfg):
        mesh = init_dist_env(cfg)
        module = build_module(cfg)
        return mesh, Engine(cfg, module, mesh)

    return build


def test_engine_records_carry_model_stats_mem_and_gauges(tmp_path, engine_env):
    """Default-on observatory: logged records carry the per-group stats
    (stats step == the logged step at every=1), the memory block, and
    the registry group gauges; non-finite steps carry provenance."""
    cfg = _engine_cfg(tmp_path, "a", logging={"model_stats_every": 1})
    mesh, engine = engine_env(cfg)
    assert engine.model_stats_every == 1
    with mesh:
        engine.fit(_batches(4, poison_at=2))

    records = [json.loads(x) for x in open(cfg.Engine.metrics_file)]
    steps = {r["step"]: r for r in records if "loss" in r}
    assert sorted(steps) == [2, 4]
    for step, rec in steps.items():
        ms = rec["model_stats"]
        assert ms["step"] == step  # every=1: the logged step's own stats
        assert ms["groups"] == ["embed", "block_0", "block_1", "head"]
        for key in ("grad_norm", "param_norm", "update_norm",
                    "update_ratio", "nonfinite_frac"):
            assert len(ms[key]) == 4, (key, ms)
        assert "mem" in rec and rec["mem"]["host_rss_bytes"] > 0
        assert rec["mem"]["fit_peak_bytes"] >= rec["mem"]["host_rss_bytes"]
    # step 3 was poisoned (found_inf) — step 4's record is healthy again,
    # but the poisoned window's stats flagged block norms as non-finite
    # via provenance on the record logged AT the poisoned step (step 3 is
    # not a logging step here, so provenance rides the rollback path /
    # guard only; assert the healthy records carry finite stats instead)
    assert all(
        math.isfinite(v) for v in steps[2]["model_stats"]["grad_norm"]
    )
    from paddlefleetx_tpu.utils.telemetry import get_registry

    reg = get_registry()
    assert reg.value("pfx_train_group_grad_norm", group="embed") > 0
    assert reg.value("pfx_train_group_update_ratio", group="block_1") > 0
    assert reg.value("pfx_mem_host_rss_bytes") > 0


def test_engine_poisoned_logged_step_names_groups(tmp_path, engine_env):
    """A found_inf step that IS a logging step carries the provenance
    list right on its record (first offending group first)."""
    cfg = _engine_cfg(tmp_path, "b", logging_freq=1)
    mesh, engine = engine_env(cfg)
    with mesh:
        engine.fit(_batches(4, poison_at=1))  # step 2 poisoned + logged
    records = [json.loads(x) for x in open(cfg.Engine.metrics_file)]
    bad = [r for r in records if r.get("found_inf")]
    assert len(bad) == 1 and bad[0]["step"] == 2
    assert bad[0]["nonfinite_groups"][0] == "embed"
    assert set(bad[0]["nonfinite_groups"]) == {
        "embed", "block_0", "block_1", "head",
    }  # a NaN batch poisons every group; order stays canonical


def test_model_stats_every_zero_adds_zero_dispatch_and_sync(tmp_path, engine_env, monkeypatch):
    """THE acceptance assertion: with model_stats_every=0 the fit loop's
    dispatched-computation and host-sync counts equal the pre-observatory
    loop exactly (guard fetches + logging fetches, nothing else), the
    metrics dict is the pre-PR set, and — the companion claim — enabling
    stats changes NEITHER count (stats ride the existing fetches)."""
    counts = {}

    def run(tag, **overrides):
        cfg = _engine_cfg(tmp_path, tag, **overrides)
        mesh, engine = engine_env(cfg)
        real_step = engine._train_step
        real_get = jax.device_get
        n = {"dispatch": 0, "get": 0}

        def counting_step(*a, **k):
            n["dispatch"] += 1
            return real_step(*a, **k)

        def counting_get(x):
            n["get"] += 1
            return real_get(x)

        engine._train_step = counting_step
        monkeypatch.setattr(jax, "device_get", counting_get)
        try:
            with mesh:
                engine.fit(_batches(4))
        finally:
            monkeypatch.setattr(jax, "device_get", real_get)
        counts[tag] = (n["dispatch"], n["get"])
        return engine

    off = run("off", logging={"model_stats_every": 0})
    assert off._group_spec is None
    on = run("on", logging={"model_stats_every": 1})
    assert on._group_spec is not None

    # pre-observatory loop arithmetic (the PR 2/PR 5 contract): one
    # dispatch per step; one guard fetch per step after the first
    # (anomaly guard observes N-1 after dispatching N); one logging
    # fetch per logging_freq steps.  max_steps=4, logging_freq=2:
    expected = (4, 3 + 2)
    assert counts["off"] == expected, counts
    # stats enabled: identical — provenance rides the guard fetch, the
    # stat vectors ride the logging fetch
    assert counts["on"] == expected, counts

    # the disabled train step's metrics are exactly the pre-PR set
    dev = off._put_batch(_batches(1)[0])
    _, m = off.train_step(off.state, dev)
    assert set(m) == {"loss", "grad_norm", "lr", "found_inf"}
    _, m_on = on.train_step(on.state, dev)
    assert {"group_nonfinite", "model_stats"} <= set(m_on)


# ---------------------------------------------------------------------------
# tools/report.py — unit (synthetic artifacts)
# ---------------------------------------------------------------------------


class _StrictHTML(HTMLParser):
    VOID = {"meta", "br", "hr", "img", "input", "link", "line", "rect",
            "polyline", "circle", "path"}

    def __init__(self):
        super().__init__(convert_charrefs=True)
        self.stack = []
        self.errors = []

    def handle_starttag(self, tag, attrs):
        if tag not in self.VOID:
            self.stack.append(tag)

    def handle_endtag(self, tag):
        if tag in self.VOID:
            return
        if not self.stack or self.stack[-1] != tag:
            self.errors.append(f"unbalanced </{tag}> (stack {self.stack[-3:]})")
        else:
            self.stack.pop()


def _validate_html(doc):
    p = _StrictHTML()
    p.feed(doc)
    assert not p.errors, p.errors
    assert doc.startswith("<!doctype html>")
    assert "http://" not in doc and "https://" not in doc.replace(
        "https://ui.perfetto.dev", ""
    ), "report must be self-contained (no external refs)"


def _synthetic_artifacts(tmp_path):
    metrics = tmp_path / "metrics.jsonl"
    rows = []
    groups = ["embed", "block_0", "head"]
    for step in range(1, 7):
        rows.append({
            "step": step, "loss": 5.0 - 0.3 * step, "lr": 1e-3,
            "grad_norm": 1.0, "ips": 1000.0, "tokens_per_sec": 1000.0,
            "mfu": 0.31, "data_wait_s": 0.01 * step,
            "mem": {"host_rss_bytes": 1 << 28, "fit_peak_bytes": 1 << 28},
            "model_stats": {
                "step": step, "groups": groups,
                "grad_norm": [0.5, 0.4, 0.1],
                "param_norm": [2.0, 3.0, 1.0],
                "update_norm": [0.1, 0.1, 0.05],
                "update_ratio": [0.05, 0.03, 0.05],
                "nonfinite_frac": [0.0, 0.0, 0.0],
            },
        })
    rows.append({"event": "rollback", "step": 4, "reason": "nan streak",
                 "ckpt": "step_2", "rewound": True,
                 "nonfinite_groups": ["embed"]})
    metrics.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    flight = tmp_path / "flight_recorder.jsonl"
    fl = [{"event": "flight_recorder_dump", "reason": "unit", "ts": 10.0,
           "pid": 1, "events": 2},
          # flight-ring copy of a step record: its ts must backfill the
          # (ts-less) metrics-stream record so compile events land on
          # the step axis even when the metrics file wins the merge
          {"event": "step", "step": 3, "loss": 4.1, "ts": 10.4, "seq": 0},
          {"event": "compile", "fn": "train_step", "elapsed_s": 4.2,
           "diff": "first compile", "ts": 10.5, "seq": 1},
          {"event": "preempt_save", "step": 6, "cause": "preemption signal",
           "ckpt": "step_6", "ts": 11.0, "seq": 2}]
    flight.write_text("\n".join(json.dumps(r) for r in fl) + "\n")
    trace = tmp_path / "trace.json"
    trace.write_text(json.dumps({"traceEvents": [
        {"ph": "X", "ts": 0, "dur": 1000, "pid": 1, "tid": 1, "name": "s"},
    ]}))
    return metrics, flight, trace


def test_report_renders_synthetic_html_and_md(tmp_path):
    import report as report_mod

    metrics, flight, trace = _synthetic_artifacts(tmp_path)
    out = tmp_path / "r.html"
    rc = report_mod.main([
        "--metrics", str(metrics), "--flight", str(flight),
        "--trace", str(trace), "-o", str(out),
    ])
    assert rc == 0
    doc = out.read_text()
    _validate_html(doc)
    for needle in ("<svg", "loss", "rollback", "preempt", "block_0",
                   "train_step", "Summary"):
        assert needle in doc, needle
    # the compile event mapped onto the step axis (via the flight step
    # copy's backfilled ts) and rendered as a curve marker
    assert "compile train_step" in doc
    # the metrics-stream record still won the merge (loss 3.5-ish, not
    # the flight copy's 4.1)
    import report as rmod

    data = rmod.RunData()
    data.add_metrics(str(metrics))
    data.add_flight(str(flight))
    assert data.records[3]["loss"] == pytest.approx(5.0 - 0.3 * 3)
    assert data.records[3]["ts"] == 10.4
    # markdown flavor
    out_md = tmp_path / "r.md"
    assert report_mod.main(["--metrics", str(metrics), "-o", str(out_md)]) == 0
    md = out_md.read_text()
    assert "## Summary" in md and "| embed |" in md


def test_report_no_inputs_is_loud_nonzero(tmp_path):
    import report as report_mod

    rc = report_mod.main(["--run-dir", str(tmp_path / "nope"),
                          "-o", str(tmp_path / "x.html")])
    assert rc == 2


# ---------------------------------------------------------------------------
# CLI drills: provenance through the real trainer + report end-to-end
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def drill_corpus(tmp_path_factory):
    from paddlefleetx_tpu.data.gpt_dataset import write_synthetic_corpus

    data = tmp_path_factory.mktemp("obs_corpus")
    write_synthetic_corpus(str(data / "corp"), vocab_size=128, num_docs=16)
    return str(data)


def _cli_run(corpus, out_dir, metrics, max_steps=6, fault=None, extra=(),
             check=True, env_extra=None):
    overrides = [
        "Model.num_layers=2", "Model.hidden_size=32",
        "Model.num_attention_heads=4", "Model.vocab_size=128",
        "Model.max_position_embeddings=32",
        "Global.global_batch_size=8", "Global.local_batch_size=8",
        "Global.micro_batch_size=8",
        f"Engine.max_steps={max_steps}", "Engine.logging_freq=1",
        "Engine.eval_freq=0", "Engine.mix_precision.enable=False",
        "Engine.save_load.save_steps=2",
        "Engine.save_load.auto_resume=True",
        f"Engine.save_load.output_dir={out_dir}",
        f"Engine.metrics_file={metrics}",
        f"Data.Train.dataset.input_dir={corpus}",
        "Data.Train.dataset.max_seq_len=32",
    ] + list(extra)
    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PFX_FAULT", None)
    if fault:
        env["PFX_FAULT"] = fault
    env.update(env_extra or {})
    cmd = [sys.executable, os.path.join(REPO, "tools", "train.py"), "-c",
           os.path.join(REPO, "configs/gpt/pretrain_gpt_345M_single.yaml")]
    for o in overrides:
        cmd += ["-o", o]
    out = subprocess.run(
        cmd, capture_output=True, text=True, timeout=420, cwd=REPO, env=env
    )
    if check:
        assert out.returncode == 0, (out.returncode, out.stderr[-2000:])
    return out


def _render_report(args, out_path):
    cmd = [sys.executable, os.path.join(REPO, "tools", "report.py"),
           "-o", str(out_path)] + args
    res = subprocess.run(cmd, capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, (res.returncode, res.stderr[-1500:])
    doc = out_path.read_text()
    _validate_html(doc)
    return doc


@pytest.mark.fault
@pytest.mark.slow  # ~18s CLI drill; tier-1 budget funding for the
# shard_map-port tests.  Replacement coverage: the nan rollback path stays
# tier-1-drilled through the real CLI by test_fault_injection::
# test_nan_rollback_rewind_replay_parity, and the per-group non-finite
# provenance (first offender named, canonical order) stays unit-asserted
# by the group_nonfinite/model_stats units above; still in make test-all
# and any `-m fault` run.
def test_nan_rollback_drill_names_group_in_event_flight_and_report(
    drill_corpus, tmp_path
):
    """PFX_FAULT=nan_grads drill (the acceptance scenario): the rollback
    event AND the flight postmortem name the first non-finite layer
    group, and the offline report renders the rollback annotation."""
    out = tmp_path / "out"
    metrics = str(tmp_path / "metrics.jsonl")
    run = _cli_run(
        drill_corpus, str(out), metrics, fault="nan_grads:3:1",
        extra=("Engine.resilience.max_skip_streak=1",),
    )
    log = run.stdout + run.stderr
    assert "first non-finite group(s): embed" in log, log[-2000:]

    events = [json.loads(line) for line in open(metrics)]
    rollbacks = [e for e in events if e.get("event") == "rollback"]
    assert len(rollbacks) == 1
    assert rollbacks[0]["nonfinite_groups"][0] == "embed"
    assert "block_0" in rollbacks[0]["nonfinite_groups"]

    # flight postmortem (dumped by _rollback into output_dir) carries it
    flight = out / "flight_recorder.jsonl"
    assert flight.exists()
    fl_events = [json.loads(line) for line in open(flight)]
    fl_rb = [e for e in fl_events if e.get("event") == "rollback"]
    assert fl_rb and fl_rb[0]["nonfinite_groups"][0] == "embed"
    # ...and compile events made it into the ring (retrace attribution)
    assert any(e.get("event") == "compile" for e in fl_events), \
        [e.get("event") for e in fl_events][:10]

    doc = _render_report(
        ["--metrics", metrics, "--flight", str(flight)],
        tmp_path / "report.html",
    )
    assert "rollback" in doc and "embed" in doc


@pytest.mark.slow  # ~12s; report rendering stays tier-1-drilled by
# test_report_from_crashed_preempted_run (the HARDER contract: render
# from the flight ring alone, no metrics file) plus the strict-HTML
# renderer units; still in make test-obs / test-all (PR 8 tier-1 budget
# convention)
@pytest.mark.fault
def test_report_from_real_12_step_run(drill_corpus, tmp_path):
    """Acceptance: a real 12-step CLI run's artifacts render into a valid
    self-contained report with curves + per-group heatmap."""
    out = tmp_path / "out"
    metrics = str(tmp_path / "metrics.jsonl")
    run = _cli_run(drill_corpus, str(out), metrics, max_steps=12)
    assert "run report: python tools/report.py" in run.stdout + run.stderr
    doc = _render_report(["--metrics", metrics], tmp_path / "report.html")
    for needle in ("<svg", "block_0", "block_1", "embed", "head",
                   "12 records", "grad norm by layer group"):
        assert needle in doc, needle
    # the loss curve is real: the summary carries a finite final loss
    assert "final loss" in doc


@pytest.mark.fault
def test_report_from_crashed_preempted_run(drill_corpus, tmp_path):
    """Acceptance: a preempted (crashed) run — report renders from the
    flight dump ALONE (no metrics file configured), naming the preempt."""
    out = tmp_path / "out"
    run = _cli_run(
        drill_corpus, str(out), metrics="", fault="sigterm:3",
        extra=("Engine.metrics_file=",),
    )
    assert "exiting cleanly" in run.stdout + run.stderr
    flight = out / "flight_recorder.jsonl"
    assert flight.exists()  # _preempt_save dumped the ring
    doc = _render_report(["--flight", str(flight)], tmp_path / "report.html")
    assert "preempt" in doc
    assert "no metrics JSONL given" in doc  # loud note, not a crash
    # the ring's step records backfilled the curves
    assert "<svg" in doc and "steps logged" in doc
