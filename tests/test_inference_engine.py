"""InferenceEngine tests: export->reload->predict, precision paths, TP serving."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from paddlefleetx_tpu.core.inference_engine import CompileConfig, InferenceEngine
from paddlefleetx_tpu.models.gpt import model as gpt
from paddlefleetx_tpu.models.gpt.config import GPTConfig
from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
from paddlefleetx_tpu.parallel.sharding import make_rules, tree_logical_to_sharding

TINY = GPTConfig(
    vocab_size=64,
    hidden_size=32,
    num_layers=2,
    num_attention_heads=4,
    max_position_embeddings=32,
    hidden_dropout_prob=0.0,
    attention_probs_dropout_prob=0.0,
    dtype="float32",
)


def _fwd(params, tokens):
    return gpt.forward(params, tokens, TINY, train=False)


def test_live_predict_and_benchmark():
    params = gpt.init(TINY, jax.random.key(0))
    eng = InferenceEngine(_fwd, params, compile_cfg=CompileConfig(precision="fp32"))
    tokens = np.zeros((2, 16), np.int32)
    out = eng.predict(tokens)
    assert out.shape == (2, 16, 64)
    stats = eng.benchmark(tokens, iters=3)
    assert stats["latency_ms"] > 0 and stats["qps"] > 0


def test_export_reload_predict(tmp_path):
    from paddlefleetx_tpu.utils.export import export_inference_model

    params = gpt.init(TINY, jax.random.key(1))
    tokens = jnp.zeros((2, 16), jnp.int32)
    ref = _fwd(params, tokens)
    out_dir = str(tmp_path / "export")
    export_inference_model(_fwd, (tokens,), params, out_dir)

    eng = InferenceEngine.from_export(out_dir, compile_cfg=CompileConfig(precision="fp32"))
    out = eng.predict(np.zeros((2, 16), np.int32))
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=1e-5, atol=1e-5)

    # the DEFAULT precision (bf16) must not break exported serving: the
    # artifact pins fp32 avals, so from_export overrides precision
    eng2 = InferenceEngine.from_export(out_dir)
    out2 = eng2.predict(np.zeros((2, 16), np.int32))
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out2), rtol=1e-5, atol=1e-5)


def test_precision_paths():
    params = gpt.init(TINY, jax.random.key(2))
    tokens = np.zeros((2, 16), np.int32)
    ref = np.asarray(_fwd(params, jnp.asarray(tokens)))

    bf16 = InferenceEngine(_fwd, params, compile_cfg=CompileConfig(precision="bf16"))
    out_bf16 = np.asarray(bf16.predict(tokens), np.float32)
    assert np.max(np.abs(out_bf16 - ref)) / (np.abs(ref).max() + 1e-9) < 0.1

    int8 = InferenceEngine(_fwd, params, compile_cfg=CompileConfig(precision="int8"))
    out_int8 = np.asarray(int8.predict(tokens), np.float32)
    assert np.max(np.abs(out_int8 - ref)) / (np.abs(ref).max() + 1e-9) < 0.2


def test_tp_serving_parity(devices8):
    """mp=4 served logits == single-device logits (the reference runs
    multi-process mp inference via its NCCL ring CSV; here it is the mesh)."""
    params = gpt.init(TINY, jax.random.key(3))
    tokens = np.zeros((4, 16), np.int32)
    ref = np.asarray(_fwd(params, jnp.asarray(tokens)))

    mesh = build_mesh(MeshConfig(dp_degree=2, mp_degree=4))
    rules = make_rules()
    shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(TINY), mesh, rules)
    eng = InferenceEngine(
        _fwd, params,
        mesh=mesh,
        param_shardings=shardings,
        batch_spec=NamedSharding(mesh, P("data")),
        compile_cfg=CompileConfig(precision="fp32"),
    )
    out = np.asarray(eng.predict(tokens))
    np.testing.assert_allclose(ref, out, rtol=2e-4, atol=2e-4)


def test_donate_args_decode_cache():
    """CompileConfig.donate_args: a donated KV-cache argument is consumed
    in place (deleted after the call) while params survive; benchmark()
    re-copies the donated buffer per iteration so repeats don't hand the
    jit a dead buffer."""
    from paddlefleetx_tpu.models.gpt.generation import (
        GenerationConfig,
        generate,
        init_cache,
    )

    params = gpt.init(TINY, jax.random.key(5))
    gen = GenerationConfig(max_dec_len=4, decode_strategy="greedy_search", eos_token_id=-1)

    def decode(p, tokens, cache):
        # returning the final cache is what makes the donation usable:
        # XLA aliases the donated input pair to this output
        return generate(p, tokens, TINY, gen, cache=cache, return_cache=True)

    eng = InferenceEngine(
        decode, params,
        compile_cfg=CompileConfig(precision="fp32", donate_args=(1,)),
    )
    tokens = jnp.zeros((2, 8), jnp.int32)
    cache = init_cache(TINY, 2, 8 + 4)
    ref = np.asarray(generate(params, tokens, TINY, gen))
    out, _cache_out = eng.predict(tokens, cache)
    np.testing.assert_array_equal(np.asarray(out), ref)
    assert cache.k.is_deleted(), "donated cache must be consumed by the call"
    assert not jax.tree.leaves(eng.params)[0].is_deleted()

    # benchmark() must survive donation (fresh copy per iter)
    stats = eng.benchmark(tokens, init_cache(TINY, 2, 8 + 4), iters=2)
    assert stats["latency_ms"] > 0

    import pytest as _pytest

    with _pytest.raises(ValueError, match="donate_args"):
        CompileConfig(donate_args=(-1,))


def test_donate_args_with_mesh_batch_spec(devices8):
    """donate_args composes with the mesh/batch_spec path: batch_spec as a
    per-argument tuple (tokens, cache) keeps in_shardings aligned with the
    3-arg call while the cache is donated."""
    from paddlefleetx_tpu.models.gpt.generation import (
        GenerationConfig,
        generate,
        init_cache,
    )

    params = gpt.init(TINY, jax.random.key(6))
    gen = GenerationConfig(max_dec_len=4, decode_strategy="greedy_search", eos_token_id=-1)

    def decode(p, tokens, cache):
        return generate(p, tokens, TINY, gen, cache=cache, return_cache=True)

    mesh = build_mesh(MeshConfig(dp_degree=2, mp_degree=4), devices8)
    rules = make_rules()
    shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(TINY), mesh, rules)
    eng = InferenceEngine(
        decode, params,
        mesh=mesh,
        param_shardings=shardings,
        batch_spec=(
            NamedSharding(mesh, P("data")),
            NamedSharding(mesh, P(None, "data")),  # cache batch axis 1
        ),
        compile_cfg=CompileConfig(precision="fp32", donate_args=(1,)),
    )
    tokens = jnp.zeros((2, 8), jnp.int32)
    ref = np.asarray(generate(params, tokens, TINY, gen))
    out, _ = eng.predict(tokens, init_cache(TINY, 2, 8 + 4))
    np.testing.assert_array_equal(np.asarray(out), ref)
