"""GLUE datasets, metrics, and GPT finetune module tests."""

import numpy as np
import pytest

from paddlefleetx_tpu.data.glue_dataset import (
    GLUEDataset,
    TASK_METRICS,
    write_synthetic_glue_task,
)
from paddlefleetx_tpu.models.metrics import (
    Accuracy,
    AccuracyAndF1,
    Mcc,
    MultiLabelsMetric,
    PearsonAndSpearman,
    build_metric,
    format_metric,
)


def test_accuracy():
    m = Accuracy()
    m.update(np.array([[0.9, 0.1], [0.2, 0.8]]), np.array([0, 0]))
    assert m.accumulate() == 0.5
    m.reset()
    m.update(np.array([1, 1]), np.array([1, 1]))
    assert m.accumulate() == 1.0


def test_accuracy_and_f1_matches_sklearn_formulas():
    preds = np.array([1, 1, 0, 1, 0, 0, 1, 0])
    labels = np.array([1, 0, 0, 1, 1, 0, 1, 1])
    m = AccuracyAndF1()
    m.update(preds, labels)
    acc, p, r, f1, avg = m.accumulate()
    # tp=3 fp=1 fn=2 tn=2
    assert acc == pytest.approx(5 / 8)
    assert p == pytest.approx(3 / 4)
    assert r == pytest.approx(3 / 5)
    assert f1 == pytest.approx(2 * (3 / 4) * (3 / 5) / (3 / 4 + 3 / 5))
    assert avg == pytest.approx((acc + f1) / 2)


def test_mcc_known_value():
    # perfectly correlated -> 1.0; anti-correlated -> -1.0
    m = Mcc()
    m.update(np.array([1, 0, 1, 0]), np.array([1, 0, 1, 0]))
    assert m.accumulate() == pytest.approx(1.0)
    m.reset()
    m.update(np.array([1, 0, 1, 0]), np.array([0, 1, 0, 1]))
    assert m.accumulate() == pytest.approx(-1.0)


def test_pearson_spearman():
    m = PearsonAndSpearman()
    x = np.array([1.0, 2.0, 3.0, 4.0, 5.0])
    m.update(x, 2 * x + 1)  # perfect linear
    pear, spear, avg = m.accumulate()
    assert pear == pytest.approx(1.0)
    assert spear == pytest.approx(1.0)
    m.reset()
    m.update(x, np.array([1.0, 4.0, 9.0, 16.0, 25.0]))  # monotone nonlinear
    pear, spear, _ = m.accumulate()
    assert spear == pytest.approx(1.0)
    assert pear < 1.0


def test_multilabels_micro_macro():
    m = MultiLabelsMetric(num_labels=3)
    m.update(np.array([0, 1, 2, 1, 0]), np.array([0, 1, 1, 1, 2]))
    micro_p, micro_r, micro_f = m.accumulate(average="micro")
    assert micro_p == pytest.approx(3 / 5)
    p1, r1, f1 = m.accumulate(pos_label=1)
    assert p1 == pytest.approx(1.0) and r1 == pytest.approx(2 / 3)
    macro = m.accumulate(average="macro")
    assert len(macro) == 3


def test_metric_registry_and_format():
    m = build_metric({"name": "AccuracyAndF1"})
    m.update(np.array([1, 0]), np.array([1, 0]))
    d = format_metric(m)
    assert set(d) == {"acc", "precision", "recall", "f1", "acc_and_f1"}
    assert d["acc"] == 1.0


def test_glue_dataset_gpt_style(tmp_path):
    root = write_synthetic_glue_task(str(tmp_path / "sst2"), "sst2", n=32)
    ds = GLUEDataset(task="SST-2", root=root, max_seq_len=32, style="gpt")
    assert len(ds) == 32
    item = ds[0]
    assert item["tokens"].shape == (32,)
    assert 0 <= item["cls_position"] < 32
    assert item["labels"] in (0, 1)
    # cls_position points at the last non-pad token
    n = int(item["cls_position"]) + 1
    assert (item["tokens"][n:] == 0).all()


def test_glue_dataset_bert_style(tmp_path):
    root = write_synthetic_glue_task(str(tmp_path / "sst2"), "sst2", n=16)
    ds = GLUEDataset(task="sst2", root=root, max_seq_len=32, style="bert")
    item = ds[0]
    assert item["input_ids"][0] == ds.cls_id
    live = int(item["attention_mask"].sum())
    assert item["input_ids"][live - 1] == ds.sep_id
    assert set(TASK_METRICS) == {
        "cola", "sst2", "mrpc", "stsb", "qqp", "mnli", "qnli", "rte", "wnli",
    }


@pytest.mark.slow  # ~36s learning curve; the glue surface stays tier-1
# via the dataset builders + every metric unit in this file, and the
# finetune Engine path shares the GPT train step the engine suites
# drill; still in make test-all (PR 8 tier-1 budget convention)
def test_gpt_finetune_learns(tmp_path):
    """End-to-end: tiny GPT finetune on synthetic SST-2 via the Engine, with
    metric-streaming eval; accuracy must beat chance."""
    import jax

    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.data.builders import build_dataloader
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import get_config
    import os

    root = write_synthetic_glue_task(str(tmp_path / "sst2"), "sst2", n=64, seed=3)
    cfg = get_config(
        os.path.join(os.path.dirname(__file__), "..", "configs/gpt/finetune_gpt_345M_glue.yaml"),
        overrides=[
            "Global.global_batch_size=16",
            "Global.local_batch_size=2",
            "Global.micro_batch_size=2",
            "Engine.max_steps=30",
            "Engine.eval_freq=0",
            "Engine.logging_freq=10",
            "Engine.save_load.save_steps=0",
            "Model.vocab_size=30100",
            "Model.hidden_size=64",
            "Model.num_layers=2",
            "Model.num_attention_heads=4",
            "Model.max_position_embeddings=64",
            "Model.attn_impl=xla",
            "Model.hidden_dropout_prob=0.0",
            "Model.attention_probs_dropout_prob=0.0",
            f"Data.Train.dataset.root={root}",
            "Data.Train.dataset.max_seq_len=32",
            f"Data.Eval.dataset.root={root}",
            "Data.Eval.dataset.max_seq_len=32",
            "Optimizer.lr.learning_rate=1.0e-3",
            "Optimizer.lr.total_steps=30",
        ],
    )
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    with mesh:
        engine = Engine(cfg, module, mesh)
        train_loader = build_dataloader(cfg, "Train")
        engine.fit(train_loader)
        eval_loader = build_dataloader(cfg, "Eval")
        metric = module.build_metric()
        assert metric is not None
        # manual metric pass (evaluate() logs it; assert via direct stream)
        import numpy as np

        preds_fn = jax.jit(lambda p, b: module.predict_fn(p, b, ctx=engine.ctx))
        for i, batch in enumerate(eval_loader):
            if i >= 4:
                break
            dev = engine._put_batch(batch)
            metric.update(np.asarray(preds_fn(engine.state.params, dev)), batch["labels"])
        acc = metric.accumulate()
        assert acc > 0.8, f"finetune failed to learn: acc={acc}"
