"""Ulysses (sep-axis alltoall) + ring attention parity tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.models.gpt import model as gpt
from paddlefleetx_tpu.models.gpt.config import GPTConfig
from paddlefleetx_tpu.ops.attention import xla_attention
from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
from paddlefleetx_tpu.parallel.ring_attention import ring_attention
from paddlefleetx_tpu.parallel.sharding import make_rules, tree_logical_to_sharding

# whole file runs in ~17s warm on a 1-core CPU mesh: context parallelism
# belongs in the default safety net (was blanket-marked slow until round 4)

TINY = GPTConfig(
    vocab_size=128,
    hidden_size=64,
    num_layers=2,
    num_attention_heads=8,
    max_position_embeddings=64,
    hidden_dropout_prob=0.0,
    attention_probs_dropout_prob=0.0,
    dtype="float32",
)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_xla(devices8, causal):
    mesh = build_mesh(MeshConfig(sep_degree=4, dp_degree=2), devices8)
    b, s, n, d = 2, 64, 4, 16
    key = jax.random.key(0)
    q = jax.random.normal(key, (b, s, n, d), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s, n, d), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s, n, d), jnp.float32)
    ref = xla_attention(q, k, v, causal=causal)
    with mesh:
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_ring_attention_grads_match(devices8):
    mesh = build_mesh(MeshConfig(sep_degree=4, dp_degree=2), devices8)
    b, s, n, d = 1, 32, 2, 16
    key = jax.random.key(1)
    q = jax.random.normal(key, (b, s, n, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s, n, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s, n, d))
    ct = jax.random.normal(jax.random.fold_in(key, 3), (b, s, n, d))

    g_ref = jax.grad(lambda q, k, v: jnp.sum(xla_attention(q, k, v, causal=True) * ct), (0, 1, 2))(q, k, v)
    with mesh:
        g = jax.jit(
            jax.grad(
                lambda q, k, v: jnp.sum(ring_attention(q, k, v, mesh, causal=True) * ct),
                (0, 1, 2),
            )
        )(q, k, v)
    for a, b_ in zip(g_ref, g):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a), rtol=5e-4, atol=5e-4)


def test_ulysses_layout_loss_parity(devices8):
    """sep-sharded (Ulysses) model loss == single-device loss."""
    params = gpt.init(TINY, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 32), 0, TINY.vocab_size)
    batch = {"tokens": tokens, "labels": jnp.roll(tokens, -1, 1)}
    ref = float(gpt.loss_fn(params, batch, TINY, train=False))

    mesh = build_mesh(MeshConfig(sep_degree=4, dp_degree=2), devices8)
    rules = make_rules()
    shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(TINY), mesh, rules)
    ctx = gpt.ShardingCtx(mesh, rules)
    with mesh:
        got = float(
            jax.jit(lambda p, b: gpt.loss_fn(p, b, TINY, ctx=ctx, train=False))(
                jax.device_put(params, shardings), batch
            )
        )
    np.testing.assert_allclose(got, ref, rtol=2e-5)


def test_ring_model_loss_parity(devices8):
    """attn_impl='ring' over sep mesh == single-device xla attention model."""
    cfg_ring = GPTConfig(**{**TINY.__dict__, "attn_impl": "ring"})
    params = gpt.init(TINY, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 32), 0, TINY.vocab_size)
    batch = {"tokens": tokens, "labels": jnp.roll(tokens, -1, 1)}
    ref = float(gpt.loss_fn(params, batch, TINY, train=False))

    mesh = build_mesh(MeshConfig(sep_degree=4, dp_degree=2), devices8)
    rules = make_rules()
    shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(TINY), mesh, rules)
    ctx = gpt.ShardingCtx(mesh, rules)
    with mesh:
        got = float(
            jax.jit(lambda p, b: gpt.loss_fn(p, b, cfg_ring, ctx=ctx, train=False))(
                jax.device_put(params, shardings), batch
            )
        )
    np.testing.assert_allclose(got, ref, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_chunked_parity(devices8, causal):
    """chunk_k bounds the per-ring-step score buffer; values and grads
    must match the unchunked ring exactly (same online-softmax math)."""
    mesh = build_mesh(MeshConfig(sep_degree=2, dp_degree=4), devices8)
    b, s, n, d = 1, 64, 2, 8  # s_local = 32, chunked into 4 x 8
    key = jax.random.key(7)
    q = jax.random.normal(key, (b, s, n, d), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s, n, d), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s, n, d), jnp.float32)
    ct = jax.random.normal(jax.random.fold_in(key, 3), (b, s, n, d), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) * ct)

    with mesh:
        ref_fn = lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal, chunk_k=None)
        got_fn = lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal, chunk_k=8)
        ref = jax.jit(ref_fn)(q, k, v)
        got = jax.jit(got_fn)(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)
        g_ref = jax.jit(jax.grad(loss(ref_fn), (0, 1, 2)))(q, k, v)
        g_got = jax.jit(jax.grad(loss(got_fn), (0, 1, 2)))(q, k, v)
    for a, b_ in zip(g_ref, g_got):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a), rtol=1e-4, atol=1e-4)
    # non-dividing / too-small chunks silently fall back to unchunked
    with mesh:
        fb = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal, chunk_k=7))(q, k, v)
    np.testing.assert_allclose(np.asarray(fb), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_attention_zigzag_positions_parity(devices8):
    """Permuted (zigzag) feeds with explicit positions produce exactly the
    contiguous result, just reordered: out_zz[:, inv] == out for both the
    values and the gradients."""
    from paddlefleetx_tpu.parallel.ring_attention import zigzag_permutation

    ring = 4
    mesh = build_mesh(MeshConfig(sep_degree=ring, dp_degree=2), devices8)
    b, s, n, d = 1, 64, 2, 8
    key = jax.random.key(3)
    q = jax.random.normal(key, (b, s, n, d), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s, n, d), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s, n, d), jnp.float32)

    perm = np.asarray(zigzag_permutation(s, ring))
    inv = np.argsort(perm)
    with mesh:
        ref = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=True))(q, k, v)
        zz = jax.jit(
            lambda q, k, v: ring_attention(
                q, k, v, mesh, causal=True, positions=jnp.asarray(perm)
            )
        )(q[:, perm], k[:, perm], v[:, perm])
    np.testing.assert_allclose(
        np.asarray(zz)[:, inv], np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_zigzag_permutation_structure():
    from paddlefleetx_tpu.parallel.ring_attention import zigzag_permutation

    perm = np.asarray(zigzag_permutation(16, 2))
    # device 0 shard = blocks 0 and 3; device 1 shard = blocks 1 and 2
    np.testing.assert_array_equal(perm[:8], [0, 1, 2, 3, 12, 13, 14, 15])
    np.testing.assert_array_equal(perm[8:], [4, 5, 6, 7, 8, 9, 10, 11])
    assert sorted(perm.tolist()) == list(range(16))
    import pytest as _pytest

    with _pytest.raises(ValueError, match="divisible by"):
        zigzag_permutation(10, 4)


@pytest.mark.slow  # ~9s (two engine boots); tier-1 budget funding for
# the shard_map-port tests.  Replacement coverage: the engine's zigzag
# install + ring positions-masking stays tier-1 via the STRICTLY HARDER
# pp2 x sep2 composition (test_engine_zigzag_pp_loss_parity, which also
# asserts the non-parity negative control) and the ring zigzag-positions
# parity test above; still in make test-parallel / test-mid / test-all.
def test_engine_zigzag_loss_parity(devices8, tmp_path):
    """Distributed.sep_zigzag: the engine permutes the batch, ring masks by
    true positions, and the loss matches the contiguous sep layout."""
    import os

    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    def run(zigzag):
        cfg = AttrDict.from_nested(
            {
                "Global": {"global_batch_size": 4, "micro_batch_size": 1, "seed": 7},
                "Engine": {
                    "max_steps": 1, "eval_freq": 0, "logging_freq": 10**9,
                    "mix_precision": {"enable": False},
                    "save_load": {"save_steps": 0},
                },
                "Model": {
                    "module": "GPTModule",
                    "vocab_size": 64, "hidden_size": 32, "num_layers": 2,
                    "num_attention_heads": 4, "max_position_embeddings": 32,
                    "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
                    "attn_impl": "ring", "dtype": "float32",
                },
                "Distributed": {"dp_degree": 4, "sep_degree": 2,
                                "sep_zigzag": zigzag},
                "Optimizer": {"name": "FusedAdamW",
                              "lr": {"name": "Constant", "learning_rate": 1e-4}},
            }
        )
        cfg = process_configs(cfg, num_devices=8)
        mesh = init_dist_env(cfg, devices=jax.devices()[:8])
        module = build_module(cfg)
        rng = np.random.default_rng(0)
        batch = {
            "tokens": rng.integers(0, 64, (4, 32)).astype(np.int64),
            "labels": rng.integers(0, 64, (4, 32)).astype(np.int64),
            "loss_mask": np.ones((4, 32), np.float32),
            "position_ids": np.tile(np.arange(32), (4, 1)),
        }
        with mesh:
            eng = Engine(cfg, module, mesh)
            dev = eng._put_batch(batch)
            eng.state, m = eng.train_step(eng.state, dev)
            return float(m["loss"])

    ref = run(False)
    zz = run(True)
    # permuted accumulation order shifts fp32 sums by a few ulps
    np.testing.assert_allclose(zz, ref, rtol=2e-4)


def test_engine_zigzag_pp_loss_parity():
    """sep_zigzag composes with pipeline parallelism: ctx.attn_positions
    rides into the 1F1B chunk fns as a stage-replicated constant and ring
    attention nests its sep shard_map inside the stages-manual map.  The
    175B-class layout (VERDICT r3 item 6): pp2 x sep2 x dp2, interleaved
    virtual stages.

    Subprocess-isolated (tests/zigzag_pp_worker.py): the nested
    (stages-manual over sep) shard_map executable is fragile in a
    long-lived CPU test process -- it fails the persistent-cache
    serialization round-trip AND has aborted in XLA CPU runtime deep into
    a full-suite process even cache-disabled (test-std, 2026-07-30); a
    fresh process runs it reliably."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = ""
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tests", "zigzag_pp_worker.py")],
        capture_output=True, text=True, cwd=repo, env=env, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    losses = json.loads(out.stdout.strip().splitlines()[-1])
    ref, zz, bad = losses["ref"], losses["zz"], losses["bad"]
    # correct positions: parity up to permuted-reduction rounding
    np.testing.assert_allclose(zz, ref, atol=2e-5, rtol=0)
    # wrong (storage-order) masking must NOT be parity -- guards against
    # the positions constant silently dropping out of the pipeline path
    assert abs(bad - ref) > 2e-5, (bad, ref)


def test_pipeline_sep_ring_1f1b_grads_match(devices8):
    """1F1B pipeline COMPOSED with nested ring attention (pp2 x sep2 x
    dp2): loss AND per-parameter grads match the single-device reference.

    Regression for the 0.4.x nested-manual backward (code review of the
    shard_map-port PR): the naive all_gather/slice seams left gradients
    sep-rank-varying (own block doubled, other blocks zero — worst rel
    err ~1.2e3) while the LOSS was exact, so a loss-only assertion
    (zigzag_pp_worker's) passed.  The frame-seam custom VJPs in
    ring_attention (_enter_replicated / _gather_replicated) are what this
    test pins — it must assert GRADS, not just loss."""
    from paddlefleetx_tpu.parallel.pipeline import PipelineConfig

    # 2 layers = 1 per stage: the smallest shape that runs both stages'
    # chunk bodies through the nested ring (the bug reproduced identically
    # at any depth; 4 layers only added compile time to tier-1)
    cfg = GPTConfig(**{**TINY.__dict__, "attn_impl": "ring"})
    params = gpt.init(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, 32), 0, cfg.vocab_size)
    batch = {
        "tokens": tokens,
        "labels": jnp.roll(tokens, -1, 1),
        "loss_mask": jnp.ones((8, 32), jnp.float32),
    }
    ref_loss, g_ref = jax.value_and_grad(
        lambda p: gpt.loss_fn(p, batch, cfg, train=True)
    )(params)

    mesh = build_mesh(
        MeshConfig(dp_degree=2, pp_degree=2, sep_degree=2), devices8
    )
    rules = make_rules()
    ctx = gpt.ShardingCtx(mesh, rules, pipeline=PipelineConfig(2, 2))
    shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(cfg), mesh, rules)
    with mesh:
        loss, g = jax.jit(
            jax.value_and_grad(
                lambda p, b: gpt.loss_fn(p, b, cfg, ctx=ctx, train=True)
            )
        )(jax.device_put(params, shardings), batch)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    for a, b_ in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g)):
        np.testing.assert_allclose(
            np.asarray(b_), np.asarray(a), rtol=5e-4, atol=1e-5
        )
