"""Flash-attention kernel vs XLA reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.ops.attention import xla_attention
from paddlefleetx_tpu.ops.flash_attention import flash_attention

# Pallas interpret-mode / big-compile file: excluded from the fast
# subset (pytest -m 'not slow'); run the full suite for release checks
pytestmark = pytest.mark.slow


@pytest.mark.parametrize("b,s,n,d", [(2, 256, 4, 64), (1, 512, 2, 32)])
def test_forward_matches_xla(b, s, n, d):
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, n, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, n, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, n, d), jnp.float32)

    ref = xla_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_grads_match_xla():
    b, s, n, d = 1, 256, 2, 32
    key = jax.random.key(1)
    kq, kk, kv, kg = jax.random.split(key, 4)
    q = jax.random.normal(kq, (b, s, n, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, n, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, n, d), jnp.float32)
    ct = jax.random.normal(kg, (b, s, n, d), jnp.float32)

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True) * ct)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) * ct)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a), rtol=5e-4, atol=5e-4)


def test_causality():
    """Changing future tokens must not affect earlier outputs."""
    b, s, n, d = 1, 256, 2, 32
    key = jax.random.key(2)
    q = jax.random.normal(key, (b, s, n, d), jnp.float32)
    k, v = q + 1.0, q - 1.0
    out1 = flash_attention(q, k, v)
    k2 = k.at[:, -1].set(99.0)
    v2 = v.at[:, -1].set(99.0)
    out2 = flash_attention(q, k2, v2)
    np.testing.assert_allclose(np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]), rtol=1e-5)


def test_bf16_runs():
    b, s, n, d = 1, 256, 2, 64
    q = jnp.ones((b, s, n, d), jnp.bfloat16)
    out = flash_attention(q, q, q)
    assert out.dtype == jnp.bfloat16
    assert np.all(np.isfinite(np.asarray(out, np.float32)))


def test_fused_bwd_matches_split(monkeypatch):
    """PFX_FLASH_BWD=fused (single-kernel dq+dk+dv) must reproduce the
    split two-kernel backward exactly up to f32 accumulation order.

    Block 64 at seq 256 gives 4 kv blocks, so the fused kernel's core
    mechanism — the dq slab zeroed at kj==0 and read-modify-written
    across kv-block grid steps — is actually exercised (a single-block
    grid would pass even with broken cross-block accumulation)."""
    monkeypatch.setenv("PFX_FLASH_BLOCK", "64")
    b, s, n, d = 1, 256, 2, 32
    key = jax.random.key(4)
    kq, kk, kv, kg = jax.random.split(key, 4)
    q = jax.random.normal(kq, (b, s, n, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, n, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, n, d), jnp.float32)
    ct = jax.random.normal(kg, (b, s, n, d), jnp.float32)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) * ct)

    monkeypatch.setenv("PFX_FLASH_BWD", "split")
    g_split = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv("PFX_FLASH_BWD", "fused")
    jax.clear_caches()  # the env knob is read at trace time
    g_fused = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    jax.clear_caches()
    for a, b_ in zip(g_split, g_fused):
        np.testing.assert_allclose(
            np.asarray(b_), np.asarray(a), rtol=1e-5, atol=1e-5
        )


def test_fused_bwd_matches_split_bf16(monkeypatch):
    """Same fused-vs-split parity in bfloat16 — the dtype the model path
    actually runs.  With fp32 inputs the kernel-internal bf16 downcasts
    (p_lo/ds in _bwd_tile) are no-ops, so only a bf16 run can catch a
    dtype-handling divergence between the two backward schedules."""
    monkeypatch.setenv("PFX_FLASH_BLOCK", "64")
    b, s, n, d = 1, 256, 2, 32
    key = jax.random.key(6)
    kq, kk, kv, kg = jax.random.split(key, 4)
    q = jax.random.normal(kq, (b, s, n, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, s, n, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, s, n, d), jnp.bfloat16)
    ct = jax.random.normal(kg, (b, s, n, d), jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True).astype(jnp.float32)
            * ct.astype(jnp.float32)
        )

    monkeypatch.setenv("PFX_FLASH_BWD", "split")
    jax.clear_caches()  # the env knob is read at trace time
    g_split = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv("PFX_FLASH_BWD", "fused")
    jax.clear_caches()
    g_fused = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    jax.clear_caches()
    for a, b_ in zip(g_split, g_fused):
        # bf16 grads: both schedules accumulate in f32 but round per-tile,
        # so allow bf16-epsilon-scale slack (2^-8 relative)
        np.testing.assert_allclose(
            np.asarray(b_, np.float32), np.asarray(a, np.float32),
            rtol=2e-2, atol=2e-2
        )


def test_flash_block_env_validation(monkeypatch):
    """Invalid PFX_FLASH_BLOCK values fail loudly with labeled errors, not
    an int() ValueError or an opaque Mosaic compile error (advisor r4)."""
    import pytest

    from paddlefleetx_tpu.ops.flash_attention import _block_sizes

    monkeypatch.delenv("PFX_FLASH_BLOCK_K", raising=False)
    monkeypatch.setenv("PFX_FLASH_BLOCK", "banana")
    with pytest.raises(ValueError, match="PFX_FLASH_BLOCK"):
        _block_sizes(256)
    monkeypatch.setenv("PFX_FLASH_BLOCK", "4")  # divides 256, not mult of 8
    with pytest.raises(ValueError, match="multiple of 8"):
        _block_sizes(256)
    monkeypatch.setenv("PFX_FLASH_BLOCK", "96")  # mult of 8, no divisor
    with pytest.raises(ValueError, match="divisor"):
        _block_sizes(256)
    monkeypatch.setenv("PFX_FLASH_BLOCK", "64")
    assert _block_sizes(256) == (64, 64)
    # asymmetric K/V block: same loud-failure contract, bk-only override
    monkeypatch.setenv("PFX_FLASH_BLOCK_K", "banana")
    with pytest.raises(ValueError, match="PFX_FLASH_BLOCK_K"):
        _block_sizes(256)
    monkeypatch.setenv("PFX_FLASH_BLOCK_K", "96")
    with pytest.raises(ValueError, match="block_k"):
        _block_sizes(256)
    monkeypatch.setenv("PFX_FLASH_BLOCK_K", "128")
    assert _block_sizes(256) == (64, 128)


def test_asymmetric_block_k_matches_reference(monkeypatch):
    """bq != bk (PFX_FLASH_BLOCK_K) must produce the same attention output
    as the symmetric kernel — the causal bounds inside the kernels use
    ceil/floor divisions that have to hold for unequal blocks."""
    import jax

    from paddlefleetx_tpu.ops.flash_attention import flash_attention

    b, s, n, d = 2, 256, 2, 64
    kq, kk, kv = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(kq, (b, s, n, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, n, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, n, d), jnp.float32)

    monkeypatch.delenv("PFX_FLASH_BLOCK_K", raising=False)
    ref = np.asarray(flash_attention(q, k, v, block=64))
    jax.clear_caches()  # env knob is read at trace time
    monkeypatch.setenv("PFX_FLASH_BLOCK_K", "128")
    asym = np.asarray(flash_attention(q, k, v, block=64))
    jax.clear_caches()
    np.testing.assert_allclose(asym, ref, rtol=1e-5, atol=1e-5)

    # gradients too: both backward schedules consume block_k
    def loss(mode):
        monkeypatch.setenv("PFX_FLASH_BWD", mode)
        jax.clear_caches()
        out = jax.grad(
            lambda qq: flash_attention(qq, k, v, block=64).astype(jnp.float32).sum()
        )(q)
        return np.asarray(out)

    monkeypatch.setenv("PFX_FLASH_BLOCK_K", "")
    g_sym = loss("split")
    monkeypatch.setenv("PFX_FLASH_BLOCK_K", "128")
    g_asym_split = loss("split")
    g_asym_fused = loss("fused")
    jax.clear_caches()
    np.testing.assert_allclose(g_asym_split, g_sym, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(g_asym_fused, g_sym, rtol=1e-5, atol=1e-5)


def test_config_knobs_reach_kernel(monkeypatch):
    """Model.flash_block / Model.flash_bwd thread through the GPT model to
    the kernel (loss parity with the defaults proves the plumbed kernel
    actually ran with valid parameters)."""
    from paddlefleetx_tpu.models.gpt import model as M
    from paddlefleetx_tpu.models.gpt.config import GPTConfig

    toks = jax.random.randint(jax.random.key(11), (2, 256), 0, 64)
    batch = {"tokens": toks, "labels": jnp.roll(toks, -1, axis=1)}
    losses = {}
    for name, kw in {
        "default": {},
        "block64_fused": {"flash_block": 64, "flash_bwd": "fused"},
        # asymmetric K block (PFX_FLASH_BLOCK_K) through the model path:
        # config bq=64 + env bk=128 must hit the same loss
        "block64_bk128": {"flash_block": 64, "_env_bk": "128"},
    }.items():
        env_bk = kw.pop("_env_bk", None)
        if env_bk is not None:
            # monkeypatch (not raw os.environ): a mid-loop assert must not
            # leak PFX_FLASH_BLOCK_K into later tests in this process
            monkeypatch.setenv("PFX_FLASH_BLOCK_K", env_bk)
            jax.clear_caches()  # env knob is read at trace time
        cfg = GPTConfig(
            vocab_size=64, hidden_size=32, num_layers=2,
            num_attention_heads=4, max_position_embeddings=256,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
            dtype="float32", attn_impl="flash", **kw,
        )
        params = M.init(cfg, jax.random.key(0))
        loss, grads = jax.value_and_grad(
            lambda p: M.loss_fn(p, batch, cfg, train=True)
        )(params)
        assert np.isfinite(float(loss))
        losses[name] = float(loss)
        if env_bk is not None:
            monkeypatch.delenv("PFX_FLASH_BLOCK_K")
            jax.clear_caches()
    np.testing.assert_allclose(
        losses["block64_fused"], losses["default"], rtol=1e-5
    )
    np.testing.assert_allclose(
        losses["block64_bk128"], losses["default"], rtol=1e-5
    )
    with pytest.raises(ValueError, match="flash_bwd"):
        GPTConfig(num_layers=2, flash_bwd="fuse")


def test_bf16_accuracy_vs_f32_reference():
    """The kernels keep MXU dots in the input dtype (bf16 on the model
    path) with fp32 accumulation; bf16 outputs must still track the fp32
    XLA reference to bf16 resolution (~3 decimal digits)."""
    b, s, n, d = 2, 256, 2, 64
    key = jax.random.key(3)
    kq, kk, kv, kg = jax.random.split(key, 4)
    q = jax.random.normal(kq, (b, s, n, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, n, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, n, d), jnp.float32)
    ct = jax.random.normal(kg, (b, s, n, d), jnp.float32)

    ref = xla_attention(q, k, v, causal=True)
    got = flash_attention(
        q.astype(jnp.bfloat16), k.astype(jnp.bfloat16), v.astype(jnp.bfloat16)
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref), rtol=0.0, atol=0.05
    )

    def loss_flash_bf16(q, k, v):
        out = flash_attention(
            q.astype(jnp.bfloat16), k.astype(jnp.bfloat16), v.astype(jnp.bfloat16)
        )
        return jnp.sum(out.astype(jnp.float32) * ct)

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True) * ct)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_flash_bf16, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gr, gf):
        np.testing.assert_allclose(
            np.asarray(b_, np.float32), np.asarray(a), rtol=0.0, atol=0.35
        )


def test_block_k_override_loud_on_unsupported_seq(monkeypatch):
    """ADVICE r5: a set-but-invalid PFX_FLASH_BLOCK_K must fail loudly on
    EVERY path, including the unsupported-seq fallback (e.g. seq=1000
    misses the ladder) — not be silently dropped with the ladder."""
    from paddlefleetx_tpu.ops.flash_attention import _block_sizes, flash_supported

    monkeypatch.setenv("PFX_FLASH_BLOCK_K", "not-an-int")
    with pytest.raises(ValueError, match="PFX_FLASH_BLOCK_K"):
        _block_sizes(1000)
    monkeypatch.setenv("PFX_FLASH_BLOCK_K", "256")  # does not divide 1000
    with pytest.raises(ValueError, match="divisor"):
        flash_supported(1000)
    # a VALID override on an unsupported seq is ignored with the rest of
    # the ladder (the XLA fallback has no blocks to apply it to)
    monkeypatch.setenv("PFX_FLASH_BLOCK_K", "8")  # divides 1000, mult of 8
    assert not flash_supported(1000)
    monkeypatch.delenv("PFX_FLASH_BLOCK_K")
    assert not flash_supported(1000)
