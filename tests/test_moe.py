"""MoE tests: gating/capacity mechanics, aux loss, expert-parallel parity."""

import jax
import jax.numpy as jnp
import numpy as np

from paddlefleetx_tpu.models.gpt import model as gpt
from paddlefleetx_tpu.models.gpt.config import GPTConfig
from paddlefleetx_tpu.models.gpt.moe import gate_and_dispatch
from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
from paddlefleetx_tpu.parallel.sharding import make_rules, tree_logical_to_sharding

MOE = GPTConfig(
    vocab_size=128,
    hidden_size=64,
    num_layers=2,
    num_attention_heads=8,
    max_position_embeddings=32,
    hidden_dropout_prob=0.0,
    attention_probs_dropout_prob=0.0,
    dtype="float32",
    num_experts=4,
    moe_gate="gshard",
)


def test_dispatch_respects_capacity():
    n, e, c = 16, 2, 3
    x = jnp.ones((n, 8))
    # all tokens prefer expert 0
    logits = jnp.tile(jnp.asarray([[5.0, 0.0]]), (n, 1))
    combine, dispatch, aux = gate_and_dispatch(x, logits, e, 1, c, "switch")
    # expert 0 gets exactly capacity tokens, rest dropped
    assert int(dispatch[:, 0, :].sum()) == c
    assert float(aux) > 1.0  # heavily imbalanced -> aux above uniform value


def test_aux_loss_uniform_is_one():
    n, e = 1024, 4
    key = jax.random.key(0)
    logits = jax.random.normal(key, (n, e)) * 0.01  # ~uniform gating
    _, _, aux = gate_and_dispatch(jnp.ones((n, 8)), logits, e, 1, n, "switch")
    assert abs(float(aux) - 1.0) < 0.1


def test_combine_weights_sum_to_one_when_kept():
    n, e, c = 32, 4, 32
    key = jax.random.key(1)
    logits = jax.random.normal(key, (n, e))
    combine, dispatch, _ = gate_and_dispatch(jnp.ones((n, 8)), logits, e, 2, c, "gshard")
    sums = np.asarray(combine.sum(axis=(1, 2)))
    np.testing.assert_allclose(sums, 1.0, atol=1e-5)


def test_moe_model_trains():
    params = gpt.init(MOE, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, MOE.vocab_size)
    batch = {"tokens": tokens, "labels": jnp.roll(tokens, -1, 1)}
    loss, grads = jax.value_and_grad(lambda p: gpt.loss_fn(p, batch, MOE, train=False))(params)
    assert np.isfinite(float(loss))
    # expert + gate params receive gradient
    gnorm = jnp.sqrt(
        sum(jnp.sum(g**2) for g in jax.tree.leaves(grads["layers"]["mlp"]))
    )
    assert float(gnorm) > 0


def test_moe_expert_parallel_parity(devices8):
    """Expert-sharded loss == single-device loss."""
    params = gpt.init(MOE, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, MOE.vocab_size)
    batch = {"tokens": tokens, "labels": jnp.roll(tokens, -1, 1)}
    ref = float(gpt.loss_fn(params, batch, MOE, train=False))

    for mesh_cfg in [MeshConfig(dp_degree=4, mp_degree=2), MeshConfig(dp_degree=8)]:
        mesh = build_mesh(mesh_cfg, devices8)
        rules = make_rules(mesh=mesh, num_experts=MOE.num_experts)
        shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(MOE), mesh, rules)
        p_sharded = jax.device_put(params, shardings)
        ctx = gpt.ShardingCtx(mesh, rules)
        with mesh:
            got = float(
                jax.jit(lambda p, b: gpt.loss_fn(p, b, MOE, ctx=ctx, train=False))(
                    p_sharded, batch
                )
            )
        np.testing.assert_allclose(got, ref, rtol=2e-5, err_msg=str(mesh_cfg))


def test_naive_gate_no_aux():
    cfg = GPTConfig(**{**MOE.__dict__, "moe_gate": "naive"})
    params = gpt.init(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    batch = {"tokens": tokens, "labels": jnp.roll(tokens, -1, 1)}
    loss = gpt.loss_fn(params, batch, cfg, train=False)
    assert np.isfinite(float(loss))
