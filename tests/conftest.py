"""Test harness: run everything on a virtual 8-device CPU mesh.

This is the mock-multinode capability the reference lacks (SURVEY.md §4):
every parallel layout (dp/tp/pp/sp/ep) runs as a multi-device unit test on
one host, numerics asserted against single-device references.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402
import pytest  # noqa: E402

# The image's sitecustomize force-registers the 'axon' TPU platform ahead of
# env vars, so pin the platform via jax.config (must run before backend init).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# Persistent compilation cache: XLA-CPU compiles dominate suite wall-clock
# (a resnet18 engine test spends >70s compiling on one core); cached repeat
# runs skip them. Keyed by jaxlib version internally, safe to keep around.
_cache_dir = os.environ.get(
    "PFX_TEST_CACHE", os.path.join(os.path.dirname(__file__), ".jax_cache")
)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

# Subprocess-based tests (golden-doc walkthroughs, config launches, bench
# contracts, distributed workers) each boot a fresh python that never sees
# the jax.config lines above — export the same cache dir through the
# environment so their XLA compiles hit the shared persistent cache too.
# setdefault: an explicit caller override always wins.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1.0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")


@pytest.fixture
def devices8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs[:8]


def _has_jax09_shard_map() -> bool:
    """True when this jax carries the 0.9-era ``jax.shard_map(axis_names=,
    check_vma=)`` API that parallel/pipeline.py + ring_attention.py target
    (jax 0.4.x only has jax.experimental.shard_map, whose lowering cannot
    express the partial-auto schedules — see the ROADMAP open item)."""
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        return False
    try:
        import inspect

        return "check_vma" in inspect.signature(fn).parameters
    except (TypeError, ValueError):  # builtins/uninspectable: assume new
        return True


def pytest_collection_modifyitems(config, items):
    """`requires_jax09`-marked tests skip-with-reason on old jax instead of
    erroring: tier-1 then reports one clean, greppable signal for the
    known shard_map-port gap rather than scattered AttributeErrors."""
    if _has_jax09_shard_map():
        return
    skip = pytest.mark.skip(
        reason=(
            f"requires jax>=0.9 jax.shard_map(axis_names=, check_vma=); "
            f"installed jax {jax.__version__} cannot lower these schedules "
            "(ROADMAP: port pipeline/ring_attention off the 0.9 API)"
        )
    )
    for item in items:
        if "requires_jax09" in item.keywords:
            item.add_marker(skip)
