"""Test harness: run everything on a virtual 8-device CPU mesh.

This is the mock-multinode capability the reference lacks (SURVEY.md §4):
every parallel layout (dp/tp/pp/sp/ep) runs as a multi-device unit test on
one host, numerics asserted against single-device references.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402
import pytest  # noqa: E402

# The image's sitecustomize force-registers the 'axon' TPU platform ahead of
# env vars, so pin the platform via jax.config (must run before backend init).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# Persistent compilation cache: XLA-CPU compiles dominate suite wall-clock
# (a resnet18 engine test spends >70s compiling on one core); cached repeat
# runs skip them. Keyed by jaxlib version internally, safe to keep around.
# The 0.1s persist threshold (was 1.0) also banks the long tail of 0.1-1s
# compiles scattered across ~600 small tests — measured ~18% off a warm
# jit-heavy file pair, bought for ~100MB of cache dir (shard_map-port PR:
# the un-skipped pipeline/ring/golden tests made the 870s tier-1 budget
# tight enough that the tail matters).
_cache_dir = os.environ.get(
    "PFX_TEST_CACHE", os.path.join(os.path.dirname(__file__), ".jax_cache")
)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

# Subprocess-based tests (golden-doc walkthroughs, config launches, bench
# contracts, distributed workers) each boot a fresh python that never sees
# the jax.config lines above — export the same cache dir through the
# environment so their XLA compiles hit the shared persistent cache too.
# setdefault: an explicit caller override always wins.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")


@pytest.fixture
def devices8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs[:8]


def _has_jax09_shard_map() -> bool:
    """True when this jax carries the 0.9-era ``jax.shard_map(axis_names=,
    check_vma=)`` API.  The parallel schedules no longer need it — they run
    on 0.4.x through the full-manual port (parallel/shard_map_compat.py) —
    so no shipped test carries the marker today; the gate stays for any
    future test that exercises a genuinely 0.9-only API."""
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        return False
    try:
        import inspect

        return "check_vma" in inspect.signature(fn).parameters
    except (TypeError, ValueError):  # builtins/uninspectable: assume new
        return True


def pytest_collection_modifyitems(config, items):
    """`requires_jax09`-marked tests skip-with-reason on old jax instead of
    erroring.  Since the shard_map port (parallel/shard_map_compat.py)
    every schedule lowers on 0.4.x too, so the marker guards only genuinely
    0.9-only API tests — currently none; a test regaining the marker must
    justify the residual skip."""
    if _has_jax09_shard_map():
        return
    skip = pytest.mark.skip(
        reason=(
            f"exercises a jax>=0.9-only API with no 0.4.x port "
            f"(installed jax {jax.__version__}); the shard_map schedules "
            "themselves run via parallel/shard_map_compat — a test wearing "
            "this marker must document why it cannot"
        )
    )
    for item in items:
        if "requires_jax09" in item.keywords:
            item.add_marker(skip)
