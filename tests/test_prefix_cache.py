"""Shared-prefix KV reuse units (`core/paged_cache.py`): block
refcounting on the allocator, the radix prefix index (block-aligned
trie + partial leaves + copy-on-write matches), LRU/leaf-first
eviction under a block budget, and the manager's shared admission with
evict-on-demand.  Pure host bookkeeping — no jax — so the whole file
rides the fast gate; the device-side parity suite lives in
tests/test_continuous_batching.py and `make test-prefix` runs both."""

import pytest

from paddlefleetx_tpu.core.paged_cache import (
    BlockAllocator,
    BlockPoolExhausted,
    PagedCacheManager,
    PrefixIndex,
)

# ---------------------------------------------------------------------------
# allocator refcounts
# ---------------------------------------------------------------------------


def test_share_then_free_ordering_keeps_block_alive():
    a = BlockAllocator(6)
    (b,) = a.alloc(1)
    a.share([b])  # second owner (e.g. the prefix index)
    assert a.refcount(b) == 2
    a.free([b])  # first owner releases: block must STAY allocated
    assert a.refcount(b) == 1
    assert a.used_count() == 1
    # the block cannot be handed out while referenced
    assert b not in a.alloc(4)
    a.free([b])  # last reference: NOW it reclaims
    assert a.refcount(b) == 0
    assert b in a.alloc(1) + a._free


def test_overfree_past_refcount_is_loud():
    a = BlockAllocator(4)
    (b,) = a.alloc(1)
    a.share([b])
    a.free([b])
    a.free([b])
    with pytest.raises(ValueError, match="double free"):
        a.free([b])


def test_share_free_or_bad_block_is_loud_and_atomic():
    a = BlockAllocator(6)
    got = a.alloc(2)
    with pytest.raises(ValueError, match="cannot share free block"):
        a.share([got[0], 4])  # 4 was never allocated
    # atomic: the valid id took no reference either
    assert a.refcount(got[0]) == 1
    with pytest.raises(ValueError, match="null block"):
        a.share([0])
    with pytest.raises(ValueError, match="out of range"):
        a.share([99])
    with pytest.raises(ValueError, match="out of range"):
        a.refcount(99)


def test_used_count_is_physical_not_reference_weighted():
    """The shared-block accounting contract: occupancy/byte gauges count
    a physical block ONCE no matter how many tables share it — a naive
    per-row summation would overstate arena occupancy and trip the
    controller's occupancy-driven scale-up spuriously."""
    a = BlockAllocator(8)
    got = a.alloc(3)
    for _ in range(4):  # 4 more rows share the same 3 blocks
        a.share(got)
    assert a.refcount(got[0]) == 5
    assert a.used_count() == 3  # physical, not 15
    assert a.used_count() + a.free_count() == 7  # never exceeds the arena


# ---------------------------------------------------------------------------
# radix prefix index
# ---------------------------------------------------------------------------

BS = 8  # small block for readable token math


def _index(num_blocks=32, budget=16):
    a = BlockAllocator(num_blocks)
    return a, PrefixIndex(a, BS, budget)


def _seq(n, start=0):
    return list(range(start, start + n))


def test_publish_and_full_block_match():
    a, idx = _index()
    table = a.alloc(3)
    prompt = _seq(20)  # 2 full blocks + 4-token tail
    assert idx.publish(prompt, table) == 3
    assert idx.cached_blocks() == 3
    # index holds one ref on each published block, the row still holds its own
    assert all(a.refcount(b) == 2 for b in table)
    a.free(table)  # row finishes: blocks survive via the index refs
    assert a.used_count() == 3

    shared, cow, m = idx.match(prompt + [99, 98])
    assert shared == table[:2] and m == 20
    assert cow == (table[2], 4)  # partial tail reused via COW
    # match() is pure; the caller commits the accounting once the
    # admission lands (a failed allocation must not desync the stats)
    assert idx.stats["hits"] == 0
    idx.record_lookup(m)
    assert idx.stats["hits"] == 1 and idx.stats["hit_tokens"] == 20


def test_match_always_leaves_one_suffix_token():
    """A full-prompt match must cap at len-1: admission needs the last
    prompt token's logits, so at least one token always recomputes."""
    a, idx = _index()
    table = a.alloc(2)
    prompt = _seq(16)  # exactly 2 full blocks
    idx.publish(prompt, table)
    shared, cow, m = idx.match(prompt)
    assert m == 15  # not 16
    assert shared == table[:1]
    assert cow == (table[1], 7)


def test_cow_divergence_inside_full_block():
    a, idx = _index()
    table = a.alloc(2)
    prompt = _seq(16)
    idx.publish(prompt, table)
    # diverges at token 11: block 0 matches whole, block 1 matches 3 tokens
    other = _seq(11) + [77, 78, 79, 80, 81, 82]
    shared, cow, m = idx.match(other)
    assert shared == table[:1]
    assert cow == (table[1], 3)
    assert m == 11


def test_divergence_inside_first_block_is_cow_only():
    a, idx = _index()
    table = a.alloc(1)
    idx.publish(_seq(8), table)
    shared, cow, m = idx.match([0, 1, 2, 99, 98, 97])
    assert shared == [] and cow == (table[0], 3) and m == 3


def test_miss_counts_and_no_overlap():
    a, idx = _index()
    idx.publish(_seq(8), a.alloc(1))
    shared, cow, m = idx.match([50, 51, 52, 53])
    assert (shared, cow, m) == ([], None, 0)
    idx.record_lookup(m)
    assert idx.stats["misses"] == 1


def test_republish_dedupes_and_bumps_not_duplicates():
    a, idx = _index()
    t1 = a.alloc(3)
    prompt = _seq(20)
    idx.publish(prompt, t1)
    t2 = a.alloc(3)  # a second row that computed the same prefix privately
    assert idx.publish(prompt, t2) == 0  # nothing new cached
    assert idx.cached_blocks() == 3
    # the duplicate row's blocks took no index reference
    assert all(a.refcount(b) == 1 for b in t2)


def test_lru_eviction_is_leaf_first_and_budget_bounded():
    a, idx = _index(budget=3)
    chain = a.alloc(3)
    idx.publish(_seq(24), chain)  # 3-node chain, exactly at budget
    a.free(chain)
    other = a.alloc(1)
    idx.publish(_seq(8, start=100), other)  # 4th block: over budget
    a.free(other)
    assert idx.cached_blocks() == 3
    assert idx.stats["evictions"] == 1
    # the CHAIN's leaf (oldest) went, never an interior node before it:
    # the surviving chain still matches its first two blocks
    shared, _, m = idx.match(_seq(24))
    assert m >= 16


def test_eviction_never_reclaims_a_live_rows_block():
    a, idx = _index(num_blocks=6, budget=4)
    table = a.alloc(2)
    idx.publish(_seq(16), table)
    # a live row shares the cached blocks (refcount 2 each)
    a.share(table)
    a.free(table)  # original publisher released
    # pressure: demand every block in the pool
    idx.evict_for(need_free=5)
    assert idx.cached_blocks() == 0  # index dropped its references...
    assert a.used_count() == 2       # ...but the live row's blocks SURVIVE
    assert a.free_count() == 3
    a.free(table)  # live row done: now they reclaim
    assert a.free_count() == 5


def test_clear_empties_index_and_is_not_an_eviction():
    a, idx = _index()
    idx.publish(_seq(20), a.alloc(3))
    ev0 = idx.stats["evictions"]
    assert idx.clear() == 3
    assert idx.cached_blocks() == 0 and idx.stats["evictions"] == ev0
    assert idx.match(_seq(20))[2] == 0  # cleared prefixes never resurface


def test_disabled_index_never_caches():
    a, idx = _index(budget=0)
    assert not idx.enabled
    assert idx.publish(_seq(20), a.alloc(3)) == 0
    assert idx.cached_blocks() == 0


# ---------------------------------------------------------------------------
# manager: shared admission + evict-on-demand
# ---------------------------------------------------------------------------


def test_manager_shared_admit_and_release():
    m = PagedCacheManager(10, block=16, prefix_blocks=8)
    t1 = m.admit(1, 40)  # 3 blocks
    m.prefix.publish(list(range(40)), t1)
    m.release(1)
    assert m.stats()["kv_blocks_used"] == 3
    assert m.stats()["prefix_cached_blocks"] == 3
    shared, cow, hit = m.prefix.match(list(range(36)) + [99, 98])
    t2 = m.admit(2, 40, shared=shared)
    assert t2[: len(shared)] == shared
    assert len(t2) == 3
    # physical accounting: 2 shared + 1 fresh + 1 cached partial = 4
    assert m.stats()["kv_blocks_used"] == 4
    m.release(2)
    assert m.stats()["kv_blocks_used"] == 3  # cache refs remain


def test_manager_admit_evicts_cached_prefixes_before_failing():
    m = PagedCacheManager(5, block=16, prefix_blocks=4)  # 4 usable
    t1 = m.admit(1, 64)  # all 4 blocks
    m.prefix.publish(list(range(64)), t1)
    m.release(1)
    assert m.allocator.free_count() == 0
    assert m.available_blocks() == 4  # all cached, all reclaimable
    t2 = m.admit(2, 48, shared=[])  # needs 3: must evict 3 cached blocks
    assert len(t2) == 3
    assert m.prefix.stats["evictions"] >= 3


def test_manager_admit_exhaustion_returns_shared_refs_atomically():
    m = PagedCacheManager(4, block=16, prefix_blocks=3)
    t1 = m.admit(1, 48)  # all 3 usable blocks
    m.prefix.publish(list(range(40)), t1)
    # live row 1 still holds everything: nothing is reclaimable
    shared = [t1[0]]
    with pytest.raises(BlockPoolExhausted):
        m.admit(2, 64, shared=shared)
    # atomic: the failed admission returned its shared reference (the
    # pressure pass legitimately dropped the INDEX's refs trying to make
    # room, so only live row 1 holds the blocks now)
    assert m.allocator.refcount(t1[0]) == 1
    assert m.prefix.cached_blocks() == 0
    m.release(1)
    assert m.stats()["kv_blocks_used"] == 0
