"""Worker for test_engine_zigzag_pp_loss_parity (subprocess-isolated).

The pp2 x sep2 nested-shard_map executable is fragile inside a long-lived
CPU test process: with the persistent compilation cache it fails the
serialization round-trip (warm rerun SIGABRTs), and even cache-disabled it
has aborted in XLA CPU runtime after ~190 prior tests' worth of in-process
state (test-std, 2026-07-30).  A fresh process runs it reliably, so the
pytest wrapper execs this worker and parses the three losses.

Prints one JSON line: {"ref": float, "zz": float, "bad": float}.
"""

import json
import os
import sys


def main() -> None:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    # jax 0.9: the nested-map executable failed the persistent-cache
    # serialization round-trip (warm rerun SIGABRT), so the cache was
    # disabled here.  The 0.4.x full-manual lowering (shard_map_compat) is
    # a different executable that round-trips fine — verified by repeated
    # warm runs — and cache hits cut this worker from ~59s to ~28s of the
    # tier-1 budget; keep the cache off only on the jax-0.9 branch.  ONE
    # detection for the version split: the adapter's flag (its import only
    # inspects jax.shard_map's signature — no backend/config state touched,
    # so it is safe after the jax.config lines above).
    from paddlefleetx_tpu.parallel.shard_map_compat import HAS_JAX09_SHARD_MAP

    if HAS_JAX09_SHARD_MAP:
        jax.config.update("jax_enable_compilation_cache", False)

    import dataclasses

    import numpy as np
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    def run(zigzag, sabotage=False):
        cfg = AttrDict.from_nested(
            {
                "Global": {"global_batch_size": 8, "micro_batch_size": 4, "seed": 7},
                "Engine": {
                    "max_steps": 1, "eval_freq": 0, "logging_freq": 10**9,
                    "mix_precision": {"enable": False},
                    "save_load": {"save_steps": 0},
                },
                "Model": {
                    "module": "GPTModule",
                    "vocab_size": 64, "hidden_size": 32, "num_layers": 4,
                    "num_attention_heads": 4, "max_position_embeddings": 32,
                    "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
                    "attn_impl": "ring", "dtype": "float32",
                },
                "Distributed": {
                    "dp_degree": 2, "pp_degree": 2, "sep_degree": 2,
                    "sep_zigzag": zigzag,
                    "pipeline": {"micro_batches": 2, "virtual_pp_degree": 2},
                },
                "Optimizer": {"name": "FusedAdamW",
                              "lr": {"name": "Constant", "learning_rate": 1e-4}},
            }
        )
        cfg = process_configs(cfg, num_devices=8)
        mesh = init_dist_env(cfg, devices=jax.devices()[:8])
        module = build_module(cfg)
        rng = np.random.default_rng(0)
        batch = {
            "tokens": rng.integers(0, 64, (8, 32)).astype(np.int64),
            "labels": rng.integers(0, 64, (8, 32)).astype(np.int64),
            "loss_mask": np.ones((8, 32), np.float32),
            "position_ids": np.tile(np.arange(32), (8, 1)),
        }
        with mesh:
            eng = Engine(cfg, module, mesh)
            if zigzag:
                # eager install must have fired with a non-identity perm
                assert eng._zigzag_perm is not None
                assert not np.array_equal(eng._zigzag_perm, np.arange(32))
            if sabotage:
                # negative control: what a stale positions-less graph would
                # compute — causal mask by storage order on permuted data
                eng.ctx = dataclasses.replace(eng.ctx, attn_positions=None)
                eng._train_step = eng._build_train_step()
            dev = eng._put_batch(batch)
            eng.state, m = eng.train_step(eng.state, dev)
            return float(m["loss"])

    print(json.dumps({
        "ref": run(False),
        "zz": run(True),
        "bad": run(True, sabotage=True),
    }), flush=True)


if __name__ == "__main__":
    main()
