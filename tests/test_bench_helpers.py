"""Unit tests for the shared bench helpers in bench.py.

host_fence is the single audited timing fence for every benchmark
(BENCH_NOTE.md round 5: jax.block_until_ready has been observed
returning while device work is still pending under the axon runtime,
so all timed loops fence with a device->host fetch instead).
"""

import sys
import pytest
import os

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import host_fence, model_flops_per_token  # noqa: E402


def test_host_fence_returns_one_element():
    out = jax.jit(lambda x: x * 2)(jnp.arange(12.0).reshape(3, 4))
    got = host_fence(out)
    assert isinstance(got, np.ndarray)
    assert got.size == 1
    assert got[0] == 0.0


def test_host_fence_pytree():
    # benches fence jit outputs that are dicts/tuples of arrays; the fence
    # fetches from the first leaf regardless of structure
    out = jax.jit(lambda x: {"loss": x.sum(), "ids": x.astype(jnp.int32)})(
        jnp.ones((2, 3))
    )
    got = host_fence(out)
    assert got.size == 1


def test_host_fence_completes_computation():
    # assert on the fence's OWN return value: it must have fetched the
    # computed buffer (a no-op fence cannot produce the right number)
    x = jnp.full((64, 64), 3.0)
    out = jax.jit(lambda a: a @ a)(x)
    np.testing.assert_allclose(host_fence(out)[0], 3.0 * 3.0 * 64)


def test_model_flops_per_token_scales_with_depth():
    one = model_flops_per_token(1024, 24, 50304, 1024)
    two = model_flops_per_token(1024, 48, 50304, 1024)
    # doubling layers should roughly double per-token FLOPs (the embedding
    # head term is shared, so strictly less than 2x)
    assert one < two < 2 * one


def test_backend_fallback_repoints_at_cpu(monkeypatch):
    """SATELLITE (dead-backend laps): when the TPU probe fails, the
    child repoints PFX_PLATFORM at cpu and proceeds — an honest row on
    the backend that exists, never a value-0.0 placeholder."""
    import bench

    monkeypatch.setenv("PFX_PLATFORM", "tpu")
    monkeypatch.setattr(bench, "wait_for_backend", lambda: False)
    note = bench.ensure_backend_or_fallback()
    assert "falling back to the cpu backend" in note
    assert os.environ["PFX_PLATFORM"] == "cpu"


def test_backend_fallback_noop_when_reachable_or_pinned(monkeypatch):
    import bench

    # reachable backend: no fallback, platform untouched
    monkeypatch.setenv("PFX_PLATFORM", "tpu")
    monkeypatch.setattr(bench, "wait_for_backend", lambda: True)
    assert bench.ensure_backend_or_fallback() == ""
    assert os.environ["PFX_PLATFORM"] == "tpu"
    # explicitly pinned non-TPU platform (CI smoke): never probed
    monkeypatch.setenv("PFX_PLATFORM", "cpu")
    monkeypatch.setattr(
        bench, "wait_for_backend",
        lambda: (_ for _ in ()).throw(AssertionError("probed a pinned cpu")),
    )
    assert bench.ensure_backend_or_fallback() == ""


def test_ring_row_contract():
    """SATELLITE (shard_map-port PR): the long-context ring case's row
    contract — null vs_baseline (no published CP reference), a fallback
    shape that shrinks heads/dim/steps but NEVER the sequence (seq >= 4096
    IS the case), and honest rows that parse."""
    import bench

    row = bench._honest_ring_row("some reason")
    assert row["metric"] == bench.RING_METRIC
    assert row["value"] == 0.0
    assert row["vs_baseline"] is None
    assert "some reason" in row["unit"]
    # the cpu fallback may shrink everything BUT the sequence
    assert "BENCH_RING_SEQ" not in bench.RING_CPU_FALLBACK_SHAPE
    assert set(bench.RING_CPU_FALLBACK_SHAPE) <= {
        "BENCH_RING_HEADS", "BENCH_RING_DIM", "BENCH_RING_STEPS",
        "BENCH_RING_BATCH", "BENCH_RING_CHUNK",
    }


@pytest.mark.slow  # ~60s: full seq-4096 ring fwd+bwd on a forced 4-device
# CPU mesh in a fresh subprocess; the row-shape contract stays tier-1 via
# test_ring_row_contract
def test_ring_bench_cpu_smoke_emits_platform_labeled_row():
    import json as _json
    import subprocess

    env = dict(os.environ)
    env["PFX_PLATFORM"] = "cpu"
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = ""  # the child forces its own 4-device host
    env.update({"BENCH_RING_STEPS": "1", "BENCH_RING_HEADS": "2",
                "BENCH_RING_DIM": "16"})
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--child-ring"],
        capture_output=True, text=True, cwd=repo, env=env, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    row = _json.loads(out.stdout.strip().splitlines()[-1])
    import bench

    assert row["metric"] == bench.RING_METRIC
    assert row["platform"] == "cpu"
    assert row["seq"] >= 4096
    assert row["ring"] >= 2
    assert row["value"] > 0.0
    assert "cpu" in row["unit"]  # labeled, never reads as chip evidence
