"""Unit tests for the shared bench helpers in bench.py.

host_fence is the single audited timing fence for every benchmark
(BENCH_NOTE.md round 5: jax.block_until_ready has been observed
returning while device work is still pending under the axon runtime,
so all timed loops fence with a device->host fetch instead).
"""

import sys
import os

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import host_fence, model_flops_per_token  # noqa: E402


def test_host_fence_returns_one_element():
    out = jax.jit(lambda x: x * 2)(jnp.arange(12.0).reshape(3, 4))
    got = host_fence(out)
    assert isinstance(got, np.ndarray)
    assert got.size == 1
    assert got[0] == 0.0


def test_host_fence_pytree():
    # benches fence jit outputs that are dicts/tuples of arrays; the fence
    # fetches from the first leaf regardless of structure
    out = jax.jit(lambda x: {"loss": x.sum(), "ids": x.astype(jnp.int32)})(
        jnp.ones((2, 3))
    )
    got = host_fence(out)
    assert got.size == 1


def test_host_fence_completes_computation():
    # assert on the fence's OWN return value: it must have fetched the
    # computed buffer (a no-op fence cannot produce the right number)
    x = jnp.full((64, 64), 3.0)
    out = jax.jit(lambda a: a @ a)(x)
    np.testing.assert_allclose(host_fence(out)[0], 3.0 * 3.0 * 64)


def test_model_flops_per_token_scales_with_depth():
    one = model_flops_per_token(1024, 24, 50304, 1024)
    two = model_flops_per_token(1024, 48, 50304, 1024)
    # doubling layers should roughly double per-token FLOPs (the embedding
    # head term is shared, so strictly less than 2x)
    assert one < two < 2 * one


def test_backend_fallback_repoints_at_cpu(monkeypatch):
    """SATELLITE (dead-backend laps): when the TPU probe fails, the
    child repoints PFX_PLATFORM at cpu and proceeds — an honest row on
    the backend that exists, never a value-0.0 placeholder."""
    import bench

    monkeypatch.setenv("PFX_PLATFORM", "tpu")
    monkeypatch.setattr(bench, "wait_for_backend", lambda: False)
    note = bench.ensure_backend_or_fallback()
    assert "falling back to the cpu backend" in note
    assert os.environ["PFX_PLATFORM"] == "cpu"


def test_backend_fallback_noop_when_reachable_or_pinned(monkeypatch):
    import bench

    # reachable backend: no fallback, platform untouched
    monkeypatch.setenv("PFX_PLATFORM", "tpu")
    monkeypatch.setattr(bench, "wait_for_backend", lambda: True)
    assert bench.ensure_backend_or_fallback() == ""
    assert os.environ["PFX_PLATFORM"] == "tpu"
    # explicitly pinned non-TPU platform (CI smoke): never probed
    monkeypatch.setenv("PFX_PLATFORM", "cpu")
    monkeypatch.setattr(
        bench, "wait_for_backend",
        lambda: (_ for _ in ()).throw(AssertionError("probed a pinned cpu")),
    )
    assert bench.ensure_backend_or_fallback() == ""
