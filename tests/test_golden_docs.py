"""Golden-log docs stay honest: execute the cheap walkthroughs' commands
verbatim and diff the step-loss lines against the doc's expected block
(the reference's runnable-docs-as-tests pattern, SURVEY §4.4).

The fast cases run in the default tier (ViT ~40 s, ERNIE ~90 s, T5
~150 s, DebertaV2 ~65 s, HelixFold tiny ~110 s, Imagen smoke ~95 s, CLIP
smoke ~40 s).  The flagship GPT-345M single-card walkthrough (~9 min)
runs slow-marked in `make test-all`.  The remaining 1.3B/sep4096/MoCo
walkthroughs use the same machinery but cost many minutes or duplicate
an existing CLI gate — their logs were captured the same way and drift
would show up in the gated cases first (shared engine/logging/config
stack).
"""

import os
import re
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STEP_RE = re.compile(r"step \d+/\d+ loss: [\d.]+ lr: [\d.e+-]+ grad_norm: [\d.]+")


def _doc_blocks(path):
    """(bash_blocks, expected_step_lines) from a walkthrough doc.

    Only bash blocks BEFORE the expected-output block are executed — the
    sections after it point at real-chip/real-data launches."""
    with open(path) as f:
        text = f.read()
    # tokenize every fenced block in document order: (language, body)
    blocks = [
        (m.group(1), m.group(2))
        for m in re.finditer(r"```(\w*)\n(.*?)\n```", text, re.S)
    ]
    # bash blocks BEFORE the first expected-output block are the commands;
    # the first non-bash block containing step lines is the golden log.
    # Later (real-chip) sections may show their own sample logs, which a
    # CPU run can never reproduce — never read past the first log block.
    bash, expected = [], []
    for lang, body in blocks:
        if lang == "bash":
            bash.append(body)
        else:
            expected = STEP_RE.findall(body)
            if expected:
                break
    return bash, expected


def _run_doc(path, timeout):
    bash, expected = _doc_blocks(path)
    assert bash and expected, path
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    log = ""
    for block in bash:
        out = subprocess.run(
            ["bash", "-e", "-c", block], capture_output=True, text=True,
            cwd=REPO, env=env, timeout=timeout,
        )
        assert out.returncode == 0, (path, block, out.stderr[-2000:])
        log += out.stdout + out.stderr
    got = STEP_RE.findall(log)
    assert got == expected, (
        f"{path}: doc log lines are stale.\nexpected: {expected}\ngot:      {got}"
    )


# Tier-1 budget (shard_map-port PR, which un-skipped this family on jax
# 0.4.37): the cheap walkthroughs (ViT ~9s, GLUE ~15s warm, plus the
# generation doc below) run tier-1 and keep the doc-freshness machinery +
# the shared engine/config/logging stack gated on every run; the expensive
# ones (T5 ~117s, ERNIE ~85s, DebertaV2 ~70s, HelixFold ~55s, Imagen ~26s,
# CLIP ~13s warm) are slow-marked with replacement coverage: each family's
# OWN tier-1 suite (test_t5, test_ernie incl. pipeline-pretrain parity,
# test_rigid/protein units, test_vision, test_clip) exercises the same
# model/engine paths directly, and walkthrough drift would surface first
# in the tier-1-gated cases through the shared stack — the same argument
# the module docstring already makes for the 1.3B/sep4096/MoCo
# walkthroughs.  All six still run in `make test-parallel` and `make
# test-all`.
_SLOW = pytest.mark.slow


@pytest.mark.parametrize(
    "doc,timeout",
    [
        ("projects/vit/docs/synthetic_ci.md", 600),
        pytest.param("projects/ernie/docs/pretrain_base.md", 900, marks=_SLOW),
        pytest.param("projects/t5/docs/pretrain_base.md", 900, marks=_SLOW),
        pytest.param("projects/debertav2/docs/pretrain_base.md", 900, marks=_SLOW),
        pytest.param("projects/protein_folding/docs/tiny_smoke.md", 900, marks=_SLOW),
        pytest.param("projects/imagen/docs/text2im_smoke.md", 900, marks=_SLOW),
        pytest.param("projects/clip/docs/synthetic_smoke.md", 900, marks=_SLOW),
        ("projects/gpt/docs/finetune_glue.md", 900),
    ],
)
def test_doc_walkthrough_matches_fresh_run(doc, timeout):
    _run_doc(os.path.join(REPO, doc), timeout)


@pytest.mark.slow
def test_flagship_345m_doc_matches_fresh_run():
    """The most-read walkthrough — GPT-345M single-card — re-executed
    verbatim (VERDICT r4 #8: the flagship docs are exactly the ones a
    user runs first, so their expected-log block must not drift).  The
    full-345M 3-step CPU run costs ~3 min, hence the slow tier
    (make test-all)."""
    _run_doc(os.path.join(REPO, "projects/gpt/docs/single_card.md"), 1200)


def test_generation_doc_matches_fresh_run():
    """The generation walkthrough's sampled ids are seed-deterministic;
    a drifted sampler/processor stack changes them."""
    doc = os.path.join(REPO, "projects", "gpt", "docs", "generation.md")
    with open(doc) as f:
        text = f.read()
    m = re.search(r"generated ids: (\[[^\]]*\])", text)
    assert m, doc
    bash = re.findall(r"```bash\n(.*?)```", text, re.S)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    out = subprocess.run(
        ["bash", "-e", "-c", bash[0]], capture_output=True, text=True,
        cwd=REPO, env=env, timeout=600,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    got = re.search(r"generated ids: (\[[^\]]*\])", out.stdout + out.stderr)
    assert got, (out.stdout + out.stderr)[-1500:]
    assert got.group(1) == m.group(1), (got.group(1), m.group(1))
