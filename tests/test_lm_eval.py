"""GPTEvalModule: WikiText-style PPL + LAMBADA accuracy streaming."""

import jax
import numpy as np

from paddlefleetx_tpu.data.gpt_dataset import LambadaEvalDataset, LMEvalDataset
from paddlefleetx_tpu.models.gpt.evaluation import GPTEvalModule, LMEvalMetric
from paddlefleetx_tpu.utils.config import AttrDict


def _cfg_dict():
    return AttrDict(
        {
            "Model": {
                "module": "GPTEvalModule",
                "vocab_size": 128,
                "hidden_size": 32,
                "num_layers": 2,
                "num_attention_heads": 4,
                "max_position_embeddings": 64,
                "dtype": "float32",
                "attn_impl": "xla",
            },
            "Engine": {"mix_precision": {"enable": False}},
        }
    )


def test_lm_eval_metric_ppl_and_acc():
    m = LMEvalMetric()
    # two sequences: nll sums 2.0/4.0 over 2/2 tokens; one all-correct
    m.update(np.array([[2.0, 2.0, 1.0], [4.0, 2.0, 0.0]]))
    out = m.accumulate()
    assert out["ppl"] == np.exp(6.0 / 4.0)
    assert out["acc"] == 0.5
    assert out["tokens"] == 4.0


def test_eval_module_stream():
    cfg = _cfg_dict()
    module = GPTEvalModule(cfg)
    params = module.init_params(jax.random.key(0))
    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(0, 128, (2, 16)),
        "labels": rng.integers(0, 128, (2, 16)),
        "loss_mask": np.ones((2, 16), np.float32),
        "position_ids": np.tile(np.arange(16), (2, 1)),
    }
    batch = {k: jax.numpy.asarray(v) for k, v in batch.items()}
    preds = module.predict_fn(params, batch)
    assert preds.shape == (2, 3)
    metric = module.build_metric()
    metric.update(np.asarray(preds))
    out = metric.accumulate()
    # random model, 128-vocab: ppl near 128, acc ~0
    assert 50 < out["ppl"] < 300
    assert out["tokens"] == 32.0


def test_lambada_dataset_mask_targets_only():
    ctx = np.arange(10, 20)
    tgt = np.array([5, 6])
    ds = LambadaEvalDataset([(ctx, tgt)], seq_len=16)
    item = ds[0]
    # mask covers exactly the positions predicting the target tokens
    assert item["loss_mask"].sum() == 2.0
    lo = len(ctx) - 1
    assert item["loss_mask"][lo] == 1.0 and item["loss_mask"][lo + 1] == 1.0
    # labels at masked positions are the target tokens
    assert item["labels"][lo] == 5 and item["labels"][lo + 1] == 6


def test_wikitext_windows_count_new_tokens_once():
    tokens = np.arange(100)
    ds = LMEvalDataset(tokens, seq_len=32, overlapping_eval=16)
    total_counted = sum(float(ds[i]["loss_mask"].sum()) for i in range(len(ds)))
    # every token (minus the first window's offset) counted exactly once
    assert total_counted <= 99
    assert total_counted >= 99 - 32


def test_perfect_model_gets_full_accuracy():
    """A 'model' that memorizes: check metric wiring end-to-end by feeding
    logits that match labels."""
    m = LMEvalMetric()
    labels = np.array([[1, 2, 3]])
    logits = np.full((1, 3, 8), -10.0, np.float32)
    for i, l in enumerate(labels[0]):
        logits[0, i, l] = 10.0
    lse = np.log(np.exp(logits).sum(-1))
    nll = (lse - np.take_along_axis(logits, labels[..., None], -1)[..., 0]).sum(-1)
    m.update(np.stack([nll, np.full(1, 3.0), np.ones(1)], -1))
    out = m.accumulate()
    assert out["acc"] == 1.0
    assert out["ppl"] < 1.01
