"""tools/lint.py self-test (the reference's codestyle stack ships its own
docstring-checker unit test, /root/reference/codestyle/test_docstring_checker.py
— same idea here)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from lint import check_file  # noqa: E402


def _lint_src(tmp_path, src):
    p = tmp_path / "mod.py"
    p.write_text(src)
    return {code for _, _, code, _ in check_file(str(p))}


def test_detects_unused_import(tmp_path):
    assert "E2" in _lint_src(tmp_path, "import os\nimport sys\n\nprint(sys.argv)\n")


def test_used_dotted_and_aliased_imports_ok(tmp_path):
    src = (
        "import jax.numpy as jnp\n"
        "from typing import Optional\n\n"
        "def f(x: Optional[int]):\n    return jnp.sin(x)\n"
    )
    assert _lint_src(tmp_path, src) == set()


def test_string_annotation_counts_as_use(tmp_path):
    src = (
        "from typing import Mapping\n\n"
        'def f(x: "Mapping[str, int]"):\n    return x\n'
    )
    assert _lint_src(tmp_path, src) == set()


def test_detects_bare_except_eval_tab_trailing_ws_mutable_default(tmp_path):
    src = (
        "def f(x=[]):\n"
        "\ttry:\n"
        "\t\treturn eval('x')   \n"
        "\texcept:\n"
        "\t\tpass\n"
    )
    codes = _lint_src(tmp_path, src)
    assert {"E3", "E4", "E5", "E7", "E8"} <= codes


def test_noqa_suppresses(tmp_path):
    assert _lint_src(tmp_path, "import os  # noqa\n") == set()


def test_syntax_error_reported(tmp_path):
    assert "E1" in _lint_src(tmp_path, "def broken(:\n")


def test_repo_is_clean():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stdout[-2000:]


def test_docstring_mention_does_not_mask_unused_import(tmp_path):
    src = '"""Helpers for os-level work."""\nimport os\n\nprint(1)\n'
    assert "E2" in _lint_src(tmp_path, src)


def test_mutable_default_call_and_lambda(tmp_path):
    assert "E8" in _lint_src(tmp_path, "def f(x=set()):\n    return x\n")
    assert "E8" in _lint_src(tmp_path, "g = lambda x=[]: x\n")
    assert "E8" in _lint_src(tmp_path, "def f(x=dict(a=1)):\n    return x\n")


def test_missing_module_docstring_in_package(tmp_path, monkeypatch):
    # hermetic: point lint.REPO at tmp_path instead of writing a temp
    # module into the real package (which races test_repo_is_clean and
    # leaks the file into the source tree on a hard kill)
    import lint as _lint

    pkg = tmp_path / "paddlefleetx_tpu"
    pkg.mkdir()
    p = pkg / "mod.py"
    p.write_text("x = 1\n")
    monkeypatch.setattr(_lint, "REPO", str(tmp_path))
    codes = {c for _, _, c, _ in check_file(str(p))}
    assert "E9" in codes
    # non-package files are exempt
    q = tmp_path / "m.py"
    q.write_text("x = 1\n")
    assert "E9" not in {c for _, _, c, _ in check_file(str(q))}


def test_metric_name_lint_undeclared_and_malformed(tmp_path):
    # undeclared name handed to a registry accessor
    src = 'reg.counter("pfx_made_up_total").inc()\n'
    assert "E10" in _lint_src(tmp_path, src)
    # schema violation (uppercase) at a registry call site
    src = 'reg.gauge("pfx_BAD_Name").set(1)\n'
    assert "E10" in _lint_src(tmp_path, src)
    # a metric-shaped string literal anywhere (e.g. a StatsView mapping)
    src = 'M = {"requests": "pfx_never_declared_total"}\n'
    assert "E10" in _lint_src(tmp_path, src)


def test_metric_name_lint_declared_names_pass(tmp_path):
    src = (
        'reg.counter("pfx_serving_requests_total").inc()\n'
        'reg.histogram("pfx_request_latency_seconds").observe(0.1)\n'
        '# exposition suffixes resolve to the declared base name\n'
        'x = "pfx_request_latency_seconds_bucket"\n'
        'y = "pfx_serving_requests_total"\n'
        'print(reg, x, y)\n'
    )
    assert "E10" not in _lint_src(tmp_path, src)


def test_metric_name_lint_declared_table_parses():
    # the AST parse of telemetry.METRICS finds the real table
    import lint as _lint

    _lint._declared_metrics = ...  # reset the cache
    names = _lint.declared_metrics()
    assert names and "pfx_serving_requests_total" in names
    assert all(n.startswith("pfx_") for n in names)


def test_metrics_docs_table_parses_and_agrees():
    """E11 happy path on the real repo: the docs table exists and the
    two-way agreement holds (the repo-clean test covers this too, but
    this one names the check)."""
    import lint as _lint

    documented, linenos = _lint.documented_metrics()
    assert documented, "docs/observability.md Metrics reference missing"
    assert documented == _lint.declared_metrics()
    assert all(n in linenos for n in documented)
    assert _lint.check_metrics_docs() == []


def test_metrics_docs_drift_is_detected(tmp_path, monkeypatch):
    """E11 both directions, hermetically: a declared-but-undocumented
    metric and a stale doc row each produce a finding; a missing table
    is itself a finding."""
    import lint as _lint

    pkg = tmp_path / "paddlefleetx_tpu" / "utils"
    pkg.mkdir(parents=True)
    (pkg / "telemetry.py").write_text(
        '"""t."""\nMETRICS = {\n'
        '    "pfx_a_total": ("counter", "a"),\n'  # noqa — fixture table
        '    "pfx_b_total": ("counter", "b"),\n'  # noqa — fixture table
        "}\n"
    )
    docs = tmp_path / "docs"
    docs.mkdir()
    doc = docs / "observability.md"
    doc.write_text(
        "# x\n\n### Metrics reference\n\n"
        "| metric | kind | meaning |\n|---|---|---|\n"
        "| `pfx_a_total` | counter | a |\n"
        "| `pfx_stale_total` | counter | gone |\n\n## next\n"  # noqa
    )
    monkeypatch.setattr(_lint, "REPO", str(tmp_path))
    _lint._declared_metrics = ...  # re-read from the tmp repo
    try:
        findings = _lint.check_metrics_docs()
        codes = {(code, msg.split("'")[1]) for _, _, code, msg in findings}
        assert ("E11", "pfx_b_total") in codes  # noqa — fixture name
        assert ("E11", "pfx_stale_total") in codes  # noqa — fixture name
        # stale rows point at their doc line
        stale = next(f for f in findings if "pfx_stale_total" in f[3])  # noqa
        assert stale[0].endswith("observability.md") and stale[1] > 1
        # a missing table is loud, not silently clean
        doc.write_text("# x\n\nno table here\n")
        missing = _lint.check_metrics_docs()
        assert len(missing) == 1 and "missing" in missing[0][3]
    finally:
        _lint._declared_metrics = ...  # drop the tmp-repo cache


def test_env_knob_docs_agree_on_the_real_repo():
    """E12 happy path: every PFX_* knob referenced in package source has
    a docs table row and no documented knob is stale (the repo-clean
    test covers this too; this one names the check)."""
    import lint as _lint

    knobs = _lint.source_env_knobs()
    assert "PFX_TRACE_SAMPLE" in knobs and "PFX_FAULT" in knobs
    documented, where = _lint.documented_env_knobs()
    assert set(knobs) <= documented
    assert _lint.check_env_knob_docs() == []


def test_env_knob_docs_drift_is_detected(tmp_path, monkeypatch):
    """E12 both directions, hermetically: an undocumented source knob
    and a stale doc row each produce a finding; prefix building blocks
    (trailing underscore) and prose mentions don't count."""
    import lint as _lint

    pkg = tmp_path / "paddlefleetx_tpu"
    pkg.mkdir(parents=True)
    (pkg / "knobs.py").write_text(
        '"""k."""\nimport os\n'
        'A = os.environ.get("PFX_REAL_KNOB")\n'
        'B = "PFX_PREFIX_"  # building block, not a knob\n'
    )
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "anydoc.md").write_text(
        "# d\n\nprose mention of `PFX_PROSE_ONLY` does not count\n\n"
        "| knob | default | meaning |\n|---|---|---|\n"
        "| `PFX_STALE_KNOB` | 1 | gone |\n"
    )
    monkeypatch.setattr(_lint, "REPO", str(tmp_path))
    findings = _lint.check_env_knob_docs()
    codes = {(code, msg.split("'")[1]) for _, _, code, msg in findings}
    assert ("E12", "PFX_REAL_KNOB") in codes
    assert ("E12", "PFX_STALE_KNOB") in codes
    assert len(findings) == 2  # PFX_PREFIX_ and PFX_PROSE_ONLY ignored
    # findings point at real locations
    src = next(f for f in findings if "PFX_REAL_KNOB" in f[3])
    assert src[0].endswith("knobs.py") and src[1] == 3
    stale = next(f for f in findings if "PFX_STALE_KNOB" in f[3])
    assert stale[0].endswith("anydoc.md") and stale[1] > 1
    # documenting the knob clears the source-side finding
    (docs / "anydoc.md").write_text(
        "| knob | default | meaning |\n|---|---|---|\n"
        "| `PFX_REAL_KNOB` | unset | real |\n"
    )
    assert _lint.check_env_knob_docs() == []
