"""Unit tests for benchmarks/bench_extra.py case configs.

The GPT-1.3B single-chip fit hangs on three exact knobs
(multi_precision=False, main_grad=False, bf16 first moment — see
BENCH_NOTE.md round 4); a silent default regression would OOM the next
chip window instead of benchmarking.  Lock the layered config frames.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.bench_extra import _gpt4k_cfg, _gpt_cfg  # noqa: E402


def test_1p3b_memory_levers_default_on():
    raw, batch, seq = _gpt_cfg(n_dev=1, steps=2)
    assert batch == 8 and seq == 1024  # measured sweet spot
    assert raw["Optimizer"]["multi_precision"] is False
    assert raw["Optimizer"]["moment_dtype"] == "bfloat16"
    assert raw["Engine"]["mix_precision"]["main_grad"] is False
    assert raw["Model"]["hidden_size"] == 2048
    assert raw["Model"]["use_chunked_ce"] is True
    assert raw["Model"]["flash_block"] == 512
    assert raw["Model"]["flash_bwd"] == "fused"
    assert raw["Distributed"]["sharding"]["sharding_offload"] is False


def test_4k_case_shares_frame_without_1p3b_levers():
    raw, batch, seq = _gpt4k_cfg(n_dev=1, steps=2)
    assert batch == 4 and seq == 4096
    assert raw["Model"]["hidden_size"] == 1024  # 345M shape at 4x seq
    assert raw["Model"]["flash_block"] == 512  # 512 divides 4096
    assert raw["Model"]["use_chunked_ce"] is True
    # the 1.3B memory levers must NOT leak into the shared frame
    assert "multi_precision" not in raw["Optimizer"]
    assert "main_grad" not in raw["Engine"]["mix_precision"]


def test_shrink_seq_falls_back_to_auto_block(monkeypatch):
    # CI shrink seqs not divisible by 512 must drop to the auto ladder
    # (flash_block 0) instead of a trace-time divisor error
    monkeypatch.setenv("BENCH_4K_SEQ", "128")
    raw, _, seq = _gpt4k_cfg(n_dev=1, steps=2)
    assert seq == 128
    assert raw["Model"]["flash_block"] == 0
