"""End-to-end engine tests: tiny GPT pretrain on the 8-device CPU mesh —
loss decreases, checkpoint save/load resumes, layouts agree.

This is the TIPC-harness analogue (SURVEY §4): loss-curve + throughput are
the golden signals; here we assert the loss actually drops."""

import os
import pytest

import jax
import numpy as np

from paddlefleetx_tpu.core.engine import Engine
from paddlefleetx_tpu.core.module import build_module
from paddlefleetx_tpu.data.builders import build_dataloader
from paddlefleetx_tpu.data.gpt_dataset import write_synthetic_corpus
from paddlefleetx_tpu.parallel.env import init_dist_env
from paddlefleetx_tpu.utils.config import AttrDict, process_configs


def tiny_cfg(tmp_path, **dist):
    data_dir = str(tmp_path / "data")
    os.makedirs(data_dir, exist_ok=True)
    write_synthetic_corpus(os.path.join(data_dir, "corpus"), vocab_size=128, num_docs=16)
    cfg = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": 16, "micro_batch_size": 1, "seed": 7},
            "Engine": {
                "max_steps": 12,
                "eval_freq": 0,
                "logging_freq": 4,
                "mix_precision": {"enable": False},
                "save_load": {"save_steps": 0, "output_dir": str(tmp_path / "out")},
            },
            "Model": {
                "module": "GPTModule",
                "vocab_size": 128,
                "hidden_size": 64,
                "num_layers": 2,
                "num_attention_heads": 8,
                "max_position_embeddings": 32,
                "hidden_dropout_prob": 0.0,
                "attention_probs_dropout_prob": 0.0,
                "dtype": "float32",
            },
            "Distributed": dist,
            "Data": {
                "Train": {
                    "dataset": {
                        "name": "GPTDataset",
                        "input_dir": data_dir,
                        "max_seq_len": 32,
                        "split": [1, 0, 0],
                    },
                    "sampler": {"shuffle": True},
                },
            },
            "Optimizer": {
                "name": "FusedAdamW",
                "weight_decay": 0.01,
                "lr": {"name": "Constant", "learning_rate": 3e-3},
                "grad_clip": {"name": "ClipGradByGlobalNorm", "clip_norm": 1.0},
            },
        }
    )
    return process_configs(cfg, num_devices=8)


def _losses_from_run(cfg, steps=12):
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    loader = build_dataloader(cfg, "Train")
    with mesh:
        engine = Engine(cfg, module, mesh)
        losses = []
        it = iter(loader)
        for _ in range(steps):
            batch = next(it)
            dev = engine._put_batch(batch)
            engine.state, m = engine.train_step(engine.state, dev)
            losses.append(float(m["loss"]))
    return losses, engine


def test_train_loss_decreases(tmp_path, devices8):
    cfg = tiny_cfg(tmp_path)
    losses, _ = _losses_from_run(cfg)
    assert losses[0] > 4.0  # ~ln(128)=4.85
    assert np.mean(losses[-3:]) < np.mean(losses[:3]) - 0.2


@pytest.mark.slow  # ~18s (six engine boots); tier-1 budget funding for
# the shard_map-port tests that re-opened this test on jax 0.4.37.
# Replacement coverage: cross-layout LOSS parity stays tier-1 via
# test_gpt_model::test_layout_parity (model-level, same layout family),
# and every layout is engine-exercised tier-1 somewhere — pp via the
# zigzag pp2xsep2 worker (Engine.train_step), fsdp via zero-offload,
# sep via the ring suite, dp/mp via serving/TP parity; this exact
# six-layout engine sweep runs in `make test-parallel` / test-mid /
# test-all.
def test_layout_loss_parity_first_step(tmp_path, devices8):
    """Same data+seed, different layouts -> same first-step loss (the
    reference's cross-layout precision-validation contract)."""
    first = {}
    for name, dist in {
        "dp8": {},
        "mp8": {"mp_degree": 8},
        "dp2mp4": {"mp_degree": 4},
        "fsdp": {"sharding": {"sharding_degree": 8, "sharding_stage": 2}},
        "dp2mp2pp2": {"mp_degree": 2, "pp_degree": 2},
        "dp2mp2sep2": {"mp_degree": 2, "sep_degree": 2},
    }.items():
        cfg = tiny_cfg(tmp_path, **dist)
        losses, _ = _losses_from_run(cfg, steps=2)
        first[name] = losses
    base = first["dp8"]
    for name, ls in first.items():
        np.testing.assert_allclose(ls, base, rtol=2e-4, err_msg=name)


@pytest.mark.slow  # ~15s engine boot; the bf16 precision family stays
# tier-1 via test_multi_precision_off_bf16_params_train (the sibling
# bf16 contract) and the fp16 loss-scaling pair; still in make test-mid
# / test-all (PR 8 tier-1 budget convention)
def test_main_grad_off_bf16_grads_train(tmp_path, devices8):
    """mix_precision.main_grad=False (bf16 grads, the 1.3B-fit lever):
    still trains, and tracks the fp32-main-grad bf16 run closely."""
    runs = {}
    for main_grad in (True, False):
        cfg = tiny_cfg(tmp_path)
        cfg.Engine.mix_precision = AttrDict.from_nested(
            {"enable": True, "dtype": "bfloat16", "main_grad": main_grad}
        )
        cfg.Model.dtype = "bfloat16"
        losses, engine = _losses_from_run(cfg, steps=8)
        # params/optimizer masters stay fp32 either way
        assert jax.tree.leaves(engine.state.params)[0].dtype == np.float32
        runs[main_grad] = losses
    # identical first step (loss is computed before any update), close after
    np.testing.assert_allclose(runs[True][0], runs[False][0], rtol=1e-5)
    np.testing.assert_allclose(runs[True], runs[False], rtol=0.05)
    assert np.mean(runs[False][-3:]) < np.mean(runs[False][:3]) - 0.1


def test_abstract_init_memory_report(tmp_path, devices8):
    """Engine(abstract_init=True): no state is allocated (leaves are
    ShapeDtypeStructs) and memory_report returns per-device byte stats
    from the AOT-compiled train step — the 6.7B fit-check path
    (benchmarks/fit_6p7b.py) at tiny dims."""
    import numpy as np_

    cfg = tiny_cfg(tmp_path)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    with mesh:
        engine = Engine(cfg, module, mesh, abstract_init=True)
        assert all(
            isinstance(x, jax.ShapeDtypeStruct)
            for x in jax.tree.leaves(engine.state.params)
        )
        seq = int(cfg.Model.max_position_embeddings)
        b = int(cfg.Global.global_batch_size)
        stats = engine.memory_report({
            "tokens": ((b, seq), np_.int32),
            "labels": ((b, seq), np_.int32),
            "loss_mask": ((b, seq), np_.float32),
            "position_ids": ((b, seq), np_.int32),
        })
    assert stats["params_bytes_per_device"] > 0
    assert stats["peak_bytes_per_device_est"] >= stats["params_bytes_per_device"]


def test_main_grad_off_requires_amp(tmp_path, devices8):
    """mix_precision.enable=False + main_grad=False is contradictory
    (main_grad only controls the AMP gradient dtype): the engine raises
    instead of silently bf16-casting a nominally-fp32 run (advisor r4)."""
    import pytest

    cfg = tiny_cfg(tmp_path)
    cfg.Engine.mix_precision = AttrDict.from_nested(
        {"enable": False, "main_grad": False}
    )
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    with pytest.raises(ValueError, match="main_grad"):
        with mesh:
            Engine(cfg, module, mesh)


def test_multi_precision_off_bf16_params_train(tmp_path, devices8):
    """Optimizer.multi_precision=False (reference FusedAdamW flag): bf16
    params, no fp32 masters, moments follow — trains, and checkpoint
    roundtrips preserve the dtype."""
    cfg = tiny_cfg(tmp_path)
    cfg.Engine.mix_precision = AttrDict.from_nested(
        {"enable": True, "dtype": "bfloat16"}
    )
    cfg.Model.dtype = "bfloat16"
    cfg.Optimizer.multi_precision = False
    losses, engine = _losses_from_run(cfg, steps=8)
    import jax.numpy as jnp

    leaves = jax.tree.leaves(engine.state.params)
    assert all(x.dtype == jnp.bfloat16 for x in leaves)
    # optax moments follow the param dtype (mu pinned bf16 by moment_dtype
    # anyway; nu now bf16 too — the multi_precision=False memory win)
    assert all(
        x.dtype in (jnp.bfloat16, jnp.int32)
        for x in jax.tree.leaves(engine.state.opt_state)
    )
    assert np.isfinite(losses).all()
    assert np.mean(losses[-3:]) < np.mean(losses[:3]) - 0.1

    path = engine.save(str(tmp_path / "ckpt_mp0"))
    cfg2 = tiny_cfg(tmp_path)
    cfg2.Engine.mix_precision = AttrDict.from_nested(
        {"enable": True, "dtype": "bfloat16"}
    )
    cfg2.Model.dtype = "bfloat16"
    cfg2.Optimizer.multi_precision = False
    mesh = init_dist_env(cfg2)
    module = build_module(cfg2)
    with mesh:
        engine2 = Engine(cfg2, module, mesh)
        engine2.load(path)
        assert jax.tree.leaves(engine2.state.params)[0].dtype == jnp.bfloat16


def test_checkpoint_roundtrip(tmp_path, devices8):
    cfg = tiny_cfg(tmp_path)
    losses, engine = _losses_from_run(cfg, steps=4)
    path = engine.save(str(tmp_path / "ckpt"))

    cfg2 = tiny_cfg(tmp_path)
    mesh = init_dist_env(cfg2)
    module = build_module(cfg2)
    with mesh:
        engine2 = Engine(cfg2, module, mesh)
        engine2.load(path)
        assert int(engine2.state.step) == 4
        for a, b in zip(jax.tree.leaves(engine.state.params), jax.tree.leaves(engine2.state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fit_smoke(tmp_path, devices8, capsys):
    cfg = tiny_cfg(tmp_path)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    loader = build_dataloader(cfg, "Train")
    with mesh:
        engine = Engine(cfg, module, mesh)
        state = engine.fit(loader)
    assert int(state.step) == 12


# ---------------------------------------------------------------------------
# fp16 parity path: DynamicLossScaler (reference apis/amp.py:193-234)
# ---------------------------------------------------------------------------


def _fp16_cfg(tmp_path, init_scale, incr_every=1000):
    cfg = tiny_cfg(tmp_path)
    cfg.Engine.mix_precision = AttrDict.from_nested(
        {
            "enable": True,
            "dtype": "float16",
            "scale_loss": {
                "init": init_scale,
                "incr_every_n_steps": incr_every,
                "incr_ratio": 2.0,
                "decr_ratio": 0.5,
            },
        }
    )
    cfg.Model.dtype = "float16"
    return cfg


def test_fp16_loss_scaling_trains_and_grows(tmp_path, devices8):
    """fp16 compute + dynamic loss scale: steps are finite, and the scale
    doubles after incr_every consecutive good steps."""
    cfg = _fp16_cfg(tmp_path, init_scale=1024.0, incr_every=2)
    losses, engine = _losses_from_run(cfg, steps=5)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    # 5 good steps with incr_every=2 -> grew twice: 1024 -> 2048 -> 4096
    assert float(engine.state.scaler["scale"]) == 4096.0


def test_fp16_overflow_shrinks_scale_and_skips(tmp_path, devices8):
    """An absurd initial scale overflows fp16 gradients: the step must be
    skipped (params unchanged) and the scale halved (found_inf contract)."""
    import jax.numpy as jnp

    cfg = _fp16_cfg(tmp_path, init_scale=float(2.0**31))
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    loader = build_dataloader(cfg, "Train")
    with mesh:
        engine = Engine(cfg, module, mesh)
        p0 = jax.tree.map(lambda x: np.asarray(x), engine.state.params)
        batch = next(iter(loader))
        dev = engine._put_batch(batch)
        engine.state, m = engine.train_step(engine.state, dev)
    assert float(m["found_inf"]) == 1.0
    assert float(engine.state.scaler["scale"]) == 2.0**30
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(engine.state.params)):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_metrics_file_stream(tmp_path, devices8):
    """Engine.metrics_file writes one parseable JSON line per logging step."""
    import json

    cfg = tiny_cfg(tmp_path)
    cfg.Engine.metrics_file = str(tmp_path / "metrics.jsonl")
    cfg.Engine.max_steps = 8
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    loader = build_dataloader(cfg, "Train")
    with mesh:
        engine = Engine(cfg, module, mesh)
        engine.fit(loader)
    lines = [json.loads(x) for x in open(cfg.Engine.metrics_file)]
    assert len(lines) == 2  # logging_freq=4, max_steps=8
    assert {"step", "loss", "lr", "grad_norm", "ips", "consumed_samples"} <= set(lines[0])
    assert lines[-1]["step"] == 8 and np.isfinite(lines[-1]["loss"])
    # training goodput ledger rides every record (docs/observability.md
    # "Goodput ledger"): exhaustive fit-loop buckets, all non-negative,
    # with compile attributed on the record that paid it
    led = lines[-1]["time_ledger"]
    assert set(led) == {"compile", "device_step", "data_wait", "host",
                        "eval"}
    assert all(v >= 0.0 for v in led.values()), led
    assert sum(led.values()) > 0.0, led


def _fake_ckpt(root, step, payload="state", meta=True, metadata=True, data=True):
    """A structurally valid step dir (meta marker + orbax-shaped payload)
    without paying for a real orbax save — see checkpoint.validate_checkpoint.
    The knockout flags build each flavor of broken dir (shared with
    tests/test_fault_tolerance.py)."""
    d = root / f"step_{step}"
    d.mkdir()
    if meta:
        (d / "meta.json").write_text('{"step": %d}' % step)
    if payload:
        (d / payload / "d").mkdir(parents=True)
        if metadata:
            (d / payload / "_METADATA").write_text("{}")
        if data:
            (d / payload / "d" / "data0").write_bytes(b"\x01" * 32)
    return d


def test_latest_checkpoint_selection(tmp_path):
    """latest_checkpoint picks the highest complete step dir and skips
    crash-truncated saves (no meta.json)."""
    from paddlefleetx_tpu.utils.checkpoint import latest_checkpoint

    assert latest_checkpoint(str(tmp_path / "missing")) is None
    for step in (2, 10):
        _fake_ckpt(tmp_path, step)
    (tmp_path / "step_30").mkdir()  # crashed save: no meta.json
    (tmp_path / "step_bogus").mkdir()
    assert latest_checkpoint(str(tmp_path)).endswith("step_10")
    # the in-flight/crashed dir is left alone (an async save from a live
    # process has no meta yet; only meta-complete-but-broken is quarantined)
    assert (tmp_path / "step_30").is_dir()


def test_latest_checkpoint_skips_corrupt_meta(tmp_path):
    """A crash-truncated meta.json must not wedge the restart loop: the
    newest PARSEABLE checkpoint wins."""
    from paddlefleetx_tpu.utils.checkpoint import latest_checkpoint

    _fake_ckpt(tmp_path, 4)
    bad = tmp_path / "step_9"
    bad.mkdir()
    (bad / "meta.json").write_text('{"step": 9')  # truncated write
    assert latest_checkpoint(str(tmp_path)).endswith("step_4")


def test_async_checkpoint_roundtrip(tmp_path, devices8):
    """save_load.async_save: the array write overlaps training; meta.json
    (the completeness marker) lands only once the write is durable, and
    wait_for_save()/load() join the in-flight write."""
    cfg = tiny_cfg(tmp_path)
    cfg.Engine.save_load.async_save = True
    losses, engine = _losses_from_run(cfg, steps=3)
    path = engine.save(str(tmp_path / "ackpt"))
    engine.wait_for_save()
    assert os.path.exists(os.path.join(path, "meta.json"))

    cfg2 = tiny_cfg(tmp_path)
    mesh = init_dist_env(cfg2)
    module = build_module(cfg2)
    with mesh:
        engine2 = Engine(cfg2, module, mesh)
        engine2.load(path)
        assert int(engine2.state.step) == 3
        for a, b in zip(
            jax.tree.leaves(engine.state.params), jax.tree.leaves(engine2.state.params)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # a second async save against the same engine joins the first
    path2 = engine.save(str(tmp_path / "ackpt2"))
    engine.wait_for_save()
    assert os.path.exists(os.path.join(path2, "meta.json"))


def test_async_save_error_surfaces(tmp_path, devices8, monkeypatch):
    """A background write failure must raise at wait_for_save, not vanish
    in the finisher thread (silent checkpoint loss)."""
    cfg = tiny_cfg(tmp_path)
    cfg.Engine.save_load.async_save = True
    _, engine = _losses_from_run(cfg, steps=1)
    path = engine.save(str(tmp_path / "good"))
    engine.wait_for_save()

    # fail the finisher (meta write) — AsyncCheckpointer.save itself calls
    # wait_until_finished, so patching that would raise in save() instead
    def boom(path, meta):
        raise OSError("disk full")

    monkeypatch.setattr(engine, "_write_meta", boom)
    bad = engine.save(str(tmp_path / "bad"))
    import pytest as _pytest

    with _pytest.raises(OSError, match="disk full"):
        engine.wait_for_save()
    # no completeness marker: resume correctly skips the directory
    assert not os.path.exists(os.path.join(bad, "meta.json"))
    assert os.path.exists(os.path.join(path, "meta.json"))


def test_evaluate_empty_loader_raises_loudly(tmp_path, devices8):
    """Satellite (ISSUE 9): evaluate on an empty/exhausted loader used to
    return float('nan') silently; the default now raises, and the in-fit
    spelling (on_empty='event') logs + emits a structured eval_empty
    event instead of poisoning downstream records."""
    import json

    cfg = tiny_cfg(tmp_path)
    cfg.Engine.metrics_file = str(tmp_path / "ev_metrics.jsonl")
    _, engine = _losses_from_run(cfg, steps=1)
    with pytest.raises(RuntimeError, match="ZERO batches"):
        engine.evaluate([], iters=4)
    # event branch: nan returned, but loudly + structured
    val = engine.evaluate([], iters=4, on_empty="event")
    assert val != val  # nan
    events = [json.loads(x) for x in open(cfg.Engine.metrics_file)]
    assert any(e.get("event") == "eval_empty" for e in events)
    with pytest.raises(ValueError, match="on_empty"):
        engine.evaluate([], on_empty="typo")


def test_evaluate_nonempty_still_returns_mean(tmp_path, devices8):
    """The healthy branch: a real loader evaluates to a finite mean."""
    cfg = tiny_cfg(tmp_path)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    loader = build_dataloader(cfg, "Train")
    with mesh:
        engine = Engine(cfg, module, mesh)
        val = engine.evaluate(loader, iters=2)
    assert np.isfinite(val)
