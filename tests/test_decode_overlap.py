"""Dispatch-ahead decode overlap acceptance (`make test-decode-overlap`).

  replay equality   the SAME seeded traffic (admissions, a pre-expired
                    shed, speculative commits) through
                    ``dispatch_ahead=True`` vs ``False`` folds to
                    IDENTICAL `replay_decision_log` totals and
                    token-identical greedy output — the exact-replay
                    contract the commit-order decision-log landing
                    exists to keep;
  ArenaReset drill  an injected crash (PFX_FAULT=cb_commit_crash) in
                    the commit readback of an IN-FLIGHT dispatched step
                    resets cleanly: exactly the live seq_ids die, the
                    stale in-flight handle is dropped, and the rebuilt
                    arena decodes token-identically;
  streamed drill    (slow) POST /generate?stream=1 through the REAL
                    router + replica CLIs yields >= 2 SSE token flushes
                    with per-row monotone token indices, ITL
                    percentiles on the replica's /metrics, and an
                    intact stitched trace at the router.
"""

import json
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from test_continuous_batching import PROMPTS, TINY  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# repetitive prompt: the n-gram self-draft's best case, so the
# speculative side actually ACCEPTS drafts and the replay-equality
# assertion covers a non-zero pfx_spec_accepted_total
REP = [5, 6] * 8


@pytest.fixture(scope="module")
def server():
    import jax

    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict.from_nested(TINY)
    cfg = process_configs(cfg, num_devices=jax.device_count())
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    return GenerationServer(cfg, mesh, module)


def _run_seeded_traffic(server, ahead: bool):
    """One deterministic traffic mix through a fresh engine+scheduler:
    4 plain admissions, 1 speculative-friendly repetitive prompt, and
    1 pre-expired request (shed before admission on both sides)."""
    from paddlefleetx_tpu.core.continuous_batching import (
        ContinuousScheduler,
        PagedDecodeEngine,
    )
    from paddlefleetx_tpu.core.request_queue import DeadlineExceeded
    from paddlefleetx_tpu.ops.speculative import SpecConfig
    from paddlefleetx_tpu.utils.tracing import replay_decision_log

    eng = PagedDecodeEngine(server, max_batch=4,
                            spec=SpecConfig(draft_k=3))
    sched = ContinuousScheduler(eng, max_depth=16, dispatch_ahead=ahead)
    doomed = sched.submit([PROMPTS[0]], 6, deadline_s=0.01)
    time.sleep(0.05)  # expired BEFORE the scheduler thread starts
    sched.start()
    futs = [sched.submit([p], 6, deadline_s=120)
            for p in PROMPTS + [REP]]
    outs = [f.result(timeout=300)[0] for f in futs]
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=60)
    assert sched.shutdown(timeout=30)
    replay = replay_decision_log(sched.decision_log)
    return outs, replay, dict(sched.stats)


def test_replay_equality_dispatch_ahead_on_vs_off(server):
    """THE overlap acceptance: identical seeded traffic folds to the
    same decision-log totals with dispatch-ahead on or off, and the
    greedy outputs are token-identical (f32)."""
    outs_a, replay_a, stats_a = _run_seeded_traffic(server, ahead=True)
    outs_s, replay_s, stats_s = _run_seeded_traffic(server, ahead=False)
    assert outs_a == outs_s
    # iteration COUNT is wall-clock (idle iterations append all-zero
    # rows); every event total must agree exactly
    fold_a = {k: v for k, v in replay_a.items() if k != "iterations"}
    fold_s = {k: v for k, v in replay_s.items() if k != "iterations"}
    assert fold_a == fold_s, (fold_a, fold_s)
    assert fold_a["prefill_admits"] == len(PROMPTS) + 1
    assert fold_a["shed"] == 1
    assert fold_a["evictions"] == replay_s["evictions"]
    # the repetitive prompt made speculation commit real tokens, so the
    # equality above covers the spec counters non-trivially
    assert fold_a["spec_accepted"] > 0
    for k in ("prefill_admits", "completed", "evictions", "shed_deadline"):
        assert stats_a[k] == stats_s[k], (k, stats_a[k], stats_s[k])


def test_arena_reset_mid_overlap_kills_exactly_the_live_rows(
    server, monkeypatch
):
    """An in-flight dispatched step whose commit readback crashes
    resets the arena cleanly: the ArenaReset carries exactly the live
    seq_ids, the poisoned in-flight handle is dropped, and the rebuilt
    arena decodes token-identically."""
    from paddlefleetx_tpu.core.continuous_batching import (
        ArenaReset,
        PagedDecodeEngine,
    )
    from paddlefleetx_tpu.utils import resilience

    ref = server.generate_ids([PROMPTS[0]], max_dec_len=6)[0]
    eng = PagedDecodeEngine(server, max_batch=4)
    eng.dispatch_ahead = True
    s0 = eng.admit(PROMPTS[0], 6)
    s1 = eng.admit(PROMPTS[1], 6)
    eng.step()  # dispatches step 1 and leaves it IN FLIGHT
    assert eng.has_inflight
    live = {eng.slots[s].seq_id for s in (s0, s1)}
    resilience.reset_fault_state()
    monkeypatch.setenv("PFX_FAULT", "cb_commit_crash:1")
    try:
        # chains step 2 on the in-flight handles, then commits step 1 —
        # where the injected readback crash fires
        with pytest.raises(ArenaReset) as ei:
            eng.step()
    finally:
        monkeypatch.delenv("PFX_FAULT")
        resilience.reset_fault_state()
    assert {r.seq_id for r in ei.value.dead_rows} == live
    assert not eng.has_inflight  # the chained step died with the arena
    assert not eng.active.any()
    # fresh pools: an identical request decodes token-identically
    s2 = eng.admit(PROMPTS[0], 6)
    for _ in range(96):
        eng.step()
        if not eng.active.any():
            break
    eng.flush()
    assert eng.slots[s2].tokens == ref


# ---------------------------------------------------------------------------
# two-process streamed drill: real serve.py + router.py CLIs
# ---------------------------------------------------------------------------


def _parse_sse(body: str):
    """SSE body -> ordered [(event, data_obj)] pairs."""
    out = []
    for frame in body.split("\n\n"):
        event, data = None, None
        for line in frame.split("\n"):
            if line.startswith("event: "):
                event = line[len("event: "):]
            elif line.startswith("data: "):
                data = json.loads(line[len("data: "):])
        if event is not None:
            out.append((event, data))
    return out


@pytest.mark.fault
@pytest.mark.slow  # two jax boots; gated by make test-decode-overlap
def test_streamed_generate_through_router_two_process(tmp_path):
    import urllib.request

    import yaml

    from test_disagg_drills import (
        _finish,
        _free_port,
        _get,
        _metrics,
        _spawn_replica,
        _spawn_router,
        _wait_eligible,
        _wait_healthy,
        SYS,
        TINY as DRILL_TINY,
    )

    cfg_path = tmp_path / "tiny_stream.yaml"
    cfg_path.write_text(yaml.safe_dump(DRILL_TINY))
    sport, rport = _free_port(), _free_port()
    replica = _spawn_replica(
        cfg_path, sport, "--scheduler", "continuous", "--cb-batch", "4",
        "--replica-id", "s0",
    )
    router = None
    try:
        _wait_healthy([(sport, replica)])
        router = _spawn_router(rport, "--replica",
                               f"http://127.0.0.1:{sport}")
        _wait_eligible(rport, 1, proc=router)

        req = urllib.request.Request(
            f"http://127.0.0.1:{rport}/generate?stream=1",
            data=json.dumps({
                "prompt_ids": SYS + [40, 41, 42], "max_tokens": 6,
                "deadline_s": 60,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.status == 200
            assert "text/event-stream" in r.headers.get("Content-Type", "")
            trace_id = r.headers.get("X-Trace-Id")
            # incremental arrival: the close-delimited body lands in
            # multiple reads because each flush leaves the replica (and
            # transits the router) the moment its step commits
            chunks = []
            while True:
                c = r.read1(65536)
                if not c:
                    break
                chunks.append(c)
        frames = _parse_sse(b"".join(chunks).decode())
        tokens = [d for e, d in frames if e == "token"]
        summaries = [d for e, d in frames if e == "summary"]
        assert not [d for e, d in frames if e == "error"], frames
        # >= 2 flushes, each a separate wire chunk end-to-end
        assert len(tokens) >= 2, frames
        assert len(chunks) >= 2, [len(c) for c in chunks]
        # per-row monotone token indices with no gaps
        seen = {}
        for d in tokens:
            assert d["index"] == seen.get(d["row"], 0), tokens
            seen[d["row"]] = d["index"] + len(d["tokens"])
        assert summaries, frames
        total = sum(len(d["tokens"]) for d in tokens)
        assert summaries[-1]["usage"]["tokens"] == total == sum(
            seen.values()
        )
        assert summaries[-1]["flushes"] == len(tokens)

        # the streamed leg still stitches: the router timeline carries
        # its own routing events AND the replica's remote spans (which
        # rode the stream's terminal summary frame, not a header)
        assert trace_id
        tl = _get(rport, f"/debug/trace?id={trace_id}")
        names = [e["name"] for e in tl["events"]]
        assert "route" in names and "routed" in names
        remote = [e for e in tl["events"] if e.get("proc")]
        assert remote, names
        assert {e["proc"]["replica_id"] for e in remote} == {"s0"}
        assert "decode_chunk" in {e["name"] for e in remote}

        # streamed accounting: TTFT observed at first flush and ITL
        # per-gap — the replica's /metrics carries both histograms
        m = _metrics(sport)
        itl_n = m.get("pfx_request_itl_seconds_count", {}).get(
            frozenset(), 0)
        assert itl_n == len(tokens) - 1, (itl_n, len(tokens))
        assert m.get("pfx_request_ttft_seconds_count", {}).get(
            frozenset(), 0) >= 1
        # and the fleet plumb: the router's healthz poll view carries
        # the replica's itl_p99_s field
        views = _get(rport, "/replicas")["replicas"]
        assert all("itl_p99_s" in v for v in views), views
    finally:
        out_r = _finish(router)
        out_s = _finish(replica)
        assert "Traceback" not in out_s, out_s[-3000:]
        assert "Traceback" not in out_r, out_r[-3000:]
