"""T5 family tests: rel-pos buckets, enc/dec numerics, causality, TP parity,
tokenizer round-trip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.data.tokenizers.t5_tokenizer import T5Tokenizer
from paddlefleetx_tpu.models.gpt.model import ShardingCtx
from paddlefleetx_tpu.models.t5 import model as t5
from paddlefleetx_tpu.models.t5.config import T5Config
from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
from paddlefleetx_tpu.parallel.sharding import make_rules, tree_logical_to_sharding

TINY = T5Config(
    vocab_size=96,
    d_model=32,
    d_kv=8,
    d_ff=48,
    num_layers=2,
    num_decoder_layers=2,
    num_heads=4,
    dtype="float32",
    dropout_rate=0.0,
)


def _batch(cfg, b=2, se=12, sd=8, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(2, cfg.vocab_size, (b, se))
    ids[:, -2:] = cfg.pad_token_id  # pad tail
    labels = rng.integers(2, cfg.vocab_size, (b, sd))
    labels[:, -1] = cfg.pad_token_id
    return {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(labels)}


def test_relative_position_bucket_properties():
    rel = jnp.arange(-20, 21)[None, :] - jnp.zeros((1, 1), jnp.int32)
    b_bi = t5.relative_position_bucket(rel, bidirectional=True, num_buckets=32, max_distance=128)
    b_uni = t5.relative_position_bucket(rel, bidirectional=False, num_buckets=32, max_distance=128)
    assert int(b_bi.min()) >= 0 and int(b_bi.max()) < 32
    assert int(b_uni.max()) < 32
    # zero offset -> bucket 0; sign separates halves in bidirectional mode
    zero = t5.relative_position_bucket(jnp.zeros((1, 1), jnp.int32), bidirectional=True, num_buckets=32, max_distance=128)
    assert int(zero[0, 0]) == 0
    past = t5.relative_position_bucket(jnp.full((1, 1), -3, jnp.int32), bidirectional=True, num_buckets=32, max_distance=128)
    fut = t5.relative_position_bucket(jnp.full((1, 1), 3, jnp.int32), bidirectional=True, num_buckets=32, max_distance=128)
    assert int(past[0, 0]) != int(fut[0, 0])
    # future positions collapse to bucket 0 in unidirectional (causal) mode
    fut_uni = t5.relative_position_bucket(jnp.full((1, 1), 5, jnp.int32), bidirectional=False, num_buckets=32, max_distance=128)
    assert int(fut_uni[0, 0]) == 0


def test_forward_shapes_and_loss_level():
    params = t5.init(TINY, jax.random.key(0))
    batch = _batch(TINY)
    logits = t5.forward(params, batch["input_ids"], t5.shift_right(batch["labels"], TINY), TINY)
    assert logits.shape == (2, 8, TINY.vocab_size)
    loss = t5.seq2seq_loss(params, batch, TINY, train=False)
    assert np.isfinite(float(loss))
    # random init -> CE near ln(V)
    assert abs(float(loss) - np.log(TINY.vocab_size)) < 1.0


def test_decoder_causality():
    """Changing a future decoder token must not affect earlier logits."""
    params = t5.init(TINY, jax.random.key(1))
    batch = _batch(TINY)
    dec = t5.shift_right(batch["labels"], TINY)
    logits_a = t5.forward(params, batch["input_ids"], dec, TINY)
    dec_b = dec.at[:, -1].set((dec[:, -1] + 7) % TINY.vocab_size)
    logits_b = t5.forward(params, batch["input_ids"], dec_b, TINY)
    np.testing.assert_allclose(
        np.asarray(logits_a[:, :-1]), np.asarray(logits_b[:, :-1]), rtol=1e-5, atol=1e-5
    )


def test_encoder_pad_invariance():
    """Logits must not depend on the content of padded encoder positions."""
    params = t5.init(TINY, jax.random.key(2))
    batch = _batch(TINY)
    mask = (batch["input_ids"] != TINY.pad_token_id).astype(jnp.int32)
    dec = t5.shift_right(batch["labels"], TINY)
    a = t5.forward(params, batch["input_ids"], dec, TINY, attention_mask=mask)
    scrambled = batch["input_ids"].at[:, -2:].set(5)
    b = t5.forward(params, scrambled, dec, TINY, attention_mask=mask)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_loss_decreases_overfit():
    import optax

    params = t5.init(TINY, jax.random.key(3))
    batch = _batch(TINY)
    tx = optax.adam(1e-2)
    opt = tx.init(params)

    @jax.jit
    def step(p, o):
        loss, g = jax.value_and_grad(lambda pp: t5.seq2seq_loss(pp, batch, TINY, train=True))(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    first = None
    for _ in range(20):
        params, opt, loss = step(params, opt)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.5


def test_tp_parity(devices8):
    """mp=4 sharded forward == single-device forward."""
    params = t5.init(TINY, jax.random.key(4))
    batch = _batch(TINY)
    dec = t5.shift_right(batch["labels"], TINY)
    ref = t5.forward(params, batch["input_ids"], dec, TINY)

    mesh = build_mesh(MeshConfig(dp_degree=2, mp_degree=4))
    rules = make_rules()
    shardings = tree_logical_to_sharding(t5.t5_logical_axes(TINY), mesh, rules)
    p_sharded = jax.tree.map(lambda x, s: jax.device_put(x, s), params, shardings)
    ctx = ShardingCtx(mesh=mesh, rules=rules)

    @jax.jit
    def fwd(p, ids, d):
        return t5.forward(p, ids, d, TINY, ctx=ctx)

    out = fwd(p_sharded, batch["input_ids"], dec)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-4, atol=2e-4)


def test_tokenizer_roundtrip():
    corpus = ["the quick brown fox", "jumps over the lazy dog", "the fox"]
    tok = T5Tokenizer.from_tiny_corpus(corpus)
    ids = tok.encode("the quick fox")
    assert ids[-1] == tok.eos_id
    assert tok.decode(ids) == "the quick fox"
    # unseen chars -> unk, does not crash
    ids2 = tok.encode("zzz@@@")
    assert all(isinstance(i, int) for i in ids2)
    # sentinel ids live above the base vocab
    assert tok.extra_id(0) >= len(tok.pieces)


def test_module_registry():
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.utils.config import AttrDict

    cfg = AttrDict(
        {
            "Model": dict(module="T5Module", vocab_size=96, d_model=32, d_kv=8,
                          d_ff=48, num_layers=2, num_decoder_layers=2, num_heads=4,
                          dtype="float32", dropout_rate=0.0),
            "Data": {},
        }
    )
    mod = build_module(cfg)
    params = mod.init_params(jax.random.key(0))
    loss = mod.loss_fn(params, _batch(mod.config), train=False)
    assert np.isfinite(float(loss))


def test_t5_pretrain_dataset_span_corruption(tmp_path):
    """The emitted example matches a manual corruption of the base window
    with the same rng: sentinels descend from the vocab top, inputs keep
    nonnoise tokens in order, targets carry the removed spans + EOS."""
    from paddlefleetx_tpu.data.gpt_dataset import write_synthetic_corpus
    from paddlefleetx_tpu.data.t5_dataset import (
        T5PretrainDataset,
        random_spans_noise_mask,
    )

    prefix = write_synthetic_corpus(str(tmp_path / "c"), vocab_size=500, num_docs=12)
    ds = T5PretrainDataset(
        data_prefix=prefix, max_seq_len=64, max_target_len=64,
        vocab_size=1000, split=(1, 0, 0), eos_token_id=1, pad_token_id=0, seed=7,
    )
    assert len(ds) > 0
    item = ds[3]
    assert item["input_ids"].shape == (64,) and item["labels"].shape == (64,)
    np.testing.assert_array_equal(item["input_ids"], ds[3]["input_ids"])

    tokens = ds.base[3]["tokens"]
    rng = np.random.default_rng((7, 3))
    mask = random_spans_noise_mask(len(tokens), 0.15, 3.0, rng)
    frac = mask.mean()
    assert 0.05 < frac < 0.3  # ~15% corruption

    exp_inputs, exp_targets, k, i = [], [], 0, 0
    while i < len(tokens):
        if mask[i]:
            exp_inputs.append(999 - k)
            exp_targets.append(999 - k)
            k += 1
            while i < len(tokens) and mask[i]:
                exp_targets.append(int(tokens[i]))
                i += 1
        else:
            exp_inputs.append(int(tokens[i]))
            i += 1
    exp_targets.append(1)
    np.testing.assert_array_equal(item["input_ids"][: len(exp_inputs)], exp_inputs)
    np.testing.assert_array_equal(item["labels"][: len(exp_targets)], exp_targets)


@pytest.mark.slow  # ~11s engine boot; T5 stays tier-1 via the forward/
# loss-level and dataset tests in this file (the Engine train loop it
# rides is drilled by the GPT engine suites); still in make test-mid /
# test-all (PR 8 tier-1 budget convention)
def test_t5_trains_from_pretrain_dataset(tmp_path, devices8):
    """End-to-end: T5PretrainDataset -> Engine train step (finite loss)."""
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.data.builders import build_dataloader
    from paddlefleetx_tpu.data.gpt_dataset import write_synthetic_corpus
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    data_dir = tmp_path / "data"
    data_dir.mkdir()
    write_synthetic_corpus(str(data_dir / "c"), vocab_size=200, num_docs=16, mean_len=120)
    cfg = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": 8, "seed": 3},
            "Engine": {"max_steps": 2, "eval_freq": 0, "logging_freq": 10,
                       "mix_precision": {"enable": False}, "save_load": {"save_steps": 0}},
            "Model": {"module": "T5Module", "vocab_size": 256, "d_model": 32,
                      "d_kv": 8, "d_ff": 64, "num_layers": 2, "num_decoder_layers": 2,
                      "num_heads": 4, "dropout_rate": 0.0, "dtype": "float32"},
            "Distributed": {},
            "Data": {"Train": {"dataset": {"name": "T5PretrainDataset",
                                           "input_dir": str(data_dir),
                                           "max_seq_len": 32, "max_target_len": 16,
                                           "vocab_size": 256, "split": [1, 0, 0]},
                               "sampler": {"shuffle": True}}},
            "Optimizer": {"name": "AdamW", "lr": {"name": "Constant", "learning_rate": 1e-3}},
        }
    )
    cfg = process_configs(cfg, num_devices=8)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    loader = build_dataloader(cfg, "Train")
    with mesh:
        engine = Engine(cfg, module, mesh)
        batch = next(iter(loader))
        dev = engine._put_batch(batch)
        engine.state, m = engine.train_step(engine.state, dev)
    assert np.isfinite(float(m["loss"]))
