"""Unified telemetry units (utils/telemetry.py) + the engine's step-record
observability contract: registry semantics, Prometheus exposition (strict
line parser, shared with the serve drills), spans, the GPT FLOPs estimator
vs a hand-computed 6·N·T, peak-FLOPs resolution, and the flight recorder."""

import json
import os
import re
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils import telemetry as T

# ---------------------------------------------------------------------------
# strict Prometheus text-exposition parser (format 0.0.4).  Reused by
# tests/test_serve_drills.py against a live /metrics endpoint: every line
# must be a well-formed HELP/TYPE comment or sample, TYPE must precede its
# samples, histogram buckets must be cumulative and end at +Inf with
# matching _sum/_count.
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^{}]*\})?"
    r" (?P<value>[-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|Inf|NaN|\+Inf))$"
)
_LABEL_RE = re.compile(r'^(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\.)*)"$')


def parse_prometheus(text):
    """Strictly parse exposition text -> {name: {labels_frozenset: value}}.
    Raises AssertionError on any malformed line or structural violation."""
    metrics = {}
    types = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            m = re.match(r"^# (HELP|TYPE) ([a-zA-Z_:][a-zA-Z0-9_:]*) (.+)$", line)
            assert m, f"line {lineno}: malformed comment: {line!r}"
            if m.group(1) == "TYPE":
                assert m.group(3) in ("counter", "gauge", "histogram", "summary"), line
                types[m.group(2)] = m.group(3)
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"line {lineno}: malformed sample: {line!r}"
        name = m.group("name")
        labels = {}
        raw = (m.group("labels") or "{}")[1:-1]
        if raw:
            for part in raw.split(","):
                lm = _LABEL_RE.match(part)
                assert lm, f"line {lineno}: malformed label {part!r} in {line!r}"
                labels[lm.group("k")] = lm.group("v")
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        assert base in types or name in types, (
            f"line {lineno}: sample {name!r} before any TYPE declaration"
        )
        value = float(m.group("value").replace("+Inf", "inf").replace("Inf", "inf"))
        metrics.setdefault(name, {})[frozenset(labels.items())] = value
    # histogram structure: cumulative buckets ending at +Inf == _count
    for name, kind in types.items():
        if kind != "histogram":
            continue
        buckets = metrics.get(f"{name}_bucket", {})
        series = {}
        for labels, v in buckets.items():
            le = dict(labels)["le"]
            rest = frozenset(kv for kv in labels if kv[0] != "le")
            series.setdefault(rest, []).append((le, v))
        for rest, pairs in series.items():
            def le_key(le):
                return float("inf") if le == "+Inf" else float(le)
            pairs.sort(key=lambda p: le_key(p[0]))
            vals = [v for _, v in pairs]
            assert vals == sorted(vals), f"{name}: non-cumulative buckets {pairs}"
            assert pairs[-1][0] == "+Inf", f"{name}: missing +Inf bucket"
            count = metrics.get(f"{name}_count", {}).get(rest)
            assert count == pairs[-1][1], f"{name}: +Inf != _count"
            assert metrics.get(f"{name}_sum", {}).get(rest) is not None, name
    return metrics, types


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    r = T.Registry()
    c = r.counter("pfx_serving_requests_total")
    c.inc()
    c.inc(2)
    assert c.get() == 3
    g = r.gauge("pfx_train_loss")
    g.set(2.5)
    g.add(-0.5)
    assert g.get() == 2.0
    h = r.histogram("pfx_request_latency_seconds")
    for v in (0.002, 0.02, 0.2, 2.0):
        h.observe(v)
    st = h.state()
    assert st["count"] == 4 and abs(st["sum"] - 2.222) < 1e-9
    assert st["p50"] in (0.02, 0.2)
    assert h.percentile(0.99) == 2.0


def test_undeclared_metric_name_raises():
    r = T.Registry()
    with pytest.raises(ValueError, match="not declared"):
        r.counter("pfx_bogus_total")  # noqa — deliberately undeclared
    with pytest.raises(ValueError, match="not declared"):
        # declared name, wrong kind: a counter cannot be re-typed
        r.gauge("pfx_serving_requests_total")


def test_labels_make_distinct_children():
    r = T.Registry()
    r.counter("pfx_http_responses_total", code="200").inc(3)
    r.counter("pfx_http_responses_total", code="503").inc()
    assert r.value("pfx_http_responses_total", code="200") == 3
    assert r.value("pfx_http_responses_total", code="503") == 1


def test_render_parses_strictly_and_matches_snapshot():
    r = T.Registry()
    r.counter("pfx_http_responses_total", code="200").inc(7)
    r.gauge("pfx_queue_depth").set(2)
    h = r.histogram("pfx_request_ttft_seconds")
    h.observe(0.03)
    h.observe(1.5)
    snap = r.snapshot()
    metrics, types = parse_prometheus(r.render_prometheus(snap))
    assert types["pfx_http_responses_total"] == "counter"
    assert types["pfx_queue_depth"] == "gauge"
    assert types["pfx_request_ttft_seconds"] == "histogram"
    assert metrics["pfx_http_responses_total"][frozenset({("code", "200")})] == 7
    assert metrics["pfx_queue_depth"][frozenset()] == 2
    assert metrics["pfx_request_ttft_seconds_count"][frozenset()] == 2


def test_stats_view_dict_interface_and_collection():
    r = T.Registry()
    sv = T.StatsView(
        {"requests": "pfx_serving_requests_total", "last_error": None},
        init={"last_error": ""},
        registry=r,
    )
    sv["requests"] += 2
    sv["last_error"] = "boom"
    sv["warmup_s"] = {"8": 0.5}  # late, non-exported key
    assert sv["requests"] == 2 and dict(sv)["last_error"] == "boom"
    assert {**sv}["warmup_s"] == {"8": 0.5}
    assert r.value("pfx_serving_requests_total") == 2
    # registry holds the view WEAKLY: a dead instance leaves the snapshot
    del sv
    import gc

    gc.collect()
    assert r.value("pfx_serving_requests_total") == 0


def test_stats_view_instances_sum_in_snapshot():
    r = T.Registry()
    a = T.StatsView({"requests": "pfx_serving_requests_total"}, registry=r)
    b = T.StatsView({"requests": "pfx_serving_requests_total"}, registry=r)
    a["requests"] += 1
    b["requests"] += 4
    # per-instance views keep absolute counts; the registry reports the
    # process-wide sum
    assert a["requests"] == 1 and b["requests"] == 4
    assert r.value("pfx_serving_requests_total") == 5


def test_span_phases_and_event():
    sp = T.Span("request", t0=100.0)
    sp.mark("admission", t=100.1)
    sp.mark("queue_wait", t=100.5)
    sp.mark("decode", t=102.5)
    ph = sp.phases()
    assert list(ph) == ["admission", "queue_wait", "decode"]
    np.testing.assert_allclose(
        [ph["admission"], ph["queue_wait"], ph["decode"]], [0.1, 0.4, 2.0]
    )
    ev = sp.event(code=200)
    assert ev["event"] == "span" and ev["span"] == "request"
    assert abs(ev["total_s"] - 2.5) < 1e-6 and ev["code"] == 200
    # injected out-of-order stamps sort into place
    sp2 = T.Span("x", t0=10.0)
    sp2.mark("late", t=12.0)
    sp2.mark("early", t=11.0)
    assert list(sp2.phases()) == ["early", "late"]


# ---------------------------------------------------------------------------
# MFU accounting
# ---------------------------------------------------------------------------


def test_gpt_flops_estimator_matches_hand_computed_6nt():
    """The acceptance anchor: 6·N·T against an independently hand-computed
    N for a tiny shape (vocab=10, h=4, L=1, ffn=16).

      embed 10*4=40; qkv 3*4*4+3*4=60; attn_out 4*4+4=20;
      mlp_up 4*16+16=80; mlp_down 16*4+4=68; 2 LN 4*4=16; final LN 8
      N = 40 + (60+20+80+68+16) + 8 = 292
    """
    n = T.gpt_param_count(vocab_size=10, hidden_size=4, num_layers=1)
    assert n == 292
    per_tok = T.model_flops_per_token(
        vocab_size=10, hidden_size=4, num_layers=1
    )
    T_tokens = 50
    assert per_tok * T_tokens == 6 * 292 * 50
    # forward-only basis (decode benches): 2·N
    assert T.model_flops_per_token(
        vocab_size=10, hidden_size=4, num_layers=1, backward=False
    ) == 2 * 292


def test_flops_estimator_reads_config_objects_and_declines_non_gpt():
    from paddlefleetx_tpu.models.gpt.config import GPTConfig

    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_attention_heads=4)
    per_tok = T.model_flops_per_token(cfg)
    assert per_tok == 6 * T.gpt_param_count(
        vocab_size=96, hidden_size=32, num_layers=2,
        ffn_hidden_size=cfg.ffn_hidden_size,
    )

    class NotGPT:
        pass

    assert T.model_flops_per_token(NotGPT()) is None


def test_peak_flops_env_override_and_table(monkeypatch):
    monkeypatch.setenv("PFX_PEAK_FLOPS", "123e12")
    assert T.peak_flops(device_kind="anything") == 123e12
    monkeypatch.setenv("PFX_PEAK_FLOPS", "not-a-number")
    with pytest.raises(ValueError, match="PFX_PEAK_FLOPS"):
        T.peak_flops()
    monkeypatch.delenv("PFX_PEAK_FLOPS")
    assert T.peak_flops(device_kind="TPU v5e") == 197e12
    assert T.peak_flops(device_kind="TPU v4") == 275e12
    assert T.peak_flops(device_kind="cpu") == 1e12  # nominal, documented
    assert T.peak_flops(device_kind="weird-npu") is None
    assert T.peak_flops(device_kind="weird-npu", default=5e12) == 5e12


def test_mfu_math():
    # 1000 tok/s * 1e6 FLOPs/tok = 1e9 FLOP/s over 2 chips of 1e12 peak
    assert T.mfu(1000.0, 1e6, 2, peak=1e12) == pytest.approx(5e-4)
    assert T.mfu(1000.0, 1e6, 2, peak=0) is None


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_and_dump(tmp_path, monkeypatch):
    monkeypatch.delenv("PFX_FLIGHT_RECORDER", raising=False)
    fr = T.FlightRecorder(capacity=3)
    for i in range(5):
        fr.record({"event": "step", "step": i})
    evs = fr.events()
    assert [e["step"] for e in evs] == [2, 3, 4]  # bounded: oldest dropped
    assert [e["seq"] for e in evs] == [3, 4, 5]
    path = fr.dump(path=str(tmp_path / "fr.jsonl"), reason="unit")
    lines = [json.loads(x) for x in open(path)]
    assert lines[0]["event"] == "flight_recorder_dump"
    assert lines[0]["reason"] == "unit" and lines[0]["events"] == 3
    assert [e["step"] for e in lines[1:]] == [2, 3, 4]


def test_flight_recorder_env_path_and_dump_never_raises(tmp_path, monkeypatch):
    fr = T.FlightRecorder(capacity=2)
    fr.record({"event": "x"})
    monkeypatch.setenv("PFX_FLIGHT_RECORDER", str(tmp_path / "sub" / "fr.jsonl"))
    # the operator's env path wins even over an explicit caller path
    path = fr.dump(path=str(tmp_path / "elsewhere.jsonl"), reason="env")
    assert path == str(tmp_path / "sub" / "fr.jsonl") and os.path.exists(path)
    assert not os.path.exists(tmp_path / "elsewhere.jsonl")
    # unwritable target: logged, returns None, never raises (crash path)
    monkeypatch.setenv("PFX_FLIGHT_RECORDER", "/proc/nope/fr.jsonl")
    assert fr.dump(reason="bad") is None


def test_flight_recorder_excepthook_dumps(tmp_path, monkeypatch):
    monkeypatch.setenv("PFX_FLIGHT_RECORDER", str(tmp_path / "crash.jsonl"))
    fr = T.FlightRecorder(capacity=8)
    fr.record({"event": "step", "step": 7})
    seen = []
    monkeypatch.setattr(sys, "excepthook", lambda *a: seen.append(a))
    fr.install_excepthook()
    try:
        raise RuntimeError("boom")
    except RuntimeError:
        sys.excepthook(*sys.exc_info())
    assert seen, "prior hook must still run"
    lines = [json.loads(x) for x in open(tmp_path / "crash.jsonl")]
    assert "uncaught RuntimeError" in lines[0]["reason"]
    assert any(e.get("event") == "crash" and "boom" in e.get("error", "")
               for e in lines)
    assert any(e.get("event") == "step" and e.get("step") == 7 for e in lines)


# ---------------------------------------------------------------------------
# SLO burn rates
# ---------------------------------------------------------------------------


def test_slo_tracker_disabled_by_default_and_loud_on_bad_config():
    t = T.SLOTracker()
    assert not t.enabled
    t.observe_request(ttft_s=1.0, ok=False)  # no-op when disabled
    assert t.evaluate()["enabled"] is False and t.collect() == []
    with pytest.raises(ValueError, match=">= 0"):
        T.SLOTracker(ttft_p99_s=-1)
    with pytest.raises(ValueError, match="positive"):
        T.SLOTracker(ttft_p99_s=1, windows_s=(0,))


def test_slo_ttft_burn_rate_breach_and_time_recovery():
    """p99-TTFT objective: a window where every request blows the
    objective burns 100x the budget (bad_frac 1.0 / allowed 0.01) and
    breaches on BOTH windows; once the events age out of the windows the
    burn returns to 0 and the breach clears — no manual reset."""
    t = T.SLOTracker(ttft_p99_s=0.5, windows_s=(5.0, 30.0))
    for i in range(10):
        t.observe_request(ttft_s=2.0, ok=True, t=100.0 + i * 0.1)
    ev = t.evaluate(now=101.0)
    assert ev["objectives"] == {"ttft_p99": 0.5}
    assert ev["burn"]["ttft_p99"] == {"5s": 100.0, "30s": 100.0}
    assert ev["breach"] and "ttft_p99" in ev["reason"]
    assert ev["ttft_p99_s"] == 2.0
    # recovery: the bad window ages out
    ev2 = t.evaluate(now=200.0)
    assert ev2["burn"]["ttft_p99"] == {"5s": 0.0, "30s": 0.0}
    assert not ev2["breach"] and ev2["reason"] is None


def test_slo_failed_requests_count_as_ttft_violations():
    """A request that never delivered a first token (shed 503 / 500) is
    a TTFT violation, NOT a missing sample — a fully wedged server
    where every request fails must breach the TTFT objective, not
    report zero burn (the worst-TTFT-invisible failure mode)."""
    t = T.SLOTracker(ttft_p99_s=0.5, windows_s=(5.0, 30.0))
    for i in range(10):
        t.observe_request(ok=False, t=100.0 + i * 0.1)  # no ttft at all
    ev = t.evaluate(now=101.0)
    assert ev["burn"]["ttft_p99"] == {"5s": 100.0, "30s": 100.0}
    assert ev["breach"] and "ttft_p99" in ev["reason"]
    # delivered-only observed percentile stays finite (0 when none)
    assert ev["ttft_p99_s"] == 0.0
    # mixed: 1 failure among 99 fast deliveries = 1% bad = burn 1.0
    t2 = T.SLOTracker(ttft_p99_s=0.5, windows_s=(5.0, 30.0))
    for i in range(99):
        t2.observe_request(ttft_s=0.1, ok=True, t=100.0 + i * 0.01)
    t2.observe_request(ok=False, t=101.0)
    ev2 = t2.evaluate(now=101.0)
    assert ev2["burn"]["ttft_p99"]["5s"] == 1.0
    assert not ev2["breach"]  # burning AT budget, not past it


def test_slo_long_window_is_time_pruned_not_count_truncated():
    """The event store prunes by TIME (the long window), never by a
    small count bound — under load a count-bounded ring would shrink
    the long window to minutes and let a short burst page through the
    multi-window gate it should have diluted."""
    t = T.SLOTracker(ttft_p99_s=0.5, windows_s=(5.0, 600.0))
    # 7000 events over ~580s: a 4096-cap ring would have dropped the
    # first ~half; time pruning keeps everything inside 600s
    for i in range(7000):
        t.observe_request(ttft_s=0.1, ok=True, t=100.0 + i * 0.083)
    ev = t.evaluate(now=100.0 + 7000 * 0.083)
    with t._lock:
        n = len(t._events)
    assert n == 7000
    # a 3-request bad burst at the end: diluted far below threshold on
    # the long window -> no breach
    for i in range(3):
        t.observe_request(ttft_s=2.0, ok=True, t=100.0 + 7000 * 0.083 + i)
    ev = t.evaluate(now=100.0 + 7000 * 0.083 + 3)
    assert ev["burn"]["ttft_p99"]["600s"] < 1.0
    assert not ev["breach"]
    # events beyond the long window drop off on the next observe
    t.observe_request(ttft_s=0.1, ok=True, t=100.0 + 7000 * 0.083 + 700)
    with t._lock:
        assert len(t._events) < 7003


def test_slo_multiwindow_gate_needs_both_windows_burning():
    """One bad spike inside the short window but diluted over the long
    window must NOT breach — the long window is the page-worthiness
    gate (multi-window burn-rate semantics)."""
    t = T.SLOTracker(ttft_p99_s=0.5, windows_s=(5.0, 60.0))
    # 200 good requests spread over the long window
    for i in range(200):
        t.observe_request(ttft_s=0.1, ok=True, t=50.0 + i * 0.25)
    # a short burst of bad ones right at the end
    for i in range(3):
        t.observe_request(ttft_s=2.0, ok=True, t=99.5 + i * 0.1)
    ev = t.evaluate(now=100.0)
    assert ev["burn"]["ttft_p99"]["5s"] > 1.0
    assert ev["burn"]["ttft_p99"]["60s"] <= 1.5  # diluted
    # short window burns but the long window gates the page
    if ev["burn"]["ttft_p99"]["60s"] <= 1.0:
        assert not ev["breach"]


def test_slo_error_rate_burn_and_collect_gauges():
    import time as _time

    t = T.SLOTracker(error_rate=0.1, windows_s=(5.0, 30.0))
    # real-clock-relative stamps: collect() evaluates at the live
    # monotonic now, so the window must contain them
    now = _time.monotonic()
    for i in range(8):
        t.observe_request(ok=True, t=now - 1.0 + i * 0.1)
    for i in range(2):
        t.observe_request(ok=False, t=now - 0.2 + i * 0.1)
    ev = t.evaluate(now=now)
    # 2/10 failures over a 0.1 objective = 2x burn, both windows
    assert ev["burn"]["error_rate"] == {"5s": 2.0, "30s": 2.0}
    assert ev["breach"] and "error_rate" in ev["reason"]
    # the collector exports the same numbers as declared pfx_slo_* rows
    r = T.Registry()
    r.register_collector(t)
    rows = {(n, frozenset(lab.items())): v for n, lab, v in t.collect()}
    assert rows[("pfx_slo_objective", frozenset({("objective", "error_rate")}))] == 0.1
    assert all(n in T.METRICS for (n, _), _ in zip(rows.keys(), rows.values()))
    snap = r.snapshot()
    assert "pfx_slo_burn_rate" in snap
    metrics, types = parse_prometheus(r.render_prometheus(snap))
    assert types["pfx_slo_breach"] == "gauge"
    assert metrics["pfx_slo_breach"][
        frozenset({("objective", "error_rate")})
    ] == 1.0


def test_flight_dir_routes_default_dump(tmp_path, monkeypatch):
    """Satellite: flight dumps land under PFX_FLIGHT_DIR (default
    ./artifacts/) instead of polluting the process cwd."""
    monkeypatch.delenv("PFX_FLIGHT_RECORDER", raising=False)
    monkeypatch.delenv("PFX_FLIGHT_DIR", raising=False)
    monkeypatch.chdir(tmp_path)
    fr = T.FlightRecorder(capacity=2)
    fr.record({"event": "x"})
    path = fr.dump(reason="unit")
    assert path == os.path.join("artifacts", "flight_recorder.jsonl")
    assert os.path.exists(tmp_path / "artifacts" / "flight_recorder.jsonl")
    # the env dir re-routes; an explicit caller path still wins over it
    monkeypatch.setenv("PFX_FLIGHT_DIR", str(tmp_path / "ops"))
    assert fr.dump(reason="dir") == str(
        tmp_path / "ops" / "flight_recorder.jsonl"
    )
    explicit = str(tmp_path / "here.jsonl")
    assert fr.dump(path=explicit, reason="explicit") == explicit


# ---------------------------------------------------------------------------
# engine step records: the training-side observability contract
# ---------------------------------------------------------------------------


def test_engine_step_records_carry_phases_compile_and_mfu(tmp_path, devices8):
    """Step records gain tokens_per_sec / model_flops / mfu (analytic
    estimator vs peak) and the per-phase breakdown; compile_s appears on
    the FIRST logged record only, and the ips window excludes it."""
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": 16, "micro_batch_size": 1, "seed": 7},
            "Engine": {
                "max_steps": 3,
                "eval_freq": 0,
                "logging_freq": 1,
                "mix_precision": {"enable": False},
                "save_load": {"save_steps": 0, "output_dir": str(tmp_path / "o")},
                "metrics_file": str(tmp_path / "metrics.jsonl"),
            },
            # same tiny shape as tests/test_engine.py::tiny_cfg so the
            # train-step compile rides the shared persistent cache
            "Model": {
                "module": "GPTModule",
                "vocab_size": 128,
                "hidden_size": 64,
                "num_layers": 2,
                "num_attention_heads": 8,
                "max_position_embeddings": 32,
                "hidden_dropout_prob": 0.0,
                "attention_probs_dropout_prob": 0.0,
                "dtype": "float32",
            },
            "Distributed": {},
            "Optimizer": {
                "name": "FusedAdamW",
                "lr": {"name": "Constant", "learning_rate": 3e-3},
            },
        }
    )
    cfg = process_configs(cfg, num_devices=8)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    rng = np.random.default_rng(0)

    def batch():
        return {
            "tokens": rng.integers(0, 128, (16, 32)).astype(np.int64),
            "labels": rng.integers(0, 128, (16, 32)).astype(np.int64),
            "loss_mask": np.ones((16, 32), np.float32),
            "position_ids": np.tile(np.arange(32), (16, 1)),
        }

    loader = [batch() for _ in range(3)]
    with mesh:
        engine = Engine(cfg, module, mesh)
        engine.fit(loader)

    records = [json.loads(x) for x in open(cfg.Engine.metrics_file)]
    assert len(records) == 3
    first = records[0]
    # the acceptance keys
    for key in ("mfu", "tokens_per_sec", "data_wait_s", "host_s", "step_s",
                "model_flops", "compile_s"):
        assert key in first, (key, first)
    assert first["compile_s"] > 0
    assert all("compile_s" not in r for r in records[1:]), records
    assert first["tokens_per_sec"] == first["ips"] > 0
    # compile excluded from the window: the first window's per-step wall
    # time must not contain the multi-second trace+compile
    assert first["step_s"] < first["compile_s"] + 1.0
    # mfu = tokens/s * flops/tok / (peak * devices), vs the same estimator
    per_tok = T.model_flops_per_token(module.config)
    peak = T.peak_flops()
    assert first["mfu"] == pytest.approx(
        first["ips"] * per_tok / (peak * mesh.size), rel=1e-3
    )
    assert first["host_s"] >= 0 and first["data_wait_s"] >= 0
    # the registry mirrors the logged values
    reg = T.get_registry()
    assert reg.value("pfx_train_steps_total") == 3
    assert reg.value("pfx_train_mfu") == records[-1]["mfu"]
    # every record also landed in the flight recorder ring
    steps = [e.get("step") for e in T.get_flight_recorder().events()
             if e.get("event") == "step"]
    assert {1, 2, 3} <= set(steps)
    # the fit's trace mirrors each logged window as a step_window span
    # (records link to it via trace_id)
    from paddlefleetx_tpu.utils.tracing import get_trace_buffer

    assert all(r["trace_id"] == records[0]["trace_id"] for r in records)
    tc = get_trace_buffer().get(records[0]["trace_id"])
    assert tc is not None and tc.name == "train"
    spans = [e for e in tc.timeline()["events"]
             if e["name"] == "step_window"]
    assert [s["args"]["step"] for s in spans] == [1, 2, 3]
    assert spans[0]["args"]["loss"] == records[0]["loss"]
    assert spans[0]["args"]["data_wait_s"] == records[0]["data_wait_s"]
