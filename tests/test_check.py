"""Replica-consistency fingerprint (parallel/check.py — the reference
`check` fused comm group analogue, comm_groups.py:64)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddlefleetx_tpu.parallel.check import (
    check_replica_consistency,
    tree_fingerprint,
)


def _tree(seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.normal(size=(8, 16)), dtype),
        "b": jnp.asarray(rng.normal(size=(16,)), dtype),
        "n": {"scale": jnp.ones((16,), dtype), "step": jnp.int32(3)},
    }


def test_fingerprint_deterministic_and_structural():
    a, b = _tree(0), _tree(0)
    assert int(tree_fingerprint(a)) == int(tree_fingerprint(b))
    assert int(tree_fingerprint(a)) != int(tree_fingerprint(_tree(1)))


def test_fingerprint_detects_one_ulp():
    a = _tree(0)
    fp = int(tree_fingerprint(a))
    # flip the lowest mantissa bit of ONE element
    w = np.asarray(a["w"]).copy()
    bits = w.view(np.uint32)
    bits[3, 7] ^= 1
    b = dict(a, w=jnp.asarray(bits.view(np.float32)))
    assert int(tree_fingerprint(b)) != fp


def test_fingerprint_detects_int_and_bf16_divergence():
    a = _tree(0, jnp.bfloat16)
    b = dict(a, b=a["b"].at[0].add(jnp.bfloat16(2**-7)))
    assert int(tree_fingerprint(a)) != int(tree_fingerprint(b))
    c = dict(a)
    c["n"] = dict(a["n"], step=jnp.int32(4))
    assert int(tree_fingerprint(a)) != int(tree_fingerprint(c))


def test_fingerprint_sharding_invariant(devices8):
    """The same values fingerprint identically replicated vs sharded (the
    reduction must not depend on layout)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh

    a = _tree(0)
    ref = int(tree_fingerprint(a))
    mesh = build_mesh(MeshConfig(dp_degree=8), devices8)
    sharded = dict(
        a,
        w=jax.device_put(a["w"], NamedSharding(mesh, P("data", None))),
        b=jax.device_put(a["b"], NamedSharding(mesh, P())),
    )
    with mesh:
        got = int(jax.jit(tree_fingerprint)(sharded))
    assert got == ref


def test_check_replica_consistency_single_process():
    fp = check_replica_consistency(_tree(0), name="t")
    assert isinstance(fp, int) and 0 <= fp < 2**32


def test_engine_runs_consistency_check(devices8, monkeypatch):
    """Engine.consistency_check_freq wires the check into the fit loop."""
    import paddlefleetx_tpu.parallel.check as check_mod
    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": 8, "micro_batch_size": 1, "seed": 7},
            "Engine": {
                "max_steps": 2,
                "eval_freq": 0,
                "logging_freq": 10**9,
                "consistency_check_freq": 1,
                "mix_precision": {"enable": False},
                "save_load": {"save_steps": 0},
            },
            "Model": {
                "module": "GPTModule",
                "vocab_size": 64,
                "hidden_size": 32,
                "num_layers": 2,
                "num_attention_heads": 4,
                "max_position_embeddings": 16,
                "dtype": "float32",
            },
            "Distributed": {"dp_degree": 8},
            "Optimizer": {
                "name": "FusedAdamW",
                "lr": {"name": "Constant", "learning_rate": 1e-4},
            },
        }
    )
    cfg = process_configs(cfg, num_devices=8)
    mesh = init_dist_env(cfg, devices=devices8)
    module = build_module(cfg)

    rng = np.random.default_rng(0)

    def loader():
        while True:
            yield {
                "tokens": rng.integers(0, 64, (8, 16)).astype(np.int64),
                "labels": rng.integers(0, 64, (8, 16)).astype(np.int64),
                "loss_mask": np.ones((8, 16), np.float32),
                "position_ids": np.tile(np.arange(16), (8, 1)),
            }

    calls = []
    real = check_mod.check_replica_consistency
    monkeypatch.setattr(
        check_mod,
        "check_replica_consistency",
        lambda tree, **kw: calls.append(1) or real(tree, **kw),
    )
    with mesh:
        engine = Engine(cfg, module, mesh)
        engine._fit_loop(loader(), None, 16, _NoProfiler(), 0.0, 0)
    assert len(calls) == 2  # freq=1 over 2 steps


class _NoProfiler:
    def step(self, _):
        pass

    def close(self):
        pass


def test_fingerprint_detects_transposition():
    """Swapping two values (same multiset of bit patterns — e.g. a
    misordered checkpoint restore) must change the fingerprint: the
    per-element index weight breaks sum commutativity."""
    a = _tree(0)
    w = np.asarray(a["w"]).copy()
    w[[0, 1]] = w[[1, 0]]
    assert not np.array_equal(w, np.asarray(a["w"]))
    b = dict(a, w=jnp.asarray(w))
    assert int(tree_fingerprint(a)) != int(tree_fingerprint(b))
