"""Rigid-body geometry tests: quaternion round-trips, rigid algebra, FAPE."""

import jax.numpy as jnp
import numpy as np

from paddlefleetx_tpu.models.protein import rigid as r3


def _rand_quat(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return r3.quat_normalize(jnp.asarray(rng.normal(size=(n, 4)), jnp.float32))


def test_quat_to_rot_orthonormal():
    rot = r3.quat_to_rot(_rand_quat())
    eye = np.eye(3)
    np.testing.assert_allclose(
        np.asarray(jnp.einsum("nij,nkj->nik", rot, rot)), np.tile(eye, (8, 1, 1)), atol=1e-5
    )
    np.testing.assert_allclose(np.asarray(jnp.linalg.det(rot)), 1.0, atol=1e-5)


def test_quat_rot_roundtrip():
    q = _rand_quat()
    q = q * jnp.sign(q[:, :1])  # canonical w >= 0
    q2 = r3.rot_to_quat(r3.quat_to_rot(q))
    np.testing.assert_allclose(np.abs(np.asarray(q2)), np.abs(np.asarray(q)), atol=1e-4)


def test_quat_multiply_matches_rot_compose():
    qa, qb = _rand_quat(seed=1), _rand_quat(seed=2)
    rot_ab = r3.rot_mul_rot(r3.quat_to_rot(qa), r3.quat_to_rot(qb))
    rot_q = r3.quat_to_rot(r3.quat_multiply(qa, qb))
    np.testing.assert_allclose(np.asarray(rot_ab), np.asarray(rot_q), atol=1e-5)


def test_rigid_compose_invert():
    rng = np.random.default_rng(3)
    r = (r3.quat_to_rot(_rand_quat(seed=4)), jnp.asarray(rng.normal(size=(8, 3)), jnp.float32))
    inv = r3.rigid_invert(r)
    ident = r3.rigid_compose(r, inv)
    np.testing.assert_allclose(np.asarray(ident[0]), np.tile(np.eye(3), (8, 1, 1)), atol=1e-5)
    np.testing.assert_allclose(np.asarray(ident[1]), 0.0, atol=1e-5)

    pts = jnp.asarray(rng.normal(size=(8, 3)), jnp.float32)
    back = r3.rigid_invert_apply(r, r3.rigid_apply(r, pts))
    np.testing.assert_allclose(np.asarray(back), np.asarray(pts), atol=1e-4)


def test_rigids_from_3_points_frame():
    rng = np.random.default_rng(5)
    n_pt = jnp.asarray(rng.normal(size=(4, 3)), jnp.float32)
    ca = jnp.asarray(rng.normal(size=(4, 3)), jnp.float32)
    c = jnp.asarray(rng.normal(size=(4, 3)), jnp.float32)
    rot, origin = r3.rigids_from_3_points(n_pt, ca, c)
    # orthonormal, right-handed
    np.testing.assert_allclose(
        np.asarray(jnp.einsum("nij,nik->njk", rot, rot)), np.tile(np.eye(3), (4, 1, 1)), atol=1e-5
    )
    np.testing.assert_allclose(np.asarray(jnp.linalg.det(rot)), 1.0, atol=1e-5)
    # AlphaFold r3 convention: N on the negative x axis, C in the
    # xy-plane with positive y
    local_n = r3.rigid_invert_apply((rot, origin), n_pt)
    np.testing.assert_allclose(np.asarray(local_n[:, 1:]), 0.0, atol=1e-4)
    assert np.all(np.asarray(local_n[:, 0]) < 0)
    local_c = r3.rigid_invert_apply((rot, origin), c)
    np.testing.assert_allclose(np.asarray(local_c[:, 2]), 0.0, atol=1e-4)
    assert np.all(np.asarray(local_c[:, 1]) > 0)


def test_pre_compose_identity_update():
    q = _rand_quat(seed=6)
    t = jnp.zeros((8, 3))
    q2, t2 = r3.pre_compose(q, t, jnp.zeros((8, 6)))
    np.testing.assert_allclose(np.asarray(q2), np.asarray(q), atol=1e-5)
    np.testing.assert_allclose(np.asarray(t2), np.asarray(t), atol=1e-5)


def test_fape_zero_for_identical():
    rng = np.random.default_rng(7)
    frames = (r3.quat_to_rot(_rand_quat(seed=8)), jnp.asarray(rng.normal(size=(8, 3)), jnp.float32))
    pts = jnp.asarray(rng.normal(size=(12, 3)), jnp.float32)
    loss = r3.frame_aligned_point_error(frames, frames, pts, pts)
    assert float(loss) < 1e-3
    # perturbed points -> positive loss
    loss2 = r3.frame_aligned_point_error(frames, frames, pts + 1.0, pts)
    assert float(loss2) > float(loss)
