"""Multi-tenant substrate units (`core/tenancy.py`) plus the router
front door's quota surface (`core/router.py`): token-bucket math with
honest Retry-After, loud config parsing, the top-k label fold, deficit
round-robin fairness/starvation-freedom, and the regression that tenant
and priority headers ride every dispatch retry and disaggregated leg
VERBATIM.  Pure-python + stub HTTP replicas — no jax, no model; the
end-to-end flood/preemption drills live in tests/test_tenant_drills.py.
"""

import json
import threading

import pytest

from paddlefleetx_tpu.core.router import RouterCore, TenantQuotaExceeded
from paddlefleetx_tpu.core.tenancy import (
    DEFAULT_TENANT,
    DeficitRoundRobin,
    OVERFLOW_TENANT,
    PRIORITY_HEADER,
    TENANT_HEADER,
    TenantAdmission,
    TenantConfig,
    TenantLabelCap,
    TokenBucket,
    normalize_tenant,
    parse_priority,
)
from tests.test_router import StubReplica, _all_serving, _ctr


@pytest.fixture
def stub():
    s = StubReplica()
    yield s
    s.stop()


# ---------------------------------------------------------------------------
# labels
# ---------------------------------------------------------------------------


def test_normalize_tenant_bounded_alphabet():
    assert normalize_tenant(None) == DEFAULT_TENANT
    assert normalize_tenant("") == DEFAULT_TENANT
    assert normalize_tenant("  ") == DEFAULT_TENANT
    assert normalize_tenant("gold") == "gold"
    assert normalize_tenant("team:alpha-1.2_x") == "team:alpha-1.2_x"
    # unsafe bytes fold to '_' — the label stays metrics-safe
    assert normalize_tenant("a b\nc{d}") == "a_b_c_d_"
    # bounded length: a hostile 4k header cannot mint a 4k label
    assert len(normalize_tenant("x" * 5000)) == 64


def test_parse_priority_clamped_and_garbage_safe():
    assert parse_priority(None) == 0
    assert parse_priority("") == 0
    assert parse_priority("not-a-number") == 0  # never a 500
    assert parse_priority("7") == 7
    assert parse_priority("  -3 ") == -3
    assert parse_priority("9999") == 100
    assert parse_priority("-9999") == -100


def test_label_cap_topk_then_overflow_stable():
    cap = TenantLabelCap(topk=2)
    assert cap.label("a") == "a"
    assert cap.label("b") == "b"
    assert cap.label("c") == OVERFLOW_TENANT
    # stable: earlier tenants never fold once assigned, later tenants
    # never un-fold — per-label counters stay monotonic
    assert cap.label("a") == "a"
    assert cap.label("c") == OVERFLOW_TENANT
    assert cap.labels() == ["a", "b"]


def test_label_cap_seeds_declared_tenants_first():
    cap = TenantLabelCap(topk=2, seed=["gold", "silver", "bronze"])
    # an interloper arriving first cannot displace a declared tenant
    assert cap.label("flood") == OVERFLOW_TENANT
    assert cap.label("gold") == "gold"
    assert cap.label("silver") == "silver"


def test_label_cap_env_knob_loud_parse(monkeypatch):
    monkeypatch.setenv("PFX_TENANT_LABEL_TOPK", "3")
    assert TenantLabelCap().topk == 3
    monkeypatch.setenv("PFX_TENANT_LABEL_TOPK", "zero")
    with pytest.raises(ValueError, match="PFX_TENANT_LABEL_TOPK"):
        TenantLabelCap()
    monkeypatch.setenv("PFX_TENANT_LABEL_TOPK", "0")
    with pytest.raises(ValueError, match=">= 1"):
        TenantLabelCap()


# ---------------------------------------------------------------------------
# config (loud parse)
# ---------------------------------------------------------------------------


def test_tenant_config_defaults_admit_everything():
    cfg = TenantConfig()
    pol = cfg.policy("anyone")
    assert pol.weight == 1.0
    assert pol.rps is None and pol.max_inflight is None
    ok, why, retry = TenantAdmission(cfg).admit("anyone")
    assert ok and why == "" and retry == 0.0


def test_tenant_config_from_obj_and_weights():
    cfg = TenantConfig.from_obj({
        "default": {"weight": 1},
        "tenants": {"gold": {"weight": 4, "rps": 50, "burst": 100,
                             "max_inflight": 32}},
    })
    assert cfg.weight("gold") == 4
    assert cfg.weight("stranger") == 1
    assert cfg.policy("gold").max_inflight == 32
    assert cfg.known_tenants() == ["gold"]


@pytest.mark.parametrize("obj,match", [
    ([], "top level"),
    ({"defualt": {}}, "unknown top-level keys"),
    ({"default": {"wieght": 2}}, "unknown keys"),
    ({"default": {"weight": 0}}, "weight must be > 0"),
    ({"tenants": {"a": {"rps": -1}}}, "rps must be > 0"),
    ({"tenants": {"a": {"burst": 0.5}}}, "burst must be >= 1"),
    ({"tenants": {"a": {"max_inflight": 0}}}, "max_inflight must be >= 1"),
    ({"tenants": {"bad name": {}}}, "label-safe"),
])
def test_tenant_config_parse_errors_are_loud(obj, match):
    with pytest.raises(ValueError, match=match):
        TenantConfig.from_obj(obj)


def test_tenant_config_from_file_loud_on_bad_file(tmp_path):
    with pytest.raises(ValueError, match="tenants config"):
        TenantConfig.from_file(str(tmp_path / "absent.json"))
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    with pytest.raises(ValueError, match="invalid JSON"):
        TenantConfig.from_file(str(bad))
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"tenants": {"gold": {"weight": 2}}}))
    assert TenantConfig.from_file(str(good)).weight("gold") == 2


# ---------------------------------------------------------------------------
# token bucket / admission
# ---------------------------------------------------------------------------


def test_token_bucket_honest_retry_after():
    b = TokenBucket(rate=2.0, burst=1.0)
    ok, retry = b.try_acquire(now=100.0)
    assert ok and retry == 0.0
    ok, retry = b.try_acquire(now=100.0)
    assert not ok
    # the bucket is empty and refills at 2/s: the next whole token is
    # 0.5s away — THAT is the Retry-After, not a made-up constant
    assert retry == pytest.approx(0.5)
    # half the refill elapsed -> half the wait remains
    ok, retry = b.try_acquire(now=100.25)
    assert not ok and retry == pytest.approx(0.25)
    ok, retry = b.try_acquire(now=100.5)
    assert ok


def test_token_bucket_burst_caps_idle_credit():
    b = TokenBucket(rate=10.0, burst=3.0)
    b.try_acquire(now=0.0)
    # an hour idle does NOT bank 36000 tokens — burst caps the credit
    granted = sum(1 for _ in range(100) if b.try_acquire(now=3600.0)[0])
    assert granted == 3


def test_admission_inflight_cap_and_release():
    cfg = TenantConfig.from_obj({"tenants": {"a": {"max_inflight": 2}}})
    adm = TenantAdmission(cfg)
    assert adm.admit("a")[0] and adm.admit("a")[0]
    ok, why, retry = adm.admit("a")
    assert not ok and why == "inflight" and retry > 0
    # unlimited tenants are unaffected by a's cap
    assert adm.admit("b")[0]
    adm.release("a")
    assert adm.admit("a")[0]
    assert adm.inflight_snapshot() == {"a": 2, "b": 1}


def test_admission_rate_uses_fake_clock():
    cfg = TenantConfig.from_obj({"tenants": {"a": {"rps": 1, "burst": 1}}})
    t = [1000.0]
    adm = TenantAdmission(cfg, clock=lambda: t[0])
    assert adm.admit("a")[0]
    adm.release("a")
    ok, why, retry = adm.admit("a")
    assert not ok and why == "rate" and retry == pytest.approx(1.0)
    t[0] += 1.0
    assert adm.admit("a")[0]


# ---------------------------------------------------------------------------
# deficit round-robin
# ---------------------------------------------------------------------------


def _drr_run(weights, backlog, picks):
    drr = DeficitRoundRobin(weight_fn=lambda t: weights.get(t, 1.0))
    served = {t: 0 for t in backlog}
    b = dict(backlog)
    for _ in range(picks):
        t = drr.pick(b)
        assert t is not None and b[t] > 0
        drr.charge(t)
        served[t] += 1
        b[t] -= 1
        b[t] = max(b[t], backlog[t])  # refill: sustained backlog
    return served


def test_drr_splits_by_weight():
    served = _drr_run({"gold": 4.0, "brz": 1.0},
                      {"gold": 10, "brz": 10}, picks=100)
    # 4:1 weights -> ~80/20 split under sustained backlog
    assert 70 <= served["gold"] <= 90
    assert served["brz"] >= 10


def test_drr_starvation_free_under_flood():
    # a 99:1 weight ratio still serves the light tenant regularly
    served = _drr_run({"flood": 99.0, "tiny": 1.0},
                      {"flood": 1000, "tiny": 1000}, picks=500)
    assert served["tiny"] >= 3


def test_drr_single_tenant_degenerates_to_fcfs():
    drr = DeficitRoundRobin()
    for _ in range(10):
        assert drr.pick({"only": 5}) == "only"
        drr.charge("only")
    assert drr.pick({}) is None


def test_drr_idle_tenant_does_not_bank_credit():
    drr = DeficitRoundRobin(weight_fn=lambda t: 1.0)
    # 'idle' waits out 50 picks with no backlog, then shows up: its
    # deficit was reset, so it cannot burst past 'busy' on stored credit
    for _ in range(50):
        assert drr.pick({"busy": 1, "idle": 0}) == "busy"
        drr.charge("busy")
    first = [None, None]
    for i in range(2):
        first[i] = drr.pick({"busy": 1, "idle": 1})
        drr.charge(first[i])
    assert sorted(first) == ["busy", "idle"]  # alternation, not a burst


# ---------------------------------------------------------------------------
# router front door
# ---------------------------------------------------------------------------


def _quota_core(stub, tenants_obj):
    return RouterCore([(stub.url, "monolith")],
                      tenant_config=TenantConfig.from_obj(tenants_obj))


def test_router_quota_429_with_honest_retry_after(stub):
    core = _quota_core(stub, {"tenants": {"a": {"rps": 1, "burst": 1}}})
    r0 = _ctr("pfx_tenant_rejected_total", tenant="a", reason="rate")
    core.acquire(tenant="a")
    with pytest.raises(TenantQuotaExceeded) as exc:
        core.acquire(tenant="a")
    assert exc.value.tenant == "a" and exc.value.reason == "rate"
    assert 0.0 < exc.value.retry_after_s <= 1.0
    assert _ctr("pfx_tenant_rejected_total", tenant="a", reason="rate") == r0 + 1
    # the rejected request holds no slot; the admitted one does
    core.release(tenant="a")
    assert core.tenant_snapshot().get("a", {}).get("in_flight", 0) == 0


def test_router_quota_inflight_cap_scoped_per_tenant(stub):
    core = _quota_core(stub, {"tenants": {"a": {"max_inflight": 1}}})
    core.acquire(tenant="a")
    with pytest.raises(TenantQuotaExceeded) as exc:
        core.acquire(tenant="a")
    assert exc.value.reason == "inflight"
    core.acquire(tenant="b")  # unlimited neighbour unaffected
    core.release(tenant="b")
    core.release(tenant="a")
    core.acquire(tenant="a")
    core.release(tenant="a")


def test_router_global_reject_rolls_back_tenant_slot(stub):
    from paddlefleetx_tpu.core.request_queue import QueueFull

    core = RouterCore(
        [(stub.url, "monolith")], max_inflight=1,
        tenant_config=TenantConfig.from_obj(
            {"tenants": {"a": {"max_inflight": 5}}}
        ),
    )
    core.acquire(tenant="b")
    with pytest.raises(QueueFull):
        core.acquire(tenant="a")
    # the global 429 must not leak a's provisional in-flight slot
    assert core.tenant_snapshot()["a"]["in_flight"] == 0
    core.release(tenant="b")


def test_tenant_snapshot_lists_declared_tenants_when_idle(stub):
    core = _quota_core(
        stub, {"tenants": {"gold": {"weight": 4, "rps": 50}}}
    )
    snap = core.tenant_snapshot()
    # declared tenants appear even with zero traffic — the operator's
    # /replicas view shows the configured universe, not just the active
    assert snap["gold"]["in_flight"] == 0
    assert snap["gold"]["weight"] == 4
    assert snap["gold"]["rps"] == 50
    core.acquire(tenant="gold")
    assert core.tenant_snapshot()["gold"]["in_flight"] == 1
    core.release(tenant="gold")


def test_collect_exports_tenant_in_flight(stub):
    core = _quota_core(stub, {"tenants": {"gold": {"weight": 2}}})
    core.acquire(tenant="gold")
    core.acquire(tenant="gold")
    rows = [r for r in core.collect()
            if r[0] == "pfx_tenant_in_flight" and r[1].get("tenant") == "gold"]
    assert rows and rows[0][2] == 2
    core.release(tenant="gold")
    core.release(tenant="gold")


def test_acquire_concurrent_under_quota_is_exact(stub):
    # 32 threads race a max_inflight=8 cap: exactly 8 win
    core = _quota_core(stub, {"tenants": {"a": {"max_inflight": 8}}})
    wins, errs = [], []

    def go():
        try:
            core.acquire(tenant="a")
            wins.append(1)
        except TenantQuotaExceeded:
            errs.append(1)

    ts = [threading.Thread(target=go) for _ in range(32)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert len(wins) == 8 and len(errs) == 24
    for _ in wins:
        core.release(tenant="a")


# ---------------------------------------------------------------------------
# header propagation (satellite b): tenant/priority ride EVERY hop
# ---------------------------------------------------------------------------

_TEN_HDRS = {TENANT_HEADER: "gold", PRIORITY_HEADER: "7"}


def _assert_tenant_headers(seen):
    assert seen.get(TENANT_HEADER.lower(), seen.get(TENANT_HEADER)) == "gold"
    assert seen.get(PRIORITY_HEADER.lower(), seen.get(PRIORITY_HEADER)) == "7"


def _hdr(seen, name):
    # BaseHTTPRequestHandler preserves case; be tolerant anyway
    for k, v in seen.items():
        if k.lower() == name.lower():
            return v
    return None


def test_dispatch_retry_carries_tenant_headers_verbatim(stub):
    """A connection-refused retry re-sends on ANOTHER replica: the
    tenant/priority headers must ride the second attempt too."""
    import socket as _socket

    with _socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead = f"http://127.0.0.1:{s.getsockname()[1]}"
    core = RouterCore([(dead, "monolith"), (stub.url, "monolith")])
    _all_serving(core)
    core.replicas["r1"].depth = 9  # the dead replica is picked first
    status, _, _ = core.dispatch(
        "POST", "/generate", b"{}", role="monolith", deadline_s=30,
        headers=dict(_TEN_HDRS),
    )
    assert status == 200
    assert _hdr(stub.post_headers[0], TENANT_HEADER) == "gold"
    assert _hdr(stub.post_headers[0], PRIORITY_HEADER) == "7"


def test_disagg_legs_carry_tenant_headers_verbatim():
    """extra_headers flows through _dispatch_prefill AND the decode
    proxy leg — both hops of a disaggregated request see the labels."""
    pre, dec = StubReplica(role="prefill"), StubReplica(role="decode")
    core = RouterCore([(pre.url, "prefill"), (dec.url, "decode")])
    try:
        _all_serving(core)
        out = core.generate_disaggregated(
            [[1, 2, 3]], 4, 30.0, extra_headers=dict(_TEN_HDRS)
        )
        assert out == [[7, 8, 9]]
        for seen in (pre.post_headers[0], dec.post_headers[0]):
            assert _hdr(seen, TENANT_HEADER) == "gold"
            assert _hdr(seen, PRIORITY_HEADER) == "7"
    finally:
        pre.stop(), dec.stop()


def test_prefill_failover_re_sends_tenant_headers():
    """The stateless prefill failover leg rebuilds the request on the
    next replica — the rebuilt attempt must carry the labels verbatim,
    not drop them with the dead connection."""
    bad, good = StubReplica(role="prefill"), StubReplica(role="prefill")
    dec = StubReplica(role="decode")
    bad.fail_mode = "reset"
    core = RouterCore([(bad.url, "prefill"), (good.url, "prefill"),
                       (dec.url, "decode")])
    try:
        _all_serving(core)
        core.replicas["r1"].depth = 9  # the doomed replica picked first
        out = core.generate_disaggregated(
            [[1, 2, 3]], 4, 30.0, extra_headers=dict(_TEN_HDRS)
        )
        assert out == [[7, 8, 9]]
        assert len(good.hits) == 1
        assert _hdr(good.post_headers[0], TENANT_HEADER) == "gold"
        assert _hdr(good.post_headers[0], PRIORITY_HEADER) == "7"
    finally:
        bad.stop(), good.stop(), dec.stop()
